"""Realize a leximin profile as a mixture of feasible compositions, fast.

Phase 1 of the type-space solver (``cg_typespace.py``) must express the
probe-certified profile ``v`` as ``M p = v`` over feasible compositions. The
classic Dantzig-Wolfe master (ε-LP + exact MILP pricing) tails badly here:
the optimal face needs ~T active columns and pricing discovers them a handful
per round (~7 %/round ε decay at sf_e scale — minutes of wall-clock).

This engine replaces it with three TPU-idiomatic ingredients:

* **Aimed slices** (`cg_typespace._slice_relaxation`) seed the hull around
  the target marginal ``x* = v·m``.
* **Face-neighbor expansion** generates columns *combinatorially* instead of
  one-per-MILP: for support columns of the current master, every feasible
  single-unit move ``t → t'`` that shifts mass from over-served types
  (residual ``r_t > 0``) to under-served ones is itself a feasible
  composition on or near the face — thousands of useful columns per round
  from pure vectorized index arithmetic (quota feasibility of all
  (composition, move) pairs is checked with per-feature *bitmasks* packed
  into machine words, so a round's full candidate screen is a handful of
  wide integer ops).
* **A device-resident approximate master**: each round's ε-LP is solved by
  the warm-started PDHG core (``lp_pdhg.py``) on the accelerator — its duals
  aim the expansion, and *acceptance needs no trusted solver at all*: the
  certificate is the arithmetic identity ``ε = ‖M p − v‖∞`` evaluated on the
  returned mixture, so an approximate solver can terminate the loop the
  moment any iterate realizes the profile within tolerance (same two-sided
  ε semantics as the reference's final LP, ``leximin.py:453-464``). A host
  interior-point polish runs only in the end-game, when the approximate
  master says the support should realize ``v`` but its iterate hasn't
  converged tightly enough to show it.

The loop itself is a *pipelined, warm-started engine*: the anchor-oracle
MILPs run on a worker thread double-buffered against the device master
(``_AnchorPricer`` — identical column schedule threaded or inline, so the
serial fallback is bit-identical), the master's and polish's PDHG iterates
carry across rounds, prunes and column-bucket growths with a stall-triggered
cold restart (``_WarmStall``), and the per-round move screen can run as one
jitted device batch (``_batched_move_screen``). Behind the
``Config.decomp_device_pricing`` gate the engine goes *device-resident*: the
anchor batch prices in one jitted dispatch (``solvers/device_pricing``, the
exact host MILP demoted to a per-task fallback), and the move screen's pair
selection moves on device so the screen chains onto the master's device
duals (``_FusedScreen``) — a steady-state round then makes exactly one
host↔device synchronization, measured by the ``decomp_host_syncs`` /
``decomp_rounds`` gauge pair. All of it is wall-clock machinery —
acceptance remains the float64 arithmetic residual of whatever mixture
comes back.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from citizensassemblies_tpu.dist import runtime as dist_runtime
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.obs.trace import begin_span, end_span
from citizensassemblies_tpu.robust import inject
from citizensassemblies_tpu.robust.checkpoint import FaceCheckpointer
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.guards import CompilationGuard, no_implicit_transfers
from citizensassemblies_tpu.utils.logging import RunLog


def _feature_bitmasks(reduction: TypeReduction):
    """Per-type donor/receiver feature masks for the move-feasibility screen.

    The quota conditions of a unit move collapse to bit tests: moving a unit
    *out* of type ``t`` decrements each of ``t``'s features, which is safe
    iff the composition's count stays ≥ lo there; moving *in* increments,
    safe iff ≤ hi. One 64-bit word covers every reference-shaped instance
    (F ≤ 64). Instances with MORE features — the household quotient's
    augmented incidence appends one one-hot class feature per household
    class, F = base + #classes — split by category: categories whose
    features all index < 64 ride the word, the rest are screened by direct
    gathers in :func:`neighbor_columns` (one gather per category — for the
    quotient that is the single class category, whose ``lo = 0`` even skips
    the donor side). Returns ``(feat_mask[T] uint64, leftover_cats)`` where
    ``leftover_cats`` lists category indices not covered by the mask, or
    ``None`` when no category fits a word at all.
    """
    feat_of = np.asarray(reduction.type_feature)
    ncat = feat_of.shape[1]
    word_cats = [ci for ci in range(ncat) if int(feat_of[:, ci].max()) < 64]
    if not word_cats:
        return None
    masks = np.zeros(reduction.T, dtype=np.uint64)
    for ci in word_cats:
        masks |= np.uint64(1) << feat_of[:, ci].astype(np.uint64)
    leftover = [ci for ci in range(ncat) if ci not in word_cats]
    return masks, leftover


def _screen_feasible(
    comps_i, counts_nb, lo_nb, hi_nb, counts_full, lo_f, hi_f,
    m_t, ti, tj, valid, ns_lo, ns_hi, na_lo, na_hi, lf_ai, lf_aj, lf_donor,
):
    """The [S, P] (composition, move) feasibility check shared by the two
    jitted screen cores: base bounds via two device gathers, per-feature
    quota conditions via the packed uint32 bitword lanes, leftover (>word)
    categories via direct gathers. Traced code — callers are jitted."""
    import jax.numpy as jnp

    ci = comps_i[:, ti]  # [Sp, Pp] gathers (padding rows are zero)
    cj = comps_i[:, tj]
    ok = (ci > 0) & (cj < m_t[tj][None, :]) & valid[None, :]
    bits32 = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def pack(bits):  # bool [Sp, 64] → (lo, hi) uint32 words [Sp]
        b = bits.astype(jnp.uint32)
        return (
            (b[:, :32] * bits32).sum(axis=1),
            (b[:, 32:] * bits32).sum(axis=1),
        )

    cs_lo, cs_hi = pack(counts_nb - 1 >= lo_nb[None, :])
    ca_lo, ca_hi = pack(counts_nb + 1 <= hi_nb[None, :])
    ok &= (ns_lo[None, :] & ~cs_lo[:, None]) == 0
    ok &= (ns_hi[None, :] & ~cs_hi[:, None]) == 0
    ok &= (na_lo[None, :] & ~ca_lo[:, None]) == 0
    ok &= (na_hi[None, :] & ~ca_hi[:, None]) == 0
    for l in range(lf_ai.shape[0]):  # static leftover-category count
        ai, aj = lf_ai[l], lf_aj[l]
        same = ai == aj
        add_ok = counts_full[:, aj] + 1 <= hi_f[aj][None, :]
        sub_ok = counts_full[:, ai] - 1 >= lo_f[ai][None, :]
        add_ok &= jnp.where(lf_donor[l], sub_ok, True)
        ok &= same[None, :] | add_ok
    return ok


_MOVE_SCREEN_CORE = None


def _get_move_screen_core():
    """Build (once) the jitted batched move screen.

    The whole [S, P] (composition, move) feasibility check of
    :func:`neighbor_columns` as ONE jitted dispatch per round: base bounds via
    two device gathers, the per-feature quota conditions via the same packed
    bitword trick as the numpy path — split into two uint32 lanes because JAX
    runs with 64-bit types disabled — and the leftover (>word) categories via
    direct gathers. Feasible (composition, pair) indices come back through a
    fixed-size ``jnp.nonzero`` (row-major, so below the cap the index set is
    bit-identical to the numpy path's ``np.nonzero``), plus the true count so
    the caller can see when the cap truncated. Compiled once per
    (T, F, pair-bucket, leftover-count) shape; ``jax`` is imported lazily so
    the module stays importable without it.
    """
    global _MOVE_SCREEN_CORE
    if _MOVE_SCREEN_CORE is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("cap",))
        def core(
            comps_i, counts_nb, lo_nb, hi_nb, counts_full, lo_f, hi_f,
            m_t, ti, tj, valid, ns_lo, ns_hi, na_lo, na_hi,
            lf_ai, lf_aj, lf_donor, cap: int,
        ):
            ok = _screen_feasible(
                comps_i, counts_nb, lo_nb, hi_nb, counts_full, lo_f, hi_f,
                m_t, ti, tj, valid, ns_lo, ns_hi, na_lo, na_hi,
                lf_ai, lf_aj, lf_donor,
            )
            flat = ok.reshape(-1)
            (idx,) = jnp.nonzero(flat, size=cap, fill_value=-1)
            return idx.astype(jnp.int32), flat.sum(dtype=jnp.int32)

        from citizensassemblies_tpu.aot.store import aot_seeded

        _MOVE_SCREEN_CORE = aot_seeded(
            "face_decompose.move_screen", core, static_argnames=("cap",)
        )
    return _MOVE_SCREEN_CORE


_FUSED_SCREEN_CORE = None


def _get_fused_screen_core():
    """Build (once) the jitted FUSED move screen of the device-pricing round.

    The classic screen needs the master's duals on host before it can even
    be marshalled (pair selection is a numpy argsort over ``r_norm``), which
    costs the round a second host↔device round trip. This core moves the
    pair selection on device so the whole screen chains onto the master's
    DEVICE dual output with no host involvement: ``r_norm = −w/m`` from the
    raw ``lam`` vector, improving pairs as a ``top_k`` meshgrid of the
    residual extremes, face pairs as the smallest-|Δ| ``top_k`` over a
    static per-instance candidate pool, need-masks gathered from the
    device-resident uint32 lanes, then the shared feasibility body. Returns
    the selected (ti, tj) alongside the feasible indices because the host
    never saw the pairs. The pair count is static (pool_cap² + face_pairs),
    so one program per (S, T, F, leftover) shape serves every round.
    """
    global _FUSED_SCREEN_CORE
    if _FUSED_SCREEN_CORE is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("cap", "pool_cap", "face_pairs"))
        def core(
            lam, m_f, comps_i, counts_nb, lo_nb, hi_nb, counts_full,
            lo_f, hi_f, m_t, mask_lo, mask_hi, cand_di, cand_dj,
            lf_feat, lf_donor, cap: int, pool_cap: int, face_pairs: int,
        ):
            T = m_f.shape[0]
            w = lam[:T] - lam[T:]
            r = -w / m_f
            _, donors = jax.lax.top_k(r, pool_cap)
            _, receivers = jax.lax.top_k(-r, pool_cap)
            delta = jnp.abs(r[cand_di] - r[cand_dj])
            _, sel = jax.lax.top_k(-delta, face_pairs)
            ti = jnp.concatenate(
                [jnp.repeat(donors, pool_cap), cand_di[sel]]
            ).astype(jnp.int32)
            tj = jnp.concatenate(
                [jnp.tile(receivers, pool_cap), cand_dj[sel]]
            ).astype(jnp.int32)
            valid = ti != tj
            dl = mask_lo[ti] ^ mask_lo[tj]
            dh = mask_hi[ti] ^ mask_hi[tj]
            ns_lo, ns_hi = mask_lo[ti] & dl, mask_hi[ti] & dh
            na_lo, na_hi = mask_lo[tj] & dl, mask_hi[tj] & dh
            lf_ai = lf_feat[:, ti]  # [L, P] leftover-category features
            lf_aj = lf_feat[:, tj]
            ok = _screen_feasible(
                comps_i, counts_nb, lo_nb, hi_nb, counts_full, lo_f, hi_f,
                m_t, ti, tj, valid, ns_lo, ns_hi, na_lo, na_hi,
                lf_ai, lf_aj, lf_donor,
            )
            flat = ok.reshape(-1)
            (idx,) = jnp.nonzero(flat, size=cap, fill_value=-1)
            return idx.astype(jnp.int32), flat.sum(dtype=jnp.int32), ti, tj

        from citizensassemblies_tpu.aot.store import aot_seeded

        _FUSED_SCREEN_CORE = aot_seeded(
            "face_decompose.fused_screen", core,
            static_argnames=("cap", "pool_cap", "face_pairs"),
        )
    return _FUSED_SCREEN_CORE


@register_ir_core("face_decompose.fused_screen", span="face_decompose.fused_screen")
def _ir_fused_screen() -> IRCase:
    """The fused (pair-selection-on-device) move screen at a small
    (T=32, F=40, one leftover category) shape — the top_k pair selection
    chained ahead of the shared bitmask feasibility body."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    i32, u32, f32 = jnp.int32, jnp.uint32, jnp.float32
    T, F, Q, L = 32, 40, 1024, 1
    return IRCase(
        fn=_get_fused_screen_core(),
        args=(
            S((2 * T,), f32), S((T,), f32),
            S((_SCREEN_ROWS, T), i32), S((_SCREEN_ROWS, 64), i32),
            S((64,), i32), S((64,), i32), S((_SCREEN_ROWS, F), i32),
            S((F,), i32), S((F,), i32), S((T,), i32),
            S((T,), u32), S((T,), u32), S((Q,), i32), S((Q,), i32),
            S((L, T), i32), S((L,), jnp.bool_),
        ),
        static=dict(cap=1024, pool_cap=8, face_pairs=64),
    )


@register_ir_core("face_decompose.move_screen", span="face_decompose.move_screen")
def _ir_move_screen() -> IRCase:
    """The batched move screen at one small (T=32, F=40, one leftover
    category) shape — the uint32 bitmask lanes and the fixed-size nonzero
    decode are the structure under verification (lint/ir.py)."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    i32, u32 = jnp.int32, jnp.uint32
    T, F, Pp, L = 32, 40, 4096, 1
    return IRCase(
        fn=_get_move_screen_core(),
        args=(
            S((_SCREEN_ROWS, T), i32), S((_SCREEN_ROWS, 64), i32),
            S((64,), i32), S((64,), i32), S((_SCREEN_ROWS, F), i32),
            S((F,), i32), S((F,), i32), S((T,), i32),
            S((Pp,), i32), S((Pp,), i32), S((Pp,), jnp.bool_),
            S((Pp,), u32), S((Pp,), u32), S((Pp,), u32), S((Pp,), u32),
            S((L, Pp), i32), S((L, Pp), i32), S((L,), jnp.bool_),
        ),
        static=dict(cap=4096),
    )


#: compositions per screening batch: ``realize_profile`` expands at most the
#: top 512 support columns, so one padded row count keeps one compiled
#: program per instance shape instead of one per round
_SCREEN_ROWS = 512

#: minimum mass-bearing support before the batched polish-face screen pays:
#: below it one structured solve is already a single small dispatch and the
#: candidate prefixes would all be the full support anyway
_POLISH_SCREEN_MIN_SUP = 256


def _move_pairs(
    reduction: TypeReduction,
    r_norm: np.ndarray,
    pool_cap: int,
    face_pairs: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """The expansion's candidate (donor, receiver) pair selection — the
    improving extremes of the residual direction plus the smallest-|Δ| face
    pairs. Factored out of :func:`neighbor_columns` so the fused device
    screen's on-device pair selection (:func:`_get_fused_screen_core`) has
    one host reference to mirror. Returns ``(ti, tj)``."""
    T = reduction.T
    order = np.argsort(-r_norm)
    # improving pairs: extremes of the residual direction
    donors = order[:pool_cap]
    receivers = order[::-1][:pool_cap]
    ti_a, tj_a = np.meshgrid(donors, receivers, indexing="ij")
    pairs = [np.stack([ti_a.ravel(), tj_a.ravel()], axis=1)]
    # face pairs: smallest |Δ| over a broad random pool (full T² only for
    # small T)
    if T * T <= 1 << 18:
        di = np.repeat(np.arange(T), T)
        dj = np.tile(np.arange(T), T)
    else:
        rng = np.random.default_rng(T)
        di = rng.integers(0, T, size=face_pairs * 8)
        dj = rng.integers(0, T, size=face_pairs * 8)
    delta = np.abs(r_norm[di] - r_norm[dj])
    sel = np.argsort(delta)[:face_pairs]
    pairs.append(np.stack([di[sel], dj[sel]], axis=1))
    tp = np.concatenate(pairs, axis=0)
    tp = tp[tp[:, 0] != tp[:, 1]]
    tp = np.unique(tp, axis=0)
    return tp[:, 0], tp[:, 1]


def _comp_feature_counts(comps: np.ndarray, reduction: TypeReduction) -> np.ndarray:
    """Per-composition feature counts [S, F]: float32 BLAS then cast — numpy
    integer matmuls bypass BLAS, and at quotient scale ([512, 1199] @
    [1199, 626]) the int64 product alone cost ~0.4 s per face round;
    counts ≤ k ≤ a few hundred, far inside float32's exact-integer range."""
    T = reduction.T
    feat_of = np.asarray(reduction.type_feature)
    ncat = feat_of.shape[1]
    F = reduction.F
    tf = np.zeros((T, F), dtype=np.float32)
    tf[np.repeat(np.arange(T), ncat), feat_of.ravel()] = 1.0
    return (comps.astype(np.float32) @ tf).astype(np.int64)


def _batched_move_screen(
    comps: np.ndarray,
    counts: np.ndarray,
    reduction: TypeReduction,
    m: np.ndarray,
    ti: np.ndarray,
    tj: np.ndarray,
    packed,
    per_round_cap: int,
    cfg=None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host marshalling for the jitted move screen: pad to the screening
    buckets, split the uint64 need-masks into uint32 lanes, decode the
    returned flat indices. Returns ``(si, pi, total_feasible)``."""
    idx_dev, _total_dev, Pp = _move_screen_dispatch(
        comps, counts, reduction, m, ti, tj, packed, per_round_cap, cfg=cfg
    )
    idx = np.asarray(idx_dev)
    idx = idx[idx >= 0]
    return idx // Pp, idx % Pp, int(_total_dev)


def _move_screen_dispatch(
    comps: np.ndarray,
    counts: np.ndarray,
    reduction: TypeReduction,
    m: np.ndarray,
    ti: np.ndarray,
    tj: np.ndarray,
    packed,
    per_round_cap: int,
    cfg=None,
):
    """The marshalling + async device dispatch half of the move screen:
    everything up to (but not including) the blocking result readback, so a
    caller can overlap the screen with other device work and harvest later
    (the lagged round of the device-pricing mode). Returns
    ``(idx device-array, total device-array, Pp)`` — decode with
    ``np.asarray`` exactly as :func:`_batched_move_screen` does."""
    masks, leftover = packed
    S, T = comps.shape
    F = reduction.F
    nb = min(F, 64)
    P = len(ti)
    Pp = -(-P // 4096) * 4096
    lo = reduction.qmin.astype(np.int32)
    hi = reduction.qmax.astype(np.int32)

    comps_p = np.zeros((_SCREEN_ROWS, T), np.int32)
    comps_p[:S] = comps
    counts_full = np.zeros((_SCREEN_ROWS, F), np.int32)
    counts_full[:S] = counts
    # padding feature slots get unbounded quotas so their bits never veto
    lo_nb = np.full(64, -(1 << 30), np.int32)
    hi_nb = np.full(64, 1 << 30, np.int32)
    lo_nb[:nb] = lo[:nb]
    hi_nb[:nb] = hi[:nb]
    counts_nb = np.zeros((_SCREEN_ROWS, 64), np.int32)
    counts_nb[:, :nb] = counts_full[:, :nb]

    ti_p = np.zeros(Pp, np.int32)
    tj_p = np.zeros(Pp, np.int32)
    ti_p[:P] = ti
    tj_p[:P] = tj
    valid = np.zeros(Pp, bool)
    valid[:P] = True
    diff = masks[ti] ^ masks[tj]
    ns = np.zeros(Pp, np.uint64)
    na = np.zeros(Pp, np.uint64)
    ns[:P] = masks[ti] & diff
    na[:P] = masks[tj] & diff
    word = np.uint64(0xFFFFFFFF)
    ns_lo, ns_hi = (ns & word).astype(np.uint32), (ns >> np.uint64(32)).astype(np.uint32)
    na_lo, na_hi = (na & word).astype(np.uint32), (na >> np.uint64(32)).astype(np.uint32)

    L = len(leftover)
    lf_ai = np.zeros((L, Pp), np.int32)
    lf_aj = np.zeros((L, Pp), np.int32)
    feat_of = np.asarray(reduction.type_feature)
    for l, ci_cat in enumerate(leftover):
        lf_ai[l, :P] = feat_of[ti, ci_cat]
        lf_aj[l, :P] = feat_of[tj, ci_cat]
    lf_donor = np.array(
        [bool((lo[feat_of[:, ci_cat]] > 0).any()) for ci_cat in leftover], dtype=bool
    )

    core = _get_move_screen_core()
    import jax.numpy as jnp

    # the screen's operands change every round, so the upload is inherent —
    # but it is made EXPLICIT here (one jnp.asarray per operand), and the
    # guard then rejects any further implicit transfer inside the jitted call
    operands = tuple(
        jnp.asarray(a)
        for a in (
            comps_p, counts_nb, lo_nb, hi_nb, counts_full,
            lo.astype(np.int32), hi.astype(np.int32),
            np.asarray(m, np.int32), ti_p, tj_p, valid,
            ns_lo, ns_hi, na_lo, na_hi, lf_ai, lf_aj, lf_donor,
        )
    )
    with dispatch_span(
        "face_decompose.move_screen", cfg=cfg, pairs=int(P)
    ) as _ds:
        with no_implicit_transfers(cfg):
            idx, total = core(*operands, cap=int(per_round_cap))
        _ds.out = (idx, total)
    return idx, total, Pp


def neighbor_columns(
    comps: np.ndarray,
    reduction: TypeReduction,
    r_norm: np.ndarray,
    # measured at 2× and 3× these widths on the two large-T regimes
    # (sf_e mild-skew T=565, household quotient T=1199): round count drops
    # ~linearly (7→4, 19→10) but per-round master cost rises to match —
    # wall-clock within noise either way, so the defaults stay at the
    # smaller, lower-variance setting
    pool_cap: int = 128,
    face_pairs: int = 12_288,
    per_round_cap: int = 16_384,
    batched: bool = False,
    cfg=None,
) -> np.ndarray:
    """Feasible single-unit moves from ``comps`` along and across the face.

    Two pair classes feed the expansion:

    * **improving** — move a unit from an over-served type (``r_norm > 0``)
      to an under-served one: pulls the hull toward the target;
    * **face-preserving** — pairs with ``|Δ(w/m)| ≈ 0``: enumerate the
      near-optimal face combinatorially, which is where the master's ~T
      active columns live (a MILP finds them only one per solve).

    A move ``t → t'`` from composition ``c`` is feasible iff ``c_t > 0``,
    ``c_{t'} < m_{t'}`` and, in every category where the two types' features
    differ, the donor's feature stays ≥ its lower quota and the receiver's
    ≤ its upper. The (composition, pair) screen packs those per-feature
    conditions into one machine word per composition (``_feature_bitmasks``),
    so the whole [S, P] check is three wide integer ops instead of 2·ncat
    float gathers. With ``batched=True`` the screen instead runs as ONE
    jitted device batch per round (``_batched_move_screen``): identical
    index set below ``per_round_cap``, and above it the first (mass-ordered,
    since callers pass support-ordered compositions) feasible moves are kept
    where the numpy path subsamples randomly. Returns the stacked new
    compositions (int16 [N, T]).
    """
    comps = comps.astype(np.int16, copy=False)  # 4× less gather traffic
    S, T = comps.shape
    feat_of = np.asarray(reduction.type_feature)  # [T, ncat]
    ncat = feat_of.shape[1]
    F = reduction.F
    # clip before the int16 cast: composition entries are <= k (small), but
    # a pool type can exceed int16 range — the receiver check only needs
    # min(m, k+1), since no composition holds more than k of any type
    m = np.minimum(reduction.msize, reduction.k + 1).astype(np.int16)
    lo = reduction.qmin.astype(np.int64)
    hi = reduction.qmax.astype(np.int64)

    ti, tj = _move_pairs(reduction, r_norm, pool_cap, face_pairs)
    P = len(ti)
    if P == 0:
        return np.zeros((0, T), dtype=np.int16)

    counts = _comp_feature_counts(comps, reduction)  # [S, F]

    packed = _feature_bitmasks(reduction)
    if batched and packed is not None and S <= _SCREEN_ROWS:
        si, pi, _total = _batched_move_screen(
            comps, counts, reduction, m, ti, tj, packed, per_round_cap, cfg=cfg
        )
        if len(si) == 0:
            return np.zeros((0, T), dtype=np.int16)
        out = comps[si].astype(np.int16)
        idx = np.arange(len(si))
        out[idx, ti[pi]] -= 1
        out[idx, tj[pi]] += 1
        return out

    ok = (comps[:, ti] > 0) & (comps[:, tj] < m[tj][None, :])  # [S, P]
    if packed is not None:
        masks, leftover = packed
        # bit f set ⇔ this composition may donate (resp. receive) a unit of
        # feature f without breaking its quota
        nb = min(F, 64)
        fbit = np.uint64(1) << np.arange(nb, dtype=np.uint64)
        can_sub = ((counts[:, :nb] - 1 >= lo[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )  # [S]
        can_add = ((counts[:, :nb] + 1 <= hi[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )
        # features touched by the move: symmetric difference of the two
        # types' feature sets (shared features cancel)
        diff = masks[ti] ^ masks[tj]  # [P]
        need_sub = masks[ti] & diff
        need_add = masks[tj] & diff
        ok &= (need_sub[None, :] & ~can_sub[:, None]) == 0
        ok &= (need_add[None, :] & ~can_add[:, None]) == 0
        # categories beyond the word (the household quotient's class
        # category): one [S, P] gather each. Its donor check vanishes when
        # every lower quota is 0 (true for class caps [0, m_c]) — the slow
        # all-gather fallback here was 62 s of a 130 s n=1200 household
        # decomposition
        for ci in leftover:
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            if (lo[feat_of[:, ci]] > 0).any():
                add_ok &= counts[:, a_i] - 1 >= lo[a_i][None, :]
            ok &= same[None, :] | add_ok
    else:  # pragma: no cover - every instance has some ≤64-feature category
        for ci in range(ncat):
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            sub_ok = counts[:, a_i] - 1 >= lo[a_i][None, :]
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            ok &= same[None, :] | (sub_ok & add_ok)

    si, pi = np.nonzero(ok)
    if len(si) == 0:
        return np.zeros((0, T), dtype=np.int16)
    if len(si) > per_round_cap:
        sel = np.random.default_rng(len(si)).choice(len(si), per_round_cap, replace=False)
        si, pi = si[sel], pi[sel]
    out = comps[si].astype(np.int16)
    idx = np.arange(len(si))
    out[idx, ti[pi]] -= 1
    out[idx, tj[pi]] += 1
    return out


class _FusedScreen:
    """Same-round device move screen chained onto the master's device duals.

    The classic round blocks on the master's readback just to marshal the
    move screen (pair selection is a host argsort over the duals), then
    blocks AGAIN on the screen's own result — two host↔device round trips
    per round. Here the pair selection runs on device
    (``_get_fused_screen_core``): ``dispatch`` is called with the master's
    raw ``lam`` still on device, enqueues the screen behind the solve with
    no host involvement, and the single blocking readback of the round
    (the master's ``finish``) leaves the screen results already complete —
    ``harvest`` then decodes them without waiting on in-flight compute. The
    screened composition block is the round's master columns (mass-ordered
    prefix from the previous prune), known before the master returns; the
    pairs come from the CURRENT duals, so the expansion aim is exactly as
    fresh as the classic path's. Gate-on only — the classic screen and its
    numpy twin are untouched.
    """

    def __init__(self, reduction: TypeReduction, per_round_cap: int, cfg=None):
        import jax.numpy as jnp

        self.red = reduction
        self.cap = int(per_round_cap)
        self.cfg = cfg
        packed = _feature_bitmasks(reduction)
        self.ok = packed is not None
        self._pending = None  # (idx_dev, ti_dev, tj_dev, comps) or None
        if not self.ok:  # pragma: no cover - every instance has a word cat
            return
        masks, leftover = packed
        T, F = reduction.T, reduction.F
        lo = reduction.qmin.astype(np.int64)
        hi = reduction.qmax.astype(np.int64)
        feat_of = np.asarray(reduction.type_feature)
        word = np.uint64(0xFFFFFFFF)
        # device-resident static operands: uploaded once per instance
        self._mask_lo = jnp.asarray((masks & word).astype(np.uint32))
        self._mask_hi = jnp.asarray((masks >> np.uint64(32)).astype(np.uint32))
        lf = (
            np.stack([feat_of[:, ci] for ci in leftover])
            if leftover else np.zeros((0, T), np.int64)
        )
        self._lf_feat = jnp.asarray(lf.astype(np.int32))
        self._lf_donor = jnp.asarray(
            np.array(
                [bool((lo[feat_of[:, ci]] > 0).any()) for ci in leftover],
                dtype=bool,
            )
        )
        # static face-pair candidate pool (same construction as _move_pairs:
        # full T² when small, a T-seeded random pool otherwise)
        if T * T <= 1 << 18:
            di = np.repeat(np.arange(T), T)
            dj = np.tile(np.arange(T), T)
        else:
            rng = np.random.default_rng(T)
            di = rng.integers(0, T, size=12_288 * 8)
            dj = rng.integers(0, T, size=12_288 * 8)
        self._cand_di = jnp.asarray(di.astype(np.int32))
        self._cand_dj = jnp.asarray(dj.astype(np.int32))
        self.pool_cap = min(128, T)
        self.face_pairs = min(12_288, len(di))
        nb = min(F, 64)
        lo_nb = np.full(64, -(1 << 30), np.int32)
        hi_nb = np.full(64, 1 << 30, np.int32)
        lo_nb[:nb] = lo[:nb]
        hi_nb[:nb] = hi[:nb]
        self._lo_nb = jnp.asarray(lo_nb)
        self._hi_nb = jnp.asarray(hi_nb)
        self._lo_f = jnp.asarray(lo.astype(np.int32))
        self._hi_f = jnp.asarray(hi.astype(np.int32))
        self._m_t = jnp.asarray(
            np.minimum(reduction.msize, reduction.k + 1).astype(np.int32)
        )
        self._m_f = jnp.asarray(reduction.msize.astype(np.float32))

    @property
    def pending(self) -> bool:
        return self._pending is not None

    def dispatch(self, comps: np.ndarray, lam_dev) -> bool:
        """Enqueue the screen behind the in-flight master whose raw device
        ``lam`` output is ``lam_dev`` (async — no readback here)."""
        if not self.ok or len(comps) > _SCREEN_ROWS:  # pragma: no cover
            self._pending = None
            return False
        import jax.numpy as jnp

        red = self.red
        comps = comps.astype(np.int16, copy=False)
        S, T = comps.shape
        counts = _comp_feature_counts(comps, red)
        F = red.F
        nb = min(F, 64)
        comps_p = np.zeros((_SCREEN_ROWS, T), np.int32)
        comps_p[:S] = comps
        counts_full = np.zeros((_SCREEN_ROWS, F), np.int32)
        counts_full[:S] = counts
        counts_nb = np.zeros((_SCREEN_ROWS, 64), np.int32)
        counts_nb[:, :nb] = counts_full[:, :nb]
        core = _get_fused_screen_core()
        operands = (
            lam_dev, self._m_f, jnp.asarray(comps_p), jnp.asarray(counts_nb),
            self._lo_nb, self._hi_nb, jnp.asarray(counts_full),
            self._lo_f, self._hi_f, self._m_t,
            self._mask_lo, self._mask_hi, self._cand_di, self._cand_dj,
            self._lf_feat, self._lf_donor,
        )
        # NOTE: no ``.out`` is parked on the span scope — this dispatch is
        # async BY DESIGN (it chains onto the master's in-flight duals and
        # must not block even in the obs sampling mode), so its span
        # measures the enqueue window only
        with dispatch_span(
            "face_decompose.fused_screen", cfg=self.cfg, rows=int(S),
            async_chain=True,
        ):
            with no_implicit_transfers(self.cfg):
                idx, _total, ti, tj = core(
                    *operands, cap=self.cap, pool_cap=self.pool_cap,
                    face_pairs=self.face_pairs,
                )
        self._pending = (idx, ti, tj, comps)
        return True

    def harvest(self) -> np.ndarray:
        """Decode the screen results (already complete by the time the
        master's readback returned) into new compositions int16 [N, T]."""
        pending, self._pending = self._pending, None
        if pending is None:
            return np.zeros((0, self.red.T), dtype=np.int16)
        idx_dev, ti_dev, tj_dev, comps = pending
        idx = np.asarray(idx_dev)
        ti = np.asarray(ti_dev)
        tj = np.asarray(tj_dev)
        idx = idx[idx >= 0]
        if len(idx) == 0:
            return np.zeros((0, self.red.T), dtype=np.int16)
        P = len(ti)
        si, pi = idx // P, idx % P
        out = comps[si].astype(np.int16)
        rows = np.arange(len(si))
        out[rows, ti[pi]] -= 1
        out[rows, tj[pi]] += 1
        return out


def _master_pdhg(
    MT: np.ndarray,
    v: np.ndarray,
    cfg,
    warm,
    max_iters: int,
    tol: float,
    ell=None,
    screen=None,
) -> Tuple[float, np.ndarray, np.ndarray, float, Optional[tuple], bool]:
    """One approximate master solve on device: the two-sided ε-LP handed to
    the STRUCTURED warm-started PDHG core (``lp_pdhg.solve_two_sided_master``
    — only MT is shipped and kept resident; the ± row structure is applied
    arithmetically, halving both the tunnel transfer and the per-iteration
    HBM traffic of the stacked-matrix formulation). With ``ell`` (the
    incrementally-maintained ELL pack of the master columns,
    ``solvers/sparse_ops``), the sparse core carries the solve instead:
    the tunnel ships only the NEW columns' packed indices/values since the
    last round, and every PDHG matvec is O(C·k_pad) gather/scatter work.

    ``screen`` (device-pricing mode) is a callback receiving the master's
    raw DEVICE dual vector the moment the solve is enqueued: the fused move
    screen it dispatches runs behind the solve with no host involvement, so
    the blocking readback below stays the round's only synchronization
    point.

    Returns ``(eps_realized, w, p_norm, eps_obj, warm', ok)`` where
    ``eps_realized = ‖M p_norm − v‖∞`` is the *arithmetic* certificate of the
    normalized primal iterate (valid regardless of solver convergence),
    ``w = y_lo − y_up`` the pricing/aiming duals, ``eps_obj`` the iterate's
    objective value (a stall indicator, not a bound), and ``ok`` the solver's
    own convergence flag. Columns are bucket-padded so the jitted core
    compiles once per bucket (same idiom as ``solve_stage_lp_pdhg``).
    """
    from citizensassemblies_tpu.solvers.lp_pdhg import (
        finish_two_sided_master,
        solve_two_sided_master_async,
        solve_two_sided_master_ell_async,
    )

    T, C = MT.shape
    if ell is not None:
        handle = solve_two_sided_master_ell_async(
            ell, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters
        )
    else:
        handle = solve_two_sided_master_async(
            MT, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters
        )
    if screen is not None:
        # chain the fused move screen onto the master's DEVICE dual output:
        # it enqueues behind the solve with no host involvement, so the
        # round's only host↔device synchronization point is the readback in
        # finish_two_sided_master below (the device-pricing round contract)
        screen(handle.lam)
    sol = finish_two_sided_master(handle)
    p = np.maximum(sol.x[:C], 0.0)
    total = p.sum()
    if not np.isfinite(total) or total <= 0.0:
        return (
            float("inf"),
            np.zeros(T),
            np.full(C, 1.0 / max(C, 1)),
            float("inf"),
            None,
            False,
        )
    p_norm = p / total
    eps_real = float(np.abs(MT @ p_norm - v).max())
    lam = np.maximum(sol.lam, 0.0)
    w = lam[:T] - lam[T:]
    return eps_real, w, p_norm, float(sol.objective), (sol.x, sol.lam, sol.mu), sol.ok


class _AnchorPricer:
    """Double-buffered host pricing for the face loop's anchor MILPs.

    The anchors (one dual-direction optimum, alternate-round noisy variants,
    up to three forced-inclusion columns for persistent deficits) are
    HEURISTIC columns — acceptance is the master iterate's arithmetic
    residual — so their aim may lag the duals by one round without touching
    exactness. That staleness buys the pipeline: round r's MILPs are
    *submitted* the moment round r's duals exist and *harvested* at round
    r+1's expansion, so with ``overlap=True`` they execute on a worker thread
    while the main thread runs the neighbor expansion, the next device master
    and any polish (HiGHS releases the GIL inside its solve, and the main
    thread releases it waiting on the device). ``overlap=False`` runs the
    SAME schedule inline at the submit point — the emitted column stream is
    bit-identical between the two modes, which is the serial fallback's
    regression contract (``tests/test_face_decompose.py``). All randomness
    (the noisy-anchor perturbations) is drawn on the caller's thread at
    submit time, so the schedule is deterministic either way.

    With ``device`` set (``solvers/device_pricing.DevicePricer``, behind the
    ``Config.decomp_device_pricing`` gate) the worker is the ACCELERATOR
    instead of a host thread: ``submit`` prices the whole task batch in one
    async device dispatch (β-ladder greedy lanes, or the exact DP lane on
    single-category reductions) and ``harvest`` decodes it — tasks the
    device served skip their host MILP entirely
    (``decomp_oracle_device_hit``), tasks with no surviving lane fall back
    to the exact host MILP (``decomp_oracle_device_miss``): the device
    screen only ever REDUCES host oracle calls, it never replaces the exact
    path. The task schedule — forced-inclusion routing, alternate-round
    noisy variants, the one-round lag — is identical to the host modes.
    """

    def __init__(
        self,
        oracle,
        rng: np.random.Generator,
        reduction: TypeReduction,
        overlap: bool,
        log: Optional[RunLog] = None,
        device=None,
    ):
        self.oracle = oracle
        self.rng = rng
        self.red = reduction
        self.log = log
        self.device = device
        # fault injection rides a ContextVar; the overlap worker thread is
        # outside the request's context scope, so capture the injector here
        # (on the constructing thread) and consult it explicitly
        self._inj = inject.active_injector()
        self._pool = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="anchor-pricer")
            if overlap and device is None
            else None
        )
        self._pending: Optional[Union[Future, List[np.ndarray], tuple]] = None

    def _run(self, tasks) -> List[np.ndarray]:
        out = []
        for weights, forced in tasks:
            # oracle backend failures (injected or real) retry once, then
            # SKIP the task: anchors are heuristic columns — acceptance is
            # the master iterate's arithmetic residual, so a missing anchor
            # costs at most convergence speed, never exactness
            for attempt in (0, 1):
                try:
                    inject.raise_if("oracle_raise", self.log, inj=self._inj)
                    # 1 % MILP gap: anchor optimality buys nothing (see the
                    # caller's acceptance semantics) and the gap cuts the
                    # anchor share of the decomposition wall-clock ~20 % on
                    # the flagship
                    got = self.oracle.maximize(
                        weights, forced_type=forced, rel_gap=1e-2
                    )
                    if got is not None:
                        out.append(got[0][None, :].astype(np.int16))
                    break
                except Exception:
                    if self.log is not None:
                        self.log.count(
                            "robust_oracle_skip" if attempt
                            else "robust_oracle_retry"
                        )
        return out

    def submit(
        self,
        rnd: int,
        r_norm: np.ndarray,
        eps: float,
        realized: Optional[np.ndarray],
        v: np.ndarray,
    ) -> None:
        """Queue round ``rnd``'s anchor MILPs (noise drawn HERE, on the
        caller's thread). Any un-harvested previous submission is replaced —
        callers harvest before submitting, so that only happens on loop exit.
        """
        tasks: List[Tuple[np.ndarray, Optional[int]]] = [(-r_norm, None)]
        if rnd % 2 == 0:
            # noisy variants only diversify, so they run on alternate rounds
            scale = float(np.mean(np.abs(r_norm))) + 1e-12
            for _ in range(2):
                tasks.append(
                    (-r_norm + self.rng.normal(0.0, 0.5 * scale, len(r_norm)), None)
                )
        if realized is not None:
            # forced-inclusion anchors on the worst under-served types: a type
            # whose deficit persists needs columns that *contain* it, which
            # the global dual direction alone may never produce (rare types
            # have near-zero objective weight)
            deficit = v - realized
            worst = np.argsort(-deficit)[:3]
            for t in worst:
                if deficit[t] > 0.25 * eps and self.red.msize[t] > 0:
                    tasks.append((-r_norm, int(t)))
        # pod runs: each process prices only its contiguous slice of the
        # anchor batch (column pools merge at the next harvest); the
        # single-process slice is the whole list, so the schedule is
        # bit-identical to the undistributed pricer
        lo, hi = dist_runtime.process_slice(len(tasks))
        tasks = tasks[lo:hi]
        if self.device is not None:
            # the accelerator is the worker: one async dispatch prices the
            # whole batch; the handle is decoded at the next harvest
            try:
                inject.raise_if("device_dispatch", self.log, inj=self._inj)
                self._pending = ("device", self.device.dispatch(tasks), tasks)
                return
            except Exception:
                # device-pricing dispatch failed (injected or real): walk
                # the ladder's first rung — the exact host MILP carries the
                # rest of the run (the device screen only ever REDUCED host
                # work, so dropping it is a pure slowdown, never a
                # correctness change)
                if self.log is not None:
                    self.log.count("robust_degrade_device_pricing")
                self.device = None
        if self._pool is not None:
            self._pending = self._pool.submit(self._run, tasks)
        else:
            self._pending = self._run(tasks)

    def _harvest_device(self, handle, tasks) -> List[np.ndarray]:
        """Decode a device pricing dispatch: device-served tasks in task
        order, then the host-MILP results for the misses (the fallback runs
        inline — misses are the exception, and by harvest time the pipeline
        has no thread to hide them behind)."""
        if handle is None:
            return []
        hits, missed = self.device.harvest(handle)
        if self.log is not None:
            if hits:
                self.log.count("decomp_oracle_device_hit", len(hits))
                self.log.count("oracle_backend_device", len(hits))
            if missed:
                self.log.count("decomp_oracle_device_miss", len(missed))
        out = [comp for _i, comp in hits]
        if missed:
            out.extend(self._run([tasks[i] for i in missed]))
        return out

    def harvest(self) -> List[np.ndarray]:
        """Collect the previously submitted round's columns (blocks only when
        the worker has not finished — counted separately from clean overlap
        hits so the bench can see how often the pipeline actually hid the
        pricing)."""
        pending, self._pending = self._pending, None
        if pending is None:
            return []
        if isinstance(pending, tuple) and pending and pending[0] == "device":
            return self._harvest_device(pending[1], pending[2])
        if isinstance(pending, list):
            if self.log is not None:
                self.log.count("decomp_oracle_inline")
            return pending
        if self.log is not None:
            self.log.count(
                "decomp_oracle_overlap_hit"
                if pending.done()
                else "decomp_oracle_overlap_wait"
            )
        return pending.result()

    def close(self) -> None:
        """Drop any un-harvested job and stop the worker. A MILP already
        executing finishes (sub-second); a queued-but-unstarted one is
        cancelled."""
        pending, self._pending = self._pending, None
        if isinstance(pending, Future):
            pending.cancel()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


class _WarmStall:
    """Cold-restart policy for the warm-started PDHG master.

    A warm iterate normally saves the equilibration transient, but a stalled
    first-order iterate can sit in a corner the (column-augmented) problem
    has moved away from, where restarting from zero re-equilibrates faster
    than escaping. Policy: a warm-started round that fails to beat the
    running-best ε by ≥ ``(1 − improve)`` extends a streak; ``patience``
    consecutive such rounds ⇒ drop the warm iterate once (the caller
    cold-starts the next master and resumes warm from its result). Cold
    rounds never extend the streak, so one reset cannot cascade into
    permanently disabling the warm path.
    """

    def __init__(self, patience: int, improve: float = 0.98):
        self.patience = max(int(patience), 1)
        self.improve = improve
        self.best = float("inf")
        self.streak = 0

    def update(self, eps: float, warm_used: bool) -> bool:
        improved = eps < self.best * self.improve
        self.best = min(self.best, eps)
        if improved or not warm_used:
            if improved:
                self.streak = 0
            return False
        self.streak += 1
        if self.streak >= self.patience:
            self.streak = 0
            return True
        return False


def realize_profile(
    reduction: TypeReduction,
    v: np.ndarray,
    seed_comps: List[np.ndarray],
    oracle,
    accept: float,
    log: Optional[RunLog] = None,
    max_rounds: int = 60,
    master_cap: int = 6_000,
    use_pdhg: Optional[bool] = None,
    cfg=None,
    ctx=None,
) -> Tuple[np.ndarray, Optional[np.ndarray], float, int]:
    """Find compositions + probabilities with ``‖Mp − v‖∞ ≤ accept``.

    The per-round master is the warm-started device PDHG (host interior
    point on CPU-only backends, where PDHG's iteration count doesn't pay):
    its duals aim the neighbor expansion and the *arithmetic* residual of
    its normalized iterate is the acceptance certificate, so no round waits
    on an exact host solve. When the approximate master's objective dips
    near ``accept`` but its iterate lags (first-order tail), one host IPM
    polish on the mass-bearing support extracts the exact LP optimum — the
    only host solve in the loop.

    Aggressive pruning (support + freshest columns) keeps every master at
    ≤ ``master_cap`` columns — the face needs only ~T active columns, and
    neighbors of the *current* support regenerate any hull information a
    prune discards.

    Returns ``(compositions int32 [C, T], probabilities float64 [C],
    eps, lp_solves)``; callers fall back to stage CG when ``eps > accept``.
    """
    from citizensassemblies_tpu.service.context import resolve as resolve_context
    from citizensassemblies_tpu.solvers.cg_typespace import _decomp_lp

    # per-request re-entrancy: resolve cfg/log through the ambient (or
    # explicitly passed) RequestContext; the context is (re)installed around
    # the round loop below so the batched-engine calls see it
    ctx, cfg, log = resolve_context(ctx, cfg, log)
    # grafttrace: the pre-loop construction (seeding, screen/pricer init,
    # pack state) as one open interval, so the phase's trace coverage is
    # round spans + polish + this — no untraced gap before round 1. All
    # span helpers are inert (None) when no tracer is installed.
    _setup_span = begin_span("decomp_setup", log=log)
    T = reduction.T
    m = reduction.msize.astype(np.float64)
    if use_pdhg is None:
        import jax

        use_pdhg = jax.default_backend() not in ("cpu",)
    accel = bool(use_pdhg)
    if T <= cfg.decomp_host_master_max_types:
        # small-T instances stay on host masters end to end: cap the column
        # set so the expansion cannot push the master past the host's sweet
        # spot (a 6k-column round paid a device round-trip OR a ~2 s host
        # solve; the top-ranked ~1.5k neighbors carry the hull information)
        master_cap = min(master_cap, cfg.decomp_host_master_max_cols)

    seen: Dict[bytes, int] = {}
    cols: List[np.ndarray] = []

    def add(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(cols)
        cols.append(c.astype(np.int16))
        return True

    # --- crash-consistent checkpointing (robust/checkpoint) ----------------
    # the loop's certified state (columns + mixture + arithmetic ε) saves
    # every N rounds; a matching snapshot resumes HERE — its columns seed
    # the hull FIRST (so its mixture maps positionally onto the warm start)
    # and the seeds dedup in behind them
    _ckpt = FaceCheckpointer(cfg, reduction, v, accept)
    _resume = _ckpt.load(T) if _ckpt.enabled else None
    if _resume is not None:
        for c in _resume.compositions:
            add(c)
        log.count("robust_resume")
        log.emit(
            f"  face checkpoint resumed: {len(cols)} columns from round "
            f"{_resume.round} (eps {_resume.eps:.2e})."
        )

    for c in seed_comps:
        add(c)

    # --- structured-sparse master state (solvers/sparse_ops) ----------------
    # Master columns are compositions: ≤ k nonzeros of T types, so at the
    # large-T regimes (sf_e mild-skew T=565, household quotient T=1199) the
    # dense MT is ≥90 % zeros. The ELL pack is maintained INCREMENTALLY in
    # lockstep with ``cols``: appends pack only the new columns
    # (``ell_synced``), a prune subsets by fancy indexing, and only a
    # column-set replacement from ``best`` invalidates it. Fill is measured
    # per master; the auto gate (``Config.sparse_ops``) decides per solve.
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack, sparse_enabled

    sparse_try = accel and getattr(cfg, "sparse_ops", None) is not False
    ell_pack: Optional[EllPack] = EllPack(minor=T) if sparse_try else None

    def ell_synced() -> Optional[EllPack]:
        """Append any columns added since the last sync (packs ONLY those);
        returns the pack, or None when the sparse path is off."""
        nonlocal ell_pack
        if ell_pack is None:
            return None
        if len(ell_pack) > len(cols):  # pragma: no cover - defensive
            ell_pack = EllPack(minor=T)
        if len(ell_pack) < len(cols):
            with log.timer("sparse_pack"):
                new = (
                    np.stack(cols[len(ell_pack) :]).astype(np.float64)
                    / m[None, :]
                )
                ell_pack.append(new)
        return ell_pack

    def top_mass(p: np.ndarray, cap: int = 2048, frac: float = 1.0 - 1e-10):
        """Indices of the smallest column set carrying ``frac`` of the mass.

        Interior-point (and averaged-PDHG) optima spread thousands of tiny
        entries across the column set; a threshold-based "support" drags all
        of them through every later master. Mass-ranked selection keeps the
        ~basis-sized set that actually matters.
        """
        order = np.argsort(-p)
        cum = np.cumsum(p[order])
        cut = int(np.searchsorted(cum, frac * cum[-1])) + 1
        return order[: min(max(cut, 1), cap)]

    if not cols:
        # nothing to decompose from (pathological seeding) — report failure
        # so the caller takes the stage-CG fallback
        end_span(_setup_span, log=log)
        return np.zeros((0, T), np.int32), np.zeros(0), float("inf"), 0

    def polish_support(
        p_now: Optional[np.ndarray],
        bar: Optional[float] = None,
        master_warm: Optional[tuple] = None,
    ):
        """End-game solve on the mass-bearing support: the first-order
        master's iterate realizes ``v`` only to O(1/k) — when its objective
        says the support can do better, one tighter solve on the ~2k
        mass-bearing columns extracts it.

        With the batched LP engine enabled, several CANDIDATE polish faces
        (nested mass-ranked support prefixes) are screened as ONE padded
        vmapped device call first: a smaller support that already realizes
        ``v`` within the bar converges in a fraction of the deep solve's
        iterations, and every candidate carries the same arithmetic float64
        ε certificate — the accept bar is unchanged, only the number of
        device dispatches per attempt drops. On a miss (or with the engine
        off) the serial path below runs bit-identically.

        On accelerators a DEEP structured-PDHG solve runs next (~2.5 s,
        host-contention-free); its normalized iterate carries the same
        arithmetic ε certificate as everything else in this loop, so it is
        accepted whenever it reaches ``bar``. ``master_warm`` (the master's
        raw (x, λ, μ) triple) warm-starts it: the primal restriction of the
        master iterate to the support plus the master's own row duals — the
        rows are the same T types, so the duals transfer exactly — which
        skips most of the polish's ramp-up instead of re-deriving it from
        zero. The host IPM (exact, but 4–7 s per call at T ≈ 1000 and the
        single most host-contention-sensitive phase of the flagship) runs
        only when the device polish misses the bar."""
        nonlocal lp_solves
        if p_now is not None and len(p_now) == len(cols):
            sup = top_mass(p_now, cap=2048)
        else:
            sup = np.arange(len(cols))[:4096]
        C_sup = np.stack([cols[i] for i in sup]).astype(np.int32)
        MTs = np.ascontiguousarray((C_sup.astype(np.float64) / m[None, :]).T)
        the_bar = bar if bar is not None else stalled_band
        # ELL pack of the support: a pure subset of the synced incremental
        # pack when the iterate still corresponds to ``cols`` (no re-pack at
        # all), a fresh pack otherwise; the fill gate then decides per solve
        ell_sup = None
        if sparse_try:
            if (
                ell_pack is not None
                and p_now is not None
                and len(p_now) == len(cols)
                and len(ell_pack) == len(cols)
            ):
                cand_pack = ell_pack.take(sup)
            else:
                with log.timer("sparse_pack"):
                    cand_pack = EllPack.from_rows(MTs.T, minor=T)
            if sparse_enabled(cfg, cand_pack.fill):
                ell_sup = cand_pack
        if accel and batch_screen and len(sup) > _POLISH_SCREEN_MIN_SUP:
            # batched polish-face screen: nested support prefixes solved as
            # one padded vmapped dispatch, each judged by its own float64
            # arithmetic residual — identical accept-bar semantics
            from citizensassemblies_tpu.solvers.batch_lp import (
                solve_lp_batch,
                solve_polish_screen_ell,
                two_sided_master_batch_lp,
            )

            # nested mass-ranked prefixes: ¼ and ½ of the support plus the
            # full set (at the production 2048-cap support that is 512/1024/
            # 2048 columns) — the small faces converge in a fraction of the
            # deep solve's iterations when they already realize v
            caps = sorted({max(len(sup) // 4, 1), max(len(sup) // 2, 1), len(sup)})
            warm_ok = (
                cfg.decomp_warm_start
                and master_warm is not None
                and p_now is not None
                and len(p_now) == len(cols)
            )
            if ell_sup is not None:
                # sparse screen: ONE shared pack feeds every prefix lane —
                # the lanes differ only in their column mask
                warms = []
                for c_ in caps:
                    if warm_ok:
                        x0 = np.concatenate(
                            [p_now[sup[:c_]], [max(float(master_warm[0][-1]), 0.0)]]
                        )
                        warms.append((x0, master_warm[1], master_warm[2]))
                    else:
                        warms.append(None)
                with log.timer("decomp_polish_screen"):
                    sols = solve_polish_screen_ell(
                        ell_sup, v, caps, warms, tol=0.25 * master_tol,
                        max_iters=24_576, cfg=cfg, log=log,
                    )
                log.count("decomp_host_syncs")
                log.count("decomp_polish_syncs")  # end-game, not steady-state
            else:
                insts = []
                for c_ in caps:
                    inst = two_sided_master_batch_lp(
                        MTs[:, :c_], v, tol=0.25 * master_tol
                    )
                    if warm_ok:
                        x0 = np.concatenate(
                            [p_now[sup[:c_]], [max(float(master_warm[0][-1]), 0.0)]]
                        )
                        inst.warm = (x0, master_warm[1], master_warm[2])
                    insts.append(inst)
                with log.timer("decomp_polish_screen"):
                    # one SHARED bucket: the nested prefixes differ only in
                    # column count, and one fused dispatch is the whole point
                    sols = solve_lp_batch(
                        insts, cfg=cfg, log=log, warm_key="decomp_polish_screen",
                        max_iters=24_576, common_bucket=True,
                    )
                log.count("decomp_host_syncs")
                log.count("decomp_polish_syncs")  # end-game, not steady-state
            lp_solves += 1
            best_s = None
            for c_, sol in zip(caps, sols):
                p_s = np.maximum(sol.x[:c_], 0.0)
                tot = p_s.sum()
                if not np.isfinite(tot) or tot <= 0:
                    continue
                p_s = p_s / tot
                eps_s = float(np.abs(MTs[:, :c_] @ p_s - v).max())
                if best_s is None or eps_s < best_s[2]:
                    best_s = (c_, p_s, eps_s)
            if best_s is not None and best_s[2] <= the_bar:
                c_, p_s, eps_s = best_s
                log.count("lp_batch_polish_hit")
                return C_sup[:c_], p_s, eps_s
            log.count("lp_batch_polish_miss")
        if accel:
            from citizensassemblies_tpu.solvers.lp_pdhg import (
                solve_two_sided_master,
                solve_two_sided_master_ell,
            )

            warm_s = None
            if (
                cfg.decomp_warm_start
                and master_warm is not None
                and p_now is not None
                and len(p_now) == len(cols)
            ):
                # x: the master iterate's mass on the support columns, ε slot
                # from the master's own ε variable; λ/μ transfer verbatim
                # (same T rows, same Σp row)
                x0 = np.concatenate(
                    [p_now[sup], [max(float(master_warm[0][-1]), 0.0)]]
                )
                warm_s = (x0, master_warm[1], master_warm[2])
                log.count("decomp_polish_warm")
            if ell_sup is not None:
                sol = solve_two_sided_master_ell(
                    ell_sup, v, cfg=cfg, warm=warm_s, tol=0.25 * master_tol,
                    max_iters=98_304,
                )
            else:
                sol = solve_two_sided_master(
                    MTs, v, cfg=cfg, warm=warm_s, tol=0.25 * master_tol,
                    max_iters=98_304,
                )
            lp_solves += 1
            log.count("decomp_host_syncs")  # deep device polish round trip
            log.count("decomp_polish_syncs")  # end-game, not steady-state
            p_s = np.maximum(sol.x[: MTs.shape[1]], 0.0)
            tot = p_s.sum()
            if np.isfinite(tot) and tot > 0:
                p_s = p_s / tot
                eps_s = float(np.abs(MTs @ p_s - v).max())
                if eps_s <= (bar if bar is not None else stalled_band):
                    return C_sup, p_s, eps_s
        eps_s, _w, _mu, p_s = _decomp_lp(MTs, v)
        lp_solves += 1
        return C_sup, p_s, float(eps_s)

    lp_solves = 0
    eps = np.inf
    p = np.zeros(0)
    rng = np.random.default_rng(0)
    eps_hist: List[float] = []
    pdhg_warm = None
    if _resume is not None and len(_resume.probabilities) <= len(cols):
        # warm the first master from the checkpointed mixture: its columns
        # were added first, so the probabilities map positionally; the ε
        # slot carries the certified residual at save time
        x_w = np.zeros(len(cols) + 1)
        x_w[: len(_resume.probabilities)] = _resume.probabilities
        x_w[-1] = max(float(_resume.eps), 0.0)
        pdhg_warm = (x_w, np.zeros(2 * T), np.zeros(1))
    #: per-request deadline (robust/policy), threaded through the ambient
    #: RequestContext — checked once per round below, at the round's
    #: existing host sync point (a host clock read: no new device syncs)
    deadline = getattr(ctx, "deadline", None) if ctx is not None else None
    best: Optional[Tuple[np.ndarray, np.ndarray, float]] = None
    t_start = time.time()
    # the stalled-acceptance band the caller still accepts (cg_typespace
    # accepts eps <= max(decomp_accept, decomp_accept_stalled) outright), so
    # stopping inside it never triggers the stage-CG fallback
    stalled_band = max(accept, getattr(cfg, "decomp_accept_stalled", accept))
    # f32 KKT tolerance for the approximate master: two orders below the
    # acceptance bar recovers the early exit once the warm-started iterate is
    # past the accuracy the (float64, arithmetic) accept check needs
    master_tol = max(0.02 * accept, cfg.pdhg_tol)
    # cooldown after a failed IPM polish: the LP optimum only decreases as
    # columns arrive, so without it a near-accept optimum would trigger a
    # host solve every remaining round
    polish_after = 0
    # --- the pipelined engine's moving parts --------------------------------
    # anchor MILPs double-buffered against the device master (see
    # _AnchorPricer: identical column schedule whether threaded or inline),
    # a cold-restart policy for the warm-started master, and the batched
    # device move screen on accelerator backends. Behind the
    # Config.decomp_device_pricing gate the anchor worker is the ACCELERATOR
    # (solvers/device_pricing): one dispatch prices the whole batch, the
    # host MILP runs only for tasks the device screen misses, and the move
    # screen chains onto the master's device duals (_FusedScreen) so the
    # steady-state round keeps a single host↔device synchronization point.
    dev_pricer = None
    if accel:
        from citizensassemblies_tpu.solvers.device_pricing import (
            DevicePricer,
            device_pricing_enabled,
        )

        if device_pricing_enabled(cfg):
            dev_pricer = DevicePricer(reduction, cfg=cfg, log=log)
    pricer = _AnchorPricer(
        oracle, rng, reduction,
        overlap=bool(getattr(cfg, "decomp_oracle_overlap", True)), log=log,
        device=dev_pricer,
    )
    warm_enabled = bool(getattr(cfg, "decomp_warm_start", True))
    warm_stall = _WarmStall(int(getattr(cfg, "decomp_warm_stall_rounds", 3)))
    batched_expand = bool(getattr(cfg, "decomp_batched_expand", True)) and accel
    fused_screen = (
        _FusedScreen(reduction, per_round_cap=16_384, cfg=cfg)
        if dev_pricer is not None and batched_expand
        else None
    )
    if fused_screen is not None and not fused_screen.ok:  # pragma: no cover
        fused_screen = None
    # batched polish-face screening (solvers/batch_lp.py): candidate support
    # prefixes solved as one vmapped dispatch in the end-game
    from citizensassemblies_tpu.solvers.batch_lp import (
        clear_warm_slots,
        lp_batch_enabled,
    )

    batch_screen = accel and lp_batch_enabled(cfg)
    if batch_screen:
        # the screen's warm slots are per-run state, not cross-run state:
        # a previous instance's iterate must not leak into this profile
        clear_warm_slots("decomp_polish_screen")

    def rank_add(cand: List[np.ndarray], r_norm: np.ndarray) -> int:
        """Grow the master where it helps: most negative <r, c/m> first
        (r_norm = -w/m, so ascending r_norm-value = descending dual
        improvement w.c/m)."""
        if not cand:
            return 0
        added = 0
        with log.timer("decomp_expand"):
            batch = np.concatenate([np.atleast_2d(c) for c in cand], axis=0)
            vals = batch.astype(np.float64) @ r_norm
            order = np.argsort(vals)
            cap = max(256, master_cap - len(cols))
            for i in order[:cap]:
                added += add(batch[i])
        return added

    # compilation counter over the whole face loop: the padded buckets exist
    # so CG rounds re-enter compiled executables — the count lands in the
    # phase counters (xla_compiles_decomp) where a per-round recompile would
    # be immediately visible next to the warm-start/overlap attribution
    from contextlib import ExitStack

    from citizensassemblies_tpu.service.context import use_context

    _guards = ExitStack()
    _guards.enter_context(use_context(ctx))
    _guards.enter_context(CompilationGuard("decomp", log=log))
    end_span(_setup_span, log=log)
    # grafttrace round tiling: consecutive OPEN intervals — each round's
    # span ends where the next begins (begin_span/end_span, unstacked), so
    # the loop's wall time is covered without re-indenting its body; the
    # phase timers inside (decomp_master, decomp_oracle, decomp_expand,
    # decomp_polish) record as sibling spans via RunLog.timer
    _round_span = None
    try:
        for rnd in range(max_rounds):
            t_round = time.time()
            end_span(_round_span, log=log)
            _round_span = begin_span("decomp_round", log=log, round=rnd)
            # robustness gates, once per round at the round's host boundary:
            # the deadline check is a host clock read (raises a graceful
            # DeadlineExceeded with the best-so-far evidence instead of
            # grinding past the budget), and face_abort is the chaos kill
            # switch the checkpoint/resume contract is tested against
            if deadline is not None:
                deadline.check(
                    "face_decompose round", log=log,
                    partial={
                        "decomp_rounds": rnd,
                        "best_eps": float(best[2]) if best is not None else None,
                    },
                )
            inject.raise_if("face_abort", log)
            # stall detection on the RUNNING BEST: the per-round arithmetic
            # eps of a first-order iterate wobbles +-30 %, and comparing raw
            # values made noisy upticks read as a stall while the hull was
            # still improving
            if len(eps_hist) >= 7 and min(eps_hist[-4:]) > min(eps_hist[:-4]) * 0.98:
                # the best of the last 4 rounds failed to beat the running
                # best of all earlier rounds by >=2 %: an integrality residual
                # the face cannot close (e.g. a fractionally-coverable type no
                # integer composition contains) -- stop burning rounds; the
                # stage-CG fallback recomputes every value over realizable
                # columns only, so such types settle at their true (possibly
                # 0) values there
                log.emit(
                    f"  face rounds stalling at eps={eps_hist[-1]:.2e}; stopping early."
                )
                break
            # per-round normalization for the host-sync gauge: bench rows and
            # the smoke assertion report decomp_host_syncs / decomp_rounds
            log.count("decomp_rounds")
            C = np.stack(cols, axis=0)
            MT = np.ascontiguousarray((C.astype(np.float64) / m[None, :]).T)
            # per-round master selection: small problems solve exactly on host
            # faster than one accelerator round-trip; large ones want the device
            use_pdhg = accel and (
                T > cfg.decomp_host_master_max_types
                or len(cols) > cfg.decomp_host_master_max_cols
            )
            polish_warm = None
            if use_pdhg:
                import jax

                if (
                    jax.device_count() > 1
                    and MT.shape[0] >= cfg.master_shard_min_types
                ):
                    # beyond-one-chip master: rows sharded over the mesh,
                    # psum-reduced transposes (no warm start -- the sharded
                    # regime trades it for memory scale-out)
                    from citizensassemblies_tpu.parallel.mesh import default_mesh
                    from citizensassemblies_tpu.parallel.solver import (
                        solve_decomp_master_sharded,
                    )

                    with log.timer("decomp_master"):
                        eps, w, p, eps_obj, _ok = solve_decomp_master_sharded(
                            MT, v, default_mesh(), cfg=cfg, tol=master_tol
                        )
                    pdhg_warm = None
                    lp_solves += 1
                    # one host→device upload + device→host harvest per
                    # sharded master (the decomp_host_syncs gauge: ROADMAP
                    # item 2 wants the CG round's round-trip count measured
                    # before device-resident pricing can claim to kill it)
                    log.count("decomp_host_syncs")
                else:
                    # adaptive budget: far from acceptance the duals only need
                    # to be roughly right to aim the expansion; near it the
                    # iterate itself must realize v, so spend the iterations
                    # where they matter. (A 4x deeper near-phase budget was
                    # measured NOT to cut the round count -- the iterate lag on
                    # the hard seeds is hull quality, not iteration starvation --
                    # while adding ~0.5 s/master, so the budgets stay here.)
                    far = not eps_hist or eps_hist[-1] > 6 * accept
                    warm_arg = pdhg_warm if warm_enabled else None
                    log.count(
                        "decomp_master_warm" if warm_arg is not None
                        else "decomp_master_cold"
                    )
                    # sparse routing: sync the incremental pack (only new
                    # columns re-pack), then gate on the measured fill
                    ell_now = ell_synced()
                    use_sparse = False
                    if ell_now is not None:
                        use_sparse = sparse_enabled(cfg, ell_now.fill)
                        log.gauge(
                            "sparse_fill_pct", int(round(100 * ell_now.fill))
                        )
                        log.count("sparse_hit" if use_sparse else "sparse_miss")
                    screen_cb = None
                    if fused_screen is not None:
                        # the screened block is this master's own columns in
                        # mass-ranked order (C is cols stacked: previous
                        # prune's support first) — known NOW, before the
                        # master returns, so the screen can chain onto its
                        # device duals with no intermediate readback
                        comps_block = C[:_SCREEN_ROWS]

                        def screen_cb(lam_dev, _blk=comps_block):
                            with log.timer("decomp_expand"):
                                fused_screen.dispatch(_blk, lam_dev)

                    with log.timer("decomp_master"):
                        eps, w, p, eps_obj, pdhg_warm, _ok = _master_pdhg(
                            MT, v, cfg, warm_arg,
                            max_iters=4_096 if far else 12_288, tol=master_tol,
                            ell=ell_now if use_sparse else None,
                            screen=screen_cb,
                        )
                    lp_solves += 1
                    # device master: operand upload + iterate harvest is one
                    # host↔device round trip of the CG round (in device-
                    # pricing mode the fused screen and the lagged anchor
                    # batch piggyback on this same synchronization point)
                    log.count("decomp_host_syncs")
                    if not np.isfinite(eps):
                        # quarantined/poisoned master (the sentinel froze the
                        # lane, or its mixture went non-finite): re-solve
                        # THIS round on the serial float64 host path — the
                        # certified ladder rung — and cold-start the next
                        # device master
                        log.count("sentinel_quarantined")
                        log.count("robust_host_resolve")
                        with log.timer("decomp_master"):
                            eps, w, _mu_h, p = _decomp_lp(MT, v)
                        eps_obj = float(eps)
                        pdhg_warm = None
                        lp_solves += 1
                    polish_warm = pdhg_warm
                    if not warm_enabled:
                        pdhg_warm = None
                    elif warm_stall.update(eps, warm_arg is not None):
                        # the warm iterate is no longer buying progress:
                        # cold-start the next master once (warm resumes from
                        # its result -- see _WarmStall)
                        pdhg_warm = None
                        log.count("decomp_warm_cold_restart")
                        log.emit(
                            f"  warm-started master stalling at eps={eps:.2e}; "
                            "cold-restarting the iterate."
                        )
                # end-game: the approximate objective says the support should
                # be able to realize v, but the first-order iterate's own
                # residual still lags -- extract the exact optimum once on the
                # support. Deep into the time budget the OBJECTIVE-based
                # trigger widens slightly (the objective signals hull
                # readiness; widening on the ITERATE gambled failed polishes
                # every cooldown -- measured +35 % flagship seed-0 wall-clock)
                deep = time.time() - t_start > 0.6 * cfg.decomp_time_budget_s
                near = (
                    eps <= accept * 1.25
                    or eps_obj <= accept * 1.05
                    or (deep and eps_obj <= 1.2 * accept)
                )
                if eps > accept and near and rnd >= polish_after:
                    with log.timer("decomp_polish"):
                        C_sup, p_sup, eps_sup = polish_support(
                            p, bar=(stalled_band if deep else accept),
                            master_warm=polish_warm,
                        )
                    log.emit(
                        f"  polish: {len(C_sup)} support cols -> eps={eps_sup:.2e} "
                        f"(iterate eps={eps:.2e}, obj~{eps_obj:.2e})."
                    )
                    # deep into the time budget, a polish inside the stalled
                    # band ends the run -- the caller accepts that band
                    # outright, and the alternative is another master round
                    # plus the same end-game polish (measured ~20 s of tail
                    # per flagship rep)
                    if eps_sup <= (stalled_band if deep else accept):
                        log.emit(
                            f"Face decomposition: eps = {eps_sup:.2e} certified on "
                            f"{len(C_sup)} support columns ({lp_solves} master solves, "
                            f"end-game polish)."
                        )
                        _ckpt.clear()  # certified: no stale resume point
                        return C_sup, p_sup, eps_sup, lp_solves
                    # discard the failed polish value: it is the optimum of a
                    # support SUBSET, not something the full-column iterate
                    # attains -- mixing it into eps/eps_hist/best would make
                    # the stall detector and the best-hull tracker compare
                    # incommensurable quantities
                    polish_after = rnd + 2
            else:
                with log.timer("decomp_master"):
                    eps, w, _mu, p = _decomp_lp(MT, v)
                lp_solves += 1
            eps_hist.append(eps)
            if best is None or eps < best[2]:
                best = (C, p, eps)
            if best is not None and len(best[1]) == len(best[0]):
                # snapshot the RUNNING BEST (already certified by its
                # arithmetic residual) at the round boundary — a killed
                # request resumes from here instead of restarting
                _ckpt.maybe_save(rnd, best[0], best[1], best[2], log=log)
            if (
                time.time() - t_start > cfg.decomp_time_budget_s
                and best[2] <= stalled_band
                and eps > accept
            ):
                # budget exhausted with a residual the caller accepts anyway:
                # stop grinding rounds and let the end-game polish extract the
                # best support (bounds the worst-of-N tail)
                log.emit(
                    f"  face rounds over time budget ({cfg.decomp_time_budget_s:.0f}s) "
                    f"with best eps={best[2]:.2e} inside the stalled band; stopping."
                )
                break
            if eps <= accept:
                # return this certified master as-is: the certificate is the
                # arithmetic residual of p itself, independent of the solver
                log.emit(
                    f"Face decomposition: eps = {eps:.2e} certified on {len(cols)} "
                    f"columns ({lp_solves} master solves)."
                )
                _ckpt.clear()  # certified: no stale resume point
                return C.astype(np.int32), p, float(eps), lp_solves
            # the eps-LP duals w (= y_lo - y_up) mark over-served (w < 0) vs
            # under-served (w > 0) types; move units down the gradient
            r_norm = -w / m
            sup_idx = top_mass(p)  # mass-ordered, largest first
            # prune BEFORE expanding: the next master sees only the
            # mass-bearing support plus this round's additions
            n_before = len(cols)
            kept = [cols[i] for i in sup_idx]
            kept_p = p[sup_idx]
            cols.clear()
            seen.clear()
            for c in kept:
                add(c)
            if ell_pack is not None:
                # the prune is a pure subset/reorder: fancy-index the packed
                # arrays instead of re-packing (EllPack.take); a pack that
                # was out of sync (host-master rounds) restarts empty and
                # re-packs lazily at the next device master
                ell_pack = (
                    ell_pack.take(sup_idx)
                    if len(ell_pack) == n_before
                    else EllPack(minor=T)
                )
            # re-align the PDHG warm start with the pruned column order (kept
            # columns keep their primal mass; fresh columns start at zero)
            if pdhg_warm is not None:
                x_w = np.zeros(len(kept) + 1)
                x_w[: len(kept)] = kept_p
                x_w[-1] = max(eps, 0.0)
                pdhg_warm = (x_w, pdhg_warm[1], pdhg_warm[2])
            base = len(cols)
            cand: List[np.ndarray] = []
            # PIPELINE: harvest round r-1's anchor MILPs, then submit round
            # r's -- exact anchors are best compositions against the dual
            # direction, *compound* moves no single swap reaches; submitted
            # here, they execute on the worker thread while this round's
            # expansion and the NEXT round's device master run (the timer
            # therefore records only schedule overhead plus any blocking
            # wait, and the overlap_hit/wait counters say which it was)
            with log.timer("decomp_oracle"):
                cand.extend(pricer.harvest())
                realized = MT @ p if len(p) == MT.shape[1] else None
                pricer.submit(rnd, r_norm, eps, realized, v)
            if fused_screen is not None and fused_screen.pending:
                with log.timer("decomp_expand"):
                    # fused screen: dispatched during this round's master
                    # against its own device duals, complete by the time the
                    # master's readback returned — decoding it here costs no
                    # additional host↔device synchronization
                    moved = fused_screen.harvest()
                    if len(moved):
                        cand.append(moved)
            elif kept:
                with log.timer("decomp_expand"):
                    cand.append(
                        neighbor_columns(
                            np.stack(kept[:512]), reduction, r_norm,
                            batched=batched_expand, cfg=cfg,
                        )
                    )
                if batched_expand:
                    # the jitted move screen ships the candidate block down
                    # and the kept-move indices back up once per round
                    log.count("decomp_host_syncs")
            if (
                T <= cfg.decomp_host_master_max_types
                and rnd == 0
                and eps <= 6 * accept
            ):
                # small-T near-miss after the first master: a deeper
                # aimed-slice pass (finer apportionment of the same target,
                # phase-shifted streams) closes the hull in one host round
                # where generic neighbors needed a 6k-column expansion
                # (sf_d-class: R=2048 slices certify at eps 4.4e-4 vs 1.1e-3
                # from the 1024 injection). Measured NOT to help large-T
                # device-master instances: adding phase-shifted streams there
                # (rounds 0-2) left the per-round eps trajectory unchanged
                # while growing masters and stream cost -- sf_e mild-skew went
                # 47-68 s -> 71-89 s -- so the gate stays small-T; the large-T
                # eps tail is integrality structure the neighbor/anchor
                # expansion addresses, not missing hull bulk.
                from citizensassemblies_tpu.solvers.cg_typespace import (
                    _slice_relaxation,
                )

                # j0 phase-shifts the apportionment relative to the injection
                # stream (which ran the same target at j0=0): same hull, fresh
                # rounding boundaries -- without the shift this pass would
                # emit mostly byte-duplicates of the injected slices
                deep_slices = _slice_relaxation(
                    v * m, reduction, R=2048, j0=1 << 20, chunks=4
                )
                if deep_slices:
                    cand.append(np.stack(deep_slices).astype(np.int16))
            added = rank_add(cand, r_norm)
            if added == 0:
                # nothing new this round -- but this round's anchor job is
                # still pending; wait for it rather than concluding
                # exhaustion with columns in flight
                with log.timer("decomp_oracle"):
                    late = pricer.harvest()
                if dev_pricer is not None:
                    # the just-dispatched device batch had no master solve to
                    # hide behind: this harvest blocks on in-flight compute
                    log.count("decomp_host_syncs")
                added = rank_add(late, r_norm)
            obj_note = f" obj~{eps_obj:.2e}" if use_pdhg else ""
            log.emit(
                f"  face round {rnd + 1}: eps={eps:.2e}{obj_note} added {added} "
                f"(master {base}+{added}, {time.time() - t_round:.1f}s)."
            )
            if added == 0:
                break

        # out of rounds / stalled: one exact end-game solve on the best support
        end_span(_round_span, log=log)
        _round_span = None
        if best is not None and (len(p) != len(cols) or eps > accept):
            C_best, p_best, _ = best
            cols = [c for c in C_best]
            p = p_best
            if ell_pack is not None:
                # the column set was REPLACED (not appended/pruned): the
                # incremental pack no longer corresponds — drop it and let
                # the final polish re-pack its support from scratch
                ell_pack = EllPack(minor=T)
        with log.timer("decomp_polish"):
            # final polish at the TIGHT bar: stalled-band acceptance is the
            # in-loop deep path's explicit fallback criterion; the shipped
            # final eps takes the accept-level device polish when it reaches
            # it and the exact host IPM otherwise
            C_sup, p_sup, eps = polish_support(
                p if len(p) == len(cols) else None, bar=accept,
                master_warm=pdhg_warm,
            )
        log.emit(
            f"Face decomposition: eps = {eps:.2e} on {len(C_sup)} support columns "
            f"({lp_solves} master solves)."
        )
        _ckpt.clear()  # the loop ran to completion: no stale resume point
        return C_sup, p_sup, float(eps), lp_solves
    finally:
        # a certified in-loop return leaves the current round span open —
        # close it here (end_span is idempotent and None-safe)
        end_span(_round_span, log=log)
        _guards.close()
        pricer.close()
