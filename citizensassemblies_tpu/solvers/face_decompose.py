"""Realize a leximin profile as a mixture of feasible compositions, fast.

Phase 1 of the type-space solver (``cg_typespace.py``) must express the
probe-certified profile ``v`` as ``M p = v`` over feasible compositions. The
classic Dantzig-Wolfe master (ε-LP + exact MILP pricing) tails badly here:
the optimal face needs ~T active columns and pricing discovers them a handful
per round (~7 %/round ε decay at sf_e scale — minutes of wall-clock).

This engine replaces it with three TPU-idiomatic ingredients:

* **Aimed slices** (`cg_typespace._slice_relaxation`) seed the hull around
  the target marginal ``x* = v·m``.
* **Face-neighbor expansion** generates columns *combinatorially* instead of
  one-per-MILP: for support columns of the current master, every feasible
  single-unit move ``t → t'`` that shifts mass from over-served types
  (residual ``r_t > 0``) to under-served ones is itself a feasible
  composition on or near the face — thousands of useful columns per round
  from pure vectorized index arithmetic (quota feasibility of all
  (composition, move) pairs is checked with per-feature *bitmasks* packed
  into machine words, so a round's full candidate screen is a handful of
  wide integer ops).
* **A device-resident approximate master**: each round's ε-LP is solved by
  the warm-started PDHG core (``lp_pdhg.py``) on the accelerator — its duals
  aim the expansion, and *acceptance needs no trusted solver at all*: the
  certificate is the arithmetic identity ``ε = ‖M p − v‖∞`` evaluated on the
  returned mixture, so an approximate solver can terminate the loop the
  moment any iterate realizes the profile within tolerance (same two-sided
  ε semantics as the reference's final LP, ``leximin.py:453-464``). A host
  interior-point polish runs only in the end-game, when the approximate
  master says the support should realize ``v`` but its iterate hasn't
  converged tightly enough to show it.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.logging import RunLog


def _feature_bitmasks(reduction: TypeReduction):
    """Per-type donor/receiver feature masks for the move-feasibility screen.

    The quota conditions of a unit move collapse to bit tests: moving a unit
    *out* of type ``t`` decrements each of ``t``'s features, which is safe
    iff the composition's count stays ≥ lo there; moving *in* increments,
    safe iff ≤ hi. One 64-bit word covers every reference-shaped instance
    (F ≤ 64). Instances with MORE features — the household quotient's
    augmented incidence appends one one-hot class feature per household
    class, F = base + #classes — split by category: categories whose
    features all index < 64 ride the word, the rest are screened by direct
    gathers in :func:`neighbor_columns` (one gather per category — for the
    quotient that is the single class category, whose ``lo = 0`` even skips
    the donor side). Returns ``(feat_mask[T] uint64, leftover_cats)`` where
    ``leftover_cats`` lists category indices not covered by the mask, or
    ``None`` when no category fits a word at all.
    """
    feat_of = np.asarray(reduction.type_feature)
    ncat = feat_of.shape[1]
    word_cats = [ci for ci in range(ncat) if int(feat_of[:, ci].max()) < 64]
    if not word_cats:
        return None
    masks = np.zeros(reduction.T, dtype=np.uint64)
    for ci in word_cats:
        masks |= np.uint64(1) << feat_of[:, ci].astype(np.uint64)
    leftover = [ci for ci in range(ncat) if ci not in word_cats]
    return masks, leftover


def neighbor_columns(
    comps: np.ndarray,
    reduction: TypeReduction,
    r_norm: np.ndarray,
    # measured at 2× and 3× these widths on the two large-T regimes
    # (sf_e mild-skew T=565, household quotient T=1199): round count drops
    # ~linearly (7→4, 19→10) but per-round master cost rises to match —
    # wall-clock within noise either way, so the defaults stay at the
    # smaller, lower-variance setting
    pool_cap: int = 128,
    face_pairs: int = 12_288,
    per_round_cap: int = 16_384,
) -> np.ndarray:
    """Feasible single-unit moves from ``comps`` along and across the face.

    Two pair classes feed the expansion:

    * **improving** — move a unit from an over-served type (``r_norm > 0``)
      to an under-served one: pulls the hull toward the target;
    * **face-preserving** — pairs with ``|Δ(w/m)| ≈ 0``: enumerate the
      near-optimal face combinatorially, which is where the master's ~T
      active columns live (a MILP finds them only one per solve).

    A move ``t → t'`` from composition ``c`` is feasible iff ``c_t > 0``,
    ``c_{t'} < m_{t'}`` and, in every category where the two types' features
    differ, the donor's feature stays ≥ its lower quota and the receiver's
    ≤ its upper. The (composition, pair) screen packs those per-feature
    conditions into one machine word per composition (``_feature_bitmasks``),
    so the whole [S, P] check is three wide integer ops instead of 2·ncat
    float gathers. Returns the stacked new compositions (int16 [N, T]).
    """
    comps = comps.astype(np.int16, copy=False)  # 4× less gather traffic
    S, T = comps.shape
    feat_of = np.asarray(reduction.type_feature)  # [T, ncat]
    ncat = feat_of.shape[1]
    # clip before the int16 cast: composition entries are <= k (small), but
    # a pool type can exceed int16 range — the receiver check only needs
    # min(m, k+1), since no composition holds more than k of any type
    m = np.minimum(reduction.msize, reduction.k + 1).astype(np.int16)
    lo = reduction.qmin.astype(np.int64)
    hi = reduction.qmax.astype(np.int64)

    order = np.argsort(-r_norm)
    # improving pairs: extremes of the residual direction
    donors = order[:pool_cap]
    receivers = order[::-1][:pool_cap]
    ti_a, tj_a = np.meshgrid(donors, receivers, indexing="ij")
    pairs = [np.stack([ti_a.ravel(), tj_a.ravel()], axis=1)]
    # face pairs: smallest |Δ| over a broad random pool (full T² only for
    # small T)
    if T * T <= 1 << 18:
        di = np.repeat(np.arange(T), T)
        dj = np.tile(np.arange(T), T)
    else:
        rng = np.random.default_rng(T)
        di = rng.integers(0, T, size=face_pairs * 8)
        dj = rng.integers(0, T, size=face_pairs * 8)
    delta = np.abs(r_norm[di] - r_norm[dj])
    sel = np.argsort(delta)[:face_pairs]
    pairs.append(np.stack([di[sel], dj[sel]], axis=1))
    tp = np.concatenate(pairs, axis=0)
    tp = tp[tp[:, 0] != tp[:, 1]]
    tp = np.unique(tp, axis=0)
    ti, tj = tp[:, 0], tp[:, 1]
    P = len(ti)
    if P == 0:
        return np.zeros((0, T), dtype=np.int16)

    # per-composition feature counts [S, F]: float32 BLAS then cast — numpy
    # integer matmuls bypass BLAS, and at quotient scale ([512, 1199] @
    # [1199, 626]) the int64 product alone cost ~0.4 s per face round;
    # counts ≤ k ≤ a few hundred, far inside float32's exact-integer range
    F = reduction.F
    tf = np.zeros((T, F), dtype=np.float32)
    tf[np.repeat(np.arange(T), ncat), feat_of.ravel()] = 1.0
    counts = (comps.astype(np.float32) @ tf).astype(np.int64)  # [S, F]

    ok = (comps[:, ti] > 0) & (comps[:, tj] < m[tj][None, :])  # [S, P]
    packed = _feature_bitmasks(reduction)
    if packed is not None:
        masks, leftover = packed
        # bit f set ⇔ this composition may donate (resp. receive) a unit of
        # feature f without breaking its quota
        nb = min(F, 64)
        fbit = np.uint64(1) << np.arange(nb, dtype=np.uint64)
        can_sub = ((counts[:, :nb] - 1 >= lo[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )  # [S]
        can_add = ((counts[:, :nb] + 1 <= hi[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )
        # features touched by the move: symmetric difference of the two
        # types' feature sets (shared features cancel)
        diff = masks[ti] ^ masks[tj]  # [P]
        need_sub = masks[ti] & diff
        need_add = masks[tj] & diff
        ok &= (need_sub[None, :] & ~can_sub[:, None]) == 0
        ok &= (need_add[None, :] & ~can_add[:, None]) == 0
        # categories beyond the word (the household quotient's class
        # category): one [S, P] gather each. Its donor check vanishes when
        # every lower quota is 0 (true for class caps [0, m_c]) — the slow
        # all-gather fallback here was 62 s of a 130 s n=1200 household
        # decomposition
        for ci in leftover:
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            if (lo[feat_of[:, ci]] > 0).any():
                add_ok &= counts[:, a_i] - 1 >= lo[a_i][None, :]
            ok &= same[None, :] | add_ok
    else:  # pragma: no cover - every instance has some ≤64-feature category
        for ci in range(ncat):
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            sub_ok = counts[:, a_i] - 1 >= lo[a_i][None, :]
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            ok &= same[None, :] | (sub_ok & add_ok)

    si, pi = np.nonzero(ok)
    if len(si) == 0:
        return np.zeros((0, T), dtype=np.int16)
    if len(si) > per_round_cap:
        sel = np.random.default_rng(len(si)).choice(len(si), per_round_cap, replace=False)
        si, pi = si[sel], pi[sel]
    out = comps[si].astype(np.int16)
    idx = np.arange(len(si))
    out[idx, ti[pi]] -= 1
    out[idx, tj[pi]] += 1
    return out


def _master_pdhg(
    MT: np.ndarray,
    v: np.ndarray,
    cfg,
    warm,
    max_iters: int,
    tol: float,
) -> Tuple[float, np.ndarray, np.ndarray, float, Optional[tuple], bool]:
    """One approximate master solve on device: the two-sided ε-LP handed to
    the STRUCTURED warm-started PDHG core (``lp_pdhg.solve_two_sided_master``
    — only MT is shipped and kept resident; the ± row structure is applied
    arithmetically, halving both the tunnel transfer and the per-iteration
    HBM traffic of the stacked-matrix formulation).

    Returns ``(eps_realized, w, p_norm, eps_obj, warm', ok)`` where
    ``eps_realized = ‖M p_norm − v‖∞`` is the *arithmetic* certificate of the
    normalized primal iterate (valid regardless of solver convergence),
    ``w = y_lo − y_up`` the pricing/aiming duals, ``eps_obj`` the iterate's
    objective value (a stall indicator, not a bound), and ``ok`` the solver's
    own convergence flag. Columns are bucket-padded so the jitted core
    compiles once per bucket (same idiom as ``solve_stage_lp_pdhg``).
    """
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_two_sided_master

    T, C = MT.shape
    sol = solve_two_sided_master(
        MT, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters
    )
    p = np.maximum(sol.x[:C], 0.0)
    total = p.sum()
    if not np.isfinite(total) or total <= 0.0:
        return (
            float("inf"),
            np.zeros(T),
            np.full(C, 1.0 / max(C, 1)),
            float("inf"),
            None,
            False,
        )
    p_norm = p / total
    eps_real = float(np.abs(MT @ p_norm - v).max())
    lam = np.maximum(sol.lam, 0.0)
    w = lam[:T] - lam[T:]
    return eps_real, w, p_norm, float(sol.objective), (sol.x, sol.lam, sol.mu), sol.ok


def realize_profile(
    reduction: TypeReduction,
    v: np.ndarray,
    seed_comps: List[np.ndarray],
    oracle,
    accept: float,
    log: Optional[RunLog] = None,
    max_rounds: int = 60,
    master_cap: int = 6_000,
    use_pdhg: Optional[bool] = None,
    cfg=None,
) -> Tuple[np.ndarray, Optional[np.ndarray], float, int]:
    """Find compositions + probabilities with ``‖Mp − v‖∞ ≤ accept``.

    The per-round master is the warm-started device PDHG (host interior
    point on CPU-only backends, where PDHG's iteration count doesn't pay):
    its duals aim the neighbor expansion and the *arithmetic* residual of
    its normalized iterate is the acceptance certificate, so no round waits
    on an exact host solve. When the approximate master's objective dips
    near ``accept`` but its iterate lags (first-order tail), one host IPM
    polish on the mass-bearing support extracts the exact LP optimum — the
    only host solve in the loop.

    Aggressive pruning (support + freshest columns) keeps every master at
    ≤ ``master_cap`` columns — the face needs only ~T active columns, and
    neighbors of the *current* support regenerate any hull information a
    prune discards.

    Returns ``(compositions int32 [C, T], probabilities float64 [C],
    eps, lp_solves)``; callers fall back to stage CG when ``eps > accept``.
    """
    from citizensassemblies_tpu.solvers.cg_typespace import _decomp_lp

    log = log or RunLog(echo=False)
    T = reduction.T
    m = reduction.msize.astype(np.float64)
    if cfg is None:
        from citizensassemblies_tpu.utils.config import default_config

        cfg = default_config()
    if use_pdhg is None:
        import jax

        use_pdhg = jax.default_backend() not in ("cpu",)
    accel = bool(use_pdhg)
    if T <= cfg.decomp_host_master_max_types:
        # small-T instances stay on host masters end to end: cap the column
        # set so the expansion cannot push the master past the host's sweet
        # spot (a 6k-column round paid a device round-trip OR a ~2 s host
        # solve; the top-ranked ~1.5k neighbors carry the hull information)
        master_cap = min(master_cap, cfg.decomp_host_master_max_cols)

    seen: Dict[bytes, int] = {}
    cols: List[np.ndarray] = []

    def add(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(cols)
        cols.append(c.astype(np.int16))
        return True

    for c in seed_comps:
        add(c)

    def top_mass(p: np.ndarray, cap: int = 2048, frac: float = 1.0 - 1e-10):
        """Indices of the smallest column set carrying ``frac`` of the mass.

        Interior-point (and averaged-PDHG) optima spread thousands of tiny
        entries across the column set; a threshold-based "support" drags all
        of them through every later master. Mass-ranked selection keeps the
        ~basis-sized set that actually matters.
        """
        order = np.argsort(-p)
        cum = np.cumsum(p[order])
        cut = int(np.searchsorted(cum, frac * cum[-1])) + 1
        return order[: min(max(cut, 1), cap)]

    if not cols:
        # nothing to decompose from (pathological seeding) — report failure
        # so the caller takes the stage-CG fallback
        return np.zeros((0, T), np.int32), np.zeros(0), float("inf"), 0

    def polish_support(p_now: Optional[np.ndarray], bar: Optional[float] = None):
        """End-game solve on the mass-bearing support: the first-order
        master's iterate realizes ``v`` only to O(1/k) — when its objective
        says the support can do better, one tighter solve on the ~2k
        mass-bearing columns extracts it.

        On accelerators a DEEP structured-PDHG solve runs first (~2.5 s,
        host-contention-free); its normalized iterate carries the same
        arithmetic ε certificate as everything else in this loop, so it is
        accepted whenever it reaches ``bar``. The host IPM (exact, but
        4–7 s per call at T ≈ 1000 and the single most
        host-contention-sensitive phase of the flagship) runs only when the
        device polish misses the bar."""
        nonlocal lp_solves
        if p_now is not None and len(p_now) == len(cols):
            sup = top_mass(p_now, cap=2048)
        else:
            sup = np.arange(len(cols))[:4096]
        C_sup = np.stack([cols[i] for i in sup]).astype(np.int32)
        MTs = np.ascontiguousarray((C_sup.astype(np.float64) / m[None, :]).T)
        if accel:
            from citizensassemblies_tpu.solvers.lp_pdhg import (
                solve_two_sided_master,
            )

            sol = solve_two_sided_master(
                MTs, v, cfg=cfg, tol=0.25 * master_tol, max_iters=98_304
            )
            lp_solves += 1
            p_s = np.maximum(sol.x[: MTs.shape[1]], 0.0)
            tot = p_s.sum()
            if np.isfinite(tot) and tot > 0:
                p_s = p_s / tot
                eps_s = float(np.abs(MTs @ p_s - v).max())
                if eps_s <= (bar if bar is not None else stalled_band):
                    return C_sup, p_s, eps_s
        eps_s, _w, _mu, p_s = _decomp_lp(MTs, v)
        lp_solves += 1
        return C_sup, p_s, float(eps_s)

    lp_solves = 0
    eps = np.inf
    p = np.zeros(0)
    rng = np.random.default_rng(0)
    eps_hist: List[float] = []
    pdhg_warm = None
    best: Optional[Tuple[np.ndarray, np.ndarray, float]] = None
    t_start = time.time()
    # the stalled-acceptance band the caller still accepts (cg_typespace
    # accepts eps ≤ max(decomp_accept, decomp_accept_stalled) outright), so
    # stopping inside it never triggers the stage-CG fallback
    stalled_band = max(accept, getattr(cfg, "decomp_accept_stalled", accept))
    # f32 KKT tolerance for the approximate master: two orders below the
    # acceptance bar recovers the early exit once the warm-started iterate is
    # past the accuracy the (float64, arithmetic) accept check needs
    master_tol = max(0.02 * accept, cfg.pdhg_tol)
    # cooldown after a failed IPM polish: the LP optimum only decreases as
    # columns arrive, so without it a near-accept optimum would trigger a
    # host solve every remaining round
    polish_after = 0
    for rnd in range(max_rounds):
        t_round = time.time()
        # stall detection on the RUNNING BEST: the per-round arithmetic ε of
        # a first-order iterate wobbles ±30 %, and comparing raw values made
        # noisy upticks read as a stall while the hull was still improving
        if len(eps_hist) >= 7 and min(eps_hist[-4:]) > min(eps_hist[:-4]) * 0.98:
            # the best of the last 4 rounds failed to beat the running best
            # of all earlier rounds by ≥2 %: an integrality residual the face
            # cannot close (e.g. a fractionally-coverable type no integer
            # composition contains) — stop burning rounds; the stage-CG
            # fallback recomputes every value over realizable columns only,
            # so such types settle at their true (possibly 0) values there
            log.emit(
                f"  face rounds stalling at ε={eps_hist[-1]:.2e}; stopping early."
            )
            break
        C = np.stack(cols, axis=0)
        MT = np.ascontiguousarray((C.astype(np.float64) / m[None, :]).T)
        # per-round master selection: small problems solve exactly on host
        # faster than one accelerator round-trip; large ones want the device
        use_pdhg = accel and (
            T > cfg.decomp_host_master_max_types
            or len(cols) > cfg.decomp_host_master_max_cols
        )
        if use_pdhg:
            import jax

            if (
                jax.device_count() > 1
                and MT.shape[0] >= cfg.master_shard_min_types
            ):
                # beyond-one-chip master: rows sharded over the mesh,
                # psum-reduced transposes (no warm start — the sharded
                # regime trades it for memory scale-out)
                from citizensassemblies_tpu.parallel.mesh import default_mesh
                from citizensassemblies_tpu.parallel.solver import (
                    solve_decomp_master_sharded,
                )

                with log.timer("decomp_master"):
                    eps, w, p, eps_obj, _ok = solve_decomp_master_sharded(
                        MT, v, default_mesh(), cfg=cfg, tol=master_tol
                    )
                pdhg_warm = None
                lp_solves += 1
            else:
                # adaptive budget: far from acceptance the duals only need
                # to be roughly right to aim the expansion; near it the
                # iterate itself must realize v, so spend the iterations
                # where they matter. (A 4× deeper near-phase budget was
                # measured NOT to cut the round count — the iterate lag on
                # the hard seeds is hull quality, not iteration starvation —
                # while adding ~0.5 s/master, so the budgets stay here.)
                far = not eps_hist or eps_hist[-1] > 6 * accept
                with log.timer("decomp_master"):
                    eps, w, p, eps_obj, pdhg_warm, _ok = _master_pdhg(
                        MT, v, cfg, pdhg_warm,
                        max_iters=4_096 if far else 12_288, tol=master_tol,
                    )
                lp_solves += 1
            # end-game: the approximate objective says the support should be
            # able to realize v, but the first-order iterate's own residual
            # still lags — extract the exact optimum once on the support.
            # Deep into the time budget the OBJECTIVE-based trigger widens
            # slightly (the objective signals hull readiness; widening on
            # the ITERATE gambled failed polishes every cooldown — measured
            # +35 % flagship seed-0 wall-clock)
            deep = time.time() - t_start > 0.6 * cfg.decomp_time_budget_s
            near = (
                eps <= accept * 1.25
                or eps_obj <= accept * 1.05
                or (deep and eps_obj <= 1.2 * accept)
            )
            if eps > accept and near and rnd >= polish_after:
                with log.timer("decomp_polish"):
                    C_sup, p_sup, eps_sup = polish_support(
                        p, bar=(stalled_band if deep else accept)
                    )
                log.emit(
                    f"  polish: {len(C_sup)} support cols → ε={eps_sup:.2e} "
                    f"(iterate ε={eps:.2e}, obj≈{eps_obj:.2e})."
                )
                # deep into the time budget, a polish inside the stalled
                # band ends the run — the caller accepts that band outright,
                # and the alternative is another master round plus the same
                # end-game polish (measured ~20 s of tail per flagship rep)
                if eps_sup <= (stalled_band if deep else accept):
                    log.emit(
                        f"Face decomposition: ε = {eps_sup:.2e} certified on "
                        f"{len(C_sup)} support columns ({lp_solves} master solves, "
                        f"end-game polish)."
                    )
                    return C_sup, p_sup, eps_sup, lp_solves
                # discard the failed polish value: it is the optimum of a
                # support SUBSET, not something the full-column iterate
                # attains — mixing it into eps/eps_hist/best would make the
                # stall detector and the best-hull tracker compare
                # incommensurable quantities
                polish_after = rnd + 2
        else:
            with log.timer("decomp_master"):
                eps, w, _mu, p = _decomp_lp(MT, v)
            lp_solves += 1
        eps_hist.append(eps)
        if best is None or eps < best[2]:
            best = (C, p, eps)
        if (
            time.time() - t_start > cfg.decomp_time_budget_s
            and best[2] <= stalled_band
            and eps > accept
        ):
            # budget exhausted with a residual the caller accepts anyway:
            # stop grinding rounds and let the end-game polish extract the
            # best support (bounds the worst-of-N tail)
            log.emit(
                f"  face rounds over time budget ({cfg.decomp_time_budget_s:.0f}s) "
                f"with best ε={best[2]:.2e} inside the stalled band; stopping."
            )
            break
        if eps <= accept:
            # return this certified master as-is: the certificate is the
            # arithmetic residual of p itself, independent of the solver
            log.emit(
                f"Face decomposition: ε = {eps:.2e} certified on {len(cols)} "
                f"columns ({lp_solves} master solves)."
            )
            return C.astype(np.int32), p, float(eps), lp_solves
        # the ε-LP duals w (= y_lo − y_up) mark over-served (w < 0) vs
        # under-served (w > 0) types; move units down the gradient
        r_norm = -w / m
        sup_idx = top_mass(p)  # mass-ordered, largest first
        # prune BEFORE expanding: the next master sees only the mass-bearing
        # support plus this round's additions
        kept = [cols[i] for i in sup_idx]
        kept_p = p[sup_idx]
        cols.clear()
        seen.clear()
        for c in kept:
            add(c)
        # re-align the PDHG warm start with the pruned column order (kept
        # columns keep their primal mass; fresh columns start at zero)
        if pdhg_warm is not None:
            x_w = np.zeros(len(kept) + 1)
            x_w[: len(kept)] = kept_p
            x_w[-1] = max(eps, 0.0)
            pdhg_warm = (x_w, pdhg_warm[1], pdhg_warm[2])
        base = len(cols)
        cand: List[np.ndarray] = []
        if kept:
            with log.timer("decomp_expand"):
                cand.append(
                    neighbor_columns(np.stack(kept[:512]), reduction, r_norm)
                )
        if (
            T <= cfg.decomp_host_master_max_types
            and rnd == 0
            and eps <= 6 * accept
        ):
            # small-T near-miss after the first master: a deeper aimed-slice
            # pass (finer apportionment of the same target, phase-shifted
            # streams) closes the hull in one host round where generic
            # neighbors needed a 6k-column expansion (sf_d-class: R=2048
            # slices certify at ε 4.4e-4 vs 1.1e-3 from the 1024 injection).
            # Measured NOT to help large-T device-master instances: adding
            # phase-shifted streams there (rounds 0-2) left the per-round ε
            # trajectory unchanged while growing masters and stream cost —
            # sf_e mild-skew went 47-68 s → 71-89 s — so the gate stays
            # small-T; the large-T ε tail is integrality structure the
            # neighbor/anchor expansion addresses, not missing hull bulk.
            from citizensassemblies_tpu.solvers.cg_typespace import (
                _slice_relaxation,
            )

            # j0 phase-shifts the apportionment relative to the injection
            # stream (which ran the same target at j0=0): same hull, fresh
            # rounding boundaries — without the shift this pass would emit
            # mostly byte-duplicates of the injected slices
            deep_slices = _slice_relaxation(
                v * m, reduction, R=2048, j0=1 << 20, chunks=4
            )
            if deep_slices:
                cand.append(np.stack(deep_slices).astype(np.int16))
        # exact anchors: best compositions against the dual direction — these
        # are *compound* moves no single swap reaches. The noisy variants
        # only diversify, so they run on alternate rounds; the forced-
        # inclusion anchors below are the aimed ones and run every round.
        with log.timer("decomp_oracle"):
            # anchors are HEURISTIC columns (acceptance is the master
            # iterate's arithmetic residual), so a 1 % MILP gap is free
            # quality-wise and cuts the anchor solves' share of the
            # decomposition wall-clock (~20 % measured on the flagship)
            got = oracle.maximize(-r_norm, rel_gap=1e-2)
            if got is not None:
                cand.append(got[0][None, :].astype(np.int16))
            if rnd % 2 == 0:
                scale = float(np.mean(np.abs(r_norm))) + 1e-12
                for _ in range(2):
                    got = oracle.maximize(
                        -r_norm + rng.normal(0.0, 0.5 * scale, T), rel_gap=1e-2
                    )
                    if got is not None:
                        cand.append(got[0][None, :].astype(np.int16))
            # forced-inclusion anchors on the worst under-served types: a type
            # whose deficit persists needs columns that *contain* it, which the
            # global dual direction alone may never produce (rare types have
            # near-zero objective weight); forcing c_t ≥ 1 yields exactly such
            # a compound column per MILP call
            realized = MT @ p if len(p) == MT.shape[1] else None
            if realized is not None:
                deficit = v - realized
                worst = np.argsort(-deficit)[:3]
                for t in worst:
                    if deficit[t] > 0.25 * eps and reduction.msize[t] > 0:
                        got = oracle.maximize(
                            -r_norm, forced_type=int(t), rel_gap=1e-2
                        )
                        if got is not None:
                            cand.append(got[0][None, :].astype(np.int16))
        added = 0
        if cand:
            with log.timer("decomp_expand"):
                batch = np.concatenate([np.atleast_2d(c) for c in cand], axis=0)
                # grow the master where it helps: most negative ⟨r, c/m⟩ first
                # (r_norm = −w/m, so ascending r_norm-value = descending dual
                # improvement w·c/m)
                vals = batch.astype(np.float64) @ r_norm
                order = np.argsort(vals)
                cap = max(256, master_cap - len(cols))
                for i in order[:cap]:
                    added += add(batch[i])
        obj_note = f" obj≈{eps_obj:.2e}" if use_pdhg else ""
        log.emit(
            f"  face round {rnd + 1}: ε={eps:.2e}{obj_note} added {added} "
            f"(master {base}+{added}, {time.time() - t_round:.1f}s)."
        )
        if added == 0:
            break

    # out of rounds / stalled: one exact end-game solve on the best support
    if best is not None and (len(p) != len(cols) or eps > accept):
        C_best, p_best, _ = best
        cols = [c for c in C_best]
        p = p_best
    with log.timer("decomp_polish"):
        C_sup, p_sup, eps = polish_support(p if len(p) == len(cols) else None)
    log.emit(
        f"Face decomposition: ε = {eps:.2e} on {len(C_sup)} support columns "
        f"({lp_solves} master solves)."
    )
    return C_sup, p_sup, float(eps), lp_solves
