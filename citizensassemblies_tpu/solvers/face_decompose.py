"""Realize a leximin profile as a mixture of feasible compositions, fast.

Phase 1 of the type-space solver (``cg_typespace.py``) must express the
probe-certified profile ``v`` as ``M p = v`` over feasible compositions. The
classic Dantzig-Wolfe master (ε-LP + exact MILP pricing) tails badly here:
the optimal face needs ~T active columns and pricing discovers them a handful
per round (~7 %/round ε decay at sf_e scale — minutes of wall-clock).

This engine replaces it with three TPU-idiomatic ingredients:

* **Aimed slices** (`cg_typespace._slice_relaxation`) seed the hull around
  the target marginal ``x* = v·m``.
* **Face-neighbor expansion** generates columns *combinatorially* instead of
  one-per-MILP: for support columns of the current master, every feasible
  single-unit move ``t → t'`` that shifts mass from over-served types
  (residual ``r_t > 0``) to under-served ones is itself a feasible
  composition on or near the face — thousands of useful columns per round
  from pure vectorized index arithmetic (quota feasibility of all
  (composition, move) pairs is checked with per-feature *bitmasks* packed
  into machine words, so a round's full candidate screen is a handful of
  wide integer ops).
* **A device-resident approximate master**: each round's ε-LP is solved by
  the warm-started PDHG core (``lp_pdhg.py``) on the accelerator — its duals
  aim the expansion, and *acceptance needs no trusted solver at all*: the
  certificate is the arithmetic identity ``ε = ‖M p − v‖∞`` evaluated on the
  returned mixture, so an approximate solver can terminate the loop the
  moment any iterate realizes the profile within tolerance (same two-sided
  ε semantics as the reference's final LP, ``leximin.py:453-464``). A host
  interior-point polish runs only in the end-game, when the approximate
  master says the support should realize ``v`` but its iterate hasn't
  converged tightly enough to show it.

The loop itself is a *pipelined, warm-started engine*: the anchor-oracle
MILPs run on a worker thread double-buffered against the device master
(``_AnchorPricer`` — identical column schedule threaded or inline, so the
serial fallback is bit-identical), the master's and polish's PDHG iterates
carry across rounds, prunes and column-bucket growths with a stall-triggered
cold restart (``_WarmStall``), and the per-round move screen can run as one
jitted device batch (``_batched_move_screen``). All of it is wall-clock
machinery — acceptance remains the float64 arithmetic residual of whatever
mixture comes back.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.guards import CompilationGuard, no_implicit_transfers
from citizensassemblies_tpu.utils.logging import RunLog


def _feature_bitmasks(reduction: TypeReduction):
    """Per-type donor/receiver feature masks for the move-feasibility screen.

    The quota conditions of a unit move collapse to bit tests: moving a unit
    *out* of type ``t`` decrements each of ``t``'s features, which is safe
    iff the composition's count stays ≥ lo there; moving *in* increments,
    safe iff ≤ hi. One 64-bit word covers every reference-shaped instance
    (F ≤ 64). Instances with MORE features — the household quotient's
    augmented incidence appends one one-hot class feature per household
    class, F = base + #classes — split by category: categories whose
    features all index < 64 ride the word, the rest are screened by direct
    gathers in :func:`neighbor_columns` (one gather per category — for the
    quotient that is the single class category, whose ``lo = 0`` even skips
    the donor side). Returns ``(feat_mask[T] uint64, leftover_cats)`` where
    ``leftover_cats`` lists category indices not covered by the mask, or
    ``None`` when no category fits a word at all.
    """
    feat_of = np.asarray(reduction.type_feature)
    ncat = feat_of.shape[1]
    word_cats = [ci for ci in range(ncat) if int(feat_of[:, ci].max()) < 64]
    if not word_cats:
        return None
    masks = np.zeros(reduction.T, dtype=np.uint64)
    for ci in word_cats:
        masks |= np.uint64(1) << feat_of[:, ci].astype(np.uint64)
    leftover = [ci for ci in range(ncat) if ci not in word_cats]
    return masks, leftover


_MOVE_SCREEN_CORE = None


def _get_move_screen_core():
    """Build (once) the jitted batched move screen.

    The whole [S, P] (composition, move) feasibility check of
    :func:`neighbor_columns` as ONE jitted dispatch per round: base bounds via
    two device gathers, the per-feature quota conditions via the same packed
    bitword trick as the numpy path — split into two uint32 lanes because JAX
    runs with 64-bit types disabled — and the leftover (>word) categories via
    direct gathers. Feasible (composition, pair) indices come back through a
    fixed-size ``jnp.nonzero`` (row-major, so below the cap the index set is
    bit-identical to the numpy path's ``np.nonzero``), plus the true count so
    the caller can see when the cap truncated. Compiled once per
    (T, F, pair-bucket, leftover-count) shape; ``jax`` is imported lazily so
    the module stays importable without it.
    """
    global _MOVE_SCREEN_CORE
    if _MOVE_SCREEN_CORE is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("cap",))
        def core(
            comps_i, counts_nb, lo_nb, hi_nb, counts_full, lo_f, hi_f,
            m_t, ti, tj, valid, ns_lo, ns_hi, na_lo, na_hi,
            lf_ai, lf_aj, lf_donor, cap: int,
        ):
            ci = comps_i[:, ti]  # [Sp, Pp] gathers (padding rows are zero)
            cj = comps_i[:, tj]
            ok = (ci > 0) & (cj < m_t[tj][None, :]) & valid[None, :]
            bits32 = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

            def pack(bits):  # bool [Sp, 64] → (lo, hi) uint32 words [Sp]
                b = bits.astype(jnp.uint32)
                return (
                    (b[:, :32] * bits32).sum(axis=1),
                    (b[:, 32:] * bits32).sum(axis=1),
                )

            cs_lo, cs_hi = pack(counts_nb - 1 >= lo_nb[None, :])
            ca_lo, ca_hi = pack(counts_nb + 1 <= hi_nb[None, :])
            ok &= (ns_lo[None, :] & ~cs_lo[:, None]) == 0
            ok &= (ns_hi[None, :] & ~cs_hi[:, None]) == 0
            ok &= (na_lo[None, :] & ~ca_lo[:, None]) == 0
            ok &= (na_hi[None, :] & ~ca_hi[:, None]) == 0
            for l in range(lf_ai.shape[0]):  # static leftover-category count
                ai, aj = lf_ai[l], lf_aj[l]
                same = ai == aj
                add_ok = counts_full[:, aj] + 1 <= hi_f[aj][None, :]
                sub_ok = counts_full[:, ai] - 1 >= lo_f[ai][None, :]
                add_ok &= jnp.where(lf_donor[l], sub_ok, True)
                ok &= same[None, :] | add_ok
            flat = ok.reshape(-1)
            (idx,) = jnp.nonzero(flat, size=cap, fill_value=-1)
            return idx.astype(jnp.int32), flat.sum(dtype=jnp.int32)

        _MOVE_SCREEN_CORE = core
    return _MOVE_SCREEN_CORE


@register_ir_core("face_decompose.move_screen")
def _ir_move_screen() -> IRCase:
    """The batched move screen at one small (T=32, F=40, one leftover
    category) shape — the uint32 bitmask lanes and the fixed-size nonzero
    decode are the structure under verification (lint/ir.py)."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    i32, u32 = jnp.int32, jnp.uint32
    T, F, Pp, L = 32, 40, 4096, 1
    return IRCase(
        fn=_get_move_screen_core(),
        args=(
            S((_SCREEN_ROWS, T), i32), S((_SCREEN_ROWS, 64), i32),
            S((64,), i32), S((64,), i32), S((_SCREEN_ROWS, F), i32),
            S((F,), i32), S((F,), i32), S((T,), i32),
            S((Pp,), i32), S((Pp,), i32), S((Pp,), jnp.bool_),
            S((Pp,), u32), S((Pp,), u32), S((Pp,), u32), S((Pp,), u32),
            S((L, Pp), i32), S((L, Pp), i32), S((L,), jnp.bool_),
        ),
        static=dict(cap=4096),
    )


#: compositions per screening batch: ``realize_profile`` expands at most the
#: top 512 support columns, so one padded row count keeps one compiled
#: program per instance shape instead of one per round
_SCREEN_ROWS = 512

#: minimum mass-bearing support before the batched polish-face screen pays:
#: below it one structured solve is already a single small dispatch and the
#: candidate prefixes would all be the full support anyway
_POLISH_SCREEN_MIN_SUP = 256


def _batched_move_screen(
    comps: np.ndarray,
    counts: np.ndarray,
    reduction: TypeReduction,
    m: np.ndarray,
    ti: np.ndarray,
    tj: np.ndarray,
    packed,
    per_round_cap: int,
    cfg=None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host marshalling for the jitted move screen: pad to the screening
    buckets, split the uint64 need-masks into uint32 lanes, decode the
    returned flat indices. Returns ``(si, pi, total_feasible)``."""
    masks, leftover = packed
    S, T = comps.shape
    F = reduction.F
    nb = min(F, 64)
    P = len(ti)
    Pp = -(-P // 4096) * 4096
    lo = reduction.qmin.astype(np.int32)
    hi = reduction.qmax.astype(np.int32)

    comps_p = np.zeros((_SCREEN_ROWS, T), np.int32)
    comps_p[:S] = comps
    counts_full = np.zeros((_SCREEN_ROWS, F), np.int32)
    counts_full[:S] = counts
    # padding feature slots get unbounded quotas so their bits never veto
    lo_nb = np.full(64, -(1 << 30), np.int32)
    hi_nb = np.full(64, 1 << 30, np.int32)
    lo_nb[:nb] = lo[:nb]
    hi_nb[:nb] = hi[:nb]
    counts_nb = np.zeros((_SCREEN_ROWS, 64), np.int32)
    counts_nb[:, :nb] = counts_full[:, :nb]

    ti_p = np.zeros(Pp, np.int32)
    tj_p = np.zeros(Pp, np.int32)
    ti_p[:P] = ti
    tj_p[:P] = tj
    valid = np.zeros(Pp, bool)
    valid[:P] = True
    diff = masks[ti] ^ masks[tj]
    ns = np.zeros(Pp, np.uint64)
    na = np.zeros(Pp, np.uint64)
    ns[:P] = masks[ti] & diff
    na[:P] = masks[tj] & diff
    word = np.uint64(0xFFFFFFFF)
    ns_lo, ns_hi = (ns & word).astype(np.uint32), (ns >> np.uint64(32)).astype(np.uint32)
    na_lo, na_hi = (na & word).astype(np.uint32), (na >> np.uint64(32)).astype(np.uint32)

    L = len(leftover)
    lf_ai = np.zeros((L, Pp), np.int32)
    lf_aj = np.zeros((L, Pp), np.int32)
    feat_of = np.asarray(reduction.type_feature)
    for l, ci_cat in enumerate(leftover):
        lf_ai[l, :P] = feat_of[ti, ci_cat]
        lf_aj[l, :P] = feat_of[tj, ci_cat]
    lf_donor = np.array(
        [bool((lo[feat_of[:, ci_cat]] > 0).any()) for ci_cat in leftover], dtype=bool
    )

    core = _get_move_screen_core()
    import jax.numpy as jnp

    # the screen's operands change every round, so the upload is inherent —
    # but it is made EXPLICIT here (one jnp.asarray per operand), and the
    # guard then rejects any further implicit transfer inside the jitted call
    operands = tuple(
        jnp.asarray(a)
        for a in (
            comps_p, counts_nb, lo_nb, hi_nb, counts_full,
            lo.astype(np.int32), hi.astype(np.int32),
            np.asarray(m, np.int32), ti_p, tj_p, valid,
            ns_lo, ns_hi, na_lo, na_hi, lf_ai, lf_aj, lf_donor,
        )
    )
    with no_implicit_transfers(cfg):
        idx, total = core(*operands, cap=int(per_round_cap))
    idx = np.asarray(idx)
    idx = idx[idx >= 0]
    return idx // Pp, idx % Pp, int(total)


def neighbor_columns(
    comps: np.ndarray,
    reduction: TypeReduction,
    r_norm: np.ndarray,
    # measured at 2× and 3× these widths on the two large-T regimes
    # (sf_e mild-skew T=565, household quotient T=1199): round count drops
    # ~linearly (7→4, 19→10) but per-round master cost rises to match —
    # wall-clock within noise either way, so the defaults stay at the
    # smaller, lower-variance setting
    pool_cap: int = 128,
    face_pairs: int = 12_288,
    per_round_cap: int = 16_384,
    batched: bool = False,
    cfg=None,
) -> np.ndarray:
    """Feasible single-unit moves from ``comps`` along and across the face.

    Two pair classes feed the expansion:

    * **improving** — move a unit from an over-served type (``r_norm > 0``)
      to an under-served one: pulls the hull toward the target;
    * **face-preserving** — pairs with ``|Δ(w/m)| ≈ 0``: enumerate the
      near-optimal face combinatorially, which is where the master's ~T
      active columns live (a MILP finds them only one per solve).

    A move ``t → t'`` from composition ``c`` is feasible iff ``c_t > 0``,
    ``c_{t'} < m_{t'}`` and, in every category where the two types' features
    differ, the donor's feature stays ≥ its lower quota and the receiver's
    ≤ its upper. The (composition, pair) screen packs those per-feature
    conditions into one machine word per composition (``_feature_bitmasks``),
    so the whole [S, P] check is three wide integer ops instead of 2·ncat
    float gathers. With ``batched=True`` the screen instead runs as ONE
    jitted device batch per round (``_batched_move_screen``): identical
    index set below ``per_round_cap``, and above it the first (mass-ordered,
    since callers pass support-ordered compositions) feasible moves are kept
    where the numpy path subsamples randomly. Returns the stacked new
    compositions (int16 [N, T]).
    """
    comps = comps.astype(np.int16, copy=False)  # 4× less gather traffic
    S, T = comps.shape
    feat_of = np.asarray(reduction.type_feature)  # [T, ncat]
    ncat = feat_of.shape[1]
    # clip before the int16 cast: composition entries are <= k (small), but
    # a pool type can exceed int16 range — the receiver check only needs
    # min(m, k+1), since no composition holds more than k of any type
    m = np.minimum(reduction.msize, reduction.k + 1).astype(np.int16)
    lo = reduction.qmin.astype(np.int64)
    hi = reduction.qmax.astype(np.int64)

    order = np.argsort(-r_norm)
    # improving pairs: extremes of the residual direction
    donors = order[:pool_cap]
    receivers = order[::-1][:pool_cap]
    ti_a, tj_a = np.meshgrid(donors, receivers, indexing="ij")
    pairs = [np.stack([ti_a.ravel(), tj_a.ravel()], axis=1)]
    # face pairs: smallest |Δ| over a broad random pool (full T² only for
    # small T)
    if T * T <= 1 << 18:
        di = np.repeat(np.arange(T), T)
        dj = np.tile(np.arange(T), T)
    else:
        rng = np.random.default_rng(T)
        di = rng.integers(0, T, size=face_pairs * 8)
        dj = rng.integers(0, T, size=face_pairs * 8)
    delta = np.abs(r_norm[di] - r_norm[dj])
    sel = np.argsort(delta)[:face_pairs]
    pairs.append(np.stack([di[sel], dj[sel]], axis=1))
    tp = np.concatenate(pairs, axis=0)
    tp = tp[tp[:, 0] != tp[:, 1]]
    tp = np.unique(tp, axis=0)
    ti, tj = tp[:, 0], tp[:, 1]
    P = len(ti)
    if P == 0:
        return np.zeros((0, T), dtype=np.int16)

    # per-composition feature counts [S, F]: float32 BLAS then cast — numpy
    # integer matmuls bypass BLAS, and at quotient scale ([512, 1199] @
    # [1199, 626]) the int64 product alone cost ~0.4 s per face round;
    # counts ≤ k ≤ a few hundred, far inside float32's exact-integer range
    F = reduction.F
    tf = np.zeros((T, F), dtype=np.float32)
    tf[np.repeat(np.arange(T), ncat), feat_of.ravel()] = 1.0
    counts = (comps.astype(np.float32) @ tf).astype(np.int64)  # [S, F]

    packed = _feature_bitmasks(reduction)
    if batched and packed is not None and S <= _SCREEN_ROWS:
        si, pi, _total = _batched_move_screen(
            comps, counts, reduction, m, ti, tj, packed, per_round_cap, cfg=cfg
        )
        if len(si) == 0:
            return np.zeros((0, T), dtype=np.int16)
        out = comps[si].astype(np.int16)
        idx = np.arange(len(si))
        out[idx, ti[pi]] -= 1
        out[idx, tj[pi]] += 1
        return out

    ok = (comps[:, ti] > 0) & (comps[:, tj] < m[tj][None, :])  # [S, P]
    if packed is not None:
        masks, leftover = packed
        # bit f set ⇔ this composition may donate (resp. receive) a unit of
        # feature f without breaking its quota
        nb = min(F, 64)
        fbit = np.uint64(1) << np.arange(nb, dtype=np.uint64)
        can_sub = ((counts[:, :nb] - 1 >= lo[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )  # [S]
        can_add = ((counts[:, :nb] + 1 <= hi[None, :nb]).astype(np.uint64) * fbit).sum(
            axis=1, dtype=np.uint64
        )
        # features touched by the move: symmetric difference of the two
        # types' feature sets (shared features cancel)
        diff = masks[ti] ^ masks[tj]  # [P]
        need_sub = masks[ti] & diff
        need_add = masks[tj] & diff
        ok &= (need_sub[None, :] & ~can_sub[:, None]) == 0
        ok &= (need_add[None, :] & ~can_add[:, None]) == 0
        # categories beyond the word (the household quotient's class
        # category): one [S, P] gather each. Its donor check vanishes when
        # every lower quota is 0 (true for class caps [0, m_c]) — the slow
        # all-gather fallback here was 62 s of a 130 s n=1200 household
        # decomposition
        for ci in leftover:
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            if (lo[feat_of[:, ci]] > 0).any():
                add_ok &= counts[:, a_i] - 1 >= lo[a_i][None, :]
            ok &= same[None, :] | add_ok
    else:  # pragma: no cover - every instance has some ≤64-feature category
        for ci in range(ncat):
            a_i = feat_of[ti, ci]
            a_j = feat_of[tj, ci]
            same = a_i == a_j
            sub_ok = counts[:, a_i] - 1 >= lo[a_i][None, :]
            add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
            ok &= same[None, :] | (sub_ok & add_ok)

    si, pi = np.nonzero(ok)
    if len(si) == 0:
        return np.zeros((0, T), dtype=np.int16)
    if len(si) > per_round_cap:
        sel = np.random.default_rng(len(si)).choice(len(si), per_round_cap, replace=False)
        si, pi = si[sel], pi[sel]
    out = comps[si].astype(np.int16)
    idx = np.arange(len(si))
    out[idx, ti[pi]] -= 1
    out[idx, tj[pi]] += 1
    return out


def _master_pdhg(
    MT: np.ndarray,
    v: np.ndarray,
    cfg,
    warm,
    max_iters: int,
    tol: float,
    ell=None,
) -> Tuple[float, np.ndarray, np.ndarray, float, Optional[tuple], bool]:
    """One approximate master solve on device: the two-sided ε-LP handed to
    the STRUCTURED warm-started PDHG core (``lp_pdhg.solve_two_sided_master``
    — only MT is shipped and kept resident; the ± row structure is applied
    arithmetically, halving both the tunnel transfer and the per-iteration
    HBM traffic of the stacked-matrix formulation). With ``ell`` (the
    incrementally-maintained ELL pack of the master columns,
    ``solvers/sparse_ops``), the sparse core carries the solve instead:
    the tunnel ships only the NEW columns' packed indices/values since the
    last round, and every PDHG matvec is O(C·k_pad) gather/scatter work.

    Returns ``(eps_realized, w, p_norm, eps_obj, warm', ok)`` where
    ``eps_realized = ‖M p_norm − v‖∞`` is the *arithmetic* certificate of the
    normalized primal iterate (valid regardless of solver convergence),
    ``w = y_lo − y_up`` the pricing/aiming duals, ``eps_obj`` the iterate's
    objective value (a stall indicator, not a bound), and ``ok`` the solver's
    own convergence flag. Columns are bucket-padded so the jitted core
    compiles once per bucket (same idiom as ``solve_stage_lp_pdhg``).
    """
    from citizensassemblies_tpu.solvers.lp_pdhg import (
        solve_two_sided_master,
        solve_two_sided_master_ell,
    )

    T, C = MT.shape
    if ell is not None:
        sol = solve_two_sided_master_ell(
            ell, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters
        )
    else:
        sol = solve_two_sided_master(
            MT, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters
        )
    p = np.maximum(sol.x[:C], 0.0)
    total = p.sum()
    if not np.isfinite(total) or total <= 0.0:
        return (
            float("inf"),
            np.zeros(T),
            np.full(C, 1.0 / max(C, 1)),
            float("inf"),
            None,
            False,
        )
    p_norm = p / total
    eps_real = float(np.abs(MT @ p_norm - v).max())
    lam = np.maximum(sol.lam, 0.0)
    w = lam[:T] - lam[T:]
    return eps_real, w, p_norm, float(sol.objective), (sol.x, sol.lam, sol.mu), sol.ok


class _AnchorPricer:
    """Double-buffered host pricing for the face loop's anchor MILPs.

    The anchors (one dual-direction optimum, alternate-round noisy variants,
    up to three forced-inclusion columns for persistent deficits) are
    HEURISTIC columns — acceptance is the master iterate's arithmetic
    residual — so their aim may lag the duals by one round without touching
    exactness. That staleness buys the pipeline: round r's MILPs are
    *submitted* the moment round r's duals exist and *harvested* at round
    r+1's expansion, so with ``overlap=True`` they execute on a worker thread
    while the main thread runs the neighbor expansion, the next device master
    and any polish (HiGHS releases the GIL inside its solve, and the main
    thread releases it waiting on the device). ``overlap=False`` runs the
    SAME schedule inline at the submit point — the emitted column stream is
    bit-identical between the two modes, which is the serial fallback's
    regression contract (``tests/test_face_decompose.py``). All randomness
    (the noisy-anchor perturbations) is drawn on the caller's thread at
    submit time, so the schedule is deterministic either way.
    """

    def __init__(
        self,
        oracle,
        rng: np.random.Generator,
        reduction: TypeReduction,
        overlap: bool,
        log: Optional[RunLog] = None,
    ):
        self.oracle = oracle
        self.rng = rng
        self.red = reduction
        self.log = log
        self._pool = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="anchor-pricer")
            if overlap
            else None
        )
        self._pending: Optional[Union[Future, List[np.ndarray]]] = None

    def _run(self, tasks) -> List[np.ndarray]:
        out = []
        for weights, forced in tasks:
            # 1 % MILP gap: anchor optimality buys nothing (see the caller's
            # acceptance semantics) and the gap cuts the anchor share of the
            # decomposition wall-clock ~20 % on the flagship
            got = self.oracle.maximize(weights, forced_type=forced, rel_gap=1e-2)
            if got is not None:
                out.append(got[0][None, :].astype(np.int16))
        return out

    def submit(
        self,
        rnd: int,
        r_norm: np.ndarray,
        eps: float,
        realized: Optional[np.ndarray],
        v: np.ndarray,
    ) -> None:
        """Queue round ``rnd``'s anchor MILPs (noise drawn HERE, on the
        caller's thread). Any un-harvested previous submission is replaced —
        callers harvest before submitting, so that only happens on loop exit.
        """
        tasks: List[Tuple[np.ndarray, Optional[int]]] = [(-r_norm, None)]
        if rnd % 2 == 0:
            # noisy variants only diversify, so they run on alternate rounds
            scale = float(np.mean(np.abs(r_norm))) + 1e-12
            for _ in range(2):
                tasks.append(
                    (-r_norm + self.rng.normal(0.0, 0.5 * scale, len(r_norm)), None)
                )
        if realized is not None:
            # forced-inclusion anchors on the worst under-served types: a type
            # whose deficit persists needs columns that *contain* it, which
            # the global dual direction alone may never produce (rare types
            # have near-zero objective weight)
            deficit = v - realized
            worst = np.argsort(-deficit)[:3]
            for t in worst:
                if deficit[t] > 0.25 * eps and self.red.msize[t] > 0:
                    tasks.append((-r_norm, int(t)))
        if self._pool is not None:
            self._pending = self._pool.submit(self._run, tasks)
        else:
            self._pending = self._run(tasks)

    def harvest(self) -> List[np.ndarray]:
        """Collect the previously submitted round's columns (blocks only when
        the worker has not finished — counted separately from clean overlap
        hits so the bench can see how often the pipeline actually hid the
        pricing)."""
        pending, self._pending = self._pending, None
        if pending is None:
            return []
        if isinstance(pending, list):
            if self.log is not None:
                self.log.count("decomp_oracle_inline")
            return pending
        if self.log is not None:
            self.log.count(
                "decomp_oracle_overlap_hit"
                if pending.done()
                else "decomp_oracle_overlap_wait"
            )
        return pending.result()

    def close(self) -> None:
        """Drop any un-harvested job and stop the worker. A MILP already
        executing finishes (sub-second); a queued-but-unstarted one is
        cancelled."""
        pending, self._pending = self._pending, None
        if isinstance(pending, Future):
            pending.cancel()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


class _WarmStall:
    """Cold-restart policy for the warm-started PDHG master.

    A warm iterate normally saves the equilibration transient, but a stalled
    first-order iterate can sit in a corner the (column-augmented) problem
    has moved away from, where restarting from zero re-equilibrates faster
    than escaping. Policy: a warm-started round that fails to beat the
    running-best ε by ≥ ``(1 − improve)`` extends a streak; ``patience``
    consecutive such rounds ⇒ drop the warm iterate once (the caller
    cold-starts the next master and resumes warm from its result). Cold
    rounds never extend the streak, so one reset cannot cascade into
    permanently disabling the warm path.
    """

    def __init__(self, patience: int, improve: float = 0.98):
        self.patience = max(int(patience), 1)
        self.improve = improve
        self.best = float("inf")
        self.streak = 0

    def update(self, eps: float, warm_used: bool) -> bool:
        improved = eps < self.best * self.improve
        self.best = min(self.best, eps)
        if improved or not warm_used:
            if improved:
                self.streak = 0
            return False
        self.streak += 1
        if self.streak >= self.patience:
            self.streak = 0
            return True
        return False


def realize_profile(
    reduction: TypeReduction,
    v: np.ndarray,
    seed_comps: List[np.ndarray],
    oracle,
    accept: float,
    log: Optional[RunLog] = None,
    max_rounds: int = 60,
    master_cap: int = 6_000,
    use_pdhg: Optional[bool] = None,
    cfg=None,
    ctx=None,
) -> Tuple[np.ndarray, Optional[np.ndarray], float, int]:
    """Find compositions + probabilities with ``‖Mp − v‖∞ ≤ accept``.

    The per-round master is the warm-started device PDHG (host interior
    point on CPU-only backends, where PDHG's iteration count doesn't pay):
    its duals aim the neighbor expansion and the *arithmetic* residual of
    its normalized iterate is the acceptance certificate, so no round waits
    on an exact host solve. When the approximate master's objective dips
    near ``accept`` but its iterate lags (first-order tail), one host IPM
    polish on the mass-bearing support extracts the exact LP optimum — the
    only host solve in the loop.

    Aggressive pruning (support + freshest columns) keeps every master at
    ≤ ``master_cap`` columns — the face needs only ~T active columns, and
    neighbors of the *current* support regenerate any hull information a
    prune discards.

    Returns ``(compositions int32 [C, T], probabilities float64 [C],
    eps, lp_solves)``; callers fall back to stage CG when ``eps > accept``.
    """
    from citizensassemblies_tpu.service.context import resolve as resolve_context
    from citizensassemblies_tpu.solvers.cg_typespace import _decomp_lp

    # per-request re-entrancy: resolve cfg/log through the ambient (or
    # explicitly passed) RequestContext; the context is (re)installed around
    # the round loop below so the batched-engine calls see it
    ctx, cfg, log = resolve_context(ctx, cfg, log)
    T = reduction.T
    m = reduction.msize.astype(np.float64)
    if use_pdhg is None:
        import jax

        use_pdhg = jax.default_backend() not in ("cpu",)
    accel = bool(use_pdhg)
    if T <= cfg.decomp_host_master_max_types:
        # small-T instances stay on host masters end to end: cap the column
        # set so the expansion cannot push the master past the host's sweet
        # spot (a 6k-column round paid a device round-trip OR a ~2 s host
        # solve; the top-ranked ~1.5k neighbors carry the hull information)
        master_cap = min(master_cap, cfg.decomp_host_master_max_cols)

    seen: Dict[bytes, int] = {}
    cols: List[np.ndarray] = []

    def add(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(cols)
        cols.append(c.astype(np.int16))
        return True

    for c in seed_comps:
        add(c)

    # --- structured-sparse master state (solvers/sparse_ops) ----------------
    # Master columns are compositions: ≤ k nonzeros of T types, so at the
    # large-T regimes (sf_e mild-skew T=565, household quotient T=1199) the
    # dense MT is ≥90 % zeros. The ELL pack is maintained INCREMENTALLY in
    # lockstep with ``cols``: appends pack only the new columns
    # (``ell_synced``), a prune subsets by fancy indexing, and only a
    # column-set replacement from ``best`` invalidates it. Fill is measured
    # per master; the auto gate (``Config.sparse_ops``) decides per solve.
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack, sparse_enabled

    sparse_try = accel and getattr(cfg, "sparse_ops", None) is not False
    ell_pack: Optional[EllPack] = EllPack(minor=T) if sparse_try else None

    def ell_synced() -> Optional[EllPack]:
        """Append any columns added since the last sync (packs ONLY those);
        returns the pack, or None when the sparse path is off."""
        nonlocal ell_pack
        if ell_pack is None:
            return None
        if len(ell_pack) > len(cols):  # pragma: no cover - defensive
            ell_pack = EllPack(minor=T)
        if len(ell_pack) < len(cols):
            with log.timer("sparse_pack"):
                new = (
                    np.stack(cols[len(ell_pack) :]).astype(np.float64)
                    / m[None, :]
                )
                ell_pack.append(new)
        return ell_pack

    def top_mass(p: np.ndarray, cap: int = 2048, frac: float = 1.0 - 1e-10):
        """Indices of the smallest column set carrying ``frac`` of the mass.

        Interior-point (and averaged-PDHG) optima spread thousands of tiny
        entries across the column set; a threshold-based "support" drags all
        of them through every later master. Mass-ranked selection keeps the
        ~basis-sized set that actually matters.
        """
        order = np.argsort(-p)
        cum = np.cumsum(p[order])
        cut = int(np.searchsorted(cum, frac * cum[-1])) + 1
        return order[: min(max(cut, 1), cap)]

    if not cols:
        # nothing to decompose from (pathological seeding) — report failure
        # so the caller takes the stage-CG fallback
        return np.zeros((0, T), np.int32), np.zeros(0), float("inf"), 0

    def polish_support(
        p_now: Optional[np.ndarray],
        bar: Optional[float] = None,
        master_warm: Optional[tuple] = None,
    ):
        """End-game solve on the mass-bearing support: the first-order
        master's iterate realizes ``v`` only to O(1/k) — when its objective
        says the support can do better, one tighter solve on the ~2k
        mass-bearing columns extracts it.

        With the batched LP engine enabled, several CANDIDATE polish faces
        (nested mass-ranked support prefixes) are screened as ONE padded
        vmapped device call first: a smaller support that already realizes
        ``v`` within the bar converges in a fraction of the deep solve's
        iterations, and every candidate carries the same arithmetic float64
        ε certificate — the accept bar is unchanged, only the number of
        device dispatches per attempt drops. On a miss (or with the engine
        off) the serial path below runs bit-identically.

        On accelerators a DEEP structured-PDHG solve runs next (~2.5 s,
        host-contention-free); its normalized iterate carries the same
        arithmetic ε certificate as everything else in this loop, so it is
        accepted whenever it reaches ``bar``. ``master_warm`` (the master's
        raw (x, λ, μ) triple) warm-starts it: the primal restriction of the
        master iterate to the support plus the master's own row duals — the
        rows are the same T types, so the duals transfer exactly — which
        skips most of the polish's ramp-up instead of re-deriving it from
        zero. The host IPM (exact, but 4–7 s per call at T ≈ 1000 and the
        single most host-contention-sensitive phase of the flagship) runs
        only when the device polish misses the bar."""
        nonlocal lp_solves
        if p_now is not None and len(p_now) == len(cols):
            sup = top_mass(p_now, cap=2048)
        else:
            sup = np.arange(len(cols))[:4096]
        C_sup = np.stack([cols[i] for i in sup]).astype(np.int32)
        MTs = np.ascontiguousarray((C_sup.astype(np.float64) / m[None, :]).T)
        the_bar = bar if bar is not None else stalled_band
        # ELL pack of the support: a pure subset of the synced incremental
        # pack when the iterate still corresponds to ``cols`` (no re-pack at
        # all), a fresh pack otherwise; the fill gate then decides per solve
        ell_sup = None
        if sparse_try:
            if (
                ell_pack is not None
                and p_now is not None
                and len(p_now) == len(cols)
                and len(ell_pack) == len(cols)
            ):
                cand_pack = ell_pack.take(sup)
            else:
                with log.timer("sparse_pack"):
                    cand_pack = EllPack.from_rows(MTs.T, minor=T)
            if sparse_enabled(cfg, cand_pack.fill):
                ell_sup = cand_pack
        if accel and batch_screen and len(sup) > _POLISH_SCREEN_MIN_SUP:
            # batched polish-face screen: nested support prefixes solved as
            # one padded vmapped dispatch, each judged by its own float64
            # arithmetic residual — identical accept-bar semantics
            from citizensassemblies_tpu.solvers.batch_lp import (
                solve_lp_batch,
                solve_polish_screen_ell,
                two_sided_master_batch_lp,
            )

            # nested mass-ranked prefixes: ¼ and ½ of the support plus the
            # full set (at the production 2048-cap support that is 512/1024/
            # 2048 columns) — the small faces converge in a fraction of the
            # deep solve's iterations when they already realize v
            caps = sorted({max(len(sup) // 4, 1), max(len(sup) // 2, 1), len(sup)})
            warm_ok = (
                cfg.decomp_warm_start
                and master_warm is not None
                and p_now is not None
                and len(p_now) == len(cols)
            )
            if ell_sup is not None:
                # sparse screen: ONE shared pack feeds every prefix lane —
                # the lanes differ only in their column mask
                warms = []
                for c_ in caps:
                    if warm_ok:
                        x0 = np.concatenate(
                            [p_now[sup[:c_]], [max(float(master_warm[0][-1]), 0.0)]]
                        )
                        warms.append((x0, master_warm[1], master_warm[2]))
                    else:
                        warms.append(None)
                with log.timer("decomp_polish_screen"):
                    sols = solve_polish_screen_ell(
                        ell_sup, v, caps, warms, tol=0.25 * master_tol,
                        max_iters=24_576, cfg=cfg, log=log,
                    )
                log.count("decomp_host_syncs")
            else:
                insts = []
                for c_ in caps:
                    inst = two_sided_master_batch_lp(
                        MTs[:, :c_], v, tol=0.25 * master_tol
                    )
                    if warm_ok:
                        x0 = np.concatenate(
                            [p_now[sup[:c_]], [max(float(master_warm[0][-1]), 0.0)]]
                        )
                        inst.warm = (x0, master_warm[1], master_warm[2])
                    insts.append(inst)
                with log.timer("decomp_polish_screen"):
                    # one SHARED bucket: the nested prefixes differ only in
                    # column count, and one fused dispatch is the whole point
                    sols = solve_lp_batch(
                        insts, cfg=cfg, log=log, warm_key="decomp_polish_screen",
                        max_iters=24_576, common_bucket=True,
                    )
                log.count("decomp_host_syncs")
            lp_solves += 1
            best_s = None
            for c_, sol in zip(caps, sols):
                p_s = np.maximum(sol.x[:c_], 0.0)
                tot = p_s.sum()
                if not np.isfinite(tot) or tot <= 0:
                    continue
                p_s = p_s / tot
                eps_s = float(np.abs(MTs[:, :c_] @ p_s - v).max())
                if best_s is None or eps_s < best_s[2]:
                    best_s = (c_, p_s, eps_s)
            if best_s is not None and best_s[2] <= the_bar:
                c_, p_s, eps_s = best_s
                log.count("lp_batch_polish_hit")
                return C_sup[:c_], p_s, eps_s
            log.count("lp_batch_polish_miss")
        if accel:
            from citizensassemblies_tpu.solvers.lp_pdhg import (
                solve_two_sided_master,
                solve_two_sided_master_ell,
            )

            warm_s = None
            if (
                cfg.decomp_warm_start
                and master_warm is not None
                and p_now is not None
                and len(p_now) == len(cols)
            ):
                # x: the master iterate's mass on the support columns, ε slot
                # from the master's own ε variable; λ/μ transfer verbatim
                # (same T rows, same Σp row)
                x0 = np.concatenate(
                    [p_now[sup], [max(float(master_warm[0][-1]), 0.0)]]
                )
                warm_s = (x0, master_warm[1], master_warm[2])
                log.count("decomp_polish_warm")
            if ell_sup is not None:
                sol = solve_two_sided_master_ell(
                    ell_sup, v, cfg=cfg, warm=warm_s, tol=0.25 * master_tol,
                    max_iters=98_304,
                )
            else:
                sol = solve_two_sided_master(
                    MTs, v, cfg=cfg, warm=warm_s, tol=0.25 * master_tol,
                    max_iters=98_304,
                )
            lp_solves += 1
            log.count("decomp_host_syncs")  # deep device polish round trip
            p_s = np.maximum(sol.x[: MTs.shape[1]], 0.0)
            tot = p_s.sum()
            if np.isfinite(tot) and tot > 0:
                p_s = p_s / tot
                eps_s = float(np.abs(MTs @ p_s - v).max())
                if eps_s <= (bar if bar is not None else stalled_band):
                    return C_sup, p_s, eps_s
        eps_s, _w, _mu, p_s = _decomp_lp(MTs, v)
        lp_solves += 1
        return C_sup, p_s, float(eps_s)

    lp_solves = 0
    eps = np.inf
    p = np.zeros(0)
    rng = np.random.default_rng(0)
    eps_hist: List[float] = []
    pdhg_warm = None
    best: Optional[Tuple[np.ndarray, np.ndarray, float]] = None
    t_start = time.time()
    # the stalled-acceptance band the caller still accepts (cg_typespace
    # accepts eps <= max(decomp_accept, decomp_accept_stalled) outright), so
    # stopping inside it never triggers the stage-CG fallback
    stalled_band = max(accept, getattr(cfg, "decomp_accept_stalled", accept))
    # f32 KKT tolerance for the approximate master: two orders below the
    # acceptance bar recovers the early exit once the warm-started iterate is
    # past the accuracy the (float64, arithmetic) accept check needs
    master_tol = max(0.02 * accept, cfg.pdhg_tol)
    # cooldown after a failed IPM polish: the LP optimum only decreases as
    # columns arrive, so without it a near-accept optimum would trigger a
    # host solve every remaining round
    polish_after = 0
    # --- the pipelined engine's moving parts --------------------------------
    # anchor MILPs double-buffered against the device master (see
    # _AnchorPricer: identical column schedule whether threaded or inline),
    # a cold-restart policy for the warm-started master, and the batched
    # device move screen on accelerator backends
    pricer = _AnchorPricer(
        oracle, rng, reduction,
        overlap=bool(getattr(cfg, "decomp_oracle_overlap", True)), log=log,
    )
    warm_enabled = bool(getattr(cfg, "decomp_warm_start", True))
    warm_stall = _WarmStall(int(getattr(cfg, "decomp_warm_stall_rounds", 3)))
    batched_expand = bool(getattr(cfg, "decomp_batched_expand", True)) and accel
    # batched polish-face screening (solvers/batch_lp.py): candidate support
    # prefixes solved as one vmapped dispatch in the end-game
    from citizensassemblies_tpu.solvers.batch_lp import (
        clear_warm_slots,
        lp_batch_enabled,
    )

    batch_screen = accel and lp_batch_enabled(cfg)
    if batch_screen:
        # the screen's warm slots are per-run state, not cross-run state:
        # a previous instance's iterate must not leak into this profile
        clear_warm_slots("decomp_polish_screen")

    def rank_add(cand: List[np.ndarray], r_norm: np.ndarray) -> int:
        """Grow the master where it helps: most negative <r, c/m> first
        (r_norm = -w/m, so ascending r_norm-value = descending dual
        improvement w.c/m)."""
        if not cand:
            return 0
        added = 0
        with log.timer("decomp_expand"):
            batch = np.concatenate([np.atleast_2d(c) for c in cand], axis=0)
            vals = batch.astype(np.float64) @ r_norm
            order = np.argsort(vals)
            cap = max(256, master_cap - len(cols))
            for i in order[:cap]:
                added += add(batch[i])
        return added

    # compilation counter over the whole face loop: the padded buckets exist
    # so CG rounds re-enter compiled executables — the count lands in the
    # phase counters (xla_compiles_decomp) where a per-round recompile would
    # be immediately visible next to the warm-start/overlap attribution
    from contextlib import ExitStack

    from citizensassemblies_tpu.service.context import use_context

    _guards = ExitStack()
    _guards.enter_context(use_context(ctx))
    _guards.enter_context(CompilationGuard("decomp", log=log))
    try:
        for rnd in range(max_rounds):
            t_round = time.time()
            # stall detection on the RUNNING BEST: the per-round arithmetic
            # eps of a first-order iterate wobbles +-30 %, and comparing raw
            # values made noisy upticks read as a stall while the hull was
            # still improving
            if len(eps_hist) >= 7 and min(eps_hist[-4:]) > min(eps_hist[:-4]) * 0.98:
                # the best of the last 4 rounds failed to beat the running
                # best of all earlier rounds by >=2 %: an integrality residual
                # the face cannot close (e.g. a fractionally-coverable type no
                # integer composition contains) -- stop burning rounds; the
                # stage-CG fallback recomputes every value over realizable
                # columns only, so such types settle at their true (possibly
                # 0) values there
                log.emit(
                    f"  face rounds stalling at eps={eps_hist[-1]:.2e}; stopping early."
                )
                break
            C = np.stack(cols, axis=0)
            MT = np.ascontiguousarray((C.astype(np.float64) / m[None, :]).T)
            # per-round master selection: small problems solve exactly on host
            # faster than one accelerator round-trip; large ones want the device
            use_pdhg = accel and (
                T > cfg.decomp_host_master_max_types
                or len(cols) > cfg.decomp_host_master_max_cols
            )
            polish_warm = None
            if use_pdhg:
                import jax

                if (
                    jax.device_count() > 1
                    and MT.shape[0] >= cfg.master_shard_min_types
                ):
                    # beyond-one-chip master: rows sharded over the mesh,
                    # psum-reduced transposes (no warm start -- the sharded
                    # regime trades it for memory scale-out)
                    from citizensassemblies_tpu.parallel.mesh import default_mesh
                    from citizensassemblies_tpu.parallel.solver import (
                        solve_decomp_master_sharded,
                    )

                    with log.timer("decomp_master"):
                        eps, w, p, eps_obj, _ok = solve_decomp_master_sharded(
                            MT, v, default_mesh(), cfg=cfg, tol=master_tol
                        )
                    pdhg_warm = None
                    lp_solves += 1
                    # one host→device upload + device→host harvest per
                    # sharded master (the decomp_host_syncs gauge: ROADMAP
                    # item 2 wants the CG round's round-trip count measured
                    # before device-resident pricing can claim to kill it)
                    log.count("decomp_host_syncs")
                else:
                    # adaptive budget: far from acceptance the duals only need
                    # to be roughly right to aim the expansion; near it the
                    # iterate itself must realize v, so spend the iterations
                    # where they matter. (A 4x deeper near-phase budget was
                    # measured NOT to cut the round count -- the iterate lag on
                    # the hard seeds is hull quality, not iteration starvation --
                    # while adding ~0.5 s/master, so the budgets stay here.)
                    far = not eps_hist or eps_hist[-1] > 6 * accept
                    warm_arg = pdhg_warm if warm_enabled else None
                    log.count(
                        "decomp_master_warm" if warm_arg is not None
                        else "decomp_master_cold"
                    )
                    # sparse routing: sync the incremental pack (only new
                    # columns re-pack), then gate on the measured fill
                    ell_now = ell_synced()
                    use_sparse = False
                    if ell_now is not None:
                        use_sparse = sparse_enabled(cfg, ell_now.fill)
                        log.gauge(
                            "sparse_fill_pct", int(round(100 * ell_now.fill))
                        )
                        log.count("sparse_hit" if use_sparse else "sparse_miss")
                    with log.timer("decomp_master"):
                        eps, w, p, eps_obj, pdhg_warm, _ok = _master_pdhg(
                            MT, v, cfg, warm_arg,
                            max_iters=4_096 if far else 12_288, tol=master_tol,
                            ell=ell_now if use_sparse else None,
                        )
                    lp_solves += 1
                    # device master: operand upload + iterate harvest is one
                    # host↔device round trip of the CG round
                    log.count("decomp_host_syncs")
                    polish_warm = pdhg_warm
                    if not warm_enabled:
                        pdhg_warm = None
                    elif warm_stall.update(eps, warm_arg is not None):
                        # the warm iterate is no longer buying progress:
                        # cold-start the next master once (warm resumes from
                        # its result -- see _WarmStall)
                        pdhg_warm = None
                        log.count("decomp_warm_cold_restart")
                        log.emit(
                            f"  warm-started master stalling at eps={eps:.2e}; "
                            "cold-restarting the iterate."
                        )
                # end-game: the approximate objective says the support should
                # be able to realize v, but the first-order iterate's own
                # residual still lags -- extract the exact optimum once on the
                # support. Deep into the time budget the OBJECTIVE-based
                # trigger widens slightly (the objective signals hull
                # readiness; widening on the ITERATE gambled failed polishes
                # every cooldown -- measured +35 % flagship seed-0 wall-clock)
                deep = time.time() - t_start > 0.6 * cfg.decomp_time_budget_s
                near = (
                    eps <= accept * 1.25
                    or eps_obj <= accept * 1.05
                    or (deep and eps_obj <= 1.2 * accept)
                )
                if eps > accept and near and rnd >= polish_after:
                    with log.timer("decomp_polish"):
                        C_sup, p_sup, eps_sup = polish_support(
                            p, bar=(stalled_band if deep else accept),
                            master_warm=polish_warm,
                        )
                    log.emit(
                        f"  polish: {len(C_sup)} support cols -> eps={eps_sup:.2e} "
                        f"(iterate eps={eps:.2e}, obj~{eps_obj:.2e})."
                    )
                    # deep into the time budget, a polish inside the stalled
                    # band ends the run -- the caller accepts that band
                    # outright, and the alternative is another master round
                    # plus the same end-game polish (measured ~20 s of tail
                    # per flagship rep)
                    if eps_sup <= (stalled_band if deep else accept):
                        log.emit(
                            f"Face decomposition: eps = {eps_sup:.2e} certified on "
                            f"{len(C_sup)} support columns ({lp_solves} master solves, "
                            f"end-game polish)."
                        )
                        return C_sup, p_sup, eps_sup, lp_solves
                    # discard the failed polish value: it is the optimum of a
                    # support SUBSET, not something the full-column iterate
                    # attains -- mixing it into eps/eps_hist/best would make
                    # the stall detector and the best-hull tracker compare
                    # incommensurable quantities
                    polish_after = rnd + 2
            else:
                with log.timer("decomp_master"):
                    eps, w, _mu, p = _decomp_lp(MT, v)
                lp_solves += 1
            eps_hist.append(eps)
            if best is None or eps < best[2]:
                best = (C, p, eps)
            if (
                time.time() - t_start > cfg.decomp_time_budget_s
                and best[2] <= stalled_band
                and eps > accept
            ):
                # budget exhausted with a residual the caller accepts anyway:
                # stop grinding rounds and let the end-game polish extract the
                # best support (bounds the worst-of-N tail)
                log.emit(
                    f"  face rounds over time budget ({cfg.decomp_time_budget_s:.0f}s) "
                    f"with best eps={best[2]:.2e} inside the stalled band; stopping."
                )
                break
            if eps <= accept:
                # return this certified master as-is: the certificate is the
                # arithmetic residual of p itself, independent of the solver
                log.emit(
                    f"Face decomposition: eps = {eps:.2e} certified on {len(cols)} "
                    f"columns ({lp_solves} master solves)."
                )
                return C.astype(np.int32), p, float(eps), lp_solves
            # the eps-LP duals w (= y_lo - y_up) mark over-served (w < 0) vs
            # under-served (w > 0) types; move units down the gradient
            r_norm = -w / m
            sup_idx = top_mass(p)  # mass-ordered, largest first
            # prune BEFORE expanding: the next master sees only the
            # mass-bearing support plus this round's additions
            n_before = len(cols)
            kept = [cols[i] for i in sup_idx]
            kept_p = p[sup_idx]
            cols.clear()
            seen.clear()
            for c in kept:
                add(c)
            if ell_pack is not None:
                # the prune is a pure subset/reorder: fancy-index the packed
                # arrays instead of re-packing (EllPack.take); a pack that
                # was out of sync (host-master rounds) restarts empty and
                # re-packs lazily at the next device master
                ell_pack = (
                    ell_pack.take(sup_idx)
                    if len(ell_pack) == n_before
                    else EllPack(minor=T)
                )
            # re-align the PDHG warm start with the pruned column order (kept
            # columns keep their primal mass; fresh columns start at zero)
            if pdhg_warm is not None:
                x_w = np.zeros(len(kept) + 1)
                x_w[: len(kept)] = kept_p
                x_w[-1] = max(eps, 0.0)
                pdhg_warm = (x_w, pdhg_warm[1], pdhg_warm[2])
            base = len(cols)
            cand: List[np.ndarray] = []
            # PIPELINE: harvest round r-1's anchor MILPs, then submit round
            # r's -- exact anchors are best compositions against the dual
            # direction, *compound* moves no single swap reaches; submitted
            # here, they execute on the worker thread while this round's
            # expansion and the NEXT round's device master run (the timer
            # therefore records only schedule overhead plus any blocking
            # wait, and the overlap_hit/wait counters say which it was)
            with log.timer("decomp_oracle"):
                cand.extend(pricer.harvest())
                realized = MT @ p if len(p) == MT.shape[1] else None
                pricer.submit(rnd, r_norm, eps, realized, v)
            if kept:
                with log.timer("decomp_expand"):
                    cand.append(
                        neighbor_columns(
                            np.stack(kept[:512]), reduction, r_norm,
                            batched=batched_expand, cfg=cfg,
                        )
                    )
                if batched_expand:
                    # the jitted move screen ships the candidate block down
                    # and the kept-move indices back up once per round
                    log.count("decomp_host_syncs")
            if (
                T <= cfg.decomp_host_master_max_types
                and rnd == 0
                and eps <= 6 * accept
            ):
                # small-T near-miss after the first master: a deeper
                # aimed-slice pass (finer apportionment of the same target,
                # phase-shifted streams) closes the hull in one host round
                # where generic neighbors needed a 6k-column expansion
                # (sf_d-class: R=2048 slices certify at eps 4.4e-4 vs 1.1e-3
                # from the 1024 injection). Measured NOT to help large-T
                # device-master instances: adding phase-shifted streams there
                # (rounds 0-2) left the per-round eps trajectory unchanged
                # while growing masters and stream cost -- sf_e mild-skew went
                # 47-68 s -> 71-89 s -- so the gate stays small-T; the large-T
                # eps tail is integrality structure the neighbor/anchor
                # expansion addresses, not missing hull bulk.
                from citizensassemblies_tpu.solvers.cg_typespace import (
                    _slice_relaxation,
                )

                # j0 phase-shifts the apportionment relative to the injection
                # stream (which ran the same target at j0=0): same hull, fresh
                # rounding boundaries -- without the shift this pass would
                # emit mostly byte-duplicates of the injected slices
                deep_slices = _slice_relaxation(
                    v * m, reduction, R=2048, j0=1 << 20, chunks=4
                )
                if deep_slices:
                    cand.append(np.stack(deep_slices).astype(np.int16))
            added = rank_add(cand, r_norm)
            if added == 0:
                # nothing new this round -- but this round's anchor job is
                # still pending; wait for it rather than concluding
                # exhaustion with columns in flight
                with log.timer("decomp_oracle"):
                    late = pricer.harvest()
                added = rank_add(late, r_norm)
            obj_note = f" obj~{eps_obj:.2e}" if use_pdhg else ""
            log.emit(
                f"  face round {rnd + 1}: eps={eps:.2e}{obj_note} added {added} "
                f"(master {base}+{added}, {time.time() - t_round:.1f}s)."
            )
            if added == 0:
                break

        # out of rounds / stalled: one exact end-game solve on the best support
        if best is not None and (len(p) != len(cols) or eps > accept):
            C_best, p_best, _ = best
            cols = [c for c in C_best]
            p = p_best
            if ell_pack is not None:
                # the column set was REPLACED (not appended/pruned): the
                # incremental pack no longer corresponds — drop it and let
                # the final polish re-pack its support from scratch
                ell_pack = EllPack(minor=T)
        with log.timer("decomp_polish"):
            # final polish at the TIGHT bar: stalled-band acceptance is the
            # in-loop deep path's explicit fallback criterion; the shipped
            # final eps takes the accept-level device polish when it reaches
            # it and the exact host IPM otherwise
            C_sup, p_sup, eps = polish_support(
                p if len(p) == len(cols) else None, bar=accept,
                master_warm=pdhg_warm,
            )
        log.emit(
            f"Face decomposition: eps = {eps:.2e} on {len(C_sup)} support columns "
            f"({lp_solves} master solves)."
        )
        return C_sup, p_sup, float(eps), lp_solves
    finally:
        _guards.close()
        pricer.close()
