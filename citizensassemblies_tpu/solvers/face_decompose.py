"""Realize a leximin profile as a mixture of feasible compositions, fast.

Phase 1 of the type-space solver (``cg_typespace.py``) must express the
probe-certified profile ``v`` as ``M p = v`` over feasible compositions. The
classic Dantzig-Wolfe master (ε-LP + exact MILP pricing) tails badly here:
the optimal face needs ~T active columns and pricing discovers them a handful
per round (~7 %/round ε decay at sf_e scale — minutes of wall-clock).

This engine replaces it with three TPU-idiomatic ingredients:

* **Aimed slices** (`cg_typespace._slice_relaxation`) seed the hull around
  the target marginal ``x* = v·m``.
* **Face-neighbor expansion** generates columns *combinatorially* instead of
  one-per-MILP: for support columns of the current master, every feasible
  single-unit move ``t → t'`` that shifts mass from over-served types
  (residual ``r_t > 0``) to under-served ones is itself a feasible
  composition on or near the face — thousands of useful columns per round
  from pure vectorized index arithmetic.
* **A prune-bounded exact master**: the host ε-LP (interior point) is solved
  every round on at most ``master_cap`` columns — the mass-bearing support of
  the previous optimum plus the round's additions. The face needs only ~T
  active columns, and neighbors of the current support regenerate any hull
  information a prune discards, so the master stays small while its duals
  aim the expansion and its ε is itself the acceptance certificate (same
  two-sided ε semantics as the reference's final LP, ``leximin.py:453-464``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.logging import RunLog


def neighbor_columns(
    comps: np.ndarray,
    reduction: TypeReduction,
    r_norm: np.ndarray,
    pool_cap: int = 128,
    face_pairs: int = 12_288,
    per_round_cap: int = 16_384,
) -> np.ndarray:
    """Feasible single-unit moves from ``comps`` along and across the face.

    Two pair classes feed the expansion:

    * **improving** — move a unit from an over-served type (``r_norm > 0``)
      to an under-served one: pulls the hull toward the target;
    * **face-preserving** — pairs with ``|Δ(w/m)| ≈ 0``: enumerate the
      near-optimal face combinatorially, which is where the master's ~T
      active columns live (a MILP finds them only one per solve).

    A move ``t → t'`` from composition ``c`` is feasible iff ``c_t > 0``,
    ``c_{t'} < m_{t'}`` and, in every category where the two types' features
    differ, the donor's feature stays ≥ its lower quota and the receiver's
    ≤ its upper. All checks are vectorized over (composition, pair).
    Returns the stacked new compositions (int16 [N, T]).
    """
    S, T = comps.shape
    feat_of = np.asarray(reduction.type_feature)  # [T, ncat]
    ncat = feat_of.shape[1]
    m = reduction.msize.astype(np.int64)
    lo = reduction.qmin.astype(np.int64)
    hi = reduction.qmax.astype(np.int64)

    order = np.argsort(-r_norm)
    # improving pairs: extremes of the residual direction
    donors = order[:pool_cap]
    receivers = order[::-1][:pool_cap]
    ti_a, tj_a = np.meshgrid(donors, receivers, indexing="ij")
    pairs = [np.stack([ti_a.ravel(), tj_a.ravel()], axis=1)]
    # face pairs: smallest |Δ| over a broad random pool (full T² only for
    # small T)
    if T * T <= 1 << 18:
        di = np.repeat(np.arange(T), T)
        dj = np.tile(np.arange(T), T)
    else:
        rng = np.random.default_rng(T)
        di = rng.integers(0, T, size=face_pairs * 8)
        dj = rng.integers(0, T, size=face_pairs * 8)
    delta = np.abs(r_norm[di] - r_norm[dj])
    sel = np.argsort(delta)[:face_pairs]
    pairs.append(np.stack([di[sel], dj[sel]], axis=1))
    tp = np.concatenate(pairs, axis=0)
    tp = tp[tp[:, 0] != tp[:, 1]]
    tp = np.unique(tp, axis=0)
    ti, tj = tp[:, 0], tp[:, 1]
    P = len(ti)
    if P == 0:
        return np.zeros((0, T), dtype=np.int16)

    # per-composition feature counts [S, F]
    F = reduction.F
    tf = np.zeros((T, F), dtype=np.int64)
    for ci in range(ncat):
        tf[np.arange(T), feat_of[:, ci]] = 1
    counts = comps.astype(np.int64) @ tf  # [S, F]

    ok = (comps[:, ti] > 0) & (comps[:, tj] < m[tj][None, :])  # [S, P]
    for ci in range(ncat):
        a_i = feat_of[ti, ci]  # [P]
        a_j = feat_of[tj, ci]
        same = a_i == a_j
        sub_ok = counts[:, a_i] - 1 >= lo[a_i][None, :]
        add_ok = counts[:, a_j] + 1 <= hi[a_j][None, :]
        ok &= same[None, :] | (sub_ok & add_ok)

    si, pi = np.nonzero(ok)
    if len(si) == 0:
        return np.zeros((0, T), dtype=np.int16)
    if len(si) > per_round_cap:
        sel = np.random.default_rng(len(si)).choice(len(si), per_round_cap, replace=False)
        si, pi = si[sel], pi[sel]
    out = comps[si].astype(np.int16)
    idx = np.arange(len(si))
    out[idx, ti[pi]] -= 1
    out[idx, tj[pi]] += 1
    return out


def realize_profile(
    reduction: TypeReduction,
    v: np.ndarray,
    seed_comps: List[np.ndarray],
    oracle,
    accept: float,
    log: Optional[RunLog] = None,
    max_rounds: int = 60,
    master_cap: int = 4_000,
) -> Tuple[np.ndarray, Optional[np.ndarray], float, int]:
    """Find compositions + probabilities with ``‖Mp − v‖∞ ≤ accept``.

    The master is the exact host ε-LP (interior point): its duals aim the
    neighbor expansion and its ε is already the certificate, so acceptance
    needs no extra solve. Aggressive pruning (support + freshest columns)
    keeps every master at ≤ ``master_cap`` columns — the face needs only ~T
    active columns, and neighbors of the *current* support regenerate any
    hull information a prune discards.

    Returns ``(compositions int32 [C, T], probabilities float64 [C],
    eps, lp_solves)``; callers fall back to stage CG when ``eps > accept``.
    """
    from citizensassemblies_tpu.solvers.cg_typespace import _decomp_lp

    log = log or RunLog(echo=False)
    T = reduction.T
    m = reduction.msize.astype(np.float64)

    seen: Dict[bytes, int] = {}
    cols: List[np.ndarray] = []

    def add(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(cols)
        cols.append(c.astype(np.int16))
        return True

    for c in seed_comps:
        add(c)

    def top_mass(p: np.ndarray, cap: int = 2048, frac: float = 1.0 - 1e-10):
        """Indices of the smallest column set carrying ``frac`` of the mass.

        Interior-point optima spread thousands of ~1e-10 entries across the
        column set; a threshold-based "support" drags all of them through
        every later master. Mass-ranked selection keeps the ~basis-sized set
        that actually matters.
        """
        order = np.argsort(-p)
        cum = np.cumsum(p[order])
        cut = int(np.searchsorted(cum, frac * cum[-1])) + 1
        return order[: min(max(cut, 1), cap)]

    if not cols:
        # nothing to decompose from (pathological seeding) — report failure
        # so the caller takes the stage-CG fallback
        return np.zeros((0, T), np.int32), np.zeros(0), float("inf"), 0

    lp_solves = 0
    eps = np.inf
    p = np.zeros(0)
    p_aligned = False  # p indexes the *current* cols list
    rng = np.random.default_rng(0)
    eps_hist: List[float] = []
    for rnd in range(max_rounds):
        t_round = time.time()
        if len(eps_hist) >= 6 and eps_hist[-1] > eps_hist[-6] * 0.98:
            # <2 % progress over 6 rounds: an integrality residual the face
            # cannot close (e.g. a fractionally-coverable type no integer
            # composition contains) — stop burning rounds; the stage-CG
            # fallback recomputes every value over realizable columns only,
            # so such types settle at their true (possibly 0) values there
            log.emit(
                f"  face rounds stalling at ε={eps_hist[-1]:.2e}; stopping early."
            )
            break
        C = np.stack(cols, axis=0)
        MT = np.ascontiguousarray((C.astype(np.float64) / m[None, :]).T)
        eps, w, _mu, p = _decomp_lp(MT, v)
        lp_solves += 1
        p_aligned = True
        eps_hist.append(eps)
        if eps <= accept:
            # return this certified master as-is: re-solving on a restricted
            # support could degrade a certificate already in hand
            log.emit(
                f"Face decomposition: ε = {eps:.2e} certified on {len(cols)} "
                f"columns ({lp_solves} master solves)."
            )
            return C.astype(np.int32), p, float(eps), lp_solves
        # the ε-LP duals w (= y_lo − y_up) mark over-served (w < 0) vs
        # under-served (w > 0) types; move units down the gradient
        r_norm = -w / m
        sup_idx = top_mass(p)  # mass-ordered, largest first
        # prune BEFORE expanding: the next master sees only the mass-bearing
        # support plus this round's additions
        kept = [cols[i] for i in sup_idx]
        cols.clear()
        seen.clear()
        for c in kept:
            add(c)
        p_aligned = False
        base = len(cols)
        cand: List[np.ndarray] = []
        if kept:
            cand.append(
                neighbor_columns(
                    np.stack(kept[:512]).astype(np.int64), reduction, r_norm
                )
            )
        # exact anchors: best compositions against the dual direction — these
        # are *compound* moves no single swap reaches
        got = oracle.maximize(-r_norm)
        if got is not None:
            cand.append(got[0][None, :].astype(np.int16))
        scale = float(np.mean(np.abs(r_norm))) + 1e-12
        for _ in range(6):
            got = oracle.maximize(-r_norm + rng.normal(0.0, 0.5 * scale, T))
            if got is not None:
                cand.append(got[0][None, :].astype(np.int16))
        added = 0
        if cand:
            batch = np.concatenate([np.atleast_2d(c) for c in cand], axis=0)
            # grow the master where it helps: most negative ⟨r, c/m⟩ first
            # (r_norm = −w/m, so ascending r_norm-value = descending dual
            # improvement w·c/m)
            vals = batch.astype(np.float64) @ r_norm
            order = np.argsort(vals)
            cap = max(256, master_cap - len(cols))
            for i in order[:cap]:
                added += add(batch[i])
        log.emit(
            f"  face round {rnd + 1}: ε={eps:.2e} added {added} "
            f"(master {base}+{added}, {time.time() - t_round:.1f}s)."
        )
        if added == 0:
            break

    if not p_aligned:
        # the loop exited after a prune/extend: p ranks the OLD column order,
        # so re-solve once on the current set before selecting the support
        C = np.stack(cols, axis=0)
        MT = np.ascontiguousarray((C.astype(np.float64) / m[None, :]).T)
        eps, _w, _mu, p = _decomp_lp(MT, v)
        lp_solves += 1
    sup = top_mass(p, cap=4096)
    C_sup = np.stack([cols[i] for i in sup]).astype(np.int32)
    MT = np.ascontiguousarray((C_sup.astype(np.float64) / m[None, :]).T)
    eps, _w, _mu, p_sup = _decomp_lp(MT, v)
    lp_solves += 1
    log.emit(
        f"Face decomposition: ε = {eps:.2e} on {len(sup)} support columns "
        f"({lp_solves} master solves)."
    )
    return C_sup, p_sup, float(eps), lp_solves