"""graftdelta: incremental re-certification under registry churn.

A real registry is never static — volunteers join and drop daily, quotas get
amended mid-recruitment — yet a from-scratch solve repeats the O(n) type
reduction, the full composition enumeration, and the whole leximin stage
ladder on every edit. The certified portfolio from the previous solve makes
almost all of that redundant: the column hull is still feasible after most
edits, and the stored stage duals *prove* which parts of the certificate
survive. This module re-certifies in ~O(edit):

1. **Edit projection** — :class:`TypeSystem` mirrors the instance's type
   reduction at the registry level (type rows, pool sizes, quota bands) and
   :meth:`TypeSystem.update` maps a :class:`~citizensassemblies_tpu.data.registry.RegistryEdit`
   onto it in O(edit): pool sizes shift, bands move, new types append — no
   O(n) pass over the pool.
2. **Dual screening on device** — ONE batched dispatch (``delta.screen``,
   IR-registered) re-prices the surviving column hull against the edited
   instance: integer feasibility per column (Σc = k, per-type caps, quota
   bands) plus the per-stage dual price gap ``μ_s − Σ_t y_t c_t/m_t``.
   Infeasible columns are dropped (``EllPack.take`` prune), near-margin
   columns are flagged and re-priced on host in float64. The ELL pack is
   maintained incrementally (PR 5 lifecycle): new columns ``append``, dead
   columns prune — never a full re-pack.
3. **Sensitivity cache certificate** — when (a) the old support survives the
   feasibility screen, (b) every newly-admitted column prices *strictly*
   below every stage's support price ``μ_s`` by ``delta_cert_margin``
   (complementary slackness: no optimal face changes), and (c) the pool-size
   drift bound stays inside the margin, the old mixture is still within the
   1e-3 L∞ contract — a **cache hit with a certificate** (zero LP solves,
   stamped ``delta_cert`` on the audit). Tighten-only edits need (a) alone:
   a leximin optimum over S that stays attainable over S' ⊆ S is the leximin
   optimum over S'. The drift path is a conservative stage-wise LP
   perturbation bound, and is additionally validated against an actual
   re-solve in ``tests/test_delta.py``.
4. **Warm resume** — when only deeper stages are invalidated (a relaxation
   admitted columns that price into stage s but not earlier), the fixing
   ladder resumes from the stored ``fixed_after`` vector of stage s−1
   (``leximin_over_compositions(fixed_init=…)``) over the screened hull plus
   the incrementally-enumerated new region; otherwise the ladder re-runs in
   full over that set — still skipping the O(n) reduction and the full
   enumeration. The 1e-3 L∞ exactness audit is unchanged as the hard
   contract on every path.

The service front door is ``SelectionRequest(revise=ReviseSpec(…))`` — see
``service/server.py``: a cold session or an edit above
``Config.delta_max_edit_frac`` falls back bit-identically to from-scratch.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.data.registry import Registry, RegistryEdit
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.solvers.compositions import (
    StageCert,
    leximin_over_compositions,
)
from citizensassemblies_tpu.solvers.sparse_ops import EllPack
from citizensassemblies_tpu.utils.guards import no_implicit_transfers
from citizensassemblies_tpu.utils.precision import iterate_dtype
from citizensassemblies_tpu.utils.logging import RunLog

#: the framework's hard L∞ exactness contract (``models/leximin.py``)
CONTRACT_LINF = 1e-3

#: support cutoff: a column below this mass is not part of the certificate
_SUPPORT_EPS = 1e-9

#: host float64 re-pricing window, in margins: device f32 gaps inside it are
#: re-derived exactly before any certificate decision reads them
_FLAG_WINDOW = 64.0


# --- the registry-level type system ------------------------------------------


@dataclasses.dataclass
class TypeSystem:
    """The type reduction carried at the *registry* level so edits update it
    in O(edit) — the piece a from-scratch solve rebuilds with an O(n) pass.

    ``rows`` stores each type's per-category feature SLOTS (the registry's
    ``assignments`` row), not global feature ids: a ``new_type`` edit appends
    a slot at the end of its category, so existing keys never shift. Types
    are append-only — a type whose pool empties keeps its index with
    ``msize = 0`` (the screen kills every column using it), so stored
    columns, duals and packs never need re-indexing.
    """

    k: int
    features: Tuple[Tuple[str, ...], ...]  # per-category feature names
    rows: np.ndarray  # int32 [T, C] per-category feature slots
    msize: np.ndarray  # int64 [T] pool size per type
    lo: np.ndarray  # int64 [F] flat quota lower bounds
    hi: np.ndarray  # int64 [F] flat quota upper bounds

    def __post_init__(self):
        self._index: Dict[Tuple[int, ...], int] = {
            tuple(int(v) for v in row): t for t, row in enumerate(self.rows)
        }

    @property
    def T(self) -> int:
        return self.rows.shape[0]

    @property
    def n_cats(self) -> int:
        return self.rows.shape[1]

    @property
    def F(self) -> int:
        return len(self.lo)

    @property
    def cell_offsets(self) -> np.ndarray:
        sizes = [len(f) for f in self.features]
        return np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(np.int64)

    @property
    def type_feature(self) -> np.ndarray:
        """int64 [T, n_cats] global feature ids, ascending per row (the same
        key layout as ``TypeReduction.type_feature``)."""
        return self.cell_offsets[None, :] + self.rows.astype(np.int64)

    @classmethod
    def from_registry(cls, reg: Registry) -> "TypeSystem":
        rows, counts = np.unique(reg.assignments, axis=0, return_counts=True)
        return cls(
            k=int(reg.k),
            features=tuple(tuple(f) for f in reg.features),
            rows=rows.astype(np.int32),
            msize=counts.astype(np.int64),
            lo=reg.qmin.astype(np.int64),
            hi=reg.qmax.astype(np.int64),
        )

    def update(
        self, edit: RegistryEdit, reg_before: Registry
    ) -> Tuple["TypeSystem", dict]:
        """Project ``edit`` onto the type space in O(edit).

        Returns the updated system plus an info dict the re-certifier
        consumes: ``changed`` (existing types whose pool moved, with old/new
        sizes), ``new_types`` (appended type indices), and the edited quota
        ``cell`` with its ``old_band``/``new_band``.
        """
        info: dict = {"kind": edit.kind, "changed": [], "new_types": []}
        features = tuple(tuple(f) for f in self.features)
        rows, msize = self.rows, self.msize.copy()
        lo, hi = self.lo.copy(), self.hi.copy()

        if edit.kind in ("agents_add", "new_type"):
            erows = np.asarray(edit.rows, dtype=np.int32)
            if edit.kind == "new_type":
                c = int(edit.category)
                name = edit.feature or f"{c}_new"
                new_slot = len(features[c])
                at = int(self.cell_offsets[c]) + new_slot
                features = tuple(
                    f + (name,) if ci == c else f for ci, f in enumerate(features)
                )
                lo = np.insert(lo, at, 0)
                hi = np.insert(hi, at, min(int(edit.dhi), self.k))
                info["cell"] = at
            uniq, counts = np.unique(erows, axis=0, return_counts=True)
            new_rows: List[np.ndarray] = []
            for row, cnt in zip(uniq, counts):
                t = self._index.get(tuple(int(v) for v in row))
                if t is None:
                    info["new_types"].append(self.T + len(new_rows))
                    new_rows.append(row)
                    msize = np.append(msize, int(cnt))
                else:
                    info["changed"].append((t, int(msize[t]), int(msize[t]) + int(cnt)))
                    msize[t] += int(cnt)
            if new_rows:
                rows = np.concatenate([rows, np.stack(new_rows)], axis=0)
        elif edit.kind == "agents_drop":
            drop = np.asarray(edit.agents, dtype=np.int64)
            uniq, counts = np.unique(
                reg_before.assignments[drop], axis=0, return_counts=True
            )
            for row, cnt in zip(uniq, counts):
                t = self._index[tuple(int(v) for v in row)]
                info["changed"].append((t, int(msize[t]), int(msize[t]) - int(cnt)))
                msize[t] -= int(cnt)
                if msize[t] < 0:
                    raise ValueError("agents_drop exceeds the type's pool")
        elif edit.kind in ("quota_relax", "quota_tighten"):
            f = int(edit.cell)
            info["cell"] = f
            info["old_band"] = (int(lo[f]), int(hi[f]))
            lo[f] = max(0, int(lo[f]) + int(edit.dlo))
            hi[f] = min(self.k, int(hi[f]) + int(edit.dhi))
            info["new_band"] = (int(lo[f]), int(hi[f]))
        else:
            raise ValueError(f"unknown edit kind {edit.kind!r}")

        return (
            TypeSystem(
                k=self.k, features=features, rows=rows, msize=msize, lo=lo, hi=hi
            ),
            info,
        )


# --- delta state: the portable certificate -----------------------------------


@dataclasses.dataclass
class DeltaState:
    """Everything the delta solver needs to re-certify after the next edit:
    the column hull, the certified mixture, the per-stage dual certificates,
    and the incrementally-maintained ELL pack. Lives in the tenant session
    keyed by the *instance content fingerprint* — a revised instance can
    never pick up a stale state (the memo-staleness contract)."""

    system: TypeSystem
    comps: np.ndarray  # int32 [C, T] surviving column hull
    probabilities: np.ndarray  # float64 [C] certified mixture
    type_values: np.ndarray  # float64 [T] served leximin values
    eps_dev: float  # the ladder's own arithmetic ε
    certs: List[StageCert]  # per-stage dual certificates
    pack: EllPack  # ELL pack of ``comps`` (minor = T)
    fingerprint: str = ""  # content fingerprint of the certified instance
    lp_solves: int = 0  # cumulative LP count across base + deltas
    #: certified L∞ bound of the served values vs the true leximin optimum:
    #: equals ``eps_dev`` after any ladder run, grows by the drift bound on
    #: each sensitivity cache hit — a hit is refused before it can cross
    #: the 1e-3 contract
    eps_bound: float = 0.0
    #: accumulated dual/value drift vs the stored stage certificates (reset
    #: to 0 by any ladder re-run); consumes ``delta_cert_margin`` headroom
    cert_drift: float = 0.0


@dataclasses.dataclass(frozen=True)
class ReviseSpec:
    """The ``revise`` payload of a ``SelectionRequest``: one registry edit
    against an identified base solve. ``base_fingerprint`` must match the
    session's stored :class:`DeltaState` — a mismatch (stale or foreign
    base) falls back to from-scratch rather than re-certifying against the
    wrong portfolio. ``reg_before`` carries the pre-edit registry so drops
    can be projected onto types without an O(n) diff."""

    edit: RegistryEdit
    reg_before: Registry
    base_fingerprint: str = ""


@dataclasses.dataclass
class DeltaOutcome:
    """One re-certification step: the successor state plus the audit block
    (``delta_cert``) describing how the answer was obtained."""

    state: DeltaState
    cert: dict


# --- the device screening core -----------------------------------------------

_SCREEN_CORE = None


def _get_screen_core():
    """One fused jitted screen over the packed column hull: integer
    feasibility against the edited instance plus the per-stage dual price
    gap. Operands are bucket-padded by the host wrapper; all padding is
    inert by construction (zero ELL rows sum to 0 ≠ k, padded types carry
    ``minv = 0`` and ``Y = 0``, padded stages carry ``mu = 1e9``) and the
    division is guarded so the roofline harness's all-zero operands trace
    cleanly."""
    global _SCREEN_CORE
    if _SCREEN_CORE is None:
        from functools import partial

        @partial(jax.jit, static_argnames=("k",))
        def core(idx, val, tfeat, minv, lo, hi, Y, mu, *, k):
            # idx/val [C, P] ELL slots (type index, member count);
            # tfeat [T, ncat] global feature ids; minv [T] pool sizes;
            # lo/hi [F] quota bands; Y [S, T] stage duals; mu [S] support
            # prices. Counts are small integers, exact in f32 (< 2^24), so
            # the ±0.5 comparisons are exact integer tests.
            total = val.sum(axis=1)  # [C]
            ok_k = jnp.abs(total - k) < 0.5
            mv = minv[idx]  # [C, P]
            ok_cap = jnp.all(val <= mv + 0.5, axis=1)
            F = lo.shape[0]
            feat = tfeat[idx]  # [C, P, ncat]
            onehot = jax.nn.one_hot(feat, F, dtype=iterate_dtype(val.dtype))  # [C, P, ncat, F]
            counts = jnp.einsum("cp,cpjf->cf", val, onehot)  # [C, F]
            ok_band = jnp.all(
                (counts >= lo[None, :] - 0.5) & (counts <= hi[None, :] + 0.5),
                axis=1,
            )
            feas = ok_k & ok_cap & ok_band
            w = val / jnp.maximum(mv, 1.0)  # [C, P] allocation weights
            price = jnp.einsum("scp,cp->sc", Y[:, idx], w)  # [S, C]
            gap = mu[:, None] - price  # [S, C]
            return feas, gap

        from citizensassemblies_tpu.aot.store import aot_seeded

        _SCREEN_CORE = aot_seeded(
            "delta.screen", core, static_argnames=("k",)
        )
    return _SCREEN_CORE


@register_ir_core("delta.screen", span="delta.screen")
def _ir_delta_screen() -> IRCase:
    """The churn screen at one small shape (C=64 columns, P=8 ELL slots,
    T=32 types over 3 categories, F=12 quota cells, S=4 stages): the fused
    gather/one-hot/einsum structure is what is under verification."""
    S = jax.ShapeDtypeStruct
    i32, f32 = jnp.int32, jnp.float32
    C, P, T, ncat, F, St = 64, 8, 32, 3, 12, 4
    return IRCase(
        fn=_get_screen_core(),
        args=(
            S((C, P), i32), S((C, P), f32), S((T, ncat), i32), S((T,), f32),
            S((F,), f32), S((F,), f32), S((St, T), f32), S((St,), f32),
        ),
        static=dict(k=8),
    )


def _round_up(x: int, m: int) -> int:
    return ((max(int(x), 1) + m - 1) // m) * m


def _host_feasible(comps: np.ndarray, system: TypeSystem) -> np.ndarray:
    """Exact int64 feasibility re-proof of every column (the same hard
    discipline as ``DevicePricer._validate``: a column the screen keeps
    becomes part of a served certificate, so its feasibility is re-proven
    in exact host arithmetic before the device verdict is trusted)."""
    T, F = system.T, system.F
    c64 = comps.astype(np.int64)
    tf = np.zeros((T, F), dtype=np.int64)
    if system.n_cats:
        tfe = system.type_feature
        tf[np.repeat(np.arange(T), system.n_cats), tfe.ravel()] = 1
    counts = c64 @ tf
    feas = c64.sum(axis=1) == system.k
    feas &= (c64 <= system.msize[None, :]).all(axis=1)
    feas &= (counts >= system.lo[None, :]).all(axis=1)
    feas &= (counts <= system.hi[None, :]).all(axis=1)
    return feas


def screen_columns(
    pack: EllPack,
    comps: np.ndarray,
    system: TypeSystem,
    certs: List[StageCert],
    margin: float,
    cfg=None,
    log: Optional[RunLog] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Screen the packed column hull against the edited instance in ONE
    batched device dispatch.

    Returns ``(feas bool [C], gap float64 [S, C])`` where ``gap[s, c] =
    μ_s − price_s(c)``. Feasibility is re-proven on host in int64 (hard
    contract); device f32 gaps inside ``_FLAG_WINDOW`` margins of the
    certificate threshold — the "re-pricing set" — are re-derived on host
    in float64 before any certificate decision reads them."""
    log = log or RunLog(echo=False)
    C, T = comps.shape
    S_n = len(certs)
    # stable compile buckets: pow2 columns, padded types/cells/stages
    Cp = max(64, 1 << (C - 1).bit_length()) if C else 64
    Tp = _round_up(T, 8)
    Fp = _round_up(system.F, 8)
    Sp = max(4, _round_up(max(S_n, 1), 4))
    idx, val = pack.padded(Cp)
    tfeat = np.zeros((Tp, max(system.n_cats, 1)), dtype=np.int32)
    if system.n_cats:
        tfeat[:T] = system.type_feature.astype(np.int32)
    minv = np.zeros(Tp, dtype=np.float32)
    minv[:T] = np.minimum(system.msize, np.iinfo(np.int32).max)
    lof = np.zeros(Fp, dtype=np.float32)
    hif = np.full(Fp, float(system.k), dtype=np.float32)
    lof[: system.F] = system.lo
    hif[: system.F] = system.hi
    Y = np.zeros((Sp, Tp), dtype=np.float32)
    mu = np.full(Sp, 1e9, dtype=np.float32)
    for s, cert in enumerate(certs):
        Y[s, :T] = cert.y
        mu[s] = cert.mu
    core = _get_screen_core()
    with dispatch_span(
        "delta.screen", cfg=cfg, log=log, cols=int(C), stages=int(S_n)
    ) as _ds:
        with no_implicit_transfers(cfg):
            feas_d, gap_d = core(
                jnp.asarray(idx), jnp.asarray(val), jnp.asarray(tfeat),
                jnp.asarray(minv), jnp.asarray(lof), jnp.asarray(hif),
                jnp.asarray(Y), jnp.asarray(mu), k=int(system.k),
            )
        _ds.out = (feas_d, gap_d)
    feas = np.asarray(feas_d)[:C] & _host_feasible(comps, system)
    gap = np.asarray(gap_d, dtype=np.float64)[:S_n, :C]
    if S_n and C:
        # float64 re-pricing of the near-margin set: the certificate
        # threshold must never ride on f32 round-off
        flagged = np.nonzero(np.min(gap, axis=0) < _FLAG_WINDOW * margin)[0]
        if flagged.size:
            log.count("delta_screen_flag", int(flagged.size))
            mm = np.maximum(system.msize.astype(np.float64), 1.0)
            M = comps[flagged].astype(np.float64) / mm[None, :]
            Ys = np.stack([c.y for c in certs])  # [S, T]
            mus = np.asarray([c.mu for c in certs])
            gap[:, flagged] = mus[:, None] - Ys @ M.T
    return feas, gap


# --- incremental enumeration of newly-admitted regions -----------------------


def _enumerate_region(
    system: TypeSystem,
    tlo: np.ndarray,
    thi: np.ndarray,
    lo: np.ndarray,
    hi: np.ndarray,
    cap: int = 200_000,
    node_budget: int = 3_000_000,
) -> Optional[np.ndarray]:
    """All compositions with per-type bounds ``tlo ≤ c_t ≤ thi`` and quota
    bands ``lo ≤ counts ≤ hi`` (int32 [R, T]); None if the region exceeds
    ``cap`` columns or ``node_budget`` search nodes (the caller falls back
    to a from-scratch solve). The same suffix-pruned DFS as
    ``enumerate_compositions``, generalised to type LOWER bounds so an
    edit's newly-admitted region — and only it — is enumerated."""
    T, F, k = system.T, system.F, system.k
    tlo = np.maximum(np.asarray(tlo, dtype=np.int64), 0)
    thi = np.minimum(np.asarray(thi, dtype=np.int64), k)
    if np.any(tlo > thi):
        return np.zeros((0, T), dtype=np.int32)
    tf = np.zeros((T, F), dtype=np.int64)
    tfe = system.type_feature
    if system.n_cats:
        tf[np.repeat(np.arange(T), system.n_cats), tfe.ravel()] = 1
    suf_max = np.zeros((T + 1, F), dtype=np.int64)
    suf_min = np.zeros((T + 1, F), dtype=np.int64)
    suf_max_t = np.zeros(T + 1, dtype=np.int64)
    suf_min_t = np.zeros(T + 1, dtype=np.int64)
    for i in range(T - 1, -1, -1):
        suf_max[i] = suf_max[i + 1] + tf[i] * int(thi[i])
        suf_min[i] = suf_min[i + 1] + tf[i] * int(tlo[i])
        suf_max_t[i] = suf_max_t[i + 1] + int(thi[i])
        suf_min_t[i] = suf_min_t[i + 1] + int(tlo[i])

    out: List[np.ndarray] = []
    counts = np.zeros(F, dtype=np.int64)
    cur = np.zeros(T, dtype=np.int32)
    nodes = 0

    def rec(i: int, total: int) -> bool:
        nonlocal nodes
        nodes += 1
        if nodes > node_budget:
            return False
        if i == T:
            if total == k and np.all(counts >= lo) and np.all(counts <= hi):
                out.append(cur.copy())
                if len(out) > cap:
                    return False
            return True
        if total + suf_max_t[i] < k or total + suf_min_t[i] > k:
            return True
        if np.any(counts + suf_min[i] > hi) or np.any(counts + suf_max[i] < lo):
            return True
        row = tfe[i]
        c_hi = min(int(thi[i]), k - total - int(suf_min_t[i + 1]))
        for c in range(c_hi, int(tlo[i]) - 1, -1):
            cur[i] = c
            counts[row] += c
            ok = rec(i + 1, total + c)
            counts[row] -= c
            cur[i] = 0
            if not ok:
                return False
        return True

    if not rec(0, 0) or len(out) > cap:
        return None
    if not out:
        return np.zeros((0, T), dtype=np.int32)
    return np.stack(out, axis=0)


def _admitted_regions(
    system: TypeSystem, info: dict
) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Disjoint (tlo, thi, lo, hi) regions covering exactly the columns the
    edit newly admits. Tighten/drop edits admit nothing; a quota relaxation
    admits the widened band minus the old band (one region per side); raised
    per-type caps (joins, new types) admit columns exceeding the old cap,
    deduplicated by capping each earlier raised type back at its old size."""
    T, k = system.T, system.k
    base_tlo = np.zeros(T, dtype=np.int64)
    base_thi = np.minimum(system.msize, k)
    lo, hi = system.lo.copy(), system.hi.copy()
    kind = info["kind"]
    if kind in ("quota_tighten", "agents_drop"):
        return []
    regions = []
    if kind == "quota_relax":
        f = info["cell"]
        ol, oh = info["old_band"]
        nl, nh = info["new_band"]
        if nl < ol:
            l2, h2 = lo.copy(), hi.copy()
            l2[f], h2[f] = nl, ol - 1
            regions.append((base_tlo, base_thi, l2, h2))
        if nh > oh:
            l2, h2 = lo.copy(), hi.copy()
            l2[f], h2[f] = oh + 1, nh
            regions.append((base_tlo, base_thi, l2, h2))
        return regions
    raised = [(t, m0) for (t, m0, m1) in info["changed"] if m1 > m0]
    raised += [(t, 0) for t in info["new_types"]]
    for i, (t, m_old) in enumerate(raised):
        tlo, thi = base_tlo.copy(), base_thi.copy()
        tlo[t] = m_old + 1
        for tj, mj in raised[:i]:
            thi[tj] = min(int(thi[tj]), mj)
        regions.append((tlo, thi, lo, hi))
    return regions


# --- base certification ------------------------------------------------------


def certify_base(
    reg: Registry,
    cfg=None,
    log: Optional[RunLog] = None,
    fingerprint: str = "",
) -> Optional[DeltaState]:
    """Solve the registry from scratch once, capturing everything the delta
    path needs: the full enumeration, the mixture, the per-stage dual
    certificates, and the ELL pack. Returns None when the instance is out
    of the enumerable regime (too many types / columns) — delta serving is
    scoped to the enumerated tier."""
    log = log or RunLog(echo=False)
    system = TypeSystem.from_registry(reg)
    max_types = getattr(cfg, "enum_max_types", 16) if cfg else 16
    if system.T > max_types:
        return None
    cap = getattr(cfg, "enum_cap", 200_000) if cfg else 200_000
    budget = getattr(cfg, "enum_node_budget", 3_000_000) if cfg else 3_000_000
    comps = _enumerate_region(
        system,
        np.zeros(system.T, dtype=np.int64),
        np.minimum(system.msize, system.k),
        system.lo,
        system.hi,
        cap=cap,
        node_budget=budget,
    )
    if comps is None or len(comps) == 0:
        return None
    ts = leximin_over_compositions(
        comps,
        np.maximum(system.msize, 1).astype(np.float64),
        probe_tol=getattr(cfg, "probe_tol", 1e-7) if cfg else 1e-7,
        log=log,
        cfg=cfg,
        capture_certs=True,
    )
    pack = EllPack.from_rows(comps, minor=system.T)
    return DeltaState(
        system=system,
        comps=comps,
        probabilities=ts.probabilities,
        type_values=ts.type_values,
        eps_dev=ts.eps_dev,
        certs=ts.stage_certs,
        pack=pack,
        fingerprint=fingerprint,
        lp_solves=ts.lp_solves,
        eps_bound=ts.eps_dev,
        cert_drift=0.0,
    )


# --- re-certification --------------------------------------------------------


def _embed_cert(cert: StageCert, T_new: int) -> StageCert:
    """Embed a stage certificate into a grown type space: appended types
    carry zero dual weight and stay OPEN (-1) in the fixed vector."""
    T_old = len(cert.y)
    if T_new == T_old:
        return cert
    return StageCert(
        z=cert.z,
        y=np.concatenate([cert.y, np.zeros(T_new - T_old)]),
        mu=cert.mu,
        fixed_after=np.concatenate(
            [cert.fixed_after, np.full(T_new - T_old, -1.0)]
        ),
    )


def _drift_bound(info: dict, comps_surviving: np.ndarray) -> float:
    """Conservative per-stage value drift from pool-size changes: the LP
    matrix rows scale by ``m_t/m'_t``, so any mixture's type-t value moves
    by at most ``max_c c_t · |1/m'_t − 1/m_t|`` — evaluated with the max
    count over the SURVIVING hull (tighter than k)."""
    d = 0.0
    for t, m0, m1 in info.get("changed", []):
        cmax = float(comps_surviving[:, t].max()) if len(comps_surviving) else 0.0
        d = max(
            d, cmax * abs(1.0 / max(m1, 1) - 1.0 / max(m0, 1))
        )
    return d


def recertify(
    state: DeltaState,
    edit: RegistryEdit,
    reg_before: Registry,
    cfg=None,
    log: Optional[RunLog] = None,
    fingerprint: str = "",
) -> Optional[DeltaOutcome]:
    """Re-certify the portfolio after one registry edit in ~O(edit).

    Decision ladder (each rung strictly cheaper than the next):

    1. **cache hit** — old support survives, every newly-admitted column
       prices out at every stage, drift bound inside the margin: serve the
       old mixture with exactly recomputed values, zero LP solves;
    2. **warm resume** — only stages ≥ s are invalidated by priced-in new
       columns: resume the ladder from stage s's stored fixed vector;
    3. **full ladder** — re-run the fixing ladder over the screened hull
       plus the incremental region (still no O(n) reduction, no full
       enumeration).

    Returns None when the edit leaves the delta envelope (region enumeration
    over budget, or the hull died) — the caller falls back to from-scratch.
    """
    log = log or RunLog(echo=False)
    margin = getattr(cfg, "delta_cert_margin", 2.0e-4) if cfg else 2.0e-4
    with log.timer("delta_recertify"):
        sys_new, info = state.system.update(edit, reg_before)
        T0, T1 = state.system.T, sys_new.T
        comps_old = state.comps
        if T1 > T0:
            comps_old = np.pad(comps_old, ((0, 0), (0, T1 - T0)))
        certs = [_embed_cert(c, T1) for c in state.certs]

        # 1) incremental enumeration of the newly-admitted regions
        cap = getattr(cfg, "enum_cap", 200_000) if cfg else 200_000
        budget = getattr(cfg, "enum_node_budget", 3_000_000) if cfg else 3_000_000
        new_parts: List[np.ndarray] = []
        for tlo, thi, lo2, hi2 in _admitted_regions(sys_new, info):
            r = _enumerate_region(sys_new, tlo, thi, lo2, hi2, cap, budget)
            if r is None:
                return None
            new_parts.append(r)
        new_rows = (
            np.concatenate(new_parts, axis=0)
            if new_parts
            else np.zeros((0, T1), dtype=np.int32)
        )
        if len(new_rows):
            log.count("delta_new_columns", int(len(new_rows)))

        # 2) incremental pack maintenance + ONE screening dispatch
        pack = state.pack.take(np.arange(len(state.pack)))  # copy, not alias
        pack.minor = T1
        if len(new_rows):
            pack.append(new_rows)
        comps_all = np.concatenate([comps_old, new_rows], axis=0)
        with log.timer("delta_screen"):
            feas, gap = screen_columns(
                pack, comps_all, sys_new, certs, margin, cfg=cfg, log=log
            )
        n_old = len(comps_old)
        feas_old, feas_new = feas[:n_old], feas[n_old:]
        dropped = int((~feas_old).sum())
        if dropped:
            log.count("delta_screen_drop", dropped)
        if not feas.any():
            return None  # the hull died: the edited instance needs a fresh solve

        support = state.probabilities > _SUPPORT_EPS
        support_ok = bool(feas_old[support].all())
        dropped_mass = float(state.probabilities[~feas_old].sum())

        # per-stage price verdict on the new feasible columns
        S_n = len(certs)
        new_feas = np.nonzero(feas_new)[0]
        margin_eff = margin - state.cert_drift
        if S_n and len(new_feas):
            gap_new = gap[:, n_old + new_feas]  # [S, R]
            priced_out = bool((gap_new > margin_eff).all())
            bad_stages = np.nonzero((gap_new <= margin_eff).any(axis=1))[0]
            first_bad = int(bad_stages[0]) if len(bad_stages) else None
        else:
            priced_out = True
            first_bad = None

        # a new TYPE covered by feasible new columns changes the leximin
        # OBJECTIVE (a fresh min to raise), not just the column set — no
        # stage face argument applies, so neither cache hit nor resume may
        # claim; only an uncoverable new type (no feasible column carries
        # it) legitimately keeps its value at 0
        new_type_covered = any(
            bool(comps_all[feas][:, t].max() > 0) for t in info["new_types"]
        )

        drift = _drift_bound(info, comps_all[feas])
        eps_grow = drift + S_n * drift + dropped_mass
        cache_ok = (
            support_ok
            and priced_out
            and not new_type_covered
            and (
                drift == 0.0
                or (
                    state.cert_drift + S_n * drift <= margin
                    and state.eps_bound + eps_grow <= CONTRACT_LINF
                )
            )
            and state.eps_bound + eps_grow <= CONTRACT_LINF
        )

        keep_idx = np.nonzero(feas)[0]
        comps_keep = comps_all[feas]
        pack_keep = pack.take(keep_idx)
        mm = np.maximum(sys_new.msize, 1).astype(np.float64)
        probe_tol = getattr(cfg, "probe_tol", 1e-7) if cfg else 1e-7

        if cache_ok:
            log.count("delta_cache_hit")
            probs_full = np.concatenate(
                [state.probabilities, np.zeros(len(new_rows))]
            )[feas]
            probs = probs_full / probs_full.sum()
            values = probs @ (comps_keep.astype(np.float64) / mm[None, :])
            new_state = DeltaState(
                system=sys_new,
                comps=comps_keep,
                probabilities=probs,
                type_values=values,
                eps_dev=state.eps_dev,
                certs=certs,
                pack=pack_keep,
                fingerprint=fingerprint,
                lp_solves=state.lp_solves,
                eps_bound=state.eps_bound + eps_grow,
                cert_drift=state.cert_drift + S_n * drift,
            )
            cert_block = {
                "mode": "cache_hit",
                "edit": edit.kind,
                "magnitude": int(edit.magnitude),
                "lp_solves": 0,
                "eps_bound": float(new_state.eps_bound),
                "drift": float(drift),
                "margin": float(margin),
                "screen": {
                    "cols": int(len(comps_all)),
                    "dropped": dropped,
                    "new": int(len(new_rows)),
                    "new_feasible": int(len(new_feas)),
                },
            }
            return DeltaOutcome(state=new_state, cert=cert_block)

        # warm resume is only sound when the stage prefix is EXACT: no pool
        # drift (values shift), no accumulated cert drift, support intact,
        # and the invalidation strictly below the first bad stage
        resume_from = None
        if (
            support_ok
            and drift == 0.0
            and state.cert_drift == 0.0
            and not new_type_covered
            and first_bad is not None
            and first_bad > 0
        ):
            resume_from = first_bad
        fixed_init = certs[resume_from - 1].fixed_after if resume_from else None
        ts = leximin_over_compositions(
            comps_keep,
            mm,
            probe_tol=probe_tol,
            log=log,
            cfg=cfg,
            fixed_init=fixed_init,
            capture_certs=True,
        )
        if resume_from:
            log.count("delta_resume")
            log.count("delta_resume_stages", int(ts.stages))
            certs_new = certs[:resume_from] + ts.stage_certs
            mode = "resume"
        else:
            log.count("delta_full_ladder")
            certs_new = ts.stage_certs
            mode = "full_ladder"
        new_state = DeltaState(
            system=sys_new,
            comps=comps_keep,
            probabilities=ts.probabilities,
            type_values=ts.type_values,
            eps_dev=ts.eps_dev,
            certs=certs_new,
            pack=pack_keep,
            fingerprint=fingerprint,
            lp_solves=state.lp_solves + ts.lp_solves,
            eps_bound=ts.eps_dev,
            cert_drift=0.0,
        )
        cert_block = {
            "mode": mode,
            "edit": edit.kind,
            "magnitude": int(edit.magnitude),
            "lp_solves": int(ts.lp_solves),
            "eps_bound": float(ts.eps_dev),
            "drift": float(drift),
            "margin": float(margin),
            "resume_stage": int(resume_from) if resume_from else 0,
            "stages_rerun": int(ts.stages),
            "screen": {
                "cols": int(len(comps_all)),
                "dropped": dropped,
                "new": int(len(new_rows)),
                "new_feasible": int(len(new_feas)),
            },
        }
        return DeltaOutcome(state=new_state, cert=cert_block)


# --- service bridge: delta certificate → agent-space realization -------------


@dataclasses.dataclass
class _TypespaceShim:
    """Duck-typed stand-in for ``TypeLeximin`` over the SERVICE's reduction
    ordering — exactly the fields ``models/leximin.realize_typespace``
    reads to decompose a certificate into a concrete panel portfolio."""

    compositions: np.ndarray  # int32 [C, T_red]
    probabilities: np.ndarray  # float64 [C]
    type_values: np.ndarray  # float64 [T_red]
    eps_dev: float
    lp_solves: int
    stages: int
    coverable: np.ndarray  # bool [T_red]


def project_to_reduction(state: DeltaState, reduction) -> Optional[_TypespaceShim]:
    """Re-key the delta certificate onto a freshly-built ``TypeReduction``.

    The delta state's types are append-only registry-level types (emptied
    types kept at ``msize = 0``); the service's reduction enumerates the
    CURRENT pool's distinct rows in ``np.unique`` order. Both key types by
    the same ascending global-feature-id tuple, so the permutation is a dict
    match. Returns None on ANY inconsistency — unmatched reduction type,
    pool-size disagreement, or a live column on a type the reduction lost —
    which the service treats as a delta fallback (never served wrong).
    """
    sysfe = state.system.type_feature
    index = {tuple(int(v) for v in row): t for t, row in enumerate(sysfe)}
    perm = np.empty(reduction.T, dtype=np.int64)
    for r, row in enumerate(np.asarray(reduction.type_feature, dtype=np.int64)):
        t = index.get(tuple(int(v) for v in row))
        if t is None:
            return None
        perm[r] = t
    if not np.array_equal(
        state.system.msize[perm], reduction.msize.astype(np.int64)
    ):
        return None
    # types the reduction does NOT carry must be empty pools with no mass in
    # the certified hull (the screen guarantees their columns died)
    missing = np.setdiff1d(np.arange(state.system.T), perm)
    if len(missing) and (
        state.system.msize[missing].any() or state.comps[:, missing].any()
    ):
        return None
    comps = np.ascontiguousarray(state.comps[:, perm])
    return _TypespaceShim(
        compositions=comps,
        probabilities=state.probabilities,
        type_values=state.type_values[perm].copy(),
        eps_dev=float(state.eps_bound),
        lp_solves=int(state.lp_solves),
        stages=len(state.certs),
        coverable=comps.max(axis=0) > 0,
    )
