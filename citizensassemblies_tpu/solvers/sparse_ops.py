"""Structured-sparse (fixed-nnz ELL) operators for the PDHG/QP cores.

Every LP/QP matrix this framework ships to the device has columns (or rows)
that are panel *compositions*: at most ``k`` nonzeros out of ``T`` types
(k ≈ 20–40 against T up to 600+ on the household quotient), yet the dense
cores do full GEMVs — ≥90 % of the MXU FLOPs and HBM bytes per PDHG
iteration are multiply-by-zero. A PDLP-style first-order method lives and
dies on matvec cost (Applegate et al. 2021), so the fix is representational:

* **ELL layout** — a ``[major, minor]`` matrix with at most ``k_pad``
  nonzeros per major row is stored as ``indices[major, k_pad]`` (int32
  minor positions) and ``values[major, k_pad]`` (float32), padding slots
  pointing at minor 0 with value 0.0 — *inert by construction* for both
  matvec directions (a zero value contributes nothing to a gather sum and
  scatters nothing into a segment sum), so no mask tensor rides along.
* **two jitted matvecs** — the gather direction ``(M x)[j] = Σ_s
  values[j,s] · x[indices[j,s]]`` and the scatter/transpose direction
  ``(Mᵀ y)[i] = Σ_{j,s: indices[j,s]=i} values[j,s] · y[j]``
  (``segment_sum``). Batched variants are plain ``vmap``s with the packed
  arrays broadcast, which is how the bucketed engine reuses them.
* **Ruiz on the ELL rep** — row/column ∞-norms come from per-row maxima
  and ``segment_max`` over the packed values directly; the dense scaled
  matrix is never materialized.
* **incremental append** — :class:`EllPack` keeps the packed arrays on the
  host and re-packs ONLY new major rows as a column-generation portfolio
  grows (``append``), subsets them by fancy indexing on a prune (``take``),
  and tracks the measured fill ratio the auto-routing gate
  (:func:`sparse_enabled`) decides on.

Routing contract: ``Config.sparse_ops`` is a tri-state — ``True`` forces the
ELL path, ``False`` forces dense, ``None`` (auto) engages ELL exactly when
the measured fill is ≤ ``Config.sparse_fill_cutoff``. With the knob off
every call site runs its dense path bit-identically; with it on, results
differ only by float32 summation order inside the same iteration, and every
caller keeps its float64 arithmetic acceptance certificate unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.precision import iterate_dtype

#: packed-slot granularity: k_pad rounds up to a multiple of 8 (the f32
#: sublane tile) so slot growth across CG rounds re-buckets rarely
_SLOT_ROUND = 8


def _round_slots(k: int) -> int:
    return max(_SLOT_ROUND, -(-int(k) // _SLOT_ROUND) * _SLOT_ROUND)


def sparse_enabled(cfg: Optional[Config], fill: float) -> bool:
    """Resolve the ``Config.sparse_ops`` tri-state for a measured fill.

    ``True``/``False`` force; ``None`` (auto) turns the ELL path on exactly
    when the measured fill ratio is at or below
    ``Config.sparse_fill_cutoff`` — the regime where the gather/scatter
    matvecs beat the dense GEMV on both FLOPs and HBM bytes.
    """
    knob = getattr(cfg, "sparse_ops", None)
    if knob is not None:
        return bool(knob)
    cutoff = float(getattr(cfg, "sparse_fill_cutoff", 0.25))
    return float(fill) <= cutoff


def ell_pack_rows(
    rows: np.ndarray, k_pad: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack the rows of a dense ``[J, minor]`` array into ELL arrays.

    Returns ``(indices int32 [J, k_pad], values float32 [J, k_pad],
    nnz int64 [J])``. Nonzeros keep their original (ascending-minor) order —
    a stable argsort on the zero mask, so the pack/unpack round trip is
    exact. ``k_pad`` defaults to the max row nnz rounded up to a slot
    multiple; passing a larger one keeps bucket shapes stable across
    appends. Raises when a row has more nonzeros than ``k_pad``.
    """
    rows = np.asarray(rows)
    J, minor = rows.shape
    mask = rows != 0
    nnz = mask.sum(axis=1).astype(np.int64)
    need = int(nnz.max()) if J else 0
    kp = _round_slots(max(need, 1)) if k_pad is None else int(k_pad)
    if need > kp:
        raise ValueError(f"row nnz {need} exceeds the ELL slot count {kp}")
    take = min(kp, minor)
    # stable sort on the zero mask: nonzero positions first, original order
    order = np.argsort(~mask, axis=1, kind="stable")[:, :take]
    vals = np.take_along_axis(rows, order, axis=1)
    slot = np.arange(take)[None, :]
    keep = slot < nnz[:, None]
    idx = np.where(keep, order, 0).astype(np.int32)
    val = np.where(keep, vals, 0.0).astype(np.float32)
    if take < kp:  # minor smaller than the slot bucket: pad inert slots
        idx = np.pad(idx, ((0, 0), (0, kp - take)))
        val = np.pad(val, ((0, 0), (0, kp - take)))
    return idx, val, nnz


def ell_unpack_rows(idx: np.ndarray, val: np.ndarray, minor: int) -> np.ndarray:
    """Dense ``[J, minor]`` reconstruction of packed rows (tests/fuzz)."""
    J = idx.shape[0]
    out = np.zeros((J, minor), dtype=np.float64)
    rows = np.repeat(np.arange(J), idx.shape[1])
    np.add.at(out, (rows, idx.ravel()), val.ravel().astype(np.float64))
    return out


@dataclasses.dataclass
class EllPack:
    """Host-side ELL pack of a *growing* set of sparse major rows.

    The face-decomposition loop adds a few thousand columns per round and
    prunes back to the mass-bearing support; re-packing the whole portfolio
    every round would repeat O(C·T) host work that the incremental contract
    avoids: :meth:`append` packs only the NEW rows (growing the shared slot
    count when a new row needs it, which only zero-pads the existing
    arrays), and :meth:`take` subsets by fancy indexing. ``fill`` is the
    measured nnz ratio the auto gate routes on, and ``pack_rows`` counts
    how many rows were ever packed (the bench's pack-overhead counter
    rides the ``sparse_pack`` timer at the call sites).
    """

    minor: int
    idx: np.ndarray = None  # [J, k_pad] int32
    val: np.ndarray = None  # [J, k_pad] float32
    nnz_total: int = 0
    pack_rows: int = 0

    def __post_init__(self):
        if self.idx is None:
            self.idx = np.zeros((0, _SLOT_ROUND), dtype=np.int32)
        if self.val is None:
            self.val = np.zeros((0, _SLOT_ROUND), dtype=np.float32)

    def __len__(self) -> int:
        return self.idx.shape[0]

    @property
    def k_pad(self) -> int:
        return self.idx.shape[1]

    @property
    def fill(self) -> float:
        J = len(self)
        return (self.nnz_total / (J * self.minor)) if J else 0.0

    @classmethod
    def from_rows(cls, rows: np.ndarray, minor: Optional[int] = None) -> "EllPack":
        pack = cls(minor=int(minor if minor is not None else rows.shape[1]))
        pack.append(rows)
        return pack

    def append(self, rows: np.ndarray) -> None:
        """Pack and append new major rows (the incremental-column contract)."""
        rows = np.asarray(rows)
        if rows.size == 0:
            return
        need = int((rows != 0).sum(axis=1).max())
        kp = max(self.k_pad, _round_slots(max(need, 1)))
        if kp > self.k_pad:  # grow the shared slot bucket: zero slots are inert
            grow = kp - self.k_pad
            self.idx = np.pad(self.idx, ((0, 0), (0, grow)))
            self.val = np.pad(self.val, ((0, 0), (0, grow)))
        idx, val, nnz = ell_pack_rows(rows, k_pad=kp)
        self.idx = np.concatenate([self.idx, idx], axis=0)
        self.val = np.concatenate([self.val, val], axis=0)
        self.nnz_total += int(nnz.sum())
        self.pack_rows += rows.shape[0]

    def take(self, sel: np.ndarray) -> "EllPack":
        """Subset (and reorder) the packed rows — a portfolio prune."""
        sel = np.asarray(sel)
        idx = self.idx[sel]
        val = self.val[sel]
        out = EllPack(minor=self.minor, idx=idx, val=val)
        out.nnz_total = int((val != 0).sum())
        out.pack_rows = self.pack_rows
        return out

    def padded(self, rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """(idx, val) zero-padded to ``rows`` major rows (bucket padding:
        all-zero rows are inert for both matvec directions)."""
        J = len(self)
        if rows < J:
            raise ValueError(f"pad target {rows} below packed row count {J}")
        if rows == J:
            return self.idx, self.val
        idx = np.zeros((rows, self.k_pad), dtype=np.int32)
        val = np.zeros((rows, self.k_pad), dtype=np.float32)
        idx[:J] = self.idx
        val[:J] = self.val
        return idx, val


# --- jitted matvec primitives ------------------------------------------------
# The gather/scatter pair every ELL core in the repo composes. They are
# deliberately tiny free functions (not methods) so the PDHG/QP cores can
# inline them inside their own jitted bodies without a pytree wrapper.


def ell_gather_mv(idx, val, x):
    """``(M x)[j] = Σ_s values[j,s] · x[indices[j,s]]`` — the row-gather
    direction. Traceable; padding slots contribute ``0 · x[0]``."""
    return (val * x[idx]).sum(axis=1)


def ell_scatter_mv(idx, val, y, minor: int):
    """``(Mᵀ y)[i]`` — the transpose/scatter direction via ``segment_sum``
    (``minor`` is a static shape at trace time)."""
    import jax

    contrib = val * y[:, None]
    return jax.ops.segment_sum(
        contrib.ravel(), idx.ravel(), num_segments=int(minor)
    )


def ell_row_absmax(idx, val, minor: int):
    """Per-MINOR max of |values| (``segment_max``, clamped at 0 so minors
    hit by no slot scale like an all-zero dense row)."""
    import jax
    import jax.numpy as jnp

    seg = jax.ops.segment_max(
        jnp.abs(val).ravel(), idx.ravel(), num_segments=int(minor)
    )
    return jnp.maximum(seg, 0.0)


def ell_col_absmax(val):
    """Per-MAJOR max of |values| (one reduction over the slot axis)."""
    import jax.numpy as jnp

    return jnp.abs(val).max(axis=1)


def batched_ell_gather_mv(idx, val, X):
    """Batched gather matvec: shared pack, ``X [B, minor]`` → ``[B, major]``
    — the bucketed engine's broadcast form."""
    import jax

    return jax.vmap(lambda x: ell_gather_mv(idx, val, x))(X)


def batched_ell_scatter_mv(idx, val, Y, minor: int):
    """Batched transpose matvec: shared pack, ``Y [B, major]`` →
    ``[B, minor]``."""
    import jax

    return jax.vmap(lambda y: ell_scatter_mv(idx, val, y, minor))(Y)


def ell_ruiz_equilibrate(idx, val, minor: int, iters: int = 8):
    """Ruiz row/column scalings computed directly on the ELL rep.

    For the packed ``[major, minor]`` matrix: returns ``(d_major, d_minor)``
    with ``d_major[j] · M[j, i] · d_minor[i]`` of ≈ unit row/col ∞-norms —
    the same 8-sweep sqrt scheme as the dense cores
    (``lp_pdhg._ruiz_equilibrate``), with the row maxima taken over the slot
    axis and the column maxima by ``segment_max``; the scaled matrix is
    never materialized. All-zero rows/columns keep scale 1 (bucket padding).
    """
    import jax
    import jax.numpy as jnp

    major = idx.shape[0]
    absv = jnp.abs(val)

    def body(_, carry):
        d_j, d_i = carry
        S = absv * d_j[:, None] * d_i[idx]
        jmax = S.max(axis=1)
        imax = jnp.maximum(
            jax.ops.segment_max(S.ravel(), idx.ravel(), num_segments=int(minor)),
            0.0,
        )
        jn = jnp.where(jmax > 0, jnp.sqrt(jnp.maximum(jmax, 1e-10)), 1.0)
        inn = jnp.where(imax > 0, jnp.sqrt(jnp.maximum(imax, 1e-10)), 1.0)
        return d_j / jn, d_i / inn

    d_j0 = jnp.ones(major, dtype=iterate_dtype(val.dtype))
    d_i0 = jnp.ones(int(minor), dtype=iterate_dtype(val.dtype))
    return jax.lax.fori_loop(0, iters, body, (d_j0, d_i0))
