"""Min-norm distribution recovery: a simplex-constrained QP on device.

XMIN's final stage augments the ε-recovery LP with a quadratic term
``min ε + Σ_C p_C²`` (``xmin.py:447-455``) — the min-L2-norm tie-break that
spreads probability over as many committees as possible. Here the solve is
lexicographic instead of summed: first an ε floor is established — from the
caller's feasible donor distribution (optionally tightened by a short device
PDHG min-ε solve; the host LP runs only on donor-less calls, since HiGHS
crawled >30 min on a degenerate example_large-shaped instance of it) — then
this module minimizes ``Σ p²`` subject to realizing the targets within that
ε: the same support-spreading effect, with a clean TPU formulation.

The QP  min_{p ∈ Δ, Pᵀp ≥ t - ε} pᵀp  is solved via projected dual ascent:
for multipliers λ ≥ 0 on the coverage constraints, the inner minimization over
the simplex has the closed form ``p(λ) = proj_Δ(P λ / 2)``, and the dual
gradient is the constraint residual — two matvecs per iteration, all jittable
(``lax.fori_loop``), MXU-friendly, no host round-trips.

Under ``Config.lp_batch`` the min-ε PDHG and the dual ascent fuse into ONE
jitted device call (``_get_l2_fused_core``): the ε-floor pick happens on
device and the ascent runs under a ``lax.while_loop`` with an on-device
convergence check, replacing the serial path's chunked fori_loop + host
sync per chunk. The float64 floor/blend arithmetic and all acceptance
decisions stay on the host either way.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.utils.memo import LRU
from citizensassemblies_tpu.utils.precision import demote_operator, iterate_dtype


@jax.jit
def project_simplex(v: jnp.ndarray) -> jnp.ndarray:
    """Euclidean projection onto the probability simplex (sort-based)."""
    d = v.shape[0]
    u = jnp.sort(v)[::-1]
    css = jnp.cumsum(u) - 1.0
    idx = jnp.arange(1, d + 1, dtype=iterate_dtype(v.dtype))
    cond = u - css / idx > 0
    rho = jnp.sum(cond.astype(jnp.int32)) - 1
    theta = css[rho] / (rho + 1).astype(v.dtype)
    return jnp.maximum(v - theta, 0.0)


# lam0 is the loop-carried multiplier buffer: donated (its shape matches the
# returned lam, so XLA reuses the buffer), and returned so repeat callers can
# warm-start the ascent instead of re-climbing from zero
@partial(jax.jit, static_argnames=("iters",), donate_argnums=(4,))
def _min_norm_dual_ascent(P, t, eps, lr, lam0, iters: int):
    """Two-sided dual ascent: multipliers on BOTH ``Pᵀp ≥ t − ε`` and
    ``Pᵀp ≤ t + ε``. One-sided floors let the spread re-route surplus mass
    upward — on heterogeneous instances the overshoot concentrated several
    ×ε on individual agents, breaking the XMIN contract that per-agent
    probabilities stay at their leximin values. Returns ``(p, lam)``."""
    C, n = P.shape

    def p_of(lam):
        return project_simplex((P @ (lam[:n] - lam[n:])) / 2.0)

    def body(_, lam):
        p = p_of(lam)
        alloc = P.T @ p
        resid_lo = (t - eps) - alloc  # violated ⇒ positive ⇒ raise λ_lo
        resid_up = alloc - (t + eps)  # violated ⇒ positive ⇒ raise λ_up
        return jnp.maximum(lam + lr * jnp.concatenate([resid_lo, resid_up]), 0.0)

    lam = jax.lax.fori_loop(0, iters, body, lam0)
    return p_of(lam), lam


# lam0 donated exactly as in the dense ascent
@partial(jax.jit, static_argnames=("iters",), donate_argnums=(5,))
def _min_norm_dual_ascent_ell(idx, val, t, eps, lr, lam0, iters: int):
    """:func:`_min_norm_dual_ascent` on the ELL rep of the portfolio.

    ``idx``/``val`` pack P's ROWS (each panel: exactly k member columns of
    the n agents, ``solvers/sparse_ops``), so ``P @ w`` is a per-row gather
    sum and ``Pᵀ p`` a ``segment_sum`` — O(C·k) per iteration instead of
    O(C·n), on a 20k-iteration loop. Same two-sided multiplier semantics
    and return contract as the dense ascent."""
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    n = t.shape[0]

    def p_of(lam):
        return project_simplex(ell_gather_mv(idx, val, lam[:n] - lam[n:]) / 2.0)

    def body(_, lam):
        p = p_of(lam)
        alloc = ell_scatter_mv(idx, val, p, n)
        resid_lo = (t - eps) - alloc
        resid_up = alloc - (t + eps)
        return jnp.maximum(lam + lr * jnp.concatenate([resid_lo, resid_up]), 0.0)

    lam = jax.lax.fori_loop(0, iters, body, lam0)
    return p_of(lam), lam


def _ell_power_norm(idx, val, n: int, iters: int = 40):
    """‖P‖₂ power estimate via the ELL matvec pair (the dense
    ``lp_pdhg._power_norm`` semantics on the packed rep)."""
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    v = jnp.ones(n, dtype=iterate_dtype(val.dtype)) / jnp.sqrt(jnp.float32(n))

    def body(_, v):
        w = ell_scatter_mv(idx, val, ell_gather_mv(idx, val, v), n)
        return w / (jnp.linalg.norm(w) + 1e-12)

    v = jax.lax.fori_loop(0, iters, body, v)
    return jnp.sqrt(
        jnp.linalg.norm(
            ell_scatter_mv(idx, val, ell_gather_mv(idx, val, v), n)
        )
        + 1e-12
    )


#: memoized fused L2 cores per iteration schedule (one jitted program; its
#: jit cache holds one executable per portfolio bucket shape) — LRU-bounded
#: so schedule sweeps cannot accrete executables (utils/memo)
_L2_FUSED_CORES: LRU = LRU(cap=4, name="l2_fused_cores")


def _get_l2_fused_core(
    eps_iters: int, check_every: int, chunk: int, max_chunks: int,
    sentinel: bool = False,
):
    """Build (once per schedule) the FUSED min-ε + dual-ascent device call.

    One jitted program runs the whole L2 stage that the serial path splits
    into two device dispatches with a host sync between them
    (``l2_eps_pdhg`` then ``l2_dual_ascent``): (1) the min-ε anchor PDHG on
    the recovery LP, (2) the donor-vs-anchor ε-floor pick, (3) the dual
    ascent with an ON-DEVICE convergence check — a ``lax.while_loop`` over
    ``chunk``-iteration blocks that stops the moment the spread iterate's
    per-block movement drops below tolerance, instead of grinding a fixed
    20k-iteration ``fori_loop``. The host sees only the final iterates; the
    float64 floor/blend arithmetic stays with the caller (soundness
    unchanged).
    """
    key = (
        int(eps_iters), int(check_every), int(chunk), int(max_chunks),
        bool(sentinel),
    )
    core = _L2_FUSED_CORES.get(key)
    if core is not None:
        return core

    import jax
    import jax.numpy as jnp

    from citizensassemblies_tpu.solvers.lp_pdhg import _pdhg_body, _power_norm

    eps_iters, check_every, chunk, max_chunks, sentinel = key

    @jax.jit
    def fused(P, t, p_don, eps_margin, eps_tol, ascent_tol):
        f32 = iterate_dtype(P.dtype)
        C, n = P.shape
        PT = P.T
        # --- stage 1: min-ε anchor on the recovery LP (same generic PDHG
        # body as the serial solver, constraint matrix built on device) ----
        c = jnp.zeros(C + 1, f32).at[C].set(1.0)
        G = jnp.concatenate([-PT, -jnp.ones((n, 1), f32)], axis=1)
        h = -t
        A = jnp.concatenate([jnp.ones(C, f32), jnp.zeros(1, f32)])[None, :]
        b = jnp.ones(1, f32)
        s1 = _pdhg_body(
            c, G, h, A, b,
            jnp.zeros(C + 1, f32), jnp.zeros(n, f32), jnp.zeros(1, f32),
            eps_tol, max_iters=eps_iters, check_every=check_every,
            sentinel=sentinel,
        )
        x, _lam, _mu, it_eps, _res = s1[:5]
        flags1 = s1[5] if sentinel else None
        q = jnp.clip(x[:C], 0.0, 1.0)
        s = q.sum()
        q_n = jnp.where(s > 0, q / jnp.maximum(s, 1e-30), p_don)
        # --- stage 2: ε-floor pick, donor vs anchor, on device ------------
        dev_q = jnp.abs(PT @ q_n - t).max()
        dev_don = jnp.abs(PT @ p_don - t).max()
        use_q = (s > 0) & (dev_q < dev_don)
        p_floor = jnp.where(use_q, q_n, p_don)
        eps = jnp.minimum(jnp.where(s > 0, dev_q, jnp.inf), dev_don) + eps_margin
        # --- stage 3: dual ascent with on-device convergence check --------
        sigma_sq = _power_norm(P) ** 2
        lr = 1.0 / jnp.maximum(sigma_sq / 2.0, 1.0)

        def p_of(lam):
            return project_simplex((P @ (lam[:n] - lam[n:])) / 2.0)

        def ascent_iter(lam, _):
            p = p_of(lam)
            alloc = PT @ p
            resid_lo = (t - eps) - alloc
            resid_up = alloc - (t + eps)
            return (
                jnp.maximum(
                    lam + lr * jnp.concatenate([resid_lo, resid_up]), 0.0
                ),
                None,
            )

        def block(carry):
            lam, p_prev, k, _delta = carry
            lam, _ = jax.lax.scan(ascent_iter, lam, None, length=chunk)
            p_new = p_of(lam)
            delta = jnp.abs(p_new - p_prev).max()
            return lam, p_new, k + 1, delta

        def cond(carry):
            _lam, _p, k, delta = carry
            return (delta > ascent_tol) & (k < max_chunks)

        lam0 = jnp.zeros(2 * n, f32)
        p0 = p_of(lam0)
        state0 = (lam0, p0, jnp.int32(0), jnp.float32(jnp.inf))
        if sentinel:
            # ascent sentinel: a non-finite per-block movement freezes the
            # carry at the last finite iterate and exits flagged — the
            # caller re-runs the serial path on a quarantine
            def s_block(state):
                inner, flags = state[:4], state[4]
                new = block(inner)
                ok = jnp.isfinite(new[3])
                merged = tuple(jnp.where(ok, a, b) for a, b in zip(new, inner))
                flags = flags | jnp.where(ok, 0, 1).astype(jnp.int32)
                return merged + (flags,)

            def s_cond(state):
                return cond(state[:4]) & (state[4] == 0)

            lam, p, k, _delta, flags3 = jax.lax.while_loop(
                s_cond, s_block, state0 + (jnp.int32(0),)
            )
            return p, p_floor, it_eps, k * chunk, flags1 | flags3
        lam, p, k, _delta = jax.lax.while_loop(cond, block, state0)
        return p, p_floor, it_eps, k * chunk

    from citizensassemblies_tpu.aot.store import aot_seeded

    fused = aot_seeded(
        "qp.l2_fused[" + ",".join(str(int(v)) for v in key) + "]", fused
    )
    _L2_FUSED_CORES[key] = fused
    return fused


#: memoized ELL fused cores per schedule (shape-keyed executables inside)
_L2_FUSED_CORES_ELL: LRU = LRU(cap=4, name="l2_fused_cores_ell")


def _get_l2_fused_core_ell(
    eps_iters: int, check_every: int, chunk: int, max_chunks: int,
    sentinel: bool = False,
):
    """The fused L2 stage on the ELL rep of the portfolio.

    Same three stages as :func:`_get_l2_fused_core` — min-ε anchor, ε-floor
    pick, dual ascent under an on-device convergence ``while_loop`` — with
    every matvec running on the packed ``indices/values`` arrays: the anchor
    solves the two-sided ε master over the portfolio columns
    (``lp_pdhg._pdhg_two_sided_body_ell`` — its arithmetic deviation is what
    the floor pick judges anyway), and the ascent is the ELL gather/scatter
    pair. The float64 floor/blend arithmetic stays with the caller,
    unchanged.
    """
    key = (
        int(eps_iters), int(check_every), int(chunk), int(max_chunks),
        bool(sentinel),
    )
    core = _L2_FUSED_CORES_ELL.get(key)
    if core is not None:
        return core

    import jax
    import jax.numpy as jnp

    from citizensassemblies_tpu.solvers.lp_pdhg import _pdhg_two_sided_body_ell
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    eps_iters, check_every, chunk, max_chunks, sentinel = key

    @jax.jit
    def fused(idx, val, t, p_don, eps_margin, eps_tol, ascent_tol):
        f32 = iterate_dtype(val.dtype)
        C = idx.shape[0]
        n = t.shape[0]
        # --- stage 1: min-ε anchor — the two-sided ε master over the
        # portfolio columns, on the packed rep ------------------------------
        s1 = _pdhg_two_sided_body_ell(
            idx, val, t, jnp.ones(C, f32),
            jnp.zeros(C + 1, f32), jnp.zeros(2 * n, f32), jnp.zeros((), f32),
            eps_tol, max_iters=eps_iters, check_every=check_every,
            sentinel=sentinel,
        )
        x, _lam, _mu, it_eps, _res = s1[:5]
        flags1 = s1[5] if sentinel else None
        q = jnp.clip(x[:C], 0.0, 1.0)
        s = q.sum()
        q_n = jnp.where(s > 0, q / jnp.maximum(s, 1e-30), p_don)
        # --- stage 2: ε-floor pick, donor vs anchor, on device ------------
        dev_q = jnp.abs(ell_scatter_mv(idx, val, q_n, n) - t).max()
        dev_don = jnp.abs(ell_scatter_mv(idx, val, p_don, n) - t).max()
        use_q = (s > 0) & (dev_q < dev_don)
        p_floor = jnp.where(use_q, q_n, p_don)
        eps = jnp.minimum(jnp.where(s > 0, dev_q, jnp.inf), dev_don) + eps_margin
        # --- stage 3: dual ascent with on-device convergence check --------
        sigma_sq = _ell_power_norm(idx, val, n) ** 2
        lr = 1.0 / jnp.maximum(sigma_sq / 2.0, 1.0)

        def p_of(lam):
            return project_simplex(
                ell_gather_mv(idx, val, lam[:n] - lam[n:]) / 2.0
            )

        def ascent_iter(lam, _):
            p = p_of(lam)
            alloc = ell_scatter_mv(idx, val, p, n)
            resid_lo = (t - eps) - alloc
            resid_up = alloc - (t + eps)
            return (
                jnp.maximum(
                    lam + lr * jnp.concatenate([resid_lo, resid_up]), 0.0
                ),
                None,
            )

        def block(carry):
            lam, p_prev, k, _delta = carry
            lam, _ = jax.lax.scan(ascent_iter, lam, None, length=chunk)
            p_new = p_of(lam)
            delta = jnp.abs(p_new - p_prev).max()
            return lam, p_new, k + 1, delta

        def cond(carry):
            _lam, _p, k, delta = carry
            return (delta > ascent_tol) & (k < max_chunks)

        lam0 = jnp.zeros(2 * n, f32)
        p0 = p_of(lam0)
        state0 = (lam0, p0, jnp.int32(0), jnp.float32(jnp.inf))
        if sentinel:
            def s_block(state):
                inner, flags = state[:4], state[4]
                new = block(inner)
                ok = jnp.isfinite(new[3])
                merged = tuple(jnp.where(ok, a, b) for a, b in zip(new, inner))
                flags = flags | jnp.where(ok, 0, 1).astype(jnp.int32)
                return merged + (flags,)

            def s_cond(state):
                return cond(state[:4]) & (state[4] == 0)

            lam, p, k, _delta, flags3 = jax.lax.while_loop(
                s_cond, s_block, state0 + (jnp.int32(0),)
            )
            return p, p_floor, it_eps, k * chunk, flags1 | flags3
        lam, p, k, _delta = jax.lax.while_loop(cond, block, state0)
        return p, p_floor, it_eps, k * chunk

    from citizensassemblies_tpu.aot.store import aot_seeded

    fused = aot_seeded(
        "qp.l2_fused_ell[" + ",".join(str(int(v)) for v in key) + "]", fused
    )
    _L2_FUSED_CORES_ELL[key] = fused
    return fused


# --- graftcheck-IR registrations (lint/ir.py) -------------------------------


# the dense/ELL pairs register at the SAME (C, n) shape — n = 64 with k_pad
# = 8 slots is the production-representative fill (panels of k members out
# of n agents) the budget-diff's dense→sparse delta is measured at


@register_ir_core("qp.l2_dual_ascent", span="qp.l2_dual_ascent")
def _ir_dual_ascent() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    C, n = 96, 64
    return IRCase(
        fn=_min_norm_dual_ascent,
        args=(S((C, n), f32), S((n,), f32), S((), f32), S((), f32), S((2 * n,), f32)),
        static=dict(iters=2048),
        donate_expected=1,  # lam0
        arg_ranges=(
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (1e-8, 1e-2, False),
            (0.0, 1.0, False),
            (-1e4, 1e4, False),
        ),
        prec_demote=(0,),  # P
    )


@register_ir_core(
    "qp.l2_dual_ascent_ell",
    dense_ref="qp.l2_dual_ascent",
    span="qp.l2_dual_ascent_ell",
)
def _ir_dual_ascent_ell() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    C, n, kp = 96, 64, 8
    return IRCase(
        fn=_min_norm_dual_ascent_ell,
        args=(
            S((C, kp), i32), S((C, kp), f32), S((n,), f32),
            S((), f32), S((), f32), S((2 * n,), f32),
        ),
        static=dict(iters=2048),
        donate_expected=1,  # lam0
        arg_ranges=(
            None,
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (1e-8, 1e-2, False),
            (0.0, 1.0, False),
            (-1e4, 1e4, False),
        ),
        prec_demote=(1,),  # ELL values
    )


@register_ir_core("qp.l2_fused_core", span="qp.l2_fused_core")
def _ir_l2_fused() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    C, n = 96, 64
    return IRCase(
        fn=_get_l2_fused_core(1024, 128, 256, 8),
        args=(
            S((C, n), f32), S((n,), f32), S((C,), f32),
            S((), f32), S((), f32), S((), f32),
        ),
        arg_ranges=(
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (0.0, 1.0, False),
            (1e-8, 1e-2, False),
            (1e-8, 1e-2, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(0,),  # P
    )


@register_ir_core(
    "qp.l2_fused_core_ell",
    dense_ref="qp.l2_fused_core",
    span="qp.l2_fused_core_ell",
)
def _ir_l2_fused_ell() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    C, n, kp = 96, 64, 8
    return IRCase(
        fn=_get_l2_fused_core_ell(1024, 128, 256, 8),
        args=(
            S((C, kp), i32), S((C, kp), f32), S((n,), f32), S((C,), f32),
            S((), f32), S((), f32), S((), f32),
        ),
        arg_ranges=(
            None,
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (0.0, 1.0, False),
            (1e-8, 1e-2, False),
            (1e-8, 1e-2, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(1,),  # ELL values
    )


def _min_eps_pdhg(P: np.ndarray, PT: np.ndarray, target: np.ndarray, cfg=None):
    """Approximate min-ε recovery LP on device via
    ``lp_pdhg.solve_final_primal_lp_pdhg`` with NO host fallback: the caller
    validates the normalized iterate arithmetically and keeps the better of
    this and its donor. Short iteration budget — the iterate only needs to
    beat the donor's deviation when the donor is loose; the default 100k
    budget ground ~48 s on example_large's degenerate shape for accuracy
    nothing downstream uses. Returns ``(p_normalized, two_sided_dev)``."""
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_final_primal_lp_pdhg

    x, _eps = solve_final_primal_lp_pdhg(
        P, target, cfg=cfg, max_iters=12_288, tol=1e-5, host_fallback=False
    )
    p = np.clip(x, 0.0, 1.0)
    s = p.sum()
    if not np.isfinite(s) or s <= 0:
        return np.full(P.shape[0], 1.0 / max(P.shape[0], 1)), float("inf")
    p = p / s
    return p, float(np.abs(PT @ p - np.asarray(target)).max())


def solve_final_primal_l2(
    P: np.ndarray,
    target: np.ndarray,
    iters: int = 20_000,
    eps_margin: float = 1e-6,
    log=None,
    floor_donor: Optional[np.ndarray] = None,
    cfg=None,
    anchor_if_above: Optional[float] = None,
    ctx=None,
) -> Tuple[np.ndarray, float]:
    """Committee probabilities realizing ``target`` within the minimal ε, with
    minimal L2 norm (maximal spread). Returns (p, ε). ``log`` (a ``RunLog``)
    records the phase timers: on the donor path ``l2_eps_pdhg`` (the device
    min-ε anchor, run only when the donor's deviation exceeds
    ``anchor_if_above``) and ``l2_dual_ascent``; without a donor, the host
    ``l2_eps_lp`` plus the ascent.

    ``floor_donor`` supplies a KNOWN feasible probability vector over (a
    prefix of) ``P``'s rows — e.g. the LEXIMIN distribution the XMIN
    expansion grew from, or the panel decomposition that produced ``P``.
    With a donor, the HOST ε-LP is skipped entirely: on large portfolios
    with a degenerate uniform target (example_large_200: 16.5k panels ×
    n=2000, every coverage row tight at the optimum) scipy's HiGHS crawled
    for over 30 minutes on that LP. The ε floor is then the better of the
    donor's own realized deviation and one DEVICE PDHG min-ε solve (no host
    fallback — its iterate is validated arithmetically): anchoring near the
    grown portfolio's true minimal ε matters because the donor's deviation
    alone can exceed the caller's spread band (leximin realizations budget
    up to ~9e-4 at n ≥ 200 vs XMIN's 8e-4 band), which would silently
    disable the support expansion the caller exists for."""
    from citizensassemblies_tpu.service.context import resolve as resolve_context

    # per-request re-entrancy: cfg/log resolve through the ambient (or
    # explicit) RequestContext; its tenant session additionally memoizes the
    # packed ELL operands below
    ctx, cfg, log = resolve_context(ctx, cfg, log)
    if anchor_if_above is None:
        # derive the gate from the configured spread band so a tightened
        # band cannot open a (gate, band) window where the anchor is
        # skipped yet the donor deviation already exceeds the band
        band = getattr(cfg, "xmin_linf_band", 8e-4) if cfg is not None else 8e-4
        anchor_if_above = 0.5 * band
    PT = P.T.astype(np.float64)
    tgt = np.asarray(target, dtype=np.float64)
    fused_p: Optional[np.ndarray] = None
    # --- structured-sparse routing (solvers/sparse_ops): the portfolio's
    # rows are panels — exactly k member columns of n agents — so at XMIN
    # scale the dense ascent/anchor matvecs are ≥90 % multiply-by-zero.
    # The pack happens ONCE per call (timed as sparse_pack; the measured
    # fill and the hit/miss decision land in the run's counters), and the
    # float64 floor/blend arithmetic below never changes.
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack, sparse_enabled

    Pnp = np.asarray(P)
    p_fill = float(np.count_nonzero(Pnp)) / max(Pnp.size, 1)
    ell = None
    if sparse_enabled(cfg, p_fill):
        # tenant-session pack memo (service layer): a repeat solve over the
        # SAME portfolio — an XMIN re-submission, a warm re-solve — reuses
        # the packed indices/values instead of re-packing (content-hashed,
        # LRU-capped per tenant with eviction attribution)
        pack_key = None
        if ctx is not None and ctx.session is not None:
            import hashlib

            pack_key = "ell:" + hashlib.sha256(Pnp.tobytes()).hexdigest()
            ell = ctx.session.pack_get(pack_key)
            if ell is not None:
                log.count("session_pack_hit")
        if ell is None:
            with log.timer("sparse_pack"):
                ell = EllPack.from_rows(Pnp.astype(np.float32))
            if pack_key is not None:
                # attributed write: a failed request's teardown rolls back
                # exactly the packs it wrote (session rollback ledger)
                ctx.session.pack_put(pack_key, ell, request_id=ctx.request_id)
        log.gauge("sparse_fill_pct", int(round(100 * ell.fill)))
        log.count("sparse_hit")
    else:
        log.count("sparse_miss")
    if floor_donor is not None:
        p_don = np.zeros(P.shape[0], dtype=np.float64)
        p_don[: len(floor_donor)] = np.asarray(floor_donor, dtype=np.float64)
        s = p_don.sum()
        if s <= 0:
            raise ValueError("floor donor carries no probability mass")
        p_don = p_don / s
        dev_don = float(np.abs(PT @ p_don - tgt).max())
        p_lp, eps_star = p_don, dev_don
        if dev_don > anchor_if_above:
            # the anchor matters only when the donor's own deviation
            # approaches a caller's band (XMIN: 8e-4); a tight donor skips
            # the device solve outright
            from citizensassemblies_tpu.solvers.batch_lp import lp_batch_enabled

            if lp_batch_enabled(cfg):
                # FUSED path (solvers/batch_lp design): the min-ε anchor,
                # the donor-vs-anchor floor pick and the dual ascent run as
                # ONE jitted device call with an on-device convergence
                # check, eliminating the anchor→host→ascent round-trip.
                # The float64 floor/blend arithmetic below is unchanged —
                # the fused call only moves WHERE the f32 iterates are
                # produced, not how they are judged.
                from citizensassemblies_tpu.utils.guards import (
                    no_implicit_transfers,
                )

                from citizensassemblies_tpu.robust import inject
                from citizensassemblies_tpu.solvers.lp_pdhg import (
                    FLAG_POISONED,
                    sentinels_enabled,
                )

                sent = sentinels_enabled(cfg)
                chunk = 512
                max_chunks = max(1, -(-int(iters) // chunk))
                check_every = int(getattr(cfg, "pdhg_check_every", 128) or 128)
                with log.timer("l2_fused"):
                    tj = jnp.asarray(target, jnp.float32)
                    dj_h = np.asarray(p_don, np.float32)
                    if inject.site("qp_nan", log):
                        # chaos: poison the donor iterate — the QP sentinel
                        # must quarantine and the serial path must recover
                        dj_h = dj_h.copy()
                        dj_h[0] = np.nan
                    dj = jnp.asarray(dj_h)
                    margin_dev = jnp.asarray(eps_margin, jnp.float32)
                    eps_tol_dev = jnp.asarray(1e-5, jnp.float32)
                    asc_tol_dev = jnp.asarray(1e-7, jnp.float32)
                    if ell is not None:
                        fused_ell = _get_l2_fused_core_ell(
                            12_288, check_every, chunk, max_chunks,
                            sentinel=sent,
                        )
                        idx_j = jnp.asarray(ell.idx)
                        val_j = demote_operator(
                            jnp.asarray(ell.val), cfg,
                            core="qp.l2_fused_core_ell", arg=1, log=log,
                        )
                        with dispatch_span(
                            "qp.l2_fused_core_ell", cfg=cfg, log=log,
                            rows=int(P.shape[0]),
                        ) as _ds:
                            with no_implicit_transfers(cfg):
                                fused_out = fused_ell(
                                    idx_j, val_j, tj, dj,
                                    margin_dev, eps_tol_dev, asc_tol_dev,
                                )
                            p_dev, pf_dev = fused_out[0], fused_out[1]
                            _ds.out = (p_dev, pf_dev)
                    else:
                        fused_dense = _get_l2_fused_core(
                            12_288, check_every, chunk, max_chunks,
                            sentinel=sent,
                        )
                        Pj = demote_operator(
                            jnp.asarray(P, jnp.float32), cfg,
                            core="qp.l2_fused_core", arg=0, log=log,
                        )
                        with dispatch_span(
                            "qp.l2_fused_core", cfg=cfg, log=log,
                            rows=int(P.shape[0]),
                        ) as _ds:
                            with no_implicit_transfers(cfg):
                                fused_out = fused_dense(
                                    Pj, tj, dj, margin_dev, eps_tol_dev,
                                    asc_tol_dev,
                                )
                            p_dev, pf_dev = fused_out[0], fused_out[1]
                            _ds.out = (p_dev, pf_dev)
                    # host materialization inside the timer (see bench.py:
                    # block_until_ready alone does not drain a TPU tunnel)
                    fused_p = np.asarray(p_dev, dtype=np.float64)
                    p_floor = np.clip(np.asarray(pf_dev, dtype=np.float64), 0.0, 1.0)
                log.count("lp_batch_l2_fused")
                fused_flags = int(np.asarray(fused_out[4])) if sent else 0
                if (fused_flags & FLAG_POISONED) or not np.all(
                    np.isfinite(fused_p)
                ):
                    # quarantine: discard the fused iterates entirely — the
                    # serial ascent below re-runs from the clean donor and
                    # the float64 floor/blend arithmetic judges it as always
                    log.count("sentinel_quarantined")
                    log.count("sentinel_host_resolve")
                    fused_p = None
                    p_floor = None
                sf = p_floor.sum() if p_floor is not None else np.nan
                if np.isfinite(sf) and sf > 0:
                    p_floor = p_floor / sf
                    # the ε floor the blend trusts is recomputed in float64
                    # from the returned floor vector — the device's f32 pick
                    # only chose WHICH vector, never the certified number
                    dev_floor = float(np.abs(PT @ p_floor - tgt).max())
                    if dev_floor < dev_don:
                        p_lp, eps_star = p_floor, dev_floor
            else:
                with log.timer("l2_eps_pdhg"):
                    p_pd, dev_pd = _min_eps_pdhg(P, PT, tgt, cfg=cfg)
                if dev_pd < dev_don:
                    p_lp, eps_star = p_pd, dev_pd
    else:
        from citizensassemblies_tpu.solvers.highs_backend import (
            solve_final_primal_lp,
        )

        with log.timer("l2_eps_lp"):
            p_lp, eps_star = solve_final_primal_lp(P, target)
    eps = eps_star + eps_margin

    if fused_p is not None:
        # the fused device call already ran the ascent (with its on-device
        # convergence check) against the same floor it picked; only the
        # float64 validation/blend below remains
        p = fused_p
    else:
        tj = jnp.asarray(target, dtype=jnp.float32)
        # dual-gradient Lipschitz constant = σ_max(P)²/2, estimated by power
        # iteration (shared with the PDHG core): the closed-form bound
        # max_row_sum · max_col_sum / 2 overestimates σ² by orders of magnitude
        # on expanded portfolios (thousands of panels all containing the popular
        # agents), making the ascent step so small the spread never moved
        if ell is not None:
            idx_j = jnp.asarray(ell.idx)
            val_j = demote_operator(
                jnp.asarray(ell.val), cfg, core="qp.l2_dual_ascent_ell",
                arg=1, log=log,
            )
            sigma_sq = float(_ell_power_norm(idx_j, val_j, int(tj.shape[0]))) ** 2
        else:
            from citizensassemblies_tpu.solvers.lp_pdhg import _power_norm

            Pj = demote_operator(
                jnp.asarray(P, dtype=jnp.float32), cfg,
                core="qp.l2_dual_ascent", arg=0, log=log,
            )
            sigma_sq = float(_power_norm(Pj)) ** 2
        L = max(sigma_sq / 2.0, 1.0)
        with log.timer("l2_dual_ascent"):
            # the jitted ascent runs under the no-implicit-transfer guard: every
            # operand is materialized to a device array BEFORE the scope (the
            # scalar conversions too — an eager convert_element_type on a python
            # float inside the guard counts as an implicit upload, utils/guards).
            # Each branch materializes its OWN lam0 carry: the buffer is
            # donated to whichever ascent runs.
            from citizensassemblies_tpu.utils.guards import no_implicit_transfers

            eps_dev = jnp.asarray(eps, jnp.float32)
            step_dev = jnp.asarray(1.0 / L, jnp.float32)
            if ell is not None:
                lam0_ell = jnp.zeros((2 * tj.shape[0],), dtype=jnp.float32)
                with dispatch_span(
                    "qp.l2_dual_ascent_ell", cfg=cfg, log=log, iters=int(iters)
                ) as _ds:
                    with no_implicit_transfers(cfg):
                        p, _lam = _min_norm_dual_ascent_ell(
                            idx_j, val_j, tj, eps_dev, step_dev, lam0_ell, iters
                        )
                    _ds.out = p
            else:
                lam0 = jnp.zeros((2 * tj.shape[0],), dtype=jnp.float32)
                with dispatch_span(
                    "qp.l2_dual_ascent", cfg=cfg, log=log, iters=int(iters)
                ) as _ds:
                    with no_implicit_transfers(cfg):
                        p, _lam = _min_norm_dual_ascent(
                            Pj, tj, eps_dev, step_dev, lam0, iters
                        )
                    _ds.out = p
            # host materialization inside the timer: through a TPU tunnel,
            # block_until_ready alone does not drain the pipeline (see bench.py)
            p = np.asarray(p, dtype=np.float64)
    p = np.clip(p, 0.0, 1.0)
    s = p.sum()
    if s <= 0:
        p = np.asarray(p_lp, dtype=np.float64)
    else:
        p = p / s
    # the f32 dual ascent converges to O(1e-3) residual; restore the exact ε
    # floor by blending with the (feasible) LP solution — the largest convex
    # weight on the spread iterate that keeps every agent above target − ε.
    # Support stays the union of both supports, so the spread survives.
    p_lp = np.clip(np.asarray(p_lp, dtype=np.float64), 0.0, 1.0)
    p_lp = p_lp / p_lp.sum()
    alloc_l2 = PT @ p
    alloc_lp = PT @ p_lp
    floor = np.asarray(target, dtype=np.float64) - eps
    deficit = floor - alloc_l2  # > 0 where the ascent iterate undershoots
    gain = alloc_lp - alloc_l2
    # a deficit below the f32 ulp of the allocation scale is representation
    # noise of the float32 iterate, not an undershoot: blending on it divides
    # two O(ulp) numbers, so β (and the returned p) would chatter with the
    # kernel's bit-level rounding choices (e.g. the certified bf16 operand
    # demotion) instead of staying a function of the solution itself
    slack = float(np.finfo(np.float32).eps) * max(
        1.0, float(np.abs(alloc_l2).max()) if alloc_l2.size else 1.0
    )
    mask = deficit > slack
    with np.errstate(divide="ignore", invalid="ignore"):
        ratios = np.where(mask & (gain > 0), deficit / gain, np.nan)
    finite = ratios[np.isfinite(ratios)]
    beta = float(finite.max()) if finite.size else (1.0 if mask.any() else 0.0)
    beta = min(max(beta, 0.0), 1.0)
    p = (1.0 - beta) * p + beta * p_lp
    return p, float(eps_star)
