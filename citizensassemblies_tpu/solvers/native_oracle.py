"""Native exact pricing oracle: ctypes bindings for ``native/bb_price.cpp``.

The host-side runtime component of the solver layer (the role Gurobi's C
libraries play for the reference, ``leximin.py:16-17``): an exact
branch-and-bound over agent *types* (agents with identical feature vectors are
interchangeable up to weights, so the n-variable pricing ILP collapses to a
#types-variable integer program — see the header comment of
``native/bb_price.cpp`` for the math).

The shared library is compiled on first use with the system ``g++`` and cached
next to the source; every call certifies optimality (status 0) or reports a
node-limit abort, in which case callers fall back to the scipy/HiGHS MILP.
Households and forced-inclusion constraints break type interchangeability, so
those calls always use the HiGHS path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "bb_price.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "libbb_price.so")

_lock = threading.Lock()
_lib = None
_lib_failed = False

_logger = logging.getLogger("citizensassemblies_tpu.native")
#: libraries whose toolchain failure has already been reported — the load
#: attempt itself happens once per process (the ``*_failed`` flags), but the
#: REASON used to be swallowed entirely; now it is logged exactly once per
#: library so a missing g++ or a broken source shows up in the run log
#: instead of silently degrading every oracle call to the HiGHS fallback
_toolchain_logged: set = set()


def _note_toolchain_failure(name: str, exc: Exception) -> None:
    """Log a native-toolchain compile/load failure ONCE per process."""
    if name in _toolchain_logged:
        return
    _toolchain_logged.add(name)
    detail = str(exc)
    if isinstance(exc, subprocess.CalledProcessError) and exc.stderr:
        detail = exc.stderr.decode("utf-8", "replace")
    _logger.warning(
        "native %s unavailable (%s: %.200s); scipy/HiGHS fallback will carry "
        "its calls for the rest of the process",
        name, type(exc).__name__, detail,
    )


def _compile_and_load(src: str, so: str) -> ctypes.CDLL:
    """g++-compile ``src`` into ``so`` when stale and load it (raises on any
    toolchain failure — callers convert that to a None / fallback)."""
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        os.makedirs(os.path.dirname(so), exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src, "-o", so],
            check=True,
            capture_output=True,
        )
    return ctypes.CDLL(so)


def _ptr(a: np.ndarray, t):
    return a.ctypes.data_as(ctypes.POINTER(t))


def _load() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the shared library; None if unavailable."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            lib = _compile_and_load(_SRC, _SO)
            lib.bb_price.restype = ctypes.c_int
            lib.bb_price.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),  # type_feature
                ctypes.POINTER(ctypes.c_int32),  # msize
                ctypes.POINTER(ctypes.c_double),  # prefix
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),  # lo
                ctypes.POINTER(ctypes.c_int32),  # hi
                ctypes.c_int, ctypes.c_double, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),  # out_counts
                ctypes.POINTER(ctypes.c_double),  # out_value
                ctypes.POINTER(ctypes.c_int64),  # out_nodes
            ]
            _lib = lib
        except Exception as exc:
            _note_toolchain_failure("bb_price", exc)
            _lib_failed = True
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


class TypeReduction:
    """Group agents by identical feature rows and precompute the per-type
    structure the native search consumes. Reused across pricing calls — only
    the weights change per call."""

    def __init__(self, dense: DenseInstance):
        A = dense.A_np.astype(np.int8)
        self.n, self.F = A.shape
        self.k = int(dense.k)
        self.qmin = dense.qmin_np.astype(np.int32)
        self.qmax = dense.qmax_np.astype(np.int32)
        # category structure: columns of A are grouped by category via the
        # one-hot property (each agent has exactly one feature per category);
        # recover per-agent feature index per category from the dense rows
        _, type_id, counts = np.unique(
            A, axis=0, return_inverse=True, return_counts=True
        )
        self.type_id = type_id  # [n] agent -> type
        self.T = len(counts)
        self.msize = counts.astype(np.int32)
        self.members = [np.nonzero(type_id == t)[0] for t in range(self.T)]
        # [T, n_cats] global feature index per category, from any member's row
        reps = np.array([m[0] for m in self.members])
        rows = A[reps]  # [T, F] one-hot per category block
        feats = [np.nonzero(r)[0].astype(np.int32) for r in rows]
        n_cats = len(feats[0]) if feats else 0
        assert all(len(f) == n_cats for f in feats), "rows must be one-hot per category"
        self.n_cats = n_cats
        self.type_feature = np.stack(feats, axis=0) if n_cats else np.zeros((self.T, 0), np.int32)
        self.maxm = int(self.msize.max()) if self.T else 0

    def prepare(self, weights: np.ndarray):
        """Sort each type's members by weight (desc) and build prefix sums."""
        w = np.asarray(weights, dtype=np.float64)
        order = []  # per type: member ids sorted by weight desc
        prefix = np.zeros((self.T, self.maxm + 1), dtype=np.float64)
        for t, mem in enumerate(self.members):
            o = mem[np.argsort(-w[mem], kind="stable")]
            order.append(o)
            prefix[t, 1 : len(o) + 1] = np.cumsum(w[o])
        return order, prefix


def price_exact(
    reduction: TypeReduction,
    weights: np.ndarray,
    incumbent: float = -1e300,
    max_nodes: int = 20_000_000,
) -> Optional[Tuple[Optional[Tuple[int, ...]], float]]:
    """Certified-exact ``max Σ w_i x_i`` over feasible committees.

    Returns ``(committee, value)``; ``committee is None`` means the incumbent
    value passed in is certified optimal (no feasible committee beats it).
    Returns ``None`` (caller should fall back to HiGHS) when the native
    library is unavailable, the node limit was hit, or no feasible committee
    exists under an unseeded search.
    """
    lib = _load()
    if lib is None:
        return None
    order, prefix = reduction.prepare(weights)
    tf = np.ascontiguousarray(reduction.type_feature, dtype=np.int32)
    msize = np.ascontiguousarray(reduction.msize, dtype=np.int32)
    prefix_c = np.ascontiguousarray(prefix, dtype=np.float64)
    lo = np.ascontiguousarray(reduction.qmin, dtype=np.int32)
    hi = np.ascontiguousarray(reduction.qmax, dtype=np.int32)
    out_counts = np.zeros(reduction.T, dtype=np.int32)
    out_value = ctypes.c_double(0.0)
    out_nodes = ctypes.c_int64(0)

    status = lib.bb_price(
        reduction.T, reduction.n_cats, reduction.F,
        _ptr(tf, ctypes.c_int32), _ptr(msize, ctypes.c_int32),
        _ptr(prefix_c, ctypes.c_double),
        reduction.maxm, _ptr(lo, ctypes.c_int32), _ptr(hi, ctypes.c_int32),
        reduction.k, float(incumbent), int(max_nodes),
        _ptr(out_counts, ctypes.c_int32), ctypes.byref(out_value),
        ctypes.byref(out_nodes),
    )
    if status == 0:
        if out_counts[0] == -1 and np.all(out_counts == -1):
            return None, float(out_value.value)  # incumbent certified optimal
        members = []
        for t in range(reduction.T):
            c = int(out_counts[t])
            if c:
                members.extend(order[t][:c].tolist())
        committee = tuple(sorted(int(i) for i in members))
        return committee, float(out_value.value)
    return None  # status 1 (infeasible unseeded), 2 (node limit), 3 (bad args)


# --- native slice repair (the aimed slicer's host hot loop) -----------------

_REPAIR_SRC = os.path.join(_REPO_ROOT, "native", "slice_repair.cpp")
_REPAIR_SO = os.path.join(_REPO_ROOT, "native", "build", "libslice_repair.so")
_repair_lib = None
_repair_failed = False


def _load_repair() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the slice-repair library; None if unavailable."""
    global _repair_lib, _repair_failed
    with _lock:
        if _repair_lib is not None or _repair_failed:
            return _repair_lib
        try:
            lib = _compile_and_load(_REPAIR_SRC, _REPAIR_SO)
            lib.slice_repair.restype = ctypes.c_int
            lib.slice_repair.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),  # type_feature
                ctypes.POINTER(ctypes.c_int32),  # msize
                ctypes.POINTER(ctypes.c_int32),  # lo
                ctypes.POINTER(ctypes.c_int32),  # hi
                ctypes.POINTER(ctypes.c_int32),  # c
                ctypes.POINTER(ctypes.c_int32),  # counts
                ctypes.POINTER(ctypes.c_double),  # need
                ctypes.c_uint32, ctypes.c_int,
            ]
            lib.slice_stream.restype = ctypes.c_int
            lib.slice_stream.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),  # type_feature
                ctypes.POINTER(ctypes.c_int32),  # msize
                ctypes.POINTER(ctypes.c_int32),  # lo
                ctypes.POINTER(ctypes.c_int32),  # hi
                ctypes.c_int,  # k
                ctypes.POINTER(ctypes.c_double),  # x
                ctypes.c_int, ctypes.c_int,  # R, max_passes
                ctypes.c_uint32,  # j0 (tie-stream offset)
                ctypes.POINTER(ctypes.c_int32),  # out [R*T]
            ]
            _repair_lib = lib
        except Exception as exc:
            _note_toolchain_failure("slice_repair", exc)
            _repair_failed = True
            _repair_lib = None
        return _repair_lib


def repair_slice_native(
    reduction: "TypeReduction",
    c: np.ndarray,
    counts: np.ndarray,
    need: np.ndarray,
    seed: int,
    max_passes: int,
) -> Optional[bool]:
    """Native greedy quota repair of one apportionment slice (mutates ``c``
    and ``counts`` in place — same scoring as the python ``swap_repair``
    fallback in ``cg_typespace._slice_relaxation``, ~100× faster at
    T ≈ 1000). Returns None when the library is unavailable."""
    lib = _load_repair()
    if lib is None:
        return None
    # c/counts are mutated in place through raw pointers: anything but
    # contiguous int32 (e.g. the int64 arrays natural elsewhere in
    # _slice_relaxation) would be reinterpreted, silently corrupting the
    # slice — reject rather than guess at a copy-back contract
    for name, arr in (("c", c), ("counts", counts)):
        if arr.dtype != np.int32 or not arr.flags.c_contiguous:
            raise ValueError(
                f"repair_slice_native: {name} must be contiguous int32 "
                f"(got {arr.dtype}, contiguous={arr.flags.c_contiguous})"
            )
    # TypeReduction stores these contiguous int32 already, so the casts are
    # zero-copy views — no per-slice conversion cost
    tf = np.ascontiguousarray(reduction.type_feature, dtype=np.int32)
    msize = np.ascontiguousarray(reduction.msize, dtype=np.int32)
    lo = np.ascontiguousarray(reduction.qmin, dtype=np.int32)
    hi = np.ascontiguousarray(reduction.qmax, dtype=np.int32)
    need = np.ascontiguousarray(need, dtype=np.float64)
    ok = lib.slice_repair(
        reduction.T, reduction.n_cats, reduction.F,
        _ptr(tf, ctypes.c_int32), _ptr(msize, ctypes.c_int32),
        _ptr(lo, ctypes.c_int32), _ptr(hi, ctypes.c_int32),
        _ptr(c, ctypes.c_int32), _ptr(counts, ctypes.c_int32),
        _ptr(need, ctypes.c_double),
        ctypes.c_uint32(seed & 0xFFFFFFFF), int(max_passes),
    )
    return bool(ok)


def slice_stream_native(
    reduction: "TypeReduction",
    x: np.ndarray,
    R: int,
    max_passes: int,
    j0: int = 0,
    chunks: int = 1,
) -> Optional[np.ndarray]:
    """The full aimed-slicer loop in one native call (``slice_stream`` in
    ``native/slice_repair.cpp``): apportionment, gap top-up, quota repair and
    cumulative feedback for all ``R`` slices. The per-slice python path costs
    ~0.3 ms/slice in ctypes marshalling and numpy bookkeeping — at R ≈ 1000
    that overhead alone dominated mid-tier (n ≈ 300-400) leximin solves.

    ``j0`` shifts the apportionment phase and the tie streams (see
    ``slice_stream`` in the C++ source), so repeated calls with different
    offsets emit *different* slices of the same hull. ``chunks > 1`` splits
    the stream into that many independent full streams of ``R // chunks``
    slices (offsets spaced by ``1 << 16``) run on a thread pool — ctypes
    releases the GIL, so the C++ streams run truly in parallel; each chunk's
    mixture still tracks ``x``, to ~chunks/R instead of ~1/R, which hull
    seeding cannot tell apart. Deterministic for fixed (R, j0, chunks).

    Returns the kept slices as int32 [kept, T], or None when the native
    toolchain is unavailable (callers run the per-slice path instead)."""
    lib = _load_repair()
    if lib is None:
        return None
    T = int(reduction.T)
    tf = np.ascontiguousarray(reduction.type_feature, dtype=np.int32)
    msize = np.ascontiguousarray(reduction.msize, dtype=np.int32)
    lo = np.ascontiguousarray(reduction.qmin, dtype=np.int32)
    hi = np.ascontiguousarray(reduction.qmax, dtype=np.int32)
    x64 = np.ascontiguousarray(x, dtype=np.float64)

    def run(r: int, off: int, out: np.ndarray) -> int:
        return int(
            lib.slice_stream(
                T, reduction.n_cats, reduction.F,
                _ptr(tf, ctypes.c_int32), _ptr(msize, ctypes.c_int32),
                _ptr(lo, ctypes.c_int32), _ptr(hi, ctypes.c_int32),
                int(reduction.k), _ptr(x64, ctypes.c_double),
                int(r), int(max_passes), ctypes.c_uint32(off & 0xFFFFFFFF),
                _ptr(out, ctypes.c_int32),
            )
        )

    chunks = max(1, min(int(chunks), int(R)))
    if chunks == 1:
        out = np.empty((int(R), T), dtype=np.int32)
        kept = run(int(R), int(j0), out)
        return out[:kept].copy()

    from concurrent.futures import ThreadPoolExecutor

    sizes = [R // chunks + (1 if i < R % chunks else 0) for i in range(chunks)]
    bufs = [np.empty((r, T), dtype=np.int32) for r in sizes]
    with ThreadPoolExecutor(max_workers=chunks) as pool:
        counts = list(
            pool.map(
                lambda i: run(sizes[i], int(j0) + i * (1 << 16), bufs[i]),
                range(chunks),
            )
        )
    return np.concatenate([bufs[i][: counts[i]] for i in range(chunks)], axis=0)

# --- native water-filling slicer (greedy_decompose's host hot loop) ---------

_SLICER_SRC = os.path.join(_REPO_ROOT, "native", "slicer.cpp")
_SLICER_SO = os.path.join(_REPO_ROOT, "native", "build", "libslicer.so")
_slicer_lib = None
_slicer_failed = False


def _load_slicer() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the slicer library; None if unavailable."""
    global _slicer_lib, _slicer_failed
    with _lock:
        if _slicer_lib is not None or _slicer_failed:
            return _slicer_lib
        try:
            lib = _compile_and_load(_SLICER_SRC, _SLICER_SO)
            lib.slicer_decompose.restype = ctypes.c_int
            lib.slicer_decompose.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),   # comps
                ctypes.POINTER(ctypes.c_double),  # probs
                ctypes.POINTER(ctypes.c_int32),   # members_flat
                ctypes.POINTER(ctypes.c_int32),   # member_off
                ctypes.POINTER(ctypes.c_int32),   # houses_flat (or NULL)
                ctypes.c_int,                     # n_houses
                ctypes.POINTER(ctypes.c_double),  # needs_flat (in/out)
                ctypes.c_double,                  # delta_cap (<=0: uncapped)
                ctypes.c_int,                     # max_panels
                ctypes.POINTER(ctypes.c_uint8),   # out_panels
                ctypes.POINTER(ctypes.c_double),  # out_probs
                ctypes.POINTER(ctypes.c_int),     # out_count
            ]
            _slicer_lib = lib
        except Exception as exc:
            _note_toolchain_failure("slicer", exc)
            _slicer_failed = True
            _slicer_lib = None
        return _slicer_lib


def greedy_decompose_native(
    reduction: "TypeReduction",
    comps_sorted: np.ndarray,
    probs_sorted: np.ndarray,
    per_type_need: np.ndarray,
    max_panels: int,
    households: Optional[np.ndarray] = None,
    delta_cap: float = 0.0,
):
    """Native water-filling decomposition (``native/slicer.cpp``) with the
    exact semantics of the Python loop in ``compositions.greedy_decompose``
    (same sort keys, cursor rotation, forced-overshoot rule). ``comps_sorted``
    /``probs_sorted`` must already be support-filtered and ordered largest
    mass first; ``per_type_need`` is the initial need per type (equal across
    a type's members). Returns ``(panels bool [R, n], probs)`` or None when
    the library is unavailable (callers then run the Python loop)."""
    lib = _load_slicer()
    if lib is None:
        return None
    T, n = reduction.T, reduction.n
    S = len(probs_sorted)
    comps = np.ascontiguousarray(comps_sorted, dtype=np.int32)
    probs = np.ascontiguousarray(probs_sorted, dtype=np.float64)
    sizes = np.array([len(m) for m in reduction.members], dtype=np.int64)
    member_off = np.zeros(T + 1, dtype=np.int32)
    member_off[1:] = np.cumsum(sizes).astype(np.int32)
    members_flat = (
        np.concatenate(reduction.members).astype(np.int32)
        if T
        else np.zeros(0, np.int32)
    )
    needs_flat = np.repeat(
        np.asarray(per_type_need, dtype=np.float64), sizes
    )
    needs_flat = np.ascontiguousarray(needs_flat)
    if households is not None:
        houses_flat = np.ascontiguousarray(
            np.asarray(households)[members_flat], dtype=np.int32
        )
        houses_ptr = _ptr(houses_flat, ctypes.c_int32)
        n_houses = int(np.asarray(households).max()) + 1
    else:
        houses_ptr = None
        n_houses = 0
    out_panels = np.zeros((max_panels, n), dtype=np.uint8)
    out_probs = np.zeros(max_panels, dtype=np.float64)
    out_count = ctypes.c_int(0)
    rc = lib.slicer_decompose(
        T, n, S,
        _ptr(comps, ctypes.c_int32), _ptr(probs, ctypes.c_double),
        _ptr(members_flat, ctypes.c_int32), _ptr(member_off, ctypes.c_int32),
        houses_ptr, n_houses,
        _ptr(needs_flat, ctypes.c_double),
        float(delta_cap), int(max_panels),
        _ptr(out_panels, ctypes.c_uint8), _ptr(out_probs, ctypes.c_double),
        ctypes.byref(out_count),
    )
    if rc != 0:
        return None
    R = int(out_count.value)
    return out_panels[:R].astype(bool), out_probs[:R].copy()
