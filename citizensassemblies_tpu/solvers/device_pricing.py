"""Device-resident anchor pricing for the face-decomposition loop.

The face loop's anchor oracle prices a bounded integer program over type
cells: ``max Σ_t w_t c_t`` over compositions ``c ∈ Z^T`` with ``0 ≤ c_t ≤
m_t``, ``Σ c_t = k`` and per-feature quotas ``qmin ≤ tfᵀ c ≤ qmax`` (the
type-space collapse of the committee ILP, ``cg_typespace.CompositionOracle``).
PR 6's ``decomp_host_syncs`` gauge showed that pricing this on the *host*
(scipy/HiGHS MILP per anchor) keeps the CG round ping-ponging between device
master solves and host solver calls; ROADMAP item 2 asks for the same
screen-reduces-host-work move the PR 3 probe prescreen proved sound — a
device kernel that finds the anchors, with the exact host MILP demoted to a
certifying fallback it only reaches on a miss.

Two jitted lanes, one dispatch per round for the WHOLE anchor batch
(dual-direction optimum, alternate-round noisy variants, forced-inclusion
anchors):

* **β-ladder greedy lanes** (:func:`_get_greedy_core`) — every anchor task
  fans out into ``_LANES`` deterministic constructive builds, lane ``l``
  scoring types by ``β_l · ŵ + urgency``: the same log-spaced
  inverse-temperature ladder the stochastic committee pricer uses
  (``pricing.beta_ladder``), so low-β lanes are urgency-dominated
  (feasibility-first, diverse) and high-β lanes are weight-greedy (what
  finds improving columns when the duals concentrate). One ``lax.scan`` over
  the k slots builds all lanes at once (vmapped): per step a type is
  eligible iff its count is below the pool size, every feature it carries
  stays ≤ its upper quota, and — in any category whose remaining lower-quota
  deficit equals the remaining slots — it covers a deficit feature (the
  tightness mask that makes the greedy land inside the quota box whenever it
  can).
* **exact small-T DP lane** (:func:`_get_dp_core`) — for single-category
  reductions every type maps 1:1 to a feature, so the pricing program
  collapses to ``max Σ w_t c_t`` over per-type bounds with one Σ = k row: an
  O(T·k²) dynamic program over (type, slots-used) solved by a scan with a
  backtracking pass, exact over the uploaded (f32) weights —
  certification-grade anchors in one dispatch, no search.

Both lanes return candidate compositions + device feasibility flags; the
harvest re-validates every candidate in exact host integer arithmetic before
it may enter the master (an anchor is a *portfolio column* — the panel
decomposition later realizes it as actual panels, so feasibility is a hard
contract, not a heuristic nicety). A task none of whose lanes survive falls
back to the host MILP: the device screen only ever *reduces* host oracle
calls, never replaces the exact path, and the stage-CG certification MILPs
(``cg_typespace``) are untouched — the 1e-3 L∞ exactness audit contract is
unchanged. Routing is the ``Config.decomp_device_pricing`` tri-state
(``None`` = auto: on on accelerator backends, off on CPU; off ⇒ the PR 6
host anchor schedule runs bit-identically).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.solvers.pricing import beta_ladder
from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.guards import no_implicit_transfers
from citizensassemblies_tpu.utils.logging import RunLog

_NEG = jnp.float32(-1e30)

#: β-ladder lanes per anchor task. Six spans urgency-dominated (β = 0.1)
#: through weight-greedy (β ≈ 300) with one compiled program; more lanes cost
#: nothing on an accelerator but pad the (rare) CPU-forced runs.
_LANES = 6

#: urgency weight added per deficit feature a type covers, against per-lane
#: weights normalized to max |ŵ| = 1 then scaled by β — so the boost
#: dominates the low-β lanes and is noise to the high-β ones, which is the
#: explore/exploit split the ladder exists to provide
_URGENCY = 2.0


def device_pricing_enabled(cfg: Optional[Config]) -> bool:
    """Resolve the ``Config.decomp_device_pricing`` tri-state.

    ``True``/``False`` force; ``None`` (auto) engages the device pricer on
    accelerator backends only — mirroring the master/expand routing, a
    CPU-only run keeps the host oracle where per-dispatch overhead outweighs
    the batching. The auto-off CPU default is also what keeps every gate-off
    code path bit-identical to the pre-device-pricing engine.
    """
    knob = getattr(cfg, "decomp_device_pricing", None)
    if knob is not None:
        return bool(knob)
    return jax.default_backend() not in ("cpu",)


_GREEDY_CORE = None


def _get_greedy_core():
    """Build (once) the jitted β-ladder greedy constructive core.

    One ``lax.scan`` over the ``k`` slots, vmapped over the lane batch. Per
    step each lane runs the LEGACY sampler's urgent-cell-first discipline in
    type space: the most urgent feature cell (highest deficit/remaining-
    supply ratio, supply counted over currently eligible types) constrains
    the pick whenever any lower-quota deficit remains, and the pick within
    the admissible set is the argmax of ``score = β·ŵ + urgency`` — so high-β
    lanes are weight-greedy wherever the quotas leave freedom and every lane
    is feasibility-first where they do not. Eligibility also enforces pool
    bounds, upper quotas, and deficit coverage in any category whose total
    deficit equals the remaining slots. Integer state only (counts, feature
    counts), so the device feasibility flag is exact, not a float tolerance.
    Compiled once per (B, T, F, ncat, k) shape.
    """
    global _GREEDY_CORE
    if _GREEDY_CORE is None:

        @partial(jax.jit, static_argnames=("k",))
        def core(
            feat_of, cat_of, tf, msize, qmin, qmax, weights, forced, k: int
        ):
            T, ncat = feat_of.shape
            F = qmin.shape[0]

            def lane(w, f):
                in_pool = msize > 0
                seed = (jnp.arange(T, dtype=jnp.int32) == f) & in_pool
                c0 = seed.astype(jnp.int32)
                s0 = jnp.zeros(F, jnp.int32).at[feat_of[jnp.maximum(f, 0)]].add(
                    jnp.where(seed.any(), 1, 0)
                )
                used0 = jnp.where(seed.any(), jnp.int32(1), jnp.int32(0))
                # a forced type outside the pool can never be priced here —
                # fail the lane so the task routes to the host MILP
                failed0 = (f >= 0) & ~seed.any()

                def step(state, _):
                    c, s, used, failed = state
                    rem = jnp.int32(k) - used
                    deficit = jnp.maximum(qmin - s, 0)
                    cat_def = jax.ops.segment_sum(
                        deficit, cat_of, num_segments=ncat
                    )
                    # more lower-quota deficit in one category than slots
                    # remain: the lane cannot recover
                    failed = failed | ((rem > 0) & (jnp.max(cat_def) > rem))
                    tight = cat_def >= rem  # == when it binds (see above)
                    d_t = deficit[feat_of]  # [T, ncat]
                    up_ok = jnp.all(s[feat_of] + 1 <= qmax[feat_of], axis=1)
                    tight_ok = jnp.all(~tight[None, :] | (d_t > 0), axis=1)
                    eligible = (c < msize) & up_ok & tight_ok
                    # urgent cell: deficit / remaining supply over ELIGIBLE
                    # types (the LEGACY ratio, legacy.py:124-157, with the
                    # starved check riding the supply count)
                    avail = ((msize - c) * eligible).astype(jnp.float32)
                    supply = avail @ tf  # [F] units still reachable per cell
                    starved = (deficit > 0) & (supply < deficit)
                    failed = failed | ((rem > 0) & starved.any())
                    urgent = deficit > 0
                    ratio = jnp.where(
                        urgent, deficit / jnp.maximum(supply, 1.0), _NEG
                    )
                    cell = jnp.argmax(ratio)
                    in_cell = jnp.any(feat_of == cell, axis=1)
                    pick_ok = eligible & jnp.where(urgent.any(), in_cell, True)
                    need = (d_t > 0).sum(axis=1).astype(jnp.float32)
                    score = w + _URGENCY * need
                    pick = jnp.argmax(jnp.where(pick_ok, score, _NEG))
                    active = (rem > 0) & ~failed
                    failed = failed | (active & ~pick_ok.any())
                    inc = jnp.where(active & pick_ok.any(), 1, 0)
                    c = c.at[pick].add(inc)
                    s = s.at[feat_of[pick]].add(inc)
                    return (c, s, used + inc, failed), None

                (c, s, used, failed), _ = jax.lax.scan(
                    step, (c0, s0, used0, failed0), None, length=k
                )
                ok = (
                    ~failed
                    & (used == k)
                    & jnp.all(s >= qmin)
                    & jnp.all(s <= qmax)
                )
                return c, ok

            return jax.vmap(lane)(weights, forced)

        from citizensassemblies_tpu.aot.store import aot_seeded

        _GREEDY_CORE = aot_seeded(
            "device_pricing.greedy", core, static_argnames=("k",)
        )
    return _GREEDY_CORE


_DP_CORE = None


def _get_dp_core():
    """Build (once) the jitted exact DP core for single-category reductions.

    With ``ncat == 1`` distinct types carry distinct features, so the quota
    rows collapse to per-type bounds ``c_t ∈ [qmin_{f_t}, min(m_t,
    qmax_{f_t})]`` and the program is a bounded exact-knapsack: DP over
    (type, slots used) with value table ``val[s]`` updated per type by
    ``val'[s] = max_c val[s−c] + w_t·c`` and the argmax choices recorded for
    a reverse-scan backtrack. Exact over the uploaded f32 weights — the lane
    the harvest labels certification-grade. Compiled once per (B, T, k).
    """
    global _DP_CORE
    if _DP_CORE is None:

        @partial(jax.jit, static_argnames=("k",))
        def core(feat1, msize, qmin, qmax, weights, forced, k: int):
            T = feat1.shape[0]
            lo_t = jnp.maximum(qmin[feat1], 0)
            hi_t = jnp.minimum(msize, qmax[feat1])
            cand = jnp.arange(k + 1, dtype=jnp.int32)

            def lane(w, f):
                lo = jnp.where(
                    jnp.arange(T, dtype=jnp.int32) == f,
                    jnp.maximum(lo_t, 1), lo_t,
                )

                def body(val, t_in):
                    w_t, lo_tt, hi_tt = t_in
                    s_idx = cand[:, None]
                    c_idx = cand[None, :]
                    feas = (c_idx >= lo_tt) & (c_idx <= hi_tt) & (c_idx <= s_idx)
                    prev = val[jnp.maximum(s_idx - c_idx, 0)]
                    tot = jnp.where(feas, prev + w_t * c_idx, _NEG)
                    return jnp.max(tot, axis=1), jnp.argmax(tot, axis=1)

                val0 = jnp.where(cand == 0, jnp.float32(0.0), _NEG)
                valK, choices = jax.lax.scan(body, val0, (w, lo, hi_t))

                def back(s, t_choice):
                    # argmax widens to int64 under an enable_x64 trace — pin
                    # the carry dtype so the scan types stay fixed
                    c_t = t_choice[s].astype(jnp.int32)
                    return s - c_t, c_t

                _s, comp = jax.lax.scan(
                    back, jnp.int32(k), choices, reverse=True
                )
                return comp.astype(jnp.int32), valK[k] > _NEG * 0.5

            return jax.vmap(lane)(weights, forced)

        from citizensassemblies_tpu.aot.store import aot_seeded

        _DP_CORE = aot_seeded(
            "device_pricing.dp", core, static_argnames=("k",)
        )
    return _DP_CORE


@register_ir_core("device_pricing.greedy_lanes", span="device_pricing.greedy_lanes")
def _ir_greedy_lanes() -> IRCase:
    """The β-ladder greedy pricer at one small (B=8 lanes, T=32 types, F=12
    features over 3 categories, k=8 slots) shape — integer scan state and the
    per-step eligibility masks are the structure under verification."""
    S = jax.ShapeDtypeStruct
    i32, f32 = jnp.int32, jnp.float32
    B, T, F, ncat = 8, 32, 12, 3
    return IRCase(
        fn=_get_greedy_core(),
        args=(
            S((T, ncat), i32), S((F,), i32), S((T, F), f32), S((T,), i32),
            S((F,), i32), S((F,), i32), S((B, T), f32), S((B,), i32),
        ),
        static=dict(k=8),
    )


@register_ir_core("device_pricing.exact_dp", span="device_pricing.exact_dp")
def _ir_exact_dp() -> IRCase:
    """The exact single-category DP at (B=4, T=16, k=8): the value-table
    scan plus the reverse backtrack scan."""
    S = jax.ShapeDtypeStruct
    i32, f32 = jnp.int32, jnp.float32
    B, T, F = 4, 16, 16
    return IRCase(
        fn=_get_dp_core(),
        args=(
            S((T,), i32), S((T,), i32), S((F,), i32), S((F,), i32),
            S((B, T), f32), S((B,), i32),
        ),
        static=dict(k=8),
    )


@dataclasses.dataclass
class PricingHandle:
    """An in-flight device pricing dispatch: device arrays plus the task
    list needed to decode them at harvest. ``lanes`` is the per-task fan-out
    (1 on the exact DP route)."""

    comps: jnp.ndarray  # [B, T] int32 device array
    ok: jnp.ndarray  # [B] bool device array
    tasks: List[Tuple[np.ndarray, Optional[int]]]
    lanes: int
    exact: bool


class DevicePricer:
    """Host wrapper: device-resident static operands + dispatch/harvest.

    The quota structure (type→feature incidence, pool sizes, quota bounds)
    uploads ONCE at construction and stays device-resident across every CG
    round; a dispatch ships only the per-round ``[B, T]`` lane-weight matrix
    (plus the forced-type vector) and returns immediately with device
    arrays, so the pricing executes while the caller runs the next master —
    the same one-round-lagged overlap the host thread pool provided, with
    the accelerator as the worker. ``harvest`` is where results cross back:
    every candidate is re-validated in exact host integer arithmetic, the
    best feasible lane per task becomes that task's anchor, and tasks with
    no surviving lane are reported as misses for the caller's host-MILP
    fallback.
    """

    def __init__(
        self,
        reduction: TypeReduction,
        cfg: Optional[Config] = None,
        log: Optional[RunLog] = None,
        lanes: int = _LANES,
    ):
        self.red = reduction
        self.cfg = cfg
        self.log = log
        self.lanes = int(lanes)
        self.exact = reduction.n_cats == 1
        feat_of = np.asarray(reduction.type_feature, dtype=np.int32)
        # feature → category map (features are one-hot per category, so each
        # feature index appears in exactly one column of type_feature)
        cat_of = np.zeros(reduction.F, dtype=np.int32)
        for ci in range(reduction.n_cats):
            cat_of[np.unique(feat_of[:, ci])] = ci
        self._feat_of = jnp.asarray(feat_of)
        self._cat_of = jnp.asarray(cat_of)
        tf32 = np.zeros((reduction.T, reduction.F), dtype=np.float32)
        if reduction.n_cats:
            tf32[
                np.repeat(np.arange(reduction.T), reduction.n_cats),
                feat_of.ravel(),
            ] = 1.0
        self._tf_dev = jnp.asarray(tf32)
        self._msize = jnp.asarray(reduction.msize.astype(np.int32))
        self._qmin = jnp.asarray(reduction.qmin.astype(np.int32))
        self._qmax = jnp.asarray(reduction.qmax.astype(np.int32))
        # host-side exact validation operands (int64 — no float tolerance)
        self._tf = np.zeros((reduction.T, reduction.F), dtype=np.int64)
        if reduction.n_cats:
            self._tf[
                np.repeat(np.arange(reduction.T), reduction.n_cats),
                feat_of.ravel(),
            ] = 1

    def dispatch(
        self, tasks: Sequence[Tuple[np.ndarray, Optional[int]]]
    ) -> Optional[PricingHandle]:
        """Price the whole anchor batch in one device dispatch (async).

        ``tasks`` are ``(weights float64[T], forced_type or None)`` exactly
        as the host oracle consumes them. Weights are normalized per task
        (argmax-invariant; values are recomputed in float64 at harvest) and
        fanned out over the β ladder on the greedy route; the exact DP route
        prices each task once.
        """
        if not tasks:
            return None
        W = np.stack([np.asarray(w, dtype=np.float64) for w, _f in tasks])
        W = W / (np.abs(W).max(axis=1, keepdims=True) + 1e-12)
        forced_np = np.array(
            [(-1 if f is None else int(f)) for _w, f in tasks], dtype=np.int32
        )
        if self.exact:
            lanes = 1
            lane_w = W.astype(np.float32)
            lane_f = forced_np
            core = _get_dp_core()
            operands = (
                jnp.asarray(self._feat_of[:, 0]), self._msize,
                self._qmin, self._qmax,
                jnp.asarray(lane_w), jnp.asarray(lane_f),
            )
        else:
            lanes = self.lanes
            betas = beta_ladder(lanes)  # the pricing.py steering ladder
            lane_w = (betas[None, :, None] * W[:, None, :]).reshape(
                len(tasks) * lanes, -1
            ).astype(np.float32)
            lane_f = np.repeat(forced_np, lanes)
            core = _get_greedy_core()
            operands = (
                self._feat_of, self._cat_of, self._tf_dev, self._msize,
                self._qmin, self._qmax,
                jnp.asarray(lane_w), jnp.asarray(lane_f),
            )
        with dispatch_span(
            "device_pricing.exact_dp" if self.exact
            else "device_pricing.greedy_lanes",
            cfg=self.cfg, log=self.log, tasks=len(tasks), lanes=int(lanes),
        ) as _ds:
            with no_implicit_transfers(self.cfg):
                comps, ok = core(*operands, k=int(self.red.k))
            _ds.out = (comps, ok)
        return PricingHandle(
            comps=comps, ok=ok, tasks=list(tasks), lanes=lanes, exact=self.exact
        )

    def _validate(self, comps: np.ndarray, ok: np.ndarray) -> np.ndarray:
        """Exact host integer re-validation of every candidate lane: the
        device flag is integer math and should agree, but an anchor becomes
        a portfolio column the panel decomposition later realizes as actual
        panels — feasibility is a hard contract, so it is re-proven in int64
        on host before a column may enter the master."""
        red = self.red
        counts = comps.astype(np.int64) @ self._tf
        feas = np.asarray(ok, dtype=bool).copy()
        feas &= comps.sum(axis=1) == red.k
        feas &= (comps >= 0).all(axis=1)
        feas &= (comps <= red.msize[None, :]).all(axis=1)
        feas &= (counts >= red.qmin[None, :]).all(axis=1)
        feas &= (counts <= red.qmax[None, :]).all(axis=1)
        return feas

    def harvest(
        self, handle: PricingHandle
    ) -> Tuple[List[Tuple[int, np.ndarray]], List[int]]:
        """Read the dispatch back and decode per task.

        Returns ``(hits, missed)``: ``hits`` as ``(task_index, composition
        int16 [1, T])`` pairs — the best surviving lane per task by exact
        float64 value — and ``missed`` as the task indices with no surviving
        lane (the caller's host-MILP fallback set). In the steady-state
        round the device work completed while the master solved, so this
        readback does not block on in-flight compute.
        """
        comps = np.asarray(handle.comps)
        ok = np.asarray(handle.ok)
        feas = self._validate(comps, ok)
        if self.log is not None and int((np.asarray(ok) & ~feas).sum()):
            # device said feasible, exact host arithmetic disagreed — should
            # never happen (integer state both sides); surfaced, not hidden
            self.log.count(
                "decomp_oracle_device_invalid",
                int((np.asarray(ok) & ~feas).sum()),
            )
        hits: List[Tuple[int, np.ndarray]] = []
        missed: List[int] = []
        L = handle.lanes
        for i, (w, f) in enumerate(handle.tasks):
            sl = slice(i * L, (i + 1) * L)
            lane_feas = feas[sl]
            if f is not None:
                lane_feas = lane_feas & (comps[sl, int(f)] >= 1)
            if not lane_feas.any():
                missed.append(i)
                continue
            vals = comps[sl].astype(np.float64) @ np.asarray(w, np.float64)
            vals = np.where(lane_feas, vals, -np.inf)
            best = int(np.argmax(vals))
            hits.append((i, comps[sl][best][None, :].astype(np.int16)))
        return hits, missed
