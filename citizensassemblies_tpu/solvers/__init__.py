from citizensassemblies_tpu.solvers.highs_backend import (  # noqa: F401
    DualSolution,
    HighsCommitteeOracle,
    solve_dual_lp,
    solve_final_primal_lp,
)
from citizensassemblies_tpu.solvers.pricing import stochastic_price  # noqa: F401
