"""Stochastic committee pricing on device.

Column generation needs, per inner iteration, a feasible committee maximizing
``Σ_{i∈C} y_i`` for the current dual weights ``y`` (the reference prices with
one exact ILP solve per iteration, ``leximin.py:420-424``). On TPU we instead
draw a *batch* of thousands of quota-feasible committees in one jitted kernel,
each steered toward high-weight agents with a different inverse temperature
(softmax-greedy via Gumbel perturbations inside the urgency-greedy sampler),
and return the best distinct candidates. Any committee with
``Σ y > ŷ + EPS`` is a violated dual constraint worth adding — stochastic
pricing only has to *find* violating columns quickly; the exact oracle is
consulted once at the end to certify that none remain (the termination test of
``leximin.py:429-443`` keeps its exactness guarantee).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance
from citizensassemblies_tpu.models.legacy import sample_panels_batch
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.precision import iterate_dtype


def beta_ladder(batch: int, lo: float = -1.0, hi: float = 3.5) -> np.ndarray:
    """Log-spaced inverse-temperature ladder β ∈ [10^lo, 10^hi].

    The steering schedule shared by the stochastic committee pricer below
    and the device anchor pricer (``solvers/device_pricing.py``): low β
    explores (feasibility/diversity dominated), high β exploits (greedy on
    the dual weights, which is what finds violated columns when the duals
    concentrate on few agents/types).
    """
    return np.logspace(lo, hi, batch)


def _pricing_scores(weights: jnp.ndarray, batch: int) -> jnp.ndarray:
    """[B, n] member-pick scores: β_b · ŵ with the log-spaced β ladder."""
    w = weights / (jnp.max(jnp.abs(weights)) + 1e-12)
    betas = jnp.asarray(beta_ladder(batch), dtype=iterate_dtype(w.dtype))
    return betas[:, None] * w[None, :]


def stochastic_price(
    dense: DenseInstance,
    weights: np.ndarray,
    key,
    batch: Optional[int] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sample a batch of feasible committees biased toward high ``weights``.

    Returns ``(panels int32[B,k] sorted rows, values float64[B], ok bool[B])``
    where ``values[b] = Σ_{i∈panel_b} weights[i]`` (only meaningful where
    ``ok``).
    """
    cfg = cfg or default_config()
    B = batch or cfg.pricing_batch
    if batch is None and jax.default_backend() == "cpu":
        # a pricing batch exists to surface ~cg_columns_per_round violating
        # panels per LP solve; on an accelerator 4096 chains cost the same
        # as 1024, but on the CPU backend the sweep is serial and the
        # oversized batch was the agent-space CG's dominant cost
        B = min(B, 1024)
    w = jnp.asarray(weights, dtype=jnp.float32)
    scores = _pricing_scores(w, B)
    panels, ok = sample_panels_batch(dense, key, B, scores=scores, households=households)
    panels = np.sort(np.asarray(panels), axis=1)
    values = np.asarray(weights, dtype=np.float64)[panels].sum(axis=1)
    return panels, values, np.asarray(ok)


def best_violating_panels(
    panels: np.ndarray,
    values: np.ndarray,
    ok: np.ndarray,
    threshold: float,
    existing: set,
    max_new: int,
) -> list:
    """Pick up to ``max_new`` distinct feasible panels with value above
    ``threshold`` (= ŷ + EPS), strongest first, skipping panels already in the
    portfolio. Selected panels are inserted into ``existing`` (the caller's
    portfolio dedup set)."""
    order = np.argsort(-values)
    out = []
    for idx in order:
        if len(out) >= max_new:
            break
        if not ok[idx] or values[idx] <= threshold:
            continue
        tup = tuple(panels[idx].tolist())
        if tup in existing:
            continue
        existing.add(tup)
        out.append((tup, values[idx]))
    return out
