"""Batched, shape-bucketed LP/QP engine: fuse fleets of small convex solves.

After the face loop was pipelined (PR 1) the remaining wall-clock is
dominated by *many small independent* LP/QP solves dispatched one at a time:
polish attempts in the decomposition end-game, per-candidate probe LPs of
the leximin certification, per-instance final LPs of a parameter sweep.
Each costs a full device round-trip (through a TPU tunnel ~0.16 s/dispatch)
regardless of its size, so a fleet of N small solves pays N dispatch floors
for work the MXU could do in one.

This engine takes N independent instances of ``min cᵀx s.t. Gx ≤ h, Ax = b,
x ≥ 0``, pads them into power-of-two shape buckets ``(rows_G, rows_A, cols,
batch)`` and solves each bucket with a single ``vmap``-ped, jitted
restarted-PDHG call — the *same* iteration body the serial solver runs
(``lp_pdhg._pdhg_body``), so the per-instance math is one definition with
two dispatch shapes. The bucketing/serving mechanics mirror a serving
stack's continuous batching:

* **shape buckets** — dims round up to a power of two below
  ``Config.lp_batch_bucket_max`` and to a multiple of it above, so each
  distinct bucket compiles once and the executable cache stays bounded
  (``CompilationGuard`` counts per-bucket compiles into the run's
  ``lp_batch_*`` phase counters);
* **padding is inert by construction** — padded rows/columns are all-zero
  with zero objective and zero offsets (0 ≤ 0 constraints, variables that
  keep zero gradient), and padding *lanes* are all-zero instances whose KKT
  residual is 0 at the start, so they converge at the first check;
* **per-instance convergence masks** — the vmapped ``lax.while_loop`` runs
  until every lane's own ``res ≤ tol``; lanes that finish early have their
  carries frozen by the batching rule's select masks, so an easy instance's
  solution is unaffected by a hard bucket-mate (each lane reports its own
  iteration count);
* **warm-start slots keyed per caller** — ``warm_key`` stores each
  instance's (x, λ, μ) triple at its REAL (unpadded) size and re-pads it
  into whatever bucket the next call lands in, including tail variables
  (e.g. an ε slot pinned to the last position) that must survive a column
  growth;
* **donated carry** — the stacked warm buffers are donated to the jitted
  core exactly as in the serial solver;
* **mesh sharding** — with a multi-device mesh the batch axis is laid out
  over the devices via an explicit ``NamedSharding`` and the same jitted
  core runs SPMD-partitioned, so sweep-level fleets scale out without a
  second code path (``parallel/sweep.py``).

The engine is strictly a wall-clock mechanism: callers keep their own
acceptance semantics (arithmetic residuals, float64 host confirms), and
with ``Config.lp_batch`` off every call site runs its serial path
bit-identically.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from citizensassemblies_tpu.lint.registry import (
    IRCase,
    register_ir_core,
    register_spmd_core,
)
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.utils.precision import demote_operator
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.guards import CompilationGuard, no_implicit_transfers
from citizensassemblies_tpu.utils.memo import LRU


def _current_context():
    """The ambient per-request context, imported lazily: the service layer
    imports the models, which import this module — a top-level import back
    into ``service`` would be circular."""
    from citizensassemblies_tpu.service.context import current_context

    return current_context()


@dataclasses.dataclass
class BatchLP:
    """One instance of ``min cᵀx s.t. Gx ≤ h, Ax = b, x ≥ 0``.

    ``tol`` overrides the engine-level tolerance per instance. ``tail_vars``
    marks how many TRAILING variables are structural (e.g. the ε slot of an
    ε-LP): a warm-slot re-pad keeps them pinned to the end of the padded
    variable vector instead of letting a column growth shift them into the
    middle. ``warm`` supplies an explicit (x, λ_G, μ_A) warm start at the
    instance's real sizes; when absent and ``warm_key`` is given, the
    engine's slot for (key, position) is used.
    """

    c: np.ndarray
    G: np.ndarray
    h: np.ndarray
    A: np.ndarray
    b: np.ndarray
    tol: Optional[float] = None
    tail_vars: int = 0
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None


#: smallest padded dimension — below this the pow-2 ladder just adds
#: dispatch-sized noise; 8 matches the f32 sublane tile
_BUCKET_FLOOR = 8

#: padding lanes make a batch a power of two; solved-instance tolerance for
#: those lanes is huge so an all-zero instance never gates the while_loop
_PAD_TOL = 1.0


def _bucket_dim(size: int, cap: int) -> int:
    """Power-of-two bucket below ``cap``, multiple-of-``cap`` above it."""
    size = max(int(size), 1)
    if size >= cap:
        return -(-size // cap) * cap
    b = _BUCKET_FLOOR
    while b < size:
        b *= 2
    return min(b, cap)


def lp_batch_enabled(cfg: Optional[Config]) -> bool:
    """Resolve the ``Config.lp_batch`` tri-state: forced on/off, or auto
    (accelerator backends on, CPU off — the same routing logic as the
    device masters: per-call dispatch overhead outweighs batching on CPU).
    """
    cfg = cfg or default_config()
    knob = getattr(cfg, "lp_batch", None)
    if knob is not None:
        return bool(knob)
    import jax

    return jax.default_backend() not in ("cpu",)


# --- the vmapped core --------------------------------------------------------

#: memoized jitted cores per (max_iters, check_every): one vmapped program
#: whose jit cache then holds one executable per padded bucket shape.
#: LRU-bounded (utils/memo): a sweep over iteration schedules must not
#: accrete executables forever — evictions land in ``memo_evictions()``.
_BATCH_CORES: LRU = LRU(cap=6, name="batch_lp_cores")

#: per-bucket dispatch / compile bookkeeping, for the bench's
#: solves-per-dispatch and per-bucket compile evidence. Updated under
#: ``_STATS_LOCK``: the serving layer dispatches buckets from several
#: request worker threads at once, and unlocked dict-increment pairs lose
#: counts under that load.
_BUCKET_STATS: Dict[str, Dict[str, int]] = {}
_STATS_LOCK = threading.Lock()


class WarmSlotStore:
    """Warm-start slots: (warm_key, position) → (x, λ, μ, tail_vars) at the
    instance's REAL sizes (host float64 — slots survive bucket changes).

    Formerly one module-level dict — which meant every run in the process
    shared one namespace of semantic keys (``"decomp_polish_screen"``), a
    direct warm-iterate collision between concurrent requests. The store is
    now a class: the module keeps ONE default instance for the offline
    single-job path (bit-identical behavior), and the service layer gives
    each request a private store via its ``RequestContext`` (with the
    semantic key additionally namespaced by tenant/request id). Mutations
    are lock-guarded; values are tiny host arrays.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: Dict[
            Tuple[str, int], Tuple[np.ndarray, np.ndarray, np.ndarray, int]
        ] = {}

    def get(self, key: Tuple[str, int]):
        with self._lock:
            return self._slots.get(key)

    def put(
        self, key: Tuple[str, int],
        value: Tuple[np.ndarray, np.ndarray, np.ndarray, int],
    ) -> None:
        with self._lock:
            self._slots[key] = value

    def clear(self, warm_key: Optional[str] = None) -> None:
        with self._lock:
            if warm_key is None:
                self._slots.clear()
                return
            for k in [k for k in self._slots if k[0] == warm_key]:
                del self._slots[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)


#: the offline single-job path's slots — requests under a RequestContext
#: never touch it (they carry their own store)
_DEFAULT_WARM_STORE = WarmSlotStore()


def _resolve_warm(warm_key: Optional[str]):
    """(store, scoped_key) for this call: the ambient RequestContext's
    private store + tenant/request-namespaced key when one is active, the
    module default otherwise (offline path, unchanged semantics)."""
    ctx = _current_context()
    if ctx is None or warm_key is None:
        store = ctx.warm_store if (ctx is not None and ctx.warm_store is not None) \
            else _DEFAULT_WARM_STORE
        return store, warm_key
    store = ctx.warm_store if ctx.warm_store is not None else _DEFAULT_WARM_STORE
    return store, ctx.scoped_warm_key(warm_key)


def _get_batch_core(max_iters: int, check_every: int, sentinel: bool = False):
    """Build (once per iteration schedule) the jitted vmapped PDHG core.

    The per-lane body is the serial solver's ``_pdhg_body`` verbatim —
    ``vmap`` adds the batch axis, the jit wrapper donates the stacked warm
    carry, and the while_loop batching rule supplies the per-instance
    convergence masks (a finished lane's carry is select-frozen while the
    bucket runs on). With ``sentinel`` (``Config.robust_sentinels``) the
    body additionally carries the per-lane QUARANTINE flag: a lane whose
    residual goes non-finite freezes at its last finite iterate and exits —
    NaN cannot propagate through the fleet, and the caller re-solves flagged
    lanes on the serial float64 host path. One run uses one flag value, so
    the compile count per bucket is unchanged.
    """
    key = (int(max_iters), int(check_every), bool(sentinel))
    core = _BATCH_CORES.get(key)
    if core is None:
        from functools import partial

        import jax

        from citizensassemblies_tpu.solvers.lp_pdhg import _pdhg_body

        one = partial(
            _pdhg_body, max_iters=key[0], check_every=key[1], sentinel=key[2]
        )
        from citizensassemblies_tpu.aot.store import aot_seeded

        core = aot_seeded(
            f"batch_lp.vmapped[{key[0]},{key[1]},{int(key[2])}]",
            jax.jit(jax.vmap(one), donate_argnums=(5, 6, 7)),
        )
        _BATCH_CORES[key] = core
    return core


@register_ir_core("batch_lp.vmapped_core", span="batch_lp.vmapped_core")
def _ir_batch_core() -> IRCase:
    """One small (m1=64, m2=1, nv=65) bucket with a 4-lane batch — the
    vmapped while_loop carries the per-lane convergence masks, which is the
    structure the IR pass must keep seeing (lint/ir.py)."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    B, nv, m1, m2 = 4, 65, 64, 1
    return IRCase(
        fn=_get_batch_core(1024, 128),
        args=(
            S((B, nv), f32), S((B, m1, nv), f32), S((B, m1), f32),
            S((B, m2, nv), f32), S((B, m2), f32),
            S((B, nv), f32), S((B, m1), f32), S((B, m2), f32), S((B,), f32),
        ),
        donate_expected=3,  # the stacked x0/lam0/mu0 carries
        arg_ranges=(
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(1, 3),  # stacked G, A
    )


@register_spmd_core("batch_lp.vmapped_core")
def _spmd_batch_core(mesh) -> IRCase:
    """graftspmd build: the same vmapped bucket core, B=8 lanes so the
    batch axis divides every swept mesh size, every operand in the declared
    ``bucket`` layout (leading instance axis over the whole mesh) — the
    layout :func:`prepartition` commits before dispatch."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    B, nv, m1, m2 = 8, 65, 64, 1
    return IRCase(
        fn=_get_batch_core(1024, 128),
        args=(
            S((B, nv), f32), S((B, m1, nv), f32), S((B, m1), f32),
            S((B, m2, nv), f32), S((B, m2), f32),
            S((B, nv), f32), S((B, m1), f32), S((B, m2), f32), S((B,), f32),
        ),
        arg_roles=(
            "bucket", "bucket", "bucket", "bucket", "bucket", "bucket",
            "bucket", "bucket", "bucket",
        ),
        donate_expected=3,
    )


def _bucket_key(insts: Sequence[BatchLP], cap: int) -> Tuple[int, int, int]:
    m1 = max(i.G.shape[0] for i in insts)
    m2 = max(i.A.shape[0] for i in insts)
    nv = max(i.c.shape[0] for i in insts)
    return (_bucket_dim(m1, cap), _bucket_dim(m2, cap), _bucket_dim(nv, cap))


def _repad_warm(
    warm: Tuple[np.ndarray, np.ndarray, np.ndarray],
    tail_vars: int,
    nv: int,
    m1: int,
    m2: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Re-pad a real-sized warm triple into (nv, m1, m2) slots, keeping the
    last ``tail_vars`` variables pinned to the END of the variable vector
    (an ε slot must survive a column-bucket growth at its structural
    position, not drift into the middle of the p block)."""
    x_w, lam_w, mu_w = (np.asarray(a, dtype=np.float64).ravel() for a in warm)
    x = np.zeros(nv)
    tv = min(int(tail_vars), len(x_w), nv)
    head_old = len(x_w) - tv
    head = min(head_old, nv - tv)
    x[:head] = x_w[:head]
    if tv:
        x[nv - tv :] = x_w[head_old:]
    lam = np.zeros(m1)
    lam[: min(m1, len(lam_w))] = lam_w[:m1]
    mu = np.zeros(m2)
    mu[: min(m2, len(mu_w))] = mu_w[:m2]
    return x, lam, mu


def clear_warm_slots(warm_key: Optional[str] = None) -> None:
    """Drop the engine's warm-start slots (all of them, or one caller's).
    Under an active RequestContext this clears the REQUEST's private store
    (with the scoped key), so a run's per-run reset cannot wipe a concurrent
    request's iterates."""
    store, scoped = _resolve_warm(warm_key)
    store.clear(scoped)


def bucket_stats() -> Dict[str, Dict[str, int]]:
    """Per-bucket dispatch/solve/compile counts since process start — the
    bench snapshots this around a row to attribute the engine's compiles."""
    with _STATS_LOCK:
        return {k: dict(v) for k, v in _BUCKET_STATS.items()}


def solve_lp_batch(
    problems: Sequence[BatchLP],
    cfg: Optional[Config] = None,
    log=None,
    warm_key: Optional[str] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
    mesh=None,
    common_bucket: bool = False,
    defer: bool = True,
):
    """Solve N independent LPs as bucketed, vmapped device calls.

    Instances are grouped into shape buckets (one jitted dispatch per
    bucket, batch padded to a power of two with inert all-zero lanes) and
    each bucket is solved by the vmapped restarted-PDHG core. Returns a
    list of :class:`~citizensassemblies_tpu.solvers.lp_pdhg.LPSolution`
    in input order, each sliced back to its instance's real sizes.

    ``warm_key`` engages the engine's warm-start slots: instance i of a
    repeat caller resumes from its previous (x, λ, μ) triple, re-padded
    into whatever bucket the new call lands in (``BatchLP.tail_vars``
    keeps structural trailing variables pinned through column growth).
    ``mesh`` (a multi-device ``jax.sharding.Mesh``) lays the batch axis
    out over the devices so whole buckets run SPMD-partitioned.
    ``common_bucket`` pads EVERY instance into one shared bucket (the max
    of each dim) — for fleets of nested/near-equal shapes (the polish-face
    screen's support prefixes) where one fused dispatch beats per-shape
    grouping; zero padding columns are free MXU work, a second dispatch is
    not.

    Counters on ``log`` (a ``RunLog``): ``lp_batch_dispatches`` (device
    calls), ``lp_batch_solves`` (real instances), ``lp_batch_pad_lanes``
    (inert padding lanes), ``lp_batch_warm_hits`` and per-bucket
    ``lp_batch_compiles_<rows>x<eq>x<cols>x<batch>`` whenever a dispatch
    compiled — so bench rows show solves-per-dispatch and per-bucket
    compile counts.
    """
    import jax
    import jax.numpy as jnp

    from citizensassemblies_tpu.solvers.lp_pdhg import LPSolution

    cfg = cfg or default_config()
    if not problems:
        return []

    # cross-request batching (service layer): when the calling thread runs
    # under a RequestContext whose service installed a CrossRequestBatcher,
    # this fleet is handed to the batcher, which briefly holds it open for
    # same-schedule fleets from OTHER concurrent requests and dispatches the
    # union through this very function (``defer=False`` breaks the
    # recursion). Mesh-sharded and shared-bucket calls keep their dedicated
    # layouts. Per-instance results come back in input order either way.
    if defer and mesh is None and not common_bucket:
        ctx = _current_context()
        if ctx is not None and ctx.batcher is not None:
            return ctx.batcher.submit(
                problems, ctx=ctx, cfg=cfg, log=log, warm_key=warm_key,
                tol=tol, max_iters=max_iters,
            )
    cap = max(int(getattr(cfg, "lp_batch_bucket_max", 4096)), _BUCKET_FLOOR)
    base_tol = float(tol if tol is not None else cfg.pdhg_tol)
    iters = int(max_iters if max_iters is not None else cfg.pdhg_max_iters)
    check_every = int(cfg.pdhg_check_every)

    # group instance positions by bucket (insertion-ordered, deterministic)
    groups: Dict[Tuple[int, int, int], List[int]] = {}
    if common_bucket:
        groups[_bucket_key(problems, cap)] = list(range(len(problems)))
    else:
        for i, inst in enumerate(problems):
            key = _bucket_key([inst], cap)
            groups.setdefault(key, []).append(i)

    from citizensassemblies_tpu.robust import inject
    from citizensassemblies_tpu.solvers.lp_pdhg import (
        FLAG_POISONED,
        _host_resolve_lp,
        sentinels_enabled,
    )

    sent = sentinels_enabled(cfg)
    # fault/sentinel evidence must survive even when the caller passes no
    # log (the cross-request batcher dispatches with log=None and fans
    # per-request counters back itself): attribute to the ambient request
    fault_log = log
    if fault_log is None:
        _ctx_amb = _current_context()
        fault_log = _ctx_amb.log if _ctx_amb is not None else None
    out: List[Optional[LPSolution]] = [None] * len(problems)
    warm_store, warm_key = _resolve_warm(warm_key)
    core = _get_batch_core(iters, check_every, sentinel=sent)
    for (m1, m2, nv), idxs in groups.items():
        B_real = len(idxs)
        B = 1 << max(B_real - 1, 0).bit_length()  # pow-2 batch, floor 1
        if mesh is not None:
            ndev = int(mesh.devices.size)
            B = -(-B // ndev) * ndev
        f32 = np.float32
        c = np.zeros((B, nv), f32)
        G = np.zeros((B, m1, nv), f32)
        h = np.zeros((B, m1), f32)
        A = np.zeros((B, m2, nv), f32)
        b = np.zeros((B, m2), f32)
        x0 = np.zeros((B, nv), f32)
        lam0 = np.zeros((B, m1), f32)
        mu0 = np.zeros((B, m2), f32)
        tols = np.full(B, _PAD_TOL, f32)
        warm_hits = 0
        for lane, i in enumerate(idxs):
            inst = problems[i]
            nvi, m1i, m2i = inst.c.shape[0], inst.G.shape[0], inst.A.shape[0]
            c[lane, :nvi] = inst.c
            G[lane, :m1i, :nvi] = inst.G
            h[lane, :m1i] = inst.h
            A[lane, :m2i, :nvi] = inst.A
            b[lane, :m2i] = inst.b
            tols[lane] = float(inst.tol if inst.tol is not None else base_tol)
            warm = inst.warm
            if warm is None and warm_key is not None:
                slot = warm_store.get((warm_key, i))
                if slot is not None:
                    warm = slot[:3]
                    warm_hits += 1
                    if inject.site("warm_slot_corrupt", fault_log):
                        # chaos: a corrupt slot must be quarantined by the
                        # lane sentinel, not poison the fleet
                        bad = np.array(warm[0], dtype=np.float64)
                        bad[:1] = np.nan
                        warm = (bad, warm[1], warm[2])
            if warm is None and inject.site("pdhg_nan", fault_log):
                x0[lane, 0] = np.nan  # chaos: poison one cold lane
            if warm is not None:
                # re-pad at the instance's REAL sizes (tail variables keep
                # their structural position inside the real column block —
                # the bucket padding beyond ``nvi`` is all-zero columns the
                # iterate never touches)
                x_w, l_w, m_w = _repad_warm(warm, inst.tail_vars, nvi, m1i, m2i)
                x0[lane, :nvi] = x_w
                lam0[lane, :m1i] = l_w
                mu0[lane, :m2i] = m_w

        bkey = f"{m1}x{m2}x{nv}x{B}"
        # operands are materialized to device arrays BEFORE the guard scope
        # (the engine's whole point is one explicit upload per bucket); with
        # a mesh the batch axis is laid out over the devices so the jitted
        # core runs SPMD-partitioned without a second code path
        if (
            mesh is not None
            and int(mesh.devices.size) > 1
            and getattr(cfg, "dist_prepartition", True)
        ):
            from citizensassemblies_tpu.dist import partition as dist_partition

            raw = (c, G, h, A, b, x0, lam0, mu0, tols)
            operands = dist_partition.prepartition_operands(
                raw,
                tuple(dist_partition.bucket(mesh, a.ndim) for a in raw),
                log=log,
            )
        elif mesh is not None and int(mesh.devices.size) > 1:
            # legacy per-call layout (dist_prepartition=False escape hatch):
            # same bucket spec, placed without the reshard accounting
            from citizensassemblies_tpu.dist import partition as dist_partition

            operands = tuple(
                jax.device_put(a, dist_partition.bucket(mesh, a.ndim))
                for a in (c, G, h, A, b, x0, lam0, mu0, tols)
            )
        else:
            operands = tuple(
                jnp.asarray(a) for a in (c, G, h, A, b, x0, lam0, mu0, tols)
            )
        if mesh is None or int(mesh.devices.size) <= 1:
            # graftgrade: the stacked constraint matrices ride at bf16 when
            # the committed plan certifies them (single-device route only —
            # the mesh layouts keep their declared f32 partition specs)
            operands = (
                operands[0],
                demote_operator(
                    operands[1], cfg, core="batch_lp.vmapped_core", arg=1,
                    log=log,
                ),
                operands[2],
                demote_operator(
                    operands[3], cfg, core="batch_lp.vmapped_core", arg=3,
                    log=log,
                ),
            ) + operands[4:]
        with dispatch_span(
            "batch_lp.vmapped_core", cfg=cfg, log=log, bucket=bkey,
            lanes=int(B_real),
        ) as _ds:
            with CompilationGuard(name=f"lp_batch_{bkey}") as guard:
                with no_implicit_transfers(cfg):
                    core_out = core(*operands)
                x, lam, mu, it, res = core_out[:5]
                flags = (
                    np.asarray(core_out[5])
                    if sent
                    else np.zeros(B, dtype=np.int32)
                )
                x = np.asarray(x, dtype=np.float64)
                lam = np.asarray(lam, dtype=np.float64)
                mu = np.asarray(mu, dtype=np.float64)
                it = np.asarray(it)
                res = np.asarray(res)
            _ds.out = x
        with _STATS_LOCK:
            stats = _BUCKET_STATS.setdefault(
                bkey, {"dispatches": 0, "solves": 0, "compiles": 0}
            )
            stats["dispatches"] += 1
            stats["solves"] += B_real
            stats["compiles"] += guard.count
        if log is not None:
            log.count("lp_batch_dispatches")
            log.count("lp_batch_solves", B_real)
            if B > B_real:
                log.count("lp_batch_pad_lanes", B - B_real)
            if warm_hits:
                log.count("lp_batch_warm_hits", warm_hits)
            if guard.count:
                log.count(f"lp_batch_compiles_{bkey}", guard.count)

        for lane, i in enumerate(idxs):
            inst = problems[i]
            nvi, m1i, m2i = inst.c.shape[0], inst.G.shape[0], inst.A.shape[0]
            if int(flags[lane]) & FLAG_POISONED:
                # per-lane quarantine: the lane froze at its last finite
                # iterate; re-solve THIS instance on the serial float64
                # host path (the fleet's other lanes are untouched) and do
                # NOT write its warm slot (the frozen iterate is suspect)
                if fault_log is not None:
                    fault_log.count("sentinel_quarantined")
                host = _host_resolve_lp(inst.c, inst.G, inst.h, inst.A, inst.b)
                if host is not None:
                    if fault_log is not None:
                        fault_log.count("sentinel_host_resolve")
                    out[i] = host
                    continue
            xi = x[lane, :nvi]
            li = lam[lane, :m1i]
            mi = mu[lane, :m2i]
            res_i = float(res[lane])
            tol_i = float(tols[lane])
            poisoned = bool(int(flags[lane]) & FLAG_POISONED)
            out[i] = LPSolution(
                ok=bool(res_i <= tol_i * 4.0) and not poisoned,
                x=xi,
                lam=li,
                mu=mi,
                objective=float(np.asarray(inst.c, dtype=np.float64) @ xi),
                iters=int(it[lane]),
                kkt=res_i,
            )
            if warm_key is not None and not poisoned:
                warm_store.put((warm_key, i), (xi, li, mi, int(inst.tail_vars)))
    return out


# --- structured-sparse (ELL) polish-face screen ------------------------------

#: memoized vmapped ELL two-sided cores per iteration schedule — the
#: bucketed engine's sparse variant (LRU-bounded like _BATCH_CORES)
_POLISH_ELL_CORES: LRU = LRU(cap=6, name="polish_ell_cores")


def _get_polish_screen_ell_core(
    max_iters: int, check_every: int, sentinel: bool = False
):
    """Build (once per schedule) the vmapped ELL two-sided master core.

    The per-lane body is ``lp_pdhg._pdhg_two_sided_body_ell`` verbatim;
    ``vmap`` broadcasts the PACKED indices/values and the profile ``v``
    (in_axes=None) and maps the per-lane (colmask, warm triple, tol) — the
    nested polish prefixes differ only in their column mask, so one shared
    pack feeds every lane and the whole screen is one device dispatch over
    O(C·k_pad) data instead of a stacked dense ``[B, 2T, C+1]`` tensor.
    """
    key = (int(max_iters), int(check_every), bool(sentinel))
    core = _POLISH_ELL_CORES.get(key)
    if core is None:
        from functools import partial

        import jax

        from citizensassemblies_tpu.solvers.lp_pdhg import (
            _pdhg_two_sided_body_ell,
        )

        one = partial(
            _pdhg_two_sided_body_ell, max_iters=key[0], check_every=key[1],
            sentinel=key[2],
        )
        from citizensassemblies_tpu.aot.store import aot_seeded

        core = aot_seeded(
            f"batch_lp.polish_ell[{key[0]},{key[1]},{int(key[2])}]",
            jax.jit(
                jax.vmap(one, in_axes=(None, None, None, 0, 0, 0, 0, 0)),
                # stacked x0/lam0 (mu0 scalar lanes stay)
                donate_argnums=(4, 5),
            ),
        )
        _POLISH_ELL_CORES[key] = core
    return core


@register_ir_core(
    "batch_lp.polish_screen_dense",
    span_optout="IR comparator only: the dense polish screen dispatches "
    "through solve_lp_batch, whose batch_lp.vmapped_core span covers it",
)
def _ir_polish_screen_dense() -> IRCase:
    """The DENSE comparator of the ELL polish screen: the generic vmapped
    core at the stacked two-sided master shape (B=4 lanes of a T=128,
    C=256 face — G is the dense ``[2T, C+1]`` block). Registered at the
    same problem shape as ``batch_lp.polish_screen_ell`` so the budget
    diff's dense→sparse delta is a same-shape measurement."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    B, T, C = 4, 128, 256
    m1, m2, nv = 2 * T, 1, C + 1
    return IRCase(
        fn=_get_batch_core(1024, 128),
        args=(
            S((B, nv), f32), S((B, m1, nv), f32), S((B, m1), f32),
            S((B, m2, nv), f32), S((B, m2), f32),
            S((B, nv), f32), S((B, m1), f32), S((B, m2), f32), S((B,), f32),
        ),
        donate_expected=3,
    )


@register_ir_core(
    "batch_lp.polish_screen_ell",
    dense_ref="batch_lp.polish_screen_dense",
    span="batch_lp.polish_screen_ell",
)
def _ir_polish_screen_ell() -> IRCase:
    """The ELL polish screen at the same (B=4, T=128, C=256) shape, packed
    at k_pad=16 slots — the production-representative fill."""
    import jax
    import jax.numpy as jnp

    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    B, T, C, kp = 4, 128, 256, 16
    return IRCase(
        fn=_get_polish_screen_ell_core(1024, 128),
        args=(
            S((C, kp), i32), S((C, kp), f32), S((T,), f32),
            S((B, C), f32), S((B, C + 1), f32), S((B, 2 * T), f32),
            S((B,), f32), S((B,), f32),
        ),
        donate_expected=2,  # stacked x0, lam0
    )


def solve_polish_screen_ell(
    ell,
    v: np.ndarray,
    caps: Sequence[int],
    warms: Sequence[Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]],
    tol: float,
    max_iters: int,
    cfg: Optional[Config] = None,
    log=None,
):
    """Solve nested polish-face prefixes as ONE vmapped ELL dispatch.

    ``ell`` packs the support columns
    (:class:`~citizensassemblies_tpu.solvers.sparse_ops.EllPack`, minor =
    the T types); ``caps`` are the prefix column counts (one lane each,
    expressed as per-lane column masks over the SHARED pack); ``warms``
    supplies each lane's (x, λ, μ) warm triple at its real size, or None.
    Returns a list of
    :class:`~citizensassemblies_tpu.solvers.lp_pdhg.LPSolution` in cap
    order, with the same ``x = [p (Cp), ε]`` layout as the serial ELL
    master so callers slice ``x[:cap]`` and certify arithmetically.
    """
    import jax.numpy as jnp

    from citizensassemblies_tpu.solvers.lp_pdhg import LPSolution

    cfg = cfg or default_config()
    T = int(ell.minor)
    S_real = len(ell)
    cap_dim = max(int(getattr(cfg, "lp_batch_bucket_max", 4096)), _BUCKET_FLOOR)
    Cp = _bucket_dim(S_real, cap_dim)
    idx_p, val_p = ell.padded(Cp)
    B_real = len(caps)
    B = 1 << max(B_real - 1, 0).bit_length()
    f32 = np.float32
    colmask = np.zeros((B, Cp), f32)
    x0 = np.zeros((B, Cp + 1), f32)
    lam0 = np.zeros((B, 2 * T), f32)
    mu0 = np.zeros(B, f32)
    tols = np.full(B, _PAD_TOL, f32)
    for lane, c_ in enumerate(caps):
        colmask[lane, : int(c_)] = 1.0
        tols[lane] = float(tol)
        warm = warms[lane] if lane < len(warms) else None
        if warm is not None:
            x_w, l_w, m_w = warm
            m = min(int(c_), len(x_w) - 1)
            x0[lane, :m] = x_w[:m]
            x0[lane, Cp] = max(float(x_w[-1]), 0.0)
            lam0[lane, : min(2 * T, len(l_w))] = l_w[: 2 * T]
            mu0[lane] = float(m_w[0] if np.ndim(m_w) else m_w)

    from citizensassemblies_tpu.solvers.lp_pdhg import (
        FLAG_POISONED,
        sentinels_enabled,
    )

    sent = sentinels_enabled(cfg)
    from citizensassemblies_tpu.kernels import pdhg_megakernel as _mk

    mode = _mk.megakernel_mode(
        cfg, _mk.two_sided_vmem_bytes(T, Cp, int(ell.k_pad))
    )
    bkey = f"ell_{T}x{Cp}x{ell.k_pad}x{B}"
    if mode != "off":
        bkey += "_mk"  # fused route compiles its own core: keep counters apart
    else:
        core = _get_polish_screen_ell_core(
            int(max_iters), int(cfg.pdhg_check_every), sentinel=sent
        )
    operands = (
        jnp.asarray(idx_p), jnp.asarray(val_p), jnp.asarray(v, jnp.float32),
        jnp.asarray(colmask), jnp.asarray(x0), jnp.asarray(lam0),
        jnp.asarray(mu0), jnp.asarray(tols),
    )
    with dispatch_span(
        "batch_lp.polish_screen_ell", cfg=cfg, log=log, bucket=bkey,
        lanes=int(B_real), megakernel=mode,
    ) as _ds:
        with CompilationGuard(name=f"lp_batch_{bkey}") as guard:
            if mode != "off":
                core_out = _mk.dispatch_two_sided(
                    operands, cfg=cfg, log=log, max_iters=int(max_iters),
                    check_every=int(cfg.pdhg_check_every), sentinel=sent,
                    mode=mode, lanes=int(B_real),
                )
            else:
                with no_implicit_transfers(cfg):
                    core_out = core(*operands)
            x, lam, mu, it, res = core_out[:5]
            flags = (
                np.asarray(core_out[5]) if sent else np.zeros(B, dtype=np.int32)
            )
            x = np.asarray(x, dtype=np.float64)
            lam = np.asarray(lam, dtype=np.float64)
            mu = np.asarray(mu, dtype=np.float64)
            it = np.asarray(it)
            res = np.asarray(res)
        _ds.out = x
    with _STATS_LOCK:
        stats = _BUCKET_STATS.setdefault(
            bkey, {"dispatches": 0, "solves": 0, "compiles": 0}
        )
        stats["dispatches"] += 1
        stats["solves"] += B_real
        stats["compiles"] += guard.count
    if log is not None:
        log.count("lp_batch_dispatches")
        log.count("lp_batch_solves", B_real)
        if B > B_real:
            log.count("lp_batch_pad_lanes", B - B_real)
        if guard.count:
            log.count(f"lp_batch_compiles_{bkey}", guard.count)
    out = []
    for lane, c_ in enumerate(caps):
        res_l = float(res[lane])
        poisoned = bool(int(flags[lane]) & FLAG_POISONED)
        if poisoned and log is not None:
            # the screen is advisory: a quarantined prefix lane is simply
            # not a candidate (its frozen iterate fails the caller's own
            # float64 accept check) — the deep polish / host IPM fallback
            # already covers the miss, so no host re-solve here
            log.count("sentinel_quarantined")
        out.append(
            LPSolution(
                ok=bool(res_l <= float(tol) * 4.0) and not poisoned,
                x=x[lane],
                lam=lam[lane],
                mu=mu[lane][None] if np.ndim(mu[lane]) == 0 else mu[lane],
                objective=float(x[lane][Cp]),
                iters=int(it[lane]),
                kkt=res_l,
            )
        )
    return out


def two_sided_master_batch_lp(
    MT: np.ndarray, v: np.ndarray, tol: Optional[float] = None
) -> BatchLP:
    """Pack one two-sided ε master ``min ε s.t. v − ε ≤ MT p ≤ v + ε,
    Σp = 1, p ≥ 0, ε ≥ 0`` into the engine's generic form (variables
    ``[p (C), ε]``, ``tail_vars=1`` so warm slots survive column growth).
    Row order matches ``solve_two_sided_master``: ``lam = [λ_lo (T),
    λ_up (T)]``, so pricing duals are ``lam[:T] − lam[T:]``."""
    T, C = MT.shape
    G = np.zeros((2 * T, C + 1))
    G[:T, :C] = -MT
    G[T:, :C] = MT
    G[:, C] = -1.0
    h = np.concatenate([-np.asarray(v, dtype=np.float64), np.asarray(v, dtype=np.float64)])
    A = np.zeros((1, C + 1))
    A[0, :C] = 1.0
    b = np.ones(1)
    c = np.zeros(C + 1)
    c[C] = 1.0
    return BatchLP(c=c, G=G, h=h, A=A, b=b, tol=tol, tail_vars=1)


def final_primal_batch_lp(
    P: np.ndarray, target: np.ndarray, tol: Optional[float] = None
) -> BatchLP:
    """Pack one final ε-LP ``min ε s.t. Pᵀp ≥ target − ε, Σp = 1, p ≥ 0,
    ε ≥ 0`` (``leximin.py:453-464``) into the engine's generic form —
    the per-instance solve of a sweep's fleet (``parallel/sweep.py``)."""
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    c = np.zeros(C + 1)
    c[C] = 1.0
    G = np.hstack([-P.T, -np.ones((n, 1))])
    h = -np.asarray(target, dtype=np.float64)
    A = np.zeros((1, C + 1))
    A[0, :C] = 1.0
    b = np.ones(1)
    return BatchLP(c=c, G=G, h=h, A=A, b=b, tol=tol, tail_vars=1)


def face_probe_batch_lp(
    objective: np.ndarray,
    A_face: np.ndarray,
    b_face: np.ndarray,
    tol: Optional[float] = None,
) -> BatchLP:
    """Pack one optimal-face probe ``max objective·x s.t. A_face x ≤ b_face,
    Σx = 1, x ≥ 0`` (the certification probe of ``compositions.py``) into
    the engine's MIN form (negated objective)."""
    C = objective.shape[0]
    A = np.ones((1, C))
    b = np.ones(1)
    return BatchLP(
        c=-np.asarray(objective, dtype=np.float64),
        G=np.asarray(A_face, dtype=np.float64),
        h=np.asarray(b_face, dtype=np.float64),
        A=A,
        b=b,
        tol=tol,
    )
