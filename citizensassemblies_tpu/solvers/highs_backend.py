"""Host exact-solver backend on scipy's HiGHS (LPs via ``linprog``, ILPs via
``milp``).

This fills the role Gurobi + python-mip/CBC play in the reference
(``leximin.py:16-17``): the committee-feasibility/pricing ILP
(``leximin.py:190-233``), the quota-relaxation ILP (``leximin.py:90-187``), the
dual leximin LP (``leximin.py:300-328``), and the final primal LP
(``leximin.py:453-464``). It is the *certification* path of the framework —
the TPU backend prices committees stochastically in huge batches and solves
LPs with PDHG on device; the exact oracle is consulted only to prove that no
violating committee remains (the dual-gap test at ``leximin.py:429-431``) and
as a reference implementation in tests.

All problems are expressed on the dense incidence representation: a committee
is ``x ∈ {0,1}^n`` with ``A.T @ x ∈ [qmin, qmax]`` and ``1.T x = k``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import scipy.sparse
from scipy.optimize import Bounds, LinearConstraint, linprog, milp

from citizensassemblies_tpu.core.instance import (
    DenseInstance,
    FeatureSpace,
    InfeasibleQuotasError,
    SelectionError,
)


def _constraint_rows(A: np.ndarray, k: int, households: Optional[np.ndarray]):
    """Shared committee constraint system: size row + per-cell quota rows +
    optional ≤1-per-household rows (``leximin.py:201-221``)."""
    n, F = A.shape
    rows = [np.ones((1, n))]
    lb = [float(k)]
    ub = [float(k)]
    rows.append(A.T.astype(np.float64))
    if households is not None:
        for members in _household_groups(households):
            row = np.zeros((1, n))
            row[0, members] = 1.0
            rows.append(row)
            lb.append(0.0)
            ub.append(1.0)
    return rows


def _household_groups(households: np.ndarray) -> List[np.ndarray]:
    groups = []
    for h in np.unique(households):
        members = np.nonzero(households == h)[0]
        if len(members) >= 2:
            groups.append(members)
    return groups


class HighsCommitteeOracle:
    """Exact committee oracle: maximize any linear agent-weight objective over
    feasible committees (the column-generation pricing oracle, used as the
    reference uses its reusable mip model ``new_committee_model``,
    ``leximin.py:190-233,420-424``)."""

    def __init__(
        self,
        dense: DenseInstance,
        households: Optional[np.ndarray] = None,
        log=None,
    ):
        #: optional RunLog for oracle-mix attribution: every pricing call
        #: counts the backend that actually served it
        #: (``oracle_backend_native`` / ``oracle_backend_highs``), so bench
        #: rows show the native-vs-MILP split instead of inferring it
        self.log = log
        self.A = dense.A_np.astype(np.float64)
        self.n, self.F = self.A.shape
        self.k = dense.k
        self.qmin = dense.qmin_np.astype(np.float64)
        self.qmax = dense.qmax_np.astype(np.float64)
        self.households = households

        mats = [np.ones((1, self.n)), self.A.T]
        lbs = [np.array([float(self.k)]), self.qmin]
        ubs = [np.array([float(self.k)]), self.qmax]
        if households is not None:
            for members in _household_groups(np.asarray(households)):
                row = np.zeros((1, self.n))
                row[0, members] = 1.0
                mats.append(row)
                lbs.append(np.array([0.0]))
                ubs.append(np.array([1.0]))
        self._mat = np.vstack(mats)
        self._lb = np.concatenate(lbs)
        self._ub = np.concatenate(ubs)
        self._integrality = np.ones(self.n)
        self._reduction = None  # lazy TypeReduction for the native oracle
        self._dense = dense

    def _native_maximize(self, weights: np.ndarray, incumbent: float = -1e300,
                         max_nodes: int = 500_000):
        """Try the native exact oracle; None means 'use the MILP path'.

        The node budget bounds the downside of a hard search to well under a
        second — the MILP fallback then decides."""
        from citizensassemblies_tpu.solvers import native_oracle

        if not native_oracle.native_available():
            return None
        if self._reduction is None:
            self._reduction = native_oracle.TypeReduction(self._dense)
        return native_oracle.price_exact(
            self._reduction, weights, incumbent=incumbent, max_nodes=max_nodes
        )

    def certify(self, weights: np.ndarray, floor: float):
        """Decide whether any feasible committee has value > ``floor``; if
        yes, return one (``(committee, value)``), else ``(None, floor)``.

        This is the column-generation termination test
        (``leximin.py:429-431``): seeded with ``floor`` as the incumbent, the
        native branch-and-bound usually certifies 'no violating committee'
        from the root bound alone — orders of magnitude less work than an
        unseeded exact maximization.
        """
        if self.households is None:
            res = self._native_maximize(weights, incumbent=float(floor))
            if res is not None:
                if self.log is not None:
                    self.log.count("oracle_backend_native")
                committee, value = res
                return (None, float(floor)) if committee is None else (committee, value)
        # native unavailable or aborted on its node budget: go straight to the
        # MILP (re-running the native search unseeded would only repeat the
        # work that just hit the limit)
        committee, value = self._milp_maximize(weights)
        return (None, float(floor)) if value <= floor else (committee, value)

    def maximize(
        self, weights: np.ndarray, forced: Sequence[int] = ()
    ) -> Tuple[Tuple[int, ...], float]:
        """Return (committee, value) maximizing ``weights @ x``; ``forced``
        agents are constrained into the committee (the ``ensure_inclusion``
        capability, ``leximin.py:104-107,129-133``).

        Dispatches to the native type-reduced branch-and-bound
        (``native/bb_price.cpp``) when the problem has no household or
        forced-inclusion side constraints (those break type
        interchangeability); falls back to the HiGHS MILP otherwise or when
        the native search aborts. Raises :class:`SelectionError` if no
        feasible committee exists under the constraints.
        """
        if self.households is None and not forced:
            res = self._native_maximize(weights)
            if res is not None:
                if self.log is not None:
                    self.log.count("oracle_backend_native")
                return res
        return self._milp_maximize(weights, forced)

    def _milp_maximize(
        self, weights: np.ndarray, forced: Sequence[int] = ()
    ) -> Tuple[Tuple[int, ...], float]:
        if self.log is not None:
            self.log.count("oracle_backend_highs")
        committee, value, _bound = self._milp_maximize_with_bound(weights, forced)
        return committee, value

    def _milp_maximize_with_bound(
        self, weights: np.ndarray, forced: Sequence[int] = ()
    ) -> Tuple[Tuple[int, ...], float, float]:
        """Like :meth:`_milp_maximize` but also returns HiGHS's PROVEN dual
        bound on the maximum. The incumbent objective can sit up to the
        solver's default MIP gap (rel 1e-4) below the true optimum, which
        matters when the value feeds a certificate: the audit functions use
        the dual bound, never the incumbent, as the certified upper."""
        lo = np.zeros(self.n)
        for i in forced:
            lo[i] = 1.0
        res = milp(
            c=-np.asarray(weights, dtype=np.float64),
            constraints=LinearConstraint(self._mat, self._lb, self._ub),
            integrality=self._integrality,
            bounds=Bounds(lo, np.ones(self.n)),
        )
        if res.status != 0 or res.x is None:
            raise SelectionError(
                f"committee pricing ILP not solved to optimality (HiGHS status {res.status}: "
                f"{res.message})"
            )
        x = res.x > 0.5
        committee = tuple(int(i) for i in np.nonzero(x)[0])
        value = float(np.asarray(weights) @ x)
        dual = getattr(res, "mip_dual_bound", None)
        # the minimization's dual bound lower-bounds min(−w·x), so its
        # negation upper-bounds max(w·x); fall back to the incumbent if the
        # solver did not report one
        bound = float(-dual) if dual is not None else value
        return committee, value, max(bound, value)

    def check_feasible(self) -> bool:
        """Solve the pure feasibility problem once (``leximin.py:223-231``).

        Without household constraints the committee polytope depends only on
        type counts, so the check collapses onto the type-space MILP —
        milliseconds, where the n-binary model (native B&B node-budget abort
        + HiGHS fallback) took ~47 s at n=1727."""
        if self.households is None:
            from citizensassemblies_tpu.solvers import native_oracle
            from citizensassemblies_tpu.solvers.cg_typespace import CompositionOracle

            if self._reduction is None:
                self._reduction = native_oracle.TypeReduction(self._dense)
            return (
                CompositionOracle(self._reduction).maximize(
                    np.zeros(self._reduction.T)
                )
                is not None
            )
        try:
            self.maximize(np.zeros(self.n))
            return True
        except SelectionError:
            return False


def relax_infeasible_quotas(
    dense: DenseInstance,
    space: FeatureSpace,
    households: Optional[np.ndarray] = None,
    ensure_inclusion: Sequence[Sequence[int]] = ((),),
) -> Tuple[Dict[Tuple[str, str], Tuple[int, int]], List[str]]:
    """Suggest a minimal quota relaxation making the instance feasible.

    Mirrors the reference's relaxation ILP (``leximin.py:90-187``): integer
    relaxation variables per feature bound; lowering a small lower quota of
    old value q costs ``1 + 2/q`` while raising an upper quota costs 1
    (``leximin.py:152-163``); ``ensure_inclusion`` demands that, for each given
    agent set, some feasible panel contains it (one committee variable block
    per set, all sharing the relaxation variables).

    Returns (suggested quotas {(category, feature): (lo, hi)}, advice lines).
    Raises :class:`SelectionError` if even fully relaxed quotas admit no panel.
    """
    A = dense.A_np.astype(np.float64)
    n, F = A.shape
    k = dense.k
    qmin = dense.qmin_np.astype(np.float64)
    qmax = dense.qmax_np.astype(np.float64)
    S = len(ensure_inclusion)
    if S == 0:
        raise ValueError("ensure_inclusion must contain at least one (possibly empty) set")

    # Fast path: without households or inclusion sets the committee block
    # collapses onto agent types (quota rows depend only on type counts), so
    # the MILP shrinks from n binaries to T bounded integers — at n=1727 the
    # agent-space model takes ~50 s, the type-space one well under a second.
    if households is None and all(len(s) == 0 for s in ensure_inclusion):
        from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

        red = TypeReduction(dense)
        T = red.T
        tf = np.zeros((T, F))
        for t in range(T):
            tf[t, red.type_feature[t]] = 1.0
        nvars = T + 2 * F
        c = np.zeros(nvars)
        for f in range(F):
            old = qmin[f]
            c[T + f] = 0.0 if old == 0 else 1.0 + 2.0 / old
            c[T + F + f] = 1.0
        lo = np.zeros(nvars)
        hi = np.concatenate([red.msize.astype(np.float64), qmin, np.full(F, float(n))])
        rows = np.zeros((1 + 2 * F, nvars))
        lbs = np.zeros(1 + 2 * F)
        ubs = np.zeros(1 + 2 * F)
        rows[0, :T] = 1.0
        lbs[0] = ubs[0] = float(k)
        rows[1 : 1 + F, :T] = tf.T
        rows[1 : 1 + F, T : T + F] = np.eye(F)  # + min_relax_f ≥ qmin_f
        lbs[1 : 1 + F] = qmin
        ubs[1 : 1 + F] = np.inf
        rows[1 + F :, :T] = tf.T
        rows[1 + F :, T + F :] = -np.eye(F)  # − max_relax_f ≤ qmax_f
        lbs[1 + F :] = -np.inf
        ubs[1 + F :] = qmax
        res = milp(
            c=c,
            constraints=LinearConstraint(rows, lbs, ubs),
            integrality=np.ones(nvars),
            bounds=Bounds(lo, hi),
        )
        if res.status != 0 or res.x is None:
            raise SelectionError(
                f"No feasible committees found even with relaxed quotas (HiGHS "
                f"status {res.status}). Either the pool is very bad or something "
                f"is wrong with the solver."
            )
        lines: List[str] = []
        new_quotas: Dict[Tuple[str, str], Tuple[int, int]] = {}
        for f, (cat, feat) in enumerate(space.cells):
            lower = int(round(qmin[f] - round(res.x[T + f])))
            upper = int(round(qmax[f] + round(res.x[T + F + f])))
            if lower < qmin[f]:
                lines.append(f"Recommend lowering lower quota of {cat}:{feat} to {lower}.")
            if upper > qmax[f]:
                lines.append(f"Recommend raising upper quota of {cat}:{feat} to {upper}.")
            new_quotas[(cat, feat)] = (lower, upper)
        return new_quotas, lines

    # variable layout: [x_0 .. x_{S-1} blocks of n | min_relax (F) | max_relax (F)]
    nvars = S * n + 2 * F
    c = np.zeros(nvars)
    for f in range(F):
        old = qmin[f]
        c[S * n + f] = 0.0 if old == 0 else 1.0 + 2.0 / old
        c[S * n + F + f] = 1.0
    lo = np.zeros(nvars)
    hi = np.ones(nvars)
    hi[S * n : S * n + F] = qmin  # cannot lower below zero
    hi[S * n + F :] = float(n)  # raising beyond the pool is pointless

    mats: List[np.ndarray] = []
    lbs: List[float] = []
    ubs: List[float] = []
    for s, inclusion in enumerate(ensure_inclusion):
        base = s * n
        row = np.zeros(nvars)
        row[base : base + n] = 1.0
        mats.append(row)
        lbs.append(float(k))
        ubs.append(float(k))
        for f in range(F):
            row = np.zeros(nvars)
            row[base : base + n] = A[:, f]
            row[S * n + f] = 1.0  # + min_relax_f ≥ qmin_f
            mats.append(row)
            lbs.append(qmin[f])
            ubs.append(np.inf)
            row = np.zeros(nvars)
            row[base : base + n] = A[:, f]
            row[S * n + F + f] = -1.0  # - max_relax_f ≤ qmax_f
            mats.append(row)
            lbs.append(-np.inf)
            ubs.append(qmax[f])
        if households is not None:
            for members in _household_groups(np.asarray(households)):
                row = np.zeros(nvars)
                row[base + members] = 1.0
                mats.append(row)
                lbs.append(0.0)
                ubs.append(1.0)
        for agent in inclusion:
            lo[base + int(agent)] = 1.0

    res = milp(
        c=c,
        constraints=LinearConstraint(np.vstack(mats), np.array(lbs), np.array(ubs)),
        integrality=np.ones(nvars),
        bounds=Bounds(lo, hi),
    )
    if res.status != 0 or res.x is None:
        raise SelectionError(
            f"No feasible committees found even with relaxed quotas (HiGHS status "
            f"{res.status}). Either the pool is very bad or something is wrong with the solver."
        )

    lines: List[str] = []
    new_quotas: Dict[Tuple[str, str], Tuple[int, int]] = {}
    for f, (cat, feat) in enumerate(space.cells):
        lower = int(round(qmin[f] - round(res.x[S * n + f])))
        upper = int(round(qmax[f] + round(res.x[S * n + F + f])))
        if lower < qmin[f]:
            lines.append(f"Recommend lowering lower quota of {cat}:{feat} to {lower}.")
        if upper > qmax[f]:
            lines.append(f"Recommend raising upper quota of {cat}:{feat} to {upper}.")
        new_quotas[(cat, feat)] = (lower, upper)
    return new_quotas, lines


def check_feasible_or_suggest(
    dense: DenseInstance,
    space: FeatureSpace,
    oracle: HighsCommitteeOracle,
    households: Optional[np.ndarray] = None,
) -> None:
    """Feasibility gate: on infeasible quotas raise
    :class:`InfeasibleQuotasError` carrying the suggested relaxation
    (``leximin.py:223-228``)."""
    if not oracle.check_feasible():
        new_quotas, lines = relax_infeasible_quotas(dense, space, households)
        raise InfeasibleQuotasError(new_quotas, lines)


@dataclasses.dataclass
class DualSolution:
    ok: bool
    y: np.ndarray  # float64[n] agent duals
    yhat: float  # ŷ, the committee cap
    objective: float  # ŷ - Σ fixed_i y_i


def solve_dual_lp(
    P: np.ndarray,
    fixed: np.ndarray,
) -> DualSolution:
    """Solve the dual leximin LP over the current portfolio.

    minimize    ŷ - Σ_{i fixed} fixed_i · y_i
    subject to  Σ_{i ∈ C} y_i ≤ ŷ           for each committee row C of P
                Σ_{i unfixed} y_i = 1
                y ≥ 0, ŷ ≥ 0

    (the LP of ``leximin.py:300-328``; ``fixed[i] < 0`` marks agent i unfixed).
    Solved with HiGHS; any non-optimal status returns ``ok=False``, which the
    caller treats the way the reference treats a non-OPTIMAL Gurobi status —
    shave the fixed probabilities and retry (``leximin.py:405-417``).
    """
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    fixed = np.asarray(fixed, dtype=np.float64)
    unfixed_mask = fixed < 0
    fixed_vals = np.where(unfixed_mask, 0.0, fixed)

    # variables z = [y_0..y_{n-1}, ŷ]
    c = np.concatenate([-fixed_vals, [1.0]])
    A_ub = np.hstack([P, -np.ones((C, 1))])
    b_ub = np.zeros(C)
    A_eq = np.concatenate([unfixed_mask.astype(np.float64), [0.0]])[None, :]
    b_eq = np.array([1.0])
    res = linprog(
        c,
        A_ub=A_ub,
        b_ub=b_ub,
        A_eq=A_eq,
        b_eq=b_eq,
        bounds=(0, None),
        method="highs",
    )
    if res.status != 0 or res.x is None:
        return DualSolution(ok=False, y=np.zeros(n), yhat=0.0, objective=0.0)
    return DualSolution(ok=True, y=res.x[:n], yhat=float(res.x[n]), objective=float(res.fun))


def solve_final_primal_lp_duals(
    P: np.ndarray, target: np.ndarray, two_sided: bool = True
) -> Tuple[np.ndarray, float, np.ndarray, float]:
    """``solve_final_primal_lp`` variant also returning the dual solution:
    ``(p, ε, y, μ)`` where ``y`` are the agent-coverage duals and ``μ`` the
    normalization dual — the quantities column-generation pricing needs
    (reduced cost of a candidate panel column is ``−y·panel − μ``).

    ``two_sided`` bounds the deviation on both sides
    (``target − ε ≤ Pᵀp ≤ target + ε``): since panels conserve total mass
    (``Σ alloc = k = Σ target``), a one-sided formulation lets a per-agent
    deficit of ε fund an n·ε overshoot concentrated on one agent; the
    two-sided ε bounds the allocation L∞ error directly. ``y`` is then the
    mixed-sign ``y_lower − y_upper``.
    """
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    target = np.asarray(target, dtype=np.float64)
    c = np.zeros(C + 1)
    c[-1] = 1.0
    lower = np.hstack([-P.T, -np.ones((n, 1))])
    if two_sided:
        A_ub = np.vstack([lower, np.hstack([P.T, -np.ones((n, 1))])])
        b_ub = np.concatenate([-target, target])
    else:
        A_ub = lower
        b_ub = -target
    A_eq = np.concatenate([np.ones(C), [0.0]])[None, :]
    b_eq = np.array([1.0])
    res = linprog(
        c, A_ub=scipy.sparse.csr_matrix(A_ub), b_ub=b_ub, A_eq=A_eq, b_eq=b_eq,
        bounds=(0, None), method="highs-ipm",
    )
    if res.status != 0 or res.x is None:
        res = linprog(
            c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq, bounds=(0, None),
            method="highs",
        )
    if res.status != 0 or res.x is None:
        raise SelectionError(f"final primal LP failed (HiGHS status {res.status}: {res.message})")
    lam = -np.asarray(res.ineqlin.marginals)
    y = lam[:n] - lam[n:] if two_sided else lam
    mu = float(res.eqlin.marginals[0])
    return res.x[:C], float(res.x[C]), y, mu


def solve_final_primal_lp(P: np.ndarray, target: np.ndarray) -> Tuple[np.ndarray, float]:
    """Recover committee probabilities realizing the fixed per-agent targets.

    minimize    ε
    subject to  Σ_C p_C = 1;   (Pᵀ p)_i ≥ target_i - ε  ∀i;   p ≥ 0, ε ≥ 0

    — the reference's numerically-robust final stage, which minimizes the
    largest downward deviation from the fixed probabilities rather than
    demanding them exactly (``leximin.py:453-464``).
    Returns (p, ε).
    """
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    target = np.asarray(target, dtype=np.float64)
    # variables [p_0..p_{C-1}, ε]; sparse (panel rows are k-of-n) + interior
    # point — XMIN portfolios reach ~5n columns, where a dense simplex build
    # takes minutes
    c = np.zeros(C + 1)
    c[-1] = 1.0
    A_ub = scipy.sparse.hstack(
        [scipy.sparse.csr_matrix(-P.T), scipy.sparse.csr_matrix(-np.ones((n, 1)))]
    ).tocsr()
    b_ub = -target
    A_eq = scipy.sparse.csr_matrix(np.concatenate([np.ones(C), [0.0]])[None, :])
    b_eq = np.array([1.0])
    res = linprog(
        c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq, bounds=(0, None),
        method="highs-ipm",
    )
    if res.status != 0 or res.x is None:
        res = linprog(
            c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq, bounds=(0, None),
            method="highs",
        )
    if res.status != 0 or res.x is None:
        raise SelectionError(f"final primal LP failed (HiGHS status {res.status}: {res.message})")
    return res.x[:C], float(max(res.x[C], 0.0))


def audit_maximin(
    dense, allocation: np.ndarray, covered: Optional[np.ndarray] = None
) -> dict:
    """Solver-independent post-hoc maximin certificate for an allocation.

    Plays the role Gurobi's dual-gap certificate plays on every reference run
    (``leximin.py:429-431``), applied after the fact to whatever produced
    ``allocation``: by LP minimax duality, for ANY probability vector ``w``
    over agents, ``maximin ≤ Σ_i w_i · alloc_i ≤ max_{feasible committee x}
    w·x``, and the right-hand maximum is evaluated by the exact agent-space
    HiGHS MILP — so the resulting bound is a valid certificate regardless of
    where ``w`` came from. The witness used is the floor-dual vector of the
    stage-1 maximin LP over the marginal polytope (one tiny host HiGHS LP),
    which is tight when the allocation is exact.

    ``covered`` masks agents contained in some feasible committee: agents
    provably in none have probability 0 under every distribution (the
    reference excludes them from the optimization, ``leximin.py:286-296``),
    so the maximin claim — and its witness floors — range over coverable
    agents only.

    Returns ``{"achieved_min", "certified_maximin_upper", "maximin_gap"}`` —
    a gap within the framework's 1e-3 tolerance certifies the first leximin
    level of ``allocation`` independently of the type-space machinery.
    """
    from citizensassemblies_tpu.solvers.lp_util import robust_linprog
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    red = TypeReduction(dense)
    T, F = red.T, red.F
    m = red.msize.astype(np.float64)
    if covered is None:
        covered = np.ones(dense.n, dtype=bool)
    covered = np.asarray(covered, dtype=bool)
    # a type is coverable iff any member is
    cov_t = np.zeros(T, dtype=bool)
    np.logical_or.at(cov_t, red.type_id, covered)
    tf = np.zeros((T, F))
    for t in range(T):
        tf[t, red.type_feature[t]] = 1.0
    # stage-1 maximin LP over the marginal polytope: vars [x (T), z];
    # floors only on coverable types
    c = np.zeros(T + 1)
    c[T] = -1.0
    A_ub = np.zeros((2 * F + T, T + 1))
    A_ub[:F, :T] = -tf.T
    A_ub[F : 2 * F, :T] = tf.T
    A_ub[2 * F + np.arange(T), np.arange(T)] = -1.0
    A_ub[2 * F :, T] = np.where(cov_t, m, 0.0)
    b_ub = np.concatenate(
        [-red.qmin.astype(float), red.qmax.astype(float), np.zeros(T)]
    )
    A_eq = np.concatenate([np.ones(T), [0.0]])[None, :]
    res = robust_linprog(
        c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=[float(red.k)],
        bounds=[(0, mm) for mm in m] + [(0, None)],
    )
    if res.status != 0:
        raise SelectionError(f"maximin witness LP failed: {res.message}")
    y_t = np.maximum(-np.asarray(res.ineqlin.marginals)[2 * F :], 0.0)
    w = np.where(cov_t, y_t, 0.0)[red.type_id]
    total = w.sum()
    if total <= 0:
        # degenerate dual (no active floor rows): fall back to the uniform
        # witness over COVERED agents only — mass on a non-coverable agent
        # (whose allocation is structurally 0) would deflate the bound below
        # the true maximin and falsely certify
        w = covered.astype(np.float64) / covered.sum()
    else:
        w = w / total
    # exact agent-space bound; the MILP path is used directly because the
    # witness is constant within types, a regime where the seeded native
    # B&B ties itself in near-equal branches while HiGHS solves instantly
    oracle = HighsCommitteeOracle(dense)
    _panel, _value, upper = oracle._milp_maximize_with_bound(w)
    z_min = float(np.asarray(allocation)[covered].min())
    return {
        "achieved_min": round(z_min, 6),
        "certified_maximin_upper": round(float(upper), 6),
        "maximin_gap": round(float(upper) - z_min, 6),
    }


def audit_leximin_profile(
    dense,
    allocation: np.ndarray,
    covered: Optional[np.ndarray] = None,
    level_tol: float = 1e-3,
    max_levels: Optional[int] = None,
) -> dict:
    """Iterated solver-independent certificate for the FULL leximin profile.

    Generalizes ``audit_maximin`` level by level: at level ``j``, types
    audited in earlier levels are floored at their *achieved* level values
    (our own allocation satisfies those floors, so the relaxed level-``j``
    problem contains it and the bound can never undercut what we achieved),
    a witness LP over the marginal polytope maximizes the min of the
    remaining types, and its floor duals enter the exact agent-space HiGHS
    MILP as Lagrange multipliers:

        level_j ≤ Σ w·a ≤ max_{feasible x} (w + λ)·x − Σ_t λ_t·floor_t·cnt_t

    for any feasible distribution honoring the earlier floors, any
    probability vector ``w`` over the remaining covered agents, and any
    λ ≥ 0 on the floored types. This certifies the same thing the
    reference's per-stage Gurobi dual gap certifies (``leximin.py:429-431``):
    each level is optimal GIVEN the prefix already fixed — stage-local
    optimality, level by level, for the whole profile. Two valid upper
    bounds are evaluated per level and both reported: ``milp_upper``, the
    Lagrangian bound from an exact agent-space HiGHS MILP entirely outside
    the type-space machinery (fully solver-independent, but carrying an
    integrality duality gap deep in the profile), and ``marginal_upper``,
    the witness LP's own optimum (tight everywhere, but it shares the
    marginal-relaxation viewpoint with the production solver). The
    headline ``gap`` uses their min — sound, since each is a valid bound —
    while ``gap_milp``/``worst_gap_milp`` record how far the fully
    independent certificate alone reaches.
    One witness LP + one MILP per distinct level (~0.15 s each at n=1727).

    Returns ``{"levels": [...], "n_levels", "worst_gap", "worst_gap_milp",
    "all_within_tol"}`` where each level entry carries
    achieved/upper/gap/gap_milp and the level set size.

    Pass the CERTIFIED profile (``Distribution.fixed_probabilities``) as
    ``allocation``, not the realized one: flooring the prefix at realized
    values leaks the realization ε across every fixed type (≈ N·ε agents of
    aggregate slack), which the polytope concentrates onto later singleton
    types as spurious headroom (measured +0.37 at n=800 with ε ≈ 6e-4).
    The realized-vs-certified gap is a separate, directly-measured number
    (``max|allocation − fixed_probabilities|``, the bench's
    ``alloc_linf_dev``); together the two facts certify the shipped
    allocation end to end. Measured: every level within 6e-6 at n=800
    (15 levels, 2.8 s) and n=1727 (14 levels, 2.1 s).
    """
    from citizensassemblies_tpu.solvers.lp_util import robust_linprog
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    red = TypeReduction(dense)
    T, F = red.T, red.F
    alloc = np.asarray(allocation, dtype=np.float64)
    if covered is None:
        covered = np.ones(dense.n, dtype=bool)
    covered = np.asarray(covered, dtype=bool)
    cov_t = np.zeros(T, dtype=bool)
    np.logical_or.at(cov_t, red.type_id, covered)
    # per-type achieved values (allocations are type-constant up to the
    # realization tolerance; take the min so floors never overstate)
    v_t = np.full(T, np.inf)
    np.minimum.at(v_t, red.type_id, np.where(covered, alloc, np.inf))
    v_t = np.where(cov_t, v_t, 0.0)
    # per-type COVERED member counts: only covered members carry level
    # guarantees (uncovered agents sit at structural 0), so floors and the
    # Lagrangian subtraction scale with the covered count, not the type size
    cnt_t = np.zeros(T)
    np.add.at(cnt_t, red.type_id, covered.astype(np.float64))
    tf = np.zeros((T, F))
    for t in range(T):
        tf[t, red.type_feature[t]] = 1.0

    oracle = HighsCommitteeOracle(dense)
    fixed_floor = np.zeros(T)
    fixed_mask = np.zeros(T, dtype=bool)
    remaining = cov_t.copy()
    levels: list = []
    worst_gap = 0.0
    worst_gap_milp = 0.0
    while remaining.any() and (max_levels is None or len(levels) < max_levels):
        lvl = float(v_t[remaining].min())
        S = remaining & (v_t <= lvl + level_tol)
        nr = int(remaining.sum())
        idxr = np.nonzero(remaining)[0]
        c = np.zeros(T + 1)
        c[T] = -1.0
        A_ub = np.zeros((2 * F + nr, T + 1))
        A_ub[:F, :T] = -tf.T
        A_ub[F : 2 * F, :T] = tf.T
        A_ub[2 * F + np.arange(nr), idxr] = -1.0
        A_ub[2 * F :, T] = cnt_t[idxr]
        b_ub = np.concatenate(
            [-red.qmin.astype(float), red.qmax.astype(float), np.zeros(nr)]
        )
        lo = np.where(fixed_mask, np.clip(fixed_floor * cnt_t, 0.0, cnt_t), 0.0)
        # upper bounds at the COVERED member counts: uncovered agents appear
        # in no feasible committee, so a real distribution can never place
        # mass on them — leaving uncoverable types free lets the LP park
        # quota pressure there and inflates the bound (measured +0.37 of
        # spurious headroom on singleton types at n=800)
        res = robust_linprog(
            c, A_ub=A_ub, b_ub=b_ub,
            A_eq=np.concatenate([np.ones(T), [0.0]])[None, :],
            b_eq=[float(red.k)],
            bounds=[(lo[t], cnt_t[t]) for t in range(T)] + [(0, None)],
        )
        if res.status != 0:
            raise SelectionError(
                f"level-{len(levels) + 1} witness LP failed: {res.message}"
            )
        y = np.maximum(-np.asarray(res.ineqlin.marginals)[2 * F :], 0.0)
        w_t = np.zeros(T)
        w_t[idxr] = y
        # per-agent weights: y_t per member (the stage dual makes
        # Σ y_t·cnt_t ≈ 1 — the z column's coefficients are the covered
        # counts); support only covered remaining agents
        w = np.where(covered, w_t[red.type_id], 0.0)
        lam_t = np.zeros(T)
        if res.lower is not None and res.lower.marginals is not None:
            lam_t = np.maximum(np.asarray(res.lower.marginals)[:T], 0.0)
        lam_t = np.where(fixed_mask, lam_t, 0.0)
        total = w.sum()
        if total <= 0:
            w = np.where(covered & remaining[red.type_id], 1.0, 0.0)
            total = w.sum()
            lam_t[:] = 0.0
        w = w / total
        lam_t = lam_t / total
        # the fractional stage optimum is itself a valid upper bound (any
        # feasible distribution's marginal lies in the floored polytope);
        # it is tight deep in the profile where the Lagrangian MILP bound
        # has an integrality duality gap — but it shares the marginal-
        # relaxation viewpoint with the production solver, so the MILP
        # bound below is the fully independent one
        marginal_upper = float(res.x[T])

        # Lagrangian MILP bound, tightened by a few projected-subgradient
        # steps on λ (each step one exact MILP): the one-shot LP-dual λ is
        # optimal for the FRACTIONAL problem, not the Lagrangian dual of
        # the integer one
        def milp_bound(lam):
            u = w + np.where(covered, lam[red.type_id], 0.0)
            panel, _value, raw = oracle._milp_maximize_with_bound(u)
            return float(raw) - float(np.sum(lam * fixed_floor * cnt_t)), panel

        upper_milp, panel = milp_bound(lam_t)
        if fixed_mask.any() and upper_milp > lvl + level_tol:
            # projected subgradient with backtracking: step from the best λ
            # found so far; a worsening step reverts (λ AND its argmax
            # panel, which seeds the next subgradient) and halves the step —
            # continuing from the worse point spent the remaining MILP calls
            # exploring a degraded region
            lam_best, panel_best = lam_t.copy(), panel
            lam = lam_t.copy()
            step = 1.0
            for _ in range(8):
                # subgradient of the Lagrangian dual at λ: the floor slack
                # of the MILP's argmax committee
                x_cnt = np.bincount(
                    red.type_id[np.asarray(panel, dtype=int)], minlength=T
                ).astype(np.float64)
                g = np.where(fixed_mask, x_cnt - fixed_floor * cnt_t, 0.0)
                if not np.any(g):
                    break
                lam = np.maximum(lam - step * g / max(np.abs(g).max(), 1.0) * 0.1, 0.0)
                val, panel = milp_bound(lam)
                if val < upper_milp - 1e-12:
                    upper_milp, lam_best, panel_best = val, lam.copy(), panel
                else:
                    lam, panel = lam_best.copy(), panel_best
                    step *= 0.5
                    if step < 0.05:
                        break

        upper = min(upper_milp, marginal_upper)
        gap = upper - lvl
        gap_milp = upper_milp - lvl
        worst_gap = max(worst_gap, gap)
        worst_gap_milp = max(worst_gap_milp, gap_milp)
        levels.append(
            {
                "achieved": round(lvl, 6),
                "certified_upper": round(upper, 6),
                "milp_upper": round(upper_milp, 6),
                "marginal_upper": round(marginal_upper, 6),
                "gap": round(gap, 6),
                "gap_milp": round(gap_milp, 6),
                "types": int(S.sum()),
            }
        )
        fixed_mask |= S
        # floor each fixed type at its own ACHIEVED value (not the level
        # min): flooring a 565-type prefix even 1e-3 low frees ~0.7 agents
        # of aggregate mass, which the polytope concentrates onto later
        # SINGLETON types (+0.5 of spurious headroom measured at n=800).
        # Our allocation satisfies these floors exactly, so the audited
        # claim stays valid: each level is optimal GIVEN the achieved
        # earlier values — the same conditional semantics as the
        # reference's per-stage Gurobi dual-gap certificate.
        fixed_floor = np.where(S, np.maximum(v_t - 1e-9, 0.0), fixed_floor)
        remaining &= ~S
    return {
        "levels": levels,
        "n_levels": len(levels),
        "worst_gap": round(worst_gap, 6),
        "worst_gap_milp": round(worst_gap_milp, 6),
        "all_within_tol": bool(worst_gap <= level_tol),
        "audited_types": int(fixed_mask.sum()),
    }


def audit_second_level(
    dense,
    allocation: np.ndarray,
    covered: Optional[np.ndarray] = None,
    level_tol: float = 1e-3,
) -> dict:
    """Level-2 view of :func:`audit_leximin_profile` (VERDICT r3 #6's
    second-level-audit criterion): the level-1 set is floored at the
    certified level-1 value and the second level is bounded by the
    Lagrangian-tightened exact MILP witness."""
    prof = audit_leximin_profile(
        dense, allocation, covered=covered, level_tol=level_tol, max_levels=2
    )
    if prof["n_levels"] < 2:
        # None throughout: a single-level profile has no second level to
        # certify — 0.0 would read as a perfect certificate downstream
        return {
            "achieved_level2": None, "certified_level2_upper": None,
            "level2_gap": None,
            "level1_set_types": prof["levels"][0]["types"] if prof["levels"] else 0,
        }
    l2 = prof["levels"][1]
    return {
        "achieved_level2": l2["achieved"],
        "certified_level2_upper": l2["certified_upper"],
        "level2_gap": l2["gap"],
        "level1_set_types": prof["levels"][0]["types"],
    }
