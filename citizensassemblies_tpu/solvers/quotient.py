"""Household quotient reduction: type-space LEXIMIN under household constraints.

The reference treats household ("same address") constraints as a reason to stay
in agent space forever (its ILPs simply add ≤1-per-household rows,
``leximin.py:211-221``), which makes household runs as slow as the unconstrained
ones. But households preserve a *quotient* symmetry the agent-space view hides:

* Group agents by feature row → base types (as in the unconstrained reduction).
* Group households by the **multiset of their members' base types** → household
  *classes*; class ``c`` has ``m_c`` structurally identical households.
* Two agents are interchangeable (an instance automorphism maps one to the
  other) iff they have the same base type AND their households belong to the
  same class — the orbits are (class, base type) pairs.

The leximin allocation is the unique optimum of a symmetric problem, hence
orbit-constant, so the problem collapses onto orbits exactly as the
unconstrained one collapses onto types. The key structural fact making the
existing type-space machinery reusable *unchanged*:

    A per-orbit selection count vector ``x`` is realizable by a
    household-disjoint panel  ⇔  it satisfies the feature quotas, ``Σx = k``,
    and the per-class cap ``Σ_{t ∈ c} x_{c,t} ≤ m_c``.

(⇐: pick ``Σ_t x_{c,t} ≤ m_c`` distinct class-``c`` households and give
``x_{c,t}`` of them type-``t`` duty — every class-``c`` household has a member
of every type in the class multiset, so any assignment works. ⇒: a
household-disjoint panel touches each household at most once.)

The class caps are plain one-sided quota rows, so the whole pipeline —
enumeration, relaxation leximin, probe certification, composition CG, face
decomposition, native B&B pricing — runs on an **augmented instance** whose
incidence matrix gains one "household class" category (one-hot class
membership, quotas ``[0, m_c]``). Distinct augmented rows ARE the orbits, and
the orbit sizes (``m_c·r_{c,t}`` agents) fall out of the standard
``TypeReduction`` automatically. Only panel *realization* — turning per-orbit
counts into concrete members — needs to know about households: within one
panel, picks across a class's orbits must land in distinct households (see
``compositions.greedy_decompose`` / ``decompose_with_pricing``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, HostView


@dataclasses.dataclass
class HouseholdQuotient:
    """The augmented instance plus the household bookkeeping realization needs."""

    dense_aug: DenseInstance
    households: np.ndarray  # int32[n] compacted household id per agent
    class_of_household: np.ndarray  # int32[H] class id per household
    class_size: np.ndarray  # int32[C] households per class (m_c)
    class_feature_base: int  # first augmented column index (= original F)
    n_classes: int


def build_household_quotient(
    dense: DenseInstance, households: np.ndarray
) -> HouseholdQuotient:
    """Build the augmented instance for the household quotient.

    ``households`` is any int array of group labels (as produced by
    ``core.instance.compute_households``); it is compacted to 0..H-1.
    """
    A = dense.A_np
    n, F = A.shape
    hh = np.asarray(households)
    assert hh.shape == (n,), "households must label every agent"
    _, hh = np.unique(hh, return_inverse=True)
    H = int(hh.max()) + 1 if n else 0

    # base types by feature row (the unconstrained reduction's grouping)
    _, base_type = np.unique(A, axis=0, return_inverse=True)

    # class signature per household: sorted multiset of member base types.
    # Size-1 households of the same base type share a class, so singleton
    # agents keep collapsing onto types instead of splintering into
    # per-agent orbits.
    members_of_hh: Dict[int, list] = {h: [] for h in range(H)}
    for i in range(n):
        members_of_hh[int(hh[i])].append(int(base_type[i]))
    sig_to_class: Dict[Tuple[int, ...], int] = {}
    class_of_household = np.zeros(H, dtype=np.int32)
    for h in range(H):
        sig = tuple(sorted(members_of_hh[h]))
        if sig not in sig_to_class:
            sig_to_class[sig] = len(sig_to_class)
        class_of_household[h] = sig_to_class[sig]
    C = len(sig_to_class)
    class_size = np.bincount(class_of_household, minlength=C).astype(np.int32)

    cls_of_agent = class_of_household[hh]
    A_aug = np.zeros((n, F + C), dtype=bool)
    A_aug[:, :F] = A
    A_aug[np.arange(n), F + cls_of_agent] = True

    qmin_aug = np.concatenate([dense.qmin_np, np.zeros(C, dtype=np.int32)])
    qmax_aug = np.concatenate([dense.qmax_np, class_size])
    cat_aug = np.concatenate(
        [
            np.asarray(dense.cat_of_feature, dtype=np.int32),
            np.full(C, dense.n_categories, dtype=np.int32),
        ]
    )
    dense_aug = DenseInstance(
        A=jnp.asarray(A_aug),
        qmin=jnp.asarray(qmin_aug, dtype=jnp.int32),
        qmax=jnp.asarray(qmax_aug, dtype=jnp.int32),
        cat_of_feature=jnp.asarray(cat_aug, dtype=jnp.int32),
        k=dense.k,
        n_categories=dense.n_categories + 1,
        host=HostView(A_aug, qmin_aug.astype(np.int32), qmax_aug.astype(np.int32)),
    )
    return HouseholdQuotient(
        dense_aug=dense_aug,
        households=hh.astype(np.int32),
        class_of_household=class_of_household,
        class_size=class_size,
        class_feature_base=F,
        n_classes=C,
    )
