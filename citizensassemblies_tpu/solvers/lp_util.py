"""Shared host-LP plumbing for the type-space solvers.

scipy's HiGHS front-end occasionally declares *feasible* LPs infeasible when
presolve encounters rows that are tight to within its tolerance — observed on
leximin stage LPs whose fixed-type floors sit 1e-9 below an attained optimum
(the witness point violated no constraint by more than 2e-14 yet both
``method="highs"`` and ``"highs-ipm"`` reported infeasibility; re-solving with
``presolve=False`` found the optimum). :func:`robust_linprog` retries across
presolve settings and methods before giving up, so borderline-degenerate
stages never abort an otherwise-exact solve.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import scipy.optimize


def robust_linprog(
    c,
    A_ub=None,
    b_ub=None,
    A_eq=None,
    b_eq=None,
    bounds=None,
    methods: Sequence[str] = ("highs", "highs-ipm"),
) -> scipy.optimize.OptimizeResult:
    """``scipy.optimize.linprog`` with a presolve/method retry ladder.

    Tries each method with presolve on, then off; returns the first optimal
    result, else the last attempt (caller checks ``res.status``).
    """
    assert methods, "need at least one LP method"
    last = None
    for method in methods:
        for presolve in (True, False):
            res = scipy.optimize.linprog(
                c,
                A_ub=A_ub,
                b_ub=b_ub,
                A_eq=A_eq,
                b_eq=b_eq,
                bounds=bounds,
                method=method,
                options=None if presolve else {"presolve": False},
            )
            if res.status == 0:
                return res
            last = res
    return last


def probe_confirm_tranche(
    face_max: Callable[[np.ndarray], Optional[float]],
    objectives: np.ndarray,
    z: float,
    probe_tol: float,
    allowances: np.ndarray,
) -> np.ndarray:
    """Certify which leximin tranche candidates are capped at ``z`` over a
    stage's optimal face.

    ``face_max(w)`` maximizes ``w`` over the face (every candidate's own value
    is ≥ z there); ``objectives[i]`` is candidate i's value functional;
    ``allowances[i]`` bounds the spurious headroom constraint slack can grant
    candidate i (see the callers' slack-gain derivations). One group LP over
    ``Σ objectives`` certifies every candidate at once when its optimum is
    ``|cand|·z`` up to one shared tolerance — since each term is ≥ z on the
    face, a sum bound of ``n·z + δ`` caps every single term at ``z + δ``;
    per-candidate probes resolve disagreement. Returns a bool mask.
    """
    n = len(objectives)
    confirmed = np.zeros(n, dtype=bool)
    if n == 0:
        return confirmed
    allowances = np.asarray(allowances, dtype=np.float64)
    # An *infeasible* face (face_max -inf) means no point attains
    # min ≥ z − slack: the solver-reported stage optimum z slightly
    # overstates the true optimum (its own feasibility tolerance), so
    # nothing can exceed z materially — certify rather than stall into the
    # dual heuristic. Any other solver failure (face_max None) certifies
    # nothing: a numerical breakdown is not evidence of tightness.
    got = face_max(np.sum(objectives, axis=0))
    if got == -np.inf or (
        got is not None and got <= n * z + probe_tol + float(allowances.min())
    ):
        confirmed[:] = True
        return confirmed
    for i in range(n):
        got = face_max(objectives[i])
        if got == -np.inf or (
            got is not None and got <= z + probe_tol + float(allowances[i])
        ):
            confirmed[i] = True
    return confirmed
