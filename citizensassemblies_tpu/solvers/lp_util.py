"""Shared host-LP plumbing for the type-space solvers.

scipy's HiGHS front-end occasionally declares *feasible* LPs infeasible when
presolve encounters rows that are tight to within its tolerance — observed on
leximin stage LPs whose fixed-type floors sit 1e-9 below an attained optimum
(the witness point violated no constraint by more than 2e-14 yet both
``method="highs"`` and ``"highs-ipm"`` reported infeasibility; re-solving with
``presolve=False`` found the optimum). :func:`robust_linprog` retries across
presolve settings and methods before giving up, so borderline-degenerate
stages never abort an otherwise-exact solve.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np
import scipy.optimize


def robust_linprog(
    c,
    A_ub=None,
    b_ub=None,
    A_eq=None,
    b_eq=None,
    bounds=None,
    methods: Sequence[str] = ("highs", "highs-ipm"),
) -> scipy.optimize.OptimizeResult:
    """``scipy.optimize.linprog`` with a presolve/method retry ladder.

    Tries each method with presolve on, then off; returns the first optimal
    result, else the last attempt (caller checks ``res.status``).
    """
    assert methods, "need at least one LP method"
    last = None
    for method in methods:
        for presolve in (True, False):
            res = scipy.optimize.linprog(
                c,
                A_ub=A_ub,
                b_ub=b_ub,
                A_eq=A_eq,
                b_eq=b_eq,
                bounds=bounds,
                method=method,
                options=None if presolve else {"presolve": False},
            )
            if res.status == 0:
                return res
            last = res
    return last


#: allowances beyond this are clamped before use: a certificate judged "up to
#: the allowance" is only meaningful while the allowance stays well inside the
#: framework's 1e-3 L∞ acceptance bar — an escalated slack ladder can push the
#: raw slack-gain for a rare type to ~1e-2, and certifying at that tolerance
#: would fix a genuinely loose type below its true leximin value.
ALLOWANCE_CAP = 1e-4


def probe_confirm_tranche(
    face_max: Callable[[np.ndarray], Optional[float]],
    objectives: np.ndarray,
    z: float,
    probe_tol: float,
    allowances: np.ndarray,
    term_deficit: float = 0.0,
    log: Optional[Callable[[str], object]] = None,
) -> np.ndarray:
    """Certify which leximin tranche candidates are capped at ``z`` over a
    stage's optimal face.

    ``face_max(w)`` maximizes ``w`` over the face; ``objectives[i]`` is
    candidate i's value functional; ``allowances[i]`` bounds the spurious
    headroom constraint slack can grant candidate i (see the callers'
    slack-gain derivations; clamped to :data:`ALLOWANCE_CAP` so a certificate
    never exceeds a tolerance material against the 1e-3 bar);
    ``term_deficit`` is how far below ``z`` a candidate's value may sit on the
    face (the callers relax the face floors to ``z − margin − slack``, so each
    term is only ≥ ``z − term_deficit`` there).

    One group LP over ``Σ objectives`` certifies every candidate at once: a
    sum bound of ``n·z + δ`` caps each term at ``z + δ + (n−1)·term_deficit``
    (the other ``n−1`` terms can each sit ``term_deficit`` below ``z``), so
    the group test passes only when ``δ ≤ probe_tol + min_allowance −
    (n−1)·term_deficit`` — a budget that shrinks with tranche size and is
    skipped when non-positive. Per-candidate probes resolve disagreement.

    An *infeasible* face from the group probe is never taken as evidence of
    tightness (this module's own header documents HiGHS falsely declaring
    feasible LPs infeasible): it falls through to the per-candidate probes.
    A per-candidate infeasible face does certify — the face provably contains
    the just-computed stage optimum, so status-2 there means the solver's own
    tolerance overstates ``z`` — but the event is logged so an
    infeasibility-driven fix is visible in run logs. Any other solver failure
    (``face_max`` None) certifies nothing. Returns a bool mask.
    """
    n = len(objectives)
    confirmed = np.zeros(n, dtype=bool)
    if n == 0:
        return confirmed
    allowances = np.minimum(
        np.asarray(allowances, dtype=np.float64), ALLOWANCE_CAP
    )
    group_budget = probe_tol + float(allowances.min()) - (n - 1) * term_deficit
    if n > 1 and group_budget > 0.0:
        got = face_max(np.sum(objectives, axis=0))
        if got is not None and got != -np.inf and got <= n * z + group_budget:
            confirmed[:] = True
            return confirmed
    infeasible_fixes = 0
    for i in range(n):
        got = face_max(objectives[i])
        if got == -np.inf:
            confirmed[i] = True
            infeasible_fixes += 1
        elif got is not None and got <= z + probe_tol + float(allowances[i]):
            confirmed[i] = True
    if infeasible_fixes and log is not None:
        log(
            f"  probe: {infeasible_fixes}/{n} candidate(s) certified via an "
            f"infeasible probe face at z={z:.6f} (solver-tolerance overstatement)."
        )
    return confirmed
