"""Shared host-LP plumbing for the type-space solvers.

scipy's HiGHS front-end occasionally declares *feasible* LPs infeasible when
presolve encounters rows that are tight to within its tolerance — observed on
leximin stage LPs whose fixed-type floors sit 1e-9 below an attained optimum
(the witness point violated no constraint by more than 2e-14 yet both
``method="highs"`` and ``"highs-ipm"`` reported infeasibility; re-solving with
``presolve=False`` found the optimum). :func:`robust_linprog` retries across
presolve settings and methods before giving up, so borderline-degenerate
stages never abort an otherwise-exact solve.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np
import scipy.optimize


def robust_linprog(
    c,
    A_ub=None,
    b_ub=None,
    A_eq=None,
    b_eq=None,
    bounds=None,
    methods: Sequence[str] = ("highs", "highs-ipm"),
) -> scipy.optimize.OptimizeResult:
    """``scipy.optimize.linprog`` with a presolve/method retry ladder.

    Tries each method with presolve on, then off; returns the first optimal
    result, else the last attempt (caller checks ``res.status``).
    """
    assert methods, "need at least one LP method"
    last = None
    for method in methods:
        for presolve in (True, False):
            res = scipy.optimize.linprog(
                c,
                A_ub=A_ub,
                b_ub=b_ub,
                A_eq=A_eq,
                b_eq=b_eq,
                bounds=bounds,
                method=method,
                options=None if presolve else {"presolve": False},
            )
            if res.status == 0:
                return res
            last = res
    return last


#: allowances beyond this are clamped before use: a certificate judged "up to
#: the allowance" is only meaningful while the allowance stays well inside the
#: framework's 1e-3 L∞ acceptance bar — an escalated slack ladder can push the
#: raw slack-gain for a rare type to ~1e-2, and certifying at that tolerance
#: would fix a genuinely loose type below its true leximin value.
ALLOWANCE_CAP = 1e-4


def probe_confirm_tranche(
    face_max: Callable[[np.ndarray], Tuple[Optional[float], Optional[np.ndarray]]],
    objectives: np.ndarray,
    z: float,
    probe_tol: float,
    allowances: np.ndarray,
    term_deficit: float = 0.0,
    log: Optional[Callable[[str], object]] = None,
    face_max_relaxed: Optional[
        Callable[[np.ndarray], Tuple[Optional[float], Optional[np.ndarray]]]
    ] = None,
    presumed_loose: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Certify which leximin tranche candidates are capped at ``z`` over a
    stage's optimal face.

    ``face_max(w)`` maximizes ``w`` over the face and returns ``(value,
    x_opt)`` — the optimizer feeds the witness elimination below;
    ``objectives[i]`` is candidate i's value functional; ``allowances[i]``
    bounds the spurious headroom constraint slack can grant candidate i (see
    the callers' slack-gain derivations; clamped to :data:`ALLOWANCE_CAP` so
    a certificate never exceeds a tolerance material against the 1e-3 bar);
    ``term_deficit`` is how far below ``z`` a candidate's value may sit on the
    face (the callers relax the face floors to ``z − margin − slack``, so each
    term is only ≥ ``z − term_deficit`` there).

    Group LPs certify many candidates per solve: a sum bound of ``g·z + δ``
    over a chunk caps each member at ``z + δ + (g−1)·term_deficit`` (the
    other members can each sit ``term_deficit`` below ``z``), and since the
    face's freed slack can concentrate on ONE member, ``δ`` must absorb the
    chunk's LARGEST allowance — sound only when every member's own
    allowance covers it. Chunks therefore group candidates of equal
    allowance (≈ equal pool size), sized so the ``(g−1)·term_deficit``
    inflation stays immaterial.

    Disagreeing chunks resolve by **witness elimination**, not per-candidate
    probes: the failed group LP's own optimizer ``x*`` values every candidate
    at once (``objectives[i]·x*``), and any candidate above the certificate
    bound at a *feasible face point* is thereby witnessed loose — drop it and
    re-probe the survivors. Each iteration removes at least one member (the
    argmax when none crosses the bound), so a tranche with ``l`` loose
    candidates costs ``O(l)`` group LPs instead of one LP per member (a
    mild-skew sf_e seed paid ~2500 per-candidate probe LPs ≈ 25–47 s under
    the flat scheme; elimination cuts the stage cost to a handful of LPs).
    A dropped candidate is merely deferred to a later stage — dropping can
    never certify, so soundness is unaffected. A whole-tranche pre-probe at
    the MINIMUM allowance (within every member's own budget) settles the
    all-tight case — the common one — in a single LP even across mixed
    allowances.

    An *infeasible* face from a group probe is never taken as evidence of
    tightness (this module's own header documents HiGHS falsely declaring
    feasible LPs infeasible): it falls through to the per-candidate probes.
    ``presumed_loose`` (bool mask, same length as ``objectives``) marks
    candidates a device prescreen has already WITNESSED loose at a
    float64-validated face point (``compositions._batched_probe_prescreen``):
    they are excluded from every probe and left unconfirmed — identical
    outcome to probing them (a genuinely loose candidate can never be
    confirmed; it is deferred to a later stage), minus the host LPs. The
    mask can only REDUCE the LP count, never add a confirmation, so
    soundness is untouched; with no mask (or an all-False one) the behavior
    is bit-identical to the unscreened scheme.

    A per-candidate infeasible face certifies only after the face itself is
    confirmed non-empty (one zero-objective feasibility solve, cached per
    tranche) AND, when the caller supplies ``face_max_relaxed`` (the same
    maximization over a slightly enlarged face — a superset, so its optimum
    upper-bounds the face optimum), a retry on that enlarged face also fails
    to produce a finite value. A finite retry value is decisive either way:
    within budget it is a genuine certificate; above budget it is genuine
    headroom and nothing is certified — so an objective-specific numerical
    failure can no longer fix a loose candidate. Only when the retry is also
    infeasible/failed is status-2 on a non-empty face read as a solver
    mis-report ("nothing exceeds z materially"), and the event is logged. If the face is genuinely empty — the reported ``z``
    overstates the true stage optimum by more than the face relaxation —
    nothing is certified: an empty face carries no tightness information,
    and falsely confirming would fix loose candidates at an understated
    value. Any other solver failure (``face_max`` None) certifies nothing.
    Returns a bool mask.
    """
    n = len(objectives)
    confirmed = np.zeros(n, dtype=bool)
    if n == 0:
        return confirmed
    allowances = np.minimum(
        np.asarray(allowances, dtype=np.float64), ALLOWANCE_CAP
    )

    infeasible_fixes = 0
    uncertified_drops = 0
    face_state = {"checked": False, "empty": False}

    def probe_one(i: int) -> None:
        nonlocal infeasible_fixes
        got, _x = face_max(objectives[i])
        if got == -np.inf:
            if not face_state["checked"]:
                face_state["checked"] = True
                z0, _ = face_max(np.zeros_like(objectives[i]))
                face_state["empty"] = z0 == -np.inf
                if face_state["empty"] and log is not None:
                    log(
                        f"  probe: face at z={z:.6f} is empty (reported stage "
                        "optimum overstates the true one beyond the face "
                        "relaxation) — certifying nothing."
                    )
            if face_state["empty"]:
                # a numerically-empty base face (solver-reported z overstates
                # the true stage optimum by more than the face relaxation)
                # still admits a sound certificate via the relaxed SUPERSET
                # face, which contains the true optimal face — without this,
                # an empty face degrades the whole stage to per-candidate
                # probes ending in the uncertified dual heuristic
                if face_max_relaxed is not None:
                    rv, _ = face_max_relaxed(objectives[i])
                    if (
                        rv is not None
                        and rv != -np.inf
                        and rv <= z + probe_tol + float(allowances[i])
                    ):
                        confirmed[i] = True
                return
            if face_max_relaxed is not None:
                rv, _ = face_max_relaxed(objectives[i])
                if rv is not None and rv != -np.inf:
                    # superset optimum ≥ face optimum: within budget it
                    # certifies, above budget it is genuine headroom —
                    # either way the infeasible report was objective-specific
                    # and must not certify on its own
                    if rv <= z + probe_tol + float(allowances[i]):
                        confirmed[i] = True
                    return
            confirmed[i] = True
            infeasible_fixes += 1
        elif got is not None and got <= z + probe_tol + float(allowances[i]):
            confirmed[i] = True

    # Chunked group probing over EQUAL-allowance groups. The sound bound for
    # a chunk probe: constraint slack lets the whole tranche's freed mass
    # concentrate on ONE member, so a passing sum certifies each member only
    # at ``z + probe_tol + max_allow(chunk) + (g−1)·term_deficit`` — usable
    # only when every member's own allowance covers ``max_allow``, i.e. when
    # the chunk's allowances are (near-)identical. Allowances are
    # ``slack_gain / m_t`` with small-integer ``m_t``, so grouping by exact
    # allowance value yields ~#distinct-pool-sizes probes per tranche
    # instead of one per candidate; chunk size is additionally capped so the
    # ``(g−1)·term_deficit`` inflation stays immaterial (≤ 10·probe_tol).
    max_infl = 10.0 * probe_tol

    def resolve(chunk: np.ndarray, a_i: float) -> None:
        """Certify an equal-allowance chunk by witness elimination (see the
        docstring): probe the sum; on disagreement, drop members the group
        optimizer itself witnesses loose and re-probe the survivors."""
        active = np.asarray(chunk)
        while len(active) > 1:
            g = len(active)
            got, xopt = face_max(np.sum(objectives[active], axis=0))
            if got is None or got == -np.inf or xopt is None:
                # infeasible/failed group face is never evidence of
                # tightness: resolve the remaining members individually
                # (probe_one owns the empty-face and superset-retry logic)
                for idx in active:
                    probe_one(int(idx))
                return
            if got <= g * z + probe_tol + a_i:
                confirmed[active] = True
                return
            vals = objectives[active] @ xopt
            # a candidate above the certificate bound at a FEASIBLE face
            # point is witnessed loose — dropping defers it to a later
            # stage, which can never falsely certify
            loose = vals > z + probe_tol + a_i
            if not loose.any():
                # the excess is spread below any individual bound: drop the
                # largest value so every iteration removes at least one.
                # Unlike a witnessed drop, this argmax drop carries NO
                # evidence of looseness — a genuinely tight candidate could
                # be deferred and the stage would silently lean on the
                # uncertified dual-progress guard. Spend one bounded LP per
                # such drop (probe_one) to certify it outright; drops that
                # still fail their probe are counted and logged so the
                # certification-coverage loss is visible, not silent.
                loose = vals >= vals.max() - 1e-12
                for idx in active[loose]:
                    probe_one(int(idx))
                    if not confirmed[int(idx)]:
                        uncertified_drops += 1
            active = active[~loose]
        if len(active) == 1:
            probe_one(int(active[0]))

    # whole-tranche pre-probe at the MINIMUM allowance: certifying every
    # member at min_allow is within each member's own budget, so one passing
    # LP settles the entire tranche even across mixed allowances (it may
    # spuriously fail when the freed slack genuinely concentrates — the
    # equal-allowance chunks below then recover the precise verdicts).
    # Prescreen-witnessed loose candidates are excluded up front: they would
    # make the group sum fail for certain, and probing them individually
    # could only repeat what the witness already proved.
    order = np.argsort(-allowances)
    if presumed_loose is not None:
        skip = np.asarray(presumed_loose, dtype=bool)
        order = order[~skip[order]]
    n_act = len(order)
    if n_act == 0:
        return confirmed
    if n_act > 1 and (n_act - 1) * term_deficit <= max_infl:
        got, _x = face_max(np.sum(objectives[order], axis=0))
        if (
            got is not None
            and got != -np.inf
            and got <= n_act * z + probe_tol + float(allowances[order].min())
        ):
            confirmed[order] = True
            return confirmed
    i = 0
    while i < n_act:
        j = i + 1
        a_i = float(allowances[order[i]])
        while (
            j < n_act
            and j - i < 256
            and abs(float(allowances[order[j]]) - a_i) <= 1e-12
            and (j - i) * term_deficit <= max_infl
        ):
            j += 1
        resolve(order[i:j], a_i)
        i = j
    if infeasible_fixes and log is not None:
        log(
            f"  probe: {infeasible_fixes}/{n} candidate(s) certified via an "
            f"infeasible probe face at z={z:.6f} (solver-tolerance overstatement)."
        )
    if uncertified_drops and log is not None:
        log(
            f"  probe: {uncertified_drops}/{n} argmax-dropped candidate(s) at "
            f"z={z:.6f} remain uncertified after an individual probe "
            "(deferred to a later stage; certification coverage reduced)."
        )
    return confirmed
