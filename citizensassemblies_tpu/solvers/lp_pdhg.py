"""Device-side LP solver: restarted, preconditioned PDHG (a PDLP-style
first-order method) in pure JAX.

The reference solves its two recurring LP shapes with Gurobi's barrier method
on the host (the dual leximin LP, ``leximin.py:300-328``, and the final primal
LP, ``leximin.py:453-464``). On TPU we solve them on device instead: dense
matvecs are MXU work, every iteration is a handful of GEMVs, and the whole
solve stays jitted — no host↔device ping-pong per column-generation round.

Method: primal-dual hybrid gradient (Chambolle–Pock) on the saddle problem

    min_{x ≥ 0} max_{λ ≥ 0, μ}  cᵀx + λᵀ(Gx − h) + μᵀ(Ax − b)

with (i) Ruiz equilibration of the stacked constraint matrix K = [G; A] so a
single scalar step size fits all rows, (ii) iterate averaging, and (iii)
restarts to the averaged iterate whenever its KKT residual beats the current
iterate's — the restart scheme that gives PDLP its linear convergence on LPs.
Everything below runs in float32 (MXU-native); achieved KKT residuals of
~1e-6 comfortably clear the framework's EPS = 5e-4 fixing tolerance.

Termination is checked every ``cfg.pdhg_check_every`` iterations inside a
``lax.while_loop`` — compile once, reuse across all column-generation rounds
of the same padded shape.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.aot.store import aot_seeded
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.guards import no_implicit_transfers
from citizensassemblies_tpu.utils.precision import demote_operator, iterate_dtype


@dataclasses.dataclass
class LPSolution:
    """Result of a PDHG solve on ``min cᵀx s.t. Gx ≤ h, Ax = b, x ≥ 0``."""

    ok: bool
    x: np.ndarray
    lam: np.ndarray  # duals of Gx ≤ h (λ ≥ 0)
    mu: np.ndarray  # duals of Ax = b (free)
    objective: float
    iters: int
    kkt: float  # final combined relative KKT residual


# --- numerical sentinels (robust/) ------------------------------------------
# With ``Config.robust_sentinels`` on, every PDHG while_loop carries a
# per-lane quarantine flag: a block whose KKT residual goes non-finite is
# REJECTED (the carry freezes at the last finite iterate — the same select
# pattern as the batched engine's convergence masks), the lane exits with
# bit 1 set, and the wrapper re-solves it on the serial float64 host path.
# Bit 2 is the report-only stall flag: _STALL_BLOCKS consecutive checks
# without a new best residual. Zero-fault runs are bit-identical with the
# sentinel on or off (the selects always take the freshly-computed branch),
# and the flag is STATIC, so one run compiles exactly as many programs as
# before.

#: consecutive convergence checks without a new best residual before the
#: stall bit is reported (8k iterations at the default check_every=128)
_STALL_BLOCKS = 64

#: quarantine-flag bits
FLAG_POISONED = 1
FLAG_STALLED = 2


def sentinels_enabled(cfg: Optional[Config]) -> bool:
    cfg = cfg or default_config()
    return bool(getattr(cfg, "robust_sentinels", True))


def _ambient_log():
    """The ambient request's RunLog (for quarantine counters), or None —
    imported lazily to keep this module importable without the service."""
    from citizensassemblies_tpu.service.context import current_context

    ctx = current_context()
    return ctx.log if ctx is not None else None


def _sentinel_while(cond, block, state0):
    """Run ``while_loop(cond, block, state0)`` under the quarantine wrapper.

    ``state0`` is the unsentineled carry whose residual sits at index -2
    (the shared (…, it, res, omega) tail of every PDHG loop here). Returns
    ``(final_inner_state, flags)`` with flags an int32 bitmask.
    """
    import jax as _jax
    import jax.numpy as _jnp

    n = len(state0)

    def s_block(state):
        inner = state[:n]
        flags, best, since = state[n], state[n + 1], state[n + 2]
        new = block(inner)
        res_n = new[n - 2]
        ok = _jnp.isfinite(res_n)
        merged = tuple(_jnp.where(ok, a, b) for a, b in zip(new, inner))
        improved = ok & (res_n < best)
        best = _jnp.where(improved, res_n, best)
        since = _jnp.where(improved, _jnp.int32(0), since + 1)
        flags = flags | _jnp.where(ok, 0, FLAG_POISONED).astype(_jnp.int32)
        flags = flags | _jnp.where(
            since >= _STALL_BLOCKS, FLAG_STALLED, 0
        ).astype(_jnp.int32)
        return merged + (flags, best, since)

    def s_cond(state):
        return cond(state[:n]) & ((state[n] & FLAG_POISONED) == 0)

    s0 = tuple(state0) + (
        _jnp.int32(0), _jnp.float32(_jnp.inf), _jnp.int32(0),
    )
    out = _jax.lax.while_loop(s_cond, s_block, s0)
    return out[:n], out[n]


def _host_resolve_lp(c, G, h, A, b) -> Optional["LPSolution"]:
    """Serial float64 host re-solve of a quarantined lane (scipy/HiGHS via
    the presolve/method retry ladder). Returns None when the host solver
    also fails — the caller then ships the frozen iterate with ok=False."""
    from citizensassemblies_tpu.solvers.lp_util import robust_linprog

    c64 = np.asarray(c, dtype=np.float64)
    res = robust_linprog(
        c64,
        A_ub=np.asarray(G, dtype=np.float64),
        b_ub=np.asarray(h, dtype=np.float64),
        A_eq=np.asarray(A, dtype=np.float64),
        b_eq=np.asarray(b, dtype=np.float64),
        bounds=(0, None),
    )
    if res is None or res.status != 0:
        return None
    x = np.asarray(res.x, dtype=np.float64)
    lam = np.zeros(np.shape(G)[0])
    mu = np.zeros(np.shape(A)[0])
    try:
        # scipy/HiGHS marginals: ≤ 0 for A_ub rows of a min problem
        lam = np.maximum(-np.asarray(res.ineqlin.marginals, np.float64), 0.0)
        mu = -np.asarray(res.eqlin.marginals, np.float64)
    except Exception:  # marginals missing on some method fallbacks
        pass
    return LPSolution(
        ok=True, x=x, lam=lam, mu=mu, objective=float(c64 @ x), iters=-1,
        kkt=0.0,
    )


def _ruiz_equilibrate(K: jnp.ndarray, iters: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Diagonal row/column scalings d_r, d_c with D_r K D_c ≈ unit row/col
    ∞-norms (Ruiz 2001). Returns (d_r[m], d_c[nv])."""
    m, nv = K.shape
    d_r = jnp.ones(m, dtype=iterate_dtype(K.dtype))
    d_c = jnp.ones(nv, dtype=iterate_dtype(K.dtype))

    def body(_, carry):
        d_r, d_c = carry
        S = d_r[:, None] * K * d_c[None, :]
        # all-zero rows/columns (bucket padding) keep scale 1: dividing by
        # the clamped norm every sweep compounds to f32 overflow, and
        # 0 × inf turns the whole scaled matrix into NaNs
        rmax = jnp.max(jnp.abs(S), axis=1)
        cmax = jnp.max(jnp.abs(S), axis=0)
        rn = jnp.where(rmax > 0, jnp.sqrt(jnp.maximum(rmax, 1e-10)), 1.0)
        cn = jnp.where(cmax > 0, jnp.sqrt(jnp.maximum(cmax, 1e-10)), 1.0)
        return d_r / rn, d_c / cn

    d_r, d_c = jax.lax.fori_loop(0, iters, body, (d_r, d_c))
    return d_r, d_c


def _power_norm(K: jnp.ndarray, iters: int = 40) -> jnp.ndarray:
    """Estimate ‖K‖₂ by power iteration on KᵀK."""
    v = jnp.ones(K.shape[1], dtype=iterate_dtype(K.dtype)) / jnp.sqrt(K.shape[1])

    def body(_, v):
        w = K.T @ (K @ v)
        return w / (jnp.linalg.norm(w) + 1e-12)

    v = jax.lax.fori_loop(0, iters, body, v)
    return jnp.sqrt(jnp.linalg.norm(K.T @ (K @ v)) + 1e-12)


def _kkt_parts(c, G, h, A, b, x, lam, mu):
    """Primal infeasibility, dual infeasibility, and duality gap (absolute)."""
    pri_ineq = jnp.maximum(G @ x - h, 0.0)
    pri_eq = A @ x - b
    pri = jnp.sqrt(jnp.sum(pri_ineq**2) + jnp.sum(pri_eq**2))
    # dual residual: c + Gᵀλ + Aᵀμ must be ≥ 0 (complementary with x ≥ 0)
    grad = c + G.T @ lam + A.T @ mu
    dua = jnp.linalg.norm(jnp.minimum(grad, 0.0))
    pobj = c @ x
    dobj = -(lam @ h) - (mu @ b)
    gap = jnp.abs(pobj - dobj)
    return pri, dua, gap, pobj, dobj


def _kkt_residual(c, G, h, A, b, x, lam, mu, scale):
    """Combined relative KKT residual: primal infeasibility, dual
    infeasibility, and duality gap, each normalized by problem scale."""
    pri, dua, gap, pobj, dobj = _kkt_parts(c, G, h, A, b, x, lam, mu)
    return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))


def _pdhg_body(
    c, G, h, A, b, x0, lam0, mu0, tol,
    max_iters: int, check_every: int, sentinel: bool = False,
):
    m1, nv = G.shape
    m2 = A.shape[0]
    K = jnp.concatenate([G, A], axis=0)
    d_r, d_c = _ruiz_equilibrate(K)
    Ks = d_r[:, None] * K * d_c[None, :]
    # scaled data: variables x = D_c x̃, duals y = D_r ỹ
    cs = c * d_c
    hs = h * d_r[:m1]
    bs = b * d_r[m1:]
    Gs = Ks[:m1]
    As = Ks[m1:]

    norm = _power_norm(Ks)
    scale = 1.0 + jnp.linalg.norm(cs) + jnp.linalg.norm(hs) + jnp.linalg.norm(bs)

    # map the (unscaled) warm start into scaled coordinates: x = D_c x̃ and
    # y = D_r ỹ, so x̃₀ = x₀ / d_c and ỹ₀ = y₀ / d_r
    x = x0 / jnp.maximum(d_c, 1e-12)
    lam = jnp.maximum(lam0 / jnp.maximum(d_r[:m1], 1e-12), 0.0)
    mu = mu0 / jnp.maximum(d_r[m1:], 1e-12)

    def kkt(x, lam, mu):
        return _kkt_residual(cs, Gs, hs, As, bs, x, lam, mu, scale)

    def one_iter(carry, _):
        # running sums ride the carry: materializing the whole block
        # trajectory (check_every × problem-size arrays) tripled the
        # per-iteration HBM traffic for what is ultimately one mean
        x, lam, mu, xs, ls, ms, tau, sigma = carry
        grad = cs + Gs.T @ lam + As.T @ mu
        x_new = jnp.maximum(x - tau * grad, 0.0)
        xb = 2.0 * x_new - x
        lam_new = jnp.maximum(lam + sigma * (Gs @ xb - hs), 0.0)
        mu_new = mu + sigma * (As @ xb - bs)
        return (
            x_new, lam_new, mu_new, xs + x_new, ls + lam_new, ms + mu_new,
            tau, sigma,
        ), None

    def block(state):
        (x, lam, mu, x_av, lam_av, mu_av, it, res, omega) = state
        # PDLP-style primal weight: τ = 0.9ω/‖K‖, σ = 0.9/(ω‖K‖) keeps the
        # step-size product fixed (convergence guarantee) while ω balances
        # primal vs dual progress — a fixed ω = 1 plateaus two orders above
        # tolerance on the decomposition masters
        tau = 0.9 * omega / norm
        sigma = 0.9 / (omega * norm)
        x_in, lam_in, mu_in = x, lam, mu
        zero = (jnp.zeros_like(x), jnp.zeros_like(lam), jnp.zeros_like(mu))
        (x, lam, mu, xs, ls, ms, _, _), _ = jax.lax.scan(
            one_iter, (x, lam, mu) + zero + (tau, sigma), None,
            length=check_every,
        )
        # fresh running average over this block, blended with the carried one
        inv = 1.0 / check_every
        xa = (x_av + xs * inv) * 0.5
        la = (lam_av + ls * inv) * 0.5
        ma = (mu_av + ms * inv) * 0.5
        r_cur = kkt(x, lam, mu)
        r_avg = kkt(xa, la, ma)
        # restart to the averaged iterate when it is strictly better
        better = r_avg < r_cur
        x = jnp.where(better, xa, x)
        lam = jnp.where(better, la, lam)
        mu = jnp.where(better, ma, mu)
        res = jnp.minimum(r_cur, r_avg)
        # PDLP primal-weight update from the block's movement norms:
        # ω ← sqrt(ω · ‖Δ(λ,μ)‖/‖Δx‖) (θ = ½ log-blend), clipped — when the
        # duals move much more than the primal, shift step size toward the
        # primal, and vice versa
        dx = jnp.linalg.norm(x - x_in)
        dy = jnp.sqrt(
            jnp.sum((lam - lam_in) ** 2) + jnp.sum((mu - mu_in) ** 2)
        )
        moved = (dx > 1e-12) & (dy > 1e-12)
        omega_new = jnp.sqrt(omega * jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-4, 1e4))
        omega = jnp.where(moved, jnp.clip(omega_new, 1.0 / 64.0, 64.0), omega)
        return (x, lam, mu, xa, la, ma, it + check_every, res, omega)

    def cond(state):
        x, lam, mu, xa, la, ma, it, res, omega = state
        return (res > tol) & (it < max_iters)

    state0 = (
        x, lam, mu, x, lam, mu, jnp.int32(0), jnp.float32(jnp.inf),
        jnp.float32(1.0),
    )
    if sentinel:
        # non-finite carries freeze at the last finite iterate and exit the
        # lane with the poisoned flag set (see _sentinel_while) — the
        # all-finite trajectory is untouched, so zero-fault runs are
        # bit-identical to sentinel=False
        (x, lam, mu, _, _, _, it, res, _omega), flags = _sentinel_while(
            cond, block, state0
        )
    else:
        x, lam, mu, _, _, _, it, res, _omega = jax.lax.while_loop(
            cond, block, state0
        )

    # unscale
    x_out = x * d_c
    lam_out = lam * d_r[:m1]
    mu_out = mu * d_r[m1:]
    if sentinel:
        return x_out, lam_out, mu_out, it, res, flags
    return x_out, lam_out, mu_out, it, res


# the warm-start buffers (x0, lam0, mu0) are donated: they are loop-carried
# iterates — each call's outputs become the next call's warm start, and the
# wrappers below always materialize FRESH device arrays for them, so donation
# lets XLA reuse the input buffers for the matching-shaped outputs instead of
# allocating (and re-laying-out) a new carry every CG round. (CPU backends
# ignore donation with a one-time note; the contract is unchanged.) The
# undecorated ``_pdhg_body`` stays importable so the batched engine
# (``solvers/batch_lp.py``) can ``vmap`` the IDENTICAL iteration over a
# padded instance bucket — one math definition, two dispatch shapes.
_pdhg_core = aot_seeded(
    "lp_pdhg.pdhg_core",
    partial(
        jax.jit,
        static_argnames=("max_iters", "check_every", "sentinel"),
        donate_argnums=(5, 6, 7),
    )(_pdhg_body),
    static_argnames=("max_iters", "check_every", "sentinel"),
)


def solve_lp(
    c: np.ndarray,
    G: np.ndarray,
    h: np.ndarray,
    A: np.ndarray,
    b: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
) -> LPSolution:
    """Solve ``min cᵀx s.t. Gx ≤ h, Ax = b, x ≥ 0`` on device.

    ``warm`` is an optional (x, λ, μ) warm start — across column-generation
    rounds the dual LP only gains rows, so the previous optimum is an
    excellent starting point.
    """
    from citizensassemblies_tpu.robust import inject

    cfg = cfg or default_config()
    tol = float(tol if tol is not None else cfg.pdhg_tol)
    sent = sentinels_enabled(cfg)
    f32 = jnp.float32
    c_, G_, h_ = jnp.asarray(c, f32), jnp.asarray(G, f32), jnp.asarray(h, f32)
    A_, b_ = jnp.asarray(A, f32), jnp.asarray(b, f32)
    nv = c_.shape[0]
    m1, m2 = G_.shape[0], A_.shape[0]
    if warm is not None:
        x0_h = np.asarray(warm[0], np.float32)
        lam0_h = np.asarray(warm[1], np.float32)
        mu0_h = np.asarray(warm[2], np.float32)
    else:
        x0_h = np.zeros(nv, np.float32)
        lam0_h = np.zeros(m1, np.float32)
        mu0_h = np.zeros(m2, np.float32)
    log = _ambient_log()
    if inject.site("pdhg_nan", log):
        # chaos: poison the lane's warm start — the in-loop sentinel must
        # quarantine it and the host re-solve below must recover
        x0_h = x0_h.copy()
        x0_h[0] = np.nan
    x0 = jnp.asarray(x0_h)
    lam0 = jnp.asarray(lam0_h)
    mu0 = jnp.asarray(mu0_h)
    # inputs are explicitly materialized above (a bare np.float32 scalar for
    # tol would itself be an implicit transfer); inside the guard a stray
    # numpy operand re-uploaded per CG round raises
    tol_ = jnp.asarray(tol, jnp.float32)
    # graftgrade: the read-only operator matrices ride at bf16 when the
    # committed plan certifies them (lossless round-trip only, so the core's
    # f32 arithmetic is bit-identical after the first promote)
    G_ = demote_operator(G_, cfg, core="lp_pdhg.pdhg_core", arg=1, log=log)
    A_ = demote_operator(A_, cfg, core="lp_pdhg.pdhg_core", arg=3, log=log)
    with dispatch_span(
        "lp_pdhg.pdhg_core", cfg=cfg, nv=int(nv), m1=int(m1), m2=int(m2)
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = _pdhg_core(
                c_, G_, h_, A_, b_, x0, lam0, mu0, tol_,
                max_iters=int(cfg.pdhg_max_iters),
                check_every=int(cfg.pdhg_check_every),
                sentinel=sent,
            )
        x, lam, mu, it, res = out[:5]
        _ds.out = (x, lam, mu, it, res)
    flags = int(np.asarray(out[5])) if sent else 0
    if flags & FLAG_POISONED:
        # quarantine: the lane froze at its last finite iterate — re-solve
        # on the serial float64 host path (certified; NaN never escapes)
        if log is not None:
            log.count("sentinel_poisoned")
        host = _host_resolve_lp(c, G, h, A, b)
        if host is not None:
            if log is not None:
                log.count("sentinel_host_resolve")
            return host
    if flags & FLAG_STALLED and log is not None:
        log.count("sentinel_stalled")
    x = np.asarray(x, dtype=np.float64)
    lam = np.asarray(lam, dtype=np.float64)
    mu = np.asarray(mu, dtype=np.float64)
    res_f = float(res)
    return LPSolution(
        ok=bool(res_f <= tol * 4.0) and not (flags & FLAG_POISONED),
        x=x,
        lam=lam,
        mu=mu,
        objective=float(np.asarray(c, dtype=np.float64) @ x),
        iters=int(it),
        kkt=res_f,
    )


# --- structured two-sided decomposition master ------------------------------


def _two_sided_iterate(
    K_apply, KT_apply, cs_eps, hs_lo, hs_up, bs,
    p, eps, l_lo, l_up, mu, tol, max_iters: int, check_every: int,
    sentinel: bool = False,
):
    """The restart-to-average PDHG loop of the two-sided ε master, generic
    over the structured operator pair ``(K_apply, KT_apply)`` — ONE loop
    definition serving the dense core (resident scaled MT) and the ELL core
    (packed indices/values), so the sparse path cannot drift from the dense
    math. Inputs arrive in SCALED coordinates; returns the final scaled
    iterates plus ``(iters, res)``. The op sequence is exactly the dense
    core's original loop — the dense path stays bit-identical."""
    f32 = iterate_dtype(p.dtype)
    C = p.shape[0]

    # power iteration for ‖K‖ via the structured matvecs
    def pow_body(_, vv):
        p_, e_ = vv
        r_lo, r_up, r_eq = K_apply(p_, e_)
        g_p, g_e = KT_apply(r_lo, r_up, r_eq)
        nrm = jnp.sqrt(jnp.sum(g_p**2) + g_e**2) + 1e-12
        return g_p / nrm, g_e / nrm

    p0n = jnp.ones(C, dtype=f32) / jnp.sqrt(jnp.float32(C + 1))
    e0n = jnp.ones((), dtype=f32) / jnp.sqrt(jnp.float32(C + 1))
    pv, ev = jax.lax.fori_loop(0, 40, pow_body, (p0n, e0n))
    r_lo, r_up, r_eq = K_apply(pv, ev)
    g_p, g_e = KT_apply(r_lo, r_up, r_eq)
    norm = jnp.sqrt(jnp.sqrt(jnp.sum(g_p**2) + g_e**2) + 1e-12)

    scale = (
        1.0
        + jnp.abs(cs_eps)
        + jnp.sqrt(jnp.sum(hs_lo**2) + jnp.sum(hs_up**2))
        + jnp.abs(bs)
    )

    def kkt(p, eps, l_lo, l_up, mu):
        r_lo, r_up, r_eq = K_apply(p, eps)
        pri = jnp.sqrt(
            jnp.sum(jnp.maximum(r_lo - hs_lo, 0.0) ** 2)
            + jnp.sum(jnp.maximum(r_up - hs_up, 0.0) ** 2)
            + (r_eq - bs) ** 2
        )
        g_p, g_e = KT_apply(l_lo, l_up, mu)
        dua = jnp.sqrt(
            jnp.sum(jnp.minimum(g_p, 0.0) ** 2)
            + jnp.minimum(g_e + cs_eps, 0.0) ** 2
        )
        pobj = cs_eps * eps
        dobj = -(l_lo @ hs_lo) - (l_up @ hs_up) - mu * bs
        gap = jnp.abs(pobj - dobj)
        return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

    def one_iter(carry, _):
        (p, eps, l_lo, l_up, mu, ps, es, lls, lus, ms, tau, sigma) = carry
        g_p, g_e = KT_apply(l_lo, l_up, mu)
        p_new = jnp.maximum(p - tau * g_p, 0.0)
        eps_new = jnp.maximum(eps - tau * (g_e + cs_eps), 0.0)
        pb = 2.0 * p_new - p
        eb = 2.0 * eps_new - eps
        r_lo, r_up, r_eq = K_apply(pb, eb)
        l_lo_new = jnp.maximum(l_lo + sigma * (r_lo - hs_lo), 0.0)
        l_up_new = jnp.maximum(l_up + sigma * (r_up - hs_up), 0.0)
        mu_new = mu + sigma * (r_eq - bs)
        return (
            p_new, eps_new, l_lo_new, l_up_new, mu_new,
            ps + p_new, es + eps_new, lls + l_lo_new, lus + l_up_new,
            ms + mu_new, tau, sigma,
        ), None

    def block(state):
        (p, eps, l_lo, l_up, mu, p_av, e_av, ll_av, lu_av, m_av, it, res, omega) = state
        tau = 0.9 * omega / norm
        sigma = 0.9 / (omega * norm)
        p_in, ll_in, lu_in, mu_in = p, l_lo, l_up, mu
        zeros = (
            jnp.zeros_like(p), jnp.zeros_like(eps), jnp.zeros_like(l_lo),
            jnp.zeros_like(l_up), jnp.zeros_like(mu),
        )
        (p, eps, l_lo, l_up, mu, ps, es, lls, lus, ms, _, _), _ = jax.lax.scan(
            one_iter,
            (p, eps, l_lo, l_up, mu) + zeros + (tau, sigma),
            None,
            length=check_every,
        )
        inv = 1.0 / check_every
        pa = (p_av + ps * inv) * 0.5
        ea = (e_av + es * inv) * 0.5
        lla = (ll_av + lls * inv) * 0.5
        lua = (lu_av + lus * inv) * 0.5
        ma = (m_av + ms * inv) * 0.5
        r_cur = kkt(p, eps, l_lo, l_up, mu)
        r_avg = kkt(pa, ea, lla, lua, ma)
        better = r_avg < r_cur
        p = jnp.where(better, pa, p)
        eps = jnp.where(better, ea, eps)
        l_lo = jnp.where(better, lla, l_lo)
        l_up = jnp.where(better, lua, l_up)
        mu = jnp.where(better, ma, mu)
        res = jnp.minimum(r_cur, r_avg)
        dx = jnp.linalg.norm(p - p_in)
        dy = jnp.sqrt(
            jnp.sum((l_lo - ll_in) ** 2)
            + jnp.sum((l_up - lu_in) ** 2)
            + (mu - mu_in) ** 2
        )
        moved = (dx > 1e-12) & (dy > 1e-12)
        omega_new = jnp.sqrt(omega * jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-4, 1e4))
        omega = jnp.where(moved, jnp.clip(omega_new, 1.0 / 64.0, 64.0), omega)
        return (p, eps, l_lo, l_up, mu, pa, ea, lla, lua, ma, it + check_every, res, omega)

    def cond(state):
        return (state[11] > tol) & (state[10] < max_iters)

    state0 = (
        p, eps, l_lo, l_up, mu, p, eps, l_lo, l_up, mu,
        jnp.int32(0), jnp.float32(jnp.inf), jnp.float32(1.0),
    )
    if sentinel:
        # the shared (…, it, res, omega) carry tail puts res at index -2,
        # which is all the quarantine wrapper needs (see _sentinel_while)
        (p, eps, l_lo, l_up, mu, *_rest), flags = _sentinel_while(
            cond, block, state0
        )
        it, res = _rest[5], _rest[6]
        return p, eps, l_lo, l_up, mu, it, res, flags
    (p, eps, l_lo, l_up, mu, *_rest) = jax.lax.while_loop(cond, block, state0)
    it, res = _rest[5], _rest[6]
    return p, eps, l_lo, l_up, mu, it, res


# x0/lam0 donated as in ``_pdhg_core`` (mu0 is a scalar with no same-shaped
# output, so donating it would only be rejected)
@partial(
    jax.jit,
    static_argnames=("max_iters", "check_every", "sentinel"),
    donate_argnums=(3, 4),
)
def _pdhg_two_sided_core(
    MT, v, colmask, x0, lam0, mu0, tol, max_iters: int, check_every: int,
    sentinel: bool = False,
):
    """PDHG specialized to the face-decomposition master

        min ε  s.t.  v − ε ≤ MT p ≤ v + ε,  Σp = 1,  p ≥ 0, ε ≥ 0.

    The generic core materializes the stacked ``[[−MT, −1], [MT, −1]]``
    constraint matrix — 2× the bytes shipped through the TPU tunnel and 2×
    the HBM traffic per iteration, for rows that are exact negations. Here
    only MT is resident: each iteration computes ``u = MT @ p`` once and
    applies the ± structure arithmetically, and the Ruiz/power-norm
    preconditioning exploits that rows t and T+t have identical magnitudes
    (so one row scale serves both sides). Same restart-to-average scheme
    and KKT semantics as ``_pdhg_core``; returns ``(x, lam, mu, iters,
    res)`` with ``x = [p (C), ε]``, ``lam = [λ_lo (T), λ_up (T)]`` so
    callers recover the pricing duals ``w = λ_lo − λ_up`` exactly as from
    the generic core's row order.
    """
    T, C = MT.shape
    f32 = iterate_dtype(MT.dtype)

    # --- Ruiz equilibration on the structured system ------------------------
    # K's distinct row blocks: the T two-sided rows (magnitude |MT| plus the
    # ε column of ones) and the Σp = 1 row. d_r[t] scales BOTH sign copies.
    d_r = jnp.ones(T, dtype=f32)
    d_e = jnp.ones((), dtype=f32)  # eq-row scale
    d_c = jnp.ones(C, dtype=f32)
    d_eps = jnp.ones((), dtype=f32)

    absMT = jnp.abs(MT)

    def ruiz_body(_, carry):
        d_r, d_e, d_c, d_eps = carry
        S = d_r[:, None] * absMT * d_c[None, :]
        row_ineq = jnp.maximum(jnp.max(S, axis=1), d_r * d_eps)
        # the Σp row spans only REAL columns (colmask zeroes the bucket
        # padding — with padded eq coefficients the solver parks probability
        # mass on zero-objective padding variables and the real columns'
        # normalized sum silently drifts off 1)
        row_eq = jnp.max(d_e * d_c * colmask)
        col = jnp.maximum(jnp.max(S, axis=0), d_e * d_c * colmask)
        col_eps = jnp.max(d_r) * d_eps
        rn = jnp.where(row_ineq > 0, jnp.sqrt(jnp.maximum(row_ineq, 1e-10)), 1.0)
        ren = jnp.where(row_eq > 0, jnp.sqrt(jnp.maximum(row_eq, 1e-10)), 1.0)
        cn = jnp.where(col > 0, jnp.sqrt(jnp.maximum(col, 1e-10)), 1.0)
        cen = jnp.where(col_eps > 0, jnp.sqrt(jnp.maximum(col_eps, 1e-10)), 1.0)
        return d_r / rn, d_e / ren, d_c / cn, d_eps / cen

    d_r, d_e, d_c, d_eps = jax.lax.fori_loop(
        0, 8, ruiz_body, (d_r, d_e, d_c, d_eps)
    )

    Ms = d_r[:, None] * MT * d_c[None, :]  # scaled MT (shared by both sides)
    e_col = d_r * d_eps  # scaled ε-column magnitude per two-sided row
    a_row = d_e * d_c * colmask  # scaled Σp-row coefficients (real cols only)
    # scaled data: h_lo = −(v − slack)·d_r for the −MT side, h_up = v·d_r
    hs_lo = -v * d_r
    hs_up = v * d_r
    bs = 1.0 * d_e
    cs_eps = 1.0 * d_eps  # objective coefficient of ε (scaled)

    def K_apply(p, eps):
        """[G; A] @ x in scaled coordinates: returns (r_lo, r_up, r_eq)."""
        u = Ms @ p
        return -u - e_col * eps, u - e_col * eps, jnp.dot(a_row, p)

    def KT_apply(l_lo, l_up, mu):
        """[G; A]ᵀ [λ; μ]: returns (grad_p, grad_eps)."""
        g_p = Ms.T @ (l_up - l_lo) + mu * a_row
        g_e = -jnp.dot(e_col, l_lo + l_up)
        return g_p, g_e

    # warm start into scaled coordinates
    p = x0[:C] / jnp.maximum(d_c, 1e-12)
    eps = x0[C] / jnp.maximum(d_eps, 1e-12)
    l_lo = jnp.maximum(lam0[:T] / jnp.maximum(d_r, 1e-12), 0.0)
    l_up = jnp.maximum(lam0[T:] / jnp.maximum(d_r, 1e-12), 0.0)
    mu = mu0 / jnp.maximum(d_e, 1e-12)

    out = _two_sided_iterate(
        K_apply, KT_apply, cs_eps, hs_lo, hs_up, bs,
        p, eps, l_lo, l_up, mu, tol, max_iters, check_every,
        sentinel=sentinel,
    )
    p, eps, l_lo, l_up, mu, it, res = out[:7]

    x_out = jnp.concatenate([p * d_c, (eps * d_eps)[None]])
    lam_out = jnp.concatenate([l_lo * d_r, l_up * d_r])
    mu_out = (mu * d_e)[None]
    if sentinel:
        return x_out, lam_out, mu_out, it, res, out[7]
    return x_out, lam_out, mu_out, it, res


_pdhg_two_sided_core = aot_seeded(
    "lp_pdhg.two_sided_core",
    _pdhg_two_sided_core,
    static_argnames=("max_iters", "check_every", "sentinel"),
)


def _pdhg_two_sided_body_ell(
    idx, val, v, colmask, x0, lam0, mu0, tol, max_iters: int, check_every: int,
    sentinel: bool = False,
):
    """The two-sided ε master on the ELL rep — same LP, same loop
    (:func:`_two_sided_iterate`), sparse matvecs.

    ``idx``/``val`` pack the COLUMNS of ``MT`` (one packed row per master
    column, minor axis = the T types, ``solvers/sparse_ops``): the dense
    core's resident ``Ms`` is replaced by the scaled values array, ``Ms @ p``
    becomes a ``segment_sum`` scatter into the T types and ``Ms.T @ y`` a
    per-column gather — O(C·k_pad) instead of O(T·C) per iteration, which at
    production fill (k ≈ 20–40 of T up to 600+) removes ≥90 % of the FLOPs
    and HBM bytes. Ruiz equilibration runs on the packed values directly.
    Returns the same ``(x, lam, mu, iters, res)`` layout as
    :func:`_pdhg_two_sided_core` so callers and warm starts are
    interchangeable between the two cores.
    """
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    T = v.shape[0]
    C = colmask.shape[0]
    f32 = iterate_dtype(val.dtype)

    # --- Ruiz equilibration on the packed rep -------------------------------
    # same four scales as the dense structured core; row maxima over the
    # packed slots (segment_max into the T types), column maxima over the
    # slot axis — the scaled matrix is never materialized
    d_r = jnp.ones(T, dtype=f32)
    d_e = jnp.ones((), dtype=f32)
    d_c = jnp.ones(C, dtype=f32)
    d_eps = jnp.ones((), dtype=f32)

    absV = jnp.abs(val)

    def ruiz_body(_, carry):
        d_r, d_e, d_c, d_eps = carry
        S = absV * d_r[idx] * d_c[:, None]  # scaled |entries| per (col, slot)
        row_from_cols = jnp.maximum(
            jax.ops.segment_max(S.ravel(), idx.ravel(), num_segments=T), 0.0
        )
        row_ineq = jnp.maximum(row_from_cols, d_r * d_eps)
        row_eq = jnp.max(d_e * d_c * colmask)
        col = jnp.maximum(S.max(axis=1), d_e * d_c * colmask)
        col_eps = jnp.max(d_r) * d_eps
        rn = jnp.where(row_ineq > 0, jnp.sqrt(jnp.maximum(row_ineq, 1e-10)), 1.0)
        ren = jnp.where(row_eq > 0, jnp.sqrt(jnp.maximum(row_eq, 1e-10)), 1.0)
        cn = jnp.where(col > 0, jnp.sqrt(jnp.maximum(col, 1e-10)), 1.0)
        cen = jnp.where(col_eps > 0, jnp.sqrt(jnp.maximum(col_eps, 1e-10)), 1.0)
        return d_r / rn, d_e / ren, d_c / cn, d_eps / cen

    d_r, d_e, d_c, d_eps = jax.lax.fori_loop(
        0, 8, ruiz_body, (d_r, d_e, d_c, d_eps)
    )

    vals_s = val * d_r[idx] * d_c[:, None]  # scaled packed entries
    e_col = d_r * d_eps
    a_row = d_e * d_c * colmask
    hs_lo = -v * d_r
    hs_up = v * d_r
    bs = 1.0 * d_e
    cs_eps = 1.0 * d_eps

    def K_apply(p, eps):
        u = ell_scatter_mv(idx, vals_s, p, T)  # Ms @ p
        return -u - e_col * eps, u - e_col * eps, jnp.dot(a_row, p)

    def KT_apply(l_lo, l_up, mu):
        g_p = ell_gather_mv(idx, vals_s, l_up - l_lo) + mu * a_row
        g_e = -jnp.dot(e_col, l_lo + l_up)
        return g_p, g_e

    p = x0[:C] / jnp.maximum(d_c, 1e-12)
    eps = x0[C] / jnp.maximum(d_eps, 1e-12)
    l_lo = jnp.maximum(lam0[:T] / jnp.maximum(d_r, 1e-12), 0.0)
    l_up = jnp.maximum(lam0[T:] / jnp.maximum(d_r, 1e-12), 0.0)
    mu = mu0 / jnp.maximum(d_e, 1e-12)

    out = _two_sided_iterate(
        K_apply, KT_apply, cs_eps, hs_lo, hs_up, bs,
        p, eps, l_lo, l_up, mu, tol, max_iters, check_every,
        sentinel=sentinel,
    )
    p, eps, l_lo, l_up, mu, it, res = out[:7]

    x_out = jnp.concatenate([p * d_c, (eps * d_eps)[None]])
    lam_out = jnp.concatenate([l_lo * d_r, l_up * d_r])
    mu_out = (mu * d_e)[None]
    if sentinel:
        return x_out, lam_out, mu_out, it, res, out[7]
    return x_out, lam_out, mu_out, it, res


# the undecorated body stays importable so the batched polish screen can
# ``vmap`` the identical ELL iteration over prefix lanes (solvers/batch_lp)
_pdhg_two_sided_core_ell = aot_seeded(
    "lp_pdhg.two_sided_core_ell",
    partial(
        jax.jit,
        static_argnames=("max_iters", "check_every", "sentinel"),
        # x0, lam0 (mu0 is a scalar, undonated by design)
        donate_argnums=(4, 5),
    )(_pdhg_two_sided_body_ell),
    static_argnames=("max_iters", "check_every", "sentinel"),
)


@dataclasses.dataclass
class MasterHandle:
    """An in-flight two-sided master solve: the core's raw DEVICE outputs
    plus the decode metadata. ``finish_two_sided_master`` converts it to an
    :class:`LPSolution` (the blocking readback); until then the arrays can
    feed further device dispatches — the device-pricing round chains the
    fused move screen onto ``lam`` so the whole round synchronizes once."""

    x: object  # [Cp+1] f32 device array
    lam: object  # [2T] f32 device array
    mu: object  # [1] f32 device array
    it: object  # i32 device scalar
    res: object  # f32 device scalar
    Cp: int
    tol: float
    #: sentinel quarantine bitmask (i32 device scalar) when the solve ran
    #: with the numerical sentinel, else None
    flags: object = None


def finish_two_sided_master(h: MasterHandle) -> LPSolution:
    """Blocking readback half of the async master solve. A sentinel-
    quarantined solve comes back with ``ok=False`` (its iterate froze at the
    last finite block) — ``_master_pdhg`` then routes the round to the
    serial float64 host master."""
    x = np.asarray(h.x, dtype=np.float64)
    lam = np.asarray(h.lam, dtype=np.float64)
    mu = np.asarray(h.mu, dtype=np.float64)
    res_f = float(h.res)
    poisoned = (
        bool(int(np.asarray(h.flags)) & FLAG_POISONED)
        if h.flags is not None
        else False
    )
    return LPSolution(
        ok=bool(res_f <= h.tol * 4.0) and not poisoned,
        x=x,
        lam=lam,
        mu=mu,
        objective=float(x[h.Cp]),
        iters=int(h.it),
        kkt=res_f,
    )


def solve_two_sided_master_async(
    MT: np.ndarray,
    v: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
    bucket: int = 2048,
) -> MasterHandle:
    """Dispatch half of :func:`solve_two_sided_master`: identical operand
    prep and core call, but the outputs stay DEVICE arrays (no readback) so
    a caller can enqueue dependent device work before blocking."""
    from citizensassemblies_tpu.robust import inject

    cfg = cfg or default_config()
    tol = float(tol if tol is not None else cfg.pdhg_tol)
    sent = sentinels_enabled(cfg)
    T, C = MT.shape
    Cp = ((C + bucket - 1) // bucket) * bucket
    if cfg.pdhg_megakernel is not False:
        # fused route: the megakernel is ELL-native, so the dense master
        # rides it through a column pack of MT (identical LP, identical
        # warm/(x, lam, mu) contract). The VMEM fit check inside
        # megakernel_mode keeps dense-fill packs off the kernel when the
        # expansion would not fit; mode "off" falls through to the dense
        # chained core untouched.
        from citizensassemblies_tpu.kernels import pdhg_megakernel as _mk
        from citizensassemblies_tpu.solvers.sparse_ops import EllPack

        ell_mt = EllPack.from_rows(np.asarray(MT, np.float32).T, minor=T)
        mode = _mk.megakernel_mode(
            cfg, _mk.two_sided_vmem_bytes(int(T), int(Cp), int(ell_mt.k_pad))
        )
        if mode != "off":
            return solve_two_sided_master_ell_async(
                ell_mt, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters,
                bucket=bucket,
            )
    MTp = np.zeros((T, Cp), dtype=np.float32)
    MTp[:, :C] = MT
    f32 = jnp.float32
    if warm is not None:
        x0 = np.zeros(Cp + 1, dtype=np.float32)
        m = min(C, len(warm[0]) - 1)
        x0[:m] = warm[0][:m]
        x0[Cp] = warm[0][-1]
        lam0 = np.zeros(2 * T, dtype=np.float32)
        lam0[: min(2 * T, len(warm[1]))] = warm[1][: 2 * T]
        mu0 = np.float32(warm[2][0] if np.ndim(warm[2]) else warm[2])
    else:
        x0 = np.zeros(Cp + 1, dtype=np.float32)
        lam0 = np.zeros(2 * T, dtype=np.float32)
        mu0 = np.float32(0.0)
    if inject.site("pdhg_nan", _ambient_log()):
        x0[0] = np.nan  # chaos: sentinel must quarantine, round must recover
    colmask = np.zeros(Cp, dtype=np.float32)
    colmask[:C] = 1.0
    # every operand is materialized to a device array BEFORE the guard scope
    # (a dtype-converting asarray binds convert_element_type eagerly, which
    # the transfer guard counts as an implicit upload); inside the guard the
    # hot call may only touch what is already resident
    operands = (
        demote_operator(
            jnp.asarray(MTp, f32), cfg,
            core="lp_pdhg.two_sided_core", arg=0, log=_ambient_log(),
        ),
        jnp.asarray(v, f32),
        jnp.asarray(colmask, f32),
        jnp.asarray(x0, f32),
        jnp.asarray(lam0, f32),
        jnp.asarray(mu0, f32),
        jnp.asarray(tol, jnp.float32),
    )
    with dispatch_span(
        "lp_pdhg.two_sided_core", cfg=cfg, T=int(T), cols=int(Cp)
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = _pdhg_two_sided_core(
                *operands,
                max_iters=int(max_iters if max_iters is not None else cfg.pdhg_max_iters),
                check_every=int(cfg.pdhg_check_every),
                sentinel=sent,
            )
        x, lam, mu, it, res = out[:5]
        _ds.out = (x, lam, mu, it, res)
    return MasterHandle(
        x=x, lam=lam, mu=mu, it=it, res=res, Cp=Cp, tol=tol,
        flags=out[5] if sent else None,
    )


def solve_two_sided_master(
    MT: np.ndarray,
    v: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
    bucket: int = 2048,
) -> LPSolution:
    """Device solve of the two-sided ε master via the structured core.

    Drop-in for the ``solve_lp`` call that ``face_decompose._master_pdhg``
    used to make on the stacked matrix, with identical (x, lam, mu) layout:
    ``x = [p (Cp), ε]``, ``lam = [λ_lo (T), λ_up (T)]`` (so the pricing
    duals are ``lam[:T] − lam[T:]``), ``mu = [μ]``. Columns are padded to
    ``bucket`` so the jitted core compiles once per bucket.
    """
    return finish_two_sided_master(
        solve_two_sided_master_async(
            MT, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters,
            bucket=bucket,
        )
    )


def solve_two_sided_master_ell_async(
    ell,
    v: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
    bucket: int = 2048,
) -> MasterHandle:
    """Dispatch half of :func:`solve_two_sided_master_ell` (see
    :func:`solve_two_sided_master_async`): device outputs, no readback.

    ``ell`` is a :class:`~citizensassemblies_tpu.solvers.sparse_ops.EllPack`
    of the master's COLUMNS (minor axis = the T types). Drop-in for
    :func:`solve_two_sided_master` with the identical (x, lam, mu) layout
    and warm-start contract; only the device operands change — instead of
    the dense ``T × Cp`` matrix, the tunnel carries ``Cp × k_pad`` packed
    indices/values (the incremental-append path re-packs only new columns,
    so successive CG rounds upload a few kilobytes of fresh pack instead of
    re-materializing ``MT``). Columns pad to ``bucket`` (all-zero packed
    rows are inert), so the jitted ELL core compiles once per
    ``(T, Cp, k_pad)`` bucket.
    """
    from citizensassemblies_tpu.robust import inject

    cfg = cfg or default_config()
    tol = float(tol if tol is not None else cfg.pdhg_tol)
    sent = sentinels_enabled(cfg)
    T = int(ell.minor)
    C = len(ell)
    Cp = ((C + bucket - 1) // bucket) * bucket
    idx_p, val_p = ell.padded(Cp)
    f32 = jnp.float32
    if warm is not None:
        x0 = np.zeros(Cp + 1, dtype=np.float32)
        m = min(C, len(warm[0]) - 1)
        x0[:m] = warm[0][:m]
        x0[Cp] = warm[0][-1]
        lam0 = np.zeros(2 * T, dtype=np.float32)
        lam0[: min(2 * T, len(warm[1]))] = warm[1][: 2 * T]
        mu0 = np.float32(warm[2][0] if np.ndim(warm[2]) else warm[2])
    else:
        x0 = np.zeros(Cp + 1, dtype=np.float32)
        lam0 = np.zeros(2 * T, dtype=np.float32)
        mu0 = np.float32(0.0)
    if inject.site("pdhg_nan", _ambient_log()):
        x0[0] = np.nan  # chaos: sentinel must quarantine, round must recover
    colmask = np.zeros(Cp, dtype=np.float32)
    colmask[:C] = 1.0
    # operands materialized BEFORE the guard scope, as in the dense wrapper
    operands = (
        jnp.asarray(idx_p),
        demote_operator(
            jnp.asarray(val_p), cfg,
            core="lp_pdhg.two_sided_core_ell", arg=1, log=_ambient_log(),
        ),
        jnp.asarray(v, f32),
        jnp.asarray(colmask, f32),
        jnp.asarray(x0, f32),
        jnp.asarray(lam0, f32),
        jnp.asarray(mu0, f32),
        jnp.asarray(tol, jnp.float32),
    )
    mi = int(max_iters if max_iters is not None else cfg.pdhg_max_iters)
    ce = int(cfg.pdhg_check_every)
    from citizensassemblies_tpu.kernels import pdhg_megakernel as _mk

    mode = _mk.megakernel_mode(
        cfg, _mk.two_sided_vmem_bytes(int(T), int(Cp), int(ell.k_pad))
    )
    if mode != "off":
        # fused route: one kernel launch per PDHG block; the single solve
        # rides the batched core as its lone lane
        bops = (
            operands[0], operands[1], operands[2], operands[3][None],
            operands[4][None], operands[5][None], operands[6][None],
            operands[7][None],
        )
        out = _mk.dispatch_two_sided(
            bops, cfg=cfg, log=_ambient_log(), max_iters=mi, check_every=ce,
            sentinel=sent, mode=mode, lanes=1,
        )
        return MasterHandle(
            x=out[0][0], lam=out[1][0], mu=out[2][0:1].reshape(1),
            it=out[3][0], res=out[4][0], Cp=Cp, tol=tol,
            flags=out[5][0] if sent else None,
        )
    with dispatch_span(
        "lp_pdhg.two_sided_core_ell", cfg=cfg, T=int(T), cols=int(Cp),
        k_pad=int(ell.k_pad),
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = _pdhg_two_sided_core_ell(
                *operands,
                max_iters=mi,
                check_every=ce,
                sentinel=sent,
            )
        x, lam, mu, it, res = out[:5]
        _ds.out = (x, lam, mu, it, res)
    return MasterHandle(
        x=x, lam=lam, mu=mu, it=it, res=res, Cp=Cp, tol=tol,
        flags=out[5] if sent else None,
    )


def solve_two_sided_master_ell(
    ell,
    v: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
    bucket: int = 2048,
) -> LPSolution:
    """Blocking wrapper of :func:`solve_two_sided_master_ell_async` — the
    drop-in ELL twin of :func:`solve_two_sided_master` (same (x, lam, mu)
    layout and warm-start contract)."""
    return finish_two_sided_master(
        solve_two_sided_master_ell_async(
            ell, v, cfg=cfg, warm=warm, tol=tol, max_iters=max_iters,
            bucket=bucket,
        )
    )


# --- generic-form PDHG on an ELL constraint matrix --------------------------


def _pdhg_body_ell(
    c, idx, val, h, A, b, x0, lam0, mu0, tol,
    max_iters: int, check_every: int, sentinel: bool = False,
):
    """``_pdhg_body`` with the inequality block ``G`` supplied as packed ELL
    ROWS (``idx``/``val`` [m1, k_pad], minor axis = the nv variables) — the
    operator-abstraction twin of the dense body: same Ruiz/restart/averaging
    scheme, with ``G @ x`` a per-row gather and ``Gᵀ λ`` a ``segment_sum``
    scatter. The dual leximin LP's rows are panels (k + 1 nonzeros of
    nv = n + 1 columns), so this core does O(m1·k) work per iteration where
    the dense core does O(m1·nv). The equality block ``A`` (one Σ row) stays
    dense."""
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    m1 = idx.shape[0]
    nv = c.shape[0]
    m2 = A.shape[0]
    f32 = iterate_dtype(val.dtype)

    # --- Ruiz on the stacked [G; A] system, G in packed form ----------------
    absV = jnp.abs(val)
    absA = jnp.abs(A)

    def ruiz_body(_, carry):
        d_r, d_c = carry
        Sg = absV * d_r[:m1][:, None] * d_c[idx]
        Sa = d_r[m1:, None] * absA * d_c[None, :]
        rmax = jnp.concatenate([Sg.max(axis=1), Sa.max(axis=1)])
        cmax = jnp.maximum(
            jnp.maximum(
                jax.ops.segment_max(
                    Sg.ravel(), idx.ravel(), num_segments=nv
                ),
                0.0,
            ),
            Sa.max(axis=0),
        )
        rn = jnp.where(rmax > 0, jnp.sqrt(jnp.maximum(rmax, 1e-10)), 1.0)
        cn = jnp.where(cmax > 0, jnp.sqrt(jnp.maximum(cmax, 1e-10)), 1.0)
        return d_r / rn, d_c / cn

    d_r, d_c = jax.lax.fori_loop(
        0, 8, ruiz_body, (jnp.ones(m1 + m2, f32), jnp.ones(nv, f32))
    )
    vals_s = val * d_r[:m1][:, None] * d_c[idx]
    As = d_r[m1:, None] * A * d_c[None, :]
    cs = c * d_c
    hs = h * d_r[:m1]
    bs = b * d_r[m1:]

    def G_mv(x):
        return ell_gather_mv(idx, vals_s, x)

    def G_rmv(y):
        return ell_scatter_mv(idx, vals_s, y, nv)

    # ‖K‖₂ power estimate via the structured matvecs
    def pow_body(_, vv):
        w = G_rmv(G_mv(vv)) + As.T @ (As @ vv)
        return w / (jnp.linalg.norm(w) + 1e-12)

    vvec = jax.lax.fori_loop(
        0, 40, pow_body, jnp.ones(nv, f32) / jnp.sqrt(jnp.float32(nv))
    )
    norm = jnp.sqrt(
        jnp.linalg.norm(G_rmv(G_mv(vvec)) + As.T @ (As @ vvec)) + 1e-12
    )
    scale = 1.0 + jnp.linalg.norm(cs) + jnp.linalg.norm(hs) + jnp.linalg.norm(bs)

    x = x0 / jnp.maximum(d_c, 1e-12)
    lam = jnp.maximum(lam0 / jnp.maximum(d_r[:m1], 1e-12), 0.0)
    mu = mu0 / jnp.maximum(d_r[m1:], 1e-12)

    def kkt(x, lam, mu):
        pri_ineq = jnp.maximum(G_mv(x) - hs, 0.0)
        pri_eq = As @ x - bs
        pri = jnp.sqrt(jnp.sum(pri_ineq**2) + jnp.sum(pri_eq**2))
        grad = cs + G_rmv(lam) + As.T @ mu
        dua = jnp.linalg.norm(jnp.minimum(grad, 0.0))
        pobj = cs @ x
        dobj = -(lam @ hs) - (mu @ bs)
        gap = jnp.abs(pobj - dobj)
        return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

    def one_iter(carry, _):
        x, lam, mu, xs, ls, ms, tau, sigma = carry
        grad = cs + G_rmv(lam) + As.T @ mu
        x_new = jnp.maximum(x - tau * grad, 0.0)
        xb = 2.0 * x_new - x
        lam_new = jnp.maximum(lam + sigma * (G_mv(xb) - hs), 0.0)
        mu_new = mu + sigma * (As @ xb - bs)
        return (
            x_new, lam_new, mu_new, xs + x_new, ls + lam_new, ms + mu_new,
            tau, sigma,
        ), None

    def block(state):
        (x, lam, mu, x_av, lam_av, mu_av, it, res, omega) = state
        tau = 0.9 * omega / norm
        sigma = 0.9 / (omega * norm)
        x_in, lam_in, mu_in = x, lam, mu
        zero = (jnp.zeros_like(x), jnp.zeros_like(lam), jnp.zeros_like(mu))
        (x, lam, mu, xs, ls, ms, _, _), _ = jax.lax.scan(
            one_iter, (x, lam, mu) + zero + (tau, sigma), None,
            length=check_every,
        )
        inv = 1.0 / check_every
        xa = (x_av + xs * inv) * 0.5
        la = (lam_av + ls * inv) * 0.5
        ma = (mu_av + ms * inv) * 0.5
        r_cur = kkt(x, lam, mu)
        r_avg = kkt(xa, la, ma)
        better = r_avg < r_cur
        x = jnp.where(better, xa, x)
        lam = jnp.where(better, la, lam)
        mu = jnp.where(better, ma, mu)
        res = jnp.minimum(r_cur, r_avg)
        dx = jnp.linalg.norm(x - x_in)
        dy = jnp.sqrt(
            jnp.sum((lam - lam_in) ** 2) + jnp.sum((mu - mu_in) ** 2)
        )
        moved = (dx > 1e-12) & (dy > 1e-12)
        omega_new = jnp.sqrt(omega * jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-4, 1e4))
        omega = jnp.where(moved, jnp.clip(omega_new, 1.0 / 64.0, 64.0), omega)
        return (x, lam, mu, xa, la, ma, it + check_every, res, omega)

    def cond(state):
        x, lam, mu, xa, la, ma, it, res, omega = state
        return (res > tol) & (it < max_iters)

    state0 = (
        x, lam, mu, x, lam, mu, jnp.int32(0), jnp.float32(jnp.inf),
        jnp.float32(1.0),
    )
    if sentinel:
        (x, lam, mu, _, _, _, it, res, _omega), flags = _sentinel_while(
            cond, block, state0
        )
        return x * d_c, lam * d_r[:m1], mu * d_r[m1:], it, res, flags
    x, lam, mu, _, _, _, it, res, _omega = jax.lax.while_loop(cond, block, state0)
    return x * d_c, lam * d_r[:m1], mu * d_r[m1:], it, res


_pdhg_core_ell = aot_seeded(
    "lp_pdhg.pdhg_core_ell",
    partial(
        jax.jit,
        static_argnames=("max_iters", "check_every", "sentinel"),
        donate_argnums=(6, 7, 8),  # x0, lam0, mu0 — same carry contract
    )(_pdhg_body_ell),
    static_argnames=("max_iters", "check_every", "sentinel"),
)


def solve_lp_ell(
    c: np.ndarray,
    ell,
    h: np.ndarray,
    A: np.ndarray,
    b: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
) -> LPSolution:
    """:func:`solve_lp` with the inequality block packed as ELL rows
    (``ell`` an :class:`~citizensassemblies_tpu.solvers.sparse_ops.EllPack`
    over the nv variables). Same acceptance contract and warm semantics."""
    from citizensassemblies_tpu.robust import inject

    cfg = cfg or default_config()
    tol = float(tol if tol is not None else cfg.pdhg_tol)
    sent = sentinels_enabled(cfg)
    f32 = jnp.float32
    c_, h_ = jnp.asarray(c, f32), jnp.asarray(h, f32)
    A_, b_ = jnp.asarray(A, f32), jnp.asarray(b, f32)
    nv = c_.shape[0]
    m1, m2 = ell.idx.shape[0], A_.shape[0]
    if warm is not None:
        x0_h = np.asarray(warm[0], np.float32)
        lam0_h = np.asarray(warm[1], np.float32)
        mu0_h = np.asarray(warm[2], np.float32)
    else:
        x0_h = np.zeros(nv, np.float32)
        lam0_h = np.zeros(m1, np.float32)
        mu0_h = np.zeros(m2, np.float32)
    log = _ambient_log()
    if inject.site("pdhg_nan", log):
        x0_h = x0_h.copy()
        x0_h[0] = np.nan
    x0, lam0, mu0 = jnp.asarray(x0_h), jnp.asarray(lam0_h), jnp.asarray(mu0_h)
    idx_d = jnp.asarray(ell.idx)
    val_d = demote_operator(
        jnp.asarray(ell.val), cfg, core="lp_pdhg.pdhg_core_ell", arg=2,
        log=log,
    )
    A_ = demote_operator(A_, cfg, core="lp_pdhg.pdhg_core_ell", arg=4, log=log)
    tol_ = jnp.asarray(tol, jnp.float32)
    from citizensassemblies_tpu.kernels import pdhg_megakernel as _mk

    mode = _mk.megakernel_mode(
        cfg, _mk.lp_vmem_bytes(int(m1), int(nv), int(ell.k_pad), int(m2))
    )
    if mode != "off":
        out = _mk.dispatch_lp(
            (c_, idx_d, val_d, h_, A_, b_, x0, lam0, mu0, tol_),
            cfg=cfg, log=log, max_iters=int(cfg.pdhg_max_iters),
            check_every=int(cfg.pdhg_check_every), sentinel=sent, mode=mode,
        )
        x, lam, mu, it, res = out[:5]
    else:
        with dispatch_span(
            "lp_pdhg.pdhg_core_ell", cfg=cfg, nv=int(nv), m1=int(m1), m2=int(m2)
        ) as _ds:
            with no_implicit_transfers(cfg):
                out = _pdhg_core_ell(
                    c_, idx_d, val_d, h_, A_, b_, x0, lam0, mu0, tol_,
                    max_iters=int(cfg.pdhg_max_iters),
                    check_every=int(cfg.pdhg_check_every),
                    sentinel=sent,
                )
            x, lam, mu, it, res = out[:5]
            _ds.out = (x, lam, mu, it, res)
    flags = int(np.asarray(out[5])) if sent else 0
    if flags & FLAG_POISONED:
        if log is not None:
            log.count("sentinel_poisoned")
        from citizensassemblies_tpu.solvers.sparse_ops import ell_unpack_rows

        G_dense = ell_unpack_rows(ell.idx, ell.val, int(nv))
        host = _host_resolve_lp(c, G_dense, h, A, b)
        if host is not None:
            if log is not None:
                log.count("sentinel_host_resolve")
            return host
    if flags & FLAG_STALLED and log is not None:
        log.count("sentinel_stalled")
    x = np.asarray(x, dtype=np.float64)
    lam = np.asarray(lam, dtype=np.float64)
    mu = np.asarray(mu, dtype=np.float64)
    res_f = float(res)
    return LPSolution(
        ok=bool(res_f <= tol * 4.0) and not (flags & FLAG_POISONED),
        x=x,
        lam=lam,
        mu=mu,
        objective=float(np.asarray(c, dtype=np.float64) @ x),
        iters=int(it),
        kkt=res_f,
    )


# --- the two LP shapes of the LEXIMIN machinery -----------------------------


# --- graftcheck-IR registrations (lint/ir.py) -------------------------------
# Representative shapes are one small dual-LP bucket (Cp=64 rows) and one
# small two-sided master bucket — structure, not scale, is what the IR
# verifier checks, so tiny buckets keep `make check-ir` CPU-cheap. Each ELL
# core registers at the SAME problem shape as its dense twin (dense_ref), so
# the budget-diff artifact's dense→sparse flops/bytes delta is a same-shape
# comparison; the two-sided pair sits at a production-representative fill
# (k_pad = 16 slots of T = 128 types).


@register_ir_core("lp_pdhg.pdhg_core", span="lp_pdhg.pdhg_core")
def _ir_pdhg_core() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    nv, m1, m2 = 65, 64, 1
    return IRCase(
        fn=_pdhg_core,
        args=(
            S((nv,), f32), S((m1, nv), f32), S((m1,), f32),
            S((m2, nv), f32), S((m2,), f32),
            S((nv,), f32), S((m1,), f32), S((m2,), f32), S((), f32),
        ),
        static=dict(max_iters=1024, check_every=128),
        donate_expected=3,  # x0, lam0, mu0
        arg_ranges=(
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(1, 3),  # G, A
    )


@register_ir_core(
    "lp_pdhg.pdhg_core_ell",
    dense_ref="lp_pdhg.pdhg_core",
    span="lp_pdhg.pdhg_core_ell",
)
def _ir_pdhg_core_ell() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    nv, m1, m2, kp = 65, 64, 1, 8
    return IRCase(
        fn=_pdhg_core_ell,
        args=(
            S((nv,), f32), S((m1, kp), i32), S((m1, kp), f32), S((m1,), f32),
            S((m2, nv), f32), S((m2,), f32),
            S((nv,), f32), S((m1,), f32), S((m2,), f32), S((), f32),
        ),
        static=dict(max_iters=1024, check_every=128),
        donate_expected=3,  # x0, lam0, mu0
        arg_ranges=(
            (-1e4, 1e4, False),
            None,
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(2, 4),  # ELL values, A
    )


@register_ir_core("lp_pdhg.two_sided_core", span="lp_pdhg.two_sided_core")
def _ir_two_sided_core() -> IRCase:
    # T=128, C=256: the committed shape is shared with the ELL twin below so
    # the dense→sparse budget delta is a same-shape measurement
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    T, C = 128, 256
    return IRCase(
        fn=_pdhg_two_sided_core,
        args=(
            S((T, C), f32), S((T,), f32), S((C,), f32),
            S((C + 1,), f32), S((2 * T,), f32), S((), f32), S((), f32),
        ),
        static=dict(max_iters=1024, check_every=128),
        donate_expected=2,  # x0, lam0 (mu0 is a scalar, undonated by design)
        arg_ranges=(
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (0.0, 1.0, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(0,),  # MT
    )


@register_ir_core(
    "lp_pdhg.two_sided_core_ell",
    dense_ref="lp_pdhg.two_sided_core",
    span="lp_pdhg.two_sided_core_ell",
)
def _ir_two_sided_core_ell() -> IRCase:
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    T, C, kp = 128, 256, 16
    return IRCase(
        fn=_pdhg_two_sided_core_ell,
        args=(
            S((C, kp), i32), S((C, kp), f32), S((T,), f32), S((C,), f32),
            S((C + 1,), f32), S((2 * T,), f32), S((), f32), S((), f32),
        ),
        static=dict(max_iters=1024, check_every=128),
        donate_expected=2,  # x0, lam0 (mu0 scalar, undonated by design)
        arg_ranges=(
            None,
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (0.0, 1.0, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(1,),  # ELL values
    )


def solve_dual_lp_pdhg(
    P: np.ndarray,
    fixed: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
):
    """Dual leximin LP (``leximin.py:300-328``) on device.

    Variables z = [y (n), ŷ]; min ŷ − Σ fixedᵢ yᵢ s.t. P y − ŷ·1 ≤ 0,
    Σ_{unfixed} y = 1, z ≥ 0. Returns the same ``DualSolution`` contract as
    :func:`citizensassemblies_tpu.solvers.highs_backend.solve_dual_lp` plus
    the raw (x, λ, μ) triple for warm starting.
    """
    from citizensassemblies_tpu.solvers.highs_backend import DualSolution

    cfg = cfg or default_config()
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    fixed = np.asarray(fixed, dtype=np.float64)
    unfixed = fixed < 0
    fixed_vals = np.where(unfixed, 0.0, fixed)

    # Pad the committee-row dimension to a bucket so the jitted PDHG core
    # compiles once per bucket instead of once per column-generation round
    # (the portfolio gains a few rows per inner iteration). A padding row of
    # zeros contributes the constraint 0·y − ŷ ≤ 0, i.e. ŷ ≥ 0 — already an
    # implicit bound, so the solution is unchanged.
    bucket = 256
    Cp = ((C + bucket - 1) // bucket) * bucket
    Ppad = np.zeros((Cp, n))
    Ppad[:C] = P

    c = np.concatenate([-fixed_vals, [1.0]])
    G = np.hstack([Ppad, -np.ones((Cp, 1))])
    h = np.zeros(Cp)
    A = np.concatenate([unfixed.astype(np.float64), [0.0]])[None, :]
    b = np.array([1.0])
    if warm is not None and warm[1].shape[0] != Cp:
        lam_w = np.zeros(Cp)
        lam_w[: min(Cp, warm[1].shape[0])] = warm[1][:Cp]
        warm = (warm[0], lam_w, warm[2])
    # G's rows are panels: k member columns plus the ŷ column — at portfolio
    # scale ≥90 % of the dense GEMV is multiply-by-zero, so the ELL core
    # carries the solve whenever the measured fill clears the cutoff
    # (sparse_ops off ⇒ the dense path below runs bit-identically)
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack, sparse_enabled

    fill = (float(np.count_nonzero(P)) + C) / max(Cp * (n + 1), 1)
    if sparse_enabled(cfg, fill):
        sol = solve_lp_ell(c, EllPack.from_rows(G), h, A, b, cfg=cfg, warm=warm)
    else:
        sol = solve_lp(c, G, h, A, b, cfg=cfg, warm=warm)
    y = sol.x[:n]
    yhat = float(sol.x[n])
    return (
        DualSolution(ok=sol.ok, y=y, yhat=yhat, objective=sol.objective),
        (sol.x, sol.lam, sol.mu),
    )


def solve_stage_lp_pdhg(
    MT: np.ndarray,
    fixed: np.ndarray,
    cfg: Optional[Config] = None,
    warm: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    tol: Optional[float] = None,
):
    """Type-space stage LP (max the min unfixed type value) on device.

    Variables x = [p (C), z]; min −z s.t. z − M_t·p ≤ 0 (t unfixed),
    −M_t·p ≤ −f_t (t fixed), Σp = 1, x ≥ 0. The λ duals of the ≤-rows are the
    per-type weights column-generation pricing needs. The column dimension is
    padded to a bucket (zero G/eq coefficients, zero cost — padding variables
    stay at 0) so the jitted PDHG core compiles once per bucket while the
    portfolio grows. Returns ``(z, y, mu, p, ok)`` plus the raw warm triple.
    """
    cfg = cfg or default_config()
    T, C = MT.shape
    fixed = np.asarray(fixed, dtype=np.float64)
    unfixed = fixed < 0
    h_rows = np.where(unfixed, 0.0, -(np.maximum(fixed, 0.0) - 1e-9))

    # wide padding bucket: zero columns are free MXU work, while every bucket
    # crossing costs a fresh jit of the PDHG core (~10 s) — with hundreds of
    # columns added per round a narrow bucket recompiles nearly every round
    bucket = 4096
    Cp = ((C + bucket - 1) // bucket) * bucket
    G = np.zeros((T, Cp + 1))
    G[:, :C] = -MT
    G[unfixed, Cp] = 1.0
    h = h_rows
    A = np.zeros((1, Cp + 1))
    A[0, :C] = 1.0
    b = np.array([1.0])
    c = np.zeros(Cp + 1)
    c[Cp] = -1.0
    if warm is not None and warm[0].shape[0] != Cp + 1:
        x_w = np.zeros(Cp + 1)
        m = min(C, warm[0].shape[0] - 1)
        x_w[:m] = warm[0][:m]
        x_w[Cp] = warm[0][-1]
        warm = (x_w, warm[1], warm[2])
    sol = solve_lp(c, G, h, A, b, cfg=cfg, warm=warm, tol=tol)
    z = float(sol.x[Cp])
    y = np.maximum(sol.lam, 0.0)
    mu = float(sol.mu[0])
    p = sol.x[:C]
    return z, y, mu, p, sol.ok, (sol.x, sol.lam, sol.mu)


def solve_final_primal_lp_pdhg(
    P: np.ndarray,
    target: np.ndarray,
    cfg: Optional[Config] = None,
    max_iters: Optional[int] = None,
    tol: Optional[float] = None,
    host_fallback: bool = True,
) -> Tuple[np.ndarray, float]:
    """Final primal LP (``leximin.py:453-464``) on device: min ε s.t.
    Σp = 1, (Pᵀp)ᵢ ≥ targetᵢ − ε, p ≥ 0, ε ≥ 0. Returns (p, ε).

    ``host_fallback=False`` returns the (possibly unconverged) device
    iterate instead of re-solving on host — for callers that validate the
    iterate arithmetically and must never touch the host LP (see
    ``qp._min_eps_pdhg``: scipy's HiGHS crawled >30 min on a degenerate
    example_large-shaped instance of this very LP)."""
    cfg = cfg or default_config()
    if max_iters is not None:
        cfg = cfg.replace(pdhg_max_iters=int(max_iters))
    P = np.asarray(P, dtype=np.float64)
    C, n = P.shape
    target = np.asarray(target, dtype=np.float64)
    c = np.zeros(C + 1)
    c[-1] = 1.0
    G = np.hstack([-P.T, -np.ones((n, 1))])
    h = -target
    A = np.concatenate([np.ones(C), [0.0]])[None, :]
    b = np.array([1.0])
    sol = solve_lp(c, G, h, A, b, cfg=cfg, tol=tol)
    if not sol.ok and host_fallback:
        from citizensassemblies_tpu.solvers.highs_backend import solve_final_primal_lp

        return solve_final_primal_lp(P, target)
    return sol.x[:C], float(max(sol.x[C], 0.0))
