"""Exact LEXIMIN in type space: enumerate feasible committee *compositions*.

Agents with identical feature rows are interchangeable: quota feasibility of a
committee depends only on how many members of each *type* it contains (the
type reduction of ``solvers/native_oracle.py``), and the leximin-optimal
allocation — the unique leximin point of the convex allocation polytope — is
therefore symmetric within types. So for instances with few distinct types the
entire problem collapses:

* a committee is a **composition** ``c ∈ Z^T`` with ``Σc = k``,
  ``0 ≤ c_t ≤ m_t`` and per-feature quota constraints;
* a distribution over committees induces the per-agent allocation
  ``π_i = Σ_c p_c · c_t(i)/m_t(i)`` (members drawn uniformly within types);
* leximin over n agents reduces to leximin over T type values with
  multiplicities.

The reference's headline benchmark instances are extreme cases:
``example_large_200`` (n=2000, reference runtime 1161.8 s,
``reference_output/example_large_200_statistics.txt:15``) has **3** distinct
types, ``example_small_20`` (2.7 s) has **4**. Enumerating every feasible
composition and running the leximin stage LPs over the full enumeration is
exact, deterministic, and takes milliseconds — replacing the reference's
column generation (``leximin.py:338-470``) outright for such instances. The
stage fixing here is *certified*: dual weights propose the tranche
(strict complementarity, as in ``leximin.py:431-443``) and per-type probe LPs
confirm every remaining candidate, so no tranche is ever fixed prematurely
(the reference trusts the ``y > EPS`` heuristic alone).
"""

from __future__ import annotations

import dataclasses
from math import gcd
from typing import List, Optional, Tuple

import numpy as np
import scipy.optimize
import scipy.sparse

from citizensassemblies_tpu.solvers.lp_util import probe_confirm_tranche
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.logging import RunLog


def enumerate_compositions(
    reduction: TypeReduction,
    cap: int = 200_000,
    node_budget: int = 3_000_000,
) -> Optional[np.ndarray]:
    """All feasible compositions ``c`` (int32 [C, T]), or None if more than
    ``cap`` exist / the search exceeds ``node_budget`` nodes.

    Feasibility: ``Σc = k``, ``0 ≤ c_t ≤ m_t`` and for every feature f
    ``lo_f ≤ Σ_{t: f ∈ t} c_t ≤ hi_f`` (the committee constraints of
    ``leximin.py:201-209`` collapsed onto types).
    """
    T = reduction.T
    F = reduction.F
    k = reduction.k
    msize = reduction.msize
    lo = reduction.qmin.astype(np.int64)
    hi = reduction.qmax.astype(np.int64)
    # per-type one-hot feature incidence [T, F]
    tf = np.zeros((T, F), dtype=np.int64)
    for t in range(T):
        tf[t, reduction.type_feature[t]] = 1
    # suffix capacity per feature: how many members types >= i can still add
    suffix = np.zeros((T + 1, F), dtype=np.int64)
    for i in range(T - 1, -1, -1):
        suffix[i] = suffix[i + 1] + tf[i] * int(msize[i])
    suffix_total = np.zeros(T + 1, dtype=np.int64)
    for i in range(T - 1, -1, -1):
        suffix_total[i] = suffix_total[i + 1] + int(msize[i])

    out: List[np.ndarray] = []
    counts = np.zeros(F, dtype=np.int64)
    cur = np.zeros(T, dtype=np.int32)
    nodes = 0

    def rec(i: int, total: int) -> bool:
        nonlocal nodes
        nodes += 1
        if nodes > node_budget:
            return False
        if i == T:
            if total == k and np.all(counts >= lo) and np.all(counts <= hi):
                out.append(cur.copy())
                if len(out) > cap:
                    return False
            return True
        # prune: total members still reachable
        if total + suffix_total[i] < k or total > k:
            return True
        # prune: every feature must stay satisfiable
        if np.any(counts > hi) or np.any(counts + suffix[i] < lo):
            return True
        row = reduction.type_feature[i]
        for c in range(min(int(msize[i]), k - total), -1, -1):
            cur[i] = c
            counts[row] += c
            ok = rec(i + 1, total + c)
            counts[row] -= c
            cur[i] = 0
            if not ok:
                return False
        return True

    if not rec(0, 0) or len(out) > cap:
        return None
    if not out:
        return np.zeros((0, T), dtype=np.int32)
    return np.stack(out, axis=0)


@dataclasses.dataclass
class StageCert:
    """Dual certificate of one leximin stage, captured for graftdelta
    (``solvers/delta.py``): enough to decide, after a registry edit, whether
    the stage's optimal face can have changed — and to resume the ladder
    from exactly this point when it has."""

    z: float  # stage value (the min the stage maximized)
    y: np.ndarray  # float64 [T] dual weights scattered over ALL types
    mu: float  # max column price max_c Σ_t y_t·c_t/m_t (the support price)
    fixed_after: np.ndarray  # float64 [T] fixed vector AFTER the stage (-1 ⇒ open)


@dataclasses.dataclass
class TypeLeximin:
    """Result of the enumerated type-space leximin solve."""

    compositions: np.ndarray  # int32 [C, T], the full feasible enumeration
    probabilities: np.ndarray  # float64 [C] final distribution over compositions
    type_values: np.ndarray  # float64 [T] leximin value per type
    eps_dev: float  # max downward deviation of the final distribution
    stages: int
    lp_solves: int
    #: per-stage dual certificates, present only when the caller asked for
    #: them (``capture_certs=True``) — the delta solver's re-pricing basis
    stage_certs: Optional[List[StageCert]] = None


_SLACK = 1e-9  # constraint slack absorbing LP solver round-off


def _linprog(c, A_ub, b_ub, A_eq, b_eq, bounds):
    from citizensassemblies_tpu.solvers.lp_util import robust_linprog

    return robust_linprog(c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=b_eq, bounds=bounds)


#: prescreen size guard: beyond this many columns a probe-fleet bucket would
#: ship tens of MB per lane through the tunnel — the host LPs win there
_SCREEN_MAX_COLS = 32_768


def _batched_probe_prescreen(
    objectives: np.ndarray,
    A_face: np.ndarray,
    b_face: np.ndarray,
    z: float,
    probe_tol: float,
    allowances: np.ndarray,
    cfg,
    log: Optional[RunLog] = None,
) -> Optional[np.ndarray]:
    """Device prescreen of a probe-candidate fleet: witness clearly-loose
    candidates in ONE padded vmapped dispatch (``solvers/batch_lp.py``).

    Every candidate's face LP (``max objectives[i]·x`` over the stage's
    optimal face) is solved approximately on device; a candidate is marked
    loose only when its APPROXIMATE optimizer, clipped, renormalized and
    re-validated **in float64 against the exact face constraints**, attains
    a value strictly above the certificate bound ``z + probe_tol +
    allowance`` — i.e. the same witness-elimination evidence the host
    scheme trusts (``lp_util.probe_confirm_tranche``): a feasible face
    point above the bound proves the host probe could never confirm the
    candidate, so its host LP is pure waste. Candidates the screen cannot
    witness keep their float64 host confirm — the screen only ever REDUCES
    the host-LP count, never certifies. Returns the bool mask, or ``None``
    when the screen is disabled or out of its size envelope.
    """
    from citizensassemblies_tpu.solvers.batch_lp import (
        face_probe_batch_lp,
        lp_batch_enabled,
        solve_lp_batch,
    )

    if cfg is None or not getattr(cfg, "lp_batch_screen", True):
        return None
    if not lp_batch_enabled(cfg):
        return None
    n_cand = len(objectives)
    if n_cand < 2 or A_face.shape[1] > _SCREEN_MAX_COLS:
        return None
    insts = [
        face_probe_batch_lp(objectives[i], A_face, b_face, tol=1e-6)
        for i in range(n_cand)
    ]
    sols = solve_lp_batch(insts, cfg=cfg, log=log, max_iters=8_192)
    loose = np.zeros(n_cand, dtype=bool)
    for i, sol in enumerate(sols):
        x = np.maximum(np.asarray(sol.x, dtype=np.float64), 0.0)
        total = x.sum()
        if not np.isfinite(total) or total <= 0.0:
            continue
        x = x / total
        # strict float64 feasibility on the SAME face the host probes use
        # (b_face already carries the probe scheme's slack): only a genuine
        # face point may witness looseness
        if not (A_face @ x <= b_face).all():
            continue
        if float(objectives[i] @ x) > z + probe_tol + float(allowances[i]) + 1e-9:
            loose[i] = True
    if log is not None:
        log.count("lp_batch_probe_screened", n_cand)
        if loose.any():
            log.count("lp_batch_probe_pruned", int(loose.sum()))
    return loose


def leximin_over_compositions(
    comps: np.ndarray,
    msize: np.ndarray,
    probe_tol: float = 1e-7,
    log: Optional[RunLog] = None,
    cfg=None,
    fixed_init: Optional[np.ndarray] = None,
    capture_certs: bool = False,
) -> TypeLeximin:
    """Exact leximin over the full composition enumeration.

    Runs the reference's outer fixing loop (``leximin.py:383-449``) with the
    portfolio replaced by *every* feasible composition, so no pricing is ever
    needed: each stage is one LP (max the min unfixed type value), and the
    final stage recovers composition probabilities minimizing the max downward
    deviation ε (``leximin.py:453-464``).

    Every fixed tranche is **probe-certified** against the stage's optimal
    face: the dual-proposed candidates (``y > 0`` at a vertex optimum proves
    tightness only at that one optimum) are confirmed by one group LP — if
    ``max Σ_cand M_t·p`` over the face equals ``|cand|·z``, no candidate can
    exceed ``z`` at any optimum — with per-candidate probes on disagreement;
    the remaining near-zero-dual types are probed individually to catch
    degenerately tight ones. The reference trusts the ``y > EPS`` heuristic
    alone (``leximin.py:431-443``); here no tranche is ever fixed prematurely.

    With the batched LP engine enabled (``cfg.lp_batch`` /
    ``cfg.lp_batch_screen``) the probe-candidate fleet is first PRESCREENED
    in one padded vmapped device call (:func:`_batched_probe_prescreen`):
    candidates witnessed loose at a float64-validated face point skip their
    host LPs outright. The screen never certifies — every surviving
    candidate keeps its float64 host confirm — so the certification
    contract is unchanged; only the host-LP count drops.

    ``fixed_init`` warm-starts the fixing ladder: entries ≥ 0 are taken as
    already-fixed type values (a prefix of a previous solve's trajectory,
    graftdelta's resume point), ``-1`` entries stay open — ``None`` is
    identical to the all-open default. ``capture_certs=True`` additionally
    records a :class:`StageCert` per stage on the result.
    """
    log = log or RunLog(echo=False)
    C, T = comps.shape
    M = comps.astype(np.float64) / np.asarray(msize, dtype=np.float64)[None, :]
    MT = np.ascontiguousarray(M.T)  # [T, C]
    if fixed_init is not None:
        fixed = np.asarray(fixed_init, dtype=np.float64).copy()
        if fixed.shape != (T,):
            raise ValueError(f"fixed_init must be float [{T}]")
    else:
        fixed = np.full(T, -1.0)
    coverable = comps.max(axis=0) > 0 if C else np.zeros(T, dtype=bool)
    fixed[~coverable & (fixed < 0)] = 0.0
    certs: List[StageCert] = [] if capture_certs else None
    if (~coverable).any():
        log.emit(
            f"{int((~coverable).sum())} type(s) appear in no feasible committee; "
            f"their probability is 0."
        )
    stages = 0
    lp_solves = 0

    while (fixed < 0).any():
        stages += 1
        unfixed = np.nonzero(fixed < 0)[0]
        done = np.nonzero(fixed >= 0)[0]
        # stage LP over x = [p (C), z]: max z
        #   s.t. -M_t·p + z ≤ 0        (t unfixed)
        #        -M_t·p     ≤ -f_t + slack  (t fixed)
        #        Σp = 1, p ≥ 0
        nu, nd = len(unfixed), len(done)
        A_ub = np.zeros((nu + nd, C + 1))
        A_ub[:nu, :C] = -MT[unfixed]
        A_ub[:nu, C] = 1.0
        b_ub = np.zeros(nu + nd)
        if nd:
            A_ub[nu:, :C] = -MT[done]
            b_ub[nu:] = -(fixed[done] - _SLACK)
        A_eq = np.ones((1, C + 1))
        A_eq[0, C] = 0.0
        c_obj = np.zeros(C + 1)
        c_obj[C] = -1.0
        bounds = [(0, None)] * C + [(None, None)]
        res = _linprog(c_obj, A_ub, b_ub, A_eq, [1.0], bounds)
        lp_solves += 1
        if res.status != 0:
            raise RuntimeError(f"type-space stage LP failed: {res.message}")
        z = float(res.x[C])
        y = -np.asarray(res.ineqlin.marginals[:nu])  # dual weights, ≥ 0

        # optimal-face constraints, hoisted: every unfixed type ≥ z, fixed ≥ f
        # (only the probe objective row changes per candidate)
        A_p = np.concatenate([-MT[unfixed], -MT[done]], axis=0) if nd else -MT[unfixed]
        b_p = np.concatenate(
            [np.full(nu, -(z - _SLACK)), -(fixed[done] - _SLACK)]
        ) if nd else np.full(nu, -(z - _SLACK))
        A_eq_p = np.ones((1, C))
        bounds_p = [(0, None)] * C

        def _face_max_over(rhs):
            def fm(obj_rows: np.ndarray):
                nonlocal lp_solves
                r = _linprog(-obj_rows, A_p, rhs, A_eq_p, [1.0], bounds_p)
                lp_solves += 1
                if r.status == 0:
                    return float(-r.fun), np.asarray(r.x)
                # infeasible vs failed — no optimizer either way
                return (-np.inf, None) if r.status == 2 else (None, None)
            return fm

        face_max = _face_max_over(b_p)
        # retry probe for objective-specific infeasible reports: floors 10×
        # looser — a superset face, so its optimum is a valid upper bound
        face_max_relaxed = _face_max_over(b_p + 9.0 * _SLACK)

        # tranche candidates from the duals, probe-certified via the shared
        # group-then-individual scheme (lp_util.probe_confirm_tranche). The
        # face floors are each relaxed by _SLACK in normalized units — i.e.
        # _SLACK·m_u raw members — and at most that freed mass can be
        # re-routed into a candidate, so tightness is judged up to
        # _SLACK·Σm/m_t or genuinely tight types probe "loose" on large pools
        msz = np.asarray(msize, dtype=np.float64)
        slack_gain = _SLACK * float(msz.sum())
        tranche = np.zeros(nu, dtype=bool)
        cand = np.nonzero(y > 1e-9)[0]
        # near-zero dual weight can still be degenerately tight everywhere —
        # but a type already above z at *this* optimum provably is not, so
        # only the ones sitting at z need a probe
        vals = MT[unfixed] @ np.maximum(res.x[:C], 0.0)
        singles = np.nonzero((y <= 1e-9) & (vals <= z + probe_tol))[0]
        # device prescreen of the WHOLE candidate fleet (dual-proposed +
        # near-zero-dual) as one batched dispatch: witnessed-loose members
        # skip their host LPs; everyone else keeps the float64 confirm
        from citizensassemblies_tpu.solvers.lp_util import ALLOWANCE_CAP

        pre_cand = pre_singles = None
        if len(cand) + len(singles) >= 2:
            fleet = np.concatenate([cand, singles]).astype(np.int64)
            allow_fleet = np.minimum(
                slack_gain / msz[unfixed[fleet]], ALLOWANCE_CAP
            )
            loose_mask = _batched_probe_prescreen(
                MT[unfixed[fleet]], A_p, b_p, z, probe_tol, allow_fleet,
                cfg, log=log,
            )
            if loose_mask is not None:
                pre_cand = loose_mask[: len(cand)]
                pre_singles = loose_mask[len(cand) :]
        if len(cand):
            conf = probe_confirm_tranche(
                face_max, MT[unfixed[cand]], z, probe_tol,
                slack_gain / msz[unfixed[cand]],
                term_deficit=_SLACK, log=log.emit,
                face_max_relaxed=face_max_relaxed,
                presumed_loose=pre_cand,
            )
            tranche[cand[conf]] = True
        for jj, j in enumerate(singles):
            if pre_singles is not None and pre_singles[jj]:
                continue  # witnessed loose on device: the host LP is waste
            if probe_confirm_tranche(
                face_max, MT[unfixed[j]][None, :], z, probe_tol,
                np.array([slack_gain / float(msz[unfixed[j]])]),
                term_deficit=_SLACK, log=log.emit,
                face_max_relaxed=face_max_relaxed,
            )[0]:
                tranche[j] = True
        if not tranche.any():
            tranche[np.argmax(y)] = True  # progress guard
        fixed[unfixed[tranche]] = max(0.0, z)
        if capture_certs:
            marg = -np.asarray(res.ineqlin.marginals, dtype=np.float64)
            y_full = np.zeros(T)
            y_full[unfixed] = marg[:nu]
            if nd:
                y_full[done] = marg[nu:]
            prices = M @ y_full
            certs.append(
                StageCert(
                    z=z,
                    y=y_full,
                    mu=float(prices.max()) if C else 0.0,
                    fixed_after=fixed.copy(),
                )
            )
        log.emit(
            f"Stage {stages}: value {z:.6f}, fixed {int(tranche.sum())} type(s), "
            f"{int((fixed >= 0).sum())}/{T} done."
        )

    # final LP: min ε s.t. M_t·p ≥ f_t − ε ∀t, Σp = 1 (leximin.py:453-464)
    A_ub = np.concatenate([-MT, -np.ones((T, 1))], axis=1)
    b_ub = -(fixed - _SLACK)
    A_eq = np.ones((1, C + 1))
    A_eq[0, C] = 0.0
    c_obj = np.zeros(C + 1)
    c_obj[C] = 1.0
    res = _linprog(c_obj, A_ub, b_ub, A_eq, [1.0], [(0, None)] * C + [(0, None)])
    lp_solves += 1
    if res.status != 0:
        raise RuntimeError(f"type-space final LP failed: {res.message}")
    probs = np.maximum(res.x[:C], 0.0)
    probs = probs / probs.sum()
    return TypeLeximin(
        compositions=comps,
        probabilities=probs,
        type_values=fixed,
        eps_dev=float(res.x[C]),
        stages=stages,
        lp_solves=lp_solves,
        stage_certs=certs,
    )


def _household_disjoint_pick(
    scores: np.ndarray,
    rot: np.ndarray,
    houses: np.ndarray,
    ct: int,
    used: set,
) -> np.ndarray:
    """Indices of ``ct`` members maximizing ``scores`` (ties broken by
    ``rot``) whose households are distinct from each other and from ``used``;
    marks the chosen households used.

    Conflicts only arise within one household class (a household's members
    all carry the class in their augmented feature row — see
    ``solvers/quotient.py``), and the class-cap quota row guarantees the
    class's total duty count never exceeds its household count, so this
    greedy always finds ``ct`` members: every class-``c`` orbit has a member
    in each of the class's ``m_c`` households.
    """
    order = np.lexsort((rot, -scores))
    picked: List[int] = []
    for j in order:
        h = int(houses[j])
        if h in used:
            continue
        used.add(h)
        picked.append(int(j))
        if len(picked) == ct:
            break
    if len(picked) < ct:
        # the input contract (class-cap quota rows) is violated; failing
        # loudly beats emitting an undersized panel that would enter the
        # distribution with positive probability
        raise ValueError(
            f"household-disjoint pick infeasible: needed {ct} members but "
            f"only {len(picked)} households available — compositions violate "
            "the quotient's class caps"
        )
    return np.asarray(picked, dtype=np.int64)


def greedy_decompose(
    comps: np.ndarray,
    probs: np.ndarray,
    reduction: TypeReduction,
    targets: np.ndarray,
    support_eps: float = 1e-11,
    max_panels: int = 16_384,
    households: Optional[np.ndarray] = None,
    delta_cap: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Water-filling decomposition of a composition distribution into panels.

    Serves each composition's probability mass in slices; every slice's panel
    takes, per type, the ``c_t`` members with the largest remaining need
    (need = target probability not yet realized), ties rotated by a per-type
    cursor so equal-need members are cycled fairly. The slice probability is
    the largest step that overshoots no member. Exact up to float rounding on
    most instances (the caller verifies and LP-polishes any residual);
    portfolio size is typically O(Σ_t m_t/c_t) per support composition.

    With ``households`` (int[n] group ids, on a household-quotient reduction —
    ``solvers/quotient.py``), each slice's picks are additionally
    household-disjoint, so every emitted panel honors the ≤1-per-household
    constraint exactly (reference ``leximin.py:211-221``).

    ``delta_cap`` (> 0) bounds each slice's probability mass: when the
    mixture is a *basic* LP solution (sparse support, e.g. from an exact
    host master), the natural need-driven steps are too coarse to mix
    members — on a nexus-shaped instance (k/n ≈ 0.5) the uncapped greedy
    leaves a 7e-3 residual that costs ~18 host-LP pricing rounds to polish,
    while capping at ~tol yields residual ≈ 0.4·cap with no LP at all.
    """
    sel = probs > support_eps
    comps = comps[sel]
    p = probs[sel].astype(np.float64)
    p = p / p.sum()
    n = reduction.n
    T = reduction.T
    msize = reduction.msize
    members = reduction.members

    # serve compositions largest-first so late slices retain mixing freedom
    order = np.argsort(-p)

    # the slice loop is the host hot path (~90k per-type partial sorts on a
    # nexus_170-shaped instance); the native slicer runs the identical
    # algorithm ~100× faster, with the Python loop below as the reference
    # implementation and fallback
    from citizensassemblies_tpu.solvers.native_oracle import (
        greedy_decompose_native,
    )

    per_type_need = np.array(
        [targets[members[t][0]] if len(members[t]) else 0.0 for t in range(T)]
    )
    got = greedy_decompose_native(
        reduction, comps[order], p[order], per_type_need,
        max_panels, households=households, delta_cap=delta_cap,
    )
    if got is not None:
        return got

    house_of = (
        [households[members[t]] for t in range(T)] if households is not None else None
    )
    needs = [np.full(int(msize[t]), 0.0) for t in range(T)]
    for t in range(T):
        needs[t][:] = targets[members[t][0]] if len(members[t]) else 0.0
    cursors = np.zeros(T, dtype=np.int64)
    panels: List[np.ndarray] = []
    pprobs: List[float] = []
    for s in order:
        c = comps[s]
        rho = float(p[s])
        while rho > 1e-12 and len(panels) < max_panels:
            row = np.zeros(n, dtype=bool)
            delta = min(rho, delta_cap) if delta_cap > 0 else rho
            chosen: List[Tuple[int, np.ndarray]] = []
            used_houses: set = set()
            for t in range(T):
                ct, mt = int(c[t]), int(msize[t])
                if not ct:
                    continue
                rot = (np.arange(mt) - cursors[t]) % mt
                if house_of is None:
                    idx = np.lexsort((rot, -needs[t]))[:ct]
                else:
                    idx = _household_disjoint_pick(
                        needs[t], rot, house_of[t], ct, used_houses
                    )
                chosen.append((t, idx))
                m = float(needs[t][idx].min())
                if m > 1e-15:
                    delta = min(delta, m)
            if delta <= 1e-15:
                # forced overshoot; the LP polish absorbs it
                delta = min(rho, delta_cap) if delta_cap > 0 else rho
            for t, idx in chosen:
                row[members[t][idx]] = True
                needs[t][idx] -= delta
                cursors[t] = (cursors[t] + int(c[t])) % max(int(msize[t]), 1)
            panels.append(row)
            pprobs.append(delta)
            rho -= delta
    return np.stack(panels, axis=0), np.asarray(pprobs, dtype=np.float64)


def decompose_with_pricing(
    comps: np.ndarray,
    probs: np.ndarray,
    reduction: TypeReduction,
    targets: np.ndarray,
    budget: int = 16_384,
    support_eps: float = 1e-11,
    max_rounds: int = 200,
    log: Optional[RunLog] = None,
    tol: float = 1e-9,
    households: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, float]:
    """Exact panel decomposition of a composition distribution.

    ``budget`` bounds the panel portfolio the greedy water-filling seed may
    emit; any mass it could not serve within the budget is recovered by the
    pricing LP loop below.

    Finds concrete panels and probabilities whose per-agent allocation matches
    ``targets`` up to LP tolerance, via column generation on the final LP
    (min ε s.t. ``Pᵀp ≥ targets − ε``, ``Σp = 1``) with **closed-form
    pricing**: the best panel for dual weights ``y`` within a feasible
    composition ``c`` simply takes each type's ``c_t`` highest-weight members,
    so pricing over the full enumeration is one prefix-sum lookup per
    composition — no ILP, unlike the reference's committee pricing
    (``leximin.py:420-424``). An exact decomposition always exists (uniform
    within-type selection is a finite convex combination of concrete panels),
    so ε converges to ~0. Returns ``(panels bool [R, n], probs, ε)``.

    With ``households`` every emitted panel is household-disjoint; the
    prefix-sum pricing value then upper-bounds the realized column's value
    (the disjoint pick may have to skip a top member), so a stall guard
    breaks the loop when ε stops improving instead of trusting the estimate.
    """
    log = log or RunLog(echo=False)
    n = reduction.n
    T = reduction.T
    members = reduction.members
    maxm = reduction.maxm

    # seed: greedy water-filling decomposition — usually already within
    # tolerance, in which case no LP runs at all
    tol = max(tol, 1e-9)
    P0, q0 = greedy_decompose(
        comps, probs, reduction, targets, support_eps=support_eps,
        max_panels=budget, households=households,
    )
    total = q0.sum()
    if abs(total - 1.0) < tol:
        # two-sided: overshoot counts too — mass conservation means a small
        # one-sided deficit can fund a concentrated overshoot elsewhere
        dev = float(np.max(np.abs(targets - P0.T.astype(np.float64) @ q0)))
        if dev <= tol:
            return P0, q0 / total, max(dev, 0.0)
        if tol >= 4e-5:
            # coarse-slice failure mode (sparse basic mixtures at high k/n):
            # retry once with capped slices — the cap equidistributes
            # members (measured residual ≈ 0.4·cap), trading a larger
            # portfolio for skipping the LP pricing loop entirely
            P1, q1 = greedy_decompose(
                comps, probs, reduction, targets, support_eps=support_eps,
                max_panels=budget, households=households,
                delta_cap=1.5 * tol,
            )
            t1 = q1.sum()
            if abs(t1 - 1.0) < tol:
                dev1 = float(
                    np.max(np.abs(targets - P1.T.astype(np.float64) @ q1))
                )
                if dev1 <= tol:
                    return P1, q1 / t1, max(dev1, 0.0)
                if dev1 < dev:
                    P0, q0, dev = P1, q1, dev1
    rows: List[np.ndarray] = [r for r in P0]
    seen = {r.tobytes() for r in rows}

    from citizensassemblies_tpu.solvers.highs_backend import solve_final_primal_lp_duals

    add_per_round = 256  # closed-form pricing is ~free; bigger rounds cut
    # the number of host LP solves, which are the loop's whole cost (64 made
    # a nexus-class polish pay ~18 LP rounds for ~1150 columns)
    p = None
    eps_dev = 1.0
    best_eps = np.inf
    stalled = 0
    for _ in range(max_rounds):
        P = np.stack(rows, axis=0)
        p, eps_dev, y, mu = solve_final_primal_lp_duals(P, targets)
        if eps_dev <= tol:
            break
        if households is not None:
            # the pricing estimate below is only an upper bound under
            # household disjointness — stop when realized columns no longer
            # move ε rather than looping on phantom improvement
            if eps_dev > best_eps - 1e-12:
                stalled += 1
                if stalled >= 8:
                    break
            else:
                best_eps, stalled = eps_dev, 0
        # price: value(c) = Σ_t (sum of the c_t largest y within type t)
        prefix = np.zeros((T, maxm + 1))
        tops: List[np.ndarray] = []
        for t in range(T):
            order = members[t][np.argsort(-y[members[t]], kind="stable")]
            tops.append(order)
            prefix[t, 1 : len(order) + 1] = np.cumsum(y[order])
        values = prefix[np.arange(T)[None, :], comps].sum(axis=1)  # [C]
        cand = np.argsort(-values)[: add_per_round]
        cand = cand[values[cand] > -mu + 1e-10]
        if len(cand) == 0:
            break  # no improving panel exists anywhere: ε is optimal
        added = 0
        for ci in cand:
            row = np.zeros(n, dtype=bool)
            if households is None:
                for t in range(T):
                    ct = int(comps[ci, t])
                    if ct:
                        row[tops[t][:ct]] = True
            else:
                used_houses: set = set()
                short = False
                for t in range(T):
                    ct = int(comps[ci, t])
                    if not ct:
                        continue
                    # tops[t] is y-descending member ids; realize the duty
                    # household-disjointly (skips cost at most the estimate)
                    picked = 0
                    for a in tops[t]:
                        h = int(households[a])
                        if h in used_houses:
                            continue
                        used_houses.add(h)
                        row[a] = True
                        picked += 1
                        if picked == ct:
                            break
                    if picked < ct:
                        short = True  # class caps violated for this column
                        break
                if short:
                    continue  # never add an undersized panel
            kb = row.tobytes()
            if kb not in seen:
                seen.add(kb)
                rows.append(row)
                added += 1
        if added == 0:
            break  # numerically stalled
        p = None
    if p is None or len(p) != len(rows):
        P = np.stack(rows, axis=0)
        p, eps_dev, _, _ = solve_final_primal_lp_duals(P, targets)
    else:
        P = np.stack(rows, axis=0)
    return P, p, float(eps_dev)


def expand_compositions(
    comps: np.ndarray,
    probs: np.ndarray,
    reduction: TypeReduction,
    budget: int = 4096,
    support_eps: float = 1e-11,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Expand a distribution over compositions into concrete panels.

    Members are assigned within each type so that every agent of type t is
    selected with (near-)equal probability ``Σ_c p_c c_t/m_t``:

    * **exact path** — when the total rotation count fits the budget, each
      composition ``c`` is expanded into ``R_c = lcm_t(m_t/gcd(c_t, m_t))``
      block-rotated panels of probability ``p_c/R_c``; within-type uniformity
      is then *exact* (each member appears in exactly ``R_c·c_t/m_t`` panels);
    * **equidistributed path** — otherwise each composition receives
      ``R_c ≈ budget·p_c`` panels with equidistributed rotation offsets
      (``floor(r·m_t/R_c)``), so member counts differ by at most one and the
      per-agent deviation from composition c is at most ``p_c/R_c ≈ 1/budget``.

    Callers polish the result with an agent-space LP against the exact type
    targets, which removes the residual construction error.

    Returns ``(panels bool [R, n], panel_probs float64 [R])``.
    """
    sel = probs > support_eps
    comps = comps[sel]
    p = probs[sel].astype(np.float64)
    p = p / p.sum()
    S, T = comps.shape
    n = reduction.n
    msize = reduction.msize
    members = reduction.members

    def lcm(a: int, b: int) -> int:
        return a // gcd(a, b) * b

    exact_R = []
    total = 0
    for c in comps:
        R = 1
        for t in range(T):
            ct, mt = int(c[t]), int(msize[t])
            if 0 < ct < mt:
                R = lcm(R, mt // gcd(ct, mt))
                if R > budget:
                    break
        exact_R.append(R)
        total += R
        if total > budget:
            break

    panels: List[np.ndarray] = []
    pprobs: List[float] = []
    if total <= budget:
        for s in range(S):
            c, R = comps[s], exact_R[s]
            for r in range(R):
                row = np.zeros(n, dtype=bool)
                for t in range(T):
                    ct, mt = int(c[t]), int(msize[t])
                    if ct:
                        idx = (r * ct + np.arange(ct)) % mt
                        row[members[t][idx]] = True
                panels.append(row)
                pprobs.append(p[s] / R)
    else:
        # proportional rotation counts, ≥ 1 per support composition
        R_s = np.maximum(1, np.round(p * budget).astype(int))
        for s in range(S):
            c, R = comps[s], int(R_s[s])
            for r in range(R):
                row = np.zeros(n, dtype=bool)
                for t in range(T):
                    ct, mt = int(c[t]), int(msize[t])
                    if ct:
                        start = (r * mt) // R
                        idx = (start + np.arange(ct)) % mt
                        row[members[t][idx]] = True
                panels.append(row)
                pprobs.append(p[s] / R)

    # merge duplicate panels (e.g. trivial rotations when c_t ∈ {0, m_t})
    seen: dict = {}
    rows: List[np.ndarray] = []
    q: List[float] = []
    for row, pr in zip(panels, pprobs):
        kb = row.tobytes()
        if kb in seen:
            q[seen[kb]] += pr
        else:
            seen[kb] = len(rows)
            rows.append(row)
            q.append(pr)
    return np.stack(rows, axis=0), np.asarray(q, dtype=np.float64)
