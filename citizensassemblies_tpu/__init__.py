"""citizensassemblies_tpu — a TPU-native framework for fair citizens'-assembly selection.

A ground-up JAX/XLA re-design of the capabilities of the
``sirandreww/citizensassemblies-replication`` package (Flanigan, Gölz, Gupta,
Hennig, Procaccia — "Fair Algorithms for Selecting Citizens' Assemblies", 2021):

* **LEGACY** — the Sortition Foundation's greedy stratified sampler, re-expressed
  as a jittable ``lax.scan`` over dense count tensors and ``vmap``-ed over
  thousands of Monte-Carlo chains (reference: ``legacy.py``).
* **LEXIMIN** — the exact lexicographic-maximin distribution over feasible
  panels, via column generation with on-device LP solves (PDHG) and a massively
  parallel stochastic pricing oracle, certified by an exact MILP oracle
  (reference: ``leximin.py``).
* **XMIN** — LEXIMIN's probabilities re-spread over a maximally large support
  of panels via a min-L2 final stage (reference: ``xmin.py``).
* A full analysis/reporting layer (statistics, plots, golden-format outputs)
  mirroring the reference's ``analysis.py``.

Core representational shift: instead of dict-of-dicts over string keys, the
framework works on the dense incidence matrix ``A ∈ {0,1}^{n×F}`` (agent ×
feature-value), quota vectors ``q_min, q_max ∈ Z^F`` and panel size ``k``.
A panel is a binary vector ``x`` with ``A.T @ x ∈ [q_min, q_max]`` and
``sum(x) = k``; a portfolio is a matrix ``P ∈ {0,1}^{|C|×n}``; a probability
allocation is ``π = P.T @ p`` — all one-line jittable reductions.
"""

__version__ = "0.1.0"

import os as _os

# Persistent XLA compilation cache: the solver stack jits a handful of
# bucket-padded PDHG/sampler shapes whose compiles cost seconds each; caching
# them on disk makes every process after the first start warm (the reference
# has no compilation step to amortize — this keeps cold-start parity).
if not _os.environ.get("CITIZENS_TPU_NO_COMPILE_CACHE"):
    try:
        import jax as _jax

        # respect a cache dir the host application (or env) already chose
        if getattr(_jax.config, "jax_compilation_cache_dir", None) is None:
            _jax.config.update(
                "jax_compilation_cache_dir",
                _os.path.join(
                    _os.path.expanduser("~"), ".cache", "citizensassemblies_tpu_xla"
                ),
            )
            _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - cache is a pure optimization
        pass

from citizensassemblies_tpu.core.instance import (  # noqa: F401
    DenseInstance,
    FeatureSpace,
    InfeasibleQuotasError,
    Instance,
    SelectionError,
    compute_households,
    featurize,
    read_instance,
    read_instance_dir,
)
from citizensassemblies_tpu.utils.config import Config, default_config  # noqa: F401
