"""graftpod runtime: process bootstrap + the hosts×devices mesh topology.

One module owns the three facts every distributed call site needs:

* **The axis names.** ``AXIS_CHAINS``/``AXIS_AGENTS`` are the canonical
  collective axis names of the framework's two parallel dimensions (data
  parallelism over Monte-Carlo chains / pricing candidates, model parallelism
  over the agent axis). Everything outside this module imports them —
  graftlint R10 flags a hardcoded ``"chains"`` literal in a collective or
  PartitionSpec anywhere else, because a renamed axis that half the call
  sites missed fails only at runtime, on the biggest mesh, inside a psum.

* **The process layout.** :func:`bootstrap` runs
  ``jax.distributed.initialize`` exactly once when a coordinator is
  configured (env vars or ``Config.dist_coordinator``) and is a no-op
  single-process fallback otherwise, so the same entry point works on a
  laptop, an 8-virtual-device CI host, and a real multi-host pod.

* **The mesh.** :func:`build_topology` lays all visible devices out as a 2-D
  ``chains × agents`` mesh whose chains axis spans processes host-major
  (``jax.devices()`` is process-major, so each host's devices land in
  contiguous chain rows — the layout under which the chain-sharded key
  streams of ``parallel/mc.py`` feed each process's rows without crossing
  DCN). Degrades gracefully: multi-host ⇒ hosts×local, one host ⇒ 1×N over
  the local devices, one device ⇒ the trivial 1×1 mesh, all through the same
  code path. ``parallel/mesh.py``'s ``make_mesh``/``default_mesh`` delegate
  here; they are kept as the compatibility surface for existing call sites.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from citizensassemblies_tpu.robust import inject

#: canonical collective axis names — THE definition site (graftlint R10).
AXIS_CHAINS = "chains"
AXIS_AGENTS = "agents"
#: the full data-parallel reduction set: a batch sharded over every mesh
#: device uses both axes, and psums over this tuple reduce across the pod.
CHAIN_AXES: Tuple[str, str] = (AXIS_CHAINS, AXIS_AGENTS)

#: environment contract for multi-process bootstrap (the standard
#: coordinator triple, prefixed so an unrelated launcher's vars don't
#: accidentally arm a pod bootstrap).
ENV_COORDINATOR = "CITIZENS_DIST_COORDINATOR"
ENV_NUM_PROCESSES = "CITIZENS_DIST_NUM_PROCESSES"
ENV_PROCESS_ID = "CITIZENS_DIST_PROCESS_ID"

#: environment contract for the graftfleet serving fleet: the fleet bench's
#: parent exports these into every serving child so the router, the
#: artifact-path scoping and the rollup all agree on the fleet shape without
#: requiring a jax.distributed coordinator (serving processes are
#: independent OS processes, each with its own virtual-device mesh).
ENV_FLEET_PROCESSES = "CITIZENS_FLEET_PROCESSES"
ENV_FLEET_INDEX = "CITIZENS_FLEET_INDEX"

_LOCK = threading.Lock()
_BOOTSTRAP: Optional["BootstrapInfo"] = None
_DEFAULT_TOPOLOGY: Optional["Topology"] = None


@dataclasses.dataclass(frozen=True)
class BootstrapInfo:
    """Outcome of :func:`bootstrap` (cached process-wide)."""

    initialized: bool  # did jax.distributed.initialize actually run
    coordinator: str  # "" on the single-process fallback
    process_index: int
    process_count: int


@dataclasses.dataclass(frozen=True)
class Topology:
    """A built mesh plus the host-layout facts call sites partition by."""

    mesh: Mesh
    hosts: int  # jax process count
    devices_per_host: int
    agents_axis: int

    @property
    def shape(self) -> Dict[str, int]:
        return dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)


def bootstrap(cfg=None) -> BootstrapInfo:
    """Initialize multi-process JAX when a coordinator is configured.

    Consults ``CITIZENS_DIST_COORDINATOR`` / ``_NUM_PROCESSES`` /
    ``_PROCESS_ID`` (or ``Config.dist_coordinator`` for the address when the
    env var is absent). With no coordinator anywhere this is the
    single-process fallback: nothing is initialized and the returned info
    reports the process facts JAX already knows. Idempotent — the first
    call's outcome is cached, later calls (any thread) return it.
    """
    global _BOOTSTRAP
    with _LOCK:
        if _BOOTSTRAP is not None:
            return _BOOTSTRAP
        coord = os.environ.get(ENV_COORDINATOR, "") or str(
            getattr(cfg, "dist_coordinator", "") or ""
        )
        if coord:
            num = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
            pid = int(os.environ.get(ENV_PROCESS_ID, "0"))
            try:
                jax.distributed.initialize(
                    coordinator_address=coord, num_processes=num, process_id=pid
                )
                initialized = True
            except RuntimeError:
                # already initialized by an outer launcher — keep its state
                initialized = False
        else:
            initialized = False
        _BOOTSTRAP = BootstrapInfo(
            initialized=initialized,
            coordinator=coord,
            process_index=int(jax.process_index()),
            process_count=int(jax.process_count()),
        )
        return _BOOTSTRAP


def build_topology(
    n_devices: Optional[int] = None,
    agents_axis: int = 1,
    axis_names: Optional[Tuple[str, str]] = None,
    cfg=None,
) -> Topology:
    """Build the hosts×devices mesh as a 2-D ``chains × agents`` Mesh.

    ``jax.devices()`` enumerates process-major, so the row-major reshape
    below gives every host a contiguous block of chain rows — the property
    :func:`process_slice` and the pre-partitioned feeding layer rely on.
    ``agents_axis`` devices are dedicated to the agent dimension; it must
    divide each host's share of the selected devices so no agent-sharded
    row straddles DCN.
    """
    bootstrap(cfg)
    devices = jax.devices()
    n = n_devices or len(devices)
    if n % max(agents_axis, 1) != 0:
        raise ValueError(f"n_devices={n} not divisible by agents_axis={agents_axis}")
    arr = np.asarray(devices[:n]).reshape(n // agents_axis, agents_axis)
    hosts = int(jax.process_count())
    per_host = max(1, n // max(hosts, 1))
    return Topology(
        mesh=Mesh(arr, axis_names or CHAIN_AXES),
        hosts=hosts,
        devices_per_host=per_host,
        agents_axis=agents_axis,
    )


def topology_mesh(
    n_devices: Optional[int] = None,
    axis_names: Optional[Tuple[str, str]] = None,
    agents_axis: int = 1,
) -> Mesh:
    """Mesh-only convenience — the delegate behind ``parallel.mesh.make_mesh``."""
    return build_topology(
        n_devices, agents_axis=agents_axis, axis_names=axis_names
    ).mesh


def default_topology() -> Topology:
    """Process-cached topology over every visible device (pure chain
    parallelism) — the delegate behind ``parallel.mesh.default_mesh``.
    Rebuilt when the visible device count changes (forced-device tests)."""
    global _DEFAULT_TOPOLOGY
    topo = _DEFAULT_TOPOLOGY
    if topo is None or topo.n_devices != len(jax.devices()):
        topo = build_topology()
        _DEFAULT_TOPOLOGY = topo
    return topo


def effective_mesh(cfg=None, log=None) -> Optional[Mesh]:
    """The mesh multi-device call sites should shard over, or ``None``.

    ``None`` means "stay on the undistributed single-device path": either
    only one device is visible, or ``Config.dist_mesh`` is off — the
    ``mesh_to_single_device`` rung of the degradation ladder, which a
    retry walks after a collective-layer fault. This is also the dist
    collective boundary's fault site: a chaos spec arming
    ``dist_collective`` makes handing out a multi-device mesh raise, so the
    retry policy demonstrably lands the run on the single-device rung.
    """
    if cfg is not None and not getattr(cfg, "dist_mesh", True):
        return None
    topo = default_topology()
    if topo.n_devices <= 1:
        return None
    inject.raise_if("dist_collective", log)
    if log is not None:
        stamp_mesh_gauges(log, topo.mesh)
    return topo.mesh


def process_slice(n_items: int, topo: Optional[Topology] = None) -> Tuple[int, int]:
    """The ``[start, stop)`` share of ``n_items`` this process owns.

    Host-pricing work (the ``_AnchorPricer``/``DevicePricer`` task batches)
    partitions by this so each process prices only its mesh slice; the
    single-process slice is the whole range, keeping the laptop/CI path
    bit-identical to the pre-pod schedule. Items are dealt in contiguous
    ceil-balanced blocks, same convention as the chain-axis shard layout.
    """
    topo = topo or default_topology()
    hosts = max(topo.hosts, 1)
    pid = int(jax.process_index())
    per = -(-n_items // hosts)  # ceil
    return min(pid * per, n_items), min((pid + 1) * per, n_items)


def host_lane() -> int:
    """This process's span-lane id (0 on single-process runs). grafttrace
    dispatch spans carry it as a ``host`` attribute so a pod run's traces
    separate per process instead of interleaving into one lane."""
    return int(jax.process_index())


def stamp_mesh_gauges(log, mesh: Mesh) -> None:
    """Latest-wins mesh gauges on the metrics registry: how many hosts and
    devices the current mesh spans, and which process stamped it."""
    log.gauge("dist_mesh_hosts", int(jax.process_count()))
    log.gauge("dist_mesh_devices", int(mesh.devices.size))
    log.gauge("dist_process_index", int(jax.process_index()))


def fleet_process_count(cfg=None) -> int:
    """How many serving processes the fleet runs.

    Resolution order: ``Config.fleet_processes`` when > 0, else the
    ``CITIZENS_FLEET_PROCESSES`` environment contract, else the jax process
    count (1 on a laptop). The fleet contract is deliberately separate from
    the jax.distributed triple above: serving processes are independent OS
    processes routed by tenant affinity, not members of one SPMD program.
    """
    n = int(getattr(cfg, "fleet_processes", 0) or 0)
    if n > 0:
        return n
    env = os.environ.get(ENV_FLEET_PROCESSES, "")
    if env:
        return max(int(env), 1)
    return max(int(jax.process_count()), 1)


def fleet_process_index() -> int:
    """This process's fleet slot: ``CITIZENS_FLEET_INDEX`` when set (the
    fleet bench's children), else the jax process index (0 on a laptop)."""
    env = os.environ.get(ENV_FLEET_INDEX, "")
    if env:
        return max(int(env), 0)
    return int(jax.process_index())


def scoped_artifact_path(path: str) -> str:
    """``artifacts/trace.json`` → ``artifacts/trace.p2.json`` on fleet
    process 2 — the multi-process artifact contract. Every fleet child
    writing evidence under a shared directory (traces, SLO/chaos reports,
    metrics dumps) routes its path through here so concurrent processes
    never clobber each other; single-process runs (index 0, fleet of 1)
    return the path unchanged, keeping every existing artifact name stable.
    """
    idx = fleet_process_index()
    if idx == 0 and fleet_process_count() <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{idx}{ext}"


def reset_for_tests() -> None:
    """Drop the cached bootstrap/topology (test isolation only)."""
    global _BOOTSTRAP, _DEFAULT_TOPOLOGY
    with _LOCK:
        _BOOTSTRAP = None
        _DEFAULT_TOPOLOGY = None
