"""graftpod partitioning: declared-once sharding specs + reshard accounting.

SNIPPETS.md's pjit excerpts ([1]-[3]) prescribe the pod idiom this module
implements: inputs are **pre-partitioned** once, into the same NamedSharding
every consuming stage declares, so pjit'd stages hand arrays to each other
without XLA inserting a resharding collective between them. The specs for
the two shardable axes live here and only here:

* the **Monte-Carlo chain axis** (``parallel/mc.py``): key streams and chain
  batches shard their leading axis over every mesh device
  (:func:`chain_batch`), portfolios shard rows over ``chains`` and the agent
  dimension over ``agents`` (:func:`portfolio`, :func:`chain_rows`);
* the **batch-LP bucket axis** (``solvers/batch_lp.py`` /
  ``service/batcher.py``): padded bucket operands shard their leading
  (instance) axis over the whole mesh (:func:`bucket`).

:func:`prepartition` is the single placement point. It distinguishes the
three cases the ``dist_reshards`` contract cares about: an operand already
in the declared sharding passes through untouched (the steady state — zero
cost, zero count); a host array is uploaded once and counted as a
``dist_placements``; a *device* array committed to a different sharding is
re-laid-out and counted as a ``dist_reshards`` — the bug class this gauge
exists to keep at zero (``bench.py --dist`` asserts the steady-state round
counts none, the same enforcement shape as ``decomp_host_syncs``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.dist.runtime import AXIS_AGENTS, AXIS_CHAINS, CHAIN_AXES
from citizensassemblies_tpu.utils.memo import LRU

# Declared-once spec cache: NamedSharding construction is cheap but the
# contract is identity — every stage that names the same (mesh, role, ndim)
# must hand off THE SAME sharding object family, so equality checks in
# prepartition are structural no-ops in the steady state. Mesh-keyed LRU,
# same eviction discipline as the shard_map memo caches (graftlint R10).
_SPEC_CACHE: LRU = LRU(cap=32, name="dist_specs")


def _cached(mesh: Mesh, role: str, ndim: int, spec: P) -> NamedSharding:
    key = (mesh, role, ndim)
    sh = _SPEC_CACHE.get(key)
    if sh is None:
        sh = NamedSharding(mesh, spec)
        _SPEC_CACHE[key] = sh
    return sh


def chain_batch(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Leading axis over EVERY mesh device (chains and agents axes both):
    the layout of per-chain key streams and chain-sharded draw batches."""
    return _cached(
        mesh, "chain_batch", ndim, P(CHAIN_AXES, *([None] * (ndim - 1)))
    )


def portfolio(mesh: Mesh) -> NamedSharding:
    """Committee matrices: rows over ``chains``, agent axis over ``agents``."""
    return _cached(mesh, "portfolio", 2, P(AXIS_CHAINS, AXIS_AGENTS))


def chain_rows(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Leading axis over ``chains`` only (per-panel probability vectors)."""
    return _cached(
        mesh, "chain_rows", ndim, P(AXIS_CHAINS, *([None] * (ndim - 1)))
    )


def bucket(mesh: Mesh, ndim: int) -> NamedSharding:
    """Batch-LP bucket operands: the padded instance axis over the whole
    mesh (both axes), trailing dims replicated."""
    return _cached(
        mesh, "bucket", ndim, P(mesh.axis_names, *([None] * (ndim - 1)))
    )


def rows(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Dual-LP row shards (``parallel/solver.py``): the leading
    (constraint-row) axis over the whole mesh, trailing dims replicated —
    the layout the sharded PDHG core's ``in_specs`` declare per device."""
    return _cached(
        mesh, "rows", ndim, P(mesh.axis_names, *([None] * (ndim - 1)))
    )


def replicated(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    return _cached(mesh, "replicated", ndim, P())


#: declared role name -> spec builder — the introspectable export graftspmd
#: (``lint/spmd.py``) cross-references: a registered core's ``arg_roles``
#: name these roles, and the S2 contract check compares each role's
#: NamedSharding against the actual ``mhlo.sharding`` annotation on the
#: lowered module's parameters. Adding a role here is what makes it
#: declarable; a spec spelled anywhere else is a graftlint R12 violation.
ROLE_BUILDERS = {
    "chain_batch": chain_batch,
    "portfolio": portfolio,
    "chain_rows": chain_rows,
    "bucket": bucket,
    "rows": rows,
    "replicated": replicated,
}


def role_sharding(mesh: Mesh, role: str, ndim: int) -> NamedSharding:
    """The declared NamedSharding for ``role`` at ``ndim`` — the single
    lookup point for graftspmd's contract checks and the spmd builders."""
    if role == "portfolio":
        return portfolio(mesh)
    return ROLE_BUILDERS[role](mesh, ndim)


def _placed_like(x, sharding: NamedSharding) -> bool:
    """Is ``x`` already a device array committed to ``sharding``?"""
    if not isinstance(x, jax.Array):
        return False
    cur = getattr(x, "sharding", None)
    if cur is None:
        return False
    try:
        return cur.is_equivalent_to(sharding, x.ndim)
    except Exception:
        return cur == sharding


def prepartition(x, sharding: NamedSharding, log=None):
    """Place ``x`` into the declared sharding, counting what it cost.

    Pass-through when already placed (steady state). A host operand's first
    upload — or a fresh single-device array's (jit outputs are committed to
    device 0 before any mesh layout exists) — counts ``dist_placements``; a
    device array already laid out over MULTIPLE devices in the wrong spec
    counts ``dist_reshards``: two stages declared different shardings for
    the same hand-off, the exact bug class the pre-partitioned pipeline
    holds at zero.
    """
    if _placed_like(x, sharding):
        return x
    if log is not None:
        cur = getattr(x, "sharding", None) if isinstance(x, jax.Array) else None
        try:
            multi = cur is not None and len(cur.device_set) > 1
        except Exception:
            multi = cur is not None
        log.count("dist_reshards" if multi else "dist_placements")
    return jax.device_put(x, sharding)


def prepartition_operands(
    operands: Tuple, shardings: Tuple[NamedSharding, ...], log=None
) -> Tuple:
    """:func:`prepartition` element-wise over an operand tuple."""
    return tuple(prepartition(x, s, log=log) for x, s in zip(operands, shardings))


def reshard_count(log) -> int:
    """The ``dist_reshards`` counter value on ``log`` (0 when never hit)."""
    if log is None:
        return 0
    return int(log.counters.get("dist_reshards", 0))


def spec_cache_stats() -> Optional[dict]:
    """Visibility hook for tests: current spec-cache size."""
    try:
        return {"size": len(_SPEC_CACHE)}
    except TypeError:
        return None
