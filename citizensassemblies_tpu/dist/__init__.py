"""graftpod: the multi-host distributed runtime (`dist/runtime`) and the
pre-partitioned input feeding layer (`dist/partition`).

`runtime` owns process bootstrap (`jax.distributed.initialize` when a
coordinator is configured, single-process fallback otherwise), the canonical
mesh axis names, and the hosts×devices topology that `parallel/mesh.py`
delegates to. `partition` owns the declared-once NamedSharding specs the
pjit'd stages hand arrays off with, plus the `dist_reshards` accounting that
proves the steady state moves zero bytes between shardings.
"""

from citizensassemblies_tpu.dist.runtime import (  # noqa: F401
    AXIS_AGENTS,
    AXIS_CHAINS,
    CHAIN_AXES,
    Topology,
    bootstrap,
    default_topology,
    effective_mesh,
    process_slice,
    topology_mesh,
)
