"""Pallas TPU kernels for the framework's hot ops."""
