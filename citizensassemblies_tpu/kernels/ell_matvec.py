"""Pallas TPU kernel for the ELL gather matvec (opt-in).

STATUS — opt-in: the XLA lowering of the ELL matvec pair
(``solvers/sparse_ops``) is already a fused gather + reduction, so this
kernel exists as the packaged example of keeping the packed operator
VMEM-resident across a grid of column blocks — the layout the PDHG
megakernel (``kernels/pdhg_megakernel.py``) builds on for the full fused
block step — not as the default dispatch path.

Shape contract: the packed ``indices[C, k_pad]`` / ``values[C, k_pad]``
arrays are tiled over a 1-D grid of column blocks; each program holds its
``[block_c, k_pad]`` index/value tiles and the full gather source ``y``
(the T-types vector — a few KB) in VMEM, computes the per-column gather sum
``z[c] = Σ_s values[c, s] · y[indices[c, s]]`` and writes its ``[block_c]``
slice of the output. Padding slots carry value 0, so they contribute
nothing regardless of their index.

Off-TPU the kernel runs under the Pallas interpreter (``interpret=None``
auto-selects it), which is how the CPU test suite and the IR registration
exercise it.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _ell_gather_kernel(idx_ref, val_ref, y_ref, out_ref):
    """One column block: gather the packed slots from the VMEM-resident
    ``y`` row and reduce over the slot axis. Output is a [block_c, 128]
    tile with column 0 meaningful (the lane-padded scalar idiom shared
    with ``kernels/pdhg_megakernel.py``)."""
    idx = idx_ref[:]  # [block_c, k_pad] int32
    val = val_ref[:]  # [block_c, k_pad] f32
    y = y_ref[0, :]  # [minor_pad] f32
    gathered = jnp.take(y, idx, axis=0)  # [block_c, k_pad]
    z = jnp.sum(val * gathered, axis=1, keepdims=True)  # [block_c, 1]
    out_ref[:] = jnp.broadcast_to(z, out_ref.shape)


@partial(jax.jit, static_argnames=("block_c", "interpret"))
def _ell_gather_call(idx_p, val_p, y_p, block_c: int, interpret: bool):
    C_pad, k_pad = idx_p.shape
    minor_pad = y_p.shape[1]
    grid = (C_pad // block_c,)
    out = pl.pallas_call(
        _ell_gather_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_c, k_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_c, k_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, minor_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_c, 128), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((C_pad, 128), jnp.float32),
        interpret=interpret,
    )(idx_p, val_p, y_p)
    return out[:, 0]


def ell_gather_mv_pallas(
    idx: np.ndarray,
    val: np.ndarray,
    y: np.ndarray,
    block_c: int = 256,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """``(M y)[c] = Σ_s values[c,s] · y[indices[c,s]]`` via the Pallas
    kernel. Drop-in for ``sparse_ops.ell_gather_mv`` (same contract; the
    jitted XLA pair remains the production dispatch). Pads the column count
    to the block multiple and the gather source to a lane multiple; both
    pads are inert (zero values / zero source entries)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    idx = np.asarray(idx, np.int32)
    val = np.asarray(val, np.float32)
    y = np.asarray(y, np.float32)
    C, k_pad = idx.shape
    block_c = max(8, min(int(block_c), _round_up(max(C, 1), 8)))
    C_pad = _round_up(max(C, 1), block_c)
    minor_pad = _round_up(max(y.shape[0], 128), 128)
    idx_p = np.zeros((C_pad, k_pad), np.int32)
    idx_p[:C] = idx
    val_p = np.zeros((C_pad, k_pad), np.float32)
    val_p[:C] = val
    y_p = np.zeros((1, minor_pad), np.float32)
    y_p[0, : y.shape[0]] = y
    with dispatch_span("kernels.pallas_ell_matvec", cols=int(C)) as _ds:
        out = _ell_gather_call(
            jnp.asarray(idx_p), jnp.asarray(val_p), jnp.asarray(y_p),
            block_c=block_c, interpret=bool(interpret),
        )
        _ds.out = out
    return out[:C]


@register_ir_core("kernels.pallas_ell_matvec", span="kernels.pallas_ell_matvec")
def _ir_pallas_ell_matvec() -> IRCase:
    """The kernel at one minimum-padded shape, in interpret mode so it
    lowers on CPU — the grid/VMEM structure (blocked packed operands, one
    resident gather source) is what the IR pass pins."""
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    C_pad, kp, minor_pad, block_c = 256, 16, 128, 64
    return IRCase(
        fn=_ell_gather_call,
        args=(
            S((C_pad, kp), i32), S((C_pad, kp), f32), S((1, minor_pad), f32),
        ),
        static=dict(block_c=block_c, interpret=True),
    )
