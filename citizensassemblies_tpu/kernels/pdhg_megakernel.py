"""Fused Pallas PDHG megakernel over the VMEM-resident ELL cores.

The chained PDHG iterate (``lp_pdhg._two_sided_iterate`` /
``lp_pdhg._pdhg_body_ell``) is a sequence of small XLA ops — gather matvec,
prox, scatter matvec, dual prox — each of which round-trips x, y and the
packed ``EllPack`` values through HBM. At flagship shapes (k_pad ≈ 40,
T ≤ 600, C ≤ a few thousand) the whole working set fits in one core's VMEM,
so this module fuses an entire PDHG *block* — ``check_every`` inner
iterations, the KKT check of both the current and the averaged iterate, the
restart-to-average selection, the ω primal-dual rebalance, and the
``robust_sentinels`` freeze-at-last-finite-iterate merge — into a single
``pallas_call``. The outer convergence loop stays a ``lax.while_loop`` whose
body is one kernel launch, so per solve the operands are read from HBM once
per block instead of ~12 times per iteration.

Two kernels cover the three hot consumers:

* :func:`dispatch_two_sided` — the two-sided ε master, batched over
  polish-screen lanes (grid = one program per lane, per-lane convergence
  masks so early finishers freeze exactly like the vmapped chained core).
  Serves ``lp_pdhg.solve_two_sided_master[_ell]_async`` (B = 1) and
  ``batch_lp.solve_polish_screen_ell`` (B = screen lanes).
* :func:`dispatch_lp` — the generic-form LP (ELL inequality rows + dense
  equality block), serving ``lp_pdhg.solve_lp_ell``.

Matvec strategy inside the kernel: the adjoint direction stays the true
packed gather (``jnp.take`` over the ELL indices — the proven
``kernels/ell_matvec.py`` idiom), while the forward direction multiplies
against a transposed dense expansion of the scaled pack, built ONCE per
kernel launch into VMEM by a static loop over the k_pad slots
(Mosaic has no in-kernel scatter-add; the expansion turns the scatter into
an MXU row-times-matrix product against data that never leaves VMEM).

The Ruiz equilibration, power-norm ‖K‖ estimate and warm-start scaling run
in plain JAX *outside* the kernel using the exact op sequence of the chained
ELL bodies, so fused-vs-chained differences reduce to matvec op order —
interpret-mode parity is ε-level, and the gate-off path is bit-identical
because it never enters this module.

Gating is the tri-state ``Config.pdhg_megakernel``: ``None`` = auto (real
accelerator backends only, and only when the estimated VMEM working set
fits ``Config.pdhg_megakernel_vmem_mb``); ``True`` forces the fused path
(interpret mode off-TPU — the CPU test path); ``False`` = off.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from citizensassemblies_tpu.aot.store import aot_seeded
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.precision import iterate_dtype
from citizensassemblies_tpu.utils.guards import no_implicit_transfers

__all__ = [
    "megakernel_mode",
    "two_sided_vmem_bytes",
    "lp_vmem_bytes",
    "dispatch_two_sided",
    "dispatch_lp",
    "two_sided_megakernel_core",
    "lp_megakernel_core",
]

_LANE = 128  # TPU lane width: minor dims and the scalar row pad to this


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# --- VMEM working-set estimates + the tri-state gate -------------------------

def two_sided_vmem_bytes(T: int, C: int, k_pad: int) -> int:
    """Per-lane VMEM bytes of the two-sided block kernel: the transposed
    dense expansion dominates; pack (idx + values), the per-lane state/operand
    rows and the scalar row ride along."""
    Cp, Tp = _round_up(max(C, 1), _LANE), _round_up(max(T, 1), _LANE)
    st = Cp * Tp * 4  # transposed expansion of the scaled pack
    pack = 2 * Cp * k_pad * 4  # idx (i32) + scaled values (f32)
    rows = 4 * (4 * Cp + 10 * Tp + _LANE)  # state + operand rows + scalars
    return st + pack + rows


def lp_vmem_bytes(m1: int, nv: int, k_pad: int, m2: int) -> int:
    """VMEM bytes of the generic-form kernel (dense expansion of the ELL
    inequality rows + the resident dense equality block)."""
    m1p, nvp = _round_up(max(m1, 1), _LANE), _round_up(max(nv, 1), _LANE)
    m2p = _round_up(max(m2, 1), 8)
    gd = m1p * nvp * 4
    pack = 2 * m1p * k_pad * 4
    dense_a = m2p * nvp * 4
    rows = 4 * (4 * nvp + 4 * m1p + 4 * m2p + _LANE)
    return gd + pack + dense_a + rows


def megakernel_mode(cfg: Optional[Config], vmem_bytes: int) -> str:
    """Resolve the tri-state gate to ``"engaged"`` (compiled Mosaic kernel),
    ``"interpret"`` (forced on a non-TPU backend — the CPU test path) or
    ``"off"``. The VMEM fit check applies in every mode: a kernel instance
    that cannot hold its expansion on-chip falls back to the chained cores
    rather than compiling a spilling kernel."""
    cfg = cfg or default_config()
    gate = cfg.pdhg_megakernel
    if gate is False:
        return "off"
    if vmem_bytes > int(cfg.pdhg_megakernel_vmem_mb) * 1024 * 1024:
        return "off"
    on_tpu = jax.default_backend() == "tpu"
    if gate is None:
        return "engaged" if on_tpu else "off"
    return "engaged" if on_tpu else "interpret"


# --- scalar-row layout -------------------------------------------------------
# Per-lane scalars travel through the kernel packed into one [B, 128] f32 row
# (column 0-style lane padding, like the ell_matvec output). Flags are split
# into separate 0/1 poisoned/stalled columns so the kernel never needs f32
# bit arithmetic; it/since are exact in f32 at their ranges (≤ max_iters ≪
# 2^24). Columns ≥ _SC_N are dead padding.
_SC_EPS = 0      # two-sided: scaled ε iterate
_SC_MU = 1       # two-sided: scaled μ iterate
_SC_EAV = 2      # two-sided: averaged ε
_SC_MAV = 3      # two-sided: averaged μ
_SC_IT = 4       # iterations completed
_SC_RES = 5      # last KKT residual (inf until the first check)
_SC_OMEGA = 6    # primal-dual balance ω
_SC_POIS = 7     # sentinel: non-finite residual seen (lane quarantined)
_SC_STALL = 8    # sentinel: ≥ _STALL_BLOCKS checks without improvement
_SC_BEST = 9     # sentinel: best finite residual so far
_SC_SINCE = 10   # sentinel: checks since the best improved
_SC_BS = 11      # scaled b (two-sided: the Σp row datum)
_SC_CEPS = 12    # scaled ε objective coefficient
_SC_NORM = 13    # power-iteration ‖K‖ estimate
_SC_TOL = 14     # per-lane tolerance
_SC_SCALE = 15   # KKT normalization scale
_SC_N = 16

_STALL_BLOCKS = 64  # mirrors lp_pdhg._STALL_BLOCKS


def _pack_scal_row(vals: dict, like=None) -> jnp.ndarray:
    """Build a [1, 128] scalar row inside the kernel from column → value."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, _LANE), 1)
    out = jnp.zeros((1, _LANE), jnp.float32) if like is None else like
    for col, v in vals.items():
        out = jnp.where(lane == col, v, out)
    return out


# --- the two-sided block kernel ---------------------------------------------

def _two_sided_block_kernel(
    idx_ref, vs_ref, ecol_ref, arow_ref, hlo_ref, hup_ref,
    p_ref, llo_ref, lup_ref, pav_ref, llav_ref, luav_ref, scal_ref,
    op_ref, ollo_ref, olup_ref, opav_ref, ollav_ref, oluav_ref, oscal_ref,
    *, check_every: int, max_iters: int, sentinel: bool,
):
    """One PDHG block for one polish-screen lane: ``check_every`` fused
    iterations + KKT/restart/ω + the sentinel merge, all VMEM-resident.

    Mirrors ``lp_pdhg._two_sided_iterate.block`` (and ``_sentinel_while``'s
    merge) op-for-op; only the matvec implementations differ. A lane whose
    convergence mask is already clear copies its inputs through unchanged —
    the same freeze the vmapped chained ``while_loop`` applies to early
    finishers.
    """
    idx = idx_ref[...]                       # [Cp, kp] i32 (shared)
    vs = vs_ref[0]                           # [Cp, kp] scaled pack values
    ecol = ecol_ref[...]                     # [1, Tp] scaled ε column
    arow = arow_ref[...]                     # [1, Cp] scaled Σp row
    hlo = hlo_ref[...]                       # [1, Tp]
    hup = hup_ref[...]                       # [1, Tp]
    p_in = p_ref[...]                        # [1, Cp]
    llo_in = llo_ref[...]                    # [1, Tp]
    lup_in = lup_ref[...]                    # [1, Tp]
    pav_in = pav_ref[...]
    llav_in = llav_ref[...]
    luav_in = luav_ref[...]
    s = scal_ref[0, :]                       # [128]

    eps_in, mu_in = s[_SC_EPS], s[_SC_MU]
    eav_in, mav_in = s[_SC_EAV], s[_SC_MAV]
    it_in, res_in, omega = s[_SC_IT], s[_SC_RES], s[_SC_OMEGA]
    pois_in, stall_in = s[_SC_POIS], s[_SC_STALL]
    best_in, since_in = s[_SC_BEST], s[_SC_SINCE]
    bs, cs_eps = s[_SC_BS], s[_SC_CEPS]
    norm, tol, scale = s[_SC_NORM], s[_SC_TOL], s[_SC_SCALE]

    Cp, kp = vs.shape
    Tp = ecol.shape[1]

    # the lane's convergence mask — identical to the chained per-lane cond
    # (non-finite res compares False, so a poisoned non-sentinel lane also
    # freezes here, exactly like the vmapped while_loop)
    active = (res_in > tol) & (it_in < float(max_iters)) & (pois_in == 0.0)

    # transposed dense expansion of the scaled pack, built once per launch:
    # st[c, t] = Σ_slots vs[c, s]·[idx[c, s] == t]. Padding slots carry
    # value 0 so they land inertly wherever their index points.
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (Cp, Tp), 1)
    st = jnp.zeros((Cp, Tp), jnp.float32)
    for sl in range(kp):
        st = st + jnp.where(idx[:, sl:sl + 1] == iota_t, vs[:, sl:sl + 1], 0.0)

    def fwd(p_row, eps):
        """K @ x: forward matvec against the VMEM-resident expansion."""
        u = jnp.dot(p_row, st, preferred_element_type=jnp.float32)  # [1, Tp]
        r_lo = -u - ecol * eps
        r_up = u - ecol * eps
        r_eq = jnp.sum(arow * p_row)
        return r_lo, r_up, r_eq

    def adj(llo, lup, mu):
        """Kᵀ y: the true packed ELL gather (ell_matvec idiom)."""
        y = (lup - llo)[0]                                   # [Tp]
        g = jnp.sum(vs * jnp.take(y, idx, axis=0), axis=1)   # [Cp]
        g_p = g.reshape(1, Cp) + mu * arow
        g_e = -jnp.sum(ecol * (llo + lup))
        return g_p, g_e

    tau = 0.9 * omega / norm
    sigma = 0.9 / (omega * norm)

    def one_iter(_, carry):
        p, eps, llo, lup, mu, ps, es, lls, lus, ms = carry
        g_p, g_e = adj(llo, lup, mu)
        p_new = jnp.maximum(p - tau * g_p, 0.0)
        eps_new = jnp.maximum(eps - tau * (g_e + cs_eps), 0.0)
        pb = 2.0 * p_new - p
        eb = 2.0 * eps_new - eps
        r_lo, r_up, r_eq = fwd(pb, eb)
        llo_new = jnp.maximum(llo + sigma * (r_lo - hlo), 0.0)
        lup_new = jnp.maximum(lup + sigma * (r_up - hup), 0.0)
        mu_new = mu + sigma * (r_eq - bs)
        return (
            p_new, eps_new, llo_new, lup_new, mu_new,
            ps + p_new, es + eps_new, lls + llo_new, lus + lup_new,
            ms + mu_new,
        )

    zero_p = jnp.zeros_like(p_in)
    zero_t = jnp.zeros_like(llo_in)
    (p, eps, llo, lup, mu, ps, es, lls, lus, ms) = jax.lax.fori_loop(
        0, check_every, one_iter,
        (p_in, eps_in, llo_in, lup_in, mu_in,
         zero_p, jnp.float32(0.0), zero_t, zero_t, jnp.float32(0.0)),
    )

    def kkt(p, eps, llo, lup, mu):
        r_lo, r_up, r_eq = fwd(p, eps)
        pri = jnp.sqrt(
            jnp.sum(jnp.maximum(r_lo - hlo, 0.0) ** 2)
            + jnp.sum(jnp.maximum(r_up - hup, 0.0) ** 2)
            + (r_eq - bs) ** 2
        )
        g_p, g_e = adj(llo, lup, mu)
        dua = jnp.sqrt(
            jnp.sum(jnp.minimum(g_p, 0.0) ** 2)
            + jnp.minimum(g_e + cs_eps, 0.0) ** 2
        )
        pobj = cs_eps * eps
        dobj = -jnp.sum(llo * hlo) - jnp.sum(lup * hup) - mu * bs
        gap = jnp.abs(pobj - dobj)
        return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

    inv = 1.0 / check_every
    pa = (pav_in + ps * inv) * 0.5
    ea = (eav_in + es * inv) * 0.5
    lla = (llav_in + lls * inv) * 0.5
    lua = (luav_in + lus * inv) * 0.5
    ma = (mav_in + ms * inv) * 0.5
    r_cur = kkt(p, eps, llo, lup, mu)
    r_avg = kkt(pa, ea, lla, lua, ma)
    better = r_avg < r_cur
    p = jnp.where(better, pa, p)
    eps = jnp.where(better, ea, eps)
    llo = jnp.where(better, lla, llo)
    lup = jnp.where(better, lua, lup)
    mu = jnp.where(better, ma, mu)
    res = jnp.minimum(r_cur, r_avg)
    dx = jnp.sqrt(jnp.sum((p - p_in) ** 2))
    dy = jnp.sqrt(
        jnp.sum((llo - llo_in) ** 2)
        + jnp.sum((lup - lup_in) ** 2)
        + (mu - mu_in) ** 2
    )
    moved = (dx > 1e-12) & (dy > 1e-12)
    omega_new = jnp.sqrt(omega * jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-4, 1e4))
    omega_out = jnp.where(moved, jnp.clip(omega_new, 1.0 / 64.0, 64.0), omega)
    it_out = it_in + float(check_every)

    pois, stall, best, since = pois_in, stall_in, best_in, since_in
    if sentinel:
        # _sentinel_while's merge: a non-finite residual reverts the WHOLE
        # carry (iterates, averages, it, res, ω) to the last finite block
        # and quarantines the lane
        ok = jnp.isfinite(res)
        p = jnp.where(ok, p, p_in)
        eps = jnp.where(ok, eps, eps_in)
        llo = jnp.where(ok, llo, llo_in)
        lup = jnp.where(ok, lup, lup_in)
        mu = jnp.where(ok, mu, mu_in)
        pa = jnp.where(ok, pa, pav_in)
        ea = jnp.where(ok, ea, eav_in)
        lla = jnp.where(ok, lla, llav_in)
        lua = jnp.where(ok, lua, luav_in)
        ma = jnp.where(ok, ma, mav_in)
        it_out = jnp.where(ok, it_out, it_in)
        res = jnp.where(ok, res, res_in)
        omega_out = jnp.where(ok, omega_out, omega)
        improved = ok & (res < best_in)
        best = jnp.where(improved, res, best_in)
        since = jnp.where(improved, 0.0, since_in + 1.0)
        pois = jnp.maximum(pois_in, jnp.where(ok, 0.0, 1.0))
        stall = jnp.maximum(
            stall_in, jnp.where(since >= float(_STALL_BLOCKS), 1.0, 0.0)
        )

    def sel(new, old):
        return jnp.where(active, new, old)

    op_ref[...] = sel(p, p_in)
    ollo_ref[...] = sel(llo, llo_in)
    olup_ref[...] = sel(lup, lup_in)
    opav_ref[...] = sel(pa, pav_in)
    ollav_ref[...] = sel(lla, llav_in)
    oluav_ref[...] = sel(lua, luav_in)
    oscal_ref[...] = _pack_scal_row(
        {
            _SC_EPS: sel(eps, eps_in),
            _SC_MU: sel(mu, mu_in),
            _SC_EAV: sel(ea, eav_in),
            _SC_MAV: sel(ma, mav_in),
            _SC_IT: sel(it_out, it_in),
            _SC_RES: sel(res, res_in),
            _SC_OMEGA: sel(omega_out, omega),
            _SC_POIS: sel(pois, pois_in),
            _SC_STALL: sel(stall, stall_in),
            _SC_BEST: sel(best, best_in),
            _SC_SINCE: sel(since, since_in),
            _SC_BS: bs,
            _SC_CEPS: cs_eps,
            _SC_NORM: norm,
            _SC_TOL: tol,
            _SC_SCALE: scale,
        }
    )


def _two_sided_block_call(
    idx_p, vs_p, ecol_p, arow_p, hlo_p, hup_p, state,
    *, check_every: int, max_iters: int, sentinel: bool, interpret: bool,
):
    """One launch of the two-sided block kernel over all B lanes."""
    p, llo, lup, pav, llav, luav, scal = state
    B, Cp = p.shape
    Tp = llo.shape[1]
    kp = idx_p.shape[1]
    f32 = jnp.float32

    row_c = lambda i: (i, 0)  # noqa: E731 — per-lane row blocks
    out = pl.pallas_call(
        partial(
            _two_sided_block_kernel,
            check_every=check_every, max_iters=max_iters, sentinel=sentinel,
        ),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((Cp, kp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, Cp, kp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Cp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Cp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Cp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LANE), row_c, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, Cp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Cp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row_c, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LANE), row_c, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Cp), f32),
            jax.ShapeDtypeStruct((B, Tp), f32),
            jax.ShapeDtypeStruct((B, Tp), f32),
            jax.ShapeDtypeStruct((B, Cp), f32),
            jax.ShapeDtypeStruct((B, Tp), f32),
            jax.ShapeDtypeStruct((B, Tp), f32),
            jax.ShapeDtypeStruct((B, _LANE), f32),
        ],
        interpret=interpret,
    )(idx_p, vs_p, ecol_p, arow_p, hlo_p, hup_p, p, llo, lup, pav, llav,
      luav, scal)
    return tuple(out)


def _mk_two_sided_body(
    idx, val, v, colmask, x0, lam0, mu0, tol,
    max_iters: int, check_every: int, sentinel: bool = False,
    interpret: bool = False,
):
    """The fused twin of the vmapped ``lp_pdhg._pdhg_two_sided_body_ell``:
    same operand layout batched over B lanes (``colmask``/``x0``/``lam0``/
    ``mu0``/``tol`` lead with the lane axis; the pack is shared), same
    ``(x, lam, mu, it, res[, flags])`` outputs. The Ruiz/power-norm/warm
    prelude reuses the chained op sequence verbatim; only the iterate loop
    runs inside the Pallas block kernel."""
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    T = v.shape[0]
    B, C = colmask.shape
    f32 = iterate_dtype(val.dtype)
    absV = jnp.abs(val)

    def prelude(cm, x0_l, lam0_l, mu0_l):
        # --- Ruiz equilibration: op-for-op _pdhg_two_sided_body_ell ---------
        def ruiz_body(_, carry):
            d_r, d_e, d_c, d_eps = carry
            S = absV * d_r[idx] * d_c[:, None]
            row_from_cols = jnp.maximum(
                jax.ops.segment_max(S.ravel(), idx.ravel(), num_segments=T),
                0.0,
            )
            row_ineq = jnp.maximum(row_from_cols, d_r * d_eps)
            row_eq = jnp.max(d_e * d_c * cm)
            col = jnp.maximum(S.max(axis=1), d_e * d_c * cm)
            col_eps = jnp.max(d_r) * d_eps
            rn = jnp.where(
                row_ineq > 0, jnp.sqrt(jnp.maximum(row_ineq, 1e-10)), 1.0
            )
            ren = jnp.where(row_eq > 0, jnp.sqrt(jnp.maximum(row_eq, 1e-10)), 1.0)
            cn = jnp.where(col > 0, jnp.sqrt(jnp.maximum(col, 1e-10)), 1.0)
            cen = jnp.where(
                col_eps > 0, jnp.sqrt(jnp.maximum(col_eps, 1e-10)), 1.0
            )
            return d_r / rn, d_e / ren, d_c / cn, d_eps / cen

        d_r, d_e, d_c, d_eps = jax.lax.fori_loop(
            0, 8, ruiz_body,
            (jnp.ones(T, f32), jnp.ones((), f32), jnp.ones(C, f32),
             jnp.ones((), f32)),
        )
        vals_s = val * d_r[idx] * d_c[:, None]
        e_col = d_r * d_eps
        a_row = d_e * d_c * cm
        hs_lo = -v * d_r
        hs_up = v * d_r
        bs = 1.0 * d_e
        cs_eps = 1.0 * d_eps

        def K_apply(p, eps):
            u = ell_scatter_mv(idx, vals_s, p, T)
            return -u - e_col * eps, u - e_col * eps, jnp.dot(a_row, p)

        def KT_apply(l_lo, l_up, mu):
            g_p = ell_gather_mv(idx, vals_s, l_up - l_lo) + mu * a_row
            g_e = -jnp.dot(e_col, l_lo + l_up)
            return g_p, g_e

        # --- power iteration: op-for-op _two_sided_iterate ------------------
        def pow_body(_, vv):
            p_, e_ = vv
            r_lo, r_up, r_eq = K_apply(p_, e_)
            g_p, g_e = KT_apply(r_lo, r_up, r_eq)
            nrm = jnp.sqrt(jnp.sum(g_p**2) + g_e**2) + 1e-12
            return g_p / nrm, g_e / nrm

        p0n = jnp.ones(C, dtype=f32) / jnp.sqrt(jnp.float32(C + 1))
        e0n = jnp.ones((), dtype=f32) / jnp.sqrt(jnp.float32(C + 1))
        pv, ev = jax.lax.fori_loop(0, 40, pow_body, (p0n, e0n))
        r_lo, r_up, r_eq = K_apply(pv, ev)
        g_p, g_e = KT_apply(r_lo, r_up, r_eq)
        norm = jnp.sqrt(jnp.sqrt(jnp.sum(g_p**2) + g_e**2) + 1e-12)
        scale = (
            1.0
            + jnp.abs(cs_eps)
            + jnp.sqrt(jnp.sum(hs_lo**2) + jnp.sum(hs_up**2))
            + jnp.abs(bs)
        )

        p = x0_l[:C] / jnp.maximum(d_c, 1e-12)
        eps = x0_l[C] / jnp.maximum(d_eps, 1e-12)
        l_lo = jnp.maximum(lam0_l[:T] / jnp.maximum(d_r, 1e-12), 0.0)
        l_up = jnp.maximum(lam0_l[T:] / jnp.maximum(d_r, 1e-12), 0.0)
        mu = mu0_l / jnp.maximum(d_e, 1e-12)
        return (
            vals_s, e_col, a_row, hs_lo, hs_up, bs, cs_eps, norm, scale,
            p, eps, l_lo, l_up, mu, d_r, d_e, d_c, d_eps,
        )

    (vals_s, e_col, a_row, hs_lo, hs_up, bs, cs_eps, norm, scale,
     p, eps, l_lo, l_up, mu, d_r, d_e, d_c, d_eps) = jax.vmap(prelude)(
        colmask, x0, lam0, mu0
    )

    # --- pad to lane-aligned kernel shapes (all-zero padding is inert) ------
    Cp, Tp = _round_up(C, _LANE), _round_up(T, _LANE)
    pc, pt = Cp - C, Tp - T
    idx_k = jnp.pad(idx, ((0, pc), (0, 0)))
    vs_k = jnp.pad(vals_s, ((0, 0), (0, pc), (0, 0)))
    ecol_k = jnp.pad(e_col, ((0, 0), (0, pt)))
    arow_k = jnp.pad(a_row, ((0, 0), (0, pc)))
    hlo_k = jnp.pad(hs_lo, ((0, 0), (0, pt)))
    hup_k = jnp.pad(hs_up, ((0, 0), (0, pt)))
    p_k = jnp.pad(p, ((0, 0), (0, pc)))
    llo_k = jnp.pad(l_lo, ((0, 0), (0, pt)))
    lup_k = jnp.pad(l_up, ((0, 0), (0, pt)))

    lane = jnp.arange(_LANE)
    scal0 = jnp.zeros((B, _LANE), jnp.float32)
    for col, vcol in (
        (_SC_EPS, eps), (_SC_MU, mu), (_SC_EAV, eps), (_SC_MAV, mu),
        (_SC_IT, jnp.zeros(B, jnp.float32)),
        (_SC_RES, jnp.full(B, jnp.inf, jnp.float32)),
        (_SC_OMEGA, jnp.ones(B, jnp.float32)),
        (_SC_POIS, jnp.zeros(B, jnp.float32)),
        (_SC_STALL, jnp.zeros(B, jnp.float32)),
        (_SC_BEST, jnp.full(B, jnp.inf, jnp.float32)),
        (_SC_SINCE, jnp.zeros(B, jnp.float32)),
        (_SC_BS, bs), (_SC_CEPS, cs_eps), (_SC_NORM, norm),
        (_SC_TOL, tol.astype(jnp.float32)), (_SC_SCALE, scale),
    ):
        scal0 = jnp.where(lane[None, :] == col, vcol[:, None], scal0)

    state0 = (p_k, llo_k, lup_k, p_k, llo_k, lup_k, scal0)

    def outer_cond(state):
        sc = state[6]
        return jnp.any(
            (sc[:, _SC_RES] > sc[:, _SC_TOL])
            & (sc[:, _SC_IT] < float(max_iters))
            & (sc[:, _SC_POIS] == 0.0)
        )

    def outer_body(state):
        return _two_sided_block_call(
            idx_k, vs_k, ecol_k, arow_k, hlo_k, hup_k, state,
            check_every=check_every, max_iters=max_iters,
            sentinel=sentinel, interpret=interpret,
        )

    p_k, llo_k, lup_k, _, _, _, scal = jax.lax.while_loop(
        outer_cond, outer_body, state0
    )

    eps = scal[:, _SC_EPS]
    mu = scal[:, _SC_MU]
    it = scal[:, _SC_IT].astype(jnp.int32)
    res = scal[:, _SC_RES]
    x_out = jnp.concatenate(
        [p_k[:, :C] * d_c, (eps * d_eps)[:, None]], axis=1
    )
    lam_out = jnp.concatenate(
        [llo_k[:, :T] * d_r, lup_k[:, :T] * d_r], axis=1
    )
    mu_out = mu * d_e
    if sentinel:
        flags = (
            (scal[:, _SC_POIS] > 0.0).astype(jnp.int32)
            + 2 * (scal[:, _SC_STALL] > 0.0).astype(jnp.int32)
        )
        return x_out, lam_out, mu_out, it, res, flags
    return x_out, lam_out, mu_out, it, res


# same donation contract as the chained batched core (x0, lam0; mu0 stays
# undonated for layout parity with _pdhg_two_sided_core_ell)
two_sided_megakernel_core = aot_seeded(
    "kernels.megakernel_two_sided",
    partial(
        jax.jit,
        static_argnames=("max_iters", "check_every", "sentinel", "interpret"),
        donate_argnums=(4, 5),
    )(_mk_two_sided_body),
    static_argnames=("max_iters", "check_every", "sentinel", "interpret"),
)


def dispatch_two_sided(
    operands, *, cfg: Config, log=None, max_iters: int, check_every: int,
    sentinel: bool, mode: str, lanes: Optional[int] = None,
):
    """Span-wrapped launch of the fused two-sided solve. ``operands`` is the
    batched device tuple ``(idx, val, v, colmask, x0, lam0, mu0, tol)``
    (lane axis leading on the last five); ``mode`` is the resolved gate
    state (``"engaged"``/``"interpret"``)."""
    idx, val = operands[0], operands[1]
    B, C = operands[3].shape
    T = operands[2].shape[0]
    with dispatch_span(
        "kernels.pdhg_megakernel_two_sided", cfg=cfg, log=log,
        T=int(T), cols=int(C), k_pad=int(idx.shape[1]),
        lanes=int(lanes if lanes is not None else B), mode=mode,
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = two_sided_megakernel_core(
                *operands,
                max_iters=max_iters, check_every=check_every,
                sentinel=sentinel, interpret=(mode == "interpret"),
            )
        _ds.out = out[:5]
    if log is not None:
        log.count("megakernel_dispatches")
        log.count("megakernel_lanes", int(lanes if lanes is not None else B))
    return out


# --- the generic-form LP kernel ---------------------------------------------
# scalar-row columns for the generic kernel (vector μ lives in its own row)
_SL_IT = 0
_SL_RES = 1
_SL_OMEGA = 2
_SL_POIS = 3
_SL_STALL = 4
_SL_BEST = 5
_SL_SINCE = 6
_SL_NORM = 7
_SL_SCALE = 8
_SL_TOL = 9


def _lp_block_kernel(
    idx_ref, vs_ref, as_ref, cs_ref, hs_ref, bs_ref,
    x_ref, lam_ref, mu_ref, xav_ref, lav_ref, mav_ref, scal_ref,
    ox_ref, olam_ref, omu_ref, oxav_ref, olav_ref, omav_ref, oscal_ref,
    *, check_every: int, max_iters: int, sentinel: bool,
):
    """One PDHG block of the generic LP (``min cᵀx, Gx ≤ h, Ax = b, x ≥ 0``)
    with G as packed ELL rows — the fused twin of
    ``lp_pdhg._pdhg_body_ell.block``. ``G @ x`` is the packed row gather;
    ``Gᵀ λ`` multiplies the dense expansion built once per launch; the small
    equality block stays a resident dense broadcast-reduce."""
    idx = idx_ref[...]            # [m1p, kp] i32
    vs = vs_ref[...]              # [m1p, kp]
    As = as_ref[...]              # [m2p, nvp]
    cs = cs_ref[...]              # [1, nvp]
    hs = hs_ref[...]              # [1, m1p]
    bs = bs_ref[...]              # [1, m2p]
    x_in = x_ref[...]             # [1, nvp]
    lam_in = lam_ref[...]         # [1, m1p]
    mu_in = mu_ref[...]           # [1, m2p]
    xav_in = xav_ref[...]
    lav_in = lav_ref[...]
    mav_in = mav_ref[...]
    s = scal_ref[0, :]

    it_in, res_in, omega = s[_SL_IT], s[_SL_RES], s[_SL_OMEGA]
    pois_in, stall_in = s[_SL_POIS], s[_SL_STALL]
    best_in, since_in = s[_SL_BEST], s[_SL_SINCE]
    norm, scale, tol = s[_SL_NORM], s[_SL_SCALE], s[_SL_TOL]

    m1p, kp = vs.shape
    nvp = cs.shape[1]

    active = (res_in > tol) & (it_in < float(max_iters)) & (pois_in == 0.0)

    # dense expansion of the scaled inequality rows: gd[j, i] = G_s[j, i]
    iota_v = jax.lax.broadcasted_iota(jnp.int32, (m1p, nvp), 1)
    gd = jnp.zeros((m1p, nvp), jnp.float32)
    for sl in range(kp):
        gd = gd + jnp.where(idx[:, sl:sl + 1] == iota_v, vs[:, sl:sl + 1], 0.0)

    def G_mv(x_row):
        xv = x_row[0]                                        # [nvp]
        g = jnp.sum(vs * jnp.take(xv, idx, axis=0), axis=1)  # [m1p]
        return g.reshape(1, m1p)

    def G_rmv(y_row):
        return jnp.dot(y_row, gd, preferred_element_type=jnp.float32)

    def A_mv(x_row):
        return jnp.sum(As * x_row, axis=1).reshape(1, -1)    # [1, m2p]

    def A_rmv(mu_row):
        return jnp.dot(mu_row, As, preferred_element_type=jnp.float32)

    tau = 0.9 * omega / norm
    sigma = 0.9 / (omega * norm)

    def one_iter(_, carry):
        x, lam, mu, xs, ls, ms = carry
        grad = cs + G_rmv(lam) + A_rmv(mu)
        x_new = jnp.maximum(x - tau * grad, 0.0)
        xb = 2.0 * x_new - x
        lam_new = jnp.maximum(lam + sigma * (G_mv(xb) - hs), 0.0)
        mu_new = mu + sigma * (A_mv(xb) - bs)
        return (
            x_new, lam_new, mu_new, xs + x_new, ls + lam_new, ms + mu_new
        )

    (x, lam, mu, xs, ls, ms) = jax.lax.fori_loop(
        0, check_every, one_iter,
        (x_in, lam_in, mu_in, jnp.zeros_like(x_in), jnp.zeros_like(lam_in),
         jnp.zeros_like(mu_in)),
    )

    def kkt(x, lam, mu):
        pri_ineq = jnp.maximum(G_mv(x) - hs, 0.0)
        pri_eq = A_mv(x) - bs
        pri = jnp.sqrt(jnp.sum(pri_ineq**2) + jnp.sum(pri_eq**2))
        grad = cs + G_rmv(lam) + A_rmv(mu)
        dua = jnp.sqrt(jnp.sum(jnp.minimum(grad, 0.0) ** 2))
        pobj = jnp.sum(cs * x)
        dobj = -jnp.sum(lam * hs) - jnp.sum(mu * bs)
        gap = jnp.abs(pobj - dobj)
        return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

    inv = 1.0 / check_every
    xa = (xav_in + xs * inv) * 0.5
    la = (lav_in + ls * inv) * 0.5
    ma = (mav_in + ms * inv) * 0.5
    r_cur = kkt(x, lam, mu)
    r_avg = kkt(xa, la, ma)
    better = r_avg < r_cur
    x = jnp.where(better, xa, x)
    lam = jnp.where(better, la, lam)
    mu = jnp.where(better, ma, mu)
    res = jnp.minimum(r_cur, r_avg)
    dx = jnp.sqrt(jnp.sum((x - x_in) ** 2))
    dy = jnp.sqrt(jnp.sum((lam - lam_in) ** 2) + jnp.sum((mu - mu_in) ** 2))
    moved = (dx > 1e-12) & (dy > 1e-12)
    omega_new = jnp.sqrt(omega * jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-4, 1e4))
    omega_out = jnp.where(moved, jnp.clip(omega_new, 1.0 / 64.0, 64.0), omega)
    it_out = it_in + float(check_every)

    pois, stall, best, since = pois_in, stall_in, best_in, since_in
    if sentinel:
        ok = jnp.isfinite(res)
        x = jnp.where(ok, x, x_in)
        lam = jnp.where(ok, lam, lam_in)
        mu = jnp.where(ok, mu, mu_in)
        xa = jnp.where(ok, xa, xav_in)
        la = jnp.where(ok, la, lav_in)
        ma = jnp.where(ok, ma, mav_in)
        it_out = jnp.where(ok, it_out, it_in)
        res = jnp.where(ok, res, res_in)
        omega_out = jnp.where(ok, omega_out, omega)
        improved = ok & (res < best_in)
        best = jnp.where(improved, res, best_in)
        since = jnp.where(improved, 0.0, since_in + 1.0)
        pois = jnp.maximum(pois_in, jnp.where(ok, 0.0, 1.0))
        stall = jnp.maximum(
            stall_in, jnp.where(since >= float(_STALL_BLOCKS), 1.0, 0.0)
        )

    def sel(new, old):
        return jnp.where(active, new, old)

    ox_ref[...] = sel(x, x_in)
    olam_ref[...] = sel(lam, lam_in)
    omu_ref[...] = sel(mu, mu_in)
    oxav_ref[...] = sel(xa, xav_in)
    olav_ref[...] = sel(la, lav_in)
    omav_ref[...] = sel(ma, mav_in)
    oscal_ref[...] = _pack_scal_row(
        {
            _SL_IT: sel(it_out, it_in),
            _SL_RES: sel(res, res_in),
            _SL_OMEGA: sel(omega_out, omega),
            _SL_POIS: sel(pois, pois_in),
            _SL_STALL: sel(stall, stall_in),
            _SL_BEST: sel(best, best_in),
            _SL_SINCE: sel(since, since_in),
            _SL_NORM: norm,
            _SL_SCALE: scale,
            _SL_TOL: tol,
        }
    )


def _lp_block_call(
    idx_p, vs_p, As_p, cs_p, hs_p, bs_p, state,
    *, check_every: int, max_iters: int, sentinel: bool, interpret: bool,
):
    x, lam, mu, xav, lav, mav, scal = state
    nvp = x.shape[1]
    m1p = lam.shape[1]
    m2p = mu.shape[1]
    kp = idx_p.shape[1]
    f32 = jnp.float32
    whole = lambda *shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        partial(
            _lp_block_kernel,
            check_every=check_every, max_iters=max_iters, sentinel=sentinel,
        ),
        grid=(1,),
        in_specs=[
            whole(m1p, kp), whole(m1p, kp), whole(m2p, nvp), whole(1, nvp),
            whole(1, m1p), whole(1, m2p), whole(1, nvp), whole(1, m1p),
            whole(1, m2p), whole(1, nvp), whole(1, m1p), whole(1, m2p),
            whole(1, _LANE),
        ],
        out_specs=[
            whole(1, nvp), whole(1, m1p), whole(1, m2p), whole(1, nvp),
            whole(1, m1p), whole(1, m2p), whole(1, _LANE),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, nvp), f32),
            jax.ShapeDtypeStruct((1, m1p), f32),
            jax.ShapeDtypeStruct((1, m2p), f32),
            jax.ShapeDtypeStruct((1, nvp), f32),
            jax.ShapeDtypeStruct((1, m1p), f32),
            jax.ShapeDtypeStruct((1, m2p), f32),
            jax.ShapeDtypeStruct((1, _LANE), f32),
        ],
        interpret=interpret,
    )(idx_p, vs_p, As_p, cs_p, hs_p, bs_p, x, lam, mu, xav, lav, mav, scal)
    return tuple(out)


def _mk_lp_body(
    c, idx, val, h, A, b, x0, lam0, mu0, tol,
    max_iters: int, check_every: int, sentinel: bool = False,
    interpret: bool = False,
):
    """The fused twin of ``lp_pdhg._pdhg_body_ell``: identical signature,
    scaling prelude and output layout; the iterate loop runs in the Pallas
    block kernel."""
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_gather_mv,
        ell_scatter_mv,
    )

    m1 = idx.shape[0]
    nv = c.shape[0]
    m2 = A.shape[0]
    f32 = iterate_dtype(val.dtype)
    absV = jnp.abs(val)
    absA = jnp.abs(A)

    # --- Ruiz: op-for-op _pdhg_body_ell -------------------------------------
    def ruiz_body(_, carry):
        d_r, d_c = carry
        Sg = absV * d_r[:m1][:, None] * d_c[idx]
        Sa = d_r[m1:, None] * absA * d_c[None, :]
        rmax = jnp.concatenate([Sg.max(axis=1), Sa.max(axis=1)])
        cmax = jnp.maximum(
            jnp.maximum(
                jax.ops.segment_max(Sg.ravel(), idx.ravel(), num_segments=nv),
                0.0,
            ),
            Sa.max(axis=0),
        )
        rn = jnp.where(rmax > 0, jnp.sqrt(jnp.maximum(rmax, 1e-10)), 1.0)
        cn = jnp.where(cmax > 0, jnp.sqrt(jnp.maximum(cmax, 1e-10)), 1.0)
        return d_r / rn, d_c / cn

    d_r, d_c = jax.lax.fori_loop(
        0, 8, ruiz_body, (jnp.ones(m1 + m2, f32), jnp.ones(nv, f32))
    )
    vals_s = val * d_r[:m1][:, None] * d_c[idx]
    As = d_r[m1:, None] * A * d_c[None, :]
    cs = c * d_c
    hs = h * d_r[:m1]
    bs = b * d_r[m1:]

    def G_mv(x):
        return ell_gather_mv(idx, vals_s, x)

    def G_rmv(y):
        return ell_scatter_mv(idx, vals_s, y, nv)

    def pow_body(_, vv):
        w = G_rmv(G_mv(vv)) + As.T @ (As @ vv)
        return w / (jnp.linalg.norm(w) + 1e-12)

    vvec = jax.lax.fori_loop(
        0, 40, pow_body, jnp.ones(nv, f32) / jnp.sqrt(jnp.float32(nv))
    )
    norm = jnp.sqrt(
        jnp.linalg.norm(G_rmv(G_mv(vvec)) + As.T @ (As @ vvec)) + 1e-12
    )
    scale = 1.0 + jnp.linalg.norm(cs) + jnp.linalg.norm(hs) + jnp.linalg.norm(bs)

    x = x0 / jnp.maximum(d_c, 1e-12)
    lam = jnp.maximum(lam0 / jnp.maximum(d_r[:m1], 1e-12), 0.0)
    mu = mu0 / jnp.maximum(d_r[m1:], 1e-12)

    # --- pad to lane-aligned kernel shapes ----------------------------------
    nvp, m1p = _round_up(nv, _LANE), _round_up(m1, _LANE)
    m2p = _round_up(m2, 8)
    pn, pm1, pm2 = nvp - nv, m1p - m1, m2p - m2
    idx_k = jnp.pad(idx, ((0, pm1), (0, 0)))
    vs_k = jnp.pad(vals_s, ((0, pm1), (0, 0)))
    As_k = jnp.pad(As, ((0, pm2), (0, pn)))
    cs_k = jnp.pad(cs, (0, pn)).reshape(1, nvp)
    hs_k = jnp.pad(hs, (0, pm1)).reshape(1, m1p)
    bs_k = jnp.pad(bs, (0, pm2)).reshape(1, m2p)
    x_k = jnp.pad(x, (0, pn)).reshape(1, nvp)
    lam_k = jnp.pad(lam, (0, pm1)).reshape(1, m1p)
    mu_k = jnp.pad(mu, (0, pm2)).reshape(1, m2p)

    lane = jnp.arange(_LANE)
    scal0 = jnp.zeros((_LANE,), jnp.float32)
    for col, vcol in (
        (_SL_IT, jnp.float32(0.0)),
        (_SL_RES, jnp.float32(jnp.inf)),
        (_SL_OMEGA, jnp.float32(1.0)),
        (_SL_BEST, jnp.float32(jnp.inf)),
        (_SL_NORM, norm), (_SL_SCALE, scale),
        (_SL_TOL, tol.astype(jnp.float32)),
    ):
        scal0 = jnp.where(lane == col, vcol, scal0)
    scal0 = scal0.reshape(1, _LANE)

    state0 = (x_k, lam_k, mu_k, x_k, lam_k, mu_k, scal0)

    def outer_cond(state):
        sc = state[6]
        return (
            (sc[0, _SL_RES] > sc[0, _SL_TOL])
            & (sc[0, _SL_IT] < float(max_iters))
            & (sc[0, _SL_POIS] == 0.0)
        )

    def outer_body(state):
        return _lp_block_call(
            idx_k, vs_k, As_k, cs_k, hs_k, bs_k, state,
            check_every=check_every, max_iters=max_iters,
            sentinel=sentinel, interpret=interpret,
        )

    x_k, lam_k, mu_k, _, _, _, scal = jax.lax.while_loop(
        outer_cond, outer_body, state0
    )

    it = scal[0, _SL_IT].astype(jnp.int32)
    res = scal[0, _SL_RES]
    x_out = x_k[0, :nv] * d_c
    lam_out = lam_k[0, :m1] * d_r[:m1]
    mu_out = mu_k[0, :m2] * d_r[m1:]
    if sentinel:
        flags = (
            (scal[0, _SL_POIS] > 0.0).astype(jnp.int32)
            + 2 * (scal[0, _SL_STALL] > 0.0).astype(jnp.int32)
        )
        return x_out, lam_out, mu_out, it, res, flags
    return x_out, lam_out, mu_out, it, res


lp_megakernel_core = aot_seeded(
    "kernels.megakernel_lp",
    partial(
        jax.jit,
        static_argnames=("max_iters", "check_every", "sentinel", "interpret"),
        donate_argnums=(6, 7, 8),  # x0, lam0, mu0 — the chained-core contract
    )(_mk_lp_body),
    static_argnames=("max_iters", "check_every", "sentinel", "interpret"),
)


def dispatch_lp(
    operands, *, cfg: Config, log=None, max_iters: int, check_every: int,
    sentinel: bool, mode: str,
):
    """Span-wrapped launch of the fused generic-form solve. ``operands`` is
    the device tuple ``(c, idx, val, h, A, b, x0, lam0, mu0, tol)``."""
    nv = operands[0].shape[0]
    m1, kp = operands[1].shape
    m2 = operands[4].shape[0]
    with dispatch_span(
        "kernels.pdhg_megakernel_lp", cfg=cfg, log=log,
        nv=int(nv), m1=int(m1), m2=int(m2), k_pad=int(kp), mode=mode,
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = lp_megakernel_core(
                *operands,
                max_iters=max_iters, check_every=check_every,
                sentinel=sentinel, interpret=(mode == "interpret"),
            )
        _ds.out = out[:5]
    if log is not None:
        log.count("megakernel_dispatches")
        log.count("megakernel_lanes")
    return out


# --- graftcheck-IR registrations (lint/ir.py) -------------------------------
# Both fused cores register at the SAME shapes as their chained ELL twins
# (dense_ref), so IR4's sparse_deltas table carries the fused-vs-chained
# flops/bytes delta at a same-shape comparison. The kernel body is opaque to
# the XLA cost model (the pallas_call reports no flops), so the fused budget
# measures the prelude + launch structure; the L∞/parity contract is carried
# by tests and the bench --kernels rows, not by the cost model. Interpret
# mode keeps the trace CPU-portable, same as kernels.pallas_ell_matvec.


@register_ir_core(
    "kernels.pdhg_megakernel_two_sided",
    dense_ref="batch_lp.polish_screen_ell",
    span="kernels.pdhg_megakernel_two_sided",
)
def _ir_megakernel_two_sided() -> IRCase:
    B, T, C, kp = 4, 128, 256, 16
    r = np.random.default_rng(5)
    idx = r.integers(0, T, size=(C, kp)).astype(np.int32)
    val = (r.random((C, kp)) < 0.5).astype(np.float32)
    S = lambda shape, dt=np.float32: jnp.asarray(  # noqa: E731
        r.random(shape).astype(dt) if dt == np.float32
        else np.zeros(shape, dt)
    )
    return IRCase(
        fn=two_sided_megakernel_core,
        args=(
            jnp.asarray(idx),
            jnp.asarray(val),
            S((T,)),
            jnp.ones((B, C), jnp.float32),
            S((B, C + 1)),
            S((B, 2 * T)),
            S((B,)),
            jnp.full((B,), 1e-6, jnp.float32),
        ),
        static=dict(
            max_iters=1024, check_every=128, sentinel=False, interpret=True
        ),
        donate_expected=2,
        arg_ranges=(
            None,
            (0.0, 256.0, True),
            (0.0, 1.0, False),
            (0.0, 1.0, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(1,),  # packed ELL values
    )


@register_ir_core(
    "kernels.pdhg_megakernel_lp",
    dense_ref="lp_pdhg.pdhg_core_ell",
    span="kernels.pdhg_megakernel_lp",
)
def _ir_megakernel_lp() -> IRCase:
    nv, m1, m2, kp = 65, 64, 1, 8
    r = np.random.default_rng(6)
    idx = r.integers(0, nv, size=(m1, kp)).astype(np.int32)
    val = (r.random((m1, kp)) < 0.5).astype(np.float32)
    return IRCase(
        fn=lp_megakernel_core,
        args=(
            jnp.asarray(r.random(nv).astype(np.float32)),
            jnp.asarray(idx),
            jnp.asarray(val),
            jnp.asarray(r.random(m1).astype(np.float32)),
            jnp.ones((m2, nv), jnp.float32),
            jnp.ones((m2,), jnp.float32),
            jnp.zeros((nv,), jnp.float32),
            jnp.zeros((m1,), jnp.float32),
            jnp.zeros((m2,), jnp.float32),
            jnp.asarray(1e-6, jnp.float32),
        ),
        static=dict(
            max_iters=1024, check_every=128, sentinel=False, interpret=True
        ),
        donate_expected=3,
        arg_ranges=(
            (-1e4, 1e4, False),
            None,
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (0.0, 256.0, True),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (-1e4, 1e4, False),
            (1e-8, 1e-2, False),
        ),
        prec_demote=(2,),  # packed ELL values
    )
