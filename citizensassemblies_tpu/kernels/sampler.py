"""Fused Pallas TPU kernel for the greedy stratified panel sampler (opt-in).

STATUS — demoted to opt-in (``sampler="pallas"``), not the default. The
kernel fuses the entire k-step draw in VMEM, eliminating the scan path's
per-step ``[B, n]`` mask round-trips through HBM; the traffic reduction is
real, but measured end-to-end on a v5e across B ∈ {1024, 4096, 16384} and
n ∈ {200, 1727, 2000} its throughput is within ±6 % of the scan path —
sampler latency at reference shapes is dominated by dispatch/transfer
overhead, not by the HBM traffic the fusion removes, so VMEM residency has
nothing left to win (VERDICT r2 item #4; see the measurement note in
``models/legacy.py::sample_panels_batch``). Kept as the packaged example of
a fused Pallas pipeline: grid over chain blocks, per-program ``[block_b, n]``
alive mask and ``[block_b, F]`` selected counts resident in VMEM for all k
steps, each step two MXU matmuls (``alive @ A`` remaining-counts, one-hot
purge cascade) plus VPU argmax / masking — the exact arithmetic of the scan
path (same urgency-ratio semantics as the reference's ``legacy.py:124-157``
greedy, first-max tie-break, Gumbel-max member pick).

Random bits come from a counter-based in-register hash RNG (two rounds of the
murmur3 finalizer over a (seed, program, row, column, step)-unique counter,
pure uint32 VPU arithmetic), so no noise tensors are streamed from HBM and
the identical kernel runs under the CPU interpreter (the on-core
``pltpu.prng_*`` primitives have no CPU lowering).

The public wrapper pads (n, F, k) to lane/tile multiples and falls back to
interpret mode off-TPU (used by the tests, which cross-check distribution
statistics against the scan path).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span

NEG_INF = -1e30


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 finalizer: a full-avalanche uint32 mix."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _uniform_bits(ctr: jnp.ndarray, salt: jnp.ndarray) -> jnp.ndarray:
    """(0,1) floats from unique uint32 counters via a double murmur3 mix."""
    h = _fmix32(_fmix32(ctr ^ salt) + jnp.uint32(0x9E3779B9))
    # Mosaic has no uint32→f32 cast; h>>8 < 2^24 so a value-preserving
    # bitcast through int32 reaches the supported int32→f32 path.
    mantissa = jax.lax.bitcast_convert_type(h >> jnp.uint32(8), jnp.int32)
    return mantissa.astype(jnp.float32) * (1.0 / 16777216.0)


def _sampler_kernel(
    seed_ref,  # SMEM [1] int32
    A_ref,  # VMEM [n_pad, F_pad] f32 (agent × feature one-hot, padded zeros)
    AT_ref,  # VMEM [F_pad, n_pad] f32
    qmin_ref,  # VMEM [1, F_pad] f32
    qmax_ref,  # VMEM [1, F_pad] f32 (padding features: qmax = 0 → never eligible)
    scores_ref,  # VMEM [block_b, n_pad] f32 member-pick bias (0 ⇒ uniform)
    hh_ref,  # VMEM [1, n_pad] f32 household ids (distinct ⇒ no households)
    panels_ref,  # VMEM out [block_b, k_pad] i32
    ok_ref,  # VMEM out [block_b, 128] i32 (column 0 meaningful)
    *,
    k: int,
    n: int,
):
    block_b, n_pad = scores_ref.shape
    F_pad = A_ref.shape[1]
    # injective uint32 counter per (global row, column): global_row·n_pad+col
    # never collides while B_pad·n_pad < 2³²; the per-step variation goes into
    # the salt instead, so (counter, salt) is unique per (row, col, step)
    pid = pl.program_id(0)
    col = jax.lax.broadcasted_iota(jnp.int32, (block_b, n_pad), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (block_b, n_pad), 0)
    ctr0 = (row + pid * block_b).astype(jnp.uint32) * jnp.uint32(n_pad) + col.astype(
        jnp.uint32
    )
    salt = seed_ref[0].astype(jnp.uint32)
    feat_col = jax.lax.broadcasted_iota(jnp.int32, (block_b, F_pad), 1)

    alive0 = (col < n).astype(jnp.float32)
    selected0 = jnp.zeros((block_b, F_pad), dtype=jnp.float32)
    failed0 = jnp.zeros((block_b, 1), dtype=jnp.float32)
    k_pad = panels_ref.shape[1]
    panel0 = jnp.zeros((block_b, k_pad), dtype=jnp.int32)
    kcol = jax.lax.broadcasted_iota(jnp.int32, (block_b, k_pad), 1)

    qmin = qmin_ref[0, :][None, :]
    qmax = qmax_ref[0, :][None, :]
    hh = hh_ref[0, :][None, :]
    A = A_ref[:]
    AT = AT_ref[:]
    scores = scores_ref[:]

    def step(j, carry):
        alive, selected, failed, panel = carry
        # per-cell remaining counts: one MXU matmul (legacy.py:47-75 counters)
        remaining = jnp.dot(alive, A, preferred_element_type=jnp.float32)
        deficit = qmin - selected
        # a cell that cannot reach its lower quota kills the draw
        # (legacy.py:55-57,132-137). Bool→f32 casts instead of
        # where(pred, 1.0, 0.0): two weak python-float branches resolve to
        # f64 under an enable_x64 trace, which breaks the f32 loop carry
        # (the IR verifier retraces every core under x64 — lint/ir.py IR2)
        starved = jnp.max(
            (deficit > remaining).astype(jnp.float32), axis=1, keepdims=True
        )
        eligible = (remaining > 0.5) & (qmax > 0.5)
        ratio = jnp.where(eligible, deficit / jnp.maximum(remaining, 1.0), NEG_INF)
        # first maximum wins, as in the reference's dict-iteration order
        cell = jnp.argmax(ratio, axis=1)  # [block_b]
        cell_oh = (feat_col == cell[:, None]).astype(jnp.float32)
        # members of each chain's urgent cell, among its alive agents
        members = alive * jnp.dot(cell_oh, AT, preferred_element_type=jnp.float32)
        has_member = jnp.max(members, axis=1, keepdims=True)

        # Gumbel-max member pick: uniform for scores≡0, softmax(scores) else
        step_salt = salt ^ (jnp.uint32(j) * jnp.uint32(0x85EBCA77))
        u = _uniform_bits(ctr0, step_salt)
        gumbel = -jnp.log(-jnp.log(u + 1e-12) + 1e-12)
        person = jnp.argmax(
            jnp.where(members > 0.5, scores + gumbel, NEG_INF), axis=1
        )
        p_oh = (col == person[:, None]).astype(jnp.float32)
        person_feats = jnp.dot(p_oh, A, preferred_element_type=jnp.float32)
        selected = selected + person_feats

        # purge cascade: cells of the pick that just hit their upper quota
        # evict all their members (legacy.py:103-120,47-62) — one matmul
        purged = (
            (jnp.abs(selected - qmax) < 0.5) & (person_feats > 0.5)
        ).astype(jnp.float32)
        kill = jnp.dot(purged, AT, preferred_element_type=jnp.float32)
        # evict the pick's whole household (distinct ids ⇒ just the pick)
        hh_person = jnp.sum(p_oh * hh, axis=1, keepdims=True)
        alive = alive * (kill <= 0.5).astype(jnp.float32)
        alive = alive * (jnp.abs(hh - hh_person) >= 0.5).astype(jnp.float32)

        failed = jnp.maximum(failed, jnp.maximum(starved, 1.0 - has_member))
        # masked select into the carried panel buffer: a dynamic-offset
        # column store cannot be proven 128-aligned by Mosaic
        panel = jnp.where(kcol == j, person[:, None].astype(jnp.int32), panel)
        return alive, selected, failed, panel

    alive, selected, failed, panel = jax.lax.fori_loop(
        0, k, step, (alive0, selected0, failed0, panel0)
    )
    panels_ref[:] = panel
    # final lower-quota audit (check_min_cats, legacy.py:160-168)
    shortfall = jnp.max(
        (selected < qmin).astype(jnp.float32), axis=1, keepdims=True
    )
    ok = 1.0 - jnp.maximum(failed, shortfall)
    ok_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), ok_ref.shape)


@partial(
    jax.jit,
    static_argnames=("B", "block_b", "k", "n", "k_pad", "interpret"),
)
def _pallas_sample(
    A_pad,
    AT_pad,
    qmin_pad,
    qmax_pad,
    scores,
    hh,
    seed,
    B: int,
    block_b: int,
    k: int,
    n: int,
    k_pad: int,
    interpret: bool,
):
    n_pad, F_pad = A_pad.shape
    grid = (B // block_b,)
    panels, ok = pl.pallas_call(
        partial(_sampler_kernel, k=k, n=n),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((n_pad, F_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((F_pad, n_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, F_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, F_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, n_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_b, k_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 128), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, k_pad), jnp.int32),
            jax.ShapeDtypeStruct((B, 128), jnp.int32),
        ],
        interpret=interpret,
    )(seed, A_pad, AT_pad, qmin_pad, qmax_pad, scores, hh)
    return panels[:, :k], ok[:, 0].astype(bool)


@register_ir_core("kernels.pallas_sampler", span="kernels.pallas_sampler")
def _ir_pallas_sampler() -> IRCase:
    """The fused draw at one minimum-padded shape, in interpret mode so the
    kernel lowers on CPU. The murmur3 RNG is in-register by design — the IR
    check pins that no host-noise callback ever sneaks into the draw."""
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    n_pad = F_pad = k_pad = 128
    B = block_b = 8
    return IRCase(
        fn=_pallas_sample,
        args=(
            S((n_pad, F_pad), f32), S((F_pad, n_pad), f32),
            S((1, F_pad), f32), S((1, F_pad), f32),
            S((B, n_pad), f32), S((1, n_pad), f32), S((1,), i32),
        ),
        static=dict(
            B=B, block_b=block_b, k=12, n=100, k_pad=k_pad, interpret=True
        ),
    )


#: VMEM budget for the per-program working set (bytes). Real VMEM is ~16 MB
#: per core; leave headroom for the compiler's own buffers.
_VMEM_BUDGET = 8 * 2**20

#: small LRU of padded device constants keyed by the DenseInstance identity —
#: rejection sampling and column generation call the sampler in a hot loop
#: with the same instance, and re-padding/re-uploading A/Aᵀ per call would be
#: pure host-side waste. Entries hold strong references (pins ≤ CAP instances;
#: acceptable for this workload shape, where a process analyzes few pools).
from collections import OrderedDict

_PAD_CACHE: "OrderedDict[int, tuple]" = OrderedDict()
_PAD_CACHE_CAP = 4


def _pads(dense) -> Tuple[int, int, int]:
    """(n_pad, F_pad, k_pad) — the single owner of the kernel's padding rule."""
    return (
        _round_up(max(dense.n, 128), 128),
        _round_up(max(dense.n_features, 128), 128),
        _round_up(dense.k, 128),
    )


def pick_block_b(n_pad: int, F_pad: int, k_pad: int = 128, max_block: int = 256) -> int:
    """Largest chain-block (multiple of 8, ≤ max_block) whose working set fits
    the VMEM budget: ~5 [block_b, n_pad] f32 buffers (alive, members, one-hot,
    noise, scores), ~8 [block_b, F_pad] buffers (selected, remaining, deficit,
    ratio, eligibility, cell one-hot, person_feats, purged), the [block_b,
    k_pad] panel output, plus the shared A/Aᵀ tiles. Returns 0 if even
    block_b = 8 does not fit (caller should use the HBM-streaming scan path
    instead)."""
    shared = 2 * n_pad * F_pad * 4
    per_row = (5 * n_pad + 8 * F_pad + k_pad) * 4
    avail = _VMEM_BUDGET - shared
    if avail <= 0:
        return 0
    block = min(max_block, (avail // per_row) // 8 * 8)
    return int(block) if block >= 8 else 0


def block_for_dense(dense, max_block: int = 256) -> int:
    """VMEM-fitted chain block for ``dense`` (0 ⇒ the fused kernel does not
    fit; dispatchers should fall back to the scan sampler)."""
    n_pad, F_pad, k_pad = _pads(dense)
    return pick_block_b(n_pad, F_pad, k_pad, max_block=max_block)


def _padded_constants(dense):
    """Padded A/Aᵀ/qmin/qmax device arrays for ``dense`` (LRU-cached)."""
    cache_key = id(dense)
    hit = _PAD_CACHE.get(cache_key)
    if hit is not None and hit[0] is dense:
        _PAD_CACHE.move_to_end(cache_key)
        return hit[1]
    n, F = dense.n, dense.n_features
    n_pad, F_pad, _ = _pads(dense)
    A = np.zeros((n_pad, F_pad), dtype=np.float32)
    A[:n, :F] = dense.A_np.astype(np.float32)
    qmin = np.zeros((1, F_pad), dtype=np.float32)
    qmin[0, :F] = dense.qmin_np.astype(np.float32)
    qmax = np.zeros((1, F_pad), dtype=np.float32)
    qmax[0, :F] = dense.qmax_np.astype(np.float32)
    out = (jnp.asarray(A), jnp.asarray(A.T.copy()), jnp.asarray(qmin), jnp.asarray(qmax))
    while len(_PAD_CACHE) >= _PAD_CACHE_CAP:
        _PAD_CACHE.popitem(last=False)
    _PAD_CACHE[cache_key] = (dense, out)
    return out


def sample_panels_pallas(
    dense,
    key,
    B: int,
    scores: Optional[jnp.ndarray] = None,
    households: Optional[np.ndarray] = None,
    block_b: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Draw ``B`` panels with the fused kernel; returns (panels[B,k], ok[B]).

    Drop-in equivalent of ``models.legacy.sample_panels_batch`` (same
    feasibility semantics; per-seed streams differ — both are rejection
    samplers of the same greedy distribution). ``interpret=None`` auto-selects
    interpret mode off-TPU so tests run on CPU. ``block_b=None`` sizes the
    chain block to the VMEM budget; raises ValueError if no block fits (use
    the scan path for such instances — ``sample_panels_batch`` does this
    automatically).
    """
    n, F, k = dense.n, dense.n_features, dense.k
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_pad, F_pad, k_pad = _pads(dense)
    if block_b is None:
        block_b = pick_block_b(n_pad, F_pad, k_pad)
        if block_b == 0:
            raise ValueError(
                f"instance too large for the fused sampler's VMEM budget "
                f"(n_pad={n_pad}, F_pad={F_pad}); use the scan sampler"
            )
    B_pad = _round_up(B, block_b)

    A_d, AT_d, qmin_d, qmax_d = _padded_constants(dense)
    if scores is None:
        sc = jnp.zeros((B_pad, n_pad), dtype=jnp.float32)
    else:
        scores = jnp.asarray(scores, dtype=jnp.float32)
        if scores.ndim == 1:
            scores = scores[None, :]
        if scores.shape[1] != n or scores.shape[0] not in (1, B):
            raise ValueError(
                f"scores must have shape (n,), (1, n) or (B, n) = ({B}, {n}); "
                f"got {scores.shape}"
            )
        scores = jnp.broadcast_to(scores, (B, n))
        sc = jnp.zeros((B_pad, n_pad), dtype=jnp.float32).at[:B, :n].set(scores)
    if households is None:
        hh = np.arange(n_pad, dtype=np.float32)[None, :]
    else:
        hh = np.full((1, n_pad), -1.0, dtype=np.float32)
        hh[0, :n] = np.asarray(households, dtype=np.float32)
        # padding agents get unique ids so they never alias a real household
        hh[0, n:] = np.arange(n_pad - n, dtype=np.float32) + float(np.max(households)) + 1.0
    seed = jnp.asarray(
        jax.random.randint(key, (1,), 0, np.iinfo(np.int32).max), dtype=jnp.int32
    )
    with dispatch_span("kernels.pallas_sampler", chains=int(B_pad)) as _ds:
        panels, ok = _pallas_sample(
            A_d,
            AT_d,
            qmin_d,
            qmax_d,
            sc,
            jnp.asarray(hh),
            seed,
            B=B_pad,
            block_b=block_b,
            k=k,
            n=n,
            k_pad=k_pad,
            interpret=bool(interpret),
        )
        _ds.out = (panels, ok)
    return panels[:B], ok[:B]
