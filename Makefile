# Test runs force the virtual CPU mesh and bypass the TPU-tunnel bootstrap
# (PALLAS_AXON_POOL_IPS= disables the sitecustomize PJRT registration, which
# otherwise stalls every interpreter start for minutes in this environment).
test:
	PALLAS_AXON_POOL_IPS= python -m pytest tests/ -x -q

bench:
	python bench.py
