# Test runs force the virtual CPU mesh and bypass the TPU-tunnel bootstrap
# (PALLAS_AXON_POOL_IPS= disables the sitecustomize PJRT registration, which
# otherwise stalls every interpreter start for minutes in this environment).
test:
	PALLAS_AXON_POOL_IPS= python -m pytest tests/ -x -q

bench:
	python bench.py

# graftlint (the repo's JAX-invariant checker — R1..R7, see README "Static
# analysis & guard rails") over the package AND bench.py/tests/, plus a ruff
# style baseline when ruff is installed. graftlint is stdlib-only, so this
# target needs no accelerator stack.
lint:
	python -m citizensassemblies_tpu.lint citizensassemblies_tpu/ bench.py tests/
	@if command -v ruff >/dev/null 2>&1; then ruff check .; else echo "ruff not installed; style baseline skipped (ruff.toml)"; fi

# graftcheck-IR (lint/ir.py): trace every registered jitted core, verify
# callback/f64/donation invariants at the jaxpr/HLO level and ratchet the
# static cost model against ANALYSIS_BUDGET.json. CPU-traceable — the same
# env pinning as `test` keeps the TPU tunnel out of the way. The measured-vs-
# budget diff lands in IR_BUDGET_DIFF.json (uploaded as a CI artifact).
check-ir:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m citizensassemblies_tpu.lint --ir --diff-out IR_BUDGET_DIFF.json

# deliberate ratchet move: re-measure every core and rewrite the budget
update-ir-budget:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m citizensassemblies_tpu.lint --ir --update-budget

# graftspmd (lint/spmd.py): compile every registered core — mesh-consuming
# cores under 1/2/4/8 virtual devices — and verify the collective census
# against SPMD_BUDGET.json, the declared dist/partition.py sharding
# contracts, and precision-flow cert isolation. The census diff lands in
# SPMD_BUDGET_DIFF.json and the S3 artifact in artifacts/PRECISION_FLOW.json
# (both uploaded as CI artifacts).
check-spmd:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python -m citizensassemblies_tpu.lint --spmd --diff-out SPMD_BUDGET_DIFF.json --precision-out artifacts/PRECISION_FLOW.json

# graftgrade (lint/prec.py): walk every registered core's jaxpr with the
# error-flow abstract interpreter, ratchet the verdict against the committed
# PRECISION_PLAN.json, and census the compiled HLO of every committed bf16
# demotion (no silent re-upcast, no bf16 into a cert sink). The
# measured-vs-plan diff lands in PRECISION_PLAN_DIFF.json (uploaded as a CI
# artifact).
check-prec:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m citizensassemblies_tpu.lint --prec --diff-out PRECISION_PLAN_DIFF.json

# deliberate ratchet move: re-certify every core and rewrite
# PRECISION_PLAN.json (P1/P3 still fail)
update-prec-plan:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m citizensassemblies_tpu.lint --prec --update-prec-plan

# deliberate ratchet move: re-measure every core's collective census and
# rewrite SPMD_BUDGET.json (S2/S3 still fail)
update-spmd-budget:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python -m citizensassemblies_tpu.lint --spmd --update-spmd-budget --precision-out artifacts/PRECISION_FLOW.json

# grafttrace bench trend gate (obs/trend.py): per-row regression check over
# the committed BENCH_*.json / BENCH_serve_*.json trajectory. Stdlib-only —
# no accelerator stack needed, same posture as `lint`.
trend:
	python bench.py --trend

# graftboot (aot/): build the AOT-serialized executable cache artifact at
# the service shapes. On CPU the legacy runtime flag is mandatory — thunk
# runtime executables do not survive cross-process deserialization — and
# the persistent XLA disk cache must be off so the serialized payloads come
# from this process's compiler (see aot/build.py).
aot-cache:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_cpu_use_thunk_runtime=false CITIZENS_TPU_NO_COMPILE_CACHE=1 python -m citizensassemblies_tpu.aot build --profile service

# graftboot coldboot evidence (bench.py --coldboot --smoke): build a cache,
# fork a FRESH interpreter per variant (cached / uncached) through the
# identical boot → fleet-prewarm → serve readiness contract, gate the
# cached child's flagship serve at ZERO XLA compilations and the two
# allocations bit-identical. The full (non-smoke) run also gates the >= 3x
# cold-boot-to-first-certified-result speedup and writes the committed
# BENCH_coldboot_r*.json trend row.
coldboot-smoke:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench.py --coldboot --smoke

coldboot:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench.py --coldboot

# graftfleet (service/fleet.py): N-process SLO-driven serving fleet under an
# open-loop seeded Poisson load — tenant-affine rendezvous placement, mesh-
# spanning fused batcher dispatches, zero steady-state reshards, allocations
# bit-identical to single-process serial references, and the shed/degrade
# drill (typed ShedRejection + ladder descent + recovery re-arm). The full
# run drives 10^4 mixed requests through >= 4 processes and writes the
# committed BENCH_fleet_r*.json trend row.
fleet-smoke:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench.py --fleet --smoke

fleet:
	PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python bench.py --fleet
