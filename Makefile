# Test runs force the virtual CPU mesh and bypass the TPU-tunnel bootstrap
# (PALLAS_AXON_POOL_IPS= disables the sitecustomize PJRT registration, which
# otherwise stalls every interpreter start for minutes in this environment).
test:
	PALLAS_AXON_POOL_IPS= python -m pytest tests/ -x -q

bench:
	python bench.py

# graftlint (the repo's JAX-invariant checker — R1..R6, see README "Static
# analysis & guard rails") plus a ruff style baseline when ruff is installed.
# graftlint is stdlib-only, so this target needs no accelerator stack.
lint:
	python -m citizensassemblies_tpu.lint citizensassemblies_tpu/
	@if command -v ruff >/dev/null 2>&1; then ruff check .; else echo "ruff not installed; style baseline skipped (ruff.toml)"; fi
