"""Benchmark: LEXIMIN wall-clock on an example_large_200-shaped instance.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

The instance mirrors ``data/example_large_200`` (n=2000, k=200, two binary
categories, quotas 99..200, pool composition 999/1000/1/0 across the four
intersections — measured from the reference respondents.csv), for which the
reference's golden median LEXIMIN runtime is 1161.8 s
(``reference_output/example_large_200_statistics.txt:15``; BASELINE.md).
``vs_baseline`` is our wall-clock divided by that baseline (< 1 ⇒ faster).

Runs on whatever accelerator JAX finds (TPU under the driver; CPU fallback
works too). Override the instance with ``BENCH_INSTANCE=small`` for a quick
smoke run.

``python bench.py --smoke`` runs the CI smoke mode instead: tiny instances,
1 rep, the slow rows skipped — but the INVARIANT assertions (batched-engine
parity vs the serial solver, solves-per-dispatch, warm-call compile bound)
run for real and fail the process, so a dispatch-count or compile-bound
regression fails CI rather than waiting for the offline bench. The smoke
also runs a TRACED face decomposition (grafttrace sampling mode), asserts
its Chrome-trace artifact validates and covers ≥ 90 % of the phase, and
writes ``artifacts/trace_smoke.json`` + ``artifacts/metrics_smoke.prom``
for the CI upload (every smoke output lands in the gitignored
``artifacts/`` directory).

``python bench.py --scenarios`` runs the graftscenario rows (dropout-robust
leximin vs the naive re-draw baseline on MC realized-min, R-round
multi-assembly scheduling with the pair-equity gauge);
``--scenarios --smoke`` is the CI variant.

``python bench.py --trend`` is the regression gate over the committed
BENCH_*.json / BENCH_serve_*.json trajectory (``obs/trend.py``): per-row
deltas vs the best earlier round, non-zero exit past the tolerance.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _artifacts_dir() -> str:
    """Gitignored ``artifacts/`` directory next to this file — every smoke
    output (traces, Prometheus dumps, chaos/scenario reports) lands here so
    the repo root stays clean and the CI upload globs one directory."""
    root = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(root, "artifacts")
    os.makedirs(path, exist_ok=True)
    return path


def _example_large_like():
    from citizensassemblies_tpu.core.generator import cross_product_instance

    # pool composition measured from the reference data: (female,liberal) 999,
    # (male,conservative) 1000, (female,conservative) 1, (male,liberal) 0
    return cross_product_instance(
        categories=["gender", "leaning"],
        features=[["female", "male"], ["liberal", "conservative"]],
        quotas=[[(99, 200), (99, 200)], [(99, 200), (99, 200)]],
        counts=[999, 1, 0, 1000],
        k=200,
        name="example_large_200_like",
    )


def _example_small_like():
    from citizensassemblies_tpu.core.generator import example_small_like_instance

    return example_small_like_instance()


def _attach_profile_audit(audit: dict, dense, probs, covered) -> None:
    """Run ``audit_leximin_profile`` and fold its headline fields into an
    existing ``audit_maximin`` dict — shared by the flagship and household
    rows so the recorded field set cannot drift between them. Audit-side
    failures never take down a bench row."""
    from citizensassemblies_tpu.solvers.highs_backend import audit_leximin_profile

    import time as _t

    t0 = _t.time()
    try:
        prof = audit_leximin_profile(dense, probs, covered)
        audit["profile_levels"] = prof["n_levels"]
        audit["profile_worst_gap"] = prof["worst_gap"]
        # MILP-only bound (no marginal-LP rescue): records per run that the
        # certificate is independent of the type-space machinery, not just
        # that it is small
        audit["profile_worst_gap_milp"] = prof["worst_gap_milp"]
        audit["profile_all_within_tol"] = prof["all_within_tol"]
        if prof["n_levels"] >= 2:
            audit["level2_gap"] = prof["levels"][1]["gap"]
    except Exception as exc:  # pragma: no cover
        audit["profile_error"] = f"{type(exc).__name__}: {exc}"[:120]
    audit["audit_s"] = round(_t.time() - t0, 1)


def _host_sync_stamp(counters: dict):
    """Per-row host↔device round-trip evidence of the face-decomposition
    loop (ROADMAP item 2): the ``decomp_host_syncs`` gauge total, the round
    count, and the per-round ratios — ``steady_per_round`` excludes the
    end-game polish syncs, which is the number the device-pricing target
    (≤ 1 per steady-state CG round) is asserted against in ``--smoke``."""
    total = counters.get("decomp_host_syncs", 0)
    rounds = counters.get("decomp_rounds", 0)
    if not total and not rounds:
        return None
    out = {"total": int(total), "rounds": int(rounds)}
    if rounds:
        steady = total - counters.get("decomp_polish_syncs", 0)
        out["per_round"] = round(total / rounds, 2)
        out["steady_per_round"] = round(steady / rounds, 2)
    for key in ("decomp_oracle_device_hit", "decomp_oracle_device_miss",
                "oracle_backend_native", "oracle_backend_highs",
                "oracle_backend_device"):
        if key in counters:
            out[key.replace("decomp_oracle_", "").replace("oracle_backend_", "oracle_")] = counters[key]
    return out


def _sparse_stamp(timers: dict, counters: dict):
    """Per-row sparse-operator evidence (solvers/sparse_ops): pack
    overhead, last measured fill, and the hit/miss routing decisions — so
    the cutoff behavior is visible next to the phase times it buys."""
    out = {}
    if "sparse_pack" in timers:
        out["pack_s"] = round(timers["sparse_pack"], 3)
    for key, short in (
        ("sparse_fill_pct", "fill_pct"),
        ("sparse_hit", "hits"),
        ("sparse_miss", "misses"),
    ):
        if key in counters:
            out[short] = counters[key]
    return out or None


def _megakernel_stamp(counters: dict, cfg=None):
    """Per-row fused-PDHG evidence (kernels/pdhg_megakernel): the resolved
    gate state for THIS environment (engaged / interpret / off), how many
    fused dispatches the row's solves actually made and how many lanes they
    fused, plus the VMEM fit budget the auto gate checks shapes against.
    mode "off" with zero dispatches is the honest CPU-CI row — the auto
    gate only engages on a real accelerator."""
    import jax

    from citizensassemblies_tpu.utils.config import default_config

    cfg = cfg or default_config()
    gate = cfg.pdhg_megakernel
    on_tpu = jax.default_backend() == "tpu"
    if gate is False:
        mode = "off"
    elif gate is None:
        mode = "engaged" if on_tpu else "off"
    else:
        mode = "engaged" if on_tpu else "interpret"
    return {
        "mode": mode,
        "dispatches": int(counters.get("megakernel_dispatches", 0)),
        "lanes_fused": int(counters.get("megakernel_lanes", 0)),
        "vmem_budget_mb": int(cfg.pdhg_megakernel_vmem_mb),
    }


BASELINES = {
    # reference golden median LEXIMIN runtimes (BASELINE.md)
    "example_large_200_like": 1161.8,
    "example_small_like_20": 2.7,
    # north-star instance (reference_output/sf_e_110_statistics.txt:22); the
    # real pool is withheld, the synthetic stand-ins match its shape
    "sf_e_like_110": 4011.6,
    "sf_e_skewed_110": 4011.6,
}


def _sampler_throughput(dense, batch: int = 4096, reps: int = 5):
    """Measure the LEGACY scan sampler's panels/s. The former Pallas
    sampler row is gone with the kernel (PR 14 verdict: across five bench
    rounds it never decisively beat the scan path — 11.9k vs 11.2k
    panels/s at the reference shape in BENCH_r05, inside the
    round-to-round variance band below).
    Results are forced to host (``np.asarray``): through a TPU tunnel,
    ``block_until_ready`` alone does not actually drain the pipeline and
    overstated throughput ~1000×.

    The sampler reports a ``{median, min, max, reps}`` BAND, not a point
    (VERDICT r4 #4): the r3→r4 point numbers (scan 18008 → 6864) implied a
    2.6× regression, but no sampler code changed between the rounds
    (``git diff cd4e24e eb869c3`` touches only bench.py) and three fresh
    isolated sessions measured 13.7k–15.7k scan — the r4 number was a
    tunnel/device-load artifact of measuring at the tail of the full
    bench. The band makes that variance visible per run instead of
    recording one draw from it as "the" throughput."""
    import jax
    import numpy as np

    from citizensassemblies_tpu.models.legacy import sample_panels_batch

    out = {}
    samplers = ["scan"]
    key = jax.random.PRNGKey(0)
    for s in samplers:
        panels, ok = sample_panels_batch(dense, key, batch, sampler=s, distribute=False)
        _ = np.asarray(panels).sum()  # compile + warm + drain
        rates = []
        for r in range(reps):
            t0 = time.time()
            panels, ok = sample_panels_batch(
                dense, jax.random.PRNGKey(r + 1), batch, sampler=s, distribute=False
            )
            _ = np.asarray(panels).sum() + np.asarray(ok).sum()
            rates.append(batch / max(time.time() - t0, 1e-9))
        rates.sort()
        out[s] = {
            "median": round(rates[len(rates) // 2]),
            "min": round(rates[0]),
            "max": round(rates[-1]),
            "reps": [round(r) for r in rates],
        }
    return out


def main() -> None:
    from citizensassemblies_tpu.core.generator import random_instance, sf_e_like_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.ops.stats import prob_allocation_stats

    which = os.environ.get("BENCH_INSTANCE", "large")
    inst = _example_small_like() if which == "small" else _example_large_like()
    dense, space = featurize(inst)

    # one warm-up on a tiny instance to amortize kernel compilation out of the
    # measured run (the reference's timing harness also times steady-state
    # re-runs, analysis.py:625-634)
    warm = random_instance(n=64, k=8, n_categories=2, seed=0)
    wdense, wspace = featurize(warm)
    find_distribution_leximin(wdense, wspace)

    # obs stamp for the evidence row: a second warm-instance rep untraced vs
    # traced (sampling mode) gives the per-run trace overhead; span count and
    # schema version ride along so every bench row records which grafttrace
    # contract it was measured under. Tracing the FLAGSHIP runs stays off —
    # the headline numbers must measure the solver, not the tracer.
    from citizensassemblies_tpu.obs import TRACE_SCHEMA_VERSION, Tracer, use_tracer
    from citizensassemblies_tpu.utils.config import default_config as _dc
    from citizensassemblies_tpu.utils.logging import RunLog as _ObsRunLog

    t_plain = time.time()
    find_distribution_leximin(wdense, wspace)
    t_plain = time.time() - t_plain
    _obs_tr = Tracer(name="bench_warm", sample_device=True)
    _obs_log = _ObsRunLog(echo=False)
    _obs_log.tracer = _obs_tr
    t_traced = time.time()
    with use_tracer(_obs_tr):
        find_distribution_leximin(
            wdense, wspace, cfg=_dc().replace(obs_trace=True), log=_obs_log
        )
    t_traced = time.time() - t_traced
    obs_stamp = {
        "schema_version": TRACE_SCHEMA_VERSION,
        "span_count": _obs_tr.span_count,
        "trace_overhead_pct": round(
            100 * (t_traced - t_plain) / max(t_plain, 1e-9), 1
        ),
    }

    t0 = time.time()
    dist = find_distribution_leximin(dense, space)
    elapsed = time.time() - t0

    stats = prob_allocation_stats(dist.allocation, cap_for_geometric_mean=False)
    baseline = BASELINES[inst.name]

    # north-star secondary metric: sf_e-class (n=1727, k=110, 7 categories,
    # ~1000 distinct agent types — the relaxation-first decomposition path)
    detail = {
        "min_prob": round(stats.min, 5),
        "gini": round(stats.gini, 5),
        "committees": int(dist.committees.shape[0]),
        "baseline_s": baseline,
        "speedup": round(baseline / max(elapsed, 1e-9), 1),
    }
    if os.environ.get("BENCH_SKIP_SFE", "") != "1":
        # PRIMARY sf_e-class metric: the *heterogeneous* (skewed-quota) regime
        # matching the real sf_e_110 allocation profile (Gini ≈ 0.5, min well
        # below k/n — reference_output/sf_e_110_statistics.txt:6-11), not the
        # structurally easier pool-proportional regime. `alloc_linf_dev` is
        # the deviation from the probe-certified relaxation-leximin profile —
        # an upper bound in leximin order computed independently of the
        # decomposition that produced the allocation, so realizing it within
        # ε certifies the allocation is the true leximin to that tolerance.
        #
        # The flagship number is a MEDIAN OF BENCH_REPS (default 3) runs,
        # mirroring the reference's own timing harness (analysis.py:625-634),
        # over two generator seeds — seed 1 (heavy skew, the tuned realistic
        # regime) and seed 0 (mild skew). The 4011.6 s baseline was measured
        # on the withheld real pool, not these synthetic stand-ins, so it is
        # marked estimated.
        from citizensassemblies_tpu.core.generator import sf_e_skewed_instance
        from citizensassemblies_tpu.utils.logging import RunLog

        reps = int(os.environ.get("BENCH_REPS", "3"))
        flagship = None
        # the flagship SEED FAMILY (VERDICT r4 #1): the north-star claim must
        # hold across sf_e-CLASS instances, not the seed the decomposition
        # likes — four seeds plus two structural variants (tighter quota
        # bands; more distinct agent types), every one median-of-reps with
        # per-rep phase splits. sf_e_like is the easy near-proportional
        # secondary regime (one rep).
        family = [
            ("sf_e_skewed", lambda: sf_e_skewed_instance(seed=1), "sf_e_skewed_110", reps),
            ("sf_e_skewed_seed0", lambda: sf_e_skewed_instance(seed=0), "sf_e_skewed_110", reps),
            ("sf_e_skewed_seed2", lambda: sf_e_skewed_instance(seed=2), "sf_e_skewed_110", reps),
            ("sf_e_skewed_seed5", lambda: sf_e_skewed_instance(seed=5), "sf_e_skewed_110", reps),
            (
                "sf_e_skewed_tight",
                lambda: sf_e_skewed_instance(seed=3, quota_slack=0.08),
                "sf_e_skewed_110",
                reps,
            ),
            (
                "sf_e_skewed_types",
                lambda: sf_e_skewed_instance(
                    seed=2, features_per_category=[3, 4, 6, 3, 2, 4, 6]
                ),
                "sf_e_skewed_110",
                1,
            ),
            ("sf_e_like", lambda: sf_e_like_instance(seed=0), "sf_e_like_110", 1),
        ]
        from citizensassemblies_tpu.utils.guards import CompilationGuard, GuardViolation

        # bounded-recompile assertion for warm reps: rep 1 may compile every
        # padded bucket the instance shape needs, but later reps of the SAME
        # instance must re-enter those executables — a steady-state rep that
        # recompiles per CG round is exactly the invariant drift graftlint's
        # runtime rails exist to catch. The guard spans the WHOLE solve, so
        # the batched LP engine's bucket executables (solvers/batch_lp.py)
        # are covered by the same bound: a warm rep whose probe prescreen or
        # polish screen re-compiles its buckets trips it exactly like a
        # drifting PDHG core (the engine's per-bucket compiles additionally
        # land in phase_counters as lp_batch_compiles_<bucket>). The bound
        # is generous (a handful of fresh bucket crossings is legitimate);
        # a violation is recorded on the row rather than killing the
        # evidence run.
        warm_rep_compile_bound = int(os.environ.get("BENCH_COMPILE_BOUND", "8"))
        for key, builder, base_key, n_reps in family:
                sfe_dense, sfe_space = featurize(builder())
                runs = []
                compile_counts = []
                compile_guard_ok = True
                for rep in range(n_reps):
                    rlog = RunLog(echo=False)
                    bound = warm_rep_compile_bound if rep > 0 else None
                    t0 = time.time()
                    try:
                        with CompilationGuard(
                            name="leximin", log=rlog, max_compiles=bound
                        ) as cguard:
                            sfe = find_distribution_leximin(
                                sfe_dense, sfe_space, log=rlog
                            )
                    except GuardViolation:
                        compile_guard_ok = False
                    compile_counts.append(cguard.count)
                    runs.append((time.time() - t0, rlog.timers, rlog.counters))
                runs.sort(key=lambda r: r[0])
                times = [r[0] for r in runs]
                # phase split of the MEDIAN rep, so the breakdown matches the
                # reported wall-clock (rep 1 may pay XLA compiles)
                median_s, median_timers, _median_counters = runs[len(runs) // 2]
                dev = float(abs(sfe.allocation - sfe.fixed_probabilities).max())
                sfe_stats = prob_allocation_stats(
                    sfe.allocation, cap_for_geometric_mean=False
                )
                if key == "sf_e_skewed":
                    # keep the flagship solve for reuse by the XMIN row —
                    # solving n=1727 an extra time there risked pushing the
                    # whole bench past a driver timeout
                    flagship = (sfe_dense, sfe_space, sfe)
                audit = None
                if key == "sf_e_skewed" and os.environ.get("BENCH_SKIP_AUDIT", "") != "1":
                    # Solver-independent post-hoc exactness audit at n=1727 —
                    # the role Gurobi's dual-gap certificate plays on every
                    # reference run (leximin.py:429-431): an exact agent-space
                    # HiGHS MILP evaluates a maximin witness, bounding the
                    # first-level suboptimality of the shipped allocation
                    # entirely outside the type-space machinery (see
                    # highs_backend.audit_maximin).
                    from citizensassemblies_tpu.solvers.highs_backend import (
                        audit_maximin,
                    )

                    # level 1 on the REALIZED allocation (the honest shipped
                    # number); the full profile on the CERTIFIED one — its
                    # documented contract, since realized floors leak the
                    # realization ε into later levels — with the
                    # realized-vs-certified gap reported as alloc_linf_dev.
                    audit = audit_maximin(sfe_dense, sfe.allocation, sfe.covered)
                    _attach_profile_audit(
                        audit, sfe_dense, sfe.fixed_probabilities, sfe.covered
                    )
                detail[key] = {
                    "seconds": round(median_s, 1),
                    "runs_s": [round(t, 1) for t in times],
                    "baseline_s": BASELINES[base_key],
                    "baseline_estimated": True,
                    "speedup": round(BASELINES[base_key] / max(median_s, 1e-9), 1),
                    "alloc_linf_dev": round(dev, 8),
                    # covered-mask form, matching the regime-sweep rows below
                    # (flagship pools are fully coverable today, so the mask
                    # is a no-op — the unified form keeps it that way by
                    # construction rather than by coincidence)
                    "min_prob": round(float(sfe.allocation[sfe.covered].min()), 6),
                    "gini": round(sfe_stats.gini, 4),
                    # warm-hit / overlap attribution of the median rep (the
                    # pipelined decomposition's counters, utils/profiling)
                    "phase_counters": runs[len(runs) // 2][2],
                    # XLA compiles per rep (utils/guards.CompilationGuard, in
                    # rep order not time order) + whether every warm rep
                    # stayed under BENCH_COMPILE_BOUND
                    "xla_compiles_per_rep": compile_counts,
                    "compile_guard_ok": compile_guard_ok,
                    "phase_times": {
                        k: round(v, 1) for k, v in sorted(
                            median_timers.items(), key=lambda kv: -kv[1]
                        )
                    },
                    # per-rep phase splits, sorted by wall-clock to align
                    # with runs_s (VERDICT r3 #3: a tail rep must be
                    # attributable to its binding phase, not summarized away
                    # by the median's split)
                    "phase_times_per_rep": [
                        {k: round(v, 1) for k, v in sorted(
                            timers.items(), key=lambda kv: -kv[1]
                        )}
                        for _, timers, _counters in runs
                    ],
                }
                sparse_row = _sparse_stamp(
                    median_timers, runs[len(runs) // 2][2]
                )
                if sparse_row:
                    detail[key]["sparse"] = sparse_row
                detail[key]["megakernel"] = _megakernel_stamp(
                    runs[len(runs) // 2][2]
                )
                sync_row = _host_sync_stamp(runs[len(runs) // 2][2])
                if sync_row:
                    detail[key]["decomp_host_syncs"] = sync_row
                if audit is not None:
                    detail[key]["exactness_audit"] = audit
                if key == "sf_e_skewed_types":
                    # stress variant BEYOND the real sf_e shape (T ≈ 1800
                    # distinct types vs ≈ 1000 on the real feature schema):
                    # the host-IPM polish dominates and the row is recorded
                    # for attribution, not claimed at the ≥50× bar the
                    # sf_e-class family rows meet
                    detail[key]["stress_variant"] = True

    if os.environ.get("BENCH_SKIP_EXTRA", "") != "1":
        import numpy as np

        from citizensassemblies_tpu.core.generator import (
            cca_skewed_instance,
            hd_skewed_instance,
            mass_like_instance,
            nexus_skewed_instance,
            obf_skewed_instance,
            sf_a_skewed_instance,
            sf_b_skewed_instance,
            sf_c_skewed_instance,
            sf_d_skewed_instance,
            sf_e_skewed_instance,
        )

        # regime sweep: ALL remaining baseline shapes, completing the
        # reference's 12-instance table (VERDICT r4 #5) — cca_75 (n=825,
        # 4 cats, strongly heterogeneous), obf_30 (n=321, 8 cats), nexus_170
        # (n=342, k=170: the high-selection-ratio regime), the mid-tier
        # hd_30/sf_d_40, the small sf_a/sf_b/sf_c shapes, and mass_24's
        # tight min=max regime. Real pools withheld; baselines are the
        # reference timings on the real instances, marked estimated. NOTE on
        # the sub-second baselines (mass_24 at 0.5 s especially): our
        # per-run floor is a few hundred ms of host/dispatch overhead, so a
        # ≥50× speedup is arithmetically impossible there — those rows
        # demonstrate coverage (the tight-quota regime solving correctly at
        # speed), not the headline ratio.
        for name, builder, base in (
            ("cca_skewed_75", cca_skewed_instance, 433.5),
            ("obf_skewed_30", obf_skewed_instance, 183.9),
            ("nexus_skewed_170", nexus_skewed_instance, 83.4),
            ("hd_skewed_30", hd_skewed_instance, 37.2),
            ("sf_d_skewed_40", sf_d_skewed_instance, 46.2),
            ("sf_a_skewed_35", sf_a_skewed_instance, 19.6),
            ("sf_b_skewed_20", sf_b_skewed_instance, 8.8),
            ("sf_c_skewed_44", sf_c_skewed_instance, 6.0),
            ("mass_like_24", mass_like_instance, 0.5),
        ):
            d2, s2 = featurize(builder())
            # median of 3: these rows are seconds each, and a single-sample
            # row is one TPU-tunnel latency burst away from recording a 20×
            # outlier as the instance's number. Keep (time, result) pairs so
            # the quality stats describe the SAME solve as the reported
            # median time, as the flagship rows do.
            from citizensassemblies_tpu.utils.logging import RunLog as _RRunLog

            runs2 = []
            for _ in range(int(os.environ.get("BENCH_REPS", "3"))):
                rlog2 = _RRunLog(echo=False)
                t0 = time.time()
                r2 = find_distribution_leximin(d2, s2, log=rlog2)
                runs2.append((time.time() - t0, r2, rlog2.counters))
            runs2.sort(key=lambda tr: tr[0])
            times2 = [t for t, _, _ in runs2]
            el2, r2, counters2 = runs2[len(runs2) // 2]
            st2 = prob_allocation_stats(r2.allocation, cap_for_geometric_mean=False)
            detail[name] = {
                "seconds": round(el2, 1),
                "runs_s": [round(t, 1) for t in times2],
                "baseline_s": base,
                "baseline_estimated": True,
                "speedup": round(base / max(el2, 1e-9), 1),
                "alloc_linf_dev": round(
                    float(abs(r2.allocation - r2.fixed_probabilities).max()), 8
                ),
                "min_prob": round(float(r2.allocation[r2.covered].min()), 6),
                "gini": round(st2.gini, 4),
            }
            if counters2:
                # lp_batch_* engine attribution — on mass_like_24-sized
                # instances this shows the probe fleet routing through ONE
                # dispatch (amortizing the per-run host/dispatch floor the
                # row's floor_note records) instead of per-candidate LPs
                detail[name]["phase_counters"] = dict(counters2)
            if base / max(el2, 1e-9) < 50 and base <= 50:
                # the recorded reason for a sub-50× ratio on a SMALL-BASELINE
                # row (gate: baseline ≤ 50 s — on larger baselines a sub-50×
                # ratio is a real finding, not a floor artifact): per-run
                # fixed costs (JAX dispatch through the TPU tunnel
                # ~0.16 s/call, host LP/solver startup) floor any solve at a
                # few hundred ms, so ratios against small baselines are
                # capped by arithmetic, not by the algorithm — the absolute
                # wall-clock is the informative number here
                detail[name]["floor_note"] = (
                    "sub-50x is the fixed per-run host/dispatch floor vs a "
                    "small baseline; absolute wall-clock is the informative "
                    "number"
                )

        # XMIN at sf_e scale (VERDICT r2 item #5): the reference's costliest
        # path (iterated full re-solves, xmin.py:511-542) replaced by the
        # one-shot batched-expansion + min-L2 design; the leximin profile
        # must be preserved while the support multiplies.
        from citizensassemblies_tpu.models.xmin import find_distribution_xmin

        if os.environ.get("BENCH_SKIP_SFE", "") != "1" and flagship is not None:
            sfe_dense, sfe_space, lex_ref = flagship
            t_lex = detail["sf_e_skewed"]["seconds"]
        else:  # BENCH_SKIP_SFE=1: solve the seed here
            sfe_dense, sfe_space = featurize(sf_e_skewed_instance(seed=1))
            t0 = time.time()
            lex_ref = find_distribution_leximin(sfe_dense, sfe_space)
            t_lex = time.time() - t0
        from citizensassemblies_tpu.utils.guards import CompilationGuard
        from citizensassemblies_tpu.utils.logging import RunLog as _RunLog

        xlog = _RunLog(echo=False)
        t0 = time.time()
        # the expansion runs under its own CompilationGuard so the batched
        # engine's per-bucket compiles (lp_batch_compiles_*) land next to an
        # overall xla_compiles_xmin count on the row — the XMIN sibling of
        # the flagship warm-rep bound (XMIN runs once, so the count is
        # recorded rather than asserted)
        with CompilationGuard(name="xmin", log=xlog):
            xm = find_distribution_xmin(sfe_dense, sfe_space, leximin=lex_ref, log=xlog)
        el_x = time.time() - t0
        detail["xmin_sf_e_skewed"] = {
            # end-to-end cost including the leximin seed it consumes (the
            # reference's XMIN likewise starts with a full LEXIMIN run)
            "seconds": round(t_lex + el_x, 1),
            "expansion_seconds": round(el_x, 1),
            # phase split of the expansion (VERDICT r4 #6): device draws,
            # host dedup, and the min-L2 stage (xmin_l2, containing the
            # device min-ε anchor l2_eps_pdhg and the dual ascent
            # l2_dual_ascent — the host ε-LP no longer runs on this path)
            "phase_times": {
                k: round(v, 1)
                for k, v in sorted(xlog.timers.items(), key=lambda kv: -kv[1])
            },
            # lp_batch_* engine counters (solves-per-dispatch, per-bucket
            # compiles, the fused-L2 marker) + xla_compiles_xmin
            "phase_counters": dict(xlog.counters),
            "support_panels": len(xm.support()),
            "leximin_support_panels": len(lex_ref.support()),
            "linf_vs_leximin": round(
                float(
                    np.abs(np.sort(xm.allocation) - np.sort(lex_ref.allocation)).max()
                ),
                8,
            ),
            # PER-AGENT L∞ (VERDICT r5 missing #3): sorting can mask a
            # permutation error, and XMIN's contract is per-agent
            # preservation — this is the already-computed
            # Distribution.realization_dev, recorded alongside the sorted
            # comparison instead of only being asserted internally
            "realization_dev": round(float(xm.realization_dev), 8),
            "min_prob": round(float(xm.allocation.min()), 6),
        }
        xmin_sparse = _sparse_stamp(xlog.timers, dict(xlog.counters))
        if xmin_sparse:
            detail["xmin_sf_e_skewed"]["sparse"] = xmin_sparse
        detail["xmin_sf_e_skewed"]["megakernel"] = _megakernel_stamp(
            dict(xlog.counters)
        )
        xmin_sync = _host_sync_stamp(dict(xlog.counters))
        if xmin_sync:
            detail["xmin_sf_e_skewed"]["decomp_host_syncs"] = xmin_sync

        # household-constrained runs (VERDICT r2 #5 / r3 #5). The reference
        # handles households by staying in agent space forever
        # (leximin.py:211-221); here they route through the household
        # QUOTIENT (solvers/quotient.py): orbits = (household class, base
        # type), class caps as quota rows, household-disjoint slicing. The
        # n=400 row shows the before/after against r3's agent-space 32.9 s;
        # the n=1200 row is the at-scale evidence, with a solver-independent
        # audit_maximin certificate evaluated on the augmented instance
        # (class caps built in ⇒ the MILP bound is tight for the
        # household-constrained feasible set, not just an over-set).
        from citizensassemblies_tpu.core.generator import skewed_instance
        from citizensassemblies_tpu.solvers.highs_backend import audit_maximin
        from citizensassemblies_tpu.solvers.quotient import build_household_quotient

        def _run_households(tag, inst_h, households):
            from citizensassemblies_tpu.utils.logging import RunLog

            hh_dense, hh_space = featurize(inst_h)
            hlog = RunLog(echo=False)
            t0 = time.time()
            try:
                hh = find_distribution_leximin(
                    hh_dense, hh_space, households=households, log=hlog
                )
            except Exception as exc:  # InfeasibleQuotasError: apply suggestion
                from citizensassemblies_tpu.core.instance import (
                    InfeasibleQuotasError,
                )

                if not isinstance(exc, InfeasibleQuotasError):
                    raise
                # household rows shrink the feasible set; the framework's
                # relaxation MILP suggests the minimal quota adjustment (the
                # reference's organizer loop, leximin.py:81-87) — apply, rerun
                import dataclasses

                repaired = {
                    cat: {f: exc.quotas[(cat, f)] for f in feats}
                    for cat, feats in inst_h.categories.items()
                }
                hh_dense, hh_space = featurize(
                    dataclasses.replace(inst_h, categories=repaired)
                )
                hlog = RunLog(echo=False)
                t0 = time.time()
                hh = find_distribution_leximin(
                    hh_dense, hh_space, households=households, log=hlog
                )
            el_h = time.time() - t0
            quotient = build_household_quotient(hh_dense, households)
            # level-1 certificate on the REALIZED allocation plus the FULL
            # leximin-profile certificate on the certified orbit values
            # (VERDICT r4 #2a) — both evaluated on the augmented instance,
            # where the class caps make the exact agent-space MILP bound
            # valid for the household-constrained feasible set (any
            # cap-respecting orbit count vector is realizable household-
            # disjoint, and the witness weights are orbit-constant, see
            # solvers/quotient.py). This is the role the reference's
            # per-stage Gurobi dual gap plays on its household runs too
            # (leximin.py:211-221,429-431).
            audit = audit_maximin(quotient.dense_aug, hh.allocation, hh.covered)
            _attach_profile_audit(
                audit, quotient.dense_aug, hh.fixed_probabilities, hh.covered
            )
            detail[tag] = {
                "seconds": round(el_h, 1),
                "alloc_linf_dev": round(
                    float(abs(hh.allocation - hh.fixed_probabilities).max()), 8
                ),
                "min_prob": round(float(hh.allocation[hh.covered].min()), 6),
                "household_classes": int(quotient.n_classes),
                "phase_times": {
                    k: round(v, 1)
                    for k, v in sorted(hlog.timers.items(), key=lambda kv: -kv[1])
                },
                "phase_counters": hlog.counters,
                "exactness_audit": audit,
            }
            hh_sparse = _sparse_stamp(hlog.timers, hlog.counters)
            if hh_sparse:
                detail[tag]["sparse"] = hh_sparse
            detail[tag]["megakernel"] = _megakernel_stamp(dict(hlog.counters))
            hh_sync = _host_sync_stamp(hlog.counters)
            if hh_sync:
                detail[tag]["decomp_host_syncs"] = hh_sync

        _run_households(
            "households_n400",
            skewed_instance(
                n=400, k=40, n_categories=6, seed=2,
                features_per_category=[2, 3, 4, 2, 3, 3],
            ),
            np.arange(400) // 2,  # 200 two-person households
        )
        _run_households(
            "households_n1200",
            skewed_instance(
                n=1200, k=110, n_categories=7, seed=2,
                features_per_category=[2, 4, 5, 3, 2, 4, 6], skew=0.4,
            ),
            np.arange(1200) // 2,  # 600 couples — sf_e-class orbit count
        )

    if os.environ.get("BENCH_SKIP_SAMPLER", "") != "1":
        # sampler throughput on the sf_e-shaped pool (the hot MC kernel)
        thr_dense, _ = featurize(sf_e_like_instance())
        detail["sampler_panels_per_s"] = _sampler_throughput(thr_dense)

    result = {
        "metric": f"leximin_wallclock_{inst.name}",
        "value": round(elapsed, 2),
        "unit": "s",
        "vs_baseline": round(elapsed / baseline, 4),
        "detail": detail,
        # grafttrace provenance of the row (schema, span count and measured
        # overhead of the warm-instance traced rep — see obs_stamp above)
        "obs": obs_stamp,
    }
    # budget provenance: which ANALYSIS_BUDGET.json ratchet state this
    # evidence row was measured against (sha + core count + jax version)
    try:
        from citizensassemblies_tpu.lint.ir import budget_provenance

        result["ir_budget"] = budget_provenance()
    except Exception:  # provenance must never kill a bench run
        result["ir_budget"] = {"error": "unavailable"}
    # and the SPMD_BUDGET.json collective-census ratchet state (graftspmd) —
    # the second budget this row's numbers are attributable to
    try:
        from citizensassemblies_tpu.lint.spmd import spmd_budget_provenance

        result["spmd_budget"] = spmd_budget_provenance()
    except Exception:
        result["spmd_budget"] = {"error": "unavailable"}
    # and the PRECISION_PLAN.json certification state (graftgrade) — which
    # committed bf16 demotion plan this row's numbers ran under
    try:
        from citizensassemblies_tpu.lint.prec import prec_plan_provenance

        result["prec_plan"] = prec_plan_provenance()
    except Exception:
        result["prec_plan"] = {"error": "unavailable"}
    try:
        from citizensassemblies_tpu.utils.memo import memo_evictions

        # LRU memo pressure over the whole run (utils/memo): nonzero means
        # some executable cache cycled — expected on mesh churn, worth
        # seeing next to the compile counters if it ever grows
        result["memo_evictions"] = memo_evictions()
    except Exception:
        pass
    print(json.dumps(result))

    # Durable evidence (VERDICT r5 missing #1): the driver records only the
    # LAST ~2000 characters of this process's output, and the flagship
    # seed-family rows print first inside the single JSON line — so every
    # prior round's committed artifact lost its own headline. Two fixes:
    # (a) the COMPLETE per-round result is written to a committed
    # BENCH_detail_rNN.json in the repo root (NN = one past the newest
    # BENCH_r*.json, override with BENCH_DETAIL_PATH), and (b) a compact
    # flagship summary prints as the FINAL line, inside any tail window.
    detail_path = os.environ.get("BENCH_DETAIL_PATH")
    if not detail_path:
        import glob
        import re

        root = os.path.dirname(os.path.abspath(__file__))
        rounds = [
            int(m.group(1))
            for f in glob.glob(os.path.join(root, "BENCH_r*.json"))
            for m in [re.match(r"BENCH_r(\d+)\.json$", os.path.basename(f))]
            if m
        ]
        nn = (max(rounds) + 1) if rounds else 1
        detail_path = os.path.join(root, f"BENCH_detail_r{nn:02d}.json")
    try:
        with open(detail_path, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=1)
    except OSError as exc:  # never let the artifact write kill the bench
        detail_path = f"(unwritable: {exc})"

    summary = {"detail_file": os.path.basename(str(detail_path))}
    if isinstance(result.get("ir_budget"), dict) and "sha256" in result["ir_budget"]:
        summary["ir_budget"] = result["ir_budget"]["sha256"]
    if isinstance(result.get("spmd_budget"), dict) and "sha256" in result["spmd_budget"]:
        summary["spmd_budget"] = result["spmd_budget"]["sha256"]
    if isinstance(result.get("prec_plan"), dict) and "sha256" in result["prec_plan"]:
        summary["prec_plan"] = result["prec_plan"]["sha256"]
    flag = {}
    for key in (
        "sf_e_skewed", "sf_e_skewed_seed0", "sf_e_skewed_seed2",
        "sf_e_skewed_seed5", "sf_e_skewed_tight", "sf_e_skewed_types",
        "sf_e_like",
    ):
        row = detail.get(key)
        if isinstance(row, dict) and "seconds" in row:
            flag[key] = {
                "s": row["seconds"],
                "worst_s": max(row.get("runs_s", [row["seconds"]])),
                "x": row.get("speedup"),
                "linf": row.get("alloc_linf_dev"),
                "compiles_ok": row.get("compile_guard_ok"),
            }
    if flag:
        summary["flagship"] = flag
    for key in ("households_n400", "households_n1200"):
        row = detail.get(key)
        if isinstance(row, dict):
            audit = row.get("exactness_audit") or {}
            summary[key] = {
                "s": row["seconds"],
                "decomp_s": row.get("phase_times", {}).get("decomp"),
                "linf": row.get("alloc_linf_dev"),
                "profile_ok": audit.get("profile_all_within_tol"),
                # the device-pricing target: host↔device syncs per CG round
                "host_syncs_per_round": (row.get("decomp_host_syncs") or {}).get(
                    "per_round"
                ),
            }
    if "xmin_sf_e_skewed" in detail:
        xr = detail["xmin_sf_e_skewed"]
        summary["xmin"] = {
            "s": xr["seconds"],
            "realization_dev": xr.get("realization_dev"),
        }
    print(json.dumps({"flagship_summary": summary}))


def smoke() -> int:
    """CI smoke mode: tiny instances, 1 rep, slow rows skipped — but the
    batched-engine INVARIANTS asserted for real.

    Three checks, each a regression CI must catch without waiting for the
    offline bench:

    * **parity** — a fleet of small final-ε LPs solved by the batched
      engine matches the serial PDHG solver's objectives within tolerance,
      and a tiny end-to-end LEXIMIN run agrees with the engine-off run;
    * **dispatch count** — the fleet solves in exactly one device call per
      shape bucket (``lp_batch_dispatches`` == bucket count), the
      solves-per-dispatch contract;
    * **compile bound** — a SECOND identical fleet call re-enters the
      compiled bucket executables with zero fresh XLA compiles, and the
      warm LEXIMIN rep stays under ``BENCH_COMPILE_BOUND``;
    * **device-pricing syncs** — the same tiny face decomposition through
      the host-oracle path and the device-pricing path: the device path
      must make STRICTLY FEWER host↔device syncs, its steady-state rounds
      at most one each, with the device screen actually serving anchors.

    Prints one JSON line and returns a process exit code (non-zero on any
    violated invariant), so ``.github/workflows/ci.yml`` can run it right
    after tier-1.
    """
    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        solve_lp_batch,
    )
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_lp
    from citizensassemblies_tpu.utils.config import default_config
    from citizensassemblies_tpu.utils.guards import CompilationGuard
    from citizensassemblies_tpu.utils.logging import RunLog

    t_start = time.time()
    failures = []
    bound = int(os.environ.get("BENCH_COMPILE_BOUND", "8"))
    # the engine is exercised explicitly (CPU CI would auto-route it off)
    cfg = default_config().replace(lp_batch=True)

    # --- batched-engine parity + dispatch count ----------------------------
    rng = np.random.default_rng(0)
    fleet = []
    serial_obj = []
    for i in range(10):
        C, n = 16 + 4 * (i % 3), 8 + (i % 3)
        P = rng.random((C, n)) < 0.5
        q = rng.random(C)
        q /= q.sum()
        inst = final_primal_batch_lp(P, P.T.astype(np.float64) @ q)
        fleet.append(inst)
        serial_obj.append(
            solve_lp(inst.c, inst.G, inst.h, inst.A, inst.b, cfg=cfg).objective
        )
    slog = RunLog(echo=False)
    sols = solve_lp_batch(fleet, cfg=cfg, log=slog, max_iters=20_000)
    parity = max(abs(s.objective - o) for s, o in zip(sols, serial_obj))
    if parity > 1e-3:
        failures.append(f"batch-vs-serial objective parity {parity:.2e} > 1e-3")
    n_buckets = len(
        {k for k in slog.counters if k.startswith("lp_batch_compiles_")}
    ) or slog.counters.get("lp_batch_dispatches", 0)
    dispatches = slog.counters.get("lp_batch_dispatches", 0)
    if dispatches != n_buckets:
        failures.append(
            f"dispatch count {dispatches} != bucket count {n_buckets} "
            "(solves-per-dispatch regression)"
        )
    # second identical call: every bucket executable must be re-entered
    with CompilationGuard(name="smoke_warm") as warm_guard:
        solve_lp_batch(fleet, cfg=cfg, max_iters=20_000)
    if warm_guard.count > 0:
        failures.append(
            f"warm fleet call compiled {warm_guard.count}x (bucket cache miss)"
        )

    # --- sparse-operator parity (solvers/sparse_ops) -----------------------
    # dense vs ELL two-sided master on one composition-shaped fixture, plus
    # the incremental-append == full-repack invariant — the CI-visible slice
    # of tests/test_sparse_ops.py, so a routing or pack regression fails the
    # smoke job without waiting for the offline bench
    from citizensassemblies_tpu.solvers.lp_pdhg import (
        solve_two_sided_master,
        solve_two_sided_master_ell,
    )
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack

    srng = np.random.default_rng(7)
    T, C = 24, 96
    comps = (srng.random((C, T)) < 0.2) * srng.integers(1, 4, (C, T))
    MT = (comps.astype(np.float64) / 8.0).T
    v_prof = MT @ (srng.dirichlet(np.ones(C)))
    sol_dense = solve_two_sided_master(MT, v_prof, cfg=cfg, max_iters=20_000)
    ell_inc = EllPack(minor=T)
    ell_inc.append(MT.T[: C // 2])  # incremental: two appends, one take
    ell_inc.append(MT.T[C // 2 :])
    ell_full = EllPack.from_rows(MT.T, minor=T)
    if not (
        np.array_equal(ell_inc.idx, ell_full.idx)
        and np.array_equal(ell_inc.val, ell_full.val)
    ):
        failures.append("incremental ELL append != full repack")
    sol_ell = solve_two_sided_master_ell(ell_full, v_prof, cfg=cfg, max_iters=20_000)
    pd_ = np.maximum(sol_dense.x[:C], 0.0)
    pe_ = np.maximum(sol_ell.x[:C], 0.0)
    pd_, pe_ = pd_ / pd_.sum(), pe_ / pe_.sum()
    eps_d = float(np.abs(MT @ pd_ - v_prof).max())
    eps_e = float(np.abs(MT @ pe_ - v_prof).max())
    sparse_parity = abs(eps_d - eps_e)
    if eps_e > max(2 * eps_d, 1e-4):
        failures.append(
            f"sparse master parity: ELL eps {eps_e:.2e} vs dense {eps_d:.2e}"
        )

    # --- megakernel parity (kernels/pdhg_megakernel) -----------------------
    # the SAME master once more through the fused Pallas iterate (interpret
    # mode on CPU CI, the compiled Mosaic kernel on a real accelerator):
    # chained-vs-fused x within the 1e-3 L∞ contract and the fused solve
    # certifying the same ε. The warm-compile bound below is asserted with
    # the DEFAULT gate (None ⇒ chained on CPU), so the fused path cannot
    # perturb the bound it rides under.
    from citizensassemblies_tpu.kernels import pdhg_megakernel as _mkmod

    mk_cfg = cfg.replace(pdhg_megakernel=True)
    mk_vmem = _mkmod.two_sided_vmem_bytes(T, 128, int(ell_full.k_pad))
    mk_mode = _mkmod.megakernel_mode(mk_cfg, mk_vmem)
    t_mk = time.time()
    sol_mk = solve_two_sided_master_ell(
        ell_full, v_prof, cfg=mk_cfg, max_iters=20_000
    )
    mk_seconds = time.time() - t_mk
    mk_parity = float(
        np.abs(np.asarray(sol_mk.x) - np.asarray(sol_ell.x)).max()
    )
    pm_ = np.maximum(sol_mk.x[:C], 0.0)
    pm_ = pm_ / pm_.sum()
    eps_m = float(np.abs(MT @ pm_ - v_prof).max())
    if mk_mode == "off":
        failures.append(
            f"megakernel gate resolved 'off' for the smoke shape "
            f"(vmem {mk_vmem} bytes) — the parity check is vacuous"
        )
    if mk_parity > 1e-3:
        failures.append(
            f"megakernel chained-vs-fused x L∞ {mk_parity:.2e} > 1e-3"
        )
    if eps_m > max(2 * eps_d, 1e-4):
        failures.append(
            f"megakernel master parity: fused eps {eps_m:.2e} vs dense "
            f"{eps_d:.2e}"
        )
    mk_stamp = {
        "mode": mk_mode,
        "parity_linf": round(mk_parity, 9),
        "eps_fused": round(eps_m, 9),
        "seconds": round(mk_seconds, 2),
        "lanes": 1,
        "vmem_bytes": int(mk_vmem),
    }

    # --- device-pricing host-sync invariants (solvers/device_pricing) ------
    # the same tiny face decomposition run twice through the forced device-
    # master route: once with the host anchor MILPs (gate off) and once with
    # the device pricer + fused screen (gate on). Three asserts, all CI-
    # cheap: the device path makes STRICTLY FEWER host↔device syncs, its
    # steady-state rounds stay at ≤ 1 sync each, and the device screen
    # actually served anchors (otherwise the comparison is vacuous). Both
    # runs certify the same profile, so the sync win cannot come from
    # giving up exactness.
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.solvers.cg_typespace import (
        CompositionOracle,
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.face_decompose import realize_profile
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    dp_dense, _dp_space = featurize(
        skewed_instance(n=160, k=14, n_categories=4, seed=2)
    )
    dp_red = TypeReduction(dp_dense)
    dp_v, _x = _leximin_relaxation(dp_red, RunLog(echo=False))
    dp_seeds = _slice_relaxation(
        dp_v * dp_red.msize.astype(np.float64), dp_red, R=4
    )
    dp_counters = {}
    dp_eps = {}
    dp_times = {}
    for gate in (False, True):
        dp_cfg = cfg.replace(
            decomp_host_master_max_types=0, decomp_device_pricing=gate
        )
        dp_log = RunLog(echo=False)
        t_dp = time.time()
        _C, _p, eps_run, _s = realize_profile(
            dp_red, dp_v, list(dp_seeds), CompositionOracle(dp_red, log=dp_log),
            5e-4, log=dp_log, max_rounds=8, use_pdhg=True, cfg=dp_cfg,
        )
        dp_times[gate] = time.time() - t_dp
        dp_counters[gate] = dp_log.counters
        dp_eps[gate] = eps_run
    sync_host = dp_counters[False].get("decomp_host_syncs", 0)
    sync_dev = dp_counters[True].get("decomp_host_syncs", 0)
    dev_rounds = dp_counters[True].get("decomp_rounds", 0)
    dev_steady = sync_dev - dp_counters[True].get("decomp_polish_syncs", 0)
    if sync_dev >= sync_host:
        failures.append(
            f"device pricing made {sync_dev} host syncs vs {sync_host} on the "
            "host-oracle path (must be strictly fewer)"
        )
    if dev_rounds and dev_steady > dev_rounds:
        failures.append(
            f"device-pricing steady-state syncs {dev_steady} exceed rounds "
            f"{dev_rounds} (> 1 per CG round)"
        )
    if dp_counters[True].get("decomp_oracle_device_hit", 0) < 1:
        failures.append("device pricer served no anchors (screen inert)")
    stalled_bar = max(5e-4, cfg.decomp_accept, cfg.decomp_accept_stalled)
    if dp_eps[True] > stalled_bar:
        failures.append(
            f"device-pricing run failed to certify (eps {dp_eps[True]:.2e})"
        )

    # --- grafttrace: traced face decomposition + artifact + coverage --------
    # the SAME tiny decomposition once more under a sampling tracer
    # (Config.obs_trace=True): asserts the acceptance-criteria contract —
    # the exported Chrome trace validates against the schema and its spans
    # cover ≥ 90 % of the face-decomposition phase's wall time — and writes
    # the trace + a Prometheus metrics snapshot as CI artifacts. The
    # untraced gate=True run above doubles as the overhead baseline for the
    # row's obs stamp (recorded, not asserted: tiny runs are noisy).
    from citizensassemblies_tpu.obs import (
        Tracer,
        export_chrome_trace,
        span_coverage,
        use_tracer,
        validate_chrome_trace,
    )

    obs_cfg = cfg.replace(
        decomp_host_master_max_types=0, decomp_device_pricing=True,
        obs_trace=True,
    )
    obs_tracer = Tracer(name="smoke_face_decompose", sample_device=True)
    obs_log = RunLog(echo=False)
    obs_log.tracer = obs_tracer
    t_traced = time.time()
    with use_tracer(obs_tracer):
        with obs_tracer.span("face_decompose"):
            realize_profile(
                dp_red, dp_v, list(dp_seeds),
                CompositionOracle(dp_red, log=obs_log),
                5e-4, log=obs_log, max_rounds=8, use_pdhg=True, cfg=obs_cfg,
            )
    t_traced = time.time() - t_traced
    coverage = span_coverage(obs_tracer, "face_decompose")
    if coverage < 0.90:
        failures.append(
            f"trace spans cover {coverage:.1%} of the face-decomposition "
            "phase (< 90%)"
        )
    trace_path = os.environ.get(
        "BENCH_TRACE_PATH", os.path.join(_artifacts_dir(), "trace_smoke.json")
    )
    trace_doc = export_chrome_trace([obs_tracer], path=trace_path)
    schema_problems = validate_chrome_trace(trace_doc)
    if schema_problems:
        failures.append(f"trace schema invalid: {schema_problems[:3]}")
    metrics_path = os.environ.get(
        "BENCH_METRICS_PATH", os.path.join(_artifacts_dir(), "metrics_smoke.prom")
    )
    try:
        with open(metrics_path, "w", encoding="utf-8") as fh:
            fh.write(obs_log.metrics.render_prometheus())
    except OSError:
        metrics_path = "(unwritable)"
    obs_stamp = {
        "schema_version": trace_doc["schema_version"],
        "span_count": obs_tracer.span_count,
        "decomp_span_coverage_pct": round(100 * coverage, 1),
        # traced (block-until-ready sampling) vs untraced wall of the same
        # tiny decomposition — noisy at this scale, recorded for the trend
        "trace_overhead_pct": round(
            100 * (t_traced - dp_times[True]) / max(dp_times[True], 1e-9), 1
        ),
        "trace_file": os.path.basename(str(trace_path)),
        "metrics_file": os.path.basename(str(metrics_path)),
    }

    # --- graftscope roofline join: static↔runtime totality ------------------
    # every dispatch span the traced decomposition fired must join a row of
    # the committed ANALYSIS_BUDGET.json — a miss means a core executed that
    # the static layer cannot see, exactly the drift R10/check-ir guard
    # against, now cross-checked at runtime on every CI run. (Achieved
    # rates are NOT asserted here: the decomposition runs at its own
    # shapes; the honest-rate rows come from ``--roofline``.)
    from citizensassemblies_tpu.obs import roofline_join

    roof = roofline_join([obs_tracer])
    if roof.misses:
        failures.append(
            f"roofline join misses (span with no budget row): {roof.misses}"
        )
    obs_stamp["roofline_cores_joined"] = len(roof.rows)

    # --- tiny end-to-end parity (engine on vs off) + warm compile bound ----
    dense, space = featurize(random_instance(n=64, k=8, n_categories=2, seed=0))
    d_off = find_distribution_leximin(dense, space, cfg=cfg.replace(lp_batch=False))
    d_on = find_distribution_leximin(dense, space, cfg=cfg)
    e2e = float(
        np.abs(d_on.fixed_probabilities - d_off.fixed_probabilities).max()
    )
    if e2e > 1e-6:
        failures.append(f"engine on/off certified-value drift {e2e:.2e} > 1e-6")
    # graftscope leak sentinel: ≥ 3 warm flagship reps under an ambient
    # memory ledger — STRICTLY monotone live-byte growth across warm reps
    # is a leak verdict and fails the smoke (warm reps re-entering compiled
    # code must reach a steady state, not accrete device buffers per call)
    from citizensassemblies_tpu.obs import MemoryLedger, leak_verdict, use_ledger

    mem_ledger = MemoryLedger(name="smoke_warm_leximin")
    mem_ledger.snapshot("baseline")
    with use_ledger(mem_ledger):
        with CompilationGuard(name="smoke_leximin", max_compiles=None) as lex_guard:
            find_distribution_leximin(dense, space, cfg=cfg)
        mem_ledger.snapshot("warm_rep")
        for _rep in range(2):
            find_distribution_leximin(dense, space, cfg=cfg)
            mem_ledger.snapshot("warm_rep")
    if lex_guard.count > bound:
        failures.append(
            f"warm leximin rep compiled {lex_guard.count}x > bound {bound}"
        )
    live_series = mem_ledger.series("warm_rep")
    if leak_verdict(live_series):
        failures.append(
            f"leak sentinel: live bytes grew monotonically across "
            f"{len(live_series)} warm leximin reps: {live_series}"
        )
    mem_full = mem_ledger.stamp()
    mem_stamp = {
        "schema_version": mem_full["schema_version"],
        "snapshots": mem_full["snapshots"],
        "high_watermark_bytes": mem_full["high_watermark_bytes"],
        "live_bytes_warm_reps": live_series,
        "live_arrays_last": mem_full.get("live_arrays_last"),
        "leak": leak_verdict(live_series),
        # top-5 owners by resident cached bytes (full map in the ledger)
        "owners_top": dict(list(mem_full.get("owners", {}).items())[:5]),
    }

    print(
        json.dumps(
            {
                "smoke_ok": not failures,
                "seconds": round(time.time() - t_start, 1),
                "parity_linf": round(parity, 9),
                "sparse_parity_eps": round(sparse_parity, 9),
                "megakernel": mk_stamp,
                "device_pricing": {
                    "host_syncs_host_oracle": sync_host,
                    "host_syncs_device": sync_dev,
                    "rounds_device": dev_rounds,
                    "steady_syncs_per_round": (
                        round(dev_steady / dev_rounds, 2) if dev_rounds else None
                    ),
                    "device_hits": dp_counters[True].get(
                        "decomp_oracle_device_hit", 0
                    ),
                    "device_misses": dp_counters[True].get(
                        "decomp_oracle_device_miss", 0
                    ),
                },
                "e2e_linf": round(e2e, 9),
                "lp_batch_counters": dict(slog.counters),
                "warm_fleet_compiles": warm_guard.count,
                "warm_leximin_compiles": lex_guard.count,
                "obs": obs_stamp,
                "memory": mem_stamp,
                "failures": failures,
            }
        )
    )
    return 1 if failures else 0


def kernels_bench(smoke_mode: bool = False) -> int:
    """``--kernels``: the kernel-family microbench — PDHG block-iteration
    throughput chained vs fused at the three hot shapes (flagship master,
    household-quotient master, the batched polish screen) plus the scan
    sampler's panels/s band, written as a ``BENCH_kernels_rNN.json``
    artifact in the BENCH_detail row schema so ``obs/trend.py`` folds the
    kernel family into the regression gate.

    On CPU the fused rows run the INTERPRET-mode kernel: they are
    correctness trajectories with honest interpreter wall times, not
    hardware numbers — the per-row megakernel stamp records which regime
    produced them, and every chained/fused pair is held to the 1e-3 L∞
    exactness contract regardless of regime."""
    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.solvers.batch_lp import solve_polish_screen_ell
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_two_sided_master_ell
    from citizensassemblies_tpu.solvers.sparse_ops import EllPack
    from citizensassemblies_tpu.utils.config import default_config

    t_start = time.time()
    iters = 256 if smoke_mode else 2048
    reps = 2 if smoke_mode else 3
    failures = []
    detail = {}

    def _master_fixture(seed, T, C, density, scale):
        r = np.random.default_rng(seed)
        comps = (r.random((C, T)) < density) * r.integers(1, 4, (C, T))
        MT = (comps / scale).T.astype(np.float64)
        v = MT @ np.full(C, 1.0 / C)
        return EllPack.from_rows(np.asarray(MT, np.float32).T, minor=T), v

    # hot shapes 1+2: the serial two-sided masters (flagship-composition
    # and household-quotient aspect ratios). tol=1e-12 pins the iteration
    # count to max_iters so the rows measure block throughput, not the
    # (shape-dependent) convergence point.
    for tag, (ell, v) in (
        ("T24_C96", _master_fixture(7, 24, 96, 0.2, 8.0)),
        ("T40_C64", _master_fixture(11, 40, 64, 0.12, 4.0)),
    ):
        xs = {}
        for path, gate in (("chained", False), ("fused", True)):
            cfg = default_config().replace(pdhg_megakernel=gate)
            solve_two_sided_master_ell(
                ell, v, cfg=cfg, tol=1e-12, max_iters=iters
            )  # warm the bucket executable out of the timed reps
            times, sol = [], None
            for _ in range(reps):
                t0 = time.time()
                sol = solve_two_sided_master_ell(
                    ell, v, cfg=cfg, tol=1e-12, max_iters=iters
                )
                times.append(time.time() - t0)
            times.sort()
            med = times[len(times) // 2]
            xs[path] = np.asarray(sol.x)
            detail[f"kernel_master_{path}_{tag}"] = {
                "seconds": round(med, 3),
                "iters": int(sol.iters),
                "iters_per_s": round(int(sol.iters) / max(med, 1e-9)),
                "megakernel": _megakernel_stamp({}, cfg),
            }
        pair_linf = float(np.abs(xs["chained"] - xs["fused"]).max())
        detail[f"kernel_master_fused_{tag}"]["pair_linf"] = round(pair_linf, 9)
        if pair_linf > 1e-3:
            failures.append(
                f"kernel row {tag}: chained-vs-fused x L∞ {pair_linf:.2e} > 1e-3"
            )

    # hot shape 3: the batched polish screen (one dispatch, 3 real lanes on
    # a B=4 grid — the megakernel's lane-fusion case)
    ell_b, v_b = _master_fixture(7, 24, 96, 0.2, 8.0)
    caps = [96, 48, 24]
    for path, gate in (("chained", False), ("fused", True)):
        cfg = default_config().replace(pdhg_megakernel=gate)
        solve_polish_screen_ell(
            ell_b, v_b, caps, [None] * 3, 1e-12, iters, cfg=cfg
        )
        times, sols = [], None
        for _ in range(reps):
            t0 = time.time()
            sols = solve_polish_screen_ell(
                ell_b, v_b, caps, [None] * 3, 1e-12, iters, cfg=cfg
            )
            times.append(time.time() - t0)
        times.sort()
        med = times[len(times) // 2]
        lane_iters = sum(int(s.iters) for s in sols)
        detail[f"kernel_screen_{path}_T24_C96_B4"] = {
            "seconds": round(med, 3),
            "lanes": len(caps),
            "lane_iters": lane_iters,
            "iters_per_s": round(lane_iters / max(med, 1e-9)),
            "megakernel": _megakernel_stamp({}, cfg),
        }

    # sampler row: the scan sampler's panels/s band (the Pallas sampler row
    # ended with the kernel — PR 14 verdict, see README "Pallas verdicts")
    thr_dense, _ = featurize(
        random_instance(n=200, k=24, n_categories=4, seed=3)
    )
    band = _sampler_throughput(
        thr_dense, batch=512 if smoke_mode else 4096, reps=max(reps, 3)
    )["scan"]
    detail["kernel_sampler_scan"] = {
        # the trend gate tracks seconds; panels/s is the human-facing band
        "seconds": round(
            (512 if smoke_mode else 4096) / max(band["median"], 1e-9), 4
        ),
        "panels_per_s": band,
    }

    doc = {
        "schema_version": 1,
        "kernels_ok": not failures,
        "seconds": round(time.time() - t_start, 1),
        "backend": __import__("jax").default_backend(),
        "smoke": bool(smoke_mode),
        "iters_per_row": iters,
        "detail": detail,
        "failures": failures,
    }
    print(json.dumps(doc))
    out_path = os.environ.get("BENCH_KERNELS_PATH")
    if out_path:
        try:
            with open(out_path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
        except OSError:
            pass
    return 1 if failures else 0


def churn_bench(smoke_mode: bool = False) -> int:
    """``--churn``: graftdelta incremental re-certification under registry
    churn — a seeded edit trail against one nationwide-scale registry, the
    delta arm running EVERY edit while the from-scratch arm is sampled per
    edit class (a full from-scratch trail would cost hours and prove
    nothing extra). Emits ``BENCH_churn_rNN.json`` in the BENCH_detail row
    schema so ``obs/trend.py`` folds the churn family into the regression
    gate.

    Evidence tiers (see README "Incremental re-certification"):

    * **bench tier** (this function) — type-space certificate only: the
      delta arm's answer is compared against an actual from-scratch
      re-certification on the sampled edits (type-value L∞ ≤ 1e-3, the
      same bound the service audits per agent), and every edit's own
      ``eps_bound`` certificate must stay inside the contract;
    * **service tier** (tests/test_delta.py) — the full Distribution
      round-trip through ``SelectionRequest(revise=…)``.

    Hard assertions (non-zero exit): delta median beats the from-scratch
    median by ≥ 5× (smoke: ≥ 2× — small pools shrink the cache-hit
    envelope), the contract holds on every edit, and the sensitivity cache
    certificate fires at least once.
    """
    import numpy as np

    from citizensassemblies_tpu.data.registry import (
        apply_edit,
        churn_trail,
        nationwide_registry,
    )
    from citizensassemblies_tpu.solvers import delta as graftdelta
    from citizensassemblies_tpu.utils.config import default_config

    t_start = time.time()
    if smoke_mode:
        n, k, n_edits, scratch_reps, speedup_floor = 30_000, 173, 40, 2, 2.0
    else:
        n, k, n_edits, scratch_reps, speedup_floor = 100_000, 316, 1000, 6, 5.0
    cfg = default_config()
    failures = []
    detail = {}

    reg = nationwide_registry(
        n=n,
        k=k,
        seed=16,
        categories=(("region", [f"r{i}" for i in range(8)]),),
        quota_slack=0.003,
    )
    # small per-edit footprints keep the drift bound inside the certificate
    # margin at this pool size — the regime the cache certificate targets.
    # The class mix leans toward agent churn (a registry's daily reality is
    # joins and drops; quota amendments are rarer), new types are capped,
    # and the quota walk carries a slight TIGHTEN bias: every relaxation
    # permanently widens the composition hull, so a non-reverting walk
    # grows the instance itself until it leaves the enumerable tier (a
    # balanced 0.12/0.12 walk blew past enum_cap around edit 700 of a
    # 1000-edit trail). The bias is self-limiting — a tighten whose band
    # edge already sits at the witness count falls through to a relax —
    # so bands hover near their seeded width and the medians describe ONE
    # near-stationary instance, not a drifting family
    edits = churn_trail(
        reg,
        n_edits,
        seed=16,
        max_edit_agents=8,
        max_new_types=2,
        weights={
            "agents_add": 0.36,
            "agents_drop": 0.34,
            "quota_relax": 0.10,
            "quota_tighten": 0.14,
            "new_type": 0.06,
        },
    )

    def scratch(r):
        t0 = time.time()
        st = graftdelta.certify_base(r, cfg=cfg)
        return time.time() - t0, st

    def type_linf(state_a, state_b):
        # match types across the two states by feature key; L∞ over types
        # with live pools equals the per-agent L∞ the service contract uses
        ia = {
            tuple(int(v) for v in row): t
            for t, row in enumerate(state_a.system.type_feature)
        }
        worst = 0.0
        for t_b, row in enumerate(state_b.system.type_feature):
            if state_b.system.msize[t_b] == 0:
                continue
            t_a = ia.get(tuple(int(v) for v in row))
            if t_a is None:
                return float("inf")
            worst = max(
                worst,
                abs(
                    float(state_a.type_values[t_a])
                    - float(state_b.type_values[t_b])
                ),
            )
        return worst

    base_s, state = scratch(reg)
    if state is None:
        print(json.dumps({"churn_ok": False, "error": "base solve failed"}))
        return 1
    detail["churn_base_certify"] = {"seconds": round(base_s, 3)}

    delta_times = []
    per_class: dict = {}
    scratch_times: dict = {}
    modes = {"cache_hit": 0, "resume": 0, "full_ladder": 0, "fallback": 0}
    worst_linf = 0.0
    worst_eps = 0.0
    cur = reg
    for i, edit in enumerate(edits):
        nxt = apply_edit(cur, edit)
        t0 = time.time()
        out = graftdelta.recertify(state, edit, cur, cfg=cfg)
        if out is not None:
            dt = time.time() - t0
            state = out.state
            modes[out.cert["mode"]] += 1
            worst_eps = max(worst_eps, float(out.cert["eps_bound"]))
        else:
            # outside the delta envelope: the honest delta-arm cost of this
            # edit is a fresh base certification
            s_fb, state = scratch(nxt)
            dt = time.time() - t0
            if state is None:
                failures.append(f"edit {i} ({edit.kind}): both arms failed")
                break
            modes["fallback"] += 1
        delta_times.append(dt)
        per_class.setdefault(edit.kind, []).append(dt)
        if len(scratch_times.setdefault(edit.kind, [])) < scratch_reps:
            s_t, s_state = scratch(nxt)
            if s_state is None:
                failures.append(f"edit {i} ({edit.kind}): from-scratch failed")
            else:
                scratch_times[edit.kind].append(s_t)
                linf = type_linf(state, s_state)
                worst_linf = max(worst_linf, linf)
                if linf > 1e-3:
                    failures.append(
                        f"edit {i} ({edit.kind}): delta vs from-scratch "
                        f"type-value L∞ {linf:.2e} > 1e-3"
                    )
        cur = nxt

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else float("nan")

    delta_median = med(delta_times)
    scratch_all = [t for ts in scratch_times.values() for t in ts]
    scratch_median = med(scratch_all)
    speedup = scratch_median / max(delta_median, 1e-9)
    detail["churn_delta_median"] = {
        "seconds": round(delta_median, 4),
        "speedup": round(speedup, 1),
        "edits": len(delta_times),
    }
    detail["churn_scratch_median"] = {
        "seconds": round(scratch_median, 4),
        "samples": len(scratch_all),
    }
    for kind, ts in sorted(per_class.items()):
        detail[f"churn_delta_{kind}"] = {
            "seconds": round(med(ts), 4),
            "edits": len(ts),
            "scratch_median_s": round(med(scratch_times.get(kind, [])), 4),
        }
    if speedup < speedup_floor:
        failures.append(
            f"delta median {delta_median:.3f}s vs from-scratch "
            f"{scratch_median:.3f}s: speedup {speedup:.1f}× < {speedup_floor}×"
        )
    if worst_eps > 1e-3:
        failures.append(
            f"certified eps_bound {worst_eps:.2e} exceeded the 1e-3 contract"
        )
    if modes["cache_hit"] < 1:
        failures.append("the sensitivity cache certificate never fired")

    doc = {
        "schema_version": 1,
        "churn_ok": not failures,
        "seconds": round(time.time() - t_start, 1),
        "backend": __import__("jax").default_backend(),
        "smoke": bool(smoke_mode),
        "n": n,
        "edits": len(delta_times),
        "modes": modes,
        "speedup": round(speedup, 1),
        "worst_linf_vs_scratch": worst_linf,
        "worst_eps_bound": worst_eps,
        "detail": detail,
        "failures": failures,
    }
    print(json.dumps(doc))
    out_path = os.environ.get(
        "BENCH_CHURN_PATH", os.path.join(_artifacts_dir(), "BENCH_churn_r16.json")
    )
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass
    return 1 if failures else 0


def roofline_bench(smoke_mode: bool = False) -> int:
    """``--roofline``: graftscope runtime roofline attribution over the
    full IR-core registry.

    Drives every registered core through its OWN :class:`IRCase` — the
    jitted callable at the exact representative shapes the committed
    ``ANALYSIS_BUDGET.json`` flops/bytes were measured at — under a
    device-sampling tracer, then joins measured dispatch seconds against
    the static budget (``obs/roofline.py``): achieved GFLOP/s and GB/s,
    arithmetic intensity, and a bytes-/compute-bound verdict per core
    against the ``Config.obs_roofline_ridge`` machine balance. Budget
    shapes == executed shapes by construction, so the rates are honest;
    the ``backend`` field records the regime (CPU CI wall times are CPU
    numbers, same posture as the kernel rows).

    ``--roofline --smoke`` asserts the static↔runtime join is TOTAL:
    every fired span joined a budget row (no misses), every budgeted core
    executed, every call was device-sampled, and every row's achieved
    rate is finite. Writes ``ROOFLINE_rNN.json`` (round = 1 past the
    newest committed round; env ``BENCH_ROOFLINE_PATH`` overrides) with a
    ``detail`` block in the BENCH row schema, so ``obs/trend.py`` folds
    the per-core seconds into the regression gate as a new row family.
    """
    import re

    import jax
    import numpy as np

    from citizensassemblies_tpu.lint.registry import collect
    from citizensassemblies_tpu.obs import (
        Tracer,
        dispatch_span,
        roofline_join,
        use_tracer,
    )
    from citizensassemblies_tpu.utils.config import default_config

    t_start = time.time()
    failures = []
    reps = 1 if smoke_mode else 3
    cfg = default_config().replace(obs_trace=True)
    tracer = Tracer(name="roofline", sample_device=True)

    def _concrete(leaf):
        # materialize an IRCase example operand: zeros for integer/bool
        # dtypes (gather/scatter indices stay in range), a deterministic
        # non-constant fill for floats — reruns are bit-stable
        if not isinstance(leaf, jax.ShapeDtypeStruct):
            # some cases register CONCRETE operands (pallas cores whose
            # index structure must be real, not zeros); copy them so a
            # donating call never sees a buffer a previous rep consumed
            if isinstance(leaf, jax.Array):
                return np.array(leaf)
            return leaf
        dt = np.dtype(leaf.dtype)
        if dt.kind in "iub":
            return np.zeros(leaf.shape, dtype=dt)
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        vals = 0.1 + 0.8 * ((np.arange(size) % 97) / 96.0)
        return np.asarray(vals, dtype=dt).reshape(leaf.shape)

    def _materialize(args):
        return jax.tree_util.tree_map(
            _concrete, args,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )

    core_errors = []
    with use_tracer(tracer):
        for entry in collect():
            try:
                case = entry.build()
                # warm the executable OUTSIDE any span — compile time must
                # not pollute the measured dispatch seconds
                out = case.fn(*_materialize(case.args), **case.static)
                jax.block_until_ready(out)
                for _ in range(reps):
                    # fresh operands every call: donating cores consumed
                    # the previous buffers
                    operands = _materialize(case.args)
                    with dispatch_span(entry.name, cfg=cfg) as ds:
                        ds.out = case.fn(*operands, **case.static)
            except Exception as exc:  # noqa: BLE001 - sweep-survivable
                core_errors.append(f"{entry.name}: {exc!r}")
    if core_errors:
        failures.append(f"cores failed to execute: {core_errors[:3]}")

    report = roofline_join([tracer])
    if report.misses:
        failures.append(
            f"roofline join misses (span with no budget row): {report.misses}"
        )
    if report.unexecuted:
        failures.append(f"budgeted cores never fired: {report.unexecuted}")
    bad_rows = [r.core for r in report.rows if not r.finite]
    if bad_rows:
        failures.append(f"non-finite achieved rates: {bad_rows}")
    unsampled = [r.core for r in report.rows if not r.sampled]
    if unsampled:
        failures.append(f"rows timed host enqueue, not execution: {unsampled}")

    # round number: 1 past the newest committed ROOFLINE_r*.json (15 seeds
    # the family), so re-running the bench next PR auto-advances the series
    repo_root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for f in os.listdir(repo_root)
        if (m := re.match(r"ROOFLINE_r(\d+)\.json$", f))
    ]
    rnd = (max(rounds) + 1) if rounds else 15

    doc = {
        "schema_version": 1,
        "roofline_ok": not failures,
        "round": rnd,
        "seconds": round(time.time() - t_start, 1),
        "backend": jax.default_backend(),
        "smoke": bool(smoke_mode),
        "reps_per_core": reps,
        "cores": len(report.rows),
        "bytes_bound": sum(1 for r in report.rows if r.bound == "bytes-bound"),
        "compute_bound": sum(
            1 for r in report.rows if r.bound == "compute-bound"
        ),
        "detail": report.trend_detail(),
        "report": report.as_json(),
        "failures": failures,
    }
    print(json.dumps(doc))
    out_path = os.environ.get("BENCH_ROOFLINE_PATH") or os.path.join(
        _artifacts_dir(), f"ROOFLINE_r{rnd:02d}.json"
    )
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass
    return 1 if failures else 0


#: the committed serving SLO spec the serve bench gates on — p99 under the
#: smoke fleet's worst honest latency with CI headroom, error budget 1 %.
#: README "Memory, roofline & SLOs (graftscope)" documents the grammar.
_SERVE_SLO_SPEC = "latency_p99:30s,error_rate:0.01"


def serve_bench(smoke_mode: bool = False) -> int:
    """graftserve bench: drive a mixed fleet of whole selection instances
    through the async service and measure the SERVING metrics — p50/p99
    request latency, throughput (instances/min), cross-request batch
    occupancy (solves per engine dispatch), warm-rep compile bound — with
    every request's allocation checked against its serial single-instance
    run under the established 1e-3 L∞ contract.

    ``--serve`` runs the full fleet (≥50 mixed-size instances, a new BENCH
    row family); ``--serve --smoke`` is the CI variant: a dozen tiny
    mixed-shape requests, with the invariants ASSERTED (cross-request
    batching occurred, per-request parity vs serial, warm reps
    compile-clean, tenant memo serves a repeat) and a process exit code.
    """
    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService
    from citizensassemblies_tpu.utils.config import default_config
    from citizensassemblies_tpu.utils.guards import CompilationGuard, GuardViolation
    from citizensassemblies_tpu.utils.memo import memo_evictions_by_owner

    t_start = time.time()
    failures = []
    bound = int(os.environ.get("BENCH_COMPILE_BOUND", "8"))
    # the engine is exercised explicitly (CPU CI would auto-route it off);
    # the window is held slightly open so concurrent fleets actually meet.
    # obs_trace=True gives every request its own sampling tracer (the serve
    # trace artifact merges them, one process lane per request), and the
    # smoke's short metrics interval exercises the periodic ("metrics", …)
    # channel snapshots the streaming satellite added.
    # graftscope: obs_memory=True stamps every request audit with its
    # memory-ledger block; obs_slo_spec arms the service SLO engine on the
    # committed spec the smoke gates on below
    cfg = default_config().replace(
        lp_batch=True, serve_batch_window_ms=8.0, serve_admission_cap=8,
        obs_trace=True,
        obs_metrics_interval_s=(0.2 if smoke_mode else 0.0),
        obs_memory=True,
        obs_slo_spec=_SERVE_SLO_SPEC,
    )

    # --- the fleet: mixed-size tenant instances (mass_like_24-class) --------
    n_requests = 12 if smoke_mode else int(os.environ.get("BENCH_SERVE_N", "60"))
    specs = []
    for i in range(n_requests):
        n = 24 + 8 * (i % (3 if smoke_mode else 8))
        k = 4 + (i % 4)
        specs.append(
            (random_instance(n=n, k=k, n_categories=2, seed=i % 7), f"tenant{i % 3}")
        )

    # serial references FIRST (also warms every executable the shapes need,
    # so the serve pass below measures steady-state serving, not compile)
    refs = []
    t_serial0 = time.time()
    for inst, _tenant in specs:
        d, s = featurize(inst)
        refs.append(find_distribution_leximin(d, s, cfg=cfg))
    serial_s = time.time() - t_serial0

    # --- the serve pass ----------------------------------------------------
    svc = SelectionService(cfg)
    lat = []
    t_serve0 = time.time()
    with CompilationGuard(name="serve_fleet") as serve_guard:
        chans = []
        for inst, tenant in specs:
            t_sub = time.time()
            chans.append(
                (t_sub, svc.submit(SelectionRequest(instance=inst, tenant=tenant)))
            )
        results = []
        for t_sub, ch in chans:
            res = ch.result(timeout=600)
            lat.append(time.time() - t_sub)
            results.append(res)
    serve_s = time.time() - t_serve0

    # --- per-request exactness vs the serial reference ---------------------
    worst_dev = 0.0
    for res, ref in zip(results, refs):
        worst_dev = max(worst_dev, float(np.abs(res.allocation - ref.allocation).max()))
    if worst_dev > 1e-3:
        failures.append(f"served allocation deviates {worst_dev:.2e} > 1e-3 vs serial")

    # --- graftscope sojourn decomposition: the parts must explain the whole.
    # Every audit carries queue-wait / prepare / solve / audit components
    # (batch-window wait is a sub-component of solve); the acceptance
    # contract is that they sum to within 5 % of the measured sojourn.
    sojourn_gap_pct = 0.0
    memory_stamps = 0
    for res in results:
        soj = res.audit.get("sojourn")
        if not soj:
            failures.append("a request audit carries no sojourn block")
            break
        parts = (
            soj["queue_wait_s"] + soj["prepare_s"] + soj["solve_s"]
            + soj["audit_s"]
        )
        gap = abs(soj["total_s"] - parts) / max(soj["total_s"], 1e-9)
        sojourn_gap_pct = max(sojourn_gap_pct, 100.0 * gap)
        memory_stamps += 1 if "memory" in res.audit else 0
    if sojourn_gap_pct > 5.0:
        failures.append(
            f"sojourn components explain only {100 - sojourn_gap_pct:.1f}% "
            "of measured request sojourn (gap > 5%)"
        )
    if memory_stamps != len(results):
        failures.append(
            f"only {memory_stamps}/{len(results)} request audits carry the "
            "obs_memory ledger stamp"
        )

    # --- occupancy: cross-request solves per engine dispatch ---------------
    bstats = svc.batcher.stats()
    occupancy = bstats["solves"] / max(bstats["dispatches"], 1)
    if bstats["fused_dispatches"] < 1:
        failures.append("no dispatch fused fleets from ≥2 requests (no cross-request batching)")
    if occupancy <= 1.0 and bstats["dispatches"] > 0:
        failures.append(f"cross-request occupancy {occupancy:.2f} ≤ 1 solve/dispatch")

    # --- warm reps: repeat a slice of the fleet; executables must be hot,
    # and an identical re-submission must be served from the tenant memo ----
    warm_ok = True
    warm_res = []
    try:
        # GuardViolation raises at scope EXIT, so the try wraps the with
        with CompilationGuard(name="serve_warm", max_compiles=bound) as warm_guard:
            # the LAST slice of the fleet: still resident in each tenant's
            # LRU memo (the earliest requests may have been evicted — which
            # the memo_evictions_by_owner field then attributes per tenant)
            warm_res = [
                svc.run(SelectionRequest(instance=inst, tenant=tenant), timeout=600)
                for inst, tenant in specs[-4:]
            ]
    except GuardViolation:
        warm_ok = False
        failures.append(
            f"warm serve reps compiled {warm_guard.count}x > bound {bound}"
        )
    memo_hits = sum(1 for r in warm_res if r.from_memo)
    if warm_ok and memo_hits == 0:
        failures.append("identical re-submission was not served from the tenant memo")

    # --- grafttrace artifacts: merged per-request trace + Prometheus dump --
    from citizensassemblies_tpu.dist.runtime import scoped_artifact_path
    from citizensassemblies_tpu.obs import validate_chrome_trace

    art_dir = _artifacts_dir()
    # fleet-safe artifact paths: suffixed by process index on multi-process
    # runs so concurrent serving children never clobber each other's
    # evidence (a no-op on single-process runs — names stay stable)
    serve_trace_path = scoped_artifact_path(
        os.environ.get(
            "BENCH_SERVE_TRACE_PATH", os.path.join(art_dir, "trace_serve_smoke.json")
        ) if smoke_mode else os.path.join(art_dir, "trace_serve.json")
    )
    serve_doc = svc.export_traces(path=serve_trace_path)
    serve_schema_problems = validate_chrome_trace(serve_doc)
    if serve_schema_problems:
        failures.append(f"serve trace schema invalid: {serve_schema_problems[:3]}")
    prom_text = svc.metrics_text()
    serve_metrics_path = scoped_artifact_path(os.path.join(
        art_dir, "metrics_serve_smoke.prom" if smoke_mode else "metrics_serve.prom"
    ))
    try:
        with open(serve_metrics_path, "w", encoding="utf-8") as fh:
            fh.write(prom_text)
    except OSError:
        serve_metrics_path = "(unwritable)"
    span_total = sum(
        1 for ev in serve_doc["traceEvents"] if ev.get("ph") == "X"
    )
    obs_stamp = {
        "schema_version": serve_doc["schema_version"],
        "span_count": span_total,
        "traced_requests": len(serve_doc["otherData"]["tracers"]),
        "trace_file": os.path.basename(str(serve_trace_path)),
        "metrics_file": os.path.basename(str(serve_metrics_path)),
    }
    if smoke_mode:
        # the streaming-snapshot satellite: at least one channel must have
        # received a periodic ("metrics", …) event during the fleet run
        metrics_events = 0
        for _t_sub, ch in chans:
            metrics_events += sum(
                1 for kind, _p in ch.events(timeout=1) if kind == "metrics"
            )
        obs_stamp["metrics_events"] = metrics_events
        if metrics_events == 0:
            failures.append(
                "no channel received a periodic metrics snapshot "
                "(obs_metrics_interval_s stream inert)"
            )
        if span_total == 0:
            failures.append("serve trace recorded no spans (obs_trace inert)")
        if "graftserve_requests_total" not in prom_text:
            failures.append("prometheus dump missing graftserve_requests_total")

    # --- graftscope SLO engine: committed-spec evaluation + report artifact
    slo_report = svc.slo.evaluate() if svc.slo is not None else None
    if slo_report is None:
        failures.append("SLO engine not armed despite committed obs_slo_spec")
    else:
        if not slo_report["slo_ok"]:
            failures.append(
                f"committed SLO spec violated: {slo_report['breaches']}"
            )
        slo_path = scoped_artifact_path(os.path.join(
            art_dir, "SLO_report_smoke.json" if smoke_mode else "SLO_report.json"
        ))
        try:
            with open(slo_path, "w", encoding="utf-8") as fh:
                json.dump(
                    {"spec": _SERVE_SLO_SPEC, "report": slo_report},
                    fh, indent=1,
                )
                fh.write("\n")
        except OSError:
            slo_path = "(unwritable)"
        obs_stamp["slo_ok"] = slo_report["slo_ok"]
        obs_stamp["slo_events"] = slo_report["events"]
        obs_stamp["slo_file"] = os.path.basename(str(slo_path))
    obs_stamp["sojourn_gap_pct"] = round(sojourn_gap_pct, 2)
    svc.shutdown()

    if smoke_mode:
        # synthetic-breach drill: ``queue_stall:1.0`` stalls every request
        # 0.25 s pre-execution, so a 100 ms p99 objective must breach —
        # asserts the ("slo", …) stream end to end (engine → open channels)
        drill_cfg = cfg.replace(
            fault_sites="queue_stall:1.0", fault_seed=7,
            obs_slo_spec="latency_p99:100ms,error_rate:0.5",
            obs_trace=False, obs_memory=None, obs_metrics_interval_s=0.0,
        )
        drill = SelectionService(drill_cfg)
        drill_chans = [
            drill.submit(SelectionRequest(instance=inst, tenant=tenant))
            for inst, tenant in specs[:3]
        ]
        breach_events = 0
        for ch in drill_chans:
            ch.result(timeout=600)
            breach_events += sum(
                1 for kind, _p in ch.events(timeout=1) if kind == "slo"
            )
        drill.shutdown()
        obs_stamp["slo_breach_events"] = breach_events
        if breach_events < 1:
            failures.append(
                "fault-injected drill streamed no ('slo', …) breach event"
            )

    lat.sort()
    p50 = lat[len(lat) // 2]
    p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
    host_syncs = sum(int(r.audit.get("decomp_host_syncs", 0)) for r in results)
    decomp_rounds = sum(
        int(r.audit.get("counters", {}).get("decomp_rounds", 0)) for r in results
    )
    row = {
        "metric": "graftserve_mixed_fleet",
        "value": round(serve_s, 2),
        "unit": "s",
        "detail": {
            "requests": n_requests,
            "p50_latency_s": round(p50, 3),
            "p99_latency_s": round(p99, 3),
            "throughput_inst_per_min": round(60.0 * n_requests / max(serve_s, 1e-9), 1),
            "serial_reference_s": round(serial_s, 2),
            "speedup_vs_serial": round(serial_s / max(serve_s, 1e-9), 2),
            "worst_alloc_linf_dev": round(worst_dev, 9),
            "cross_request_batcher": bstats,
            "solves_per_dispatch": round(occupancy, 2),
            "decomp_host_syncs_total": host_syncs,
            "decomp_rounds_total": decomp_rounds,
            "decomp_host_syncs_per_round": (
                round(host_syncs / decomp_rounds, 2) if decomp_rounds else None
            ),
            "xla_compiles_serve": serve_guard.count,
            "xla_compiles_warm": warm_guard.count,
            "warm_memo_hits": memo_hits,
            "tenants": svc.tenants.all_stats(),
            "memo_evictions_by_owner": memo_evictions_by_owner(),
            "obs": obs_stamp,
            "failures": failures,
        },
    }
    if smoke_mode:
        row = {
            "serve_smoke_ok": not failures,
            "seconds": round(time.time() - t_start, 1),
            "p50_latency_s": round(p50, 3),
            "solves_per_dispatch": round(occupancy, 2),
            "fused_dispatches": bstats["fused_dispatches"],
            "worst_alloc_linf_dev": round(worst_dev, 9),
            "warm_compiles": warm_guard.count,
            "obs": obs_stamp,
            "failures": failures,
        }
    print(json.dumps(row))
    return 1 if failures else 0


def scenario_bench(smoke_mode: bool = False) -> int:
    """graftscenario bench (``--scenarios``): one row per scenario model.

    * ``scenario_dropout``: solve the SAME heterogeneous-dropout instance
      attendance-aware (``find_distribution_dropout``, "type" replacement)
      and attendance-blind (plain leximin, "naive" re-draw replacement),
      then evaluate BOTH portfolios with the MC dropout-realization kernel
      on the same key stream. The acceptance assertion: the aware portfolio
      beats the naive re-draw baseline on realized-min selection probability
      (minimum covered-agent frequency of a seat on a quota-VALID realized
      panel), with the MC stamp recorded on the row.
    * ``scenario_multi``: R-round multi-assembly scheduling — asserts the
      1e-3 L∞ aggregate contract, zero repeats on drawn schedules, and
      records the pair-equity gauge (max co-selection probability vs the
      uniform pair value).

    ``--scenarios --smoke`` is the CI variant (tiny instances, fewer MC
    draws). Writes the full row set to ``artifacts/SCENARIO_report.json``.
    """
    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.scenarios import (
        find_distribution_dropout,
        find_distribution_multi,
    )
    from citizensassemblies_tpu.scenarios.dropout import evaluate_realization
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
    from citizensassemblies_tpu.utils.config import default_config
    from citizensassemblies_tpu.utils.logging import RunLog

    t_start = time.time()
    failures = []
    draws = 8_192 if smoke_mode else int(os.environ.get("BENCH_MC_DRAWS", "65536"))
    if smoke_mode:
        n, k, n_categories = 24, 5, 2
    else:
        n, k, n_categories = 60, 8, 2
    cfg = default_config().replace(scenario_mc_draws=draws)

    # --- dropout row: aware "type" policy vs blind naive re-draw -----------
    dense, space = featurize(
        random_instance(n=n, k=k, n_categories=n_categories, seed=0)
    )
    drop = np.random.default_rng(0).uniform(0.0, 0.5, size=dense.n)
    t0 = time.time()
    log = RunLog(echo=False)
    aware = find_distribution_dropout(dense, space, dropout=drop, cfg=cfg, log=log)
    dropout_s = time.time() - t0
    if not aware.contract_ok:
        failures.append(
            f"dropout portfolio broke the 1e-3 contract "
            f"(dev {aware.realization_dev:.2e})"
        )
    blind = find_distribution_leximin(dense, space, cfg=cfg)

    class _Blind:
        """The naive re-draw baseline the acceptance row compares against:
        the attendance-blind leximin portfolio, realized under the "naive"
        policy (re-draw replacements uniformly from ALL off-panel agents)."""

        committees = blind.committees
        probabilities = blind.probabilities
        attendance = aware.attendance
        type_id = TypeReduction(dense).type_id
        covered = blind.covered

    ours_mc = evaluate_realization(
        aware, dense, cfg=cfg, draws=draws, policy="type", seed=0
    )
    naive_mc = evaluate_realization(
        _Blind(), dense, cfg=cfg, draws=draws, policy="naive", seed=0
    )
    if not ours_mc["realized_min"] > naive_mc["realized_min"]:
        failures.append(
            f"dropout-aware portfolio did not beat the naive re-draw "
            f"baseline on realized-min ({ours_mc['realized_min']:.4f} vs "
            f"{naive_mc['realized_min']:.4f})"
        )
    dropout_row = {
        "metric": "scenario_dropout",
        "value": round(ours_mc["realized_min"], 6),
        "unit": "realized_min_prob",
        "detail": {
            "n": dense.n,
            "k": dense.k,
            "seconds": round(dropout_s, 2),
            "buckets": aware.scenario_audit.get("buckets"),
            "product_types": aware.scenario_audit.get("types"),
            "fallback": aware.scenario_audit.get("fallback"),
            "certified_min_realized": aware.scenario_audit.get(
                "certified_min_realized"
            ),
            "realization_dev": round(aware.realization_dev, 9),
            "mc_aware_type": ours_mc,
            "mc_blind_naive": naive_mc,
            "beats_naive_redraw": ours_mc["realized_min"]
            > naive_mc["realized_min"],
        },
    }

    # --- multi row: R-round scheduling + pair-equity gauge -----------------
    # lp_batch=True so the row exercises the R-fold fleet through the
    # batched engine (the host per-round path is the gate-off fallback)
    R = 3
    t0 = time.time()
    multi = find_distribution_multi(
        dense, space, rounds=R, cfg=cfg.replace(lp_batch=True)
    )
    multi_s = time.time() - t0
    if not multi.contract_ok:
        failures.append(
            f"multi aggregate allocation broke the 1e-3 contract "
            f"(dev {multi.realization_dev:.2e})"
        )
    repeat_free = True
    for seed in range(4):
        sched = multi.realize(seed=seed)
        if len(np.unique(sched.ravel())) != R * dense.k:
            repeat_free = False
            failures.append(f"multi schedule (seed {seed}) seats an agent twice")
    multi_row = {
        "metric": "scenario_multi",
        "value": round(multi.pair_ratio, 4),
        "unit": "pair_ratio_vs_uniform",
        "detail": {
            "n": dense.n,
            "k": dense.k,
            "rounds": R,
            "seconds": round(multi_s, 2),
            "fleet_backend": multi.scenario_audit.get("fleet_backend"),
            "round_eps_max": multi.scenario_audit.get("round_eps_max"),
            "pair_max": round(multi.pair_max, 6),
            "pair_uniform": round(multi.pair_uniform, 6),
            "certified_min_aggregate": multi.scenario_audit.get(
                "certified_min_aggregate"
            ),
            "realization_dev": round(multi.realization_dev, 9),
            "zero_repeats": repeat_free,
        },
    }

    report = {
        "scenario_ok": not failures,
        "seconds": round(time.time() - t_start, 1),
        "mc_draws": draws,
        "rows": [dropout_row, multi_row],
        "failures": failures,
    }
    out_path = os.environ.get(
        "BENCH_SCENARIO_REPORT",
        os.path.join(_artifacts_dir(), "SCENARIO_report.json"),
    )
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
    except OSError:
        pass
    print(json.dumps(report))
    return 1 if failures else 0


def chaos_bench(smoke_mode: bool = False) -> int:
    """graftfault chaos bench: hammer the service with a FIXED, seeded fault
    mix (``Config.fault_sites`` + ``fault_seed`` — the schedule is
    crc-deterministic, so every run of this mode injects the identical
    faults) and assert the hardening contract:

    * every COMPLETED request still passes the 1e-3 L∞ exactness audit
      (``contract_ok`` / ``realization_dev`` — degraded, retried and resumed
      paths are certified by the same arithmetic check as the fast path);
    * every injected fault class fired at least once AND shows up in the
      recovery counters (quarantine / host re-solve / retry / degrade /
      oracle-skip / leader-reclaim / resume);
    * no request hangs past its deadline (every channel reaches a terminal
      event within deadline + margin; a DeadlineExceeded rejection is a
      VALID outcome — a hang or an unexplained failure is not).

    Writes the full evidence to ``CHAOS_report.json`` (the CI ``chaos`` job
    uploads it). ``--chaos --smoke`` is the CI variant (small fleet); plain
    ``--chaos`` scales the fleet via ``BENCH_CHAOS_N``.
    """
    import tempfile

    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance, skewed_instance
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService
    from citizensassemblies_tpu.utils.config import default_config

    t_start = time.time()
    failures = []
    deadline_s = float(os.environ.get("BENCH_CHAOS_DEADLINE_S", "240"))
    ckpt_dir = tempfile.mkdtemp(prefix="graftfault_ckpt_")
    #: the fixed seeded SERVICE mix — the fault classes whose hot boundary
    #: lives in the serving path; rates are tuned so every class fires
    #: within the smoke fleet under fault_seed=7 (the schedule is
    #: crc-deterministic: this is a pinned schedule, not luck). The solver-
    #: boundary classes (oracle_raise, face_abort, warm_slot_corrupt,
    #: qp_nan) are driven through their real entry points by the OFFLINE
    #: passes below — under the service's production-seeded fleet the face
    #: loop certifies at round 0 (the aimed-slice seed is that strong), so
    #: they would not fire here at all
    fault_mix = os.environ.get(
        "BENCH_CHAOS_MIX",
        "pdhg_nan:0.5,worker_crash:0.25,batcher_leader_death:0.2,"
        "queue_stall:0.4",
    )
    cfg = default_config().replace(
        lp_batch=True,
        serve_batch_window_ms=8.0,
        serve_admission_cap=4,
        fault_sites=fault_mix,
        fault_seed=7,
        serve_deadline_s=deadline_s,
        serve_retry_max=3,
        serve_retry_backoff_s=0.02,
        robust_checkpoint_every=1,
        robust_checkpoint_dir=ckpt_dir,
    )

    # the fleet: mostly tiny mixed-shape requests (they exercise the batched
    # engine + batcher + retry paths) plus face-loop instances (they exercise
    # the anchor oracle, the per-round deadline gate and checkpoint/resume)
    n_requests = 10 if smoke_mode else int(os.environ.get("BENCH_CHAOS_N", "24"))
    specs = []
    for i in range(n_requests):
        if i % 5 == 4:
            inst = skewed_instance(n=120, k=12, n_categories=3, seed=i % 3)
        else:
            inst = random_instance(
                n=24 + 8 * (i % 3), k=4 + (i % 4), n_categories=2, seed=i % 7
            )
        specs.append((inst, f"tenant{i % 3}"))

    svc = SelectionService(cfg)
    chans = [
        svc.submit(SelectionRequest(instance=inst, tenant=tenant))
        for inst, tenant in specs
    ]
    results, rejections, errors, hangs = [], [], [], []
    for i, ch in enumerate(chans):
        try:
            # the no-hang assertion: a terminal event MUST arrive within the
            # deadline plus scheduling margin
            results.append((i, ch.result(timeout=deadline_s + 120)))
        except TimeoutError:
            hangs.append(i)
        except RuntimeError as exc:
            if "DeadlineExceeded" in str(exc):
                rejections.append((i, str(exc)[:200]))
            else:
                errors.append((i, str(exc)[:200]))
    svc.shutdown()
    if hangs:
        failures.append(f"requests hung past their deadline: {hangs}")

    # --- exactness: every completed request under the 1e-3 L∞ contract -----
    worst_dev = 0.0
    for i, res in results:
        dev = float(res.audit.get("realization_dev", 0.0))
        worst_dev = max(worst_dev, dev)
        if not res.audit.get("contract_ok", True) or dev > 1e-3:
            failures.append(
                f"request {i} survived chaos but broke the contract "
                f"(realization_dev={dev:.2e})"
            )

    # --- every injected fault class fired, and its recovery registered -----
    fired = {}
    counters = {}
    for _i, res in results:
        for site, n in res.audit.get("faults", {}).get("fired", {}).items():
            fired[site] = fired.get(site, 0) + n
        for name, n in res.audit.get("counters", {}).items():
            if isinstance(n, (int, float)):
                counters[name] = counters.get(name, 0) + n
    bstats = svc.batcher.stats()

    def recovered(*names) -> bool:
        return any(counters.get(n, 0) > 0 for n in names)

    recovery_of = {
        "pdhg_nan": lambda: recovered(
            "sentinel_quarantined", "sentinel_host_resolve", "robust_host_resolve"
        ),
        "worker_crash": lambda: recovered("robust_retry"),
        "batcher_leader_death": lambda: (
            recovered("robust_retry", "batcher_leader_reclaim")
            or bstats.get("leader_reclaims", 0) > 0
        ),
        "queue_stall": lambda: (
            len(results) + len(rejections) + len(errors) == n_requests
        ),
    }
    mix_sites = [part.split(":")[0].strip() for part in fault_mix.split(",") if part]
    for site in mix_sites:
        if fired.get(site, 0) < 1:
            failures.append(f"fault class '{site}' never fired under the mix")
        elif not recovery_of.get(site, lambda: True)():
            failures.append(
                f"fault class '{site}' fired {fired[site]}x but no recovery "
                "counter registered"
            )

    # --- offline solver-boundary chaos: the fault classes whose boundary
    # the service-seeded fleet cannot reach (round-0 certification), each
    # driven through its REAL entry point with the process-default injector
    from citizensassemblies_tpu.robust.inject import FaultInjector, use_injector
    from citizensassemblies_tpu.utils.logging import RunLog

    offline = {}

    def offline_pass(name, spec, seed, fn):
        """Run one offline chaos exerciser under its own injector; a clean
        twin must agree within the contract; fired/recovery evidence is
        collected like the fleet's."""
        olog = RunLog(echo=False)
        inj = FaultInjector(spec, seed=seed)
        try:
            with use_injector(inj):
                ok, note = fn(olog)
        except Exception as exc:  # an unabsorbed fault IS a failure
            ok, note = False, f"{type(exc).__name__}: {exc}"
        stats = inj.stats()
        offline[name] = {
            "spec": spec,
            "fired": stats["fired"],
            "counters": {
                k: v for k, v in sorted(olog.counters.items())
                if k.startswith(("sentinel_", "robust_", "fault_", "deadline_"))
            },
            "ok": ok,
            "note": note,
        }
        for site, n in stats["fired"].items():
            fired[site] = fired.get(site, 0) + n
        if not ok:
            failures.append(f"offline chaos pass '{name}': {note}")
        return olog, stats

    # (a) face loop under oracle failures + mid-round kills, with
    # checkpoints: weak seeds force multi-round CG so the anchor oracle
    # actually prices; the aborted run must RESUME and still certify
    def face_pass(olog):
        from citizensassemblies_tpu.core.instance import featurize as _feat
        from citizensassemblies_tpu.robust.inject import FaultInjected
        from citizensassemblies_tpu.solvers.cg_typespace import (
            CompositionOracle,
            _leximin_relaxation,
            _slice_relaxation,
        )
        from citizensassemblies_tpu.solvers.face_decompose import realize_profile
        from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

        dense, _s = _feat(skewed_instance(n=120, k=12, n_categories=3, seed=1))
        red = TypeReduction(dense)
        v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
        seeds = _slice_relaxation(
            v_relax * red.msize.astype(np.float64), red, R=4
        )
        face_cfg = default_config().replace(
            robust_checkpoint_every=1, robust_checkpoint_dir=ckpt_dir
        )
        eps = None
        for _attempt in range(6):  # aborted runs resume from the checkpoint
            try:
                _C, _p, eps, _n = realize_profile(
                    red, v_relax, list(seeds), CompositionOracle(red),
                    accept=5e-4, log=olog, max_rounds=8, use_pdhg=False,
                    cfg=face_cfg,
                )
                break
            except FaultInjected:
                continue
        if eps is None:
            return False, "face loop never completed within 6 resume attempts"
        if eps > 8e-4:
            return False, f"resumed face loop missed the band (eps {eps:.2e})"
        if not olog.counters.get("fault_face_abort", 0):
            return False, "face_abort never fired (pinned schedule drifted)"
        if not olog.counters.get("robust_resume", 0):
            return False, "face_abort fired but no checkpoint resume happened"
        return True, f"eps {eps:.2e}"

    # seed 8 pins: abort at round 1 of attempt 1 (after the round-0
    # checkpoint) and again on attempt 2, so the resume path genuinely runs
    offline_pass(
        "face_oracle_abort", "oracle_raise:0.5,face_abort:0.3", 8, face_pass
    )

    # (b) warm-slot corruption on the batched engine's REAL reuse path: a
    # repeat caller's second fleet loads (corrupted) slots — the lane
    # sentinel must quarantine and the host re-solve must match the clean
    # twin within the f32↔f64 band
    def warm_pass(olog):
        from citizensassemblies_tpu.solvers.batch_lp import (
            final_primal_batch_lp,
            solve_lp_batch,
        )

        rng = np.random.default_rng(5)
        insts, probs = [], []
        for _ in range(3):
            P = (rng.random((16, 8)) < 0.5).astype(np.float64)
            q = rng.random(16)
            q /= q.sum()
            target = P.T @ q
            probs.append((P, target))
            insts.append(final_primal_batch_lp(P, target))
        wcfg = default_config().replace(lp_batch=True)
        solve_lp_batch(  # warms the slots
            insts, cfg=wcfg, log=olog, warm_key="chaos_warm",
            max_iters=20_000, defer=False,
        )
        got = solve_lp_batch(  # loads (and corrupts) the slots
            insts, cfg=wcfg, log=olog, warm_key="chaos_warm",
            max_iters=20_000, defer=False,
        )
        if not all(np.all(np.isfinite(g.x)) for g in got):
            return False, "corrupt warm slot leaked NaN through the fleet"
        # every quarantined re-solve must still COVER its target (the ε-LP
        # is one-sided — overshoot is free, SHORTFALL is the ε being
        # minimized, and a feasible mixture with ε = 0 exists by
        # construction); iterate equality is not the contract (the optimal
        # face is non-unique)
        worst = max(
            float(np.maximum(target - P.T @ g.x[: P.shape[0]], 0.0).max())
            for g, (P, target) in zip(got, probs)
        )
        if worst > 1e-3:
            return False, f"quarantined re-solve shortfall {worst:.2e}"
        return True, f"worst shortfall {worst:.2e}"

    offline_pass("warm_slot", "warm_slot_corrupt:1.0", 13, warm_pass)

    # (c) the fused L2 stage under a poisoned donor: the QP sentinel must
    # quarantine and the serial float64-validated path must recover
    def qp_pass(olog):
        from citizensassemblies_tpu.service.context import (
            RequestContext,
            use_context,
        )
        from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2

        rng = np.random.default_rng(9)
        P = (rng.random((24, 12)) < 0.4).astype(np.float64)
        P[0] = 1.0  # no all-zero agents
        q = rng.random(24)
        q /= q.sum()
        target = P.T @ q
        # a slightly-off donor so the fused anchor actually runs (an exact
        # donor's deviation is 0 and skips the device stage entirely)
        donor = q + 0.02 * rng.random(24)
        donor /= donor.sum()
        qcfg = default_config().replace(lp_batch=True)
        qctx = RequestContext.create(cfg=qcfg, log=olog)
        with use_context(qctx):
            p_out, eps_out = solve_final_primal_l2(
                P, target, floor_donor=donor, cfg=qcfg, log=olog,
                anchor_if_above=0.0,
            )
        alloc_dev = float(np.abs(P.T @ p_out - target).max())
        if not np.all(np.isfinite(p_out)):
            return False, "poisoned donor leaked NaN out of the L2 stage"
        if alloc_dev > max(2.0 * eps_out, 1e-3):
            return False, f"L2 allocation off its own eps ({alloc_dev:.2e})"
        return True, f"alloc dev {alloc_dev:.2e} (eps {eps_out:.2e})"

    offline_pass("qp_donor", "qp_nan:1.0", 17, qp_pass)

    # (d) scenario entry point under an EXPIRED deadline: the dropout model
    # must reject gracefully (DeadlineExceeded with the trip counted), not
    # hang or return an uncertified portfolio
    def scenario_deadline_pass(olog):
        from citizensassemblies_tpu.core.instance import featurize as _feat
        from citizensassemblies_tpu.robust.policy import Deadline, DeadlineExceeded
        from citizensassemblies_tpu.scenarios import find_distribution_dropout
        from citizensassemblies_tpu.service.context import RequestContext

        dense, space = _feat(
            random_instance(n=24, k=5, n_categories=2, seed=0)
        )
        drop = np.random.default_rng(0).uniform(0.0, 0.5, size=dense.n)
        dctx = RequestContext.create(
            cfg=default_config(), log=olog, deadline=Deadline(0.0)
        )
        try:
            find_distribution_dropout(
                dense, space, dropout=drop, log=olog, ctx=dctx
            )
        except DeadlineExceeded as exc:
            if not olog.counters.get("deadline_exceeded", 0):
                return False, "rejection raised but the trip was not counted"
            return True, f"graceful rejection: {str(exc)[:80]}"
        return False, "expired deadline was ignored by the dropout model"

    offline_pass("scenario_deadline", "", 0, scenario_deadline_pass)

    # (e) the multi-assembly R-fold fleet under lane NaN poisoning: the
    # batched-LP sentinel must quarantine + host re-solve, and the schedule
    # must still come out contract-clean with zero repeats
    def scenario_fleet_pass(olog):
        from citizensassemblies_tpu.core.instance import featurize as _feat
        from citizensassemblies_tpu.scenarios import find_distribution_multi

        dense, space = _feat(
            random_instance(n=24, k=5, n_categories=2, seed=0)
        )
        mcfg = default_config().replace(lp_batch=True, scenario_rounds=2)
        multi = find_distribution_multi(dense, space, rounds=2, cfg=mcfg, log=olog)
        if not multi.contract_ok or multi.realization_dev > 1e-3:
            return False, (
                f"poisoned fleet broke the contract "
                f"(dev {multi.realization_dev:.2e})"
            )
        sched = multi.realize(seed=0)
        if len(np.unique(sched.ravel())) != 2 * dense.k:
            return False, "poisoned fleet produced a schedule with repeats"
        if not (
            olog.counters.get("sentinel_quarantined", 0)
            or olog.counters.get("sentinel_host_resolve", 0)
            or olog.counters.get("robust_host_resolve", 0)
        ):
            return False, "pdhg_nan fired but no sentinel recovery registered"
        return True, (
            f"dev {multi.realization_dev:.2e}, "
            f"backend {multi.scenario_audit.get('fleet_backend')}"
        )

    offline_pass("scenario_fleet_sentinel", "pdhg_nan:1.0", 21, scenario_fleet_pass)

    report = {
        "chaos_ok": not failures,
        "seconds": round(time.time() - t_start, 1),
        "requests": n_requests,
        "completed": len(results),
        "deadline_rejections": len(rejections),
        "failed": len(errors),
        "hung": len(hangs),
        "worst_realization_dev": round(worst_dev, 9),
        "fault_mix": fault_mix,
        "fault_seed": 7,
        "fired": fired,
        "recovery_counters": {
            k: v
            for k, v in sorted(counters.items())
            if k.startswith(("sentinel_", "robust_", "fault_", "deadline_",
                             "batcher_leader_"))
        },
        "batcher": bstats,
        "offline": offline,
        "errors": errors,
        "failures": failures,
    }
    out_path = os.environ.get(
        "BENCH_CHAOS_REPORT", os.path.join(_artifacts_dir(), "CHAOS_report.json")
    )
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
    except OSError:
        pass
    print(json.dumps(report))
    return 1 if failures else 0


def trend() -> int:
    """``bench.py --trend``: the regression gate over the committed BENCH
    trajectory (``obs/trend.py``). Prints one JSON line (per-row deltas,
    statuses, failures) and exits non-zero on any row whose latest value
    regressed beyond ``Config.obs_trend_tol`` × its best earlier round —
    the CI job that turns the BENCH_*.json series into an enforced budget.

    Stdlib-only on purpose (no jax import), so the CI gate job needs no
    accelerator stack — same posture as graftlint.
    """
    from citizensassemblies_tpu.obs.trend import trend_gate

    root = os.environ.get(
        "BENCH_TREND_ROOT", os.path.dirname(os.path.abspath(__file__))
    )
    report = trend_gate(root)
    print(json.dumps(report.as_json()))
    return 0 if report.ok else 1


def _dist_scope_caches() -> None:
    """Reset the platform + scope the XLA persistent compilation cache to
    THIS run (the ``__graft_entry__.dryrun_multichip`` recipe): the package
    points the cache at a shared ~/.cache directory, and a forced-device
    run then tries to load AOT artifacts persisted by other
    machines/topologies — every miss is a ``cpu_aot_loader``
    machine-mismatch warning that buries the report line the artifact tail
    exists to show. The parent asserts the tail is clean."""
    import tempfile

    import jax

    want = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in want.split(","):
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            tempfile.mkdtemp(prefix="citizens_dist_xla_"),
        )
    except Exception:
        pass


def dist_bench_child(smoke_mode: bool) -> int:
    """``bench.py --dist`` (child, forced-device process): the graftpod
    weak-scaling row family — MEASURED, not dryrun.

    Per mesh size (1/2/4/8 virtual devices, capped at what XLA exposes):
    MC panels/s through the production ``distributed_sample_panels`` path at
    a fixed per-device batch (weak scaling: total work grows with the mesh),
    and sharded dual-LP wall-clock over the registry portfolio. Every size
    enforces the exactness contract — panels bit-identical to the
    undistributed kernel (the 1-device case pins the undistributed path
    itself), allocation L∞ ≤ 1e-3 vs the host reference, dual objective
    within 1e-3 of the exact HiGHS LP — and the steady-state repeat round
    must add ZERO ``dist_reshards`` (declared-once shardings hand off
    without re-layout). The honest-hardware rule: the ≥ 4× 1→8 gate is
    enforced only when the host has at least as many cores as devices;
    virtual devices multiplexed onto fewer cores measure dispatch overhead,
    not parallelism, and the artifact records the waiver instead of a fake
    ratio.
    """
    _dist_scope_caches()

    import jax
    import numpy as np

    from citizensassemblies_tpu.data import nationwide_registry
    from citizensassemblies_tpu.dist import partition as dist_partition
    from citizensassemblies_tpu.dist import runtime as dist_runtime
    from citizensassemblies_tpu.models.legacy import _sample_panels_kernel
    from citizensassemblies_tpu.parallel.mc import (
        distributed_allocation,
        distributed_sample_panels,
    )
    from citizensassemblies_tpu.parallel.mesh import make_mesh
    from citizensassemblies_tpu.parallel.solver import solve_dual_lp_pdhg_sharded
    from citizensassemblies_tpu.solvers.highs_backend import solve_dual_lp
    from citizensassemblies_tpu.utils.logging import RunLog

    n_visible = len(jax.devices())
    sizes = [s for s in (1, 2, 4, 8) if s <= n_visible]
    host_cores = os.cpu_count() or 1
    if smoke_mode:
        n, per_dev_b, lp_rows, reps = 800, 32, 512, 1
    else:
        # sized so the full family (4 mesh sizes × warm+reps, MC + sharded
        # dual LP + exact HiGHS reference) fits a small CI host; the
        # registry generator itself scales to n = 10⁶ when hardware does
        n, per_dev_b, lp_rows, reps = 2000, 48, 768, 2

    reg = nationwide_registry(n=n, seed=0)
    dense, _space = reg.to_dense()
    key = jax.random.PRNGKey(0)
    log = RunLog(echo=False)
    failures: list = []

    mc_rows = []
    for nd in sizes:
        mesh = make_mesh(nd)
        B = nd * per_dev_b
        # reference: the undistributed scan kernel at the same total batch
        ref_p, ref_ok = _sample_panels_kernel(dense, key, B)
        ref_p = np.asarray(ref_p)
        ref_ok = np.asarray(ref_ok)
        # warm-up compiles + steady-state reshard audit: the repeat round
        # must be pure pass-through placement
        distributed_sample_panels(dense, key, B, mesh, log=log)
        before = dist_partition.reshard_count(log)
        p, ok = distributed_sample_panels(dense, key, B, mesh, log=log)
        jax.block_until_ready((p, ok))
        steady_reshards = dist_partition.reshard_count(log) - before
        bit_identical = np.array_equal(np.asarray(p), ref_p) and np.array_equal(
            np.asarray(ok), ref_ok
        )
        t0 = time.time()
        for _ in range(reps):
            p, ok = distributed_sample_panels(dense, key, B, mesh, log=log)
            jax.block_until_ready((p, ok))
        dt = time.time() - t0
        row = {
            "devices": nd,
            "batch": B,
            "panels_per_s": round(reps * B / max(dt, 1e-9), 1),
            "bit_identical": bool(bit_identical),
            "steady_reshards": int(steady_reshards),
        }
        mc_rows.append(row)
        if not bit_identical:
            failures.append(f"mc bit-identity broke at {nd} devices")
        if steady_reshards:
            failures.append(
                f"{steady_reshards} steady-state reshard(s) at {nd} devices"
            )

    # sharded dual-LP throughput + exactness vs the host LP, per mesh size
    from citizensassemblies_tpu.models.legacy import sample_feasible_panels

    dual_panels, _draws = sample_feasible_panels(
        dense, lp_rows, seed=2, distribute=False
    )
    P_dual = np.zeros((lp_rows, dense.n), dtype=bool)
    for r, prow in enumerate(dual_panels):
        P_dual[r, prow] = True
    fixed = np.full(dense.n, -1.0)
    exact = solve_dual_lp(P_dual, fixed)
    lp_rows_out = []
    for nd in sizes:
        mesh = make_mesh(nd)
        sharded = solve_dual_lp_pdhg_sharded(P_dual, fixed, mesh)  # warm-up
        t0 = time.time()
        for _ in range(reps):
            sharded = solve_dual_lp_pdhg_sharded(P_dual, fixed, mesh)
        dt = time.time() - t0
        obj_gap = abs(float(sharded.objective) - float(exact.objective))
        row = {
            "devices": nd,
            "portfolio_rows": lp_rows,
            "solves_per_s": round(reps / max(dt, 1e-9), 3),
            "objective_gap": round(obj_gap, 8),
            "converged": bool(sharded.ok),
        }
        lp_rows_out.append(row)
        if not sharded.ok:
            failures.append(f"sharded dual LP did not converge at {nd} devices")
        if obj_gap > 1e-3:
            failures.append(
                f"dual objective gap {obj_gap:.2e} > 1e-3 at {nd} devices"
            )

    # allocation L∞ contract: the sharded portfolio matvec vs host numpy
    probs = np.full(lp_rows, 1.0 / lp_rows, dtype=np.float32)
    host_alloc = P_dual.astype(np.float32).T @ probs
    alloc_linf = []
    for nd in sizes:
        mesh = make_mesh(nd)
        alloc = np.asarray(
            distributed_allocation(
                P_dual.astype(np.float32), probs, mesh, log=log
            )
        )
        linf = float(np.max(np.abs(alloc - host_alloc)))
        alloc_linf.append({"devices": nd, "linf": round(linf, 8)})
        if linf > 1e-3:
            failures.append(f"allocation L∞ {linf:.2e} > 1e-3 at {nd} devices")

    # honest weak-scaling verdict: ratio is measured either way; the ≥ 4×
    # gate binds only when the host can actually run the devices in parallel
    r1 = next((r["panels_per_s"] for r in mc_rows if r["devices"] == 1), None)
    r8 = next((r["panels_per_s"] for r in mc_rows if r["devices"] == sizes[-1]), None)
    ratio = round(r8 / r1, 3) if r1 and r8 else None
    gate_enforced = host_cores >= sizes[-1] and not smoke_mode
    waiver = None
    if not gate_enforced:
        waiver = (
            f"host_cores={host_cores} < devices={sizes[-1]}: forced virtual "
            "devices multiplex onto the same core(s), so throughput measures "
            "dispatch overhead, not parallel speedup — the >=4x gate needs "
            "real parallel hardware"
            if host_cores < sizes[-1]
            else "smoke mode: timing too short to gate on"
        )
    elif ratio is not None and ratio < 4.0:
        failures.append(
            f"weak-scaling 1->{sizes[-1]} ratio {ratio} < 4.0 with "
            f"{host_cores} host cores available"
        )

    report = {
        "metric": "dist_weak_scaling",
        "dryrun": False,
        "smoke": smoke_mode,
        "host_cores": host_cores,
        "visible_devices": n_visible,
        "mesh_sizes": sizes,
        "registry": {"n": reg.n, "k": reg.k, "households": reg.n_households},
        "mc": mc_rows,
        "dual_lp": lp_rows_out,
        "allocation_linf": alloc_linf,
        "scaling": {
            "mc_ratio_1_to_max": ratio,
            "gate_enforced": gate_enforced,
            "waiver": waiver,
        },
        "dist_reshards_total": dist_partition.reshard_count(log),
        "mesh_gauges": {
            k: v for k, v in sorted(log.counters.items())
            if k.startswith("dist_")
        },
        "failures": failures,
    }
    print(json.dumps(report))
    return 1 if failures else 0


def dist_bench(smoke_mode: bool) -> int:
    """``bench.py --dist`` (parent): re-exec the child under forced host
    devices, assert its output tail is clean of ``cpu_aot_loader``
    machine-mismatch spam (the scoped-cache contract), and commit the
    measured report to ``artifacts/MULTICHIP_weak_scaling.json`` — the
    honest replacement for the dryrun MULTICHIP_r0x artifact family."""
    import subprocess

    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["BENCH_DIST_CHILD"] = "1"
    cmd = [sys.executable, os.path.abspath(__file__), "--dist"]
    if smoke_mode:
        cmd.append("--smoke")
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=3600
    )
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)

    # satellite contract: the run tail shows the report, not AOT-cache spam
    tail = "\n".join((proc.stdout + "\n" + proc.stderr).splitlines()[-25:])
    for marker in ("cpu_aot_loader", "machine mismatch"):
        if marker in tail:
            print(f"dist bench FAILED: '{marker}' spam in the run tail")
            return 1

    report = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                report = json.loads(line)
                break
            except ValueError:
                continue
    if report is None:
        print("dist bench FAILED: no report line from the child")
        return 1
    out_path = os.path.join(_artifacts_dir(), "MULTICHIP_weak_scaling.json")
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass
    return proc.returncode


def coldboot_bench_child(variant: str, smoke_mode: bool) -> int:
    """``bench.py --coldboot`` (child, FRESH interpreter): one boot-to-
    first-certified-result run under the graftboot readiness contract.

    Both variants execute the IDENTICAL sequence — construct a
    ``SelectionService`` (which boots the AOT store), warm the flagship
    request class's featurization shapes, replay the predicted bucket
    lattice (``aot.build.bucket_lattice_workload`` — the same function the
    cache was built from), then serve one flagship request under a
    :class:`CompilationGuard`. The only difference is ``Config.aot_cache``:
    ``cached`` deserializes every lattice executable, ``uncached`` pays each
    bucket's full XLA compile. The cached variant GATES zero compiles
    inside the serve window; both report an allocation checksum so the
    parent can pin bit-identity between the two paths.
    """
    t0 = time.perf_counter()
    import hashlib

    import numpy as np

    from citizensassemblies_tpu.aot.build import (
        bucket_lattice_workload,
        coldboot_config,
        flagship_instance,
    )
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService
    from citizensassemblies_tpu.utils.guards import CompilationGuard

    profile = "smoke" if smoke_mode else "service"
    cfg = coldboot_config().replace(
        aot_cache=(variant == "cached"),
        aot_cache_path=os.environ.get("BENCH_COLDBOOT_CACHE", ""),
        aot_prewarm=False,  # symmetric children: no off-thread warm racing
    )
    t_import = time.perf_counter()

    svc = SelectionService(cfg)  # boots (or skips) the AOT store
    t_boot = time.perf_counter()

    # readiness contract: warm the flagship request CLASS's featurization
    # (same shapes, different seed — first-touch eager converts) and the
    # predicted bucket lattice, then serve
    featurize(flagship_instance(seed=1))
    lattice = bucket_lattice_workload(cfg, profile)
    t_warm = time.perf_counter()

    with CompilationGuard(name="coldboot_serve") as guard:
        res = svc.run(
            SelectionRequest(instance=flagship_instance(), tenant="coldboot"),
            timeout=1200,
        )
    t_serve = time.perf_counter()

    alloc = np.asarray(res.allocation, dtype=np.float64)
    checksum = hashlib.sha256(np.round(alloc, 9).tobytes()).hexdigest()[:16]
    certified = bool(res.audit.get("contract_ok", True))
    store = svc.aot_store
    report = {
        "variant": variant,
        "import_s": round(t_import - t0, 3),
        "boot_s": round(t_boot - t_import, 3),
        "warm_s": round(t_warm - t_boot, 3),
        "serve_s": round(t_serve - t_warm, 3),
        "total_s": round(t_serve - t0, 3),
        "lattice_buckets": lattice["buckets"],
        "serve_compiles": int(guard.count),
        "compiles_by_core": dict(guard.by_name),
        "certified": certified,
        "alloc_checksum": checksum,
        "aot": store.stamp() if store is not None else None,
    }
    failures = []
    if not certified:
        failures.append("flagship request served without a certificate")
    if variant == "cached" and guard.count != 0:
        failures.append(
            f"cached coldboot serve window saw {guard.count} XLA "
            f"compilations (by core: {guard.by_name}) — the gate is 0"
        )
    report["failures"] = failures
    print(json.dumps(report))
    return 1 if failures else 0


def coldboot_bench(smoke_mode: bool) -> int:
    """``bench.py --coldboot`` (parent): the graftboot evidence harness.

    Builds the cache artifact once (``python -m citizensassemblies_tpu.aot
    build``), then forks TWO fresh interpreters through the identical
    readiness contract — cached (``aot_cache=True``) and uncached
    (``aot_cache=False``) — and measures each child's spawn-to-exit wall
    clock: the honest cold-boot-to-first-certified-result number, python
    and jax imports included. Gates: the cached child serves its flagship
    request with ZERO XLA compilations (enforced in the child), both
    children produce bit-identical allocations (``aot_cache=False`` is the
    plain-jit path by construction), and — full mode — the cached boot is
    ≥ 3× faster. Writes ``artifacts/BENCH_coldboot_smoke.json`` (smoke) or
    ``artifacts/BENCH_coldboot_r18.json`` with ``coldboot_cached`` /
    ``coldboot_uncached`` detail rows for the obs/trend.py family loader.
    """
    import subprocess
    import tempfile

    profile = "smoke" if smoke_mode else "service"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # XLA:CPU thunk-runtime executables do not survive cross-process
    # deserialization ("Symbols not found") — build AND load legacy (the
    # runtime choice is part of the artifact fingerprint, store.py)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_cpu_use_thunk_runtime=false"
    ).strip()
    # the package-level persistent XLA cache would lend BOTH children warm
    # compiles from earlier processes on this machine — the uncached child
    # must pay true cold-start compiles and the builder must serialize
    # executables from its own compiler, so the whole harness opts out
    env["CITIZENS_TPU_NO_COMPILE_CACHE"] = "1"

    tmpdir = tempfile.mkdtemp(prefix="coldboot_")
    cache_path = os.path.join(tmpdir, "aot_cache.pkl")
    env["BENCH_COLDBOOT_CACHE"] = cache_path

    t0 = time.time()
    build = subprocess.run(
        [
            sys.executable, "-m", "citizensassemblies_tpu.aot", "build",
            "--out", cache_path, "--profile", profile,
        ],
        env=env, capture_output=True, text=True, timeout=3600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    build_s = time.time() - t0
    if build.returncode != 0 or not os.path.exists(cache_path):
        sys.stdout.write(build.stdout)
        sys.stderr.write(build.stderr)
        print("coldboot bench FAILED: cache build failed")
        return 1
    try:  # the build CLI's stdout IS its pretty-printed JSON report
        build_report = json.loads(build.stdout)
    except ValueError:
        build_report = {}

    def _child(variant: str):
        cmd = [sys.executable, os.path.abspath(__file__), "--coldboot"]
        if smoke_mode:
            cmd.append("--smoke")
        cenv = dict(env)
        cenv["BENCH_COLDBOOT_CHILD"] = variant
        t = time.time()
        proc = subprocess.run(
            cmd, env=cenv, capture_output=True, text=True, timeout=3600
        )
        wall = time.time() - t
        report = None
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    report = json.loads(line)
                    break
                except ValueError:
                    continue
        if report is None:
            sys.stdout.write(proc.stdout)
            sys.stderr.write(proc.stderr)
        return proc.returncode, wall, report

    failures = []
    rc_u, wall_u, rep_u = _child("uncached")
    rc_c, wall_c, rep_c = _child("cached")
    if rep_u is None or rep_c is None:
        print("coldboot bench FAILED: no report line from a child")
        return 1
    failures += rep_u.get("failures", []) + rep_c.get("failures", [])
    if rc_u != 0:
        failures.append(f"uncached child exited {rc_u}")
    if rc_c != 0:
        failures.append(f"cached child exited {rc_c}")
    if rep_u["alloc_checksum"] != rep_c["alloc_checksum"]:
        failures.append(
            "cached and uncached allocations diverged: "
            f"{rep_c['alloc_checksum']} != {rep_u['alloc_checksum']}"
        )
    ratio = wall_u / max(wall_c, 1e-9)
    if not smoke_mode and ratio < 3.0:
        failures.append(
            f"cached coldboot only {ratio:.2f}x faster (gate: >= 3x)"
        )

    report = {
        "schema_version": 1,
        "coldboot_ok": not failures,
        "smoke": smoke_mode,
        "backend": "cpu",
        "profile": profile,
        "build_s": round(build_s, 2),
        "cache_entries": build_report.get("entries"),
        "cache_sha": build_report.get("sha"),
        "cached_wall_s": round(wall_c, 2),
        "uncached_wall_s": round(wall_u, 2),
        "speedup": round(ratio, 2),
        "cached": rep_c,
        "uncached": rep_u,
        "detail": {
            "coldboot_cached": {
                "seconds": round(wall_c, 3),
                "serve_compiles": rep_c["serve_compiles"],
                "aot_hits": (rep_c.get("aot") or {}).get("hits", 0),
            },
            "coldboot_uncached": {"seconds": round(wall_u, 3)},
            "coldboot_build": {"seconds": round(build_s, 3)},
        },
        "failures": failures,
    }
    name = "BENCH_coldboot_smoke.json" if smoke_mode else "BENCH_coldboot_r18.json"
    out_path = os.environ.get(
        "BENCH_COLDBOOT_PATH", os.path.join(_artifacts_dir(), name)
    )
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps({k: v for k, v in report.items() if k not in ("cached", "uncached")}, indent=1))
    for f in failures:
        print(f"coldboot bench FAILED: {f}")
    return 1 if failures else 0


def _fleet_rate_hz(smoke_mode: bool) -> float:
    """The fleet offered rate: ``BENCH_FLEET_RATE`` env override, else a
    small smoke literal, else the ``Config.fleet_offered_rate_hz`` knob —
    the single source of the full-run default (README table, R6)."""
    env = os.environ.get("BENCH_FLEET_RATE", "")
    if env:
        return float(env)
    if smoke_mode:
        return 30.0
    from citizensassemblies_tpu.utils.config import default_config

    return float(default_config().fleet_offered_rate_hz)


def fleet_bench_child(idx: int, smoke_mode: bool) -> int:
    """``bench.py --fleet`` (child, one serving process of the fleet).

    Every child deterministically rebuilds the IDENTICAL global plan —
    seeded Poisson arrivals at the fleet offered rate, seeded tenant draws,
    rendezvous tenant→process placement — and serves only its own share,
    so the fleet needs no IPC beyond process launch. The child runs serial
    references first (which also warms every executable its shapes need),
    drives its share open-loop through a :class:`FleetProcess`, checks
    every served allocation against its serial reference, and — child 0
    only — runs the SLO shed/degrade drill (induced overload → breach
    events streamed → typed ShedRejection shedding + ladder descent →
    recovery re-arms). Prints ONE JSON report line for the parent.
    """
    _dist_scope_caches()

    import jax
    import numpy as np

    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.dist import runtime as dist_runtime
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.service import (
        SelectionRequest,
        SelectionService,
    )
    from citizensassemblies_tpu.service.fleet import (
        FleetProcess,
        plan_from_config,
    )
    from citizensassemblies_tpu.utils.config import default_config

    t_start = time.time()
    failures: list = []
    nproc = dist_runtime.fleet_process_count()
    seed = int(os.environ.get("BENCH_FLEET_SEED", "20"))
    rate = _fleet_rate_hz(smoke_mode)
    n_requests = int(
        os.environ.get("BENCH_FLEET_REQUESTS", "60" if smoke_mode else "10000")
    )
    # the full run's p99 target is sized to the container: 10^4 open-loop
    # requests at the full offered rate on an N-way-oversubscribed CPU host
    # queue for minutes BY DESIGN (open loop makes queueing visible instead
    # of self-throttling) — the objective gates completion health, not a
    # fabricated hardware latency
    slo_spec = (
        _SERVE_SLO_SPEC
        if smoke_mode
        else os.environ.get("BENCH_FLEET_SLO", "latency_p99:600s,error_rate:0.01")
    )
    cfg = default_config().replace(
        lp_batch=True, serve_batch_window_ms=8.0, serve_admission_cap=8,
        # open loop: arrivals never wait for completions, so the queue must
        # absorb the whole backlog — admission back-pressure off for the
        # measurement run (the shed drill exercises load rejection instead)
        serve_queue_depth=max(n_requests, 64),
        obs_slo_spec=slo_spec,
    )

    # --- the global plan (identical in every child) ------------------------
    tenants, plan = plan_from_config(
        cfg, n_requests, seed=seed, n_processes=nproc, rate_hz=rate
    )
    mine = [a for a in plan if a.owner == idx]

    # deterministic mixed workload: each tenant owns a small pool of unique
    # mixed-size instances; plan slot i reuses pool[i % uniq], so identical
    # re-submissions ride the tenant memo — the unique/repeat split of a
    # serving workload, recorded honestly on the report
    uniq = 3 if smoke_mode else 6
    tenant_ix = {t: i for i, t in enumerate(tenants)}
    pools: dict = {}

    def spec_for(a):
        ti = tenant_ix[a.tenant]
        pool = pools.get(a.tenant)
        if pool is None:
            pool = [
                random_instance(
                    n=24 + 8 * ((ti + j) % 3), k=4 + ((ti + j) % 4),
                    n_categories=2, seed=(ti * 31 + j) % 97,
                )
                for j in range(uniq)
            ]
            pools[a.tenant] = pool
        j = a.index % uniq
        return pool[j], (a.tenant, j)

    items = []
    needed: dict = {}
    key_of: dict = {}
    for a in mine:
        inst, key = spec_for(a)
        items.append((a, SelectionRequest(instance=inst, tenant=a.tenant)))
        needed.setdefault(key, inst)
        key_of[a.index] = key

    # serial references FIRST: the single-process bit-identity baseline,
    # and the warm-up that makes the drive measure steady-state serving
    refs: dict = {}
    t_serial0 = time.time()
    for key in sorted(needed):
        d, s = featurize(needed[key])
        refs[key] = np.asarray(
            find_distribution_leximin(d, s, cfg=cfg).allocation
        )
    serial_s = time.time() - t_serial0

    # --- the open-loop drive -----------------------------------------------
    worst = {"linf": 0.0, "bit_identical": True}

    def check(a, res):
        ref = refs.get(key_of[a.index])
        alloc = np.asarray(res.allocation)
        if ref is None or alloc.shape != ref.shape:
            worst["linf"] = max(worst["linf"], float("inf"))
            return
        if alloc.size:
            worst["linf"] = max(
                worst["linf"], float(np.max(np.abs(alloc - ref)))
            )
        if not np.array_equal(alloc, ref):
            worst["bit_identical"] = False

    fp = FleetProcess(idx, nproc, cfg)
    t_drive0 = time.time()
    rollup = fp.drive(
        items, timeout_s=900.0 if smoke_mode else 3000.0, on_result=check
    )
    drive_s = time.time() - t_drive0
    prom_text = fp.service.metrics_text()
    slo_report = fp.service.slo.evaluate() if fp.service.slo else None
    fp.shutdown()

    # --- child gates --------------------------------------------------------
    b = rollup["batcher"]
    if b.get("dist_reshards", 0):
        failures.append(
            f"p{idx}: {b['dist_reshards']} steady-state reshard(s) "
            "(gauge must hold at 0)"
        )
    if len(jax.devices()) > 1 and b.get("mesh_dispatches", 0) < 1:
        failures.append(f"p{idx}: no batcher dispatch spanned the mesh")
    if rollup["failed"] or rollup["shed"] or rollup["admission_rejected"]:
        failures.append(
            f"p{idx}: {rollup['failed']} failed / {rollup['shed']} shed / "
            f"{rollup['admission_rejected']} rejected in the measurement run"
        )
    if rollup["completed"] != rollup["offered"]:
        failures.append(
            f"p{idx}: completed {rollup['completed']} != offered "
            f"{rollup['offered']}"
        )
    if worst["linf"] > 1e-3:
        failures.append(
            f"p{idx}: served allocation deviates {worst['linf']:.2e} > 1e-3 "
            "vs serial reference"
        )
    if slo_report is not None and not slo_report["slo_ok"]:
        failures.append(f"p{idx}: SLO report red: {slo_report['breaches']}")

    # --- per-process artifacts, suffixed by process index (the satellite
    # multi-process contract: concurrent children never clobber evidence)
    art = _artifacts_dir()
    suffix = "_smoke" if smoke_mode else ""
    prom_path = dist_runtime.scoped_artifact_path(
        os.path.join(art, f"metrics_fleet{suffix}.prom")
    )
    slo_path = dist_runtime.scoped_artifact_path(
        os.path.join(art, f"SLO_fleet{suffix}.json")
    )
    try:
        with open(prom_path, "w", encoding="utf-8") as fh:
            fh.write(prom_text)
        with open(slo_path, "w", encoding="utf-8") as fh:
            json.dump({"spec": slo_spec, "report": slo_report}, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass

    # --- the SLO shed/degrade drill (child 0 only: one drill per fleet) ----
    drill_block = None
    if idx == 0:
        # a small dedicated instance — pools only hold this child's OWNED
        # tenants, and tenant0 may belong to a sibling process
        drill_inst = random_instance(n=24, k=4, n_categories=2, seed=0)
        drill_block = _fleet_drill(cfg, drill_inst, failures)

    report = {
        "fleet_child": idx,
        "processes": nproc,
        "visible_devices": len(jax.devices()),
        "seconds": round(time.time() - t_start, 2),
        "serial_refs_s": round(serial_s, 2),
        "drive_s": round(drive_s, 2),
        "unique_specs": len(needed),
        "worst_alloc_linf": (
            worst["linf"] if np.isfinite(worst["linf"]) else "shape-mismatch"
        ),
        "bit_identical": worst["bit_identical"],
        "rollup": rollup,
        "slo_ok": None if slo_report is None else slo_report["slo_ok"],
        "artifacts": [os.path.basename(prom_path), os.path.basename(slo_path)],
        "drill": drill_block,
        "failures": failures,
    }
    print(json.dumps(report))
    return 1 if failures else 0


def _fleet_drill(cfg, inst, failures: list):
    """Induced overload → breach stream → shedding + ladder descent →
    recovery re-arm, on a dedicated service. ``queue_stall:1.0`` stalls
    every request 0.25 s pre-execution against a 50 ms p99 objective, so
    the fast window must breach; ``serve_shed=True`` closes the loop."""
    from citizensassemblies_tpu.service import (
        SelectionRequest,
        SelectionService,
    )

    drill_cfg = cfg.replace(
        fault_sites="queue_stall:1.0", fault_seed=7,
        obs_slo_spec="latency_p99:50ms,error_rate:0.5",
        serve_shed=True, serve_shed_window_s=1.0,
        serve_shed_burn=2.0, serve_shed_recover=0.5,
        serve_batch_window_ms=0.0, serve_queue_depth=64,
        obs_metrics_interval_s=0.0,
    )
    drill = SelectionService(drill_cfg)
    block = {}
    try:
        # phase 1 — overload: a stalled burst must stream breach events
        chans = [
            drill.submit(SelectionRequest(instance=inst, tenant="drill"))
            for _ in range(6)
        ]
        breach_events = 0
        for ch in chans:
            try:
                ch.result(timeout=600)
            except RuntimeError:
                pass  # late burst members may already be shed — counted below
            breach_events += sum(
                1 for kind, _p in ch.events(timeout=1) if kind == "slo"
            )
        # phase 2 — shedding: new submissions get the typed rejection
        shed_payloads = []
        for _ in range(4):
            ch = drill.submit(SelectionRequest(instance=inst, tenant="drill"))
            for kind, payload in ch.events(timeout=10):
                if kind == "error" and isinstance(payload, dict):
                    shed_payloads.append(payload)
        sheds = [p for p in shed_payloads if p.get("kind") == "ShedRejection"]
        stamp_hot = drill.load_policy.stamp()
        # phase 3 — recovery: the fast window empties, the next clean
        # submission re-arms the policy and is served normally
        time.sleep(1.2 * drill_cfg.serve_shed_window_s)
        clean = drill_cfg.replace(fault_sites="")
        res = drill.run(
            SelectionRequest(instance=inst, tenant="drill", cfg=clean),
            timeout=600,
        )
        stamp_rearmed = drill.load_policy.stamp()
        block = {
            "breach_events": breach_events,
            "shed": len(sheds),
            "audit_stub_ok": all(
                {"tenant", "request_id", "worst_burn", "rung"}
                <= set(p.get("audit", {}))
                for p in sheds
            ),
            "rung_hot": stamp_hot["rung"],
            "shed_total": stamp_hot["shed_total"],
            "rearm_total": stamp_rearmed["rearm_total"],
            "recovered_request_ok": bool(res.allocation is not None),
        }
        if breach_events < 1:
            failures.append("drill: no ('slo', …) breach event streamed")
        if len(sheds) < 1:
            failures.append("drill: overload shed no submission")
        if not block["audit_stub_ok"]:
            failures.append("drill: a ShedRejection carried no audit stub")
        if stamp_hot["rung"] < 1:
            failures.append("drill: ladder never descended under overload")
        if stamp_rearmed["rearm_total"] < 1:
            failures.append("drill: recovery never re-armed the policy")
    finally:
        drill.shutdown()
    return block


def fleet_bench(smoke_mode: bool) -> int:
    """``bench.py --fleet`` (parent): the graftfleet serving harness.

    Forks N serving children (independent OS processes, 2 forced virtual
    devices each — the per-process mesh the batcher's sharded merge spans),
    exports the ``CITIZENS_FLEET_*`` contract, and aggregates their rollups
    into the fleet row. Gates: every process served its share, the summed
    PR 11 reshard gauge held at ZERO, ≥1 mesh-spanning and ≥1 cross-request
    fused dispatch occurred, worst served-vs-serial allocation L∞ ≤ 1e-3,
    the SLO reports are green, and child 0's shed/degrade drill passed.
    Writes ``artifacts/BENCH_fleet_smoke.json`` (smoke) or the next
    ``BENCH_fleet_rNN.json`` round (``BENCH_FLEET_PATH`` overrides) with
    ``detail`` rows for the obs/trend.py BENCH_fleet family loader.
    """
    import subprocess

    n_proc = int(
        os.environ.get("BENCH_FLEET_PROCESSES", "2" if smoke_mode else "4")
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
    ).strip()
    env["CITIZENS_FLEET_PROCESSES"] = str(n_proc)
    env.setdefault("BENCH_FLEET_SEED", "20")

    t0 = time.time()
    cmd = [sys.executable, os.path.abspath(__file__), "--fleet"]
    if smoke_mode:
        cmd.append("--smoke")
    procs = []
    for i in range(n_proc):
        cenv = dict(env)
        cenv["BENCH_FLEET_CHILD"] = str(i)
        cenv["CITIZENS_FLEET_INDEX"] = str(i)
        procs.append(
            subprocess.Popen(
                cmd, env=cenv, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
        )
    failures: list = []
    children = []
    for i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=1200 if smoke_mode else 5400)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            failures.append(f"child {i} timed out")
        tail = "\n".join((out + "\n" + err).splitlines()[-25:])
        for marker in ("cpu_aot_loader", "machine mismatch"):
            if marker in tail:
                failures.append(f"child {i}: '{marker}' spam in the run tail")
        report = None
        for line in reversed(out.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    report = json.loads(line)
                    break
                except ValueError:
                    continue
        if report is None:
            sys.stdout.write(out)
            sys.stderr.write(err)
            failures.append(f"child {i}: no report line")
            continue
        if proc.returncode != 0:
            failures.append(f"child {i} exited {proc.returncode}")
        failures.extend(report.get("failures", []))
        children.append(report)
    wall_s = time.time() - t0

    from citizensassemblies_tpu.service.fleet import fleet_aggregate

    agg = fleet_aggregate([c["rollup"] for c in children])
    drill = next((c.get("drill") for c in children if c.get("drill")), None)
    worst_linf = max(
        (
            c["worst_alloc_linf"]
            for c in children
            if isinstance(c.get("worst_alloc_linf"), (int, float))
        ),
        default=float("inf") if children else 0.0,
    )

    # --- fleet gates --------------------------------------------------------
    if len(children) != n_proc:
        failures.append(f"only {len(children)}/{n_proc} children reported")
    if any(c["rollup"]["completed"] == 0 for c in children):
        failures.append("a fleet process served zero requests")
    if agg["steady_state_reshards"] != 0:
        failures.append(
            f"fleet reshard gauge {agg['steady_state_reshards']} != 0"
        )
    if agg["batcher"]["mesh_dispatches"] < 1:
        failures.append("no fused batcher dispatch spanned a mesh")
    if agg["batcher"]["fused_dispatches"] < 1:
        failures.append("no dispatch fused fleets from >=2 requests")
    if worst_linf > 1e-3:
        failures.append(f"fleet worst allocation L-inf {worst_linf:.2e} > 1e-3")
    if drill is None:
        failures.append("no child ran the shed/degrade drill")

    # round number: 1 past the newest committed BENCH_fleet_r*.json
    import re

    repo_root = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for f in os.listdir(repo_root)
        if (m := re.match(r"BENCH_fleet_r(\d+)\.json$", f))
    ]
    rnd = (max(rounds) + 1) if rounds else 20

    doc = {
        "schema_version": 1,
        "fleet_ok": not failures,
        "round": rnd,
        "smoke": bool(smoke_mode),
        "backend": "cpu",
        "processes": n_proc,
        "offered_rate_hz": _fleet_rate_hz(smoke_mode),
        "requests": agg["offered"],
        "seconds": round(wall_s, 2),
        "aggregate": agg,
        "worst_alloc_linf": (
            round(worst_linf, 9) if worst_linf != float("inf") else None
        ),
        "drill": drill,
        "per_process": [
            {
                **{k: v for k, v in c.items() if k not in ("rollup", "drill")},
                "rollup": {
                    k: v for k, v in c["rollup"].items() if k != "sojourns_s"
                },
            }
            for c in children
        ],
        "detail": {
            "fleet_open_loop": {
                "seconds": round(
                    max((c["drive_s"] for c in children), default=0.0), 3
                ),
                "sustained_req_per_s": agg["sustained_req_per_s"],
                "p50_sojourn_s": agg["p50_sojourn_s"],
                "p99_sojourn_s": agg["p99_sojourn_s"],
            },
            "fleet_serial_refs": {
                "seconds": round(
                    max((c["serial_refs_s"] for c in children), default=0.0), 3
                ),
            },
            "fleet_wall": {"seconds": round(wall_s, 3)},
        },
        "failures": failures,
    }
    name = "BENCH_fleet_smoke.json" if smoke_mode else f"BENCH_fleet_r{rnd:02d}.json"
    out_path = os.environ.get(
        "BENCH_FLEET_PATH", os.path.join(_artifacts_dir(), name)
    )
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
    except OSError:
        pass
    print(json.dumps({k: v for k, v in doc.items() if k != "per_process"}, indent=1))
    for f in failures:
        print(f"fleet bench FAILED: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    if "--trend" in sys.argv:
        raise SystemExit(trend())
    if "--chaos" in sys.argv:
        raise SystemExit(chaos_bench(smoke_mode="--smoke" in sys.argv))
    if "--scenarios" in sys.argv:
        raise SystemExit(scenario_bench(smoke_mode="--smoke" in sys.argv))
    if "--serve" in sys.argv:
        raise SystemExit(serve_bench(smoke_mode="--smoke" in sys.argv))
    if "--dist" in sys.argv:
        if os.environ.get("BENCH_DIST_CHILD"):
            raise SystemExit(dist_bench_child(smoke_mode="--smoke" in sys.argv))
        raise SystemExit(dist_bench(smoke_mode="--smoke" in sys.argv))
    if "--coldboot" in sys.argv:
        child = os.environ.get("BENCH_COLDBOOT_CHILD")
        if child:
            raise SystemExit(
                coldboot_bench_child(child, smoke_mode="--smoke" in sys.argv)
            )
        raise SystemExit(coldboot_bench(smoke_mode="--smoke" in sys.argv))
    if "--fleet" in sys.argv:
        child = os.environ.get("BENCH_FLEET_CHILD")
        if child is not None and child != "":
            raise SystemExit(
                fleet_bench_child(int(child), smoke_mode="--smoke" in sys.argv)
            )
        raise SystemExit(fleet_bench(smoke_mode="--smoke" in sys.argv))
    if "--kernels" in sys.argv:
        raise SystemExit(kernels_bench(smoke_mode="--smoke" in sys.argv))
    if "--churn" in sys.argv:
        raise SystemExit(churn_bench(smoke_mode="--smoke" in sys.argv))
    if "--roofline" in sys.argv:
        raise SystemExit(roofline_bench(smoke_mode="--smoke" in sys.argv))
    if "--smoke" in sys.argv:
        raise SystemExit(smoke())
    main()
