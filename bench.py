"""Benchmark: LEXIMIN wall-clock on an example_large_200-shaped instance.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

The instance mirrors ``data/example_large_200`` (n=2000, k=200, two binary
categories, quotas 99..200, pool composition 999/1000/1/0 across the four
intersections — measured from the reference respondents.csv), for which the
reference's golden median LEXIMIN runtime is 1161.8 s
(``reference_output/example_large_200_statistics.txt:15``; BASELINE.md).
``vs_baseline`` is our wall-clock divided by that baseline (< 1 ⇒ faster).

Runs on whatever accelerator JAX finds (TPU under the driver; CPU fallback
works too). Override the instance with ``BENCH_INSTANCE=small`` for a quick
smoke run.
"""

from __future__ import annotations

import json
import os
import time


def _example_large_like():
    from citizensassemblies_tpu.core.generator import cross_product_instance

    # pool composition measured from the reference data: (female,liberal) 999,
    # (male,conservative) 1000, (female,conservative) 1, (male,liberal) 0
    return cross_product_instance(
        categories=["gender", "leaning"],
        features=[["female", "male"], ["liberal", "conservative"]],
        quotas=[[(99, 200), (99, 200)], [(99, 200), (99, 200)]],
        counts=[999, 1, 0, 1000],
        k=200,
        name="example_large_200_like",
    )


def _example_small_like():
    from citizensassemblies_tpu.core.generator import example_small_like_instance

    return example_small_like_instance()


BASELINES = {
    # reference golden median LEXIMIN runtimes (BASELINE.md)
    "example_large_200_like": 1161.8,
    "example_small_like_20": 2.7,
}


def main() -> None:
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.ops.stats import prob_allocation_stats

    which = os.environ.get("BENCH_INSTANCE", "large")
    inst = _example_small_like() if which == "small" else _example_large_like()
    dense, space = featurize(inst)

    # one warm-up on a tiny instance to amortize kernel compilation out of the
    # measured run (the reference's timing harness also times steady-state
    # re-runs, analysis.py:625-634)
    from citizensassemblies_tpu.core.generator import random_instance

    warm = random_instance(n=64, k=8, n_categories=2, seed=0)
    wdense, wspace = featurize(warm)
    find_distribution_leximin(wdense, wspace)

    t0 = time.time()
    dist = find_distribution_leximin(dense, space)
    elapsed = time.time() - t0

    stats = prob_allocation_stats(dist.allocation, cap_for_geometric_mean=False)
    baseline = BASELINES[inst.name]
    print(
        json.dumps(
            {
                "metric": f"leximin_wallclock_{inst.name}",
                "value": round(elapsed, 2),
                "unit": "s",
                "vs_baseline": round(elapsed / baseline, 4),
                "detail": {
                    "min_prob": round(stats.min, 5),
                    "gini": round(stats.gini, 5),
                    "committees": int(dist.committees.shape[0]),
                    "baseline_s": baseline,
                    "speedup": round(baseline / max(elapsed, 1e-9), 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
