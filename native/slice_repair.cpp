// Greedy quota repair for apportionment slices — the host-runtime hot loop of
// the aimed slicer (citizensassemblies_tpu/solvers/cg_typespace.py::
// _slice_relaxation). A slice is an integer composition c[T] whose feature
// counts may violate the per-feature quotas after largest-remainder rounding;
// repair moves single units between types, each pass applying the best
// strictly-violation-reducing (donor, receiver) swap with tracking-residual
// tie preference — identical scoring to the python reference implementation
// in swap_repair (kept as the fallback), minus its per-pass numpy dispatch
// overhead, which dominated the slicer at T ≈ 1000 (~250 µs/pass python vs
// ~2 µs/pass here).
//
// slice_stream runs the ENTIRE R-slice loop natively (apportionment, gap
// top-up, repair, cumulative feedback): the per-slice ctypes round-trip plus
// numpy bookkeeping cost ~0.3 ms/slice on the python side — at R ≈ 1000 that
// was the dominant cost of the whole mid-tier leximin solve.
//
// Pure C++17, no dependencies; built like bb_price.cpp (g++ -O2 -shared) and
// loaded via ctypes from solvers/native_oracle.py.

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>
#include <cmath>

namespace {

// xorshift32: deterministic per-slice tie noise (any full-period stream
// works — parity with numpy's Generator is not required, only determinism)
inline uint32_t xs32(uint32_t& s) {
    s ^= s << 13; s ^= s >> 17; s ^= s << 5;
    return s;
}
inline double urand(uint32_t& s) { return (xs32(s) >> 8) * (1.0 / 16777216.0); }

// reusable per-call scratch so the stream loop allocates nothing per slice
struct RepairScratch {
    std::vector<double> viol, dv_sub_f, dv_add_f, dv_sub, dv_add, pref_sub,
        pref_add;
    std::vector<int> donors, receivers;
    void init(int T, int F) {
        viol.resize(F);
        dv_sub_f.resize(F);
        dv_add_f.resize(F);
        dv_sub.resize(T);
        dv_add.resize(T);
        pref_sub.resize(T);
        pref_add.resize(T);
        donors.reserve(T);
        receivers.reserve(T);
    }
};

// Repairs one slice in place. Returns 1 on success (all quotas met), 0 on
// failure (caller drops the slice).
int repair_impl(
    int T, int ncat, int F,
    const int32_t* type_feature,
    const int32_t* msize,
    const int32_t* lo, const int32_t* hi,
    int32_t* c, int32_t* counts,
    const double* need,
    uint32_t seed, int max_passes, RepairScratch& S) {
    uint32_t rng = seed ? seed : 1u;

    for (int pass = 0; pass < max_passes; ++pass) {
        // per-feature violation and one-unit removal/addition deltas
        double total = 0.0;
        int worst_over = -1, worst_under = -1;
        double worst_over_v = 0.0, worst_under_v = 0.0;
        for (int f = 0; f < F; ++f) {
            double over = std::max(0, counts[f] - hi[f]);
            double under = std::max(0, lo[f] - counts[f]);
            S.viol[f] = over + under;
            total += S.viol[f];
            double vs = std::max(0, counts[f] - 1 - hi[f]) +
                        std::max(0, lo[f] - counts[f] + 1);
            double va = std::max(0, counts[f] + 1 - hi[f]) +
                        std::max(0, lo[f] - counts[f] - 1);
            S.dv_sub_f[f] = vs - S.viol[f];
            S.dv_add_f[f] = va - S.viol[f];
            if (over > 0 && S.viol[f] > worst_over_v) {
                worst_over_v = S.viol[f];
                worst_over = f;
            }
            if (under > 0 && S.viol[f] > worst_under_v) {
                worst_under_v = S.viol[f];
                worst_under = f;
            }
        }
        if (total == 0.0) return 1;

        // per-type deltas + tracking preference (donate above target,
        // receive below target — the slice-stream self-correction)
        for (int t = 0; t < T; ++t) {
            double s = 0.0, a = 0.0;
            const int32_t* tf = type_feature + (size_t)t * ncat;
            for (int ci = 0; ci < ncat; ++ci) {
                s += S.dv_sub_f[tf[ci]];
                a += S.dv_add_f[tf[ci]];
            }
            S.dv_sub[t] = s;
            S.dv_add[t] = a;
            double track = (double)c[t] - need[t];
            track = std::min(2.0, std::max(-2.0, track));
            S.pref_sub[t] = -0.4 * track;
            S.pref_add[t] = 0.4 * track;
        }

        auto has_feature = [&](int t, int f) {
            const int32_t* tf = type_feature + (size_t)t * ncat;
            for (int ci = 0; ci < ncat; ++ci)
                if (tf[ci] == f) return true;
            return false;
        };

        S.donors.clear();
        S.receivers.clear();
        for (int t = 0; t < T; ++t) {
            bool can_d = c[t] > 0 && (worst_over < 0 || has_feature(t, worst_over));
            bool can_r =
                c[t] < msize[t] && (worst_under < 0 || has_feature(t, worst_under));
            if (can_d) S.donors.push_back(t);
            if (can_r) S.receivers.push_back(t);
        }
        if (S.donors.empty() || S.receivers.empty()) return 0;

        // keep the 16 most promising per side (score + tie noise)
        auto shrink = [&](std::vector<int>& v, const std::vector<double>& dv,
                          const std::vector<double>& pref) {
            if ((int)v.size() <= 16) return;
            std::vector<std::pair<double, int>> scored;
            scored.reserve(v.size());
            for (int t : v)
                scored.emplace_back(dv[t] + pref[t] + urand(rng) * 0.3, t);
            std::partial_sort(scored.begin(), scored.begin() + 16, scored.end());
            v.clear();
            for (int i = 0; i < 16; ++i) v.push_back(scored[i].second);
        };
        shrink(S.donors, S.dv_sub, S.pref_sub);
        shrink(S.receivers, S.dv_add, S.pref_add);

        // exact delta on the small cross product, with the shared-feature
        // correction (a category where donor and receiver share the feature
        // is a no-op there)
        double best = 1e300;
        double best_delta = 0.0;
        int bd = -1, br = -1;
        for (int d : S.donors) {
            const int32_t* tfd = type_feature + (size_t)d * ncat;
            for (int r : S.receivers) {
                if (d == r) continue;
                const int32_t* tfr = type_feature + (size_t)r * ncat;
                double delta = S.dv_sub[d] + S.dv_add[r];
                for (int ci = 0; ci < ncat; ++ci)
                    if (tfd[ci] == tfr[ci])
                        delta -= S.dv_sub_f[tfd[ci]] + S.dv_add_f[tfr[ci]];
                double noisy =
                    delta + S.pref_sub[d] + S.pref_add[r] + urand(rng) * 0.3;
                if (noisy < best) {
                    best = noisy;
                    best_delta = delta;
                    bd = d;
                    br = r;
                }
            }
        }
        if (bd < 0 || best_delta >= 0.0) return 0;
        c[bd] -= 1;
        c[br] += 1;
        const int32_t* tfd = type_feature + (size_t)bd * ncat;
        const int32_t* tfr = type_feature + (size_t)br * ncat;
        for (int ci = 0; ci < ncat; ++ci) {
            counts[tfd[ci]] -= 1;
            counts[tfr[ci]] += 1;
        }
    }
    for (int f = 0; f < F; ++f)
        if (counts[f] < lo[f] || counts[f] > hi[f]) return 0;
    return 1;
}

}  // namespace

extern "C" {

// Repairs one slice in place. Returns 1 on success (all quotas met), 0 on
// failure (caller drops the slice). Arguments:
//   T, ncat, F          — type/category/feature counts
//   type_feature [T*ncat] — global feature index per (type, category)
//   msize [T]           — pool size per type
//   lo, hi [F]          — feature quota bounds
//   c [T]               — slice composition (mutated)
//   counts [F]          — feature counts of c (mutated, kept consistent)
//   need [T]            — tracking residual target (j*x - assigned)
//   seed                — per-slice RNG seed
//   max_passes          — pass budget (python used 3*F)
int slice_repair(
    int T, int ncat, int F,
    const int32_t* type_feature,
    const int32_t* msize,
    const int32_t* lo, const int32_t* hi,
    int32_t* c, int32_t* counts,
    const double* need,
    uint32_t seed, int max_passes) {
    RepairScratch S;
    S.init(T, F);
    return repair_impl(T, ncat, F, type_feature, msize, lo, hi, c, counts,
                       need, seed, max_passes, S);
}

// The full aimed-slicer stream (cg_typespace._slice_relaxation's loop body):
// for j = 1..R, apportion the residual j*x − assigned by cumulative
// largest-remainder rounding, top up/trim to Σc = k by residual fraction
// (golden-ratio jitter rotating exact ties), quota-repair, and feed every
// emitted unit back into `assigned` so the uniform mixture tracks x to ~1/R.
// Kept (feasible) slices are written to out[kept*T .. ]; returns kept.
// Matches the python loop's arithmetic exactly; per-slice repair seeds are
// j + j0, identical to the per-slice native path at j0 = 0.
//
// j0 shifts the APPORTIONMENT PHASE as well as the tie streams (top-up
// jitter, repair RNG): slice j apportions the residual (j + φ_t)·x_t −
// assigned_t with a PER-TYPE phase φ_t = frac(j0·0.38196601125 +
// t·0.61803398875) ∈ [0, 1). Slices needing no repair are a pure function of
// the apportionment, so tie noise alone cannot diversify them — a measured
// j0-without-phase deep pass emitted ~75 % byte-duplicates of the injection
// stream, and a single scalar phase still duplicated most slices (it moves
// boundaries by φ·x_t, negligible for the many small-x types). Per-type
// phases stagger every type's rounding boundary independently, so calls with
// different j0 emit genuinely different slices of the same hull, while each
// call's mixture still tracks x to ~1/R (the telescoping leaves a one-off
// φ_t·x_t ≤ 1-unit offset per type). j0 = 0 keeps the original arithmetic
// bit-for-bit. This is also what makes chunked parallel streams productive
// (each chunk is a full stream at its own phase).
int slice_stream(
    int T, int ncat, int F,
    const int32_t* type_feature,
    const int32_t* msize,
    const int32_t* lo, const int32_t* hi,
    int k, const double* x, int R, int max_passes, uint32_t j0,
    int32_t* out) {
    std::vector<double> assigned(T, 0.0), need(T), frac(T);
    std::vector<int32_t> c(T);
    std::vector<int32_t> counts(F);
    std::vector<int> order(T);
    RepairScratch S;
    S.init(T, F);
    int kept = 0;
    std::vector<double> phase(T, 0.0);
    if (j0)
        for (int t = 0; t < T; ++t)
            phase[t] = std::fmod(
                (double)j0 * 0.38196601125 + (double)t * 0.61803398875, 1.0);
    for (int j = 1; j <= R; ++j) {
        long long sum = 0;
        for (int t = 0; t < T; ++t) {
            need[t] = ((double)j + phase[t]) * x[t] - assigned[t];
            double fl = std::floor(need[t] + 1e-12);
            double cv = std::max(fl, 0.0);
            double mv = (double)msize[t];
            if (cv > mv) cv = mv;
            c[t] = (int32_t)cv;
            // golden-ratio jitter rotates exact fraction ties across slices
            frac[t] = (need[t] - fl) +
                      std::fmod((double)t * 0.6180339887 +
                                    (double)(j + j0) * 0.7548776662,
                                1.0) *
                          1e-6;
            sum += c[t];
        }
        long long gap = (long long)k - sum;
        // feature counts of the floor assignment, maintained through the
        // top-up so it can stay quota-aware
        std::fill(counts.begin(), counts.end(), 0);
        for (int t = 0; t < T; ++t) {
            if (!c[t]) continue;
            const int32_t* tf = type_feature + (size_t)t * ncat;
            for (int ci = 0; ci < ncat; ++ci) counts[tf[ci]] += c[t];
        }
        if (gap != 0) {
            for (int t = 0; t < T; ++t) order[t] = t;
            // two sweeps by residual fraction: the first only accepts moves
            // that keep the moved unit's features inside their quota bounds
            // (additions below hi / removals above lo), the second takes any
            // eligible type. Quota-blind top-up was the main source of the
            // ~10-20 repair passes per slice — most of the stream's cost.
            if (gap > 0) {
                std::sort(order.begin(), order.end(),
                          [&](int a, int b) { return frac[a] > frac[b]; });
                for (int sweep = 0; sweep < 2 && gap != 0; ++sweep) {
                    for (int t : order) {
                        if (gap == 0) break;
                        if (c[t] >= msize[t]) continue;
                        const int32_t* tf = type_feature + (size_t)t * ncat;
                        if (sweep == 0) {
                            bool safe = true;
                            for (int ci = 0; ci < ncat; ++ci)
                                if (counts[tf[ci]] + 1 > hi[tf[ci]]) {
                                    safe = false;
                                    break;
                                }
                            if (!safe) continue;
                        }
                        c[t] += 1;
                        gap -= 1;
                        for (int ci = 0; ci < ncat; ++ci) counts[tf[ci]] += 1;
                    }
                }
            } else {
                std::sort(order.begin(), order.end(),
                          [&](int a, int b) { return frac[a] < frac[b]; });
                for (int sweep = 0; sweep < 2 && gap != 0; ++sweep) {
                    for (int t : order) {
                        if (gap == 0) break;
                        if (c[t] <= 0) continue;
                        const int32_t* tf = type_feature + (size_t)t * ncat;
                        if (sweep == 0) {
                            bool safe = true;
                            for (int ci = 0; ci < ncat; ++ci)
                                if (counts[tf[ci]] - 1 < lo[tf[ci]]) {
                                    safe = false;
                                    break;
                                }
                            if (!safe) continue;
                        }
                        c[t] -= 1;
                        gap += 1;
                        for (int ci = 0; ci < ncat; ++ci) counts[tf[ci]] -= 1;
                    }
                }
            }
        }
        if (gap != 0) {  // un-toppable slice: feed back and drop
            for (int t = 0; t < T; ++t) assigned[t] += (double)c[t];
            continue;
        }
        int ok = repair_impl(T, ncat, F, type_feature, msize, lo, hi, c.data(),
                             counts.data(), need.data(), (uint32_t)j + j0,
                             max_passes, S);
        // feedback includes repaired units even when the repair failed —
        // the stream stays honest about what was actually emitted
        for (int t = 0; t < T; ++t) assigned[t] += (double)c[t];
        if (ok) {
            std::memcpy(out + (size_t)kept * T, c.data(),
                        (size_t)T * sizeof(int32_t));
            ++kept;
        }
    }
    return kept;
}

}  // extern "C"
