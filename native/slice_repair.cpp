// Greedy quota repair for apportionment slices — the host-runtime hot loop of
// the aimed slicer (citizensassemblies_tpu/solvers/cg_typespace.py::
// _slice_relaxation). A slice is an integer composition c[T] whose feature
// counts may violate the per-feature quotas after largest-remainder rounding;
// repair moves single units between types, each pass applying the best
// strictly-violation-reducing (donor, receiver) swap with tracking-residual
// tie preference — identical scoring to the python reference implementation
// in swap_repair (kept as the fallback), minus its per-pass numpy dispatch
// overhead, which dominated the slicer at T ≈ 1000 (~250 µs/pass python vs
// ~2 µs/pass here).
//
// Pure C++17, no dependencies; built like bb_price.cpp (g++ -O2 -shared) and
// loaded via ctypes from solvers/native_oracle.py.

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>
#include <cmath>

namespace {

// xorshift32: deterministic per-slice tie noise (any full-period stream
// works — parity with numpy's Generator is not required, only determinism)
inline uint32_t xs32(uint32_t& s) {
    s ^= s << 13; s ^= s >> 17; s ^= s << 5;
    return s;
}
inline double urand(uint32_t& s) { return (xs32(s) >> 8) * (1.0 / 16777216.0); }

}  // namespace

extern "C" {

// Repairs one slice in place. Returns 1 on success (all quotas met), 0 on
// failure (caller drops the slice). Arguments:
//   T, ncat, F          — type/category/feature counts
//   type_feature [T*ncat] — global feature index per (type, category)
//   msize [T]           — pool size per type
//   lo, hi [F]          — feature quota bounds
//   c [T]               — slice composition (mutated)
//   counts [F]          — feature counts of c (mutated, kept consistent)
//   need [T]            — tracking residual target (j*x - assigned)
//   seed                — per-slice RNG seed
//   max_passes          — pass budget (python used 3*F)
int slice_repair(
    int T, int ncat, int F,
    const int32_t* type_feature,
    const int32_t* msize,
    const int32_t* lo, const int32_t* hi,
    int32_t* c, int32_t* counts,
    const double* need,
    uint32_t seed, int max_passes) {
    uint32_t rng = seed ? seed : 1u;
    std::vector<double> viol(F), dv_sub_f(F), dv_add_f(F);
    std::vector<double> dv_sub(T), dv_add(T), pref_sub(T), pref_add(T);
    std::vector<int> donors, receivers;
    donors.reserve(T);
    receivers.reserve(T);

    for (int pass = 0; pass < max_passes; ++pass) {
        // per-feature violation and one-unit removal/addition deltas
        double total = 0.0;
        int worst_over = -1, worst_under = -1;
        double worst_over_v = 0.0, worst_under_v = 0.0;
        for (int f = 0; f < F; ++f) {
            double over = std::max(0, counts[f] - hi[f]);
            double under = std::max(0, lo[f] - counts[f]);
            viol[f] = over + under;
            total += viol[f];
            double vs = std::max(0, counts[f] - 1 - hi[f]) +
                        std::max(0, lo[f] - counts[f] + 1);
            double va = std::max(0, counts[f] + 1 - hi[f]) +
                        std::max(0, lo[f] - counts[f] - 1);
            dv_sub_f[f] = vs - viol[f];
            dv_add_f[f] = va - viol[f];
            if (over > 0 && viol[f] > worst_over_v) {
                worst_over_v = viol[f];
                worst_over = f;
            }
            if (under > 0 && viol[f] > worst_under_v) {
                worst_under_v = viol[f];
                worst_under = f;
            }
        }
        if (total == 0.0) return 1;

        // per-type deltas + tracking preference (donate above target,
        // receive below target — the slice-stream self-correction)
        for (int t = 0; t < T; ++t) {
            double s = 0.0, a = 0.0;
            const int32_t* tf = type_feature + (size_t)t * ncat;
            for (int ci = 0; ci < ncat; ++ci) {
                s += dv_sub_f[tf[ci]];
                a += dv_add_f[tf[ci]];
            }
            dv_sub[t] = s;
            dv_add[t] = a;
            double track = (double)c[t] - need[t];
            track = std::min(2.0, std::max(-2.0, track));
            pref_sub[t] = -0.4 * track;
            pref_add[t] = 0.4 * track;
        }

        auto has_feature = [&](int t, int f) {
            const int32_t* tf = type_feature + (size_t)t * ncat;
            for (int ci = 0; ci < ncat; ++ci)
                if (tf[ci] == f) return true;
            return false;
        };

        donors.clear();
        receivers.clear();
        for (int t = 0; t < T; ++t) {
            bool can_d = c[t] > 0 && (worst_over < 0 || has_feature(t, worst_over));
            bool can_r =
                c[t] < msize[t] && (worst_under < 0 || has_feature(t, worst_under));
            if (can_d) donors.push_back(t);
            if (can_r) receivers.push_back(t);
        }
        if (donors.empty() || receivers.empty()) return 0;

        // keep the 16 most promising per side (score + tie noise)
        auto shrink = [&](std::vector<int>& v, const std::vector<double>& dv,
                          const std::vector<double>& pref) {
            if ((int)v.size() <= 16) return;
            std::vector<std::pair<double, int>> scored;
            scored.reserve(v.size());
            for (int t : v)
                scored.emplace_back(dv[t] + pref[t] + urand(rng) * 0.3, t);
            std::partial_sort(scored.begin(), scored.begin() + 16, scored.end());
            v.clear();
            for (int i = 0; i < 16; ++i) v.push_back(scored[i].second);
        };
        shrink(donors, dv_sub, pref_sub);
        shrink(receivers, dv_add, pref_add);

        // exact delta on the small cross product, with the shared-feature
        // correction (a category where donor and receiver share the feature
        // is a no-op there)
        double best = 1e300;
        double best_delta = 0.0;
        int bd = -1, br = -1;
        for (int d : donors) {
            const int32_t* tfd = type_feature + (size_t)d * ncat;
            for (int r : receivers) {
                if (d == r) continue;
                const int32_t* tfr = type_feature + (size_t)r * ncat;
                double delta = dv_sub[d] + dv_add[r];
                for (int ci = 0; ci < ncat; ++ci)
                    if (tfd[ci] == tfr[ci])
                        delta -= dv_sub_f[tfd[ci]] + dv_add_f[tfr[ci]];
                double noisy =
                    delta + pref_sub[d] + pref_add[r] + urand(rng) * 0.3;
                if (noisy < best) {
                    best = noisy;
                    best_delta = delta;
                    bd = d;
                    br = r;
                }
            }
        }
        if (bd < 0 || best_delta >= 0.0) return 0;
        c[bd] -= 1;
        c[br] += 1;
        const int32_t* tfd = type_feature + (size_t)bd * ncat;
        const int32_t* tfr = type_feature + (size_t)br * ncat;
        for (int ci = 0; ci < ncat; ++ci) {
            counts[tfd[ci]] -= 1;
            counts[tfr[ci]] += 1;
        }
    }
    for (int f = 0; f < F; ++f)
        if (counts[f] < lo[f] || counts[f] > hi[f]) return 0;
    return 1;
}

}  // extern "C"
