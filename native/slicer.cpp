// Native water-filling slicer: the hot loop of
// citizensassemblies_tpu/solvers/compositions.py::greedy_decompose.
//
// Decomposes a distribution over type-space compositions into concrete
// panels: each slice picks, per type, the c_t members with the largest
// remaining need (need = target selection probability not yet realized),
// ties rotated by a per-type cursor; the slice's probability is the largest
// step that overshoots no chosen member. Semantics mirror the Python
// reference implementation exactly (same sort keys, same cursor updates) so
// the two can be cross-checked; the Python loop costs seconds at
// reference-benchmark shapes (e.g. ~2.5 s on a nexus_170-shaped instance,
// ~90k per-type partial sorts) while this loop is ~100x faster.
//
// Household mode (houses != nullptr): within one slice the picks are
// additionally household-disjoint — the quotient reduction's class-cap
// quota rows (solvers/quotient.py) guarantee a disjoint assignment exists,
// and the scan simply skips members of already-used households. Returns -2
// if a pick cannot be completed (caps violated upstream); the caller falls
// back to the Python implementation.
//
// C ABI only — loaded with ctypes (no pybind11 in this toolchain).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" int slicer_decompose(
    int T, int n, int S,
    const int32_t* comps,        // [S, T] compositions, caller-sorted by -prob
    const double* probs,         // [S] composition probabilities
    const int32_t* members_flat, // member agent ids, concatenated per type
    const int32_t* member_off,   // [T+1] offsets into members_flat
    const int32_t* houses_flat,  // household id per member (same layout) or null
    int n_houses,                // households overall (size of used bitmap)
    double* needs_flat,          // per-member remaining need (in/out)
    double delta_cap,            // max slice mass (<=0: uncapped); capping
                                 // equidistributes members when the support
                                 // is a basic (sparse) LP solution whose
                                 // natural slices are too coarse to mix
    int max_panels,
    uint8_t* out_panels,         // [max_panels, n] row-major
    double* out_probs,           // [max_panels]
    int* out_count) {
  std::vector<int64_t> cursors(T, 0);
  std::vector<int32_t> idx_buf;
  std::vector<int32_t> chosen_types;
  std::vector<std::pair<int32_t, int32_t>> chosen; // (type, member slot)
  std::vector<uint8_t> house_used(houses_flat ? n_houses : 0, 0);
  std::vector<int32_t> touched;
  int count = 0;

  for (int s = 0; s < S; ++s) {
    double rho = probs[s];
    const int32_t* c = comps + (int64_t)s * T;
    while (rho > 1e-12 && count < max_panels) {
      double delta = (delta_cap > 0.0) ? std::min(rho, delta_cap) : rho;
      chosen.clear();
      if (houses_flat) {
        for (int32_t h : touched) house_used[h] = 0;
        touched.clear();
      }
      for (int t = 0; t < T; ++t) {
        int ct = c[t];
        if (!ct) continue;
        int off = member_off[t];
        int mt = member_off[t + 1] - off;
        if (ct > mt) return -2; // caps violated upstream — caller falls back
        const double* need = needs_flat + off;
        int64_t cur = cursors[t];
        idx_buf.resize(mt);
        for (int j = 0; j < mt; ++j) idx_buf[j] = j;
        // order by (need desc, rotation asc); rotation = (j - cursor) mod mt
        auto rot = [cur, mt](int j) { return (int)(((int64_t)j - cur) % mt + mt) % mt; };
        auto cmp = [&](int a, int b) {
          if (need[a] != need[b]) return need[a] > need[b];
          return rot(a) < rot(b);
        };
        int picked = 0;
        if (!houses_flat) {
          if (ct < mt)
            std::partial_sort(idx_buf.begin(), idx_buf.begin() + ct,
                              idx_buf.end(), cmp);
          for (int j = 0; j < ct; ++j)
            chosen.emplace_back(t, off + idx_buf[j]);
          picked = std::min(ct, mt);
        } else {
          std::sort(idx_buf.begin(), idx_buf.end(), cmp);
          const int32_t* house = houses_flat + off;
          for (int j = 0; j < mt && picked < ct; ++j) {
            int32_t h = house[idx_buf[j]];
            if (house_used[h]) continue;
            house_used[h] = 1;
            touched.push_back(h);
            chosen.emplace_back(t, off + idx_buf[j]);
            ++picked;
          }
        }
        if (picked < ct) return -2; // caps violated upstream — caller falls back
        double mn = needs_flat[chosen[chosen.size() - ct].second];
        for (size_t q = chosen.size() - ct; q < chosen.size(); ++q)
          mn = std::min(mn, needs_flat[chosen[q].second]);
        if (mn > 1e-15) delta = std::min(delta, mn);
      }
      if (delta <= 1e-15)
        delta = (delta_cap > 0.0) ? std::min(rho, delta_cap)
                                : rho; // forced overshoot; LP polish absorbs it
      uint8_t* row = out_panels + (int64_t)count * n;
      std::memset(row, 0, n);
      for (auto& tc : chosen) {
        row[members_flat[tc.second]] = 1;
        needs_flat[tc.second] -= delta;
      }
      for (int t = 0; t < T; ++t) {
        int ct = c[t];
        if (!ct) continue;
        int mt = member_off[t + 1] - member_off[t];
        if (mt > 0) cursors[t] = (cursors[t] + ct) % mt;
      }
      out_probs[count++] = delta;
      rho -= delta;
    }
    if (count >= max_panels) break;
  }
  *out_count = count;
  return 0;
}
