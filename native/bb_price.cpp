// Exact committee-pricing oracle: type-reduced branch-and-bound.
//
// The LEXIMIN column-generation certification step must solve, exactly,
//
//     max  Σᵢ yᵢ xᵢ   s.t.  Σᵢ xᵢ = k,  lo_f ≤ Σ_{i∈f} xᵢ ≤ hi_f  ∀f,
//          x ∈ {0,1}ⁿ
//
// (the reference prices with a Gurobi/CBC ILP over n binary variables,
// leximin.py:190-233,420-424). Key structural fact: agents with identical
// feature vectors ("types") are interchangeable up to their weights, and
// within a type an optimal solution always takes the heaviest members. The
// ILP therefore collapses to choosing a COUNT c_t per type:
//
//     max  Σ_t v_t(c_t)   s.t.  Σ_t c_t = k,
//          lo_f ≤ Σ_{t: type t has feature f} c_t ≤ hi_f,
//          0 ≤ c_t ≤ m_t,
//
// where v_t(c) = sum of the c largest weights in type t — concave in c.
// Real pools have FAR fewer types than agents (each agent has one feature
// per category), so this is a tiny integer program. We solve it with
// depth-first branch-and-bound:
//
//   * bound: for each category, the single-category relaxation (choose
//     per-feature counts within that category's quotas only) is solved
//     EXACTLY by greedy marginal allocation — all per-feature value
//     functions are concave, so picking the globally largest remaining
//     marginal weight is optimal. The min over categories is a valid upper
//     bound for the full problem.
//   * branching: on the count of the next type in weight order; children
//     enumerated greedily (largest count first, which tends to hit good
//     incumbents early).
//   * incumbent: the caller seeds the search with the best panel value its
//     stochastic (TPU-side) pricer found, so certification usually reduces
//     to pure pruning.
//
// Exposed as a flat C ABI for ctypes. Single-threaded, no allocations
// outside setup. Returns certified-optimal counts per type.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Problem {
  int T = 0;          // number of types
  int n_cats = 0;     // number of categories
  int F = 0;          // total number of feature cells
  int k = 0;          // committee size
  const int32_t* type_feature = nullptr;  // [T * n_cats] global feature id per category
  const int32_t* msize = nullptr;         // [T] type sizes
  const double* prefix = nullptr;         // [T * (maxm+1)] prefix sums of sorted-desc weights
  int maxm = 0;
  const int32_t* lo = nullptr;  // [F]
  const int32_t* hi = nullptr;  // [F]

  double value(int t, int c) const { return prefix[size_t(t) * (maxm + 1) + c]; }
  double marginal(int t, int c) const {  // weight of the (c+1)-th member of type t
    return value(t, c + 1) - value(t, c);
  }
};

struct SearchState {
  std::vector<int> counts;      // [T] chosen counts for types < depth
  std::vector<int> feat_used;   // [F] committee members already committed per feature
  int chosen = 0;               // Σ counts
  double val = 0.0;             // Σ v_t(counts_t)
};

// Greedy single-category bound. For category `cat`, relax every constraint
// outside it: remaining members may be drawn from any not-yet-branched type,
// subject only to this category's per-feature windows. Returns an upper bound
// on the best completion value, or -inf if even this relaxation is
// infeasible. Exact because every per-feature pooled value function is
// concave (merge of sorted lists).
double category_bound(const Problem& P, const SearchState& s, int depth,
                      int cat, std::vector<std::vector<double>>& pool_scratch,
                      std::vector<int>& feat_of_pool) {
  const int rem = P.k - s.chosen;
  // pool the marginal weights of un-branched types by their feature in `cat`
  for (auto& v : pool_scratch) v.clear();
  feat_of_pool.clear();
  // collect features of this category present among remaining types
  // (feature ids are global; category membership given by type_feature)
  // map: global feature id -> slot in pool_scratch
  static thread_local std::vector<int> slot;
  slot.assign(P.F, -1);
  int nslots = 0;
  for (int t = depth; t < P.T; ++t) {
    int f = P.type_feature[size_t(t) * P.n_cats + cat];
    if (slot[f] < 0) {
      slot[f] = nslots++;
      if ((int)pool_scratch.size() < nslots) pool_scratch.emplace_back();
      pool_scratch[nslots - 1].clear();
      feat_of_pool.push_back(f);
    }
    auto& pool = pool_scratch[slot[f]];
    for (int c = 0; c < P.msize[t]; ++c) pool.push_back(P.marginal(t, c));
  }
  for (int sidx = 0; sidx < nslots; ++sidx)
    std::sort(pool_scratch[sidx].begin(), pool_scratch[sidx].end(),
              std::greater<double>());

  // per-feature windows for the remaining picks in this category
  // NOTE: features of `cat` NOT present among remaining types still must have
  // feat_used within [lo, hi] eventually; if lo not yet met and no remaining
  // member can supply it, the node is infeasible. Detect via a pass over all
  // features of this category: we only know this category's features through
  // types; a feature with unmet lo and zero pool is infeasible.
  // (Features of other categories are ignored here by design.)
  long long min_total = 0;
  std::vector<int> need(nslots), cap(nslots);
  for (int sidx = 0; sidx < nslots; ++sidx) {
    int f = feat_of_pool[sidx];
    int used = s.feat_used[f];
    int pool_sz = (int)pool_scratch[sidx].size();
    need[sidx] = std::max(0, P.lo[f] - used);
    cap[sidx] = std::min(P.hi[f] - used, pool_sz);
    if (cap[sidx] < 0 || need[sidx] > cap[sidx]) return -HUGE_VAL;
    min_total += need[sidx];
  }
  // any feature of this category entirely absent from the remaining pool but
  // with unmet lower quota makes completion impossible — detected by the
  // caller via the all-features check (cheap), skipped here.
  if (min_total > rem) return -HUGE_VAL;
  long long max_total = 0;
  for (int sidx = 0; sidx < nslots; ++sidx) max_total += cap[sidx];
  if (max_total < rem) return -HUGE_VAL;

  // mandatory minima first, then best marginals up to rem
  double bound = 0.0;
  int taken_total = 0;
  std::vector<int> taken(nslots, 0);
  for (int sidx = 0; sidx < nslots; ++sidx) {
    for (int j = 0; j < need[sidx]; ++j) bound += pool_scratch[sidx][j];
    taken[sidx] = need[sidx];
    taken_total += need[sidx];
  }
  // greedy: repeatedly take the best next marginal among features with
  // spare capacity (heap-free k-way pass; rem is small)
  while (taken_total < rem) {
    int best_s = -1;
    double best_w = -HUGE_VAL;
    for (int sidx = 0; sidx < nslots; ++sidx) {
      if (taken[sidx] < cap[sidx]) {
        double w = pool_scratch[sidx][taken[sidx]];
        if (w > best_w) { best_w = w; best_s = sidx; }
      }
    }
    if (best_s < 0) return -HUGE_VAL;  // cannot reach k
    bound += best_w;
    ++taken[best_s];
    ++taken_total;
  }
  return bound;
}

struct Searcher {
  const Problem& P;
  std::vector<int> best_counts;
  double best_val;
  long long nodes = 0;
  long long max_nodes;
  bool aborted = false;
  std::vector<std::vector<double>> pool_scratch;
  std::vector<int> feat_of_pool;

  Searcher(const Problem& p, double incumbent, long long mn)
      : P(p), best_counts(p.T, 0), best_val(incumbent), max_nodes(mn) {}

  // quick global feasibility screen on lower quotas: every feature's unmet
  // lower quota must be suppliable by remaining types
  bool lower_quotas_reachable(const SearchState& s, int depth) {
    static thread_local std::vector<long long> avail;
    avail.assign(P.F, 0);
    for (int t = depth; t < P.T; ++t)
      for (int c = 0; c < P.n_cats; ++c)
        avail[P.type_feature[size_t(t) * P.n_cats + c]] += P.msize[t];
    for (int f = 0; f < P.F; ++f)
      if (s.feat_used[f] + avail[f] < P.lo[f]) return false;
    return true;
  }

  double bound(const SearchState& s, int depth) {
    double b = HUGE_VAL;
    for (int cat = 0; cat < P.n_cats; ++cat) {
      double cb = category_bound(P, s, depth, cat, pool_scratch, feat_of_pool);
      if (cb == -HUGE_VAL) return -HUGE_VAL;
      b = std::min(b, cb);
      if (s.val + b <= best_val + 1e-12) break;  // already pruned
    }
    return b;
  }

  void dfs(SearchState& s, int depth) {
    if (aborted) return;
    if (++nodes > max_nodes) { aborted = true; return; }
    if (s.chosen == P.k) {
      // all features' lower quotas must be met exactly now
      for (int f = 0; f < P.F; ++f)
        if (s.feat_used[f] < P.lo[f]) return;
      if (s.val > best_val + 1e-12) {
        best_val = s.val;
        std::copy(s.counts.begin(), s.counts.end(), best_counts.begin());
      }
      return;
    }
    if (depth >= P.T) return;
    if (!lower_quotas_reachable(s, depth)) return;
    double ub = bound(s, depth);
    if (s.val + ub <= best_val + 1e-12) return;

    // feasible count window for this type from its own features' headroom
    int t = depth;
    int cmax = std::min(P.msize[t], P.k - s.chosen);
    for (int c = 0; c < P.n_cats; ++c) {
      int f = P.type_feature[size_t(t) * P.n_cats + c];
      cmax = std::min(cmax, P.hi[f] - s.feat_used[f]);
    }
    // enumerate counts, largest first (concave v_t ⇒ big counts carry the
    // heaviest prefix sums; good incumbents early)
    for (int c = cmax; c >= 0; --c) {
      s.counts[t] = c;
      s.chosen += c;
      s.val += P.value(t, c);
      for (int cc = 0; cc < P.n_cats; ++cc)
        s.feat_used[P.type_feature[size_t(t) * P.n_cats + cc]] += c;
      dfs(s, depth + 1);
      for (int cc = 0; cc < P.n_cats; ++cc)
        s.feat_used[P.type_feature[size_t(t) * P.n_cats + cc]] -= c;
      s.val -= P.value(t, c);
      s.chosen -= c;
      s.counts[t] = 0;
      if (aborted) return;
    }
  }
};

}  // namespace

extern "C" {

// Returns 0 = certified optimal, 1 = infeasible (no committee at all),
// 2 = node limit hit (result not certified), 3 = bad arguments.
// `incumbent` seeds the lower bound; pass -1e300 for none. If the search
// cannot beat the incumbent, out_value is the incumbent and out_counts is
// all -1 (meaning: keep the caller's incumbent panel).
int bb_price(int T, int n_cats, int F, const int32_t* type_feature,
             const int32_t* msize, const double* prefix, int maxm,
             const int32_t* lo, const int32_t* hi, int k, double incumbent,
             int64_t max_nodes, int32_t* out_counts, double* out_value,
             int64_t* out_nodes) {
  if (T <= 0 || n_cats <= 0 || F <= 0 || k < 0 || maxm < 0) return 3;
  Problem P;
  P.T = T; P.n_cats = n_cats; P.F = F; P.k = k;
  P.type_feature = type_feature; P.msize = msize; P.prefix = prefix;
  P.maxm = maxm; P.lo = lo; P.hi = hi;

  Searcher search(P, incumbent > -1e299 ? incumbent : -HUGE_VAL,
                  max_nodes > 0 ? max_nodes : (1LL << 62));
  SearchState s;
  s.counts.assign(T, 0);
  s.feat_used.assign(F, 0);
  search.dfs(s, 0);

  *out_nodes = search.nodes;
  if (search.aborted) return 2;
  bool improved = search.best_val > (incumbent > -1e299 ? incumbent : -HUGE_VAL);
  // re-run detection: best_counts only valid if some full assignment beat the
  // initial incumbent
  if (!improved) {
    if (incumbent > -1e299) {
      for (int t = 0; t < T; ++t) out_counts[t] = -1;
      *out_value = incumbent;
      return 0;  // incumbent certified optimal
    }
    return 1;  // no feasible committee found
  }
  std::copy(search.best_counts.begin(), search.best_counts.end(), out_counts);
  *out_value = search.best_val;
  return 0;
}

}  // extern "C"
