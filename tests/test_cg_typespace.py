"""Composition-space column generation (`solvers/cg_typespace.py`): oracle
exactness, relaxation bounds, two-sided decomposition, and end-to-end
equivalence with the enumerated type-space path."""

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import cross_product_instance, random_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.solvers.cg_typespace import (
    CompositionOracle,
    _decomp_lp,
    _leximin_relaxation,
    _relaxation_bound,
    _round_relaxation,
)
from citizensassemblies_tpu.solvers.compositions import enumerate_compositions
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.config import default_config


@pytest.fixture(scope="module")
def midsize():
    inst = random_instance(n=60, k=10, n_categories=2, features_per_category=3, seed=5)
    dense, space = featurize(inst)
    return dense, space, TypeReduction(dense)


def test_oracle_matches_enumeration_max(midsize):
    dense, _, red = midsize
    comps = enumerate_compositions(red, cap=500_000)
    assert comps is not None and len(comps)
    oracle = CompositionOracle(red)
    rng = np.random.default_rng(0)
    M = comps.astype(float)
    for _ in range(5):
        w = rng.normal(size=red.T)  # mixed-sign weights (two-sided pricing)
        comp, value = oracle.maximize(w)
        brute = float((M @ w).max())
        assert value == pytest.approx(brute, abs=1e-9)
        assert comp.sum() == red.k


def test_oracle_forced_type(midsize):
    _, _, red = midsize
    oracle = CompositionOracle(red)
    for t in range(0, red.T, max(1, red.T // 5)):
        got = oracle.maximize(np.zeros(red.T), forced_type=t)
        if got is not None:
            assert got[0][t] >= 1


def test_relaxation_bound_dominates_compositions(midsize):
    """Every integer composition lies inside the relaxation polytope, so the
    stage bound must weakly exceed the best single-composition min value."""
    _, _, red = midsize
    comps = enumerate_compositions(red, cap=500_000)
    z_ub, x_star = _relaxation_bound(red, np.full(red.T, -1.0))
    m = red.msize.astype(float)
    best_single = max(float((c / m).min()) for c in comps)
    assert z_ub >= best_single - 1e-9
    assert x_star.sum() == pytest.approx(red.k, abs=1e-6)


def test_round_relaxation_feasible(midsize):
    _, _, red = midsize
    _, x_star = _relaxation_bound(red, np.full(red.T, -1.0))
    rng = np.random.default_rng(1)
    rounded = _round_relaxation(x_star, red, rng, count=64)
    assert rounded, "at least some roundings must be quota-feasible"
    tf = np.zeros((red.T, red.F), dtype=np.int64)
    for t in range(red.T):
        tf[t, red.type_feature[t]] = 1
    for c in rounded:
        assert c.sum() == red.k
        counts = c @ tf
        assert np.all(counts >= red.qmin) and np.all(counts <= red.qmax)


def test_relaxation_leximin_matches_enumerated_values(midsize):
    """On an instance where the relaxation profile is realizable, its leximin
    values equal the enumerated (exact) type values."""
    dense, space, red = midsize
    v, _ = _leximin_relaxation(red)
    dist = find_distribution_leximin(dense, space)  # enumerated path if viable
    # per-type values from the exact run
    got = np.array([dist.fixed_probabilities[red.members[t][0]] for t in range(red.T)])
    assert np.max(np.abs(np.sort(v) - np.sort(got))) <= 5e-4 + 1e-6


def test_decomp_lp_two_sided_bounds():
    """The two-sided master's ε bounds max |Mp − v|, including overshoot."""
    rng = np.random.default_rng(2)
    T, C = 6, 40
    comps = rng.integers(0, 4, size=(C, T)).astype(np.int32)
    msize = np.full(T, 4.0)
    M = comps / msize[None, :]
    v = (np.full(C, 1.0 / C) @ M)  # realizable target
    eps, w, mu, p = _decomp_lp(np.ascontiguousarray(M.T), v)
    dev = np.max(np.abs(p @ M - v))
    assert dev <= eps + 1e-6
    assert eps <= 1e-6  # v is realizable by construction


def test_cg_end_to_end_matches_enumeration():
    inst = cross_product_instance(
        ["g", "l"],
        [["a", "b"], ["x", "y"]],
        [[(4, 12), (4, 12)], [(2, 12), (2, 12)]],
        [40, 5, 3, 12],
        k=12,
        name="skew",
    )
    dense, space = featurize(inst)
    d_cg = find_distribution_leximin(
        dense, space, cfg=default_config().replace(enum_max_types=0)
    )
    d_en = find_distribution_leximin(dense, space)
    assert np.max(np.abs(d_cg.allocation - d_en.allocation)) <= 1e-4


def test_cg_heterogeneous_matches_enumeration():
    """Skewed quotas (decoupled from pool shares) give a strongly
    heterogeneous leximin profile — the multi-stage relaxation + decomposition
    must still match the exact enumerated path."""
    from citizensassemblies_tpu.core.generator import skewed_instance

    inst = skewed_instance(n=80, k=14, n_categories=2, features_per_category=[3, 4], seed=3)
    dense, space = featurize(inst)
    d_en = find_distribution_leximin(
        dense,
        space,
        cfg=default_config().replace(
            enum_max_types=64, enum_cap=2_000_000, enum_node_budget=80_000_000
        ),
    )
    d_cg = find_distribution_leximin(
        dense, space, cfg=default_config().replace(enum_max_types=0)
    )
    spread = float(d_en.allocation.max() - d_en.allocation.min())
    assert spread > 0.3, "instance must actually be heterogeneous"
    assert np.max(np.abs(d_cg.allocation - d_en.allocation)) <= 1e-4


def test_neighbor_columns_feasible_beyond_word_width():
    """The face expansion's move screen on an instance with F > 64 (the
    household quotient's augmented incidence): every emitted column must
    still satisfy all quotas and Σc = k. Pins the hybrid screen — word
    bitmask for base categories, direct gather for the class category —
    that replaced the all-gather fallback (62 s of a 130 s n=1200 household
    decomposition)."""
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.solvers.face_decompose import neighbor_columns
    from citizensassemblies_tpu.solvers.quotient import build_household_quotient

    inst = skewed_instance(n=240, k=16, n_categories=3, seed=7,
                           features_per_category=[3, 3, 3])
    dense, _ = featurize(inst)
    hh = (np.arange(240) // 2).astype(np.int32)
    q = build_household_quotient(dense, hh)
    red = TypeReduction(q.dense_aug)
    assert red.F > 64  # the regime under test

    # feasible seed compositions straight from the exact oracle
    oracle = CompositionOracle(red)
    rng = np.random.default_rng(1)
    comps = []
    for _ in range(12):
        got = oracle.maximize(rng.normal(0, 1.0, red.T))
        if got is not None:
            comps.append(got[0])
    comps = np.stack(comps).astype(np.int16)
    out = neighbor_columns(comps, red, rng.normal(0, 1e-3, red.T))
    assert out.shape[0] > 0  # the screen admits genuine moves
    tf = np.zeros((red.T, red.F), dtype=np.int64)
    for t in range(red.T):
        tf[t, red.type_feature[t]] = 1
    counts = out.astype(np.int64) @ tf
    assert np.all(out.sum(axis=1) == red.k)
    assert np.all(counts >= red.qmin[None, :])
    assert np.all(counts <= red.qmax[None, :])
    assert np.all(out >= 0) and np.all(out <= red.msize[None, :])


def test_stalled_band_accepts_instead_of_stage_cg():
    """A face residual above decomp_accept but inside the stalled band is
    accepted (stages == 0 — no stage-CG fallback) and the end-to-end
    allocation still honors the 1e-3 contract: the panel tolerance is
    budgeted against the mixture ε from the config knobs."""
    import numpy as np

    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.utils.config import default_config

    inst = skewed_instance(n=250, k=25, n_categories=4, seed=5, skew=0.9)
    dense, space = featurize(inst)
    # an unreachable soft target forces the face loop to stall; the stalled
    # band must then accept the best residual rather than paying stage CG
    cfg = default_config().replace(decomp_accept=1e-12, decomp_max_rounds=8)
    dist = find_distribution_leximin(dense, space, cfg=cfg)
    dev = float(np.abs(dist.allocation - dist.fixed_probabilities).max())
    assert dev <= 1e-3, dev
    assert any(
        "stalled-band" in line or "profile realized" in line
        for line in dist.output_lines
    )
