"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Distributed code paths (shard_map Monte-Carlo, sharded LP matvecs) run in CI
without TPU hardware on 8 virtual CPU devices, per the multi-chip test strategy
in SURVEY.md §4.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# The session environment may pin JAX_PLATFORMS to a TPU tunnel (e.g. "axon");
# tests must run on the virtual CPU mesh, so override unconditionally. The
# tunnel plugin's sitecustomize hook re-forces its own platform via
# ``jax.config.update`` at interpreter start, so the env var alone is not
# enough — reset the *config* too, before any backend is materialized
# (backend construction is lazy, so this prevents the tunnel client from ever
# being created; with a hung tunnel that client blocks forever).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Post-mortem hook for in-process hangs: `kill -USR1 <pid>` dumps every
# thread's Python stack to stderr without killing the run. Motivated by two
# observed livelocks (98 % CPU, ≥55 min, no progress) of RUN_SLOW
# certification tests inside a jitted CPU-mesh execution that completes in
# minutes standalone — an XLA-CPU runtime flake this hook lets us attribute
# next time instead of losing the evidence to a blind SIGINT.
import faulthandler
import signal

if hasattr(signal, "SIGUSR1"):  # POSIX-only debug hook
    faulthandler.register(signal.SIGUSR1, all_threads=True)

from pathlib import Path

import pytest

REFERENCE_DATA = Path("/root/reference/data")


@pytest.fixture(scope="session")
def reference_data_dir():
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference data not mounted")
    return REFERENCE_DATA


@pytest.fixture(scope="session")
def example_small(reference_data_dir):
    from citizensassemblies_tpu.core.instance import read_instance_dir

    return read_instance_dir(reference_data_dir / "example_small_20")


@pytest.fixture(scope="session")
def example_large(reference_data_dir):
    from citizensassemblies_tpu.core.instance import read_instance_dir

    return read_instance_dir(reference_data_dir / "example_large_200")
