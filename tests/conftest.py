"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Distributed code paths (shard_map Monte-Carlo, sharded LP matvecs) run in CI
without TPU hardware on 8 virtual CPU devices, per the multi-chip test strategy
in SURVEY.md §4.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
# The session environment may pin JAX_PLATFORMS to a TPU tunnel (e.g. "axon");
# tests must run on the virtual CPU mesh, so override unconditionally. The
# tunnel plugin's sitecustomize hook re-forces its own platform via
# ``jax.config.update`` at interpreter start, so the env var alone is not
# enough — reset the *config* too, before any backend is materialized
# (backend construction is lazy, so this prevents the tunnel client from ever
# being created; with a hung tunnel that client blocks forever).
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Post-mortem hook for in-process hangs: `kill -USR1 <pid>` dumps every
# thread's Python stack to stderr without killing the run. Motivated by two
# observed livelocks (98 % CPU, ≥55 min, no progress) of RUN_SLOW
# certification tests inside a jitted CPU-mesh execution that completes in
# minutes standalone — an XLA-CPU runtime flake this hook lets us attribute
# next time instead of losing the evidence to a blind SIGINT.
import faulthandler
import signal

if hasattr(signal, "SIGUSR1"):  # POSIX-only debug hook
    faulthandler.register(signal.SIGUSR1, all_threads=True)

import functools
import subprocess
import sys
from pathlib import Path

import pytest

REFERENCE_DATA = Path("/root/reference/data")

#: child-process marker for :func:`subprocess_isolated` — when set, the
#: wrapped test body executes normally (we ARE the isolated process)
_ISOLATED_ENV = "CA_TPU_ISOLATED_TEST"


def subprocess_isolated(timeout_s: float = 3600.0):
    """Run the decorated test in its OWN pytest subprocess.

    Motivation (VERDICT r5 weak #2): two RUN_SLOW certification tests were
    observed to livelock (98 % CPU, ≥55 min, no progress) inside a jitted
    CPU-mesh execution when run after other tests in one process, while
    completing in minutes standalone — an XLA-CPU runtime interaction that a
    shared process cannot defend against. fork() after JAX has initialized is
    unsafe (XLA's thread pools don't survive it), so isolation is a fresh
    interpreter: the parent re-invokes pytest on this one node id with a hard
    timeout, and the child — marked via the environment — runs the body
    normally (fixtures such as ``monkeypatch`` apply inside the child). A
    timeout or failure in the child fails the parent test with the child's
    output tail, so a livelock now costs ``timeout_s`` instead of the whole
    evidence session.
    """

    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if os.environ.get(_ISOLATED_ENV) == "1":
                return fn(*args, **kwargs)
            nodeid = f"tests/{Path(fn.__code__.co_filename).name}::{fn.__name__}"
            env = dict(os.environ)
            env[_ISOLATED_ENV] = "1"
            env.setdefault("PALLAS_AXON_POOL_IPS", "")
            try:
                res = subprocess.run(
                    [
                        sys.executable, "-m", "pytest", nodeid, "-x", "-q",
                        "-p", "no:cacheprovider", "-p", "no:randomly",
                    ],
                    cwd=str(Path(__file__).resolve().parent.parent),
                    env=env,
                    capture_output=True,
                    text=True,
                    timeout=timeout_s,
                )
            except subprocess.TimeoutExpired as exc:
                tail = ((exc.stdout or "") + "\n" + (exc.stderr or ""))[-2000:]
                pytest.fail(
                    f"isolated run of {nodeid} exceeded {timeout_s:.0f}s "
                    f"(the livelock guard). Output tail:\n{tail}",
                    pytrace=False,
                )
            if res.returncode != 0:
                tail = (res.stdout + "\n" + res.stderr)[-2000:]
                pytest.fail(
                    f"isolated run of {nodeid} failed "
                    f"(rc={res.returncode}). Output tail:\n{tail}",
                    pytrace=False,
                )

        return wrapper

    return decorate


@pytest.fixture(scope="session")
def reference_data_dir():
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference data not mounted")
    return REFERENCE_DATA


@pytest.fixture(scope="session")
def example_small(reference_data_dir):
    from citizensassemblies_tpu.core.instance import read_instance_dir

    return read_instance_dir(reference_data_dir / "example_small_20")


@pytest.fixture(scope="session")
def example_large(reference_data_dir):
    from citizensassemblies_tpu.core.instance import read_instance_dir

    return read_instance_dir(reference_data_dir / "example_large_200")
