"""Type-space enumeration, stage-LP leximin, and exact panel decomposition
(``solvers/compositions.py``) — the fast path behind ``find_distribution_leximin``
for instances with few distinct agent types."""

import itertools

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import cross_product_instance, random_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.solvers.compositions import (
    decompose_with_pricing,
    enumerate_compositions,
    expand_compositions,
    greedy_decompose,
    leximin_over_compositions,
)
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction


def _brute_compositions(red):
    """All feasible compositions by direct product enumeration."""
    out = []
    ranges = [range(int(m) + 1) for m in red.msize]
    for c in itertools.product(*ranges):
        if sum(c) != red.k:
            continue
        counts = np.zeros(red.F, dtype=int)
        for t, ct in enumerate(c):
            counts[red.type_feature[t]] += ct
        if np.all(counts >= red.qmin) and np.all(counts <= red.qmax):
            out.append(c)
    return sorted(out)


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_enumeration_matches_bruteforce(seed):
    inst = random_instance(n=14, k=4, n_categories=2, features_per_category=2, seed=seed)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    assert comps is not None
    got = sorted(tuple(int(x) for x in row) for row in comps)
    assert got == _brute_compositions(red)


def test_enumeration_cap_returns_none():
    inst = random_instance(n=64, k=20, n_categories=2, features_per_category=2, seed=1)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    assert enumerate_compositions(red, cap=3) is None


def _large_like():
    return cross_product_instance(
        categories=["gender", "leaning"],
        features=[["female", "male"], ["liberal", "conservative"]],
        quotas=[[(99, 200), (99, 200)], [(99, 200), (99, 200)]],
        counts=[999, 1, 0, 1000],
        k=200,
        name="example_large_200_like",
    )


def test_typespace_leximin_large_like_uniform():
    """The skewed example_large-shaped pool still admits the uniform k/n
    allocation (min prob 10.0%, the reference's golden value), and the stage
    LPs find it exactly."""
    dense, _ = featurize(_large_like())
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    ts = leximin_over_compositions(comps, red.msize)
    assert ts.type_values == pytest.approx([0.1, 0.1, 0.1], abs=1e-9)
    # distribution realizes the targets
    M = comps / red.msize[None, :]
    np.testing.assert_allclose(ts.probabilities @ M, ts.type_values, atol=1e-8)


def test_greedy_decompose_near_exact_large_like():
    dense, _ = featurize(_large_like())
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    ts = leximin_over_compositions(comps, red.msize)
    targets = ts.type_values[red.type_id]
    P, q = greedy_decompose(comps, ts.probabilities, red, targets)
    assert q.sum() == pytest.approx(1.0, abs=1e-9)
    # greedy alone may strand a ~1e-6 residual on a few agents; the pricing
    # CG wrapper below removes it (that pairing is the shipped pipeline)
    np.testing.assert_allclose(P.T.astype(float) @ q, targets, atol=1e-5)
    # every panel quota-feasible
    counts = P.astype(np.int64) @ np.asarray(dense.A)
    assert np.all(counts >= np.asarray(dense.qmin)[None, :])
    assert np.all(counts <= np.asarray(dense.qmax)[None, :])
    assert np.all(P.sum(axis=1) == dense.k)


def test_decompose_with_pricing_exact_large_like():
    dense, _ = featurize(_large_like())
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    ts = leximin_over_compositions(comps, red.msize)
    targets = ts.type_values[red.type_id]
    P, q, eps = decompose_with_pricing(comps, ts.probabilities, red, targets)
    assert eps <= 1e-8
    assert np.all(P.T.astype(float) @ q >= targets - 1e-8)


@pytest.mark.parametrize("seed", [2, 5])
def test_decompose_with_pricing_random(seed):
    inst = random_instance(n=40, k=8, n_categories=2, features_per_category=2, seed=seed)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    assert comps is not None and len(comps) > 0
    ts = leximin_over_compositions(comps, red.msize)
    targets = ts.type_values[red.type_id]
    P, q, eps = decompose_with_pricing(comps, ts.probabilities, red, targets)
    assert eps <= 1e-8
    alloc = P.T.astype(float) @ q
    assert np.all(alloc >= targets - 1e-8)


def test_expand_compositions_exact_lcm_path():
    """Tiny sizes take the exact LCM rotation path: per-agent allocation is
    exactly c_t/m_t-weighted."""
    inst = random_instance(n=12, k=4, n_categories=2, features_per_category=2, seed=9)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    comps = enumerate_compositions(red)
    ts = leximin_over_compositions(comps, red.msize)
    P, q = expand_compositions(comps, ts.probabilities, red, budget=4096)
    M = comps / red.msize[None, :]
    target = (ts.probabilities @ M)[red.type_id]
    np.testing.assert_allclose(P.T.astype(float) @ q, target, atol=1e-9)


def test_native_slicer_matches_python_reference():
    """native/slicer.cpp must reproduce the Python water-filling loop
    bit-for-bit (same sort keys, cursors, overshoot rule), with and without
    household disjointness."""
    import numpy as np

    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.solvers.compositions import greedy_decompose
    from citizensassemblies_tpu.solvers.native_oracle import (
        TypeReduction,
        greedy_decompose_native,
        _load_slicer,
    )

    if _load_slicer() is None:
        import pytest

        pytest.skip("native slicer unavailable (no toolchain)")

    rng = np.random.default_rng(0)
    inst = skewed_instance(n=80, k=12, n_categories=3, seed=9,
                           features_per_category=[2, 3, 2])
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    # random feasible-ish compositions: project a random point to counts
    S = 12
    comps = np.zeros((S, red.T), dtype=np.int32)
    for s in range(S):
        w = rng.dirichlet(np.ones(red.T)) * red.k
        c = np.minimum(np.floor(w).astype(np.int32), red.msize)
        gap = red.k - c.sum()
        t = 0
        while gap > 0:
            if c[t % red.T] < red.msize[t % red.T]:
                c[t % red.T] += 1
                gap -= 1
            t += 1
        comps[s] = c
    probs = rng.dirichlet(np.ones(S))

    def check_equivalence(reduction, comps_c, probs_c, hh):
        targets = (probs_c @ (comps_c / reduction.msize[None, :]))[
            reduction.type_id
        ]
        order = np.argsort(-probs_c)
        per_type_need = np.array(
            [targets[reduction.members[t][0]] for t in range(reduction.T)]
        )
        native = greedy_decompose_native(
            reduction, comps_c[order], probs_c[order] / probs_c.sum(),
            per_type_need, max_panels=4096, households=hh,
        )
        assert native is not None
        # force the Python reference path
        import citizensassemblies_tpu.solvers.native_oracle as no_mod

        saved = no_mod.greedy_decompose_native
        no_mod.greedy_decompose_native = lambda *a, **k: None
        try:
            py = greedy_decompose(comps_c, probs_c, reduction, targets,
                                  max_panels=4096, households=hh)
        finally:
            no_mod.greedy_decompose_native = saved
        np.testing.assert_array_equal(native[0], py[0])
        np.testing.assert_allclose(native[1], py[1], rtol=0, atol=1e-15)

    check_equivalence(red, comps, probs, None)

    # household case: compositions must satisfy the quotient's class caps —
    # take orbit counts of actual household-disjoint sampler draws on the
    # augmented instance (guaranteed feasible by construction)
    import jax.random as jr

    from citizensassemblies_tpu.models.legacy import sample_panels_batch
    from citizensassemblies_tpu.solvers.quotient import build_household_quotient

    hh = (np.arange(80) // 2).astype(np.int32)
    quotient = build_household_quotient(dense, hh)
    red_q = TypeReduction(quotient.dense_aug)
    panels, ok = sample_panels_batch(dense, jr.PRNGKey(3), 64, households=hh)
    panels = np.asarray(panels)[np.asarray(ok)]
    seen_c = set()
    rows_c = []
    for pan in panels:
        counts = np.bincount(red_q.type_id[pan], minlength=red_q.T)
        kb = counts.tobytes()
        if kb not in seen_c:
            seen_c.add(kb)
            rows_c.append(counts.astype(np.int32))
    comps_q = np.stack(rows_c[:10], axis=0)
    probs_q = rng.dirichlet(np.ones(len(comps_q)))
    check_equivalence(red_q, comps_q, probs_q, quotient.households)
