"""Independent at-scale exactness evidence (VERDICT r1 items #2 and #6).

The production LEXIMIN path is the type-space solver (probe-certified
relaxation + face decomposition). These tests cross-check it against the
*agent-space* HiGHS-certified column-generation path — forced by passing
singleton households, which disables the type collapse without changing the
problem (≤1-per-household rows over singletons are vacuous) — the role
Gurobi's dual-gap certificate plays for the reference
(``/root/reference/leximin.py:429-431``).
"""

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import random_instance, skewed_instance
from citizensassemblies_tpu.core.instance import Instance, featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.ops.stats import prob_allocation_stats


def _mass24_shaped(seed: int = 3) -> Instance:
    """A mass_24-shaped instance: n=70, k=24, 5 categories, with two
    categories fully pinned (min = max on every cell) — the degenerate/tight
    regime SURVEY §7 flags as a top risk (the real mass pool is withheld;
    shape from ``reference_output/mass_24_statistics.txt:2-4``)."""
    base = random_instance(
        n=70, k=24, n_categories=5, features_per_category=[2, 3, 2, 3, 2],
        seed=seed, name="mass24_shaped",
    )
    cats = {}
    for ci, (cat, feats) in enumerate(base.categories.items()):
        names = list(feats)
        counts = np.array(
            [sum(1 for a in base.agents if a[cat] == f) for f in names], float
        )
        if ci < 2:
            # pin to the proportional integer composition: min = max
            exact = np.floor(counts / 70.0 * 24.0).astype(int)
            order = np.argsort(-(counts / 70.0 * 24.0 - exact))
            for j in order[: 24 - exact.sum()]:
                exact[j] += 1
            cats[cat] = {f: (int(c), int(c)) for f, c in zip(names, exact)}
        else:
            cats[cat] = feats
    import dataclasses

    return dataclasses.replace(base, categories=cats)


def test_mass24_shaped_tight_quotas_full_stack():
    """min=max cells through the full type-space solver stack, cross-checked
    against the agent-space HiGHS-certified CG."""
    inst = _mass24_shaped()
    dense, space = featurize(inst)
    qmin = dense.qmin_np
    qmax = dense.qmax_np
    assert int((qmin == qmax).sum()) >= 5  # genuinely tight cells

    ts = find_distribution_leximin(dense, space)
    # every support panel satisfies every quota exactly
    for row, p in zip(ts.committees, ts.probabilities):
        if p <= 1e-11:
            continue
        counts = dense.A_np[row].sum(axis=0)
        assert np.all(counts >= qmin) and np.all(counts <= qmax)
    assert ts.allocation.sum() == pytest.approx(24.0, abs=1e-6)

    ag = find_distribution_leximin(dense, space, households=np.arange(70))
    # allocations agree as distributions (agents are type-interchangeable, so
    # compare the sorted profiles)
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )
    s_ts = prob_allocation_stats(ts.allocation, cap_for_geometric_mean=False)
    s_ag = prob_allocation_stats(ag.allocation, cap_for_geometric_mean=False)
    assert s_ts.min == pytest.approx(s_ag.min, abs=1e-3)
    assert s_ts.gini == pytest.approx(s_ag.gini, abs=5e-3)


def test_skewed_midsize_matches_agent_space_certified():
    """Heterogeneous-regime cross-check at mid size: the type-space result
    matches the agent-space HiGHS-certified CG within tolerance (VERDICT r1
    #2a, extending the n=40 cross-check upward)."""
    inst = skewed_instance(n=120, k=12, n_categories=3, seed=1)
    dense, space = featurize(inst)
    ts = find_distribution_leximin(dense, space)
    ag = find_distribution_leximin(dense, space, households=np.arange(120))
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )


def test_skewed_n400_matches_agent_space_certified():
    """sf_d/cca-shaped heterogeneous cross-check at n=400, k=40, 6 categories
    (VERDICT r2 item #2a): the production type-space solver matches the
    agent-space HiGHS-certified CG within 1e-3, and the solver-independent
    maximin audit (the post-hoc role of Gurobi's per-run dual-gap
    certificate, ``/root/reference/leximin.py:429-431``) certifies the first
    leximin level."""
    from citizensassemblies_tpu.solvers.highs_backend import audit_maximin

    inst = skewed_instance(
        n=400, k=40, n_categories=6, seed=2,
        features_per_category=[2, 3, 4, 2, 3, 3],
    )
    dense, space = featurize(inst)
    ts = find_distribution_leximin(dense, space)
    ag = find_distribution_leximin(dense, space, households=np.arange(400))
    # agents are type-interchangeable, so compare the sorted profiles
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )
    audit = audit_maximin(dense, ts.allocation, ts.covered)
    assert audit["maximin_gap"] <= 1e-3, audit
    assert audit["certified_maximin_upper"] >= audit["achieved_min"] - 1e-9
