"""Independent at-scale exactness evidence (VERDICT r1 items #2 and #6).

The production LEXIMIN path is the type-space solver (probe-certified
relaxation + face decomposition). These tests cross-check it against the
*agent-space* HiGHS-certified column-generation path — forced explicitly via
``force_agent_space`` (singleton households no longer disable the type
collapse: the household quotient recognizes them as trivial classes) — the
role Gurobi's dual-gap certificate plays for the reference
(``/root/reference/leximin.py:429-431``).
"""

import os

import numpy as np
import pytest
from conftest import subprocess_isolated

from citizensassemblies_tpu.core.generator import random_instance, skewed_instance
from citizensassemblies_tpu.core.instance import Instance, featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.ops.stats import prob_allocation_stats
from citizensassemblies_tpu.utils.config import default_config

#: the independent oracle: the agent-space HiGHS-certified CG, explicitly
#: forced — singleton households no longer force it, since the household
#: quotient (solvers/quotient.py) collapses them straight back to type space
AGENT_SPACE = default_config().replace(force_agent_space=True)


def _mass24_shaped(seed: int = 3) -> Instance:
    """The mass_24-shaped tight-quota instance, shared with the bench's
    baseline sweep (``core.generator.mass_like_instance``)."""
    from citizensassemblies_tpu.core.generator import mass_like_instance

    return mass_like_instance(seed=seed)


def test_mass24_shaped_tight_quotas_full_stack():
    """min=max cells through the full type-space solver stack, cross-checked
    against the agent-space HiGHS-certified CG."""
    inst = _mass24_shaped()
    dense, space = featurize(inst)
    qmin = dense.qmin_np
    qmax = dense.qmax_np
    assert int((qmin == qmax).sum()) >= 5  # genuinely tight cells

    ts = find_distribution_leximin(dense, space)
    # every support panel satisfies every quota exactly
    for row, p in zip(ts.committees, ts.probabilities):
        if p <= 1e-11:
            continue
        counts = dense.A_np[row].sum(axis=0)
        assert np.all(counts >= qmin) and np.all(counts <= qmax)
    assert ts.allocation.sum() == pytest.approx(24.0, abs=1e-6)

    ag = find_distribution_leximin(dense, space, cfg=AGENT_SPACE)
    # allocations agree as distributions (agents are type-interchangeable, so
    # compare the sorted profiles)
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )
    s_ts = prob_allocation_stats(ts.allocation, cap_for_geometric_mean=False)
    s_ag = prob_allocation_stats(ag.allocation, cap_for_geometric_mean=False)
    assert s_ts.min == pytest.approx(s_ag.min, abs=1e-3)
    assert s_ts.gini == pytest.approx(s_ag.gini, abs=5e-3)


def test_skewed_midsize_matches_agent_space_certified():
    """Heterogeneous-regime cross-check at mid size: the type-space result
    matches the agent-space HiGHS-certified CG within tolerance (VERDICT r1
    #2a, extending the n=40 cross-check upward)."""
    inst = skewed_instance(n=120, k=12, n_categories=3, seed=1)
    dense, space = featurize(inst)
    ts = find_distribution_leximin(dense, space)
    ag = find_distribution_leximin(dense, space, cfg=AGENT_SPACE)
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )


@pytest.mark.skipif(
    os.environ.get("RUN_SLOW") != "1",
    reason="the genuinely agent-space oracle takes ~20 min on the CPU mesh "
    "now that force_agent_space is required to bypass the quotient; "
    "set RUN_SLOW=1 (recorded evidence below)",
)
@subprocess_isolated()
def test_skewed_n400_matches_agent_space_certified():
    """sf_d/cca-shaped heterogeneous cross-check at n=400, k=40, 6 categories
    (VERDICT r2 item #2a): the production type-space solver matches the
    agent-space HiGHS-certified CG within 1e-3, and the solver-independent
    maximin audit (the post-hoc role of Gurobi's per-run dual-gap
    certificate, ``/root/reference/leximin.py:429-431``) certifies the first
    leximin level.

    Recorded evidence runs (RUN_SLOW=1, 8-device CPU mesh): 2026-07-31 r4,
    ~25 min alongside the n=70/n=120 cross-checks; 2026-07-31 round-5 re-run
    with the witness-elimination/structured-master stack, this test plus the
    n=200 forced-miss test passed together in 10 min 21 s — sorted-profile
    agreement within 1e-3 and audit gap within 1e-3 both times."""
    from citizensassemblies_tpu.solvers.highs_backend import audit_maximin

    inst = skewed_instance(
        n=400, k=40, n_categories=6, seed=2,
        features_per_category=[2, 3, 4, 2, 3, 3],
    )
    dense, space = featurize(inst)
    ts = find_distribution_leximin(dense, space)
    ag = find_distribution_leximin(dense, space, cfg=AGENT_SPACE)
    # agents are type-interchangeable, so compare the sorted profiles
    np.testing.assert_allclose(
        np.sort(ts.allocation), np.sort(ag.allocation), atol=1e-3
    )
    audit = audit_maximin(dense, ts.allocation, ts.covered)
    assert audit["maximin_gap"] <= 1e-3, audit
    assert audit["certified_maximin_upper"] >= audit["achieved_min"] - 1e-9


@pytest.mark.skipif(
    os.environ.get("RUN_SLOW") != "1",
    reason="agent-space CG at n=800 takes minutes (hours on CPU); "
    "set RUN_SLOW=1 (VERDICT r3 #6 evidence run)",
)
def test_skewed_n800_matches_agent_space_certified():
    """Full-profile independent cross-check at n=800 (VERDICT r3 #6,
    extending the n=400 evidence): the production type-space solver's sorted
    profile matches the agent-space HiGHS-certified CG within 1e-3 L∞, and
    the solver-independent maximin audit certifies the first level.

    Budget note (2026-07-31): the type-space side solves in ~90 s, but the
    agent-space ORACLE at n=800 did not finish within a 3.5 h budget on one
    v5e + host (the n=400 oracle takes ~20 min on the 8-device CPU mesh —
    recorded passing above). The at-scale independent evidence is instead
    ``audit_leximin_profile`` — EVERY leximin level certified by exact MILP
    witnesses on this same n=800 instance (15 levels, worst gap 6e-6,
    2.8 s) and on the n=1727 flagship (14 levels, worst gap 6e-6, 2.1 s;
    bench-recorded), which needs no CG oracle to terminate. This test stays
    for anyone with a longer budget."""
    from citizensassemblies_tpu.solvers.highs_backend import audit_maximin

    inst = skewed_instance(
        n=800, k=80, n_categories=7, seed=4,
        features_per_category=[2, 4, 5, 3, 2, 4, 6], skew=0.4,
    )
    dense, space = featurize(inst)
    ts = find_distribution_leximin(dense, space)
    ag = find_distribution_leximin(dense, space, cfg=AGENT_SPACE)
    prof_dev = float(
        np.abs(np.sort(ts.allocation) - np.sort(ag.allocation)).max()
    )
    assert prof_dev <= 1e-3, prof_dev
    audit = audit_maximin(dense, ts.allocation, ts.covered)
    assert audit["maximin_gap"] <= 1e-3, audit


def _force_realization_miss(monkeypatch, shift: float = 2e-3):
    """Monkeypatch ``decompose_with_pricing`` to perturb the returned panel
    probabilities so the realized allocation misses the 1e-3 contract — the
    failure mode the agent-space fallback exists for (a stalled household-
    disjoint pricing loop in the wild; synthesized here deterministically)."""
    from citizensassemblies_tpu.solvers import compositions

    real = compositions.decompose_with_pricing

    def miss(*args, **kwargs):
        P, probs, eps = real(*args, **kwargs)
        probs = np.asarray(probs, dtype=np.float64).copy()
        if len(probs) >= 2:
            # blend toward one panel: alloc' = (1−s)·alloc + s·P[b], so any
            # agent in panel b with allocation below ~0.5 moves by > s/2
            # (mass moved panel-to-panel is bounded by the heaviest panel's
            # own probability, which a spread-out optimum keeps tiny)
            b = int(np.argmax(probs))
            probs *= 1.0 - 2.0 * shift
            probs[b] += 2.0 * shift
        return P, probs, eps

    monkeypatch.setattr(compositions, "decompose_with_pricing", miss)


def test_forced_contract_miss_budgeted_fallback(monkeypatch):
    """A type-space realization that misses the 1e-3 contract routes to the
    agent-space CG; when that CG exceeds ``agent_space_budget_s``, the
    certified type-space profile ships with an explicit ε statement instead
    of stalling for hours (VERDICT r4 #3). Fast shape for the default suite;
    the at-scale demonstration is the RUN_SLOW n=800 test below."""
    _force_realization_miss(monkeypatch)
    inst = skewed_instance(
        n=200, k=24, n_categories=5, seed=6, features_per_category=[2, 3, 4, 2, 3]
    )
    dense, space = featurize(inst)
    cfg = default_config().replace(agent_space_budget_s=0.5)
    dist = find_distribution_leximin(dense, space, cfg=cfg)
    assert dist.contract_ok is False
    assert dist.realization_dev > 1e-3  # the forced miss, honestly reported
    assert any("budget" in line for line in dist.output_lines)
    assert dist.allocation.sum() == pytest.approx(float(dense.k), abs=1e-6)
    # the shipped allocation realizes the certified profile to the stated ε
    dev = float(np.abs(dist.allocation - dist.fixed_probabilities).max())
    assert dev == pytest.approx(dist.realization_dev, abs=1e-9)
    assert dev < 5e-3  # ε-wide, not garbage: the perturbation scale


@pytest.mark.skipif(
    os.environ.get("RUN_SLOW") != "1",
    reason="n=800 type-space solve is ~2 min on the CPU mesh; set RUN_SLOW=1 "
    "(recorded evidence below)",
)
@subprocess_isolated()
def test_forced_contract_miss_n800_budgeted_fallback(monkeypatch):
    """At-scale graceful completion (VERDICT r4 #3's acceptance): a forced
    realization miss at n=800 completes in minutes — the budget-expired
    agent-space CG returns the certified type-space profile with the explicit
    ε statement — where the unbudgeted CG did not finish in 3.5 h
    (see test_skewed_n800_matches_agent_space_certified's budget note).

    Recorded evidence run (2026-07-31, RUN_SLOW=1, 8-device CPU mesh):
    passed in 147 s end to end STANDALONE. Flake note (same date): when run
    in-process AFTER other RUN_SLOW tests, this test (and once its n=200
    sibling) was twice observed to livelock inside a jitted CPU-mesh
    execution (98 % CPU, no progress for ≥55 min) that standalone completes
    in minutes — an XLA-CPU runtime interaction, not an algorithmic stall
    (the budget logic under test fires on host wall-clock between solver
    calls). ``@subprocess_isolated`` now enforces the one-test-per-process
    workaround structurally: the body runs in a fresh interpreter with a
    hard timeout, so the in-process interaction cannot reach it and a
    recurrence costs an hour, not the evidence session; conftest still
    registers SIGUSR1 → faulthandler for live stack dumps inside the
    child."""
    _force_realization_miss(monkeypatch)
    inst = skewed_instance(
        n=800, k=80, n_categories=7, seed=4,
        features_per_category=[2, 4, 5, 3, 2, 4, 6], skew=0.4,
    )
    dense, space = featurize(inst)
    cfg = default_config().replace(agent_space_budget_s=5.0)
    dist = find_distribution_leximin(dense, space, cfg=cfg)
    assert dist.contract_ok is False
    assert dist.realization_dev > 1e-3
    assert any("budget" in line for line in dist.output_lines)
    assert dist.allocation.sum() == pytest.approx(80.0, abs=1e-6)
    dev = float(np.abs(dist.allocation - dist.fixed_probabilities).max())
    assert dev == pytest.approx(dist.realization_dev, abs=1e-9)
    assert dev < 5e-3


def test_second_level_audit_certifies():
    """``audit_second_level`` (solver-independent level-2 certificate with
    Lagrangian S1-floor tightening — VERDICT r3 #6's second-level-audit
    criterion) is tight on heterogeneous instances: gap ≈ 0 at both shapes,
    and the bound is genuinely an upper bound."""
    from citizensassemblies_tpu.solvers.highs_backend import (
        audit_maximin,
        audit_second_level,
    )

    inst = skewed_instance(n=120, k=12, n_categories=3, seed=1)
    dense, space = featurize(inst)
    dist = find_distribution_leximin(dense, space)
    a1 = audit_maximin(dense, dist.allocation, dist.covered)
    # the profile-style audits floor the prefix at the CERTIFIED values
    # (their documented contract — realized floors leak realization ε)
    a2 = audit_second_level(dense, dist.fixed_probabilities, dist.covered)
    assert a1["maximin_gap"] <= 1e-3
    assert a2["achieved_level2"] is not None
    assert a2["certified_level2_upper"] >= a2["achieved_level2"] - 1e-9
    assert a2["level2_gap"] <= 1e-3, a2
    # the level-1 set is a strict, nonempty subset of the covered types —
    # an S1 inflated to (nearly) everything would make the level-2
    # certificate vacuous
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    total_types = TypeReduction(dense).T
    assert 0 < a2["level1_set_types"] < total_types


def test_full_profile_audit_certifies_every_level():
    """``audit_leximin_profile`` on the CERTIFIED profile: every leximin
    level's stage-local optimality confirmed by an exact MILP witness
    (VERDICT r3 #6 closed in its strongest form — measured 6e-6 worst gap
    over 15 levels at n=800 and 14 levels at n=1727; here a CI-sized
    instance exercises the same loop)."""
    from citizensassemblies_tpu.solvers.highs_backend import (
        audit_leximin_profile,
    )

    inst = skewed_instance(
        n=300, k=45, n_categories=4, seed=14,
        features_per_category=[3, 4, 2, 3], skew=0.6,
    )
    dense, space = featurize(inst)
    dist = find_distribution_leximin(dense, space)
    prof = audit_leximin_profile(
        dense, dist.fixed_probabilities, dist.covered
    )
    assert prof["n_levels"] >= 2
    assert prof["all_within_tol"], prof
    assert prof["worst_gap"] <= 1e-3
    # the exact-MILP bound alone (no marginal-LP rescue) must certify every
    # level: the audit's independence from the type-space machinery is a
    # measured per-run fact, not an assumption. Its tolerance is LOOSER than
    # the certified min-of-two bound's (ADVICE r4): the Lagrangian bound
    # carries an integrality duality gap deep in the profile that the
    # 8-step heuristic subgradient closes only approximately — on the
    # measured instances it reaches 1e-3, but a seed/HiGHS-version change
    # that deepens the profile can legitimately loosen it without the
    # certificate (worst_gap, asserted tight above) being any weaker
    assert prof["worst_gap_milp"] <= 5e-3, prof
    for lvl in prof["levels"]:
        assert lvl["certified_upper"] >= lvl["achieved"] - 1e-9
        assert lvl["milp_upper"] >= lvl["achieved"] - 1e-9
    # the realized allocation tracks the certified profile within the
    # framework contract — the second half of the evidence chain
    assert float(
        np.abs(dist.allocation - dist.fixed_probabilities).max()
    ) <= 1e-3
