"""XMIN: leximin-optimal allocation spread over a maximal panel support
(golden diversity numbers: analysis/..._statistics.txt — example_small LEXIMIN
198 vs XMIN 1205 panels; couples 10 vs 116)."""

import numpy as np
import pytest

from citizensassemblies_tpu.core.instance import featurize, read_instance_dir
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.models.xmin import find_distribution_xmin
from citizensassemblies_tpu.ops.stats import prob_allocation_stats


def test_xmin_example_small_allocation_and_support(reference_data_dir):
    """Real example_small_20 data: XMIN keeps the exact leximin allocation
    (min 10.0 %, within 1e-3) and spreads mass over at least as many panels
    as the reference fork reports (1205 unique XMIN panels,
    ``analysis/example_small_20_statistics.txt:13``; our batched expansion
    reaches ~1400+). This pins VERDICT r1 item #5 as an assertion."""
    inst = read_instance_dir(reference_data_dir / "example_small_20")
    dense, space = featurize(inst)
    leximin = find_distribution_leximin(dense, space)
    xmin = find_distribution_xmin(dense, space)

    st = prob_allocation_stats(xmin.allocation, cap_for_geometric_mean=False)
    assert st.min == pytest.approx(0.100, abs=1e-3)
    np.testing.assert_allclose(
        xmin.allocation, leximin.fixed_probabilities, atol=1e-3
    )
    support = int((xmin.probabilities > 1e-11).sum())
    assert support >= 1205, support
    assert xmin.probabilities.sum() == pytest.approx(1.0, abs=1e-9)
    assert (xmin.committees.sum(axis=1) == dense.k).all()


def test_xmin_never_runs_the_host_eps_lp(reference_data_dir, monkeypatch):
    """The XMIN expansion must take its ε floor from the leximin donor, not
    the host minimal-ε LP: on example_large's degenerate uniform target that
    LP crawled for over 30 minutes (16.5k panels × n=2000, every coverage
    row tight at the optimum) while the donor answers in one matvec. Pinned
    by poisoning the LP entry point for the duration of the XMIN call."""
    from citizensassemblies_tpu.solvers import highs_backend

    inst = read_instance_dir(reference_data_dir / "example_small_20")
    dense, space = featurize(inst)
    leximin = find_distribution_leximin(dense, space)

    def boom(*a, **k):  # pragma: no cover - the point is it never runs
        raise AssertionError("XMIN must not call the host eps-LP")

    monkeypatch.setattr(highs_backend, "solve_final_primal_lp", boom)
    xmin = find_distribution_xmin(dense, space, leximin=leximin)
    np.testing.assert_allclose(
        xmin.allocation, leximin.fixed_probabilities, atol=1e-3
    )
    assert int((xmin.probabilities > 1e-11).sum()) > len(leximin.support())

    # force the device min-ε ANCHOR path too (anchor_if_above=0 makes every
    # donor "loose"): it must run without the host LP, its iterate must be
    # arithmetically validated, and the result must stay band-feasible —
    # this pins the host_fallback=False plumbing the poisoned LP guards
    from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2
    from citizensassemblies_tpu.utils.logging import RunLog

    rlog = RunLog(echo=False)
    probs, eps = solve_final_primal_l2(
        leximin.committees, leximin.fixed_probabilities,
        iters=2_000, log=rlog, floor_donor=leximin.probabilities,
        anchor_if_above=0.0,
    )
    assert "l2_eps_pdhg" in rlog.timers  # the anchor actually ran
    dev = float(
        np.abs(
            leximin.committees.T.astype(np.float64) @ probs
            - leximin.fixed_probabilities
        ).max()
    )
    assert dev <= 1e-3, dev


def test_xmin_couples_spreads_support(reference_data_dir):
    inst = read_instance_dir(
        reference_data_dir / "couples_panel_from_twenty_people_no_constraints_2"
    )
    dense, space = featurize(inst)
    leximin = find_distribution_leximin(dense, space)
    xmin = find_distribution_xmin(dense, space)

    # per-agent allocation preserved (leximin-optimal): min prob 10%
    st = prob_allocation_stats(xmin.allocation, cap_for_geometric_mean=False)
    assert st.min == pytest.approx(0.100, abs=2e-3)
    np.testing.assert_allclose(
        xmin.allocation, leximin.fixed_probabilities, atol=2e-3
    )
    # support grows far beyond leximin's (golden: 10 -> 116; the batched
    # sampler reaches every greedy-reachable panel, ~100 here)
    assert len(leximin.support()) == 10
    assert (xmin.probabilities > 1e-11).sum() > 60
    # all committees feasible and probabilities normalized
    assert xmin.probabilities.sum() == pytest.approx(1.0, abs=1e-9)
    assert (xmin.committees.sum(axis=1) == dense.k).all()
