"""Solver-layer tests: the native type-reduced branch-and-bound oracle
(``native/bb_price.cpp``) against the scipy/HiGHS MILP, and the device PDHG
LP solver (``solvers/lp_pdhg.py``) against the HiGHS LPs — the two exact
backends must agree because LEXIMIN's optimality certificate rests on them
(reference dual-gap test, ``leximin.py:429-431``)."""

import numpy as np
import pytest
from scipy.optimize import Bounds, LinearConstraint, milp

from citizensassemblies_tpu.core.generator import random_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.models.legacy import sample_feasible_panels
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.solvers.highs_backend import (
    HighsCommitteeOracle,
    solve_dual_lp,
    solve_final_primal_lp,
)
from citizensassemblies_tpu.solvers.lp_pdhg import (
    solve_dual_lp_pdhg,
    solve_final_primal_lp_pdhg,
)
from citizensassemblies_tpu.solvers.native_oracle import (
    TypeReduction,
    native_available,
    price_exact,
)
from citizensassemblies_tpu.utils.config import Config


needs_native = pytest.mark.skipif(not native_available(), reason="g++/native lib unavailable")


def _milp_optimum(dense, w):
    """Reference optimum straight from scipy's HiGHS MILP (no native path)."""
    oracle = HighsCommitteeOracle(dense)
    res = milp(
        c=-w,
        constraints=LinearConstraint(oracle._mat, oracle._lb, oracle._ub),
        integrality=np.ones(dense.n),
        bounds=Bounds(np.zeros(dense.n), np.ones(dense.n)),
    )
    if res.status != 0 or res.x is None:
        return None
    return float(w @ (res.x > 0.5))


@needs_native
def test_native_oracle_matches_milp_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(12):
        n = int(rng.integers(20, 90))
        k = int(rng.integers(3, max(4, n // 4)))
        inst = random_instance(
            n=n, k=k,
            n_categories=int(rng.integers(1, 4)),
            features_per_category=int(rng.integers(2, 4)),
            seed=trial,
        )
        dense, _ = featurize(inst)
        w = rng.normal(size=n)
        res = price_exact(TypeReduction(dense), w)
        ref = _milp_optimum(dense, w)
        if res is None:
            assert ref is None, f"native gave up but MILP solved (trial {trial})"
            continue
        committee, value = res
        assert ref is not None
        assert abs(value - ref) < 1e-6, f"trial {trial}: native {value} vs milp {ref}"
        # the returned committee must itself be feasible and consistent
        x = np.zeros(n)
        x[list(committee)] = 1.0
        counts = np.asarray(dense.A).T @ x
        assert len(committee) == k
        assert (counts >= np.asarray(dense.qmin) - 1e-9).all()
        assert (counts <= np.asarray(dense.qmax) + 1e-9).all()
        assert abs(w @ x - value) < 1e-9


@needs_native
def test_native_certify_floor_semantics():
    inst = random_instance(n=60, k=10, n_categories=2, features_per_category=3, seed=3)
    dense, _ = featurize(inst)
    rng = np.random.default_rng(0)
    w = rng.exponential(size=dense.n)
    opt = _milp_optimum(dense, w)
    red = TypeReduction(dense)
    # floor above the optimum: certified, no committee returned
    committee, value = price_exact(red, w, incumbent=opt + 1e-6)
    assert committee is None and value == pytest.approx(opt + 1e-6)
    # floor below the optimum: must find a strictly better committee
    committee, value = price_exact(red, w, incumbent=opt - 1e-3)
    assert committee is not None
    assert value == pytest.approx(opt, abs=1e-6)
    # oracle.certify wires the same semantics with MILP fallback
    oracle = HighsCommitteeOracle(dense)
    c2, v2 = oracle.certify(w, opt + 1e-6)
    assert c2 is None
    c3, v3 = oracle.certify(w, opt - 1e-3)
    assert c3 is not None and v3 == pytest.approx(opt, abs=1e-6)


def _random_portfolio(rng, n=40, C=25, k=8):
    P = np.zeros((C, n))
    for r in range(C):
        P[r, rng.choice(n, k, replace=False)] = 1.0
    return P


def test_pdhg_dual_lp_matches_highs():
    rng = np.random.default_rng(5)
    for trial in range(3):
        P = _random_portfolio(rng)
        n = P.shape[1]
        fixed = np.full(n, -1.0)
        # fix only agents that appear in some committee (as in the real
        # algorithm) — otherwise the dual LP is unbounded
        covered = np.nonzero(P.any(axis=0))[0]
        chosen = rng.choice(covered, 8, replace=False)
        fixed[chosen] = rng.uniform(0.05, 0.3, 8)
        ref = solve_dual_lp(P, fixed)
        got, warm = solve_dual_lp_pdhg(P, fixed)
        assert ref.ok and got.ok
        assert got.objective == pytest.approx(ref.objective, abs=5e-5)
        assert got.yhat == pytest.approx(ref.yhat, abs=5e-5)
        # warm-started re-solve with extra rows converges fast and agrees
        P2 = np.vstack([P, _random_portfolio(rng, n=n, C=4)])
        warm2 = (warm[0], np.concatenate([warm[1], np.zeros(4)]), warm[2])
        ref2 = solve_dual_lp(P2, fixed)
        got2, _ = solve_dual_lp_pdhg(P2, fixed, warm=warm2)
        assert got2.ok
        assert got2.objective == pytest.approx(ref2.objective, abs=5e-5)


def test_pdhg_final_lp_matches_highs():
    rng = np.random.default_rng(9)
    P = _random_portfolio(rng)
    target = rng.uniform(0.0, 0.25, P.shape[1])
    p_ref, e_ref = solve_final_primal_lp(P, target)
    p_got, e_got = solve_final_primal_lp_pdhg(P, target)
    assert e_got == pytest.approx(e_ref, abs=1e-4)
    assert np.sum(p_got) == pytest.approx(1.0, abs=1e-4)


def test_structured_two_sided_master_matches_host():
    """The structured master core (``solve_two_sided_master`` — only MT
    resident, ± rows applied arithmetically) must reproduce the host-exact
    two-sided ε-LP: same optimum ε, usable pricing duals, simplex-feasible
    primal. This is the kernel behind every face-decomposition round."""
    from citizensassemblies_tpu.solvers.cg_typespace import _decomp_lp
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_two_sided_master

    rng = np.random.default_rng(17)
    for trial in range(3):
        T, C = 24, 160
        # random compositions over small pools: columns of a plausible master
        m = rng.integers(1, 9, T)
        comps = np.minimum(rng.poisson(1.0, (C, T)), m[None, :])
        MT = (comps / np.maximum(m, 1)[None, :]).T.astype(np.float64)
        # target inside the hull, perturbed so ε* > 0
        mix = rng.dirichlet(np.ones(C))
        v = MT @ mix + rng.normal(0.0, 5e-3, T)
        e_ref, w_ref, _mu, _p = _decomp_lp(MT, v)
        sol = solve_two_sided_master(MT, v, tol=1e-7)
        assert sol.ok, sol.kkt
        p = np.maximum(sol.x[:C], 0.0)
        # the KKT tolerance is scale-relative, so the raw iterate's simplex
        # residual can sit at O(1e-2); the face loop consumes the NORMALIZED
        # iterate (p / Σp) and its arithmetic residual, asserted tight below
        assert p.sum() == pytest.approx(1.0, abs=0.05)
        e_got = float(np.abs(MT @ (p / p.sum()) - v).max())
        # the normalized iterate's arithmetic residual is what the face loop
        # consumes — it must reach the exact optimum's neighborhood
        assert e_got <= e_ref + 2e-4, (trial, e_got, e_ref)
        assert sol.objective == pytest.approx(e_ref, abs=2e-4)
        # pricing duals: same layout as the stacked formulation
        w = sol.lam[:T] - sol.lam[T:]
        assert w.shape == w_ref.shape
        # warm restart with extra columns converges and stays consistent
        extra = np.minimum(rng.poisson(1.0, (16, T)), m[None, :])
        MT2 = np.concatenate([MT.T, extra / np.maximum(m, 1)[None, :]]).T
        e_ref2, _w2, _mu2, _p2 = _decomp_lp(MT2, v)
        sol2 = solve_two_sided_master(
            MT2, v, warm=(sol.x, sol.lam, sol.mu), tol=1e-7
        )
        assert sol2.ok
        assert sol2.objective == pytest.approx(e_ref2, abs=2e-4)


def test_leximin_jax_backend_matches_hybrid():
    """Full column generation with device PDHG LPs reproduces the HiGHS-LP
    allocation (same math, different LP engine)."""
    inst = random_instance(n=40, k=8, n_categories=2, features_per_category=2, seed=11)
    dense, space = featurize(inst)
    d_h = find_distribution_leximin(dense, space, cfg=Config(backend="hybrid"))
    d_j = find_distribution_leximin(dense, space, cfg=Config(backend="jax"))
    assert np.abs(d_h.allocation - d_j.allocation).max() < 1e-3
    assert d_j.probabilities.sum() == pytest.approx(1.0, abs=1e-6)


def test_pdhg_loosened_acceptance_boundary():
    """The PDHG solver accepts near-tolerance finishes (``ok = kkt ≤ 4·tol``,
    ``lp_pdhg.py``). At the boundary this loosening must stay *consistent*
    (the flag mirrors the residual exactly) and *safe* (an accepted solve is
    still close to the exact optimum; a rejected one routes callers to the
    HiGHS fallback). VERDICT r1 weak #8."""
    from citizensassemblies_tpu.solvers.highs_backend import solve_dual_lp
    from citizensassemblies_tpu.utils.config import default_config

    inst = random_instance(n=36, k=6, n_categories=2, seed=5)
    dense, _ = featurize(inst)
    panels, _ = sample_feasible_panels(dense, 40, seed=1)
    P = np.zeros((40, dense.n), dtype=bool)
    for r, row in enumerate(panels):
        P[r, row] = True
    fixed = np.full(dense.n, -1.0)
    exact = solve_dual_lp(P, fixed)

    # the 4·tol acceptance lives in solve_lp: exercise it on the dual-LP
    # system directly so the kkt residual is visible
    from citizensassemblies_tpu.solvers.lp_pdhg import solve_lp

    n = dense.n
    fixed_vals = np.zeros(n)
    c = np.concatenate([-fixed_vals, [1.0]])
    G = np.hstack([P.astype(np.float64), -np.ones((P.shape[0], 1))])
    h = np.zeros(P.shape[0])
    A = np.concatenate([np.ones(n), [0.0]])[None, :]
    b = np.array([1.0])

    # starved iteration budget: the flag must mirror the residual exactly
    cfg_starved = default_config().replace(pdhg_max_iters=96, pdhg_check_every=32)
    sol = solve_lp(c, G, h, A, b, cfg=cfg_starved)
    assert sol.ok == (sol.kkt <= 4.0 * cfg_starved.pdhg_tol)

    # converged solve: accepted at ≤ 4·tol, and the loosening is safe — the
    # objective error is of the order of the residual, far under the EPS=5e-4
    # fixing tolerance the duals feed
    cfg_full = default_config()
    sol2 = solve_lp(c, G, h, A, b, cfg=cfg_full)
    assert sol2.ok and sol2.kkt <= 4.0 * cfg_full.pdhg_tol
    assert abs(sol2.objective - exact.objective) <= max(100.0 * sol2.kkt, 1e-4)

    got2, _ = solve_dual_lp_pdhg(P, fixed, cfg=cfg_full)
    assert got2.ok
    assert abs(got2.objective - exact.objective) <= 1e-4
    assert abs(got2.yhat - exact.yhat) <= 1e-4


def test_native_slice_repair_matches_python_fallback(monkeypatch):
    """The C++ slice repair (``native/slice_repair.cpp``) and the python
    ``swap_repair`` fallback must both emit only quota-feasible slices from
    the same apportionment stream, with comparable yield — pins the default-on
    native path against the reference implementation it replaces."""
    import citizensassemblies_tpu.solvers.native_oracle as native_oracle
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.solvers.cg_typespace import _slice_relaxation
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    from citizensassemblies_tpu.solvers.cg_typespace import _relaxation_bound

    inst = skewed_instance(n=300, k=30, n_categories=4, seed=3)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    # a quota-consistent fractional target: the stage-1 marginal optimum
    # (pool-proportional targets are quota-infeasible on skewed instances,
    # so every slice would be dropped)
    _z, x = _relaxation_bound(red, np.full(red.T, -1.0))

    def check(slices):
        assert len(slices) > 0
        tf = np.zeros((red.T, red.F), dtype=np.int64)
        for t in range(red.T):
            tf[t, red.type_feature[t]] = 1
        C = np.stack(slices)
        counts = C @ tf
        assert np.all(C.sum(axis=1) == red.k)
        assert np.all(counts >= red.qmin[None, :])
        assert np.all(counts <= red.qmax[None, :])
        return len(slices)

    native_n = check(_slice_relaxation(x, red, R=128))
    if native_oracle._load_repair() is None:
        pytest.skip("native toolchain unavailable — python path already covered")

    # the batched native stream and the per-slice native path run the same
    # arithmetic (apportionment, top-up ordering, repair seeds), so their
    # outputs must be identical slice-for-slice
    streamed = native_oracle.slice_stream_native(red, x, R=128, max_passes=3 * red.F)

    # the chunked production configuration (face_decompose uses j0=1<<20,
    # chunks=4): output must be quota-feasible, deterministic, exactly the
    # concatenation of the per-chunk single streams at the spaced offsets,
    # and the j0 phase shift must yield mostly-fresh slices vs the j0=0 run
    j0 = 1 << 20
    chunked = native_oracle.slice_stream_native(
        red, x, R=128, max_passes=3 * red.F, j0=j0, chunks=4
    )
    check(list(chunked))
    manual = np.concatenate(
        [
            native_oracle.slice_stream_native(
                red, x, R=32, max_passes=3 * red.F, j0=j0 + i * (1 << 16)
            )
            for i in range(4)
        ],
        axis=0,
    )
    assert np.array_equal(chunked, manual)
    # what the face master consumes is UNIQUE columns (its add() dedups), so
    # the phase shift is measured on hull growth: the offset stream must
    # contribute a substantial set of unique columns the base stream lacks.
    # (Within-stream repetition is inherent — an apportionment stream cycles
    # once R exceeds the pattern period — so a raw fresh-slice ratio would
    # mismeasure diversity.)
    base_u = {c.astype(np.int32).tobytes() for c in streamed}
    chunk_u = {c.astype(np.int32).tobytes() for c in chunked}
    grown = len(chunk_u - base_u)
    assert grown >= max(8, 0.2 * len(base_u)), (
        f"phase-shifted stream grew the unique-column hull by only {grown} "
        f"over {len(base_u)} base uniques"
    )

    monkeypatch.setattr(native_oracle, "slice_stream_native", lambda *a, **k: None)
    per_slice = _slice_relaxation(x, red, R=128)
    assert np.array_equal(np.stack(per_slice), streamed)

    # force the python fallback on the same stream
    # cg_typespace imports repair_slice_native function-locally at call
    # time, so patching the native_oracle module attribute is sufficient
    monkeypatch.setattr(native_oracle, "repair_slice_native", lambda *a, **k: None)
    python_n = check(_slice_relaxation(x, red, R=128))
    # tie noise differs between implementations; yields must be in the same
    # ballpark (both repair the same near-feasible stream)
    assert native_n >= 0.7 * python_n
    assert python_n >= 0.7 * native_n


def test_probe_confirm_tranche_chunks_equal_allowances():
    """Equal-allowance candidates are certified in chunked group probes (one
    LP per pool-size class), not one LP per candidate — the regression that
    degraded relaxation certification to ~1000 LPs per stage."""
    from citizensassemblies_tpu.solvers.lp_util import probe_confirm_tranche

    n = 100
    z = 0.5
    calls = {"n": 0}
    objectives = np.eye(n)

    def face_max(w):
        calls["n"] += 1
        # every candidate is exactly tight at z on this synthetic face; the
        # optimizer (second element) is the witness point — tight everywhere
        return float(w.sum()) * z, np.full(n, z)

    allowances = np.full(n, 1e-5)  # one allowance class
    conf = probe_confirm_tranche(
        face_max, objectives, z, probe_tol=1e-7, allowances=allowances,
        term_deficit=1e-8,
    )
    assert conf.all()
    assert calls["n"] <= 2, f"expected ~1 group probe, saw {calls['n']}"

    # two allowance classes ⇒ at most two group probes
    calls["n"] = 0
    allowances = np.concatenate([np.full(50, 1e-5), np.full(50, 2e-5)])
    conf = probe_confirm_tranche(
        face_max, objectives, z, probe_tol=1e-7, allowances=allowances,
        term_deficit=1e-8,
    )
    assert conf.all()
    assert calls["n"] <= 3


def test_probe_confirm_tranche_empty_face_certifies_nothing():
    """A genuinely empty probe face (reported z overstating the true stage
    optimum beyond the face relaxation) must certify NOTHING — previously it
    silently confirmed every candidate, fixing loose types low."""
    from citizensassemblies_tpu.solvers.lp_util import probe_confirm_tranche

    logged = []
    conf = probe_confirm_tranche(
        # every solve reports infeasible, incl. w = 0
        lambda w: (-np.inf, None),
        np.eye(4), 0.5, probe_tol=1e-7, allowances=np.full(4, 1e-6),
        term_deficit=1e-8, log=logged.append,
    )
    assert not conf.any()
    assert any("empty" in line for line in logged)


def test_probe_confirm_tranche_spurious_infeasible_still_certifies():
    """A solver mis-report (per-candidate objective claims infeasible while
    the zero-objective feasibility solve proves the face non-empty) keeps the
    documented certify-with-log behavior."""
    from citizensassemblies_tpu.solvers.lp_util import probe_confirm_tranche

    def face_max(w):
        if not w.any():  # feasibility probe: the face is non-empty
            return 0.0, np.zeros_like(w)
        return -np.inf, None  # mis-reported objective solves

    logged = []
    conf = probe_confirm_tranche(
        face_max, np.eye(3), 0.5, probe_tol=1e-7,
        allowances=np.full(3, 1e-6), term_deficit=1e-8, log=logged.append,
    )
    assert conf.all()
    assert any("infeasible probe face" in line for line in logged)
