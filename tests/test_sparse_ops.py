"""The structured-sparse (fixed-nnz ELL) operator layer.

Contracts pinned here:

* **pack/unpack round trip** — fuzzed random-composition matrices survive
  ``ell_pack_rows``/``ell_unpack_rows`` exactly (at float32 value
  precision), including rows of very different nnz and all-zero rows.
* **incremental append == full repack** — simulated CG rounds (append a
  batch, prune to a subset, append again) leave the :class:`EllPack`
  bit-identical to packing the final column set from scratch.
* **sparse-vs-dense solver parity** — the ELL two-sided master, the generic
  ELL dual LP, the batched ELL polish screen, the sharded ELL dual LP and
  both QP L2 paths reach the same solutions (x, duals, objective) as their
  dense twins within the PDHG tolerance regime, on flagship- and
  household-quotient-shaped fixtures.
* **the dense fallback is bit-identical** — with ``Config.sparse_ops=False``
  the routing call sites execute exactly the dense path.
* **gating** — the ``sparse_ops`` tri-state and the fill cutoff behave as
  documented, and the LRU memo bound evicts (and counts) as designed.
"""

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import skewed_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.solvers.lp_pdhg import (
    solve_dual_lp_pdhg,
    solve_lp,
    solve_two_sided_master,
    solve_two_sided_master_ell,
)
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.solvers.sparse_ops import (
    EllPack,
    ell_pack_rows,
    ell_unpack_rows,
    sparse_enabled,
)
from citizensassemblies_tpu.utils.config import default_config
from citizensassemblies_tpu.utils.logging import RunLog


def _composition_columns(n=160, k=14, seed=5, n_cols=48):
    """Flagship-shaped master columns: feasible compositions of a skewed
    instance's type space (≤ k nonzeros of T types), as the dense MT."""
    from citizensassemblies_tpu.solvers.cg_typespace import (
        _leximin_relaxation,
        _slice_relaxation,
    )

    inst = skewed_instance(n=n, k=k, n_categories=3, seed=seed)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    x_target = v_relax * red.msize.astype(np.float64)
    slices = _slice_relaxation(x_target, red, R=max(n_cols, 16))
    comps = np.stack(slices[:n_cols]).astype(np.float64)
    m = red.msize.astype(np.float64)
    MT = np.ascontiguousarray((comps / m[None, :]).T)  # [T, C]
    v = MT @ np.full(comps.shape[0], 1.0 / comps.shape[0])
    return MT, v


def _household_columns():
    """Household-quotient-shaped columns (augmented incidence, F > 64)."""
    from citizensassemblies_tpu.solvers.cg_typespace import (
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.quotient import build_household_quotient

    inst = skewed_instance(
        n=240, k=16, n_categories=3, seed=7, features_per_category=[3, 3, 3]
    )
    dense, _ = featurize(inst)
    hh = (np.arange(240) // 2).astype(np.int32)
    q = build_household_quotient(dense, hh)
    red = TypeReduction(q.dense_aug)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    x_target = v_relax * red.msize.astype(np.float64)
    slices = _slice_relaxation(x_target, red, R=32)
    comps = np.stack(slices).astype(np.float64)
    m = red.msize.astype(np.float64)
    MT = np.ascontiguousarray((comps / m[None, :]).T)
    v = MT @ np.full(comps.shape[0], 1.0 / comps.shape[0])
    return MT, v


# --- pack/unpack -------------------------------------------------------------


def test_pack_unpack_roundtrip_fuzz():
    """Random-composition matrices round-trip exactly (f32 values),
    across densities, all-zero rows, and k_pad growth."""
    rng = np.random.default_rng(0)
    for trial in range(20):
        J = int(rng.integers(1, 40))
        minor = int(rng.integers(2, 120))
        density = float(rng.uniform(0.02, 0.9))
        rows = (rng.random((J, minor)) < density) * rng.normal(size=(J, minor))
        rows = rows.astype(np.float32).astype(np.float64)
        if trial % 5 == 0:
            rows[rng.integers(0, J)] = 0.0  # all-zero row
        idx, val, nnz = ell_pack_rows(rows)
        assert idx.shape == val.shape
        assert idx.shape[1] % 8 == 0
        assert int(nnz.sum()) == int((rows != 0).sum())
        back = ell_unpack_rows(idx, val, minor)
        assert np.array_equal(back, rows), f"trial {trial}"


def test_pack_rejects_overfull_rows():
    rows = np.ones((2, 20))
    with pytest.raises(ValueError):
        ell_pack_rows(rows, k_pad=8)


def test_incremental_append_equals_full_repack():
    """Simulated CG rounds: append → prune (take) → append again must leave
    the pack bit-identical to packing the surviving column set fresh."""
    rng = np.random.default_rng(3)
    T = 60

    def make(n):
        return (rng.random((n, T)) < 0.2) * rng.integers(1, 5, (n, T))

    pack = EllPack(minor=T)
    batch1 = make(30).astype(np.float64)
    pack.append(batch1)
    history = [r for r in batch1]
    # round 2: prune to a support subset (reordered), then append fresh cols
    keep = rng.permutation(len(history))[:17]
    pack = pack.take(keep)
    history = [history[i] for i in keep]
    batch2 = make(25).astype(np.float64)
    pack.append(batch2)
    history.extend(r for r in batch2)
    # round 3: another prune + a batch with HIGHER nnz (k_pad growth)
    keep2 = rng.permutation(len(history))[:20]
    pack = pack.take(keep2)
    history = [history[i] for i in keep2]
    dense_batch = (rng.random((10, T)) < 0.7) * rng.integers(1, 5, (10, T))
    pack.append(dense_batch.astype(np.float64))
    history.extend(r for r in dense_batch.astype(np.float64))

    full = EllPack.from_rows(np.stack(history), minor=T)
    # same unpacked matrix; slot layouts agree up to the shared k_pad
    assert ell_unpack_rows(pack.idx, pack.val, T).tolist() == (
        ell_unpack_rows(full.idx, full.val, T).tolist()
    )
    kp = max(pack.k_pad, full.k_pad)
    assert pack.nnz_total == full.nnz_total
    assert len(pack) == len(full)
    # and the packed arrays themselves agree on the common slots
    assert np.array_equal(
        np.pad(pack.val, ((0, 0), (0, kp - pack.k_pad))),
        np.pad(full.val, ((0, 0), (0, kp - full.k_pad))),
    )


def test_ell_matvecs_match_dense():
    import jax.numpy as jnp

    from citizensassemblies_tpu.solvers.sparse_ops import (
        batched_ell_gather_mv,
        batched_ell_scatter_mv,
        ell_gather_mv,
        ell_scatter_mv,
    )

    rng = np.random.default_rng(1)
    M = ((rng.random((50, 33)) < 0.25) * rng.normal(size=(50, 33))).astype(
        np.float32
    )
    idx, val, _ = ell_pack_rows(M)
    x = rng.normal(size=33).astype(np.float32)
    y = rng.normal(size=50).astype(np.float32)
    got_g = np.asarray(ell_gather_mv(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(x)))
    got_s = np.asarray(
        ell_scatter_mv(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(y), 33)
    )
    np.testing.assert_allclose(got_g, M @ x, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got_s, M.T @ y, rtol=1e-5, atol=1e-5)
    X = rng.normal(size=(4, 33)).astype(np.float32)
    Y = rng.normal(size=(4, 50)).astype(np.float32)
    got_bg = np.asarray(
        batched_ell_gather_mv(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(X))
    )
    got_bs = np.asarray(
        batched_ell_scatter_mv(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(Y), 33)
    )
    np.testing.assert_allclose(got_bg, X @ M.T, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got_bs, Y @ M, rtol=1e-5, atol=1e-5)


# --- solver parity -----------------------------------------------------------


def _master_parity(MT, v, tol=1e-6, iters=20_000):
    T, C = MT.shape
    dense = solve_two_sided_master(MT, v, tol=tol, max_iters=iters, bucket=64)
    ell = EllPack.from_rows(MT.T, minor=T)
    sparse = solve_two_sided_master_ell(
        ell, v, tol=tol, max_iters=iters, bucket=64
    )
    assert dense.ok and sparse.ok
    pd = np.maximum(dense.x[:C], 0.0)
    ps = np.maximum(sparse.x[:C], 0.0)
    pd, ps = pd / pd.sum(), ps / ps.sum()
    eps_d = float(np.abs(MT @ pd - v).max())
    eps_s = float(np.abs(MT @ ps - v).max())
    # objective, realized ε, and pricing duals within the PDHG tol regime
    assert abs(dense.objective - sparse.objective) <= 5e-5
    assert abs(eps_d - eps_s) <= 5e-5
    w_d = dense.lam[:T] - dense.lam[T:]
    w_s = sparse.lam[:T] - sparse.lam[T:]
    assert float(np.abs(w_d - w_s).max()) <= 5e-3


def test_two_sided_master_parity_flagship_shape():
    MT, v = _composition_columns()
    _master_parity(MT, v)


def test_two_sided_master_parity_household_shape():
    MT, v = _household_columns()
    _master_parity(MT, v)


def test_dual_lp_sparse_vs_dense_and_bit_identical_fallback():
    """The dual leximin LP: ELL vs dense parity, and the ``sparse_ops=False``
    fallback is BIT-identical to calling the dense solver directly."""
    rng = np.random.default_rng(4)
    C, n, k = 200, 40, 8
    P = np.zeros((C, n))
    for r in range(C):
        P[r, rng.choice(n, k, replace=False)] = 1.0
    fixed = np.full(n, -1.0)
    cfg_off = default_config().replace(sparse_ops=False)
    cfg_on = default_config().replace(sparse_ops=True)
    d_off, _ = solve_dual_lp_pdhg(P, fixed, cfg=cfg_off)
    d_on, _ = solve_dual_lp_pdhg(P, fixed, cfg=cfg_on)
    assert d_off.ok and d_on.ok
    assert abs(d_off.objective - d_on.objective) <= 1e-4
    assert float(np.abs(d_off.y - d_on.y).max()) <= 1e-3

    # bit-identity of the fallback: the routing with the knob off must run
    # exactly the dense assembly + solve_lp path
    bucket = 256
    Cp = ((C + bucket - 1) // bucket) * bucket
    Ppad = np.zeros((Cp, n))
    Ppad[:C] = P
    c = np.concatenate([np.zeros(n), [1.0]])
    G = np.hstack([Ppad, -np.ones((Cp, 1))])
    h = np.zeros(Cp)
    A = np.concatenate([np.ones(n), [0.0]])[None, :]
    b = np.array([1.0])
    direct = solve_lp(c, G, h, A, b, cfg=cfg_off)
    assert np.array_equal(direct.x[:n], d_off.y)
    assert float(direct.x[n]) == d_off.yhat


def test_polish_screen_ell_matches_dense_prefixes():
    """The vmapped ELL polish screen certifies the same prefix ε values as
    the dense batched screen (both judged by the float64 arithmetic
    residual, the accept-bar contract)."""
    from citizensassemblies_tpu.solvers.batch_lp import (
        solve_lp_batch,
        solve_polish_screen_ell,
        two_sided_master_batch_lp,
    )

    MT, v = _composition_columns(n_cols=40)
    T, C = MT.shape
    caps = [C // 4, C // 2, C]
    cfg = default_config().replace(lp_batch=True)
    insts = [
        two_sided_master_batch_lp(MT[:, :c_], v, tol=1e-6) for c_ in caps
    ]
    dense_sols = solve_lp_batch(
        insts, cfg=cfg, max_iters=20_000, common_bucket=True
    )
    ell = EllPack.from_rows(MT.T, minor=T)
    ell_sols = solve_polish_screen_ell(
        ell, v, caps, [None] * len(caps), tol=1e-6, max_iters=20_000, cfg=cfg
    )
    for c_, sd, se in zip(caps, dense_sols, ell_sols):
        pd = np.maximum(sd.x[:c_], 0.0)
        ps = np.maximum(se.x[:c_], 0.0)
        if pd.sum() <= 0 or ps.sum() <= 0:
            continue
        eps_d = float(np.abs(MT[:, :c_] @ (pd / pd.sum()) - v).max())
        eps_s = float(np.abs(MT[:, :c_] @ (ps / ps.sum()) - v).max())
        assert abs(eps_d - eps_s) <= 1e-4, (c_, eps_d, eps_s)


def test_qp_l2_sparse_paths_match_dense():
    from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2

    rng = np.random.default_rng(2)
    C, n, k = 150, 40, 8
    P = np.zeros((C, n), bool)
    for r in range(C):
        P[r, rng.choice(n, k, replace=False)] = True
    q = rng.dirichlet(np.ones(C))
    t = P.T.astype(np.float64) @ q
    donor = q * 0.5 + rng.dirichlet(np.ones(C)) * 0.5
    results = {}
    for tag, cfg in (
        ("dense-serial", default_config().replace(sparse_ops=False, lp_batch=False)),
        ("ell-serial", default_config().replace(sparse_ops=True, lp_batch=False)),
        ("dense-fused", default_config().replace(sparse_ops=False, lp_batch=True)),
        ("ell-fused", default_config().replace(sparse_ops=True, lp_batch=True)),
    ):
        log = RunLog(echo=False)
        p, _eps = solve_final_primal_l2(
            P, t, iters=4000, log=log, floor_donor=donor, cfg=cfg,
            anchor_if_above=1e-9,
        )
        dev = float(np.abs(P.T.astype(np.float64) @ p - t).max())
        results[tag] = (dev, int((p > 1e-11).sum()), log.counters)
    for tag, (dev, support, counters) in results.items():
        assert dev <= 5e-4, (tag, dev)
        assert support >= int(0.8 * C), (tag, support)
        if tag.startswith("ell"):
            assert counters.get("sparse_hit", 0) == 1, (tag, counters)
            assert "sparse_fill_pct" in counters
        else:
            assert counters.get("sparse_miss", 0) == 1, (tag, counters)


def test_sharded_dual_lp_ell_parity_one_device():
    """The mesh-sharded ELL dual LP on a 1-device mesh matches the dense
    sharded program and the exact host LP."""
    import jax
    from jax.sharding import Mesh

    from citizensassemblies_tpu.parallel.solver import solve_dual_lp_pdhg_sharded
    from citizensassemblies_tpu.solvers.highs_backend import solve_dual_lp

    rng = np.random.default_rng(6)
    C, n, k = 128, 24, 6
    P = np.zeros((C, n), dtype=np.float32)
    for r in range(C):
        P[r, rng.choice(n, k, replace=False)] = 1.0
    fixed = np.full(n, -1.0)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("rows",))
    d_dense = solve_dual_lp_pdhg_sharded(
        P, fixed, mesh, cfg=default_config().replace(sparse_ops=False)
    )
    d_ell = solve_dual_lp_pdhg_sharded(
        P, fixed, mesh, cfg=default_config().replace(sparse_ops=True)
    )
    exact = solve_dual_lp(P.astype(bool), fixed)
    assert d_dense.ok and d_ell.ok
    assert abs(d_dense.objective - d_ell.objective) <= 1e-4
    assert abs(d_ell.objective - exact.objective) <= 1e-3


def test_face_decompose_sparse_counters_and_parity():
    """The accelerated face loop with the sparse master engaged certifies
    the same profile as the dense loop, and records the routing evidence
    (hit counter, fill gauge, pack timer)."""
    from citizensassemblies_tpu.solvers.cg_typespace import (
        CompositionOracle,
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.face_decompose import realize_profile

    inst = skewed_instance(n=120, k=12, n_categories=3, seed=1)
    dense, _ = featurize(inst)
    red = TypeReduction(dense)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    # R=64 seeds the hull well enough to certify in few rounds — the sparse
    # master still runs (and records its routing evidence) every round, and
    # the under-seeded multi-round regime is test_face_decompose's job
    seeds = _slice_relaxation(
        v_relax * red.msize.astype(np.float64), red, R=64
    )
    # the dense leg of this loop is already pinned by
    # tests/test_face_decompose.py (same accept bar, same master path) —
    # only the ELL leg runs here, against the same certification contract
    cfg = default_config().replace(
        sparse_ops=True, decomp_host_master_max_types=0
    )
    log = RunLog(echo=False)
    _C, probs, eps, _s = realize_profile(
        red, v_relax, list(seeds), CompositionOracle(red), 1e-3,
        log=log, max_rounds=8, use_pdhg=True, cfg=cfg,
    )
    ce, te = log.counters, log.timers
    assert eps <= 1e-3
    assert ce.get("sparse_hit", 0) >= 1, ce
    assert "sparse_fill_pct" in ce
    assert "sparse_pack" in te


# --- gating, memo, kernel ----------------------------------------------------


def test_sparse_enabled_tri_state():
    cfg_auto = default_config()
    assert sparse_enabled(cfg_auto, 0.1)
    assert sparse_enabled(cfg_auto, 0.25)
    assert not sparse_enabled(cfg_auto, 0.3)
    assert sparse_enabled(default_config().replace(sparse_ops=True), 0.99)
    assert not sparse_enabled(default_config().replace(sparse_ops=False), 0.01)
    tight = default_config().replace(sparse_fill_cutoff=0.05)
    assert not sparse_enabled(tight, 0.1)


def test_lru_memo_bounds_and_counts_evictions():
    from citizensassemblies_tpu.utils.memo import LRU, memo_evictions

    before = memo_evictions()
    cache = LRU(cap=2, name="t")
    cache["a"] = 1
    cache["b"] = 2
    assert cache.get("a") == 1  # refreshes recency: b is now oldest
    cache["c"] = 3
    assert "b" not in cache and "a" in cache and "c" in cache
    assert len(cache) == 2
    assert cache.evictions == 1
    assert memo_evictions() == before + 1
    # a rebuilt entry after eviction works like a fresh insert
    cache["b"] = 20
    assert cache.get("b") == 20


def test_pallas_ell_matvec_matches_xla():
    import jax.numpy as jnp

    from citizensassemblies_tpu.kernels.ell_matvec import ell_gather_mv_pallas
    from citizensassemblies_tpu.solvers.sparse_ops import ell_gather_mv

    rng = np.random.default_rng(9)
    M = ((rng.random((300, 90)) < 0.1) * rng.normal(size=(300, 90))).astype(
        np.float32
    )
    idx, val, _ = ell_pack_rows(M)
    y = rng.normal(size=90).astype(np.float32)
    got = np.asarray(ell_gather_mv_pallas(idx, val, y))
    want = np.asarray(
        ell_gather_mv(jnp.asarray(idx), jnp.asarray(val), jnp.asarray(y))
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
