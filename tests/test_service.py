"""graftserve: the async selection service and its re-entrancy contract.

What is pinned here:

* **RunLog thread safety** — ``count()`` hammered from a pool loses no
  increments (the service counts into shared engine logs from concurrent
  request threads).
* **Re-entrancy bit-identity** — two INTERLEAVED leximin solves with
  *different* Config knobs each honor their own config and produce
  allocations bit-identical to their serial twins: the per-request
  RequestContext isolates knobs, counters, and warm slots.
* **Service end-to-end** — submitted requests match direct solver calls,
  progress streams, and the audit stamp carries the exactness fields.
* **Cross-request batching** — fleets submitted from two threads inside the
  window fuse into one engine dispatch, with per-request results identical
  to solo dispatches.
* **Warm-slot isolation** — a context's warm slots land in ITS store under
  a tenant/request-scoped key; the module default store is untouched.
* **Per-tenant eviction attribution** — overflowing a tenant session's LRU
  counts into ``memo_evictions_by_owner()`` under that tenant.
* **Admission control** — ``serve_queue_depth`` in-flight requests reject
  the next submit.
* **decomp_host_syncs** — the face loop's device rounds count host↔device
  round trips into the gauge the audit stamp and bench rows report.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import random_instance, skewed_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.service import (
    AdmissionError,
    CrossRequestBatcher,
    RequestContext,
    SelectionRequest,
    SelectionService,
    use_context,
)
from citizensassemblies_tpu.utils.config import default_config
from citizensassemblies_tpu.utils.logging import RunLog
from citizensassemblies_tpu.utils.memo import LRU, memo_evictions_by_owner


def _tiny(seed=0, n=24, k=5):
    return featurize(random_instance(n=n, k=k, n_categories=2, seed=seed))


# --- RunLog thread safety ----------------------------------------------------


def test_runlog_count_no_lost_increments():
    """dict-get+store is not atomic; the lock must make it so."""
    log = RunLog(echo=False)
    workers, per = 8, 5_000

    def hammer():
        for _ in range(per):
            log.count("hits")
        return True

    with ThreadPoolExecutor(max_workers=workers) as pool:
        assert all(f.result() for f in [pool.submit(hammer) for _ in range(workers)])
    assert log.counters["hits"] == workers * per


def test_runlog_timer_and_gauge_concurrent():
    log = RunLog(echo=False)

    def one(i):
        with log.timer("t"):
            pass
        log.gauge("g", i)
        return True

    with ThreadPoolExecutor(max_workers=4) as pool:
        assert all(f.result() for f in [pool.submit(one, i) for i in range(64)])
    assert log.timers["t"] >= 0.0
    assert 0 <= log.counters["g"] < 64


# --- re-entrancy: interleaved solves, different knobs, bit-identical ---------


def test_interleaved_leximin_bit_identical_to_serial():
    """Two concurrent requests with DIFFERENT configs (batched engine on vs
    off, sparse layer forced vs disabled) must each honor their own knobs
    and reproduce their serial twins bit-for-bit."""
    d1, s1 = _tiny(seed=1, n=32, k=6)
    d2, s2 = _tiny(seed=2, n=40, k=7)
    cfg_a = default_config().replace(lp_batch=True, sparse_ops=False)
    cfg_b = default_config().replace(lp_batch=False, sparse_ops=True)

    serial_a = find_distribution_leximin(d1, s1, cfg=cfg_a)
    serial_b = find_distribution_leximin(d2, s2, cfg=cfg_b)

    ctx_a = RequestContext.create(cfg=cfg_a, tenant="a", request_id="ra")
    ctx_b = RequestContext.create(cfg=cfg_b, tenant="b", request_id="rb")
    barrier = threading.Barrier(2)

    def run(ctx, d, s):
        barrier.wait(timeout=30)  # both requests genuinely in flight
        return find_distribution_leximin(d, s, ctx=ctx)

    with ThreadPoolExecutor(max_workers=2) as pool:
        fa = pool.submit(run, ctx_a, d1, s1)
        fb = pool.submit(run, ctx_b, d2, s2)
        conc_a, conc_b = fa.result(timeout=300), fb.result(timeout=300)

    np.testing.assert_array_equal(conc_a.allocation, serial_a.allocation)
    np.testing.assert_array_equal(conc_b.allocation, serial_b.allocation)
    np.testing.assert_array_equal(conc_a.probabilities, serial_a.probabilities)
    np.testing.assert_array_equal(conc_b.probabilities, serial_b.probabilities)
    # each run's counters landed on its OWN log, not a shared one
    assert ctx_a.log.counters is not None and ctx_b.log.counters is not None
    assert ctx_a.log.lines and ctx_b.log.lines


# --- service end-to-end ------------------------------------------------------


def test_service_end_to_end_parity_stream_and_audit():
    cfg = default_config().replace(lp_batch=True, serve_batch_window_ms=5.0)
    insts = [random_instance(n=24 + 8 * i, k=5, n_categories=2, seed=i) for i in range(3)]
    with SelectionService(cfg) as svc:
        chans = [
            svc.submit(
                SelectionRequest(instance=inst, algorithm="leximin", tenant=f"t{i}")
            )
            for i, inst in enumerate(insts)
        ]
        results = [c.result(timeout=300) for c in chans]
    for inst, res in zip(insts, results):
        d, s = featurize(inst)
        ref = find_distribution_leximin(d, s, cfg=cfg)
        np.testing.assert_array_equal(res.allocation, ref.allocation)
        assert res.audit["contract_ok"] is True
        assert res.audit["realization_dev"] <= 1e-3
        for field in ("decomp_host_syncs", "xla_compiles", "counters", "timers",
                      "session", "tenant_memo_evictions"):
            assert field in res.audit, field
    # the channel retained the progress stream (RunLog lines)
    events = list(chans[0].events(timeout=5))
    kinds = [k for k, _ in events]
    assert kinds[-1] == "result" and "progress" in kinds


def test_service_memo_and_xmin_seed_reuse():
    cfg = default_config()
    inst = random_instance(n=24, k=5, n_categories=2, seed=3)
    with SelectionService(cfg) as svc:
        r1 = svc.run(SelectionRequest(instance=inst, tenant="memo"), timeout=300)
        assert not r1.from_memo
        # identical re-submission: served from the tenant memo
        r2 = svc.run(SelectionRequest(instance=inst, tenant="memo"), timeout=300)
        assert r2.from_memo
        np.testing.assert_array_equal(r1.allocation, r2.allocation)
        # XMIN on the same problem reuses the session's LEXIMIN seed
        rx = svc.run(
            SelectionRequest(instance=inst, algorithm="xmin", tenant="memo"),
            timeout=300,
        )
        assert any("reusing the tenant session's LEXIMIN seed" in line
                   for line in rx.result.output_lines)
        # XMIN preserves the leximin profile within its band
        assert float(np.abs(np.sort(rx.allocation) - np.sort(r1.allocation)).max()) \
            <= 1e-3


def test_service_legacy_algorithm_parity():
    from citizensassemblies_tpu.models.legacy import legacy_probabilities

    cfg = default_config()
    inst = random_instance(n=24, k=5, n_categories=2, seed=4)
    d, _s = featurize(inst)
    ref = legacy_probabilities(d, iterations=300, seed=7, cfg=cfg)
    with SelectionService(cfg) as svc:
        res = svc.run(
            SelectionRequest(instance=inst, algorithm="legacy", iterations=300, seed=7),
            timeout=300,
        )
    np.testing.assert_array_equal(res.allocation, ref.allocation)
    assert res.audit["draws_attempted"] >= 300


def test_admission_control_queue_depth():
    cfg = default_config().replace(serve_queue_depth=2, serve_admission_cap=1)
    svc = SelectionService(cfg)
    try:
        # white-box: pin the in-flight count at the depth — submit must
        # reject deterministically (no reliance on a request staying slow)
        with svc._lock:
            svc._in_flight = svc.queue_depth
        with pytest.raises(AdmissionError):
            svc.submit(SelectionRequest(instance=random_instance(n=24, k=5,
                                                                 n_categories=2)))
        with svc._lock:
            svc._in_flight = 0
    finally:
        svc.shutdown()


# --- cross-request batching --------------------------------------------------


def test_cross_request_batcher_fuses_and_matches_solo():
    """Two threads submit same-schedule fleets inside the window: one engine
    dispatch, per-request results identical to solo dispatches."""
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        solve_lp_batch,
    )

    rng = np.random.default_rng(0)
    cfg = default_config().replace(lp_batch=True, serve_batch_window_ms=500.0)

    def fleet(seed):
        out = []
        r = np.random.default_rng(seed)
        for _ in range(3):
            P = r.random((16, 8)) < 0.5
            q = r.random(16)
            q /= q.sum()
            out.append(final_primal_batch_lp(P, P.T.astype(np.float64) @ q))
        return out

    fleets = [fleet(1), fleet(2)]
    solo = [
        solve_lp_batch(f, cfg=cfg, max_iters=20_000, defer=False) for f in fleets
    ]

    batcher = CrossRequestBatcher(cfg)
    ctxs = [
        RequestContext.create(cfg=cfg, tenant=f"t{i}", request_id=f"r{i}",
                              batcher=batcher)
        for i in range(2)
    ]
    barrier = threading.Barrier(2)

    def run(i):
        barrier.wait(timeout=30)
        with use_context(ctxs[i]):
            return solve_lp_batch(fleets[i], cfg=cfg, max_iters=20_000)

    with ThreadPoolExecutor(max_workers=2) as pool:
        fused = [f.result(timeout=120) for f in [pool.submit(run, i) for i in range(2)]]

    stats = batcher.stats()
    assert stats["submissions"] == 2
    assert stats["fused_dispatches"] >= 1, stats
    assert stats["max_requests_fused"] == 2, stats
    for got, want in zip(fused, solo):
        for g, w in zip(got, want):
            # identical lanes of an identical padded bucket: bit-identical
            np.testing.assert_array_equal(g.x, w.x)
            assert g.objective == w.objective
    _ = rng  # noqa: F841 - seed source for future fleet variants


def test_warm_slot_isolation_across_contexts():
    from citizensassemblies_tpu.solvers.batch_lp import (
        _DEFAULT_WARM_STORE,
        WarmSlotStore,
        final_primal_batch_lp,
        solve_lp_batch,
    )

    rng = np.random.default_rng(5)
    P = rng.random((16, 8)) < 0.5
    q = rng.random(16)
    q /= q.sum()
    inst = [final_primal_batch_lp(P, P.T.astype(np.float64) @ q)]
    cfg = default_config().replace(lp_batch=True)

    store_a, store_b = WarmSlotStore(), WarmSlotStore()
    ctx_a = RequestContext.create(cfg=cfg, tenant="ta", request_id="r1",
                                  warm_store=store_a)
    ctx_b = RequestContext.create(cfg=cfg, tenant="tb", request_id="r2",
                                  warm_store=store_b)
    before_default = len(_DEFAULT_WARM_STORE)
    with use_context(ctx_a):
        solve_lp_batch(inst, cfg=cfg, warm_key="probe", max_iters=10_000)
    assert len(store_a) == 1
    assert store_a.get(("ta/r1/probe", 0)) is not None
    assert len(store_b) == 0
    assert len(_DEFAULT_WARM_STORE) == before_default
    # a request-scoped clear drops only that context's slots
    with use_context(ctx_a):
        from citizensassemblies_tpu.solvers.batch_lp import clear_warm_slots

        clear_warm_slots("probe")
    assert len(store_a) == 0


# --- per-tenant eviction attribution ----------------------------------------


def test_lru_owner_attributed_evictions():
    before = memo_evictions_by_owner().get("tenant:evict-me", 0)
    cache = LRU(cap=2, name="tenant:evict-me:memo")
    for i in range(4):
        cache.put(i, i, owner="tenant:evict-me")
    after = memo_evictions_by_owner().get("tenant:evict-me", 0)
    assert after - before == 2
    assert cache.evictions == 2


def test_tenant_session_caps_and_attributes():
    from citizensassemblies_tpu.service.session import TenantSession

    sess = TenantSession("cap-t", cap=2)
    before = memo_evictions_by_owner().get(sess.owner, 0)
    for i in range(4):
        sess.memo_put(f"fp{i}", object())
    assert sess.memo_get("fp3") is not None
    assert sess.memo_get("fp0") is None  # evicted
    assert memo_evictions_by_owner().get(sess.owner, 0) - before == 2
    assert sess.stats()["evictions"] == 2


# --- decomp_host_syncs gauge -------------------------------------------------


def test_decomp_host_syncs_counts_device_rounds():
    """Forcing device masters on the face loop must tick the gauge once per
    device round trip; the pure host-master run keeps it at zero."""
    from citizensassemblies_tpu.solvers.cg_typespace import (
        CompositionOracle,
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.face_decompose import realize_profile
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    dense, _space = featurize(skewed_instance(n=120, k=12, n_categories=3, seed=1))
    red = TypeReduction(dense)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    seeds = _slice_relaxation(v_relax * red.msize.astype(np.float64), red, R=8)
    # host-master route (CPU default): no device round trips
    log_host = RunLog(echo=False)
    realize_profile(red, v_relax, list(seeds), CompositionOracle(red),
                    accept=5e-3, log=log_host, max_rounds=3, use_pdhg=False)
    assert log_host.counters.get("decomp_host_syncs", 0) == 0
    # device-master route forced: every master is a host↔device round trip
    cfg = default_config().replace(decomp_host_master_max_types=0)
    log_dev = RunLog(echo=False)
    realize_profile(red, v_relax, list(seeds), CompositionOracle(red),
                    accept=5e-3, log=log_dev, max_rounds=3, use_pdhg=True,
                    cfg=cfg)
    assert log_dev.counters.get("decomp_host_syncs", 0) >= 1, log_dev.counters
