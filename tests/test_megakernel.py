"""Fused Pallas PDHG megakernel (``kernels/pdhg_megakernel.py``) — the
contract of ISSUE 14: interpret-mode parity vs the chained ELL iterate
(flagship- and household-quotient-shaped fixtures), tri-state gate semantics
with gate-off bitwise identity, warm-start slot survival across bucket
re-pads, realized donation (IR3), and ``pdhg_nan`` quarantine + host
re-solve through the fused path. All fused runs here use interpret mode
(``pdhg_megakernel=True`` off-TPU); the chained baselines are the default
CPU path (``pdhg_megakernel=False`` or the ``None`` auto-gate, which
resolves to "off" without a real accelerator)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from citizensassemblies_tpu.kernels import pdhg_megakernel as mk
from citizensassemblies_tpu.robust.inject import FaultInjector, use_injector
from citizensassemblies_tpu.solvers.lp_pdhg import (
    solve_lp_ell,
    solve_two_sided_master_ell,
)
from citizensassemblies_tpu.solvers.sparse_ops import EllPack
from citizensassemblies_tpu.utils.config import default_config


def _cfg(**kw):
    return default_config().replace(**kw)


CFG_FUSED = _cfg(pdhg_megakernel=True)
CFG_CHAINED = _cfg(pdhg_megakernel=False)


def _flagship_master(seed=7, T=24, C=96):
    """The bench smoke fixture shape: the sf_e-style composition matrix of
    the decomposition master (counts over T types, scaled by 1/k)."""
    r = np.random.default_rng(seed)
    comps = (r.random((C, T)) < 0.2) * r.integers(1, 4, (C, T))
    MT = (comps / 8.0).T.astype(np.float64)
    v = MT @ np.full(C, 1.0 / C)
    ell = EllPack.from_rows(np.asarray(MT, np.float32).T, minor=T)
    return ell, v


def _household_master(seed=11, T=40, C=64):
    """Household-quotient shape: more types than the flagship fixture
    relative to the column count, sparser integer cells (the product
    type-space of the household quotient, PR 10)."""
    r = np.random.default_rng(seed)
    comps = (r.random((C, T)) < 0.12) * r.integers(1, 3, (C, T))
    comps[:, 0] = 1  # every composition hits the root cell
    MT = (comps / 4.0).T.astype(np.float64)
    v = MT @ np.full(C, 1.0 / C)
    ell = EllPack.from_rows(np.asarray(MT, np.float32).T, minor=T)
    return ell, v


def _master_pair(fixture, **kw):
    ell, v = fixture
    a = solve_two_sided_master_ell(ell, v, cfg=CFG_CHAINED, **kw)
    b = solve_two_sided_master_ell(ell, v, cfg=CFG_FUSED, **kw)
    return a, b


# --- tri-state gate ----------------------------------------------------------


def test_megakernel_mode_tri_state():
    small = mk.two_sided_vmem_bytes(128, 256, 16)
    assert mk.megakernel_mode(_cfg(pdhg_megakernel=False), small) == "off"
    # auto engages only on a real accelerator; this suite runs on CPU
    assert jax.default_backend() != "tpu"
    assert mk.megakernel_mode(_cfg(pdhg_megakernel=None), small) == "off"
    assert mk.megakernel_mode(CFG_FUSED, small) == "interpret"
    # the VMEM fit check applies in EVERY mode: an expansion that cannot
    # stay on-chip falls back to the chained cores rather than spilling
    huge = mk.two_sided_vmem_bytes(4096, 65536, 128)
    assert mk.megakernel_mode(CFG_FUSED, huge) == "off"
    assert mk.megakernel_mode(_cfg(pdhg_megakernel=None), huge) == "off"


def test_gate_off_bitwise_identity():
    """cfg(None) on CPU and cfg(False) are the SAME chained path — gate-off
    must be bit-identical, not merely close."""
    ell, v = _flagship_master()
    auto = solve_two_sided_master_ell(ell, v, cfg=_cfg(pdhg_megakernel=None))
    off = solve_two_sided_master_ell(ell, v, cfg=CFG_CHAINED)
    np.testing.assert_array_equal(auto.x, off.x)
    np.testing.assert_array_equal(auto.lam, off.lam)
    np.testing.assert_array_equal(auto.mu, off.mu)
    assert auto.iters == off.iters and auto.kkt == off.kkt


# --- interpret-mode parity vs the chained ELL iterate ------------------------


def test_parity_flagship_shape():
    a, b = _master_pair(_flagship_master())
    assert a.ok and b.ok
    assert np.max(np.abs(a.x - b.x)) < 5e-4
    assert np.max(np.abs(a.lam - b.lam)) < 5e-4
    assert abs(a.objective - b.objective) < 5e-5


def test_parity_household_quotient_shape():
    a, b = _master_pair(_household_master())
    assert a.ok and b.ok
    assert np.max(np.abs(a.x - b.x)) < 5e-4
    assert np.max(np.abs(a.lam - b.lam)) < 5e-4
    assert abs(a.objective - b.objective) < 5e-5


def test_parity_generic_lp_route():
    """solve_lp_ell (the generic-form consumer) through the fused kernel."""
    r = np.random.default_rng(3)
    nv, m1, m2 = 40, 32, 1
    G = (r.random((m1, nv)) < 0.25) * r.random((m1, nv))
    h = G @ np.full(nv, 1.0 / nv) + 0.01
    A = np.ones((1, nv))
    b = np.ones(1)
    c = r.random(nv)
    ell = EllPack.from_rows(np.asarray(G, np.float32), minor=nv)
    a = solve_lp_ell(c, ell, h, A, b, cfg=CFG_CHAINED)
    bsol = solve_lp_ell(c, ell, h, A, b, cfg=CFG_FUSED)
    assert a.ok and bsol.ok
    assert np.max(np.abs(a.x - bsol.x)) < 5e-4
    assert abs(a.objective - bsol.objective) < 5e-5


def test_parity_batched_polish_screen():
    """solve_polish_screen_ell: per-lane iteration counts match the chained
    vmapped core exactly and iterates agree to float32 op-order noise."""
    from citizensassemblies_tpu.solvers.batch_lp import solve_polish_screen_ell

    ell, v = _flagship_master()
    caps = [96, 48, 24]
    warms = [None] * len(caps)
    off = solve_polish_screen_ell(ell, v, caps, warms, 1e-5, 4096, cfg=CFG_CHAINED)
    on = solve_polish_screen_ell(ell, v, caps, warms, 1e-5, 4096, cfg=CFG_FUSED)
    for a, b in zip(off, on):
        assert a.ok == b.ok
        assert a.iters == b.iters  # per-lane convergence masks agree
        assert np.max(np.abs(a.x - b.x)) < 5e-4


# --- warm-start slot survival across bucket re-pads --------------------------


def test_warm_slot_survives_bucket_repad():
    """A warm triple from a Cp=128 solve is re-sliced into the Cp=256
    bucket when the column count grows past the pad (the CG append path);
    the fused route must consume it exactly like the chained route — warm
    restarts converge in strictly fewer blocks than cold on both paths."""
    ell_small, v = _flagship_master(C=96)  # Cp=128 at bucket=128
    # grow the same master by 64 fresh columns: Cp re-pads 128 → 256
    r7 = np.random.default_rng(7)
    comps = (r7.random((96, 24)) < 0.2) * r7.integers(1, 4, (96, 24))
    r19 = np.random.default_rng(19)
    extra = (r19.random((64, 24)) < 0.2) * r19.integers(1, 4, (64, 24))
    rows = np.concatenate([comps / 8.0, extra / 8.0], axis=0).astype(np.float32)
    ell_big = EllPack.from_rows(rows, minor=24)
    sol_small = solve_two_sided_master_ell(
        ell_small, v, cfg=CFG_FUSED, bucket=128
    )
    warm = (sol_small.x, sol_small.lam, sol_small.mu)
    kw = dict(v=v, warm=warm, bucket=128)
    cold_f = solve_two_sided_master_ell(ell_big, v, cfg=CFG_FUSED, bucket=128)
    warm_f = solve_two_sided_master_ell(ell_big, cfg=CFG_FUSED, **kw)
    cold_c = solve_two_sided_master_ell(ell_big, v, cfg=CFG_CHAINED, bucket=128)
    warm_c = solve_two_sided_master_ell(ell_big, cfg=CFG_CHAINED, **kw)
    assert warm_f.ok and warm_c.ok
    # the slot survived the 128→256 re-pad: warm beats cold on BOTH paths,
    # and fused/chained agree on the warm-started optimum
    assert warm_f.iters < cold_f.iters
    assert warm_c.iters < cold_c.iters
    assert abs(warm_f.objective - warm_c.objective) < 5e-5


# --- realized donation (IR3) -------------------------------------------------


def _alias_count(lowered) -> int:
    return lowered.as_text().count("tf.aliasing_output")


def test_two_sided_core_realizes_donation():
    B, T, C, kp = 2, 24, 96, 8
    r = np.random.default_rng(0)
    idx = jnp.asarray(r.integers(0, T, (C, kp)).astype(np.int32))
    val = jnp.asarray(r.random((C, kp)).astype(np.float32))
    low = mk.two_sided_megakernel_core.lower(
        idx, val, jnp.zeros(T, jnp.float32), jnp.ones((B, C), jnp.float32),
        jnp.zeros((B, C + 1), jnp.float32), jnp.zeros((B, 2 * T), jnp.float32),
        jnp.zeros(B, jnp.float32), jnp.full(B, 1e-6, jnp.float32),
        max_iters=256, check_every=64, sentinel=False, interpret=True,
    )
    assert _alias_count(low) == 2  # x0 and lam0 donate through the pad


def test_lp_core_realizes_donation():
    nv, m1, m2, kp = 40, 32, 1, 8
    r = np.random.default_rng(1)
    idx = jnp.asarray(r.integers(0, nv, (m1, kp)).astype(np.int32))
    val = jnp.asarray(r.random((m1, kp)).astype(np.float32))
    low = mk.lp_megakernel_core.lower(
        jnp.zeros(nv, jnp.float32), idx, val, jnp.ones(m1, jnp.float32),
        jnp.ones((m2, nv), jnp.float32), jnp.ones(m2, jnp.float32),
        jnp.zeros(nv, jnp.float32), jnp.zeros(m1, jnp.float32),
        jnp.zeros(m2, jnp.float32), jnp.asarray(1e-6, jnp.float32),
        max_iters=256, check_every=64, sentinel=False, interpret=True,
    )
    assert _alias_count(low) == 3  # x0, lam0 and mu0


# --- sentinels: quarantine + host re-solve through the fused path ------------


def test_pdhg_nan_quarantine_host_resolve_fused():
    """pdhg_nan poisons the warm start; the in-kernel sentinel must freeze
    the lane (FLAG_POISONED) and solve_lp_ell's float64 host re-solve must
    recover — same ladder as the chained path, now through the kernel."""
    r = np.random.default_rng(3)
    nv, m1 = 40, 32
    G = (r.random((m1, nv)) < 0.25) * r.random((m1, nv))
    h = G @ np.full(nv, 1.0 / nv) + 0.01
    A, b = np.ones((1, nv)), np.ones(1)
    c = r.random(nv)
    ell = EllPack.from_rows(np.asarray(G, np.float32), minor=nv)
    with use_injector(FaultInjector("pdhg_nan:1.0", seed=5)):
        out = solve_lp_ell(c, ell, h, A, b, cfg=CFG_FUSED)
    assert np.all(np.isfinite(out.x))
    assert out.iters == -1  # the certified host optimum, not the frozen lane
    assert out.ok


def test_poisoned_lane_isolated_in_fused_batch():
    """One NaN warm lane through the batched fused screen: that lane is
    quarantined (ok=False, frozen-finite iterate) while its fleet mates are
    BIT-identical to the clean fused dispatch."""
    from citizensassemblies_tpu.solvers.batch_lp import solve_polish_screen_ell

    ell, v = _flagship_master()
    caps = [96, 48, 24]
    clean = solve_polish_screen_ell(
        ell, v, caps, [None] * 3, 1e-5, 4096, cfg=CFG_FUSED
    )
    bad = np.zeros(97, np.float64)
    bad[0] = np.nan
    poisoned_warms = [None, (bad, np.zeros(48), np.zeros(1)), None]
    mixed = solve_polish_screen_ell(
        ell, v, caps, poisoned_warms, 1e-5, 4096, cfg=CFG_FUSED
    )
    # the poisoned lane is quarantined exactly like the chained vmapped
    # core: frozen at iterate 0 (the poisoned input IS the last "iterate",
    # so there is no finite state to freeze at), kkt=inf, ok=False — the
    # screen's caller-side float64 accept check rejects it
    assert not mixed[1].ok
    assert mixed[1].iters == 0 and not np.isfinite(mixed[1].kkt)
    for lane in (0, 2):  # …and its fleet mates never see the NaN
        np.testing.assert_array_equal(mixed[lane].x, clean[lane].x)
        assert mixed[lane].iters == clean[lane].iters
