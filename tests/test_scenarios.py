"""graftscenario tests: dropout-robust leximin, multi-assembly scheduling,
the dropout-realization MC kernel, and the service/scenario integration."""

import jax
import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import random_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.parallel.mc import dropout_realization_round
from citizensassemblies_tpu.parallel.mesh import make_mesh
from citizensassemblies_tpu.scenarios import (
    ScenarioError,
    SchedulingInfeasible,
    find_distribution_dropout,
    find_distribution_multi,
)
from citizensassemblies_tpu.scenarios.dropout import evaluate_realization
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.config import default_config
from citizensassemblies_tpu.utils.logging import RunLog


def _tiny(seed=0, n=24, k=5, n_categories=2):
    return featurize(random_instance(n=n, k=k, n_categories=n_categories, seed=seed))


def _hetero_dropout(n, seed=0, lo=0.0, hi=0.5):
    return np.random.default_rng(seed).uniform(lo, hi, size=n)


# --- dropout-robust leximin ---------------------------------------------------


def test_dropout_contract_and_certified_improvement():
    """The dropout model's certified realized-min dominates the
    attendance-blind leximin's realized-min, and the portfolio realizes the
    selection targets within the 1e-3 contract."""
    dense, space = _tiny(seed=0)
    drop = _hetero_dropout(dense.n, seed=0)
    w = 1.0 - np.clip(drop, 0.0, 0.95)

    d = find_distribution_dropout(dense, space, dropout=drop)
    assert d.contract_ok and d.realization_dev <= 1e-3
    assert "fallback" not in d.scenario_audit
    # exact identity: certified realized values are the attendance-weighted
    # selection targets (up to the bucket-representative quantization)
    assert d.realized_values.shape == (dense.n,)

    plain = find_distribution_leximin(dense, space)
    blind_min = float((w * plain.allocation)[plain.covered].min())
    aware_min = float(d.realized_values[d.covered].min())
    # the dropout objective leximin-maximizes exactly this quantity, so it
    # can only improve on the attendance-blind portfolio (quantization slack
    # is bounded by the audit's recorded L∞ error)
    slack = d.scenario_audit["quantization_linf"] + 1e-6
    assert aware_min >= blind_min - slack
    assert aware_min > blind_min  # strict on this heterogeneous instance


def test_dropout_mc_stamp_and_audit():
    dense, space = _tiny(seed=1)
    cfg = default_config().replace(scenario_mc_draws=512)
    d = find_distribution_dropout(dense, space, dropout=_hetero_dropout(dense.n, 1), cfg=cfg)
    mc = d.scenario_audit["mc"]
    assert mc["policy"] == "type"
    assert mc["draws"] == 512
    assert 0.0 <= mc["realized_min"] <= 1.0
    assert 0.0 < mc["quota_ok_rate"] <= 1.0


def test_dropout_fallback_when_product_space_too_large():
    dense, space = _tiny(seed=2)
    cfg = default_config().replace(enum_max_types=2, scenario_mc_draws=0)
    d = find_distribution_dropout(dense, space, dropout=_hetero_dropout(dense.n, 2), cfg=cfg)
    assert "fallback" in d.scenario_audit
    assert d.contract_ok  # the selection-space certificate still holds


def test_dropout_requires_dropout_and_rejects_households():
    dense, space = _tiny(seed=0)
    with pytest.raises(ScenarioError):
        find_distribution_dropout(dense, space, dropout=None)
    with pytest.raises(ScenarioError):
        find_distribution_dropout(
            dense, space, dropout=np.zeros(dense.n),
            households=np.zeros(dense.n, dtype=np.int64),
        )


# --- dropout-realization MC kernel -------------------------------------------


def _exact_realization(P, probs, w, type_id, policy):
    """Exact expected seating frequency by enumerating the 2^k attendance
    patterns of every support panel (the small-case oracle of the MC
    kernel's acceptance test)."""
    import itertools

    n = P.shape[1]
    freq = np.zeros(n)
    for row, pc in zip(P, probs):
        S = np.nonzero(row)[0]
        off = np.nonzero(~row)[0]
        for pattern in itertools.product([0, 1], repeat=len(S)):
            pa = 1.0
            shows = []
            noshows = []
            for i, bit in zip(S, pattern):
                if bit:
                    pa *= w[i]
                    shows.append(i)
                else:
                    pa *= 1.0 - w[i]
                    noshows.append(i)
            contrib = np.zeros(n)
            contrib[shows] = 1.0
            if policy == "type" and noshows:
                for t in set(type_id[noshows].tolist()):
                    need = sum(1 for i in noshows if type_id[i] == t)
                    cand = off[type_id[off] == t]
                    if len(cand):
                        contrib[cand] += min(need, len(cand)) / len(cand)
            elif policy == "naive" and noshows:
                contrib[off] += min(len(noshows), len(off)) / len(off)
            freq += pc * pa * contrib
    return freq


@pytest.mark.parametrize("policy", ["none", "type", "naive"])
def test_dropout_mc_matches_exact_enumeration(policy):
    """Satellite: the realized-attendance distribution of the MC kernel
    matches an exact small-case enumeration for every replacement policy."""
    dense, _ = _tiny(seed=3, n=18, k=4)
    red = TypeReduction(dense)
    P = np.zeros((3, dense.n), dtype=bool)
    P[0, [0, 1, 2, 3]] = True
    P[1, [4, 5, 6, 7]] = True
    P[2, [2, 5, 9, 12]] = True
    probs = np.array([0.5, 0.3, 0.2])
    w = np.linspace(0.45, 0.95, dense.n)
    draws = 60_000
    real = dropout_realization_round(
        P, probs, w, red.type_id, dense, jax.random.PRNGKey(11), draws, policy=policy
    )
    exact = _exact_realization(P, probs, w, red.type_id, policy)
    # 4σ of the per-agent binomial noise at p=0.5
    tol = 4.0 * 0.5 / np.sqrt(draws)
    assert np.abs(real.frequencies - exact).max() < tol


@pytest.mark.parametrize("policy", ["none", "type", "naive"])
def test_dropout_mc_mesh_bit_identical(policy):
    """Satellite: the chain-sharded path on a 1-device mesh is bit-identical
    to the plain vmapped path (same global key stream)."""
    dense, _ = _tiny(seed=4, n=20, k=4)
    red = TypeReduction(dense)
    P = np.zeros((2, dense.n), dtype=bool)
    P[0, [0, 1, 2, 3]] = True
    P[1, [4, 5, 6, 7]] = True
    probs = np.array([0.6, 0.4])
    w = np.linspace(0.5, 1.0, dense.n)
    key = jax.random.PRNGKey(5)
    a = dropout_realization_round(P, probs, w, red.type_id, dense, key, 128, policy=policy)
    b = dropout_realization_round(
        P, probs, w, red.type_id, dense, key, 128, policy=policy, mesh=make_mesh(1)
    )
    assert np.array_equal(a.counts, b.counts)
    assert a.quota_ok_rate == b.quota_ok_rate


def test_dropout_beats_naive_redraw_baseline_mc():
    """Acceptance: dropout-aware portfolio + type replacement beats the
    attendance-blind portfolio + naive re-draw on MC realized-min."""
    dense, space = _tiny(seed=0)
    drop = _hetero_dropout(dense.n, seed=0)
    cfg = default_config().replace(scenario_mc_draws=0)
    d = find_distribution_dropout(dense, space, dropout=drop, cfg=cfg)
    plain = find_distribution_leximin(dense, space, cfg=cfg)

    class _Baseline:
        committees = plain.committees
        probabilities = plain.probabilities
        attendance = d.attendance
        type_id = TypeReduction(dense).type_id
        covered = plain.covered

    draws = 8_192
    ours = evaluate_realization(d, dense, draws=draws, policy="type", seed=0)
    base = evaluate_realization(_Baseline(), dense, draws=draws, policy="naive", seed=0)
    assert ours["realized_min"] > base["realized_min"]


# --- multi-assembly scheduling -----------------------------------------------


def test_multi_zero_repeats_contract_and_pair_gauge():
    dense, space = _tiny(seed=0)
    R = 3
    m = find_distribution_multi(dense, space, rounds=R)
    assert m.contract_ok and m.realization_dev <= 1e-3
    assert len(m.round_portfolios) == R == len(m.round_probabilities)
    # pair gauge is against the uniform pair value and must carry real mass
    assert m.pair_uniform > 0 and m.pair_ratio >= 1.0 - 1e-9
    assert m.scenario_audit["model"] == "multi"
    # zero repeats on every drawn schedule
    for seed in range(5):
        sched = m.realize(seed=seed)
        assert sched.shape == (R, dense.k)
        flat = sched.ravel()
        assert len(np.unique(flat)) == flat.size, "agent seated twice"


def test_multi_aggregate_certificate_caps():
    """Aggregate (≥1-of-R) values are true probabilities: within [0, 1] and
    consistent with the capped composition support."""
    dense, space = _tiny(seed=5)
    m = find_distribution_multi(dense, space, rounds=2)
    assert np.all(m.fixed_probabilities <= 1.0 + 1e-9)
    assert np.all(m.fixed_probabilities >= -1e-12)
    assert float(m.allocation.sum()) == pytest.approx(2 * dense.k, abs=1e-6)


def test_multi_rfold_fleet_through_batch_lp():
    """The R per-round ε-LPs go through the batched engine as one fleet
    (cross-fleet bucketing: ≥ R solves, at least one dispatch)."""
    dense, space = _tiny(seed=0)
    log = RunLog(echo=False)
    cfg = default_config().replace(lp_batch=True)
    R = 3
    m = find_distribution_multi(dense, space, rounds=R, cfg=cfg, log=log)
    assert m.scenario_audit["fleet_backend"] == "batch_lp"
    assert log.counters.get("lp_batch_solves", 0) >= R
    assert log.counters.get("lp_batch_dispatches", 0) >= 1
    assert m.contract_ok


def test_multi_infeasible_rounds():
    dense, space = _tiny(seed=0, n=12, k=5)
    with pytest.raises(SchedulingInfeasible):
        find_distribution_multi(dense, space, rounds=4)


def test_multi_rejects_households_and_bad_rounds():
    dense, space = _tiny(seed=0)
    with pytest.raises(ScenarioError):
        find_distribution_multi(
            dense, space, rounds=2, households=np.zeros(dense.n, dtype=np.int64)
        )
    with pytest.raises(ScenarioError):
        find_distribution_multi(dense, space, rounds=0)


# --- service integration ------------------------------------------------------


def test_service_scenario_algorithms():
    from citizensassemblies_tpu.service.server import SelectionRequest, SelectionService

    cfg = default_config().replace(scenario_mc_draws=256)
    svc = SelectionService(cfg)
    try:
        inst = random_instance(n=24, k=5, n_categories=2, seed=1)
        drop = _hetero_dropout(24, seed=1, hi=0.4)
        r1 = svc.submit(
            SelectionRequest(algorithm="dropout", instance=inst, dropout=drop)
        ).result(timeout=600)
        assert r1.audit["scenario"]["model"] == "dropout"
        assert "mc" in r1.audit["scenario"]
        assert r1.audit["contract_ok"]

        r2 = svc.submit(
            SelectionRequest(algorithm="multi", instance=inst, rounds=2)
        ).result(timeout=600)
        assert r2.audit["scenario"]["model"] == "multi"
        assert r2.audit["scenario"]["pair_ratio"] >= 1.0 - 1e-9

        # a dropout request without the dropout vector is a clean error
        with pytest.raises(RuntimeError):
            svc.submit(
                SelectionRequest(algorithm="dropout", instance=inst)
            ).result(timeout=600)
    finally:
        svc.shutdown()


def test_service_dropout_fingerprint_distinguishes_profiles():
    """Two dropout requests on the same instance with different no-show
    vectors must not share a memo fingerprint."""
    from citizensassemblies_tpu.service.server import SelectionRequest, SelectionService

    cfg = default_config()
    svc = SelectionService(cfg)
    try:
        dense, _ = _tiny(seed=0)
        r_a = SelectionRequest(algorithm="dropout", dense=dense, dropout=np.full(dense.n, 0.1))
        r_b = SelectionRequest(algorithm="dropout", dense=dense, dropout=np.full(dense.n, 0.3))
        r_m = SelectionRequest(algorithm="multi", dense=dense, rounds=2)
        r_m2 = SelectionRequest(algorithm="multi", dense=dense, rounds=3)
        fps = {
            svc._fingerprint(r, dense, cfg) for r in (r_a, r_b, r_m, r_m2)
        }
        assert len(fps) == 4
    finally:
        svc.shutdown()


# --- gate-off parity ----------------------------------------------------------


def test_existing_models_bit_identical_with_scenarios_unused():
    """Acceptance: with the scenario knobs changed but scenarios unused, the
    existing models produce bit-identical results — the subsystem is inert
    unless invoked."""
    dense, space = _tiny(seed=0)
    base = find_distribution_leximin(dense, space, cfg=default_config())
    tweaked = find_distribution_leximin(
        dense,
        space,
        cfg=default_config().replace(
            scenario_dropout_buckets=9,
            scenario_replacement="naive",
            scenario_rounds=7,
            scenario_mc_draws=17,
        ),
    )
    assert np.array_equal(base.allocation, tweaked.allocation)
    assert np.array_equal(base.probabilities, tweaked.probabilities)
    assert np.array_equal(base.committees, tweaked.committees)
