"""LEGACY sampler tests: feasibility of every accepted panel, rejection
semantics, and distribution-level agreement with the reference's golden
Monte-Carlo statistics (reference_output/example_small_20_statistics.txt)."""

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import random_instance
from citizensassemblies_tpu.core.instance import SelectionError, featurize
from citizensassemblies_tpu.models.legacy import (
    legacy_probabilities,
    sample_feasible_panels,
)
from citizensassemblies_tpu.ops.stats import prob_allocation_stats
from citizensassemblies_tpu.utils.config import Config


def assert_panels_feasible(panels, dense):
    A = np.asarray(dense.A)
    qmin = np.asarray(dense.qmin)
    qmax = np.asarray(dense.qmax)
    for panel in panels:
        assert len(set(panel.tolist())) == dense.k, "duplicate agent in panel"
        counts = A[panel].sum(axis=0)
        assert (counts >= qmin).all(), f"lower quota violated: {counts} vs {qmin}"
        assert (counts <= qmax).all(), f"upper quota violated: {counts} vs {qmax}"


def test_sampled_panels_satisfy_quotas(example_small):
    dense, _ = featurize(example_small)
    panels, draws = sample_feasible_panels(dense, num=300, seed=0)
    assert panels.shape == (300, 20)
    assert draws >= 300
    assert_panels_feasible(panels, dense)


def test_sampled_panels_satisfy_quotas_random_instances():
    for seed in range(3):
        inst = random_instance(n=120, k=15, n_categories=3, seed=seed)
        dense, _ = featurize(inst)
        panels, _ = sample_feasible_panels(dense, num=64, seed=seed)
        assert_panels_feasible(panels, dense)


def test_determinism():
    inst = random_instance(n=80, k=10, n_categories=2, seed=3)
    dense, _ = featurize(inst)
    p1, _ = sample_feasible_panels(dense, num=32, seed=7)
    p2, _ = sample_feasible_panels(dense, num=32, seed=7)
    np.testing.assert_array_equal(p1, p2)


def test_infeasible_raises():
    # k=10 but one feature has min=max=0 while holding the whole pool: the
    # pool empties before the panel fills -> every draw fails
    inst = random_instance(n=40, k=10, n_categories=1, features_per_category=2, seed=0)
    cat = list(inst.categories)[0]
    feats = list(inst.categories[cat])
    # demand 10 members of a feature only 3 agents have
    for agent in inst.agents[:37]:
        agent[cat] = feats[0]
    for agent in inst.agents[37:]:
        agent[cat] = feats[1]
    inst.categories[cat][feats[0]] = (0, 0)
    inst.categories[cat][feats[1]] = (10, 10)
    dense, _ = featurize(inst)
    cfg = Config(mc_max_resample_rounds=3, mc_batch=64)
    with pytest.raises(SelectionError):
        sample_feasible_panels(dense, num=16, seed=0, cfg=cfg)


def test_legacy_statistics_match_reference_within_mc_noise(example_small):
    """Golden check: reference_output/example_small_20_statistics.txt reports
    (from 10,000 draws) gini 2.1%, geometric mean 9.9%, min probability 0.96%,
    and 10,000 unique panels for LEGACY. MC-gini carries a positive noise bias
    that shrinks with draw count, so the comparison runs at the reference's
    full 10,000 draws (verified: the reference's own sampler at 4,000 draws
    reads gini 3.0%)."""
    dense, _ = featurize(example_small)
    res = legacy_probabilities(dense, iterations=10_000, seed=0)
    assert res.allocation.sum() == pytest.approx(20.0, rel=1e-9)  # k per draw
    assert len(res.unique_panels) == 10_000  # golden: 10000 unique in 10000 draws
    stats = prob_allocation_stats(res.allocation, cap_for_geometric_mean=True)
    assert stats.gini == pytest.approx(0.021, abs=0.004)
    assert stats.geometric_mean == pytest.approx(0.099, abs=0.002)
    assert 0.005 <= stats.min <= 0.016
    # mean selection probability must be k/n = 10% exactly
    assert res.allocation.mean() == pytest.approx(0.1, rel=1e-9)
    # pair matrix total mass: each draw contributes k*(k-1) ordered pairs
    total = res.pair_matrix.sum()
    assert total == pytest.approx(20 * 19, rel=1e-4)


def test_legacy_respects_tight_quotas():
    # min == max quotas: every panel composition is forced exactly
    inst = random_instance(n=100, k=12, n_categories=1, features_per_category=3, seed=5)
    cat = list(inst.categories)[0]
    dense0, _ = featurize(inst)
    A = np.asarray(dense0.A)
    counts = A.sum(axis=0)
    feats = list(inst.categories[cat])
    # force exact cell counts 4/4/4
    inst.categories[cat] = {feats[0]: (4, 4), feats[1]: (4, 4), feats[2]: (4, 4)}
    dense, _ = featurize(inst)
    panels, _ = sample_feasible_panels(dense, num=50, seed=1)
    assert_panels_feasible(panels, dense)
