"""The batched shape-bucketed LP/QP engine (``solvers/batch_lp.py``).

Contracts pinned here:

* **Batch-vs-serial parity** — a fleet solved by the vmapped engine matches
  the serial PDHG solver per instance (same iteration body, two dispatch
  shapes), and a full LEXIMIN run with the engine on certifies the same
  values/ε as the engine-off run on flagship-shaped and household fixtures.
* **Per-instance convergence masks** — an easy instance sharing a bucket
  with a hard one is select-frozen at ITS OWN convergence: same solution
  and same recorded iteration count as when solved alone.
* **Warm-start slots survive a bucket re-pad** — a caller-keyed slot saved
  at one column count is re-padded into a larger bucket when the instance
  grows, including the structural ε tail variable.
* **Prescreen soundness** — the device probe prescreen never prunes a
  candidate the float64 host LP would certify tight: every pruned candidate
  is verified genuinely loose by an exact host solve.
* **Sharded sweeps** — the mesh-sharded batch axis returns the same
  solutions as the single-device engine (8-device virtual CPU mesh).
* **Serial fallback** — with ``lp_batch`` off, no engine counter appears:
  the call sites run their serial paths untouched.
"""

import numpy as np
import pytest

from citizensassemblies_tpu.solvers.batch_lp import (
    BatchLP,
    clear_warm_slots,
    final_primal_batch_lp,
    lp_batch_enabled,
    solve_lp_batch,
    two_sided_master_batch_lp,
)
from citizensassemblies_tpu.solvers.lp_pdhg import solve_lp
from citizensassemblies_tpu.utils.config import default_config
from citizensassemblies_tpu.utils.logging import RunLog

CFG_ON = default_config().replace(lp_batch=True)
CFG_OFF = default_config().replace(lp_batch=False)


def _final_primal_fleet(n_inst=6, seed=0):
    """Feasible final-ε LPs of varied small shapes (targets realizable)."""
    rng = np.random.default_rng(seed)
    fleet = []
    for i in range(n_inst):
        C, n = 18 + 4 * i, 9 + i
        P = rng.random((C, n)) < 0.5
        P[:n, :n] |= np.eye(n, dtype=bool)
        q = rng.random(C)
        q /= q.sum()
        fleet.append(final_primal_batch_lp(P, P.T.astype(np.float64) @ q))
    return fleet


def test_batch_matches_serial_per_instance():
    fleet = _final_primal_fleet()
    log = RunLog(echo=False)
    batch = solve_lp_batch(fleet, cfg=CFG_ON, log=log, max_iters=30_000)
    for inst, sol in zip(fleet, batch):
        ser = solve_lp(inst.c, inst.G, inst.h, inst.A, inst.b, cfg=CFG_ON)
        assert sol.ok and ser.ok
        assert abs(sol.objective - ser.objective) <= 1e-4
        assert sol.x.shape == ser.x.shape  # real sizes, bucket pad stripped
        assert sol.lam.shape == ser.lam.shape
    # solves-per-dispatch: every instance solved, ≤ one dispatch per bucket
    assert log.counters["lp_batch_solves"] == len(fleet)
    n_buckets = sum(
        1 for k in log.counters if k.startswith("lp_batch_compiles_")
    )
    assert log.counters["lp_batch_dispatches"] == n_buckets


def test_convergence_mask_freezes_early_finisher():
    """An easy lane bucketed with a hard one converges to its OWN result:
    identical solution and identical recorded iteration count as solo."""
    rng = np.random.default_rng(3)
    n = 10
    P_easy = np.eye(n, dtype=bool)  # trivial: p = t realizes exactly
    t_easy = np.full(n, 1.0 / n)
    easy = final_primal_batch_lp(P_easy, t_easy)
    C = 10  # same shape bucket as easy (n+... rows, C+1 cols)
    P_hard = rng.random((C, n)) < 0.5
    t_hard = np.clip(
        P_hard.T.astype(np.float64) @ np.full(C, 1.0 / C)
        + rng.normal(0, 5e-3, n),
        0.0,
        1.0,
    )
    hard = final_primal_batch_lp(P_hard, t_hard)
    solo = solve_lp_batch([easy], cfg=CFG_ON, max_iters=30_000)[0]
    both = solve_lp_batch([easy, hard], cfg=CFG_ON, max_iters=30_000)
    assert both[0].ok
    assert both[0].iters == solo.iters  # frozen at its own convergence
    np.testing.assert_allclose(both[0].x, solo.x, atol=1e-6)
    # the hard lane genuinely ran longer — the mask wasn't a global stop
    assert both[1].iters >= both[0].iters


def test_warm_slots_survive_bucket_repad():
    """A caller-keyed warm slot saved at one column bucket re-pads into a
    larger bucket when the instance grows, ε tail slot included, and the
    warm call converges at least as fast as the cold one."""
    clear_warm_slots("test_repad")
    rng = np.random.default_rng(4)
    T, C = 12, 28  # C+1 = 29 → bucket 32
    MT = rng.uniform(0.0, 1.0, (T, C))
    v = MT @ rng.dirichlet(np.ones(C))
    log = RunLog(echo=False)
    first = solve_lp_batch(
        [two_sided_master_batch_lp(MT, v)], cfg=CFG_ON, log=log,
        warm_key="test_repad", max_iters=40_000,
    )[0]
    assert first.ok
    # grow past the bucket boundary: 28 → 40 columns ⇒ bucket 32 → 64
    MT2 = np.concatenate([MT, rng.uniform(0.0, 1.0, (T, 12))], axis=1)
    log2 = RunLog(echo=False)
    warm = solve_lp_batch(
        [two_sided_master_batch_lp(MT2, v)], cfg=CFG_ON, log=log2,
        warm_key="test_repad", max_iters=40_000,
    )[0]
    assert warm.ok
    assert log2.counters.get("lp_batch_warm_hits", 0) == 1
    assert len(warm.x) == MT2.shape[1] + 1  # real size, ε slot last
    # the grown problem keeps the old columns, so the re-padded iterate is
    # near-feasible: it must not be slower than a cold start
    cold = solve_lp_batch(
        [two_sided_master_batch_lp(MT2, v)], cfg=CFG_ON, max_iters=40_000
    )[0]
    assert warm.iters <= cold.iters
    p_w = np.maximum(warm.x[:-1], 0.0)
    p_w /= p_w.sum()
    p_c = np.maximum(cold.x[:-1], 0.0)
    p_c /= p_c.sum()
    eps_w = float(np.abs(MT2 @ p_w - v).max())
    eps_c = float(np.abs(MT2 @ p_c - v).max())
    assert eps_w <= eps_c + 5e-5  # warm is exactness-neutral


def test_probe_prescreen_never_prunes_a_tight_candidate():
    """Soundness: every candidate the device screen prunes is verified
    GENUINELY loose by the exact float64 host LP — i.e. the host probe
    could never have confirmed it. Fuzzed over seeds; the screen is also
    required to actually fire (prune something) on at least one seed, so
    the assertion is not vacuous."""
    from citizensassemblies_tpu.solvers.compositions import (
        _SLACK,
        _batched_probe_prescreen,
    )
    from citizensassemblies_tpu.solvers.lp_util import robust_linprog

    pruned_total = 0
    for seed in range(4):
        rng = np.random.default_rng(seed)
        T, C = 8, 30
        MT = rng.uniform(0.0, 1.0, (T, C))
        p0 = rng.dirichlet(np.ones(C))
        z = float((MT @ p0).min())
        # the stage's optimal face: every type ≥ z − slack, Σp = 1
        A_face = -MT
        b_face = np.full(T, -(z - _SLACK))
        objectives = MT.copy()  # one candidate per type
        allowances = np.full(T, 1e-6)
        probe_tol = 1e-7
        loose = _batched_probe_prescreen(
            objectives, A_face, b_face, z, probe_tol, allowances,
            CFG_ON, log=RunLog(echo=False),
        )
        assert loose is not None
        for i in np.nonzero(loose)[0]:
            r = robust_linprog(
                -objectives[i], A_ub=A_face, b_ub=b_face,
                A_eq=np.ones((1, C)), b_eq=[1.0], bounds=[(0, None)] * C,
            )
            assert r.status == 0
            host_max = float(-r.fun)
            # host face max strictly above the certificate bound ⇒ the host
            # probe would NOT have confirmed this candidate either
            assert host_max > z + probe_tol + allowances[i], (
                f"seed {seed}: pruned candidate {i} is tight "
                f"(host max {host_max:.2e} ≤ bound)"
            )
        pruned_total += int(loose.sum())
    assert pruned_total > 0  # the screen genuinely fired somewhere


def test_prescreen_disabled_returns_none():
    from citizensassemblies_tpu.solvers.compositions import (
        _batched_probe_prescreen,
    )

    obj = np.eye(3)
    out = _batched_probe_prescreen(
        obj, -obj, np.zeros(3), 0.0, 1e-7, np.full(3, 1e-6),
        CFG_ON.replace(lp_batch_screen=False), log=None,
    )
    assert out is None
    out = _batched_probe_prescreen(
        obj, -obj, np.zeros(3), 0.0, 1e-7, np.full(3, 1e-6),
        CFG_OFF, log=None,
    )
    assert out is None


def test_leximin_parity_engine_on_vs_off_flagship_shaped():
    """Same certified leximin values and realization ε (within float64
    noise) with the engine on vs off, on a small flagship-shaped (CG
    type-space) fixture."""
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin

    dense, space = featurize(skewed_instance(n=120, k=12, n_categories=3, seed=1))
    log_on, log_off = RunLog(echo=False), RunLog(echo=False)
    d_on = find_distribution_leximin(dense, space, cfg=CFG_ON, log=log_on)
    d_off = find_distribution_leximin(dense, space, cfg=CFG_OFF, log=log_off)
    assert (
        float(np.abs(d_on.fixed_probabilities - d_off.fixed_probabilities).max())
        <= 1e-9
    )
    assert abs(d_on.realization_dev - d_off.realization_dev) <= 1e-6
    # the engine-off run must not have touched the engine at all
    assert not any(k.startswith("lp_batch") for k in log_off.counters)


def test_leximin_parity_engine_on_vs_off_households():
    """Same parity contract on a household-quotient fixture (the
    households_n1200 bench row's shape class, scaled down)."""
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin

    dense, space = featurize(skewed_instance(n=80, k=10, n_categories=3, seed=2))
    hh = np.arange(80) // 2
    d_on = find_distribution_leximin(dense, space, cfg=CFG_ON, households=hh)
    d_off = find_distribution_leximin(dense, space, cfg=CFG_OFF, households=hh)
    assert (
        float(np.abs(d_on.fixed_probabilities - d_off.fixed_probabilities).max())
        <= 1e-9
    )
    assert abs(d_on.realization_dev - d_off.realization_dev) <= 1e-6


def test_l2_fused_matches_serial_within_tolerance():
    """The fused anchor+ascent device call reaches the same ε floor and an
    equivalent spread as the serial two-dispatch path."""
    from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2

    rng = np.random.default_rng(7)
    C, n = 100, 24
    P = rng.random((C, n)) < 0.35
    P[:n, :n] |= np.eye(n, dtype=bool)
    donor = np.zeros(C)
    donor[:30] = rng.random(30)
    donor /= donor.sum()
    t = np.clip(
        P[:30].T.astype(np.float64) @ donor[:30] + rng.normal(0, 2e-3, n),
        0.0, 1.0,
    )
    log_s, log_f = RunLog(echo=False), RunLog(echo=False)
    p_s, e_s = solve_final_primal_l2(
        P, t, iters=4000, log=log_s, floor_donor=donor, cfg=CFG_OFF,
        anchor_if_above=1e-4,
    )
    p_f, e_f = solve_final_primal_l2(
        P, t, iters=4000, log=log_f, floor_donor=donor, cfg=CFG_ON,
        anchor_if_above=1e-4,
    )
    assert log_f.counters.get("lp_batch_l2_fused") == 1
    assert "l2_fused" in log_f.timers
    assert "l2_eps_pdhg" in log_s.timers  # the serial path stayed serial
    PT = P.T.astype(np.float64)
    dev_s = float(np.abs(PT @ p_s - t).max())
    dev_f = float(np.abs(PT @ p_f - t).max())
    assert abs(e_f - e_s) <= 5e-5  # same float64 ε floor
    assert dev_f <= dev_s + 1e-4  # equivalent realized deviation
    # the fused spread is a genuine spread, not a degenerate point
    assert (p_f > 1e-11).sum() >= (donor > 1e-11).sum()


def test_polish_screen_certifies_at_the_bar(monkeypatch):
    """The batched polish-face screen returns only arithmetically certified
    mixtures: whatever candidate it accepts satisfies ‖Mp − v‖∞ ≤ bar in
    float64 — the accept-bar semantics are identical to the serial path."""
    import citizensassemblies_tpu.solvers.face_decompose as fd
    from citizensassemblies_tpu.core.generator import skewed_instance
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.solvers.cg_typespace import (
        CompositionOracle,
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    monkeypatch.setattr(fd, "_POLISH_SCREEN_MIN_SUP", 0)
    dense, _ = featurize(skewed_instance(n=120, k=12, n_categories=3, seed=2))
    red = TypeReduction(dense)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    seeds = _slice_relaxation(
        v_relax * red.msize.astype(np.float64), red, R=4
    )
    cfg = CFG_ON.replace(decomp_host_master_max_types=0)
    log = RunLog(echo=False)
    C_sup, probs, eps, _solves = fd.realize_profile(
        red, v_relax, list(seeds), CompositionOracle(red), 1e-5,
        log=log, max_rounds=3, use_pdhg=True, cfg=cfg,
    )
    # the screen ran as ONE fused dispatch per polish attempt
    assert log.counters.get("lp_batch_dispatches", 0) >= 1
    hit = log.counters.get("lp_batch_polish_hit", 0)
    miss = log.counters.get("lp_batch_polish_miss", 0)
    assert hit + miss >= 1
    # float64 arithmetic certificate of whatever was returned
    mix = probs @ (C_sup.astype(np.float64) / red.msize[None, :])
    assert float(np.abs(mix - v_relax).max()) <= eps + 1e-12


def test_sweep_sharded_matches_single_device():
    """The mesh-sharded batch axis (8 virtual CPU devices) returns the same
    per-instance solutions as the single-device engine."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs the multi-device virtual mesh")
    from citizensassemblies_tpu.parallel.mesh import default_mesh
    from citizensassemblies_tpu.parallel.sweep import sweep_final_primal_eps

    rng = np.random.default_rng(11)
    ports, tgts = [], []
    for i in range(5):
        C, n = 20 + 4 * i, 10 + i
        P = rng.random((C, n)) < 0.5
        q = rng.random(C)
        q /= q.sum()
        ports.append(P)
        tgts.append(P.T.astype(np.float64) @ q)
    log = RunLog(echo=False)
    sharded = sweep_final_primal_eps(
        ports, tgts, cfg=CFG_ON, log=log, mesh=default_mesh()
    )
    single = sweep_final_primal_eps(ports, tgts, cfg=CFG_ON, mesh=None)
    assert log.counters.get("lp_batch_dispatches", 0) >= 1
    for (p_sh, e_sh), (p_si, e_si) in zip(sharded, single):
        np.testing.assert_allclose(p_sh, p_si, atol=1e-5)
        assert abs(e_sh - e_si) <= 1e-5
        assert e_sh <= 1e-4  # realizable targets: the downward deficit ~0


def test_lp_batch_enabled_resolution():
    """Tri-state knob: forced on/off wins; auto follows the backend (CPU in
    this suite ⇒ auto-off)."""
    assert lp_batch_enabled(CFG_ON)
    assert not lp_batch_enabled(CFG_OFF)
    assert not lp_batch_enabled(default_config())  # auto on CPU


def test_empty_and_single_instance_batches():
    assert solve_lp_batch([], cfg=CFG_ON) == []
    inst = _final_primal_fleet(n_inst=1)[0]
    sol = solve_lp_batch([inst], cfg=CFG_ON, max_iters=20_000)[0]
    ser = solve_lp(inst.c, inst.G, inst.h, inst.A, inst.b, cfg=CFG_ON)
    assert sol.ok
    assert abs(sol.objective - ser.objective) <= 1e-4


def test_generic_batchlp_with_inequalities_only():
    """A bucket mixing instances with different row counts still pads
    soundly (zero rows are 0 ≤ 0 constraints)."""
    rng = np.random.default_rng(5)
    fleet = []
    for i in range(3):
        nv, m1 = 6, 4 + i
        G = rng.uniform(-1.0, 1.0, (m1, nv))
        x_feas = rng.uniform(0.1, 1.0, nv)
        h = G @ x_feas + 0.1
        c = rng.uniform(0.0, 1.0, nv)  # c ≥ 0 and x ≥ 0 ⇒ bounded below
        A = np.ones((1, nv))
        b = np.array([x_feas.sum()])
        fleet.append(BatchLP(c=c, G=G, h=h, A=A, b=b))
    sols = solve_lp_batch(fleet, cfg=CFG_ON, max_iters=40_000, common_bucket=True)
    for inst, sol in zip(fleet, sols):
        ser = solve_lp(inst.c, inst.G, inst.h, inst.A, inst.b, cfg=CFG_ON)
        assert abs(sol.objective - ser.objective) <= 5e-4
