"""LEXIMIN correctness: brute-force comparison on tiny instances, golden-value
checks on reference instances, and property tests (quota feasibility of every
committee, allocation consistency)."""

import itertools

import numpy as np
import pytest
from scipy.optimize import linprog

from citizensassemblies_tpu.core.generator import random_instance
from citizensassemblies_tpu.core.instance import (
    InfeasibleQuotasError,
    featurize,
    read_instance_dir,
)
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.ops.stats import prob_allocation_stats
from citizensassemblies_tpu.utils.config import default_config


def brute_force_leximin(A, qmin, qmax, k):
    """Independent exact leximin over the full feasible-panel polytope:
    iterative primal LPs with per-agent improvement tests (no strict
    complementarity shortcut)."""
    n = A.shape[0]
    panels = [
        c
        for c in itertools.combinations(range(n), k)
        if (A[list(c)].sum(0) >= qmin).all() and (A[list(c)].sum(0) <= qmax).all()
    ]
    P = np.zeros((len(panels), n))
    for r, c in enumerate(panels):
        P[r, list(c)] = 1
    fixed = np.full(n, -1.0)
    while (fixed < 0).any():
        nv = len(panels) + 1  # [p, z]
        c_obj = np.zeros(nv)
        c_obj[-1] = -1
        A_ub, b_ub = [], []
        for i in range(n):
            row = np.zeros(nv)
            row[: len(panels)] = -P[:, i]
            if fixed[i] < 0:
                row[-1] = 1
                b_ub.append(0.0)
            else:
                b_ub.append(-fixed[i])
            A_ub.append(row)
        A_eq = np.zeros((1, nv))
        A_eq[0, : len(panels)] = 1
        res = linprog(c_obj, A_ub=np.array(A_ub), b_ub=np.array(b_ub), A_eq=A_eq,
                      b_eq=[1.0], bounds=(0, None), method="highs")
        z = -res.fun
        for i in np.nonzero(fixed < 0)[0]:
            c2 = np.zeros(nv)
            c2[: len(panels)] = -P[:, i]
            A_ub2 = A_ub + [np.eye(1, nv, nv - 1)[0] * -1]
            b_ub2 = b_ub + [-z + 1e-9]
            r2 = linprog(c2, A_ub=np.array(A_ub2), b_ub=np.array(b_ub2), A_eq=A_eq,
                         b_eq=[1.0], bounds=(0, None), method="highs")
            if -r2.fun <= z + 1e-7:
                fixed[i] = z
    return fixed


def assert_committees_feasible(dist, dense):
    A = np.asarray(dense.A)
    qmin = np.asarray(dense.qmin)
    qmax = np.asarray(dense.qmax)
    counts = dist.committees.astype(int) @ A
    assert (dist.committees.sum(axis=1) == dense.k).all()
    assert (counts >= qmin).all() and (counts <= qmax).all()
    assert dist.probabilities.sum() == pytest.approx(1.0, abs=1e-9)
    np.testing.assert_allclose(
        dist.allocation, dist.committees.T.astype(float) @ dist.probabilities, atol=1e-12
    )


def test_leximin_matches_bruteforce_asymmetric():
    inst = random_instance(n=12, k=3, n_categories=1, features_per_category=2, seed=2)
    cat = list(inst.categories)[0]
    feats = list(inst.categories[cat])
    for i, agent in enumerate(inst.agents):
        agent[cat] = feats[0] if i < 9 else feats[1]
    inst.categories[cat][feats[0]] = (1, 2)
    inst.categories[cat][feats[1]] = (1, 2)
    dense, space = featurize(inst)
    brute = brute_force_leximin(
        np.asarray(dense.A), np.asarray(dense.qmin), np.asarray(dense.qmax), dense.k
    )
    dist = find_distribution_leximin(dense, space)
    # leximin values: 2/9 for the 9 majority agents, 1/3 for the 3 minority
    np.testing.assert_allclose(brute[:9], 2 / 9, atol=1e-9)
    np.testing.assert_allclose(brute[9:], 1 / 3, atol=1e-9)
    np.testing.assert_allclose(dist.allocation, brute, atol=5e-6)
    assert_committees_feasible(dist, dense)


def test_leximin_matches_bruteforce_random():
    for seed in (4, 9):
        inst = random_instance(n=10, k=3, n_categories=2, features_per_category=2, seed=seed)
        dense, space = featurize(inst)
        brute = brute_force_leximin(
            np.asarray(dense.A), np.asarray(dense.qmin), np.asarray(dense.qmax), dense.k
        )
        dist = find_distribution_leximin(dense, space)
        np.testing.assert_allclose(dist.allocation, brute, atol=5e-6)
        assert_committees_feasible(dist, dense)


def test_leximin_example_small_golden(example_small):
    """Golden: reference_output/example_small_20_statistics.txt — LEXIMIN min
    10.0%, gini 0.0%, geometric mean 10.0%. The reference's ~198-panel support
    is a column-generation artifact, not part of the spec (SURVEY §4.4: only
    the allocation is canonical; portfolios vary run to run) — the type-space
    water-filling decomposition realizes the identical allocation exactly with
    a far more compact, auditable portfolio."""
    dense, space = featurize(example_small)
    dist = find_distribution_leximin(dense, space)
    st = prob_allocation_stats(dist.allocation, cap_for_geometric_mean=False)
    assert st.min == pytest.approx(0.100, abs=1e-3)
    assert st.gini == pytest.approx(0.0, abs=1e-3)
    assert st.geometric_mean == pytest.approx(0.100, abs=1e-3)
    assert dist.allocation.sum() == pytest.approx(20.0, abs=1e-6)
    # enough panels to realize uniform 10% (≥ 1/0.1) and within the vertex
    # bound of the final decomposition LP (≤ n + 1)
    assert 10 <= len(dist.support()) <= dense.n + 1
    # allocation realized exactly by the emitted portfolio
    realized = dist.committees.T.astype(float) @ dist.probabilities
    np.testing.assert_allclose(realized, dist.fixed_probabilities, atol=1e-8)
    assert_committees_feasible(dist, dense)


def test_leximin_couples_golden(reference_data_dir):
    """Golden: analysis/couples_..._statistics.txt — LEXIMIN min 10.0%,
    support 10 panels."""
    inst = read_instance_dir(
        reference_data_dir / "couples_panel_from_twenty_people_no_constraints_2"
    )
    dense, space = featurize(inst)
    dist = find_distribution_leximin(dense, space)
    st = prob_allocation_stats(dist.allocation, cap_for_geometric_mean=False)
    assert st.min == pytest.approx(0.100, abs=1e-3)
    assert len(dist.support()) == 10
    assert_committees_feasible(dist, dense)


def test_infeasible_quotas_raise_with_suggestion():
    inst = random_instance(n=30, k=10, n_categories=1, features_per_category=2, seed=1)
    cat = list(inst.categories)[0]
    feats = list(inst.categories[cat])
    # demand at least 5 members of a feature only 2 agents have
    for i, agent in enumerate(inst.agents):
        agent[cat] = feats[0] if i < 2 else feats[1]
    inst.categories[cat][feats[0]] = (5, 10)
    inst.categories[cat][feats[1]] = (0, 10)
    dense, space = featurize(inst)
    with pytest.raises(InfeasibleQuotasError) as exc:
        find_distribution_leximin(dense, space)
    # suggested relaxation must lower the impossible lower quota to ≤ 2
    quotas = exc.value.quotas
    assert quotas[(cat, feats[0])][0] <= 2
    assert any("lowering lower quota" in line for line in exc.value.output)


def test_uncoverable_agent_prefixed_zero_agent_space():
    """An agent in no feasible committee (their cell's quota is (0,0)) gets
    probability 0 up front on the agent-space CG path — the reference
    excludes such agents from the optimization (leximin.py:286-296); without
    the pre-fix the first stages grind through z = 0 (VERDICT r1 weak #4)."""
    import numpy as np

    from citizensassemblies_tpu.core.instance import Instance, featurize
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin

    agents = [{"g": "a" if i else "x"} for i in range(12)]
    inst = Instance(
        k=3,
        categories={"g": {"a": (3, 3), "x": (0, 0)}},
        agents=agents,
        name="uncoverable",
    )
    dense, space = featurize(inst)
    # the agent-space path must be requested explicitly: singleton households
    # no longer force it (the household quotient collapses them back)
    dist = find_distribution_leximin(
        dense, space,
        cfg=default_config().replace(force_agent_space=True),
    )
    assert dist.allocation[0] == 0.0
    assert not dist.covered[0]
    assert dist.fixed_probabilities[0] == 0.0
    # the coverable agents share the leximin value 3/11
    np.testing.assert_allclose(dist.allocation[1:], 3.0 / 11.0, atol=1e-4)


def test_enumerated_large_n_polish_terminates_quickly():
    """Regression (broad fuzz, round 4): an enumerated-path instance with
    large n (single category, 4 features, n=469, k=90, heavy skew) built a
    ~6000-panel greedy portfolio and ground ~20 s polish LPs toward a 1e-6
    panel tolerance the 1e-3 contract cannot see — a many-minute stall on a
    sub-second instance. The n >= 200 tolerance floor now applies to the
    enumerated path too; this shape must solve in seconds with the contract
    intact."""
    import time

    from citizensassemblies_tpu.core.generator import skewed_instance

    inst = skewed_instance(
        n=469, k=90, n_categories=1, seed=204242,
        features_per_category=[4], skew=0.85,
    )
    dense, space = featurize(inst)
    t0 = time.time()
    dist = find_distribution_leximin(dense, space)
    elapsed = time.time() - t0
    dev = float(np.abs(dist.allocation - dist.fixed_probabilities).max())
    assert dev <= 1e-3
    # pre-fix this ran for many minutes; allow generous headroom over the
    # measured 0.2 s so CI noise cannot flake the regression signal
    assert elapsed < 60.0, f"enumerated polish took {elapsed:.1f}s"
