"""Outer-round CG checkpointing: save/load/clear roundtrip, resume semantics,
and cleanup on successful completion (capability beyond the reference's
finished-run-only pickle cache — SURVEY §5)."""

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import cross_product_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.utils.checkpoint import (
    CGState,
    clear_cg_state,
    load_cg_state,
    problem_fingerprint,
    save_cg_state,
)
from citizensassemblies_tpu.utils.logging import RunLog


@pytest.fixture(scope="module")
def small():
    inst = cross_product_instance(
        categories=["gender", "age"],
        features=[["f", "m"], ["y", "o"]],
        quotas=[[(2, 4), (2, 4)], [(2, 4), (2, 4)]],
        counts=[8, 8, 8, 8],
        k=6,
        name="ckpt_6",
    )
    return featurize(inst)


def test_save_load_clear_roundtrip(tmp_path):
    path = tmp_path / "cg.npz"
    state = CGState(
        portfolio=np.eye(4, 10, dtype=bool),
        fixed=np.array([0.1, -1.0, 0.2, -1.0, 0.3, -1.0, 0.1, 0.1, -1.0, 0.2]),
        covered=np.ones(10, dtype=bool),
        key=np.array([0, 42], dtype=np.uint32),
        reduction_counter=1,
        dual_solves=7,
        exact_prices=2,
    )
    save_cg_state(path, state)
    loaded = load_cg_state(path, n=10)
    assert loaded is not None
    np.testing.assert_array_equal(loaded.portfolio, state.portfolio)
    np.testing.assert_array_equal(loaded.fixed, state.fixed)
    assert loaded.dual_solves == 7 and loaded.exact_prices == 2
    # wrong pool size ⇒ checkpoint ignored
    assert load_cg_state(path, n=11) is None
    clear_cg_state(path)
    assert load_cg_state(path, n=10) is None
    clear_cg_state(path)  # idempotent


def test_completion_clears_checkpoint(small, tmp_path):
    dense, space = small
    path = tmp_path / "cg.npz"
    dist = find_distribution_leximin(dense, space, checkpoint_path=str(path))
    assert not path.exists(), "checkpoint must be removed on success"
    assert abs(dist.allocation.sum() - dense.k) < 1e-3


def test_resume_from_mid_state(small, tmp_path):
    dense, space = small
    n = dense.n
    # reference run, no checkpointing
    ref = find_distribution_leximin(dense, space)

    # craft a mid-run state: full portfolio, half the agents' leximin values
    # already fixed (a tranche boundary), and resume from it
    fixed = ref.fixed_probabilities.copy()
    unfix = np.argsort(fixed)[n // 2:]
    fixed[unfix] = -1.0
    path = tmp_path / "cg.npz"
    from citizensassemblies_tpu.utils.config import default_config
    fp = problem_fingerprint(dense, default_config())
    save_cg_state(path, CGState(
        portfolio=ref.committees,
        fixed=fixed,
        covered=ref.covered,
        key=np.array([0, 123], dtype=np.uint32),
        fingerprint=fp,
    ))
    log = RunLog(echo=False)
    dist = find_distribution_leximin(dense, space, checkpoint_path=str(path), log=log)
    assert any("Resumed checkpoint" in line for line in log.lines)
    assert not path.exists()
    # resumed run must reproduce the leximin allocation
    np.testing.assert_allclose(dist.allocation, ref.allocation, atol=2e-2)
    assert abs(dist.allocation.min() - ref.allocation.min()) < 1e-2


def test_foreign_checkpoint_ignored(small, tmp_path):
    """A checkpoint written for a different problem (config/households/quotas)
    must not be resumed — it starts fresh instead of producing wrong output."""
    dense, space = small
    ref = find_distribution_leximin(dense, space)
    path = tmp_path / "cg.npz"
    save_cg_state(path, CGState(
        portfolio=ref.committees,
        fixed=np.full(dense.n, -1.0),
        covered=ref.covered,
        key=np.array([0, 1], dtype=np.uint32),
        fingerprint="deadbeef-some-other-problem",
    ))
    log = RunLog(echo=False)
    dist = find_distribution_leximin(dense, space, checkpoint_path=str(path), log=log)
    assert not any("Resumed checkpoint" in line for line in log.lines)
    np.testing.assert_allclose(dist.allocation, ref.allocation, atol=2e-2)


def test_corrupt_checkpoint_ignored(small, tmp_path):
    dense, space = small
    path = tmp_path / "cg.npz"
    path.write_bytes(b"not an npz at all")
    assert load_cg_state(path, dense.n) is None
    dist = find_distribution_leximin(dense, space, checkpoint_path=str(path))
    assert abs(dist.allocation.sum() - dense.k) < 1e-3


def test_typespace_state_roundtrip(tmp_path):
    from citizensassemblies_tpu.utils.checkpoint import (
        TypeCGState,
        load_cg_state,
        load_ts_state,
        save_ts_state,
    )

    path = tmp_path / "ts.npz"
    state = TypeCGState(
        compositions=np.arange(12, dtype=np.int32).reshape(4, 3),
        v_relax=np.array([0.1, 0.2, 0.3]),
        coverable=np.array([True, True, False]),
        key=np.array([0, 7], dtype=np.uint32),
        round=5,
        fingerprint="fp",
    )
    save_ts_state(path, state)
    loaded = load_ts_state(path, T=3, fingerprint="fp")
    assert loaded is not None and loaded.round == 5
    np.testing.assert_array_equal(loaded.compositions, state.compositions)
    np.testing.assert_array_equal(loaded.v_relax, state.v_relax)
    # wrong type count or fingerprint ⇒ ignored
    assert load_ts_state(path, T=4) is None
    assert load_ts_state(path, T=3, fingerprint="other") is None
    # the agent-space loader must not confuse a type-space file for its own
    assert load_cg_state(path, n=3) is None
