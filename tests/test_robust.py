"""graftfault: fault injection, sentinels, deadline/retry, checkpoints.

What is pinned here:

* **Injector determinism** — the same spec + seed replays the identical
  fault schedule (crc-based, process-stable); unknown sites are rejected.
* **Zero-fault bit-identity** — with ``fault_sites`` empty and the
  numerical sentinels ENABLED, leximin output is bitwise identical to the
  sentinel-off (pre-sentinel) path, serial and batched.
* **Quarantine** — a poisoned lane (injected NaN warm start / corrupted
  warm slot) freezes, is re-solved on the float64 host path, and its fleet
  mates are untouched.
* **Deadline** — an expired deadline raises a graceful ``DeadlineExceeded``
  with a partial audit stamp (service-level typed rejection included).
* **Retry + degradation ladder** — a transient worker crash retries with
  backoff and walks the ladder in its documented order; the request still
  completes under the 1e-3 contract.
* **Checkpoint/resume** — a face decomposition killed mid-round resumes
  from its last certified checkpoint and lands within the contract band of
  the uninterrupted run, across 2 instance seeds.
* **Batcher watchdog** — a leader that dies after claiming a group is
  detected and a follower re-elects and dispatches (no 120 s hang).
* **Channel cap** — retained events are bounded, drops are counted, the
  terminal result always arrives.
* **Teardown rollback** — failed requests leave no warm slots or session
  packs behind.
* **Shutdown drain** — in-flight requests complete, queued requests get a
  typed rejection, no service threads leak (thread enumeration).
"""

import threading
import time

import numpy as np
import pytest

from citizensassemblies_tpu.core.generator import random_instance, skewed_instance
from citizensassemblies_tpu.core.instance import featurize
from citizensassemblies_tpu.robust.inject import (
    FaultInjected,
    FaultInjector,
    _hash_unit,
    use_injector,
)
from citizensassemblies_tpu.robust.policy import (
    DEGRADATION_LADDER,
    Deadline,
    DeadlineExceeded,
    DegradationLadder,
    RetryBudget,
)
from citizensassemblies_tpu.utils.config import default_config
from citizensassemblies_tpu.utils.logging import RunLog


def _tiny(seed=0, n=24, k=5):
    return featurize(random_instance(n=n, k=k, n_categories=2, seed=seed))


# --- injector ----------------------------------------------------------------


def test_injector_deterministic_and_seeded():
    a = FaultInjector("pdhg_nan:0.5,oracle_raise:0.25", seed=3)
    b = FaultInjector("pdhg_nan:0.5,oracle_raise:0.25", seed=3)
    seq_a = [a.fire("pdhg_nan") for _ in range(64)]
    seq_b = [b.fire("pdhg_nan") for _ in range(64)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)  # the rate actually gates
    # a different seed produces a different schedule
    c = FaultInjector("pdhg_nan:0.5", seed=4)
    assert [c.fire("pdhg_nan") for _ in range(64)] != seq_a
    assert a.stats()["fired"]["pdhg_nan"] == sum(seq_a)


def test_injector_rejects_unknown_sites():
    with pytest.raises(ValueError):
        FaultInjector("not_a_site:0.5")
    with pytest.raises(ValueError):
        FaultInjector("pdhg_nan:0.5").fire("not_a_site")


def test_injection_inert_without_injector():
    from citizensassemblies_tpu.robust import inject

    log = RunLog(echo=False)
    assert inject.site("pdhg_nan", log) is False
    assert log.counters.get("fault_pdhg_nan", 0) == 0


# --- zero-fault bit-identity (sentinels enabled vs disabled) -----------------


def test_sentinels_zero_fault_bit_identity_leximin():
    """The acceptance pin: with fault_sites empty and the sentinel machinery
    ENABLED (the default), leximin output is bitwise identical to the
    sentinel-off jaxpr — serial engine and batched engine both."""
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin

    for lp_batch in (False, True):
        d, s = _tiny(seed=1, n=32, k=6)
        cfg_on = default_config().replace(robust_sentinels=True, lp_batch=lp_batch)
        cfg_off = default_config().replace(robust_sentinels=False, lp_batch=lp_batch)
        on = find_distribution_leximin(d, s, cfg=cfg_on)
        off = find_distribution_leximin(d, s, cfg=cfg_off)
        np.testing.assert_array_equal(on.allocation, off.allocation)
        np.testing.assert_array_equal(on.probabilities, off.probabilities)


def test_sentinel_quarantines_poisoned_batch_lane():
    """One NaN-poisoned lane freezes + host re-solves; fleet mates are
    BIT-identical to the clean run (per-lane isolation)."""
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        solve_lp_batch,
    )

    rng = np.random.default_rng(3)
    insts, data = [], []
    for s in range(4):
        P = (rng.random((16, 8)) < 0.5).astype(np.float64)
        q = rng.random(16)
        q /= q.sum()
        data.append((P, P.T @ q))
        insts.append(final_primal_batch_lp(P, P.T @ q))
    cfg = default_config().replace(lp_batch=True)
    clean = solve_lp_batch(insts, cfg=cfg, max_iters=20_000, defer=False)
    log = RunLog(echo=False)
    # seed chosen so pdhg_nan fires on SOME lanes of the first dispatch
    with use_injector(FaultInjector("pdhg_nan:0.6", seed=2)):
        chaos = solve_lp_batch(
            insts, cfg=cfg, log=log, max_iters=20_000, defer=False
        )
    quarantined = log.counters.get("sentinel_quarantined", 0)
    assert quarantined >= 1
    assert log.counters.get("sentinel_host_resolve", 0) == quarantined
    for i, (c, g) in enumerate(zip(clean, chaos)):
        assert np.all(np.isfinite(g.x))
        P, target = data[i]
        # quarantined lanes: exact host optimum still covers the target;
        # untouched lanes: bitwise identical to the clean dispatch
        if g.iters == -1:
            assert float(np.maximum(target - P.T @ g.x[:16], 0.0).max()) <= 1e-6
        else:
            np.testing.assert_array_equal(g.x, c.x)


def test_corrupt_warm_slot_quarantined_not_propagated():
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        solve_lp_batch,
    )

    rng = np.random.default_rng(7)
    P = (rng.random((16, 8)) < 0.5).astype(np.float64)
    q = rng.random(16)
    q /= q.sum()
    target = P.T @ q
    cfg = default_config().replace(lp_batch=True)
    log = RunLog(echo=False)
    inst = [final_primal_batch_lp(P, target)]
    solve_lp_batch(inst, cfg=cfg, log=log, warm_key="t", max_iters=20_000,
                   defer=False)
    with use_injector(FaultInjector("warm_slot_corrupt:1.0", seed=1)):
        out = solve_lp_batch(
            inst, cfg=cfg, log=log, warm_key="t", max_iters=20_000,
            defer=False,
        )
    assert log.counters.get("fault_warm_slot_corrupt", 0) == 1
    assert log.counters.get("sentinel_quarantined", 0) == 1
    assert np.all(np.isfinite(out[0].x))
    assert float(np.maximum(target - P.T @ out[0].x[:16], 0.0).max()) <= 1e-6


# --- policy: deadline, retry, ladder -----------------------------------------


def test_deadline_and_retry_budget_primitives():
    d = Deadline(1000.0)
    assert not d.expired and d.remaining() > 999.0
    d0 = Deadline(0.0)
    log = RunLog(echo=False)
    with pytest.raises(DeadlineExceeded) as ei:
        d0.check("unit", log=log, partial={"best_eps": 1.0})
    assert ei.value.partial["best_eps"] == 1.0
    assert log.counters["deadline_exceeded"] == 1

    r = RetryBudget(attempts=2, backoff_s=0.01)
    assert r.take() == pytest.approx(0.01)
    assert r.take() == pytest.approx(0.02)  # exponential
    assert r.take() is None  # exhausted


def test_degradation_ladder_order_and_cumulative_config():
    cfg = default_config()
    log = RunLog(echo=False)
    ladder = DegradationLadder()
    names = []
    for _ in range(len(DEGRADATION_LADDER) + 2):  # past the bottom: no-op
        cfg = ladder.degrade(cfg, log)
    names = ladder.steps
    assert names == [n for n, _p in DEGRADATION_LADDER]
    # every rung's gate is off, CUMULATIVELY
    assert cfg.decomp_device_pricing is False
    assert cfg.sparse_ops is False
    assert cfg.lp_batch is False
    assert cfg.decomp_batched_expand is False
    assert log.counters["robust_degrade_steps"] == len(DEGRADATION_LADDER)


def test_service_retry_walks_ladder_and_still_certifies():
    """A transient worker crash (fires once, then clears) retries, degrades
    one rung, and the request still completes under the contract."""
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService

    # pick a seed whose first worker_crash consult fires and second does
    # not — the schedule is crc-deterministic, so search it explicitly.
    # The service derives the per-request seed as fault_seed + crc32(rid),
    # so pin the request_id and solve for fault_seed.
    import zlib

    rid = "retry-pin"
    base = zlib.crc32(rid.encode())
    seed = next(
        s for s in range(2000)
        if _hash_unit(base + s, "worker_crash", 0) < 0.5
        and _hash_unit(base + s, "worker_crash", 1) >= 0.5
    )
    cfg = default_config().replace(
        fault_sites="worker_crash:0.5", fault_seed=seed, serve_retry_max=2,
        serve_retry_backoff_s=0.01,
    )
    with SelectionService(cfg) as svc:
        res = svc.run(
            SelectionRequest(
                instance=random_instance(n=24, k=5, n_categories=2, seed=3),
                request_id=rid,
            ),
            timeout=300,
        )
    assert res.audit["counters"].get("fault_worker_crash", 0) == 1
    assert res.audit["counters"].get("robust_retry", 0) == 1
    assert res.audit["retries_used"] == 1
    # the retry walked the first ladder rung (megakernel → chained cores)
    assert res.audit["counters"].get(
        "robust_degrade_megakernel_to_chained", 0
    ) == 1
    assert res.audit["contract_ok"] is True
    assert res.audit["realization_dev"] <= 1e-3


def test_service_deadline_graceful_typed_rejection():
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService

    cfg = default_config().replace(serve_deadline_s=1e-4)
    with SelectionService(cfg) as svc:
        ch = svc.submit(
            SelectionRequest(
                instance=random_instance(n=24, k=5, n_categories=2, seed=4)
            )
        )
        events = list(ch.events(timeout=60))
    kind, payload = events[-1]
    assert kind == "error"
    assert isinstance(payload, dict) and payload["kind"] == "DeadlineExceeded"
    # the partial audit stamp ships evidence, not a bare timeout
    assert payload["audit"]["deadline_s"] == pytest.approx(1e-4)
    assert "elapsed_s" in payload["audit"] and "counters" in payload["audit"]


# --- checkpoint/resume (acceptance pin, 2 seeds) -----------------------------


@pytest.mark.parametrize("inst_seed", [1, 2])
def test_face_checkpoint_resume_matches_uninterrupted(tmp_path, inst_seed):
    """A face decomposition killed mid-round (injected face_abort) and
    resumed from its last checkpoint lands within the 1e-3 L∞ contract of
    the uninterrupted run."""
    from citizensassemblies_tpu.solvers.cg_typespace import (
        CompositionOracle,
        _leximin_relaxation,
        _slice_relaxation,
    )
    from citizensassemblies_tpu.solvers.face_decompose import realize_profile
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    dense, _s = featurize(
        skewed_instance(n=120, k=12, n_categories=3, seed=inst_seed)
    )
    red = TypeReduction(dense)
    v_relax, _x = _leximin_relaxation(red, RunLog(echo=False))
    m = red.msize.astype(np.float64)
    # WEAK seed hull (R=4) so the loop genuinely runs multiple rounds —
    # checkpoints exist before the kill
    seeds = _slice_relaxation(v_relax * m, red, R=4)
    accept = 5e-4

    def run(cfg, log, inj=None):
        ctx_mgr = use_injector(inj) if inj is not None else use_injector(None)
        with ctx_mgr:
            return realize_profile(
                red, v_relax, list(seeds), CompositionOracle(red),
                accept=accept, log=log, max_rounds=8, use_pdhg=False, cfg=cfg,
            )

    base = default_config()
    C_ref, p_ref, eps_ref, _ = run(base, RunLog(echo=False))
    assert eps_ref <= 8e-4

    cfg = base.replace(
        robust_checkpoint_every=1, robust_checkpoint_dir=str(tmp_path)
    )
    # seed 8 pins the abort at round 1 of the first attempt (after the
    # round-0 checkpoint), so the resume path genuinely runs
    inj = FaultInjector("face_abort:0.3", seed=8)
    log = RunLog(echo=False)
    killed = False
    result = None
    for _attempt in range(6):
        try:
            result = run(cfg, log, inj=inj)
            break
        except FaultInjected:
            killed = True
    assert killed, "the pinned schedule must kill the first attempt"
    assert result is not None, "resume never completed"
    assert log.counters.get("robust_resume", 0) >= 1
    assert log.counters.get("robust_checkpoint_saved", 0) >= 1
    C_res, p_res, eps_res, _ = result
    assert eps_res <= 8e-4
    # allocations (realized type profiles) within the contract of each other
    alloc_ref = (C_ref.astype(np.float64) / m[None, :]).T @ p_ref
    alloc_res = (C_res.astype(np.float64) / m[None, :]).T @ p_res
    assert float(np.abs(alloc_ref - alloc_res).max()) <= 1e-3


# --- batcher leader watchdog -------------------------------------------------


def test_batcher_follower_reelects_after_leader_death():
    """Kill the leader mid-merge (after claiming, before dispatch): the
    follower must detect it via the watchdog and dispatch the group itself,
    promptly — not after the 120 s safety net."""
    from citizensassemblies_tpu.service import CrossRequestBatcher, RequestContext
    from citizensassemblies_tpu.service.context import use_context
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        solve_lp_batch,
    )

    def fleet(seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(2):
            P = r.random((16, 8)) < 0.5
            q = r.random(16)
            q /= q.sum()
            out.append(final_primal_batch_lp(P, P.T.astype(np.float64) @ q))
        return out

    cfg = default_config().replace(lp_batch=True, serve_batch_window_ms=250.0)
    batcher = CrossRequestBatcher(cfg)
    ctxs = [
        RequestContext.create(cfg=cfg, tenant=f"t{i}", request_id=f"r{i}",
                              batcher=batcher)
        for i in range(2)
    ]
    leader_exc, follower_out = [], []
    started = threading.Event()

    def leader():
        # the injected death fires on the leader's raise_if after claiming
        with use_injector(FaultInjector("batcher_leader_death:1.0", seed=0)):
            with use_context(ctxs[0]):
                try:
                    solve_lp_batch(fleet(1), cfg=cfg, max_iters=20_000)
                except FaultInjected as exc:
                    leader_exc.append(exc)

    def follower():
        started.wait(timeout=10)
        time.sleep(0.05)  # join the window the leader already opened
        with use_context(ctxs[1]):
            follower_out.append(
                solve_lp_batch(fleet(2), cfg=cfg, max_iters=20_000)
            )

    t_lead = threading.Thread(target=leader)
    t_fol = threading.Thread(target=follower)
    t0 = time.time()
    t_lead.start()
    started.set()
    t_fol.start()
    t_fol.join(timeout=30)
    t_lead.join(timeout=30)
    elapsed = time.time() - t0
    assert leader_exc, "the leader must have died (injected)"
    assert follower_out and follower_out[0], "follower never got results"
    assert elapsed < 20, f"watchdog too slow ({elapsed:.1f}s — safety-net wait?)"
    stats = batcher.stats()
    assert stats["leader_deaths"] == 1
    assert stats["leader_reclaims"] == 1
    # the re-elected follower's solutions are real solves
    assert all(np.all(np.isfinite(s.x)) for s in follower_out[0])


def test_batcher_watchdog_detects_hard_killed_leader_thread():
    """White-box: a leader whose THREAD died without running any cleanup
    (no exception path) is detected via is_alive() and re-elected."""
    from citizensassemblies_tpu.service import CrossRequestBatcher, RequestContext
    from citizensassemblies_tpu.service.batcher import _Pending
    from citizensassemblies_tpu.solvers.batch_lp import final_primal_batch_lp

    rng = np.random.default_rng(0)
    P = rng.random((16, 8)) < 0.5
    q = rng.random(16)
    q /= q.sum()
    cfg = default_config().replace(lp_batch=True, serve_batch_window_ms=10.0)
    batcher = CrossRequestBatcher(cfg)
    ctx = RequestContext.create(cfg=cfg, tenant="t", request_id="r")
    key = (int(cfg.pdhg_max_iters), int(cfg.pdhg_check_every),
           int(cfg.lp_batch_bucket_max), str(cfg.transfer_guard))
    pend = _Pending(
        [final_primal_batch_lp(P, P.T.astype(np.float64) @ q)], ctx, None, None
    )
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    with batcher._lock:
        batcher._groups[key] = [pend]
        batcher._leaders.add(key)
        batcher._leader_threads[key] = dead  # a claim whose thread is gone
    batcher._follower_wait(key, pend, cfg)
    assert pend.results is not None
    assert batcher.stats()["leader_reclaims"] == 1


# --- channel cap, teardown, shutdown drain -----------------------------------


def test_result_channel_cap_drops_counted_result_retained():
    from citizensassemblies_tpu.service.server import ResultChannel

    ch = ResultChannel("r", cap=8)
    for i in range(20):
        ch.push("progress", f"line {i}")
    ch.push("result", "the-result")
    assert ch.dropped == 12  # 8 retained, 12 dropped, counted
    events = list(ch.events(timeout=1))
    assert len(events) == 9  # 8 progress + the terminal
    assert events[-1] == ("result", "the-result")


def test_teardown_rolls_back_warm_slots_and_session_packs():
    from citizensassemblies_tpu.service import RequestContext
    from citizensassemblies_tpu.service.session import TenantSession

    sess = TenantSession("t", cap=8)
    store = sess.warm_store_for("req-1")
    store.put(("k", 0), (np.zeros(2), np.zeros(1), np.zeros(1), 0))
    sess.pack_put("pack-a", object(), request_id="req-1")
    ctx = RequestContext.create(
        cfg=default_config(), request_id="req-1", tenant="t",
        warm_store=store, session=sess,
    )
    ctx.teardown(success=False)
    assert len(store) == 0
    assert sess.pack_get("pack-a") is None
    assert sess.warm_stores.get("req-1") is None
    # the success path keeps everything
    store2 = sess.warm_store_for("req-2")
    store2.put(("k", 0), (np.zeros(2), np.zeros(1), np.zeros(1), 0))
    sess.pack_put("pack-b", object(), request_id="req-2")
    ctx2 = RequestContext.create(
        cfg=default_config(), request_id="req-2", tenant="t",
        warm_store=store2, session=sess,
    )
    sess.finish_request("req-2")
    ctx2.teardown(success=True)
    assert len(store2) == 1
    assert sess.pack_get("pack-b") is not None


def test_service_shutdown_drain_semantics():
    """In-flight requests complete, queued requests get a typed rejection,
    post-shutdown submits are refused, and no service thread leaks."""
    from citizensassemblies_tpu.service import (
        AdmissionError,
        SelectionRequest,
        SelectionService,
    )

    cfg = default_config().replace(
        serve_admission_cap=1, obs_metrics_interval_s=0.05
    )
    svc = SelectionService(cfg)
    # one multi-second request occupies the single worker; the two queued
    # behind it are deterministically unstarted when shutdown lands
    slow = svc.submit(
        SelectionRequest(
            instance=skewed_instance(n=120, k=12, n_categories=3, seed=1)
        )
    )
    queued = [
        svc.submit(
            SelectionRequest(
                instance=random_instance(n=24, k=5, n_categories=2, seed=i)
            )
        )
        for i in range(2)
    ]
    svc.shutdown(wait=True)
    # the in-flight request completed normally
    res = slow.result(timeout=5)
    assert res.audit["contract_ok"] is True
    # the queued requests got the typed rejection as their terminal event
    for ch in queued:
        events = list(ch.events(timeout=5))
        kind, payload = events[-1]
        assert kind == "error"
        assert isinstance(payload, dict) and payload["kind"] == "ServiceShutdown"
    # post-shutdown submissions are refused
    with pytest.raises(AdmissionError):
        svc.submit(
            SelectionRequest(
                instance=random_instance(n=24, k=5, n_categories=2, seed=9)
            )
        )
    # no service thread survives (workers drained, snapshot thread joined)
    alive = [
        t.name for t in threading.enumerate()
        if t.is_alive() and t.name.startswith(("graftserve", "anchor-pricer"))
    ]
    assert not alive, alive
